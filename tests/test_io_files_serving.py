"""Round-2 IO: binary/image file sources, PowerBI sink, distributed serving
(DistributedHTTPSource analog), serving backpressure."""

import json
import threading
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

import synapseml_tpu as st
from synapseml_tpu.core.dataframe import DataFrame
from synapseml_tpu.core.pipeline import Transformer
from synapseml_tpu.io import (
    PowerBIWriter,
    read_binary_files,
    read_image_files,
    serve_pipeline_distributed,
)


def test_read_binary_files(tmp_path):
    (tmp_path / "sub").mkdir()
    (tmp_path / "a.bin").write_bytes(b"alpha")
    (tmp_path / "sub" / "b.bin").write_bytes(b"beta!")
    df = read_binary_files(str(tmp_path), num_partitions=2)
    assert df.count() == 2
    rows = {r["path"].rsplit("/", 1)[-1]: r for p in df.partitions
            for r in [dict(zip(p, vals)) for vals in zip(*p.values())]}
    assert rows["a.bin"]["content"] == b"alpha"
    assert rows["b.bin"]["length"] == 5
    # extension filter
    assert read_binary_files(str(tmp_path), extensions=(".txt",)).count() == 0


def test_read_image_files(tmp_path):
    from PIL import Image

    arr = np.arange(12 * 10 * 3, dtype=np.uint8).reshape(12, 10, 3)
    Image.fromarray(arr).save(tmp_path / "img.png")
    (tmp_path / "junk.png").write_bytes(b"not an image")
    df = read_image_files(str(tmp_path))
    assert df.count() == 1  # invalid dropped
    row = {k: v[0] for k, v in df.partitions[0].items()}
    assert (row["height"], row["width"], row["channels"]) == (12, 10, 3)
    np.testing.assert_array_equal(row["image"], arr)

    # feeds straight into ImageTransformer
    from synapseml_tpu.image import ImageTransformer

    out = ImageTransformer(input_col="image", output_col="small").resize(4, 4) \
        .transform(df)
    assert np.asarray(list(out.collect_column("small"))[0]).shape == (4, 4, 3)


def test_powerbi_writer():
    received = []

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length") or 0)
            received.append(json.loads(self.rfile.read(n)))
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_port}"

    df = DataFrame.from_rows([{"name": f"r{i}", "value": float(i)}
                              for i in range(25)], num_partitions=3)
    n = PowerBIWriter(url, batch_size=10).write(df)
    assert n == 25
    flat = [r for batch in received for r in batch]
    assert len(flat) == 25 and {r["name"] for r in flat} == {f"r{i}" for i in range(25)}
    assert all(len(b) <= 10 for b in received)
    srv.shutdown()

    with pytest.raises(ValueError, match="10000"):
        PowerBIWriter(url, batch_size=20_000)


class EchoPid(Transformer):
    """Reply with the input plus the serving process pid (proves requests
    spread across worker processes)."""

    def _transform(self, df):
        import os

        def per_part(p):
            out = dict(p)
            out["reply"] = np.asarray(
                [{"echo": b, "pid": os.getpid()} for b in p["body"]],
                dtype=object)
            return out

        return df.map_partitions(per_part)


def test_distributed_serving_round_robin_under_load():
    handle = serve_pipeline_distributed(EchoPid(), num_workers=2,
                                        batch_interval_ms=0)
    try:
        def call(i):
            req = urllib.request.Request(
                handle.address, data=json.dumps({"i": i}).encode(),
                method="POST")
            with urllib.request.urlopen(req, timeout=60) as r:
                return json.loads(r.read())

        with ThreadPoolExecutor(8) as pool:
            replies = list(pool.map(call, range(40)))
        # every request got its own body echoed back (reply routing correct)
        assert sorted(r["echo"]["i"] for r in replies) == list(range(40))
        # and at least two distinct worker processes served them
        assert len({r["pid"] for r in replies}) >= 2
    finally:
        handle.stop()


def test_routing_front_resurrects_dead_workers():
    """A worker whose breaker tripped open after a connect failure rejoins
    the rotation once its resurrection window passes (advisor finding: the
    old front 503'd forever after every worker failed once)."""
    from synapseml_tpu.io.serving import serve_pipeline
    from synapseml_tpu.io.distributed_serving import RoutingFront

    srv = serve_pipeline(EchoPid())
    live = {"host": srv.host, "port": srv.port, "pid": 1}
    front = RoutingFront([live], timeout_s=10, resurrect_after_s=0.5)
    try:
        def call():
            req = urllib.request.Request(
                front.address, data=json.dumps({"i": 0}).encode(),
                method="POST")
            with urllib.request.urlopen(req, timeout=10) as r:
                return r.status

        assert call() == 200
        # poison the routing table entry: trip the (only) worker's breaker
        breaker = front._breaker((live["host"], live["port"]))
        breaker.record_failure()
        assert breaker.state == breaker.OPEN
        # inside the window, the desperation probe still reaches it (the front
        # never settles into a permanent 503 while a worker is reachable)
        assert call() == 200
        # a success closes the breaker entirely
        assert breaker.state == breaker.CLOSED
    finally:
        front.close()
        srv.stop()


def test_distributed_serving_chaos_worker_killed_and_rejoins():
    """Kill a worker mid-load: traffic keeps succeeding on the survivor, the
    supervisor respawns the worker, it re-registers, and new traffic reaches
    the replacement pid (VERDICT round-2 weak #6)."""
    import time as _time

    handle = serve_pipeline_distributed(EchoPid(), num_workers=2,
                                        batch_interval_ms=0)
    try:
        def call(i):
            req = urllib.request.Request(
                handle.address, data=json.dumps({"i": i}).encode(),
                method="POST")
            with urllib.request.urlopen(req, timeout=60) as r:
                return json.loads(r.read())

        first = [call(i) for i in range(6)]
        pids0 = {r["pid"] for r in first}
        assert len(pids0) == 2

        victim = handle.procs[0]
        victim.kill()
        victim.wait()

        # traffic continues without interruption (survivor + retries)
        mid = [call(100 + i) for i in range(10)]
        assert sorted(r["echo"]["i"] for r in mid) == list(range(100, 110))

        # the supervisor respawns; the replacement registers and serves
        deadline = _time.monotonic() + 60
        seen = set()
        while _time.monotonic() < deadline:
            seen = {call(200 + i)["pid"] for i in range(8)}
            if len(seen) >= 2 and victim.pid not in seen:
                break
            _time.sleep(0.3)
        assert len(seen) >= 2, f"replacement worker never served (pids {seen})"
        assert victim.pid not in seen
    finally:
        handle.stop()


def test_keepalive_routes_and_routing_client():
    """Round-4 serving upgrades: HTTP/1.1 keep-alive end-to-end (one client
    connection serves many requests through the front's pooled worker
    connections), GET /routes exposes the live table, and RoutingClient
    serves where-it-lands (direct worker hits, zero proxy hops) with
    failover when a worker dies."""
    import http.client

    from synapseml_tpu.io.distributed_serving import RoutingClient

    handle = serve_pipeline_distributed(EchoPid(), num_workers=2,
                                        batch_interval_ms=0)
    try:
        host, port = handle.address.split("//")[1].split(":")
        # one persistent connection, many requests (keep-alive front)
        conn = http.client.HTTPConnection(host, int(port), timeout=30)
        pids = set()
        for i in range(6):
            conn.request("POST", "/", body=json.dumps({"i": i}).encode())
            r = conn.getresponse()
            assert r.status == 200
            pids.add(json.loads(r.read())["pid"])
        conn.close()
        assert len(pids) >= 2  # still round-robins across workers

        # /routes: the live table, served by the front itself
        with urllib.request.urlopen(handle.address + "/routes",
                                    timeout=30) as r:
            table = json.loads(r.read())
        assert len(table) == 2 and all("port" in w for w in table)

        # client-side routing straight to workers
        client = RoutingClient(front_address=handle.address)
        seen = set()
        for i in range(6):
            status, payload = client.request(
                "/", body=json.dumps({"i": i}).encode())
            assert status == 200
            seen.add(json.loads(payload)["pid"])
        assert len(seen) >= 2

        # failover: kill one worker; the client keeps serving via the other
        handle.procs[0].kill()
        handle.procs[0].wait()
        ok = 0
        for i in range(8):
            try:
                status, _ = client.request(
                    "/", body=json.dumps({"i": i}).encode())
                ok += int(status == 200)
            except ConnectionError:
                pass
        assert ok >= 6  # at most the in-flight rotation misses
        client.close()
    finally:
        handle.stop()


def test_csv_round_trip(tmp_path):
    from synapseml_tpu.io import read_csv, write_csv

    df = DataFrame.from_dict({"a": np.arange(10).astype(np.int64),
                              "b": np.linspace(0, 1, 10),
                              "s": np.asarray([f"r{i}" for i in range(10)],
                                              dtype=object)},
                             num_partitions=3)
    files = write_csv(df, str(tmp_path / "out"), partitioned=True)
    assert len(files) == 3 and all(f.endswith(".csv") for f in files)
    back = read_csv(str(tmp_path / "out"))
    assert back.num_partitions == 3  # one partition per file (Spark model)
    assert back.count() == 10
    np.testing.assert_array_equal(np.sort(back.collect_column("a")),
                                  np.arange(10))
    # single-file form + repartition
    one = write_csv(df, str(tmp_path / "single.csv"))
    back1 = read_csv(one[0], num_partitions=2)
    assert back1.count() == 10 and back1.num_partitions == 2


def test_jsonl_round_trip(tmp_path):
    from synapseml_tpu.io import read_jsonl, write_jsonl

    df = DataFrame.from_rows(
        [{"x": float(i), "name": f"n{i}", "v": np.asarray([i, i + 1])}
         for i in range(6)], num_partitions=2)
    path = write_jsonl(df, str(tmp_path / "rows.jsonl"))
    back = read_jsonl(path)
    assert back.count() == 6
    assert list(back.collect_column("name")[:2]) == ["n0", "n1"]
    assert list(back.collect_column("v")[0]) == [0, 1]


def test_read_csv_missing_raises(tmp_path):
    from synapseml_tpu.io import read_csv

    with pytest.raises(FileNotFoundError):
        read_csv(str(tmp_path / "*.csv"))


def test_write_csv_removes_stale_parts(tmp_path):
    from synapseml_tpu.io import read_csv, write_csv

    wide = DataFrame.from_dict({"a": np.arange(10)}, num_partitions=5)
    narrow = DataFrame.from_dict({"a": np.arange(4)}, num_partitions=2)
    out = str(tmp_path / "dir")
    write_csv(wide, out, partitioned=True)
    write_csv(narrow, out, partitioned=True)  # must clear part-00002..4
    back = read_csv(out)
    assert back.count() == 4 and back.num_partitions == 2


def test_read_csv_bracket_glob_and_empty_file(tmp_path):
    import pandas as pd

    from synapseml_tpu.io import read_csv

    pd.DataFrame({"a": [1, 2]}).to_csv(tmp_path / "part-0.csv", index=False)
    pd.DataFrame({"a": [3]}).to_csv(tmp_path / "part-1.csv", index=False)
    df = read_csv(str(tmp_path / "part-[01].csv"))
    assert df.count() == 3
    # header-only file keeps its (empty) partition: file<->partition mapping
    pd.DataFrame({"a": []}).to_csv(tmp_path / "part-2.csv", index=False)
    df3 = read_csv(str(tmp_path / "part-[012].csv"))
    assert df3.num_partitions == 3 and df3.count() == 3


def test_read_jsonl_heterogeneous_records(tmp_path):
    from synapseml_tpu.io import read_jsonl

    p = tmp_path / "h.jsonl"
    p.write_text('{"a": 1}\n{"a": 2, "b": "x"}\n{"b": "y"}\n')
    df = read_jsonl(str(p))
    assert sorted(df.columns) == ["a", "b"]
    assert list(df.collect_column("a")) == [1, 2, None]
    assert list(df.collect_column("b")) == [None, "x", "y"]
