"""Pipeline parallelism over the `pipe` mesh axis (GPipe schedule with
ppermute activation rotation) vs the sequential stage-chain oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from synapseml_tpu.parallel import MeshConfig, create_mesh
from synapseml_tpu.parallel.pipeline import (
    pipeline_apply,
    pipeline_sharded,
    stack_stage_params,
)


def mlp_stage(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def make_stages(n_stages, d, seed=0):
    rs = np.random.default_rng(seed)
    return [{"w": jnp.asarray(rs.normal(size=(d, d)) * 0.4, jnp.float32),
             "b": jnp.asarray(rs.normal(size=(d,)) * 0.1, jnp.float32)}
            for _ in range(n_stages)]


def sequential(stages, x_micro):
    y = x_micro
    for p in stages:
        y = jax.vmap(lambda x, p=p: mlp_stage(p, x))(y)
    return y


@pytest.mark.parametrize("n_micro", [1, 4, 8])
def test_pipeline_matches_sequential(n_micro):
    n_stages, mb, d = 4, 3, 8
    stages = make_stages(n_stages, d)
    stacked = stack_stage_params(stages)
    rs = np.random.default_rng(1)
    x = jnp.asarray(rs.normal(size=(n_micro, mb, d)), jnp.float32)
    mesh = create_mesh(MeshConfig(data=2, pipe=4))
    out = pipeline_sharded(mesh, mlp_stage, stacked, x)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(sequential(stages, x)),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_gradients_match_sequential():
    n_stages, n_micro, mb, d = 4, 6, 2, 8
    stages = make_stages(n_stages, d, seed=2)
    stacked = stack_stage_params(stages)
    rs = np.random.default_rng(3)
    x = jnp.asarray(rs.normal(size=(n_micro, mb, d)), jnp.float32)
    mesh = create_mesh(MeshConfig(data=2, pipe=4))

    def loss_pp(params):
        return jnp.sum(pipeline_sharded(mesh, mlp_stage, params, x) ** 2)

    def loss_seq(params):
        y = x
        for s in range(n_stages):
            p = jax.tree.map(lambda q: q[s], params)
            y = jax.vmap(lambda xx, p=p: mlp_stage(p, xx))(y)
        return jnp.sum(y ** 2)

    g_pp = jax.grad(loss_pp)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    for a, b in zip(jax.tree.leaves(g_seq), jax.tree.leaves(g_pp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_pipeline_jit_and_pipe_times_data_mesh():
    # composition: pipe=2 x data=4, jitted end-to-end
    n_stages, n_micro, mb, d = 2, 5, 2, 4
    stages = make_stages(n_stages, d, seed=4)
    stacked = stack_stage_params(stages)
    x = jnp.asarray(np.random.default_rng(5).normal(size=(n_micro, mb, d)),
                    jnp.float32)
    mesh = create_mesh(MeshConfig(data=4, pipe=2))
    out = jax.jit(lambda p, xx: pipeline_sharded(mesh, mlp_stage, p, xx))(
        stacked, x)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(sequential(stages, x)),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_stage_count_mismatch_rejected():
    stages = make_stages(8, 4, seed=10)  # 8 stages on a pipe=4 axis
    stacked = stack_stage_params(stages)
    x = jnp.zeros((2, 2, 4), jnp.float32)
    mesh = create_mesh(MeshConfig(data=2, pipe=4))
    with pytest.raises(ValueError, match="one stage per device"):
        pipeline_sharded(mesh, mlp_stage, stacked, x)


def test_pipeline_fallback_without_pipe_axis():
    stages = make_stages(3, 4, seed=6)
    stacked = stack_stage_params(stages)
    x = jnp.asarray(np.random.default_rng(7).normal(size=(2, 2, 4)),
                    jnp.float32)
    mesh = create_mesh(MeshConfig(data=-1))  # no pipe axis
    out = pipeline_sharded(mesh, mlp_stage, stacked, x)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(sequential(stages, x)),
                               rtol=1e-6)


def test_pipeline_inside_shard_map_direct():
    # the collective form composes with a manual shard_map call site
    from jax.sharding import PartitionSpec as P

    n_stages, n_micro, mb, d = 8, 3, 2, 4
    stages = make_stages(n_stages, d, seed=8)
    stacked = stack_stage_params(stages)
    x = jnp.asarray(np.random.default_rng(9).normal(size=(n_micro, mb, d)),
                    jnp.float32)
    from synapseml_tpu.parallel.pipeline import _shard_map

    mesh = create_mesh(MeshConfig(data=1, pipe=8))
    mapped = _shard_map(
        lambda p, xx: pipeline_apply(mlp_stage, p, xx),
        mesh.mesh,
        (jax.tree.map(lambda _: P("pipe"), stacked), P()),
        P(),
    )
    np.testing.assert_allclose(np.asarray(mapped(stacked, x)),
                               np.asarray(sequential(stages, x)),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_remat_gradients_match():
    # jax.checkpoint on the stage fn: same grads, recomputed activations
    n_stages, n_micro, mb, d = 4, 4, 2, 8
    stages = make_stages(n_stages, d, seed=12)
    stacked = stack_stage_params(stages)
    x = jnp.asarray(np.random.default_rng(13).normal(size=(n_micro, mb, d)),
                    jnp.float32)
    mesh = create_mesh(MeshConfig(data=2, pipe=4))

    def loss(params, remat):
        return jnp.sum(pipeline_sharded(mesh, mlp_stage, params, x,
                                        remat=remat) ** 2)

    g_plain = jax.grad(lambda p: loss(p, False))(stacked)
    g_remat = jax.grad(lambda p: loss(p, True))(stacked)
    for a, b in zip(jax.tree.leaves(g_plain), jax.tree.leaves(g_remat)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_pipeline_pytree_payload_carries_mask():
    """Stages may pipe PYTREE payloads: (hidden, mask) travel together, the
    stage transforms hidden under its mask and passes the mask through —
    the transformer-block shape of pipelining."""
    n_stages, n_micro, mb, d = 4, 5, 3, 8
    stages = make_stages(n_stages, d, seed=14)
    stacked = stack_stage_params(stages)
    rs = np.random.default_rng(15)
    x = jnp.asarray(rs.normal(size=(n_micro, mb, d)), jnp.float32)
    mask = jnp.asarray(rs.random((n_micro, mb, d)) > 0.3, jnp.float32)

    def masked_stage(p, payload):
        h, m = payload
        return jnp.tanh((h * m) @ p["w"] + p["b"]), m

    def seq(x, mask):
        y = x
        for p in stages:
            y, _ = jax.vmap(lambda h, m, p=p: masked_stage(p, (h, m)))(y, mask)
        return y

    mesh = create_mesh(MeshConfig(data=2, pipe=4))
    out_h, out_m = pipeline_sharded(mesh, masked_stage, stacked, (x, mask))
    np.testing.assert_allclose(np.asarray(out_h), np.asarray(seq(x, mask)),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(out_m), np.asarray(mask))


@pytest.mark.parametrize("n_micro", [4, 8])
def test_pipeline_sharded_io_matches_sequential(n_micro):
    # io='sharded': microbatches in AND out live sharded over pipe
    n_stages, mb, d = 4, 3, 8
    stages = make_stages(n_stages, d)
    stacked = stack_stage_params(stages)
    rs = np.random.default_rng(21)
    x = jnp.asarray(rs.normal(size=(n_micro, mb, d)), jnp.float32)
    mesh = create_mesh(MeshConfig(data=2, pipe=4))
    out = pipeline_sharded(mesh, mlp_stage, stacked, x, io="sharded")
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(sequential(stages, x)),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_sharded_io_gradients_match():
    n_stages, n_micro, mb, d = 4, 8, 2, 8
    stages = make_stages(n_stages, d, seed=22)
    stacked = stack_stage_params(stages)
    x = jnp.asarray(np.random.default_rng(23).normal(size=(n_micro, mb, d)),
                    jnp.float32)
    mesh = create_mesh(MeshConfig(data=2, pipe=4))

    def loss(params, io):
        return jnp.sum(pipeline_sharded(mesh, mlp_stage, params, x,
                                        io=io) ** 2)

    g_rep = jax.grad(lambda p: loss(p, "replicated"))(stacked)
    g_shd = jax.grad(lambda p: loss(p, "sharded"))(stacked)
    for a, b in zip(jax.tree.leaves(g_rep), jax.tree.leaves(g_shd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_pipeline_sharded_io_memory_scales_inverse_with_stages():
    """The 1/S memory contract: with io='sharded' each device addresses only
    n_micro/S microbatches of the output (and the schedule's carry holds
    O(chunk) slots), vs the replicated layout's full n_micro everywhere."""
    n_stages, n_micro, mb, d = 4, 8, 2, 8
    stages = make_stages(n_stages, d, seed=24)
    stacked = stack_stage_params(stages)
    x = jnp.asarray(np.random.default_rng(25).normal(size=(n_micro, mb, d)),
                    jnp.float32)
    mesh = create_mesh(MeshConfig(data=2, pipe=4))
    assert dict(mesh.mesh.shape)["pipe"] == n_stages  # not the seq fallback
    with mesh.mesh:
        out_s = jax.jit(lambda p, xx: pipeline_sharded(
            mesh, mlp_stage, p, xx, io="sharded"))(stacked, x)
        out_r = jax.jit(lambda p, xx: pipeline_sharded(
            mesh, mlp_stage, p, xx, io="replicated"))(stacked, x)
    # per-device shard of the sharded output is 1/S of the microbatches
    shard_shapes = {s.data.shape for s in out_s.addressable_shards}
    assert shard_shapes == {(n_micro // n_stages, mb, d)}, shard_shapes
    # the replicated layout holds ALL microbatches on every device
    assert {s.data.shape for s in out_r.addressable_shards} \
        == {(n_micro, mb, d)}
    # and the compiled per-device program's live buffers reflect it when the
    # backend reports memory analysis (probing guarded — the assert is not)
    out_sz_s = out_sz_r = 0
    try:
        lowered_s = jax.jit(lambda p, xx: pipeline_sharded(
            mesh, mlp_stage, p, xx, io="sharded")).lower(stacked, x)
        lowered_r = jax.jit(lambda p, xx: pipeline_sharded(
            mesh, mlp_stage, p, xx, io="replicated")).lower(stacked, x)
        ma_s = lowered_s.compile().memory_analysis()
        ma_r = lowered_r.compile().memory_analysis()
        out_sz_s = getattr(ma_s, "output_size_in_bytes", 0)
        out_sz_r = getattr(ma_r, "output_size_in_bytes", 0)
    except (NotImplementedError, AttributeError, RuntimeError):
        pass  # backend without memory analysis: shard-shape assertions above
    if out_sz_s and out_sz_r:
        assert out_sz_s <= out_sz_r, (out_sz_s, out_sz_r)


def test_pipeline_sharded_io_pytree_payload():
    n_stages, n_micro, mb, d = 4, 4, 3, 8
    stages = make_stages(n_stages, d, seed=26)
    stacked = stack_stage_params(stages)
    rs = np.random.default_rng(27)
    x = jnp.asarray(rs.normal(size=(n_micro, mb, d)), jnp.float32)
    mask = jnp.asarray(rs.random((n_micro, mb, d)) > 0.3, jnp.float32)

    def masked_stage(p, payload):
        h, m = payload
        return jnp.tanh((h * m) @ p["w"] + p["b"]), m

    def seq(x, mask):
        y = x
        for p in stages:
            y, _ = jax.vmap(lambda h, m, p=p: masked_stage(p, (h, m)))(y, mask)
        return y

    mesh = create_mesh(MeshConfig(data=2, pipe=4))
    out_h, out_m = pipeline_sharded(mesh, masked_stage, stacked, (x, mask),
                                    io="sharded")
    np.testing.assert_allclose(np.asarray(out_h), np.asarray(seq(x, mask)),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(out_m), np.asarray(mask))


def test_pipeline_sharded_io_rejects_indivisible():
    stages = make_stages(4, 4, seed=28)
    stacked = stack_stage_params(stages)
    x = jnp.zeros((6, 2, 4), jnp.float32)  # 6 % 4 != 0
    mesh = create_mesh(MeshConfig(data=2, pipe=4))
    with pytest.raises(ValueError, match="divisible"):
        pipeline_sharded(mesh, mlp_stage, stacked, x, io="sharded")


@pytest.mark.parametrize("n_micro", [4, 8])
def test_pipeline_interleaved_matches_sequential(n_micro):
    # circular schedule: 8 stages round-robin on pipe=4 (v=2)
    n_stages, mb, d = 8, 3, 8
    stages = make_stages(n_stages, d, seed=31)
    stacked = stack_stage_params(stages)
    rs = np.random.default_rng(32)
    x = jnp.asarray(rs.normal(size=(n_micro, mb, d)), jnp.float32)
    mesh = create_mesh(MeshConfig(data=2, pipe=4))
    out = pipeline_sharded(mesh, mlp_stage, stacked, x, interleave=2)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(sequential(stages, x)),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_interleaved_gradients_match_sequential():
    n_stages, n_micro, mb, d = 8, 8, 2, 8
    stages = make_stages(n_stages, d, seed=33)
    stacked = stack_stage_params(stages)
    x = jnp.asarray(np.random.default_rng(34).normal(size=(n_micro, mb, d)),
                    jnp.float32)
    mesh = create_mesh(MeshConfig(data=2, pipe=4))

    def loss_pp(params):
        return jnp.sum(pipeline_sharded(mesh, mlp_stage, params, x,
                                        interleave=2) ** 2)

    def loss_seq(params):
        y = x
        for s in range(n_stages):
            p = jax.tree.map(lambda q: q[s], params)
            y = jax.vmap(lambda xx, p=p: mlp_stage(p, xx))(y)
        return jnp.sum(y ** 2)

    g_pp = jax.grad(loss_pp)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    for a, b in zip(jax.tree.leaves(g_seq), jax.tree.leaves(g_pp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_pipeline_interleaved_deeper_chunks():
    # v=4: 8 stages on pipe=2, jitted, payload wraps three times
    n_stages, n_micro, mb, d = 8, 6, 2, 4
    stages = make_stages(n_stages, d, seed=35)
    stacked = stack_stage_params(stages)
    x = jnp.asarray(np.random.default_rng(36).normal(size=(n_micro, mb, d)),
                    jnp.float32)
    mesh = create_mesh(MeshConfig(data=4, pipe=2))
    out = jax.jit(lambda p, xx: pipeline_sharded(
        mesh, mlp_stage, p, xx, interleave=4))(stacked, x)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(sequential(stages, x)),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_interleaved_remat_gradients_match():
    n_stages, n_micro, mb, d = 8, 4, 2, 8
    stages = make_stages(n_stages, d, seed=38)
    stacked = stack_stage_params(stages)
    x = jnp.asarray(np.random.default_rng(39).normal(size=(n_micro, mb, d)),
                    jnp.float32)
    mesh = create_mesh(MeshConfig(data=2, pipe=4))

    def loss(params, remat):
        return jnp.sum(pipeline_sharded(mesh, mlp_stage, params, x,
                                        interleave=2, remat=remat) ** 2)

    g_plain = jax.grad(lambda p: loss(p, False))(stacked)
    g_remat = jax.grad(lambda p: loss(p, True))(stacked)
    for a, b in zip(jax.tree.leaves(g_plain), jax.tree.leaves(g_remat)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_pipeline_interleaved_real_transformer_blocks():
    """Eight REAL transformer Blocks on pipe=4 with v=2 round-robin chunks:
    the circular schedule must match the sequential Encoder chain with the
    attention mask riding the payload."""
    from flax.core import meta

    from synapseml_tpu.models.flax_nets.transformer import (Block,
                                                            TransformerConfig)

    cfg = TransformerConfig(hidden=16, n_layers=8, n_heads=2, mlp_dim=32,
                            max_len=16, dtype=jnp.float32)
    block = Block(cfg)
    rs = np.random.default_rng(40)
    n_micro, mb, T = 4, 2, 8
    x = jnp.asarray(rs.normal(size=(n_micro, mb, T, cfg.hidden)), jnp.float32)
    mask_rows = rs.random((n_micro, mb, T)) > 0.2
    mask = jnp.asarray(mask_rows[:, :, None, None, :])

    layer_params = []
    for i in range(8):
        v = block.init(jax.random.PRNGKey(i), x[0], mask[0])
        layer_params.append(meta.unbox(v)["params"])
    stacked = stack_stage_params(layer_params)

    def stage(p, payload):
        h, m = payload
        return block.apply({"params": p}, h, m), m

    def sequential_blocks(xs, ms):
        y = xs
        for p in layer_params:
            y = jnp.stack([block.apply({"params": p}, y[i], ms[i])
                           for i in range(n_micro)])
        return y

    mesh = create_mesh(MeshConfig(data=2, pipe=4))
    out, _ = pipeline_sharded(mesh, stage, stacked, (x, mask), interleave=2)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(sequential_blocks(x, mask)),
                               rtol=2e-4, atol=2e-5)


def test_pipeline_interleaved_rejections():
    stages = make_stages(8, 4, seed=37)
    stacked = stack_stage_params(stages)
    mesh = create_mesh(MeshConfig(data=2, pipe=4))
    with pytest.raises(ValueError, match="divisible"):
        pipeline_sharded(mesh, mlp_stage, stacked,
                         jnp.zeros((6, 2, 4), jnp.float32), interleave=2)
    with pytest.raises(ValueError, match="pipe\\*interleave"):
        pipeline_sharded(mesh, mlp_stage, stacked,
                         jnp.zeros((8, 2, 4), jnp.float32), interleave=3)
    with pytest.raises(ValueError, match="io='replicated'"):
        pipeline_sharded(mesh, mlp_stage, stacked,
                         jnp.zeros((8, 2, 4), jnp.float32), interleave=2,
                         io="sharded")


def test_pipeline_real_transformer_blocks():
    """REAL transformer Blocks through the pipeline: an Encoder's per-layer
    params restack into stages, each stage applies its Block with the
    attention mask riding the payload — outputs match the sequential
    Encoder apply exactly."""
    from flax.core import meta

    from synapseml_tpu.models.flax_nets.transformer import (Block,
                                                            TransformerConfig)

    cfg = TransformerConfig(hidden=16, n_layers=4, n_heads=2, mlp_dim=32,
                            max_len=16, dtype=jnp.float32)
    block = Block(cfg)
    rs = np.random.default_rng(16)
    n_micro, mb, T = 4, 2, 8
    x = jnp.asarray(rs.normal(size=(n_micro, mb, T, cfg.hidden)), jnp.float32)
    mask_rows = rs.random((n_micro, mb, T)) > 0.2
    mask = jnp.asarray(mask_rows[:, :, None, None, :])  # [nm, mb, 1, 1, T]

    layer_params = []
    for i in range(4):
        v = block.init(jax.random.PRNGKey(i), x[0], mask[0])
        layer_params.append(meta.unbox(v)["params"])
    stacked = stack_stage_params(layer_params)

    def stage(p, payload):
        h, m = payload
        return block.apply({"params": p}, h, m), m

    def sequential_blocks(xs, ms):
        y = xs
        for p in layer_params:
            y = jnp.stack([block.apply({"params": p}, y[i], ms[i])
                           for i in range(n_micro)])
        return y

    mesh = create_mesh(MeshConfig(data=2, pipe=4))
    out, _ = pipeline_sharded(mesh, stage, stacked, (x, mask))
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(sequential_blocks(x, mask)),
                               rtol=2e-4, atol=2e-5)
