"""Real multi-process distributed bootstrap: DriverRendezvous + 2 OS worker
processes -> jax.distributed.initialize on CPU -> one cross-process psum.

The reference's NetworkManager semantics (``NetworkManager.scala:59-125``)
exercised with actual process boundaries, not just the in-process 8-device
mesh (VERDICT round-1 item 7)."""

import socket
import subprocess
import sys
import textwrap

import pytest

from synapseml_tpu.parallel.backend import DriverRendezvous

WORKER = textwrap.dedent("""
    import sys

    import jax

    jax.config.update("jax_platforms", "cpu")

    from synapseml_tpu.parallel.backend import initialize_backend

    driver_addr, executor_id, partition_id = sys.argv[1], sys.argv[2], int(sys.argv[3])
    backend = initialize_backend(driver_addr, executor_id=executor_id,
                                 partition_id=partition_id)
    assert backend.initialized and backend.world == 2
    print(f"RANK {backend.rank} procs {jax.process_count()} "
          f"devices {len(jax.devices())}", flush=True)

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    world = jax.process_count()
    mesh = Mesh(np.array(jax.devices()), ("data",))
    sharding = NamedSharding(mesh, P("data"))
    local = jnp.ones((1,), jnp.float32) * (backend.rank + 1)
    garr = jax.make_array_from_single_device_arrays(
        (world,), sharding, [jax.device_put(local, jax.local_devices()[0])])
    total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(garr)
    print(f"PSUM {float(total.addressable_data(0)):.1f}", flush=True)
""")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_rendezvous_and_psum(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)

    driver = DriverRendezvous(world_size=2, coordinator_port=_free_port())
    driver.start()
    addr = f"127.0.0.1:{driver.port}"

    env = {"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": "/root/repo", "HOME": "/root",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
    # launch in partition order 1, 0: rank assignment must follow partition id,
    # not arrival order (NetworkManager's min-partition ordering)
    procs = [subprocess.Popen([sys.executable, str(script), addr, f"exec-{p}", str(p)],
                              stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                              text=True, env=env)
             for p in (1, 0)]
    driver.join(timeout_s=120)
    outs = []
    for proc in procs:
        out, _ = proc.communicate(timeout=150)
        outs.append(out)
        assert proc.returncode == 0, f"worker failed:\n{out}"

    # partition 1 -> rank 1, partition 0 -> rank 0
    assert "RANK 1" in outs[0] and "RANK 0" in outs[1], outs
    for out in outs:
        assert "procs 2" in out and "devices 2" in out
        assert "PSUM 3.0" in out  # 1 + 2 across the two processes
