"""Real multi-process distributed bootstrap: DriverRendezvous + 2 OS worker
processes -> jax.distributed.initialize on CPU -> one cross-process psum.

The reference's NetworkManager semantics (``NetworkManager.scala:59-125``)
exercised with actual process boundaries, not just the in-process 8-device
mesh (VERDICT round-1 item 7)."""

import socket
import subprocess
import sys
import textwrap

import pytest

from synapseml_tpu.parallel.backend import DriverRendezvous

WORKER = textwrap.dedent("""
    import sys

    import jax

    jax.config.update("jax_platforms", "cpu")

    from synapseml_tpu.parallel.backend import initialize_backend

    driver_addr, executor_id, partition_id = sys.argv[1], sys.argv[2], int(sys.argv[3])
    backend = initialize_backend(driver_addr, executor_id=executor_id,
                                 partition_id=partition_id)
    assert backend.initialized and backend.world == 2
    print(f"RANK {backend.rank} procs {jax.process_count()} "
          f"devices {len(jax.devices())}", flush=True)

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    world = jax.process_count()
    mesh = Mesh(np.array(jax.devices()), ("data",))
    sharding = NamedSharding(mesh, P("data"))
    local = jnp.ones((1,), jnp.float32) * (backend.rank + 1)
    garr = jax.make_array_from_single_device_arrays(
        (world,), sharding, [jax.device_put(local, jax.local_devices()[0])])
    total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(garr)
    print(f"PSUM {float(total.addressable_data(0)):.1f}", flush=True)
""")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_two_workers(script_text: str, tmp_path, partition_order,
                     timeout_s: float = 420.0) -> list[str]:
    """Launch the worker script in 2 OS processes through a
    DriverRendezvous; return each worker's combined output (asserting
    rc=0). Shared by every multi-process test in this file."""
    import pathlib

    script = tmp_path / "worker.py"
    script.write_text(script_text)
    repo_root = str(pathlib.Path(__file__).resolve().parent.parent)

    driver = DriverRendezvous(world_size=2, coordinator_port=_free_port())
    driver.start()
    addr = f"127.0.0.1:{driver.port}"
    env = {"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": repo_root, "HOME": "/root",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
    procs = [subprocess.Popen(
        [sys.executable, str(script), addr, f"exec-{p}", str(p)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
        for p in partition_order]
    try:
        driver.join(timeout_s=120)
        # Thread.join returns silently on timeout: a live thread here means
        # the rendezvous never completed — fail NOW with worker output
        # instead of burning the communicate timeout on each worker
        if driver._thread.is_alive():
            tails = [p.stdout.read() if p.poll() is not None else "<running>"
                     for p in procs]
            raise TimeoutError(f"rendezvous incomplete after 120s: {tails}")
        outs = []
        for proc in procs:
            out, _ = proc.communicate(timeout=timeout_s)
            outs.append(out)
            assert proc.returncode == 0, f"worker failed:\n{out}"
        return outs
    finally:
        for proc in procs:  # never leave an orphaned worker pinning the CPU
            if proc.poll() is None:
                proc.kill()
                proc.wait()


def test_two_process_rendezvous_and_psum(tmp_path):
    # launch in partition order 1, 0: rank assignment must follow partition id,
    # not arrival order (NetworkManager's min-partition ordering)
    outs = _run_two_workers(WORKER, tmp_path, partition_order=(1, 0),
                            timeout_s=150)

    # partition 1 -> rank 1, partition 0 -> rank 0
    assert "RANK 1" in outs[0] and "RANK 0" in outs[1], outs
    for out in outs:
        assert "procs 2" in out and "devices 2" in out
        assert "PSUM 3.0" in out  # 1 + 2 across the two processes


GBDT_WORKER = textwrap.dedent("""
    import sys

    import jax

    jax.config.update("jax_platforms", "cpu")

    from synapseml_tpu.parallel.backend import initialize_backend

    driver_addr, executor_id, partition_id = sys.argv[1], sys.argv[2], int(sys.argv[3])
    backend = initialize_backend(driver_addr, executor_id=executor_id,
                                 partition_id=partition_id)
    assert backend.initialized and backend.world == 2

    import numpy as np
    from jax.sharding import Mesh

    from synapseml_tpu.gbdt.booster import train_booster

    # both processes hold the same global table; device_put scatters each
    # process's addressable row shard over the cross-process data axis
    rs = np.random.default_rng(0)
    N, F = 2000, 8
    X = rs.normal(size=(N, F)).astype(np.float32)
    w = rs.normal(size=F)
    y = ((X @ w) > 0).astype(np.float32)

    mesh = Mesh(np.array(jax.devices()), ("data",))
    b = train_booster(X, y, objective="binary", num_iterations=5,
                      learning_rate=0.3, num_leaves=7, max_depth=3,
                      min_data_in_leaf=5, seed=0, mesh=mesh)
    # forest arrays come back replicated: both ranks must hold the SAME model
    print("FEATSUM", int(np.sum(b.feature[b.feature >= 0])), flush=True)
    acc = float(((np.asarray(b.predict(X)).ravel() > 0.5) == (y > 0.5)).mean())
    print(f"ACC {acc:.3f}", flush=True)
    assert acc > 0.85, acc
""")


@pytest.mark.slow
def test_two_process_distributed_gbdt_training(tmp_path):
    """FULL GBDT training across 2 OS processes: rows shard over a
    cross-process data axis, so every level's histogram reduction IS a
    cross-process collective (the reference's NetworkManager socket-ring
    allreduce during LGBM_BoosterUpdateOneIter, ``TrainUtils.scala:98``) —
    and both ranks must finish holding the identical forest."""
    outs = _run_two_workers(GBDT_WORKER, tmp_path, partition_order=(0, 1))
    featsums = {ln for o in outs for ln in o.splitlines()
                if ln.startswith("FEATSUM")}
    assert len(featsums) == 1, featsums  # identical forest on both ranks
    for out in outs:
        assert "ACC " in out


DL_WORKER = textwrap.dedent("""
    import sys

    import jax

    jax.config.update("jax_platforms", "cpu")

    from synapseml_tpu.parallel.backend import initialize_backend

    driver_addr, executor_id, partition_id = sys.argv[1], sys.argv[2], int(sys.argv[3])
    backend = initialize_backend(driver_addr, executor_id=executor_id,
                                 partition_id=partition_id)
    assert backend.initialized and backend.world == 2

    import numpy as np

    from synapseml_tpu.models.flax_nets.bert import BertClassifier, bert_tiny
    from synapseml_tpu.models.trainer import Trainer, TrainerConfig
    from synapseml_tpu.parallel import MeshConfig
    from synapseml_tpu.parallel.mesh import create_mesh

    cfg = bert_tiny(n_layers=2)
    model = BertClassifier(cfg, num_classes=2)
    rs = np.random.default_rng(0)
    batch = {
        "input_ids": rs.integers(0, cfg.vocab_size, (8, 16)).astype(np.int32),
        "attention_mask": np.ones((8, 16), np.int32),
        "labels": rs.integers(0, 2, (8,)).astype(np.int32),
    }
    mesh = create_mesh(MeshConfig(data=-1))  # data axis spans both processes
    tr = Trainer(model, mesh, TrainerConfig(learning_rate=1e-3, total_steps=3))
    state = tr.init_state(batch)
    losses = []
    for _ in range(3):
        state, m = tr.train_step(state, batch)
        losses.append(float(m["loss"]))
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses
    print("LOSSES " + " ".join(f"{l:.6f}" for l in losses), flush=True)
""")


@pytest.mark.slow
def test_two_process_data_parallel_train_step(tmp_path):
    """The deep-learning trainer's data-parallel step across 2 OS
    processes: the gradient psum rides the cross-process mesh axis (the
    reference's horovod.spark allreduce role), losses decrease, and both
    ranks must observe the IDENTICAL loss curve (same replicated params)."""
    outs = _run_two_workers(DL_WORKER, tmp_path, partition_order=(0, 1))
    curves = {ln for o in outs for ln in o.splitlines()
              if ln.startswith("LOSSES")}
    assert len(curves) == 1, curves  # identical replicated training on both
