"""Fabric platform glue + platform detection (SURVEY §2.5: PlatformDetails,
FabricClient/TokenLibrary/FabricTokenParser, CertifiedEventClient) — the
whole surface unit-tested off-platform through injectable roots/envs."""

import base64
import json
import os

import numpy as np
import pytest

from synapseml_tpu.core.platform import (
    PLATFORM_DATABRICKS,
    PLATFORM_FABRIC,
    PLATFORM_SYNAPSE,
    PLATFORM_TPU_VM,
    PLATFORM_UNKNOWN,
    current_platform,
    running_on_fabric,
)
from synapseml_tpu.services.fabric import (
    FabricClient,
    InvalidJwtToken,
    JwtExpiryMissing,
    install_certified_events,
    log_to_certified_events,
    parse_jwt_expiry,
)


# ---------------------------------------------------------------------------
# platform detection
# ---------------------------------------------------------------------------

def _fabric_root(tmp_path, context_lines=(), spark_lines=(), cluster=None):
    ctx = tmp_path / "home" / "trusted-service-user"
    ctx.mkdir(parents=True, exist_ok=True)
    (ctx / ".trident-context").write_text("\n".join(context_lines) + "\n")
    if spark_lines:
        conf = tmp_path / "opt" / "spark" / "conf"
        conf.mkdir(parents=True, exist_ok=True)
        (conf / "spark-defaults.conf").write_text("\n".join(spark_lines) + "\n")
    if cluster is not None:
        info = tmp_path / "opt" / "health-agent" / "conf"
        info.mkdir(parents=True, exist_ok=True)
        (info / "cluster-info.json").write_text(json.dumps(cluster))
    return str(tmp_path)


def test_platform_detection_precedence(tmp_path):
    assert current_platform(env={}, root=str(tmp_path)) == PLATFORM_UNKNOWN
    assert current_platform(env={"TPU_NAME": "v5e-16"},
                            root=str(tmp_path)) == PLATFORM_TPU_VM
    assert current_platform(env={"AZURE_SERVICE": "Microsoft.ProjectArcadia"},
                            root=str(tmp_path)) == PLATFORM_SYNAPSE
    (tmp_path / "dbfs").mkdir()
    assert current_platform(env={}, root=str(tmp_path)) == PLATFORM_DATABRICKS
    root = _fabric_root(tmp_path)  # trident-context wins over everything
    assert current_platform(env={"TPU_NAME": "x"}, root=root) == PLATFORM_FABRIC
    assert running_on_fabric(env={}, root=root)


# ---------------------------------------------------------------------------
# FabricClient context / endpoints
# ---------------------------------------------------------------------------

def make_client(tmp_path, **kw):
    root = _fabric_root(
        tmp_path,
        context_lines=[
            "trident.capacity.id=cap-123",
            "trident.artifact.workspace.id=AB-work-456",
            "trident.artifact.id=art-789",
            "ambiguous=a=b",              # double-separator line: dropped
        ],
        spark_lines=[
            "# comment",
            "spark.trident.pbienv MSIT",
            "trident.lakehouse.tokenservice.endpoint https://tokens.fabric.example.com/x/y",
        ],
        cluster=kw.pop("cluster", None))
    return FabricClient(root=root, env=kw.pop("env", {}), **kw)


def test_context_parsing_and_ids(tmp_path):
    c = make_client(tmp_path)
    assert c.capacity_id == "cap-123"
    assert c.workspace_id == "AB-work-456"
    assert c.artifact_id == "art-789"
    assert "ambiguous" not in c.context       # reference drops double-= lines
    assert c.pbi_env == "msit"                # lowercased


def test_spark_conf_whitespace_forms(tmp_path):
    # real spark-defaults.conf separates with tabs or aligned multi-space
    root = _fabric_root(
        tmp_path,
        spark_lines=["spark.a\tv1", "spark.b      v2", "spark.c v3 extra"])
    c = FabricClient(root=root, env={})
    assert c.context["spark.a"] == "v1"
    assert c.context["spark.b"] == "v2"
    assert "spark.c" not in c.context  # multi-token value: ambiguous, dropped


def test_ml_workload_endpoint(tmp_path):
    c = make_client(tmp_path)
    assert c.ml_workload_host == "https://tokens.fabric.example.com"
    ep = c.ml_workload_endpoint("ML")
    assert ep == ("https://tokens.fabric.example.com/webapi/capacities/"
                  "cap-123/workloads/ML/ML/Automatic/workspaceid/"
                  "AB-work-456/")
    assert c.openai_endpoint.endswith("/cognitive/openai/")


def test_private_endpoint_hosts(tmp_path):
    c = make_client(tmp_path,
                    cluster={"cluster_metadata": {"workspace-pe-enabled": "True"}})
    # cleaned workspace id: lowercase, dashes stripped; msit env mark applied
    assert c.ml_workload_host == \
        "https://abwork456.zab.msit-c.fabric.microsoft.com"
    assert c.pbi_shared_host == \
        "https://abwork456.zab.w.msitapi.fabric.microsoft.com"


def test_pbi_shared_host_env_table(tmp_path):
    c = make_client(tmp_path)
    assert c.pbi_shared_host == "https://msitapi.fabric.microsoft.com"


# ---------------------------------------------------------------------------
# JWT expiry (FabricTokenParser)
# ---------------------------------------------------------------------------

def _jwt(payload: dict) -> str:
    seg = base64.urlsafe_b64encode(json.dumps(payload).encode()
                                   ).decode().rstrip("=")
    return f"hdr.{seg}.sig"


def test_parse_jwt_expiry():
    assert parse_jwt_expiry(_jwt({"exp": 1700000000})) == 1700000000000
    with pytest.raises(JwtExpiryMissing):
        parse_jwt_expiry(_jwt({"sub": "x"}))
    with pytest.raises(InvalidJwtToken):
        parse_jwt_expiry("only.two")
    with pytest.raises(InvalidJwtToken):
        parse_jwt_expiry("a.!!!!.c")


# ---------------------------------------------------------------------------
# auth + certified events
# ---------------------------------------------------------------------------

def test_usage_post_auth_headers(tmp_path):
    sent = []
    c = make_client(tmp_path, env={"SYNAPSEML_TPU_FABRIC_TOKEN": "tok123"},
                    http_send=lambda req: sent.append(req))
    c.usage_post("https://x.example/telemetry", {"a": 1})
    (req,) = sent
    assert req.headers["Authorization"] == "Bearer tok123"
    assert "RequestId" in req.headers
    assert json.loads(req.entity) == {"a": 1}


def test_access_token_requires_provider_off_platform(tmp_path):
    c = make_client(tmp_path)
    with pytest.raises(RuntimeError, match="token"):
        c.access_token()
    c2 = make_client(tmp_path, token_provider=lambda: "prov")
    assert c2.access_token() == "prov"


def test_certified_events_noop_off_fabric(tmp_path):
    sent = []
    c = FabricClient(root=str(tmp_path / "nowhere"), env={},
                     http_send=lambda req: sent.append(req))
    assert log_to_certified_events("gbdt", "fit", client=c) is False
    assert not sent


def test_certified_events_post_on_fabric(tmp_path):
    sent = []
    c = make_client(tmp_path, env={"SYNAPSEML_TPU_FABRIC_TOKEN": "t"},
                    http_send=lambda req: sent.append(req))
    assert log_to_certified_events("gbdt", "fit", {"rows": "10"},
                                   client=c) is True
    (req,) = sent
    assert req.url.endswith("/workloads/ML/MLAdmin/Automatic/workspaceid/"
                            "AB-work-456/telemetry")
    body = json.loads(req.entity)
    assert body["feature_name"] == "gbdt" and body["activity_name"] == "fit"


def test_assert_model_status(tmp_path):
    from synapseml_tpu.services.fabric import assert_model_status

    class FakeResp:
        def __init__(self, body):
            self._body = body

        def json(self):
            return self._body

    def client_with(status):
        return make_client(
            tmp_path, env={"SYNAPSEML_TPU_FABRIC_TOKEN": "t"},
            http_send=lambda req: FakeResp({"gpt-4o-mini": status}))

    assert_model_status("gpt-4o-mini", client_with("Allowed"))  # no raise
    with pytest.raises(RuntimeError, match="Disallowed"):
        assert_model_status("gpt-4o-mini", client_with("Disallowed"))
    with pytest.raises(RuntimeError, match="Disallowed"):
        # service keys lowercase; a mixed-case request must still match
        assert_model_status("GPT-4o-Mini", client_with("Disallowed"))
    with pytest.raises(RuntimeError, match="not found"):
        assert_model_status("gpt-4o-mini", client_with("ModelNotFound"))
    # transport failure: advisory no-op (system-context Fabric)
    boom = make_client(tmp_path, env={"SYNAPSEML_TPU_FABRIC_TOKEN": "t"},
                       http_send=lambda req: (_ for _ in ()).throw(OSError()))
    assert_model_status("gpt-4o-mini", boom)


def test_telemetry_sinks_receive_scrubbed_payloads():
    from synapseml_tpu.core import logging as stage_logging

    got = []
    sink = got.append
    stage_logging.add_telemetry_sink(sink)
    try:
        stage_logging.log_stage_event(
            {"uid": "u1", "error": "HTTPError https://x/?sig=SECRET123&a=1"})
    finally:
        stage_logging.remove_telemetry_sink(sink)
    assert not stage_logging._TELEMETRY_SINKS
    assert got and "SECRET123" not in got[0]["error"]
    assert "sig=####" in got[0]["error"]


def test_install_certified_events_fires_from_stage_telemetry(tmp_path):
    import synapseml_tpu as st
    from synapseml_tpu.core import logging as stage_logging
    from synapseml_tpu.stages import SelectColumns

    sent = []
    c = make_client(tmp_path, env={"SYNAPSEML_TPU_FABRIC_TOKEN": "t"},
                    http_send=lambda req: sent.append(req))
    first = install_certified_events(client=c)
    # idempotent: re-install replaces, never stacks — and the replaced
    # sink's worker thread must exit instead of leaking on its queue
    sink = install_certified_events(client=c)
    assert stage_logging._TELEMETRY_SINKS.count(sink) == 1
    assert first not in stage_logging._TELEMETRY_SINKS
    first._thread.join(timeout=5)
    assert not first._thread.is_alive(), "replaced worker thread leaked"
    try:
        df = st.DataFrame.from_dict({"a": np.arange(3), "b": np.arange(3)})
        SelectColumns(cols=["a"]).transform(df)
        sink._queue.join()  # posting is ASYNC — drain the worker queue
        assert sent, "stage transform did not emit a certified event"
        body = json.loads(sent[-1].entity)
        assert body["activity_name"] == "transform"
    finally:
        stage_logging.remove_telemetry_sink(sink)
