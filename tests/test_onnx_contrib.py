"""com.microsoft contrib ops (the ORT transformer-fusion opset) vs numpy
oracles, plus an end-to-end fused-BERT-block graph of the shape ORT's
optimizer emits (EmbedLayerNormalization -> Attention ->
SkipLayerNormalization -> FusedMatMul/BiasGelu) run through ConvertedModel."""

import numpy as np
import pytest

from synapseml_tpu.onnx import (
    AttributeProto,
    GraphProto,
    ModelProto,
    NodeProto,
    ValueInfoProto,
    numpy_to_tensor,
)
from synapseml_tpu.onnx import proto as P
from synapseml_tpu.onnx.convert import OP_REGISTRY, ConvertedModel

rs = np.random.default_rng(0)


def run_op(opname, ins, **attrs):
    return OP_REGISTRY[opname](
        [None if x is None else np.asarray(x) for x in ins], attrs)


def node(op, inputs, outputs, domain="com.microsoft", **attrs):
    return NodeProto(input=list(inputs), output=list(outputs), op_type=op,
                     domain=domain,
                     attribute=[AttributeProto.make(k, v)
                                for k, v in attrs.items()])


def np_gelu(x):
    from scipy.special import erf
    return 0.5 * x * (1 + erf(x / np.sqrt(2)))


def np_layernorm(h, gamma, beta, eps=1e-12):
    mean = h.mean(-1, keepdims=True)
    var = ((h - mean) ** 2).mean(-1, keepdims=True)
    return (h - mean) / np.sqrt(var + eps) * gamma + beta


def test_bias_gelu_and_fast_gelu():
    x = rs.normal(size=(3, 8)).astype(np.float32)
    b = rs.normal(size=(8,)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(run_op("BiasGelu", [x, b])),
                               np_gelu(x + b), rtol=1e-5, atol=1e-6)
    # FastGelu is the tanh approximation (+ optional bias)
    h = x + b
    expect = 0.5 * h * (1 + np.tanh(0.7978845608 * (h + 0.044715 * h ** 3)))
    np.testing.assert_allclose(np.asarray(run_op("FastGelu", [x, b])), expect,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(run_op("QuickGelu", [x], alpha=1.702)),
        x / (1 + np.exp(-1.702 * x)), rtol=1e-5, atol=1e-6)


def test_skip_layer_normalization():
    x = rs.normal(size=(2, 4, 8)).astype(np.float32)
    skip = rs.normal(size=(2, 4, 8)).astype(np.float32)
    gamma = rs.normal(size=(8,)).astype(np.float32)
    beta = rs.normal(size=(8,)).astype(np.float32)
    bias = rs.normal(size=(8,)).astype(np.float32)
    out = run_op("SkipLayerNormalization", [x, skip, gamma, beta, bias],
                 epsilon=1e-12)
    np.testing.assert_allclose(np.asarray(out[0]),
                               np_layernorm(x + skip + bias, gamma, beta),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out[3]), x + skip + bias, rtol=1e-6)


def test_embed_layer_normalization():
    V, S, H = 20, 6, 8
    ids = rs.integers(0, V, (2, S)).astype(np.int64)
    seg = rs.integers(0, 2, (2, S)).astype(np.int64)
    word = rs.normal(size=(V, H)).astype(np.float32)
    pos = rs.normal(size=(S + 2, H)).astype(np.float32)
    segemb = rs.normal(size=(2, H)).astype(np.float32)
    gamma = np.ones(H, np.float32)
    beta = np.zeros(H, np.float32)
    mask = np.asarray([[1, 1, 1, 1, 0, 0], [1, 1, 0, 0, 0, 0]], np.int64)
    out, mask_index, emb_sum = run_op(
        "EmbedLayerNormalization",
        [ids, seg, word, pos, segemb, gamma, beta, mask])
    expect_sum = word[ids] + pos[:S][None] + segemb[seg]
    np.testing.assert_allclose(np.asarray(emb_sum), expect_sum, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out),
                               np_layernorm(expect_sum, gamma, beta),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(mask_index), [4, 2])


def test_fused_matmul():
    a = rs.normal(size=(3, 4)).astype(np.float32)
    b = rs.normal(size=(5, 4)).astype(np.float32)
    out = run_op("FusedMatMul", [a, b], transB=1, alpha=0.5)
    np.testing.assert_allclose(np.asarray(out), 0.5 * (a @ b.T), rtol=1e-5)


def np_attention(x, w, b, n_heads, key_mask=None, unidirectional=False):
    B, S, _ = x.shape
    qkv = x @ w + b
    H = qkv.shape[-1] // 3
    d = H // n_heads
    q, k, v = np.split(qkv, 3, axis=-1)

    def heads(t):
        return t.reshape(B, S, n_heads, d).transpose(0, 2, 1, 3)

    q, k, v = heads(q), heads(k), heads(v)
    scores = np.einsum("bnqd,bnkd->bnqk", q, k) / np.sqrt(d)
    if key_mask is not None:
        scores = np.where(key_mask[:, None, None, :].astype(bool), scores, -1e30)
    if unidirectional:
        causal = np.tril(np.ones((S, S), bool))
        scores = np.where(causal[None, None], scores, -1e30)
    e = np.exp(scores - scores.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    out = np.einsum("bnqk,bnkd->bnqd", p, v)
    return out.transpose(0, 2, 1, 3).reshape(B, S, H)


@pytest.mark.parametrize("mask_kind", [None, "raw2d", "lengths"])
@pytest.mark.parametrize("unidirectional", [0, 1])
def test_attention(mask_kind, unidirectional):
    B, S, Hin, n_heads = 2, 6, 8, 2
    x = rs.normal(size=(B, S, Hin)).astype(np.float32)
    w = (rs.normal(size=(Hin, 3 * Hin)) * 0.3).astype(np.float32)
    b = rs.normal(size=(3 * Hin,)).astype(np.float32)
    raw = np.asarray([[1, 1, 1, 1, 1, 0], [1, 1, 1, 0, 0, 0]], np.int64)
    if mask_kind is None:
        mask, key_mask = None, None
    elif mask_kind == "raw2d":
        mask, key_mask = raw, raw
    else:
        mask = raw.sum(1)                       # right-padded lengths
        key_mask = np.arange(S)[None] < mask[:, None]
    got = np.asarray(run_op("Attention", [x, w, b, mask],
                            num_heads=n_heads, unidirectional=unidirectional))
    expect = np_attention(x, w, b, n_heads, key_mask, bool(unidirectional))
    np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5)


def test_attention_custom_scale():
    B, S, Hin = 1, 4, 8
    x = rs.normal(size=(B, S, Hin)).astype(np.float32)
    w = (rs.normal(size=(Hin, 3 * Hin)) * 0.3).astype(np.float32)
    b = np.zeros(3 * Hin, np.float32)
    got = np.asarray(run_op("Attention", [x, w, b], num_heads=2, scale=0.125))
    # oracle with the custom scale folded in (heads d=4 -> default would be 0.5)
    qkv = x @ w
    q, k, v = np.split(qkv.reshape(B, S, 3, 2, 4).transpose(2, 0, 3, 1, 4), 3)
    q, k, v = q[0], k[0], v[0]
    s = np.einsum("bnqd,bnkd->bnqk", q, k) * 0.125
    e = np.exp(s - s.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    expect = np.einsum("bnqk,bnkd->bnqd", p, v).transpose(0, 2, 1, 3).reshape(B, S, Hin)
    np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5)


def test_unsupported_fusion_forms_rejected():
    x = rs.normal(size=(1, 2, 4)).astype(np.float32)
    w = rs.normal(size=(4, 12)).astype(np.float32)
    b = np.zeros(12, np.float32)
    with pytest.raises(NotImplementedError, match="rotary"):
        run_op("Attention", [x, w, b], num_heads=1, do_rotary=1)
    with pytest.raises(NotImplementedError, match="transBatch"):
        run_op("FusedMatMul", [x, x], transBatchA=1)


def test_attention_past_rejected():
    x = rs.normal(size=(1, 2, 4)).astype(np.float32)
    w = rs.normal(size=(4, 12)).astype(np.float32)
    b = np.zeros(12, np.float32)
    past = np.zeros((2, 1, 1, 2, 2), np.float32)
    with pytest.raises(NotImplementedError, match="past"):
        run_op("Attention", [x, w, b, None, past], num_heads=1)


def test_fused_bert_block_graph():
    """The ORT-optimizer output shape: EmbedLayerNormalization -> Attention ->
    SkipLayerNormalization -> FusedMatMul+BiasGelu -> FusedMatMul ->
    SkipLayerNormalization, as one ConvertedModel — vs a numpy oracle."""
    V, S, H, n_heads, F = 30, 6, 8, 2, 16
    ids = rs.integers(0, V, (2, S)).astype(np.int64)
    mask = np.asarray([[1] * 6, [1, 1, 1, 1, 0, 0]], np.int64)
    word = (rs.normal(size=(V, H)) * 0.5).astype(np.float32)
    pos = (rs.normal(size=(S, H)) * 0.5).astype(np.float32)
    g1, b1 = np.ones(H, np.float32), np.zeros(H, np.float32)
    wq = (rs.normal(size=(H, 3 * H)) * 0.3).astype(np.float32)
    bq = np.zeros(3 * H, np.float32)
    g2, b2 = np.ones(H, np.float32), np.zeros(H, np.float32)
    w_up = (rs.normal(size=(H, F)) * 0.3).astype(np.float32)
    b_up = rs.normal(size=(F,)).astype(np.float32)
    w_dn = (rs.normal(size=(F, H)) * 0.3).astype(np.float32)
    g3, b3 = np.ones(H, np.float32), np.zeros(H, np.float32)

    g = GraphProto(
        name="fused_bert_block",
        node=[
            node("EmbedLayerNormalization",
                 ["ids", "", "word", "pos", "", "g1", "b1", "mask"],
                 ["emb", "mask_idx"], epsilon=1e-12),
            node("Attention", ["emb", "wq", "bq", "mask"], ["attn"],
                 num_heads=n_heads),
            node("SkipLayerNormalization", ["attn", "emb", "g2", "b2"],
                 ["h1"], epsilon=1e-12),
            node("FusedMatMul", ["h1", "w_up"], ["up"]),
            node("BiasGelu", ["up", "b_up"], ["act"]),
            node("FusedMatMul", ["act", "w_dn"], ["down"]),
            node("SkipLayerNormalization", ["down", "h1", "g3", "b3"],
                 ["out"], epsilon=1e-12),
        ],
        initializer=[numpy_to_tensor(a, n) for a, n in [
            (word, "word"), (pos, "pos"), (g1, "g1"), (b1, "b1"),
            (wq, "wq"), (bq, "bq"), (g2, "g2"), (b2, "b2"),
            (w_up, "w_up"), (b_up, "b_up"), (w_dn, "w_dn"),
            (g3, "g3"), (b3, "b3")]],
        input=[ValueInfoProto(name="ids", elem_type=P.INT64, dims=["B", S]),
               ValueInfoProto(name="mask", elem_type=P.INT64, dims=["B", S])],
        output=[ValueInfoProto(name="out", elem_type=P.FLOAT,
                               dims=["B", S, H])],
    )
    m = ConvertedModel(ModelProto(graph=g))
    got = np.asarray(m(ids=ids, mask=mask)["out"])

    emb = np_layernorm(word[ids] + pos[None], g1, b1)
    attn = np_attention(emb, wq, bq, n_heads, mask)
    h1 = np_layernorm(attn + emb, g2, b2)
    act = np_gelu(h1 @ w_up + b_up)
    expect = np_layernorm(act @ w_dn + h1, g3, b3)
    np.testing.assert_allclose(got, expect, rtol=1e-3, atol=1e-4)


def test_embed_layer_norm_position_ids():
    V, S, H = 12, 4, 8
    ids = rs.integers(0, V, (2, S)).astype(np.int64)
    word = rs.normal(size=(V, H)).astype(np.float32)
    pos = rs.normal(size=(10, H)).astype(np.float32)
    gamma, beta = np.ones(H, np.float32), np.zeros(H, np.float32)
    pos_ids = np.asarray([[5, 6, 7, 8], [0, 1, 2, 3]], np.int64)
    out, _, emb_sum = run_op(
        "EmbedLayerNormalization",
        [ids, None, word, pos, None, gamma, beta, None, pos_ids])
    expect = word[ids] + pos[pos_ids]
    np.testing.assert_allclose(np.asarray(emb_sum), expect, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out), np_layernorm(expect, gamma, beta),
                               rtol=1e-4, atol=1e-5)


def test_attention_additive_bias_input():
    B, S, Hin = 1, 4, 8
    x = rs.normal(size=(B, S, Hin)).astype(np.float32)
    w = (rs.normal(size=(Hin, 3 * Hin)) * 0.3).astype(np.float32)
    b = np.zeros(3 * Hin, np.float32)
    bias = rs.normal(size=(1, 2, S, S)).astype(np.float32)  # per-head additive
    got = np.asarray(run_op("Attention", [x, w, b, None, None, bias],
                            num_heads=2))
    # oracle with the bias folded into scores
    qkv = x @ w
    q, k, v = np.split(qkv.reshape(B, S, 3, 2, 4).transpose(2, 0, 3, 1, 4), 3)
    q, k, v = q[0], k[0], v[0]
    s = np.einsum("bnqd,bnkd->bnqk", q, k) / 2.0 + bias
    e = np.exp(s - s.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    expect = np.einsum("bnqk,bnkd->bnqd", p, v).transpose(0, 2, 1, 3).reshape(B, S, Hin)
    np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5)
