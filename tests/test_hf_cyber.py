"""hf (causal LM generation + embedder) and cyber (AccessAnomaly, scalers)."""

import numpy as np
import pytest

from synapseml_tpu.core import DataFrame
from synapseml_tpu.cyber import (
    AccessAnomaly,
    ComplementAccessTransformer,
    IdIndexer,
    PartitionedMinMaxScaler,
    PartitionedStandardScaler,
)
from synapseml_tpu.hf import HuggingFaceCausalLM, HuggingFaceSentenceEmbedder


# ---------------- hf ----------------

def test_causal_lm_generates():
    df = DataFrame.from_dict({"prompt": ["hello world", "the quick brown fox",
                                         "a"]}, num_partitions=2)
    lm = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=5,
                             prompt_bucket=8, batch_size=2)
    out = lm.transform(df)
    gens = out.collect_column("completions")
    assert len(gens) == 3
    for g in gens:
        assert len(np.asarray(g)) == 5  # token ids (hashing tokenizer, no decode)
    # deterministic greedy decode
    gens2 = lm.transform(df).collect_column("completions")
    for a, b in zip(gens, gens2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_causal_lm_chat_mode():
    msgs = np.empty(1, dtype=object)
    msgs[0] = [{"role": "system", "content": "be brief"},
               {"role": "user", "content": "hi"}]
    df = DataFrame.from_dict({"messages": msgs})
    lm = HuggingFaceCausalLM(model_name="llama-tiny", messages_col="messages",
                             max_new_tokens=3, prompt_bucket=16, batch_size=1)
    out = lm.transform(df).collect_column("completions")
    assert len(np.asarray(out[0])) == 3


def test_sentence_embedder():
    df = DataFrame.from_dict({"text": ["alpha beta", "alpha beta", "zzz qqq xxx"]},
                             num_partitions=2)
    emb = HuggingFaceSentenceEmbedder(model_name="bert-tiny", batch_size=2,
                                      max_token_len=16, normalize=True)
    out = emb.transform(df)
    E = np.stack(list(out.collect_column("embeddings")))
    assert E.shape[0] == 3
    np.testing.assert_allclose(np.linalg.norm(E, axis=1), 1.0, atol=1e-5)
    # identical texts -> identical embeddings; different text -> different
    np.testing.assert_allclose(E[0], E[1], atol=1e-6)
    assert np.abs(E[0] - E[2]).max() > 1e-4
    # cls pooling differs from mean pooling
    emb_cls = HuggingFaceSentenceEmbedder(model_name="bert-tiny", pooling="cls",
                                          batch_size=2, max_token_len=16)
    E_cls = np.stack(list(emb_cls.transform(df).collect_column("embeddings")))
    assert np.abs(E - E_cls).max() > 1e-4


# ---------------- cyber ----------------

def make_access_df(seed=0):
    """Two tenants; in tenant A, users u0-u3 access r0-r3 heavily, u4 only r9."""
    rs = np.random.default_rng(seed)
    rows = {"tenant": [], "user": [], "res": []}
    for _ in range(300):
        u = f"u{rs.integers(0, 4)}"
        r = f"r{rs.integers(0, 4)}"
        rows["tenant"].append("A")
        rows["user"].append(u)
        rows["res"].append(r)
    for _ in range(30):
        rows["tenant"].append("A")
        rows["user"].append("u4")
        rows["res"].append("r9")
    for _ in range(50):
        rows["tenant"].append("B")
        rows["user"].append(f"u{rs.integers(0, 3)}")
        rows["res"].append(f"s{rs.integers(0, 3)}")
    return DataFrame.from_dict({k: np.asarray(v, dtype=object)
                                for k, v in rows.items()})


def test_access_anomaly():
    df = make_access_df()
    model = AccessAnomaly(tenant_col="tenant", rank=4, max_iter=8).fit(df)
    # normal access (u0 -> r0, heavily seen) vs cross-clique (u4 -> r0: never)
    test = DataFrame.from_dict({
        "tenant": np.asarray(["A", "A", "A"], dtype=object),
        "user": np.asarray(["u0", "u4", "unknown_user"], dtype=object),
        "res": np.asarray(["r0", "r0", "r0"], dtype=object)})
    scores = model.transform(test).collect_column("anomaly_score")
    assert scores[1] > scores[0] + 0.5   # unusual access scores higher
    assert scores[2] == 2.0              # unseen entity
    # unknown tenant -> nan
    t2 = DataFrame.from_dict({"tenant": np.asarray(["Z"], dtype=object),
                              "user": np.asarray(["u0"], dtype=object),
                              "res": np.asarray(["r0"], dtype=object)})
    assert np.isnan(model.transform(t2).collect_column("anomaly_score")[0])


def test_access_anomaly_sparse_matches_dense():
    # the edge-list ALS is the same math as the dense solver — identical
    # init (same seed, same shapes), so factors must agree to float tolerance
    from synapseml_tpu.cyber.anomaly import _als, _als_sparse

    rs = np.random.default_rng(0)
    U, R, nnz = 40, 25, 300
    u = rs.integers(0, U, nnz)
    r = rs.integers(0, R, nnz)
    w = rs.uniform(0.5, 3.0, nnz)
    w[:15] = 0.0  # zero-weight edges: preference 0 on both paths
    counts = np.zeros((U, R))
    np.add.at(counts, (u, r), w)
    key = u.astype(np.int64) * R + r
    uniq, inv = np.unique(key, return_inverse=True)
    w_agg = np.zeros(len(uniq))
    np.add.at(w_agg, inv, w)

    uf_d, rf_d = _als(counts, rank=6, reg=0.1, n_iter=6, seed=3)
    uf_s, rf_s = _als_sparse(uniq // R, uniq % R, w_agg, U, R,
                             rank=6, reg=0.1, n_iter=6, seed=3)
    np.testing.assert_allclose(uf_s, uf_d, rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(rf_s, rf_d, rtol=2e-3, atol=2e-4)


def test_access_anomaly_sparse_path_through_estimator(monkeypatch):
    # force the sparse solver for the public fit/transform flow: the same
    # behavioral guarantees as the dense path must hold
    from synapseml_tpu.cyber import anomaly as anomaly_mod

    monkeypatch.setattr(anomaly_mod, "_DENSE_LIMIT", 0)
    df = make_access_df()
    model = AccessAnomaly(tenant_col="tenant", rank=4, max_iter=8).fit(df)
    test = DataFrame.from_dict({
        "tenant": np.asarray(["A", "A"], dtype=object),
        "user": np.asarray(["u0", "u4"], dtype=object),
        "res": np.asarray(["r0", "r0"], dtype=object)})
    scores = model.transform(test).collect_column("anomaly_score")
    assert scores[1] > scores[0] + 0.5


@pytest.mark.slow
def test_access_anomaly_large_tenant_gate():
    # >=100k interactions on a tenant whose U*R cell count (5M) exceeds
    # _DENSE_LIMIT: fitting must take the edge-list path (never building
    # the dense matrix) and still separate in-clique from cross-clique
    from synapseml_tpu.cyber.anomaly import _DENSE_LIMIT

    rs = np.random.default_rng(0)
    U, R, n = 5000, 1000, 120_000
    assert U * R > _DENSE_LIMIT
    # two cliques: users 0..U/2 access resources 0..R/2, rest the other half
    uu = rs.integers(0, U, n)
    clique = (uu < U // 2).astype(np.int64)
    rr = rs.integers(0, R // 2, n) + (1 - clique) * (R // 2)
    df = DataFrame.from_dict({
        "user": np.char.add("u", uu.astype(str)).astype(object),
        "res": np.char.add("r", rr.astype(str)).astype(object)})
    model = AccessAnomaly(rank=8, max_iter=4).fit(df)
    probe = DataFrame.from_dict({
        "user": np.asarray(["u10", "u10"], dtype=object),
        "res": np.asarray(["r10", f"r{R - 10}"], dtype=object)})
    s = model.transform(probe).collect_column("anomaly_score")
    assert s[1] > s[0] + 0.5, s  # cross-clique access is anomalous


def test_complement_access():
    df = make_access_df()
    comp = ComplementAccessTransformer(tenant_col="tenant", factor=1, seed=0)
    out = comp.transform(df)
    assert out.count() > 0
    seen = set(zip(df.collect_column("tenant"), df.collect_column("user"),
                   df.collect_column("res")))
    for row in out.collect_rows():
        assert (row["tenant"], row["user"], row["res"]) not in seen


def test_partitioned_scalers():
    df = DataFrame.from_dict({
        "tenant": np.asarray(["A"] * 50 + ["B"] * 50, dtype=object),
        "value": np.concatenate([np.random.default_rng(0).normal(10, 2, 50),
                                 np.random.default_rng(1).normal(-5, 0.5, 50)])})
    out = (PartitionedStandardScaler(tenant_col="tenant", input_col="value")
           .fit(df).transform(df))
    scaled = out.collect_column("scaled")
    tenants = out.collect_column("tenant")
    for t in ("A", "B"):
        vals = scaled[tenants == t]
        assert abs(vals.mean()) < 1e-9
        assert abs(vals.std() - 1.0) < 1e-9

    mm = (PartitionedMinMaxScaler(tenant_col="tenant", input_col="value",
                                  min_value=0.0, max_value=1.0).fit(df).transform(df))
    mvals = mm.collect_column("scaled")
    assert mvals.min() == pytest.approx(0.0) and mvals.max() == pytest.approx(1.0)


def test_id_indexer():
    df = DataFrame.from_dict({
        "tenant": np.asarray(["A", "A", "B", "B"], dtype=object),
        "user": np.asarray(["x", "y", "x", "z"], dtype=object)})
    model = IdIndexer(tenant_col="tenant", input_col="user").fit(df)
    ids = model.transform(df).collect_column("user_id")
    assert ids[0] != ids[1]          # distinct users distinct ids
    assert ids[0] == 0 and ids[2] == 0  # per-tenant reset
    unseen = DataFrame.from_dict({"tenant": np.asarray(["A"], dtype=object),
                                  "user": np.asarray(["nope"], dtype=object)})
    assert model.transform(unseen).collect_column("user_id")[0] == -1


def test_causal_lm_sharded_inference_matches_unsharded():
    """Sharded batch inference (the Llama-2-7B BASELINE config shape): params
    distributed over tensor/fsdp axes must generate the SAME tokens as the
    single-device path."""
    import jax

    from synapseml_tpu.hf import HuggingFaceCausalLM
    from synapseml_tpu.models.flax_nets.llama import LlamaLM, llama_tiny
    from synapseml_tpu.models.tokenizer import HashingTokenizer
    from synapseml_tpu.parallel import MeshConfig

    tok = HashingTokenizer(vocab_size=256)
    cfg = llama_tiny(vocab_size=256)
    import jax.numpy as jnp

    params = LlamaLM(cfg).init(jax.random.PRNGKey(1),
                               jnp.zeros((1, 8), jnp.int32))["params"]
    df = DataFrame.from_rows([{"prompt": "the quick brown fox"},
                              {"prompt": "hello world again"}])
    kw = dict(model_name="llama-tiny", model_params=params, tokenizer=tok,
              max_new_tokens=6, batch_size=4, prompt_bucket=8)
    plain = HuggingFaceCausalLM(**kw).transform(df)
    sharded = HuggingFaceCausalLM(
        **kw, mesh_config=MeshConfig(data=2, fsdp=2, tensor=2, seq=1)).transform(df)
    a = [np.asarray(x) for x in plain.collect_column("completions")]
    b = [np.asarray(x) for x in sharded.collect_column("completions")]
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)

    # weights are actually distributed: a sharded param has >1 addressable shard
    from flax.core import meta
    from synapseml_tpu.models.flax_nets.llama import LlamaLM as _L
    from synapseml_tpu.parallel.mesh import create_mesh, shard_inference_params

    mesh = create_mesh(MeshConfig(data=2, fsdp=2, tensor=2, seq=1),
                       allow_fewer=False)
    plainp = jax.tree.map(lambda x: x.value if isinstance(x, meta.Partitioned) else x,
                          params, is_leaf=lambda x: isinstance(x, meta.Partitioned))
    placed = shard_inference_params(_L(cfg), {"input_ids": jnp.zeros((1, 8), jnp.int32)},
                                    plainp, mesh)
    emb = placed["embed"]["embedding"]
    # genuinely partitioned, not replicated: each shard holds a strict subset
    shard0 = emb.addressable_shards[0].data
    assert shard0.shape != emb.shape and int(np.prod(shard0.shape)) < int(np.prod(emb.shape))
    # mlp kernels shard over tensor too
    up = placed["decoder"]["layer_0"]["mlp"]["up"]["kernel"]
    assert up.addressable_shards[0].data.shape != up.shape


def test_sentence_embedder_sharded_matches_unsharded():
    from synapseml_tpu.hf import HuggingFaceSentenceEmbedder
    from synapseml_tpu.parallel import MeshConfig

    df = DataFrame.from_rows([{"text": "alpha beta gamma"},
                              {"text": "delta epsilon"}] * 4)
    kw = dict(model_name="bert-tiny", max_token_len=16, batch_size=8)
    plain = HuggingFaceSentenceEmbedder(**kw).transform(df)
    sharded = HuggingFaceSentenceEmbedder(
        **kw, mesh_config=MeshConfig(data=-1, fsdp=2)).transform(df)
    a = np.asarray(list(plain.collect_column("embeddings")))
    b = np.asarray(list(sharded.collect_column("embeddings")))
    np.testing.assert_allclose(a, b, atol=1e-5)


def test_sampled_generation_deterministic_under_seed():
    """do_sample with a fixed seed is reproducible; changing the seed changes
    the sample; top_k=1 sampling equals greedy (ref forwards HF generate
    kwargs, HuggingFaceCausalLMTransform.py:284-331)."""
    df = DataFrame.from_dict({"prompt": ["hello world", "the quick brown fox",
                                         "another prompt here"]})
    kw = dict(model_name="llama-tiny", max_new_tokens=8, prompt_bucket=8,
              batch_size=4)
    lm = HuggingFaceCausalLM(**kw, do_sample=True, temperature=0.9, top_p=0.95,
                             seed=42)
    a = [np.asarray(g) for g in lm.transform(df).collect_column("completions")]
    b = [np.asarray(g) for g in lm.transform(df).collect_column("completions")]
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)

    lm.set(seed=43)
    c = [np.asarray(g) for g in lm.transform(df).collect_column("completions")]
    assert any(not np.array_equal(x, y) for x, y in zip(a, c)), \
        "different seeds produced identical samples for every row"

    greedy = [np.asarray(g) for g in HuggingFaceCausalLM(**kw).transform(df)
              .collect_column("completions")]
    k1 = [np.asarray(g) for g in
          HuggingFaceCausalLM(**kw, do_sample=True, temperature=0.7, top_k=1,
                              seed=7).transform(df).collect_column("completions")]
    for x, y in zip(greedy, k1):
        np.testing.assert_array_equal(x, y)

    # identical prompts in DIFFERENT batches must draw different samples
    # (per-batch RNG offset), not replay the same stream
    dup = DataFrame.from_dict({"prompt": ["the same prompt"] * 3})
    lm_dup = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=8,
                                 prompt_bucket=8, batch_size=1, do_sample=True,
                                 temperature=1.0, seed=5)
    outs = [np.asarray(g)
            for g in lm_dup.transform(dup).collect_column("completions")]
    assert not np.array_equal(outs[0], outs[1]), \
        "duplicate prompts in different batches replayed identical samples"


def test_selector_topk_topp_masking():
    """top-k and nucleus masks restrict the support exactly."""
    import jax
    import jax.numpy as jnp

    from synapseml_tpu.models.flax_nets.llama import _make_selector

    # probs ~ [0.6, 0.3, 0.08, 0.02]
    logits = jnp.log(jnp.asarray([[0.6, 0.3, 0.08, 0.02]], jnp.float32))
    keys = jax.random.split(jax.random.PRNGKey(0), 200)

    top_p = _make_selector(1.0, None, 0.5)  # exclusive-cum < 0.5 -> {0}
    toks = np.asarray([top_p(logits, k)[0] for k in keys[:50]])
    assert set(toks) == {0}

    top_p2 = _make_selector(1.0, None, 0.7)  # {0, 1}
    toks = np.asarray([top_p2(logits, k)[0] for k in keys])
    assert set(toks) <= {0, 1} and len(set(toks)) == 2

    top_k2 = _make_selector(1.0, 2, None)
    toks = np.asarray([top_k2(logits, k)[0] for k in keys])
    assert set(toks) <= {0, 1}

    greedy = _make_selector(0.0, None, None)
    assert int(greedy(logits, keys[0])[0]) == 0


@pytest.mark.slow
def test_llama2_7b_code_path_reduced_width():
    """Execute the REAL Llama-2-7B code path — all 32 layers, 32 heads, RoPE,
    SwiGLU, KV cache, sampling — at reduced width, with params sharded over a
    tensor x fsdp mesh (the BASELINE Llama-2-7B sharded-inference config,
    previously validated only as an abstract footprint check)."""
    import jax
    import jax.numpy as jnp
    from flax.core import meta

    from synapseml_tpu.models.flax_nets.llama import (LlamaLM, generate,
                                                      llama2_7b)
    from synapseml_tpu.parallel import MeshConfig
    from synapseml_tpu.parallel.mesh import create_mesh, shard_inference_params

    cfg = llama2_7b(hidden=128, mlp_dim=344, max_len=64, vocab_size=512)
    assert cfg.n_layers == 32 and cfg.n_heads == 32  # full 7B depth/structure
    model = LlamaLM(cfg, decode=True)
    params = LlamaLM(cfg).init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 8), jnp.int32))["params"]
    plain = jax.tree.map(lambda x: x.value if isinstance(x, meta.Partitioned) else x,
                         params, is_leaf=lambda x: isinstance(x, meta.Partitioned))
    mesh = create_mesh(MeshConfig(data=1, fsdp=2, tensor=4), allow_fewer=False)
    placed = shard_inference_params(LlamaLM(cfg),
                                    {"input_ids": jnp.zeros((1, 8), jnp.int32)},
                                    plain, mesh)
    B, P = 2, 8
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 512, (B, P)), jnp.int32)
    with mesh.mesh:
        out = generate(model, placed, ids, 4, temperature=0.8, top_k=50,
                       top_p=0.9, rng=jax.random.PRNGKey(1))
    out = np.asarray(out)
    assert out.shape == (B, P + 4)
    assert np.all((out >= 0) & (out < 512))


def test_per_row_generation_params_two_configs():
    """Per-row generate kwargs (reference forwards per-call HF generate
    kwargs, HuggingFaceCausalLMTransform.py:284-331): one DataFrame carrying
    TWO distinct configs — different max_new_tokens, one sampled with its
    own seed — buckets by config, generates each with its own settings, and
    keeps row order."""
    cfgs = np.empty(4, dtype=object)
    cfgs[0] = {"max_new_tokens": 3}
    cfgs[1] = {"max_new_tokens": 6, "do_sample": True, "temperature": 0.8,
               "seed": 7}
    cfgs[2] = {"max_new_tokens": 3}
    cfgs[3] = None  # falls back to the transformer-level params
    df = DataFrame.from_dict({
        "prompt": ["hello world", "the quick brown fox", "lazy dog", "a"],
        "gen": cfgs}, num_partitions=1)
    lm = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=5,
                             prompt_bucket=8, batch_size=2,
                             generation_params_col="gen")
    from synapseml_tpu.core import batching as cb

    misses0 = cb.get_compiled_cache().miss_count("hf_causal_lm")
    out = lm.transform(df).collect_column("completions")
    lengths = [len(np.asarray(g)) for g in out]
    assert lengths == [3, 6, 3, 5]
    # two distinct configs + default -> exactly 3 compiled variants (the
    # per-instance _cache_gen dict became the shared CompiledCache)
    assert cb.get_compiled_cache().miss_count("hf_causal_lm") - misses0 == 3
    # deterministic under the per-row seed
    out2 = lm.transform(df).collect_column("completions")
    for a, b in zip(out, out2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # unknown kwargs are rejected, not silently ignored
    bad = np.empty(1, dtype=object)
    bad[0] = {"num_beams": 4}
    bad_df = DataFrame.from_dict({"prompt": ["x"], "gen": bad})
    import pytest
    with pytest.raises(ValueError, match="num_beams"):
        lm.transform(bad_df)
