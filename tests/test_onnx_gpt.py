"""REAL torch-exported CAUSAL decoder through the ONNX path: Trilu masks,
Not/Where masked_fill chains, GatherElements, and the TorchScript exporter's
shape-guard If nodes must all convert and match torch logits. Decoder-side
complement of ``test_onnx_bert.py`` (reference runs the full opset through
ONNX Runtime, ``deep-learning/src/main/scala/.../onnx/ONNXModel.scala:211``).
"""

import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).parent))

torch = pytest.importorskip("torch")

from _torch_gpt import TorchTinyGPT, export_gpt_onnx_bytes  # noqa: E402


@pytest.fixture(scope="module")
def exported():
    torch.manual_seed(0)
    model = TorchTinyGPT(vocab=256, d=32, layers=2, heads=2, max_len=64)
    ids = torch.randint(0, 256, (2, 12))
    gi = torch.tensor([3, 11])
    return model, export_gpt_onnx_bytes(model, ids, gi)


def test_decoder_export_ops_all_supported(exported):
    from synapseml_tpu.onnx.convert import OP_REGISTRY, _all_op_types
    from synapseml_tpu.onnx.proto import ModelProto

    _, data = exported
    ops = _all_op_types(ModelProto.parse(data).graph)
    for must in ("Trilu", "GatherElements", "Not", "Where"):
        assert must in ops, f"export no longer exercises {must}"
    missing = sorted(o for o in ops if o != "If" and o not in OP_REGISTRY)
    assert not missing, f"unsupported decoder ops: {missing}"


def test_decoder_logits_match_torch(exported):
    """Causal-mask semantics survive conversion: logits match torch at two
    sequence lengths (Trilu masks are rebuilt per trace), and the
    GatherElements row-position pick is honored."""
    import jax

    from synapseml_tpu.onnx import convert_graph

    model, data = exported
    conv = convert_graph(data)
    fn = jax.jit(lambda i, g: conv(ids=i, gather_idx=g)["logits"])

    for B, T in ((2, 12), (3, 20)):
        gen = torch.Generator().manual_seed(B * 31 + T)
        ids = torch.randint(0, 256, (B, T), generator=gen)
        gi = torch.arange(B) % T
        with torch.no_grad():
            want = model(ids, gi).numpy()
        got = np.asarray(fn(ids.numpy(), gi.numpy()))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_causality_holds_through_conversion(exported):
    """Changing a FUTURE token must not change the gathered logits at an
    earlier position — the Trilu/Where mask chain actually masks."""
    import jax

    from synapseml_tpu.onnx import convert_graph

    model, data = exported
    conv = convert_graph(data)
    fn = jax.jit(lambda i, g: conv(ids=i, gather_idx=g)["logits"])
    gen = torch.Generator().manual_seed(5)
    ids = torch.randint(0, 256, (1, 12), generator=gen).numpy()
    gi = np.asarray([4])
    base = np.asarray(fn(ids, gi))
    mutated = ids.copy()
    mutated[0, 9] = (mutated[0, 9] + 7) % 256  # future of position 4
    np.testing.assert_array_equal(np.asarray(fn(mutated, gi)), base)
