"""image module: transformer stage pipeline, augmenter, unroll, superpixels."""

import numpy as np
import pytest

from synapseml_tpu.core import DataFrame
from synapseml_tpu.image import (
    ImageSetAugmenter,
    ImageTransformer,
    SuperpixelTransformer,
    UnrollImage,
    slic_segments,
)
from synapseml_tpu.image.transforms import bilinear_resize


def make_image_df(n=4, h=24, w=32, c=3, seed=0, ragged=False):
    rs = np.random.default_rng(seed)
    imgs = []
    for i in range(n):
        hh = h + (i * 4 if ragged else 0)
        imgs.append(rs.integers(0, 256, size=(hh, w, c)).astype(np.float32))
    return DataFrame.from_dict({"image": imgs, "label": np.arange(n)}, num_partitions=2)


def test_resize_crop_normalize_tensor_pipeline():
    df = make_image_df(ragged=True)
    it = (ImageTransformer(input_col="image", output_col="features")
          .resize(size=20, keep_aspect_ratio=True)
          .center_crop(16, 16)
          .normalize(means=[0.485, 0.456, 0.406], stds=[0.229, 0.224, 0.225],
                     color_scale_factor=1 / 255.0))
    out = it.transform(df)
    feats = out.partitions[0]["features"]
    assert feats.shape[1:] == (3, 16, 16)  # CHW, rectangular stack
    assert feats.dtype == np.float32
    assert abs(float(feats.mean())) < 5  # normalized scale


def test_bilinear_resize_identity_and_shape():
    img = np.arange(12, dtype=np.float32).reshape(3, 4, 1)
    assert np.array_equal(bilinear_resize(img, 3, 4), img)
    up = bilinear_resize(img, 6, 8)
    assert up.shape == (6, 8, 1)
    assert up.min() >= img.min() - 1e-5 and up.max() <= img.max() + 1e-5


def test_flip_and_threshold_and_gray():
    df = make_image_df(n=2)
    it = (ImageTransformer(input_col="image", output_col="out")
          .color_format("gray").threshold(127, 255).flip(1))
    out = it.transform(df).collect_column("out")
    first = out[0]
    assert first.shape[-1] in (1,)  # gray
    assert set(np.unique(first)).issubset({0.0, 255.0})
    # horizontal flip of threshold equals threshold of flip
    it2 = (ImageTransformer(input_col="image", output_col="out")
           .flip(1).color_format("gray").threshold(127, 255))
    out2 = it2.transform(df).collect_column("out")
    np.testing.assert_array_equal(out[0], out2[0])


def test_gaussian_blur_smooths():
    rs = np.random.default_rng(0)
    img = rs.normal(size=(16, 16, 1)).astype(np.float32)
    df = DataFrame.from_dict({"image": [img]})
    out = (ImageTransformer(input_col="image", output_col="out")
           .gaussian_blur(sigma=2.0).transform(df).collect_column("out")[0])
    assert float(np.var(out)) < float(np.var(img))
    assert abs(float(out.mean()) - float(img.mean())) < 0.05  # kernel sums to 1


def test_augmenter_doubles_rows():
    df = make_image_df(n=3)
    aug = ImageSetAugmenter(input_col="image", output_col="image",
                            flip_left_right=True, flip_up_down=True)
    out = aug.transform(df)
    assert out.count() == 9  # original + lr + ud
    imgs = out.collect_column("image")
    np.testing.assert_array_equal(np.asarray(imgs[3]), np.asarray(imgs[0])[:, ::-1])


def test_unroll():
    df = make_image_df(n=3, h=8, w=8)
    out = UnrollImage(input_col="image", output_col="vec").transform(df)
    vecs = out.partitions[0]["vec"]
    assert vecs.shape[-1] == 8 * 8 * 3


def test_slic_superpixels():
    # two clearly-separated color regions
    img = np.zeros((32, 32, 3), np.float32)
    img[:, 16:] = 255.0
    labels = slic_segments(img, cell_size=8.0)
    assert labels.shape == (32, 32)
    n = labels.max() + 1
    assert 4 <= n <= 40
    # no superpixel straddles the color boundary
    for k in range(n):
        cols = img[labels == k][:, 0]
        assert cols.std() < 1.0

    df = DataFrame.from_dict({"image": [img]})
    out = SuperpixelTransformer(cell_size=8.0).transform(df)
    assert out.collect_column("superpixels")[0].shape == (32, 32)


def test_missing_column_errors():
    df = make_image_df()
    with pytest.raises(ValueError, match="input column"):
        ImageTransformer(input_col="nope").transform(df)


def test_unroll_binary_image(tmp_path):
    import io as _io

    from PIL import Image

    from synapseml_tpu.image import UnrollBinaryImage

    buf = _io.BytesIO()
    arr = np.arange(27, dtype=np.uint8).reshape(3, 3, 3)
    Image.fromarray(arr).save(buf, format="PNG")
    good = buf.getvalue()
    df = DataFrame.from_rows([{"content": good}, {"content": b"not-an-image"}])
    out = UnrollBinaryImage().transform(df)
    vecs = out.collect_column("unrolled")
    np.testing.assert_array_equal(vecs[0], arr.ravel())
    assert len(vecs[1]) == 0  # undecodable -> empty vector, not a crash
