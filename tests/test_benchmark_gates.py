"""Golden-tolerance benchmark gates — the reference's accuracy-regression
mechanism (SURVEY §4: `lightgbm/src/test/resources/benchmarks/*.csv` with
name,value,precision,higherIsBetter rows; BASELINE.md BreastTissue /
energy-efficiency gates). The datasets are deterministic synthetic stand-ins
(no egress for the originals); the MECHANISM and per-mode coverage
(gbdt/goss/dart/rf, classifier + regressor) mirror the reference exactly:
any regression beyond the recorded tolerance fails CI."""

import csv
import pathlib

import numpy as np
import pytest

from synapseml_tpu.gbdt.booster import train_booster

GATES = {
    r["name"]: (float(r["value"]), float(r["precision"]),
                r["higherIsBetter"] == "1")
    for r in csv.DictReader(
        open(pathlib.Path(__file__).parent / "resources" / "benchmark_gates.csv"))
}


def _assert_gate(name: str, measured: float):
    value, precision, higher = GATES[name]
    if higher:
        assert measured >= value - precision, \
            f"{name}: {measured:.4f} regressed below gate {value} - {precision}"
    else:
        assert measured <= value + precision, \
            f"{name}: {measured:.4f} regressed above gate {value} + {precision}"


def _cls_data(seed=1234, n=1000, f=9):
    rs = np.random.default_rng(seed)
    X = rs.normal(size=(n, f))
    logits = X[:, 0] * 1.5 - X[:, 1] + 0.5 * X[:, 2] * X[:, 3] \
        + 0.3 * rs.normal(size=n)
    y = np.digitize(logits, np.quantile(logits, [0.33, 0.66]))
    return X, y.astype(np.float32)


def _reg_data(seed=4321, n=1000, f=8):
    rs = np.random.default_rng(seed)
    X = rs.normal(size=(n, f))
    y = 3 * X[:, 0] + np.sin(2 * X[:, 1]) * 2 + 0.5 * rs.normal(size=n)
    return X, y.astype(np.float32)


@pytest.mark.parametrize("mode", ["gbdt", "goss", "dart", "rf"])
def test_classifier_gate(mode):
    X, y = _cls_data()
    kw = dict(objective="multiclass", num_class=3, num_iterations=50,
              learning_rate=0.1, num_leaves=15, seed=0, boosting_type=mode)
    if mode == "rf":
        kw.update(bagging_fraction=0.7, bagging_freq=1)
    b = train_booster(X[:800], y[:800], **kw)
    acc = float(np.mean(np.argmax(b.predict(X[800:]), axis=1) == y[800:]))
    _assert_gate(f"classifier_{mode}_accuracy", acc)


@pytest.mark.parametrize("mode", ["gbdt", "goss", "dart", "rf"])
def test_regressor_gate(mode):
    X, y = _reg_data()
    kw = dict(objective="regression", num_iterations=50, learning_rate=0.1,
              num_leaves=15, seed=0, boosting_type=mode)
    if mode == "rf":
        kw.update(bagging_fraction=0.7, bagging_freq=1)
    b = train_booster(X[:800], y[:800], **kw)
    rmse = float(np.sqrt(np.mean((b.predict(X[800:]).ravel() - y[800:]) ** 2)))
    _assert_gate(f"regressor_{mode}_rmse", rmse)


def test_vw_regressor_gate():
    """VW gate (reference vw/src/test/resources/benchmarks/
    benchmarks_VerifyVowpalWabbitRegressor.csv mechanism)."""
    import jax.numpy as jnp

    from synapseml_tpu.vw.learner import LinearConfig, linear_predict, train_linear

    rs = np.random.default_rng(99)
    n, f = 1000, 6
    X = rs.normal(size=(n, f)).astype(np.float32)
    y = (X @ np.array([2, -1, .5, 0, 1, -.5], np.float32)
         + 0.3 * rs.normal(size=n)).astype(np.float32)
    idx = np.tile(np.arange(f, dtype=np.int32), (n, 1))
    cfg = LinearConfig(num_bits=10, loss="squared", learning_rate=0.5,
                       num_passes=5, batch_size=64, seed=0)
    w = train_linear(idx[:800], X[:800], y[:800], cfg)
    pred = np.asarray(linear_predict(jnp.asarray(w), jnp.asarray(idx[800:]),
                                     jnp.asarray(X[800:])))
    rmse = float(np.sqrt(np.mean((pred - y[800:]) ** 2)))
    _assert_gate("vw_regressor_rmse", rmse)
