"""Token-level LLM serving: paged KV cache + prefill/decode continuous
batching (models/paged_engine.py, models/flax_nets/llama.py paged modules,
io/serving.serve_llm).

The load-bearing guarantees:
  * greedy paged prefill+decode is TOKEN-IDENTICAL to the dense
    ``greedy_generate`` across prompt lengths spanning >= 3 seq-ladder
    rungs, including early-EOS rows;
  * block free/realloc never aliases a live page (property test);
  * decode slots refill the moment a sequence finishes — no
    run-to-completion barrier;
  * compile counts stay bounded by the ladders and every jit goes through
    the shared CompiledCache (static check in test_codegen.py);
  * the token scheduler streams chunked replies and never strands a client
    on a dropped request.
"""

import json
import time

import numpy as np
import pytest

from synapseml_tpu.core import batching as cb
from synapseml_tpu.core.batching import ShapeBucketer
from synapseml_tpu.models.paged_engine import BlockAllocator, PagedDecodeEngine


def _tiny_cfg_params(**kw):
    import jax
    import jax.numpy as jnp
    from flax.core import meta

    from synapseml_tpu.models.flax_nets.llama import LlamaLM, llama_tiny

    cfg = llama_tiny(**kw)
    params = LlamaLM(cfg).init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 8), jnp.int32))["params"]
    params = jax.tree.map(
        lambda x: x.value if isinstance(x, meta.Partitioned) else x, params,
        is_leaf=lambda x: isinstance(x, meta.Partitioned))
    return cfg, params


@pytest.fixture(scope="module")
def tiny_lm():
    # f32 compute: the parity guarantee is exact under f32, where XLA
    # fusion cannot move bf16 rounding points. Under bf16 the dense and
    # paged PROGRAMS round intermediates at different fusion boundaries,
    # so a near-tie argmax can flip (observed: top-2 logits 0.0035 apart
    # flipped on one prompt) — documented in docs/SERVING.md. Serving and
    # offline transform share ONE engine (same executables), so they are
    # token-identical to each other at any dtype.
    import jax.numpy as jnp

    return _tiny_cfg_params(dtype=jnp.float32)


def _dense_greedy(cfg, params, prompt, max_new, eos_id=None):
    import jax.numpy as jnp

    from synapseml_tpu.models.flax_nets.llama import LlamaLM, greedy_generate

    P = max(((len(prompt) + 7) // 8) * 8, 8)
    ids = np.zeros((1, P), np.int32)
    mask = np.zeros((1, P), np.int32)
    ids[0, :len(prompt)] = prompt
    mask[0, :len(prompt)] = 1
    out = np.asarray(greedy_generate(
        LlamaLM(cfg, decode=True), params, jnp.asarray(ids), max_new,
        eos_id=eos_id, prompt_mask=jnp.asarray(mask)))[0, P:]
    return out.tolist()


def _trim_eos(tokens, eos_id):
    if eos_id is None:
        return list(tokens)
    out = []
    for t in tokens:
        if t == eos_id:
            break
        out.append(t)
    return out


# ---------------------------------------------------------------------------
# parity
# ---------------------------------------------------------------------------

def test_paged_greedy_parity_across_rungs(tiny_lm):
    """Paged prefill+decode produces bit-identical token ids to the dense
    greedy_generate for prompt lengths spanning FOUR seq-ladder rungs, run
    through the continuous scheduler all at once (mixed buckets in flight
    together)."""
    cfg, params = tiny_lm
    rng = np.random.default_rng(3)
    lens = [5, 12, 27, 50]  # rungs 8, 16, 32, 64
    prompts = [rng.integers(2, cfg.vocab_size, (n,)).tolist() for n in lens]
    max_new = 12
    dense = [_dense_greedy(cfg, params, p, max_new) for p in prompts]

    eng = PagedDecodeEngine(
        cfg, params, block_len=16, max_slots=4,
        bucketer=ShapeBucketer(ladder=[1, 2, 4, 8],
                               seq_ladder=[8, 16, 32, 64]))
    paged = eng.generate(prompts, max_new)
    for d, p, n in zip(dense, paged, lens):
        assert d == p, f"paged decode diverged from dense at prompt len {n}"
    eng.release()


def test_paged_greedy_parity_with_early_eos(tiny_lm):
    """Early-EOS parity: pick a token the dense output actually emits
    mid-stream, rerun BOTH engines with it as eos_id — the paged row must
    stop at the same token, and its freed capacity must not corrupt any
    still-running row."""
    cfg, params = tiny_lm
    rng = np.random.default_rng(5)
    lens = [5, 12, 27, 50]
    prompts = [rng.integers(2, cfg.vocab_size, (n,)).tolist() for n in lens]
    max_new = 16
    free_run = [_dense_greedy(cfg, params, p, max_new) for p in prompts]
    # an eos that hits mid-stream for at least one row but not all rows
    eos_id = None
    for row in free_run:
        for tok in row[1:max_new // 2]:
            others = sum(tok in r for r in free_run)
            if others < len(free_run):
                eos_id = int(tok)
                break
        if eos_id is not None:
            break
    assert eos_id is not None
    dense = [_trim_eos(_dense_greedy(cfg, params, p, max_new, eos_id=eos_id),
                       eos_id) for p in prompts]
    assert any(len(d) < max_new for d in dense), "eos never fired"

    eng = PagedDecodeEngine(
        cfg, params, block_len=16, max_slots=4, eos_id=eos_id,
        bucketer=ShapeBucketer(ladder=[1, 2, 4, 8],
                               seq_ladder=[8, 16, 32, 64]))
    paged = [_trim_eos(row, eos_id) for row in eng.generate(prompts, max_new)]
    assert paged == dense
    # every page freed once every sequence finished
    assert eng.allocator.used_count == 0
    eng.release()


def test_paged_sampling_deterministic_per_uid(tiny_lm):
    """Sampled paged decode is a pure function of (seed, uid): same uids ->
    identical streams, different engine seed -> different streams."""
    cfg, params = tiny_lm
    rng = np.random.default_rng(11)
    prompts = [rng.integers(2, cfg.vocab_size, (9,)).tolist()
               for _ in range(3)]
    kw = dict(block_len=16, max_slots=4, temperature=0.9, top_p=0.95)
    a = PagedDecodeEngine(cfg, params, seed=1, **kw).generate(
        prompts, 8, uids=[10, 11, 12])
    b = PagedDecodeEngine(cfg, params, seed=1, **kw).generate(
        prompts, 8, uids=[10, 11, 12])
    c = PagedDecodeEngine(cfg, params, seed=2, **kw).generate(
        prompts, 8, uids=[10, 11, 12])
    assert a == b
    assert a != c


# ---------------------------------------------------------------------------
# block allocator: free/realloc never aliases live pages
# ---------------------------------------------------------------------------

def test_block_allocator_invariants_property():
    rng = np.random.default_rng(0)
    alloc = BlockAllocator(33)
    live: dict[int, list[int]] = {}
    next_id = 0
    for _ in range(500):
        if live and rng.random() < 0.45:
            victim = int(rng.choice(list(live)))
            alloc.free(live.pop(victim))
        else:
            got = alloc.alloc(int(rng.integers(1, 5)))
            if got is None:
                continue
            assert 0 not in got, "trash page handed out"
            flat = [b for blocks in live.values() for b in blocks]
            assert not (set(got) & set(flat)), "live page re-allocated"
            assert len(set(got)) == len(got)
            live[next_id] = got
            next_id += 1
        held = sum(len(b) for b in live.values())
        assert alloc.used_count == held
        assert alloc.free_count == alloc.capacity - held
    with pytest.raises(RuntimeError):
        alloc.free([0])  # trash page was never allocatable


def test_engine_live_pages_never_alias(tiny_lm):
    """Scheduler-level no-aliasing: while a mixed stream churns through
    admit/finish/refill, the union of active block tables stays disjoint
    and never touches the trash page."""
    cfg, params = tiny_lm
    rng = np.random.default_rng(9)
    prompts = [rng.integers(2, cfg.vocab_size, (int(n),)).tolist()
               for n in rng.integers(3, 40, 12)]
    budgets = [int(n) for n in rng.integers(1, 14, 12)]
    eng = PagedDecodeEngine(cfg, params, block_len=8, max_slots=4,
                            n_blocks=40)
    seqs = [eng.submit(p, n) for p, n in zip(prompts, budgets)]
    while any(not s.done for s in seqs):
        eng.admit()
        eng.step()
        seen: set[int] = set()
        for s in eng._active:
            assert 0 not in s.blocks
            overlap = seen & set(s.blocks)
            assert not overlap, f"live pages aliased: {overlap}"
            seen |= set(s.blocks)
        assert len(seen) == eng.allocator.used_count
    assert eng.allocator.used_count == 0
    eng.release()


def test_preemption_recomputes_identically(tiny_lm):
    """A pool too small for the whole stream forces preemption; preempted
    sequences re-prefill prompt+generated and still produce the exact
    unconstrained greedy output."""
    cfg, params = tiny_lm
    rng = np.random.default_rng(13)
    prompts = [rng.integers(2, cfg.vocab_size, (20,)).tolist()
               for _ in range(4)]
    max_new = 20
    roomy = PagedDecodeEngine(cfg, params, block_len=8, max_slots=4)
    want = roomy.generate(prompts, max_new)
    # 4 seqs x (20 prompt + 20 gen) needs 4x5 blocks of 8; 13 usable
    # blocks cannot hold all four -> at least one preemption
    tight = PagedDecodeEngine(cfg, params, block_len=8, max_slots=4,
                              n_blocks=14)
    seqs = [tight.submit(p, max_new) for p in prompts]
    while any(not s.done for s in seqs):
        tight.admit()
        tight.step()
    assert [list(s.generated) for s in seqs] == want
    assert sum(s.preemptions for s in seqs) >= 1, \
        "pool was supposed to be tight enough to preempt"
    roomy.release()
    tight.release()


def test_oversized_sequence_finishes_kv_capacity_not_wedge(tiny_lm):
    """A sequence whose page need exceeds TOTAL pool capacity can never be
    satisfied by freeing — admit must terminate it (finish_reason
    'kv_capacity') instead of wedging the FIFO head, and the request queued
    behind it must still decode."""
    cfg, params = tiny_lm
    rng = np.random.default_rng(7)
    # capacity = 3 usable blocks of 8 = 24 tokens; 30-token prompt needs 4
    eng = PagedDecodeEngine(cfg, params, block_len=8, max_slots=2,
                            n_blocks=4)
    big = eng.submit(rng.integers(2, cfg.vocab_size, (30,)).tolist(), 4)
    ok = eng.submit(rng.integers(2, cfg.vocab_size, (8,)).tolist(), 4)
    for _ in range(50):
        if big.done and ok.done:
            break
        eng.admit()
        eng.step()
    assert big.finish_reason == "kv_capacity" and not big.generated
    assert ok.finish_reason == "length" and len(ok.generated) == 4
    assert eng.allocator.used_count == 0
    eng.release()


def test_released_engine_is_rebuilt_not_reused():
    """release() may leave donated page buffers consumed — the stage's
    engine cache must hand out a FRESH engine afterwards (the serve_llm
    engine-failure rebuild path depends on this), and the serving adapter
    must delegate single-sequence abort()."""
    from synapseml_tpu.hf import HuggingFaceCausalLM

    lm = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=4,
                             engine="paged")
    eff = lm._effective_gen_cfg()
    e1 = lm._paged_engine(eff)
    e1.release()
    e2 = lm._paged_engine(eff)
    assert e2 is not e1 and not e2._released
    adapter = lm.serving_engine()
    seq = adapter.submit({"prompt": "abort me"}, "r1")
    adapter.abort(seq)
    assert seq.finish_reason == "aborted"
    adapter.release()


def test_stream_chunks_decode_cumulatively_not_per_token():
    """Byte-level BPE pieces are not independently decodable: streamed
    chunk text must be the delta of the CUMULATIVE decode (incomplete
    tails held back), so concatenated chunks equal the final text."""
    from synapseml_tpu.hf import HuggingFaceCausalLM

    lm = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=4,
                             engine="paged")
    adapter = lm.serving_engine()

    def decode(ids):  # id pairs -> one char; odd tail -> replacement char
        s = "".join(chr(97 + (a + b) % 26)
                    for a, b in zip(ids[::2], ids[1::2]))
        return s + ("�" if len(ids) % 2 else "")

    adapter._decode = decode
    seq = adapter.submit({"prompt": "x", "stream": True}, "r")
    texts = []
    for t in (5, 6, 7, 8):
        seq.generated.append(t)
        texts.append(adapter.chunk_for({"token": t, "seq": seq})["text"])
    assert "".join(texts) == decode(seq.generated)
    assert "�" not in "".join(texts)
    adapter.release()


def test_paged_transform_tolerates_zero_token_rows():
    """A row whose text tokenizes to ZERO tokens gets an empty completion;
    it must not fail the whole scan (engine.submit rejects empty prompts,
    the dense path does not)."""
    import numpy as np

    from synapseml_tpu.core import DataFrame
    from synapseml_tpu.hf import HuggingFaceCausalLM

    from synapseml_tpu.models.tokenizer import HashingTokenizer

    class _ZeroForBlank(HashingTokenizer):
        def __call__(self, texts, **kw):
            enc = super().__call__(texts, **kw)
            enc["attention_mask"] = np.asarray(enc["attention_mask"]).copy()
            for i, t in enumerate(texts):
                if not t:
                    enc["attention_mask"][i, :] = 0
            return enc

    lm = HuggingFaceCausalLM(model_name="llama-tiny", engine="paged",
                             tokenizer=_ZeroForBlank(),
                             max_new_tokens=4, batch_size=4)
    out = lm.transform(DataFrame.from_dict(
        {"prompt": ["hello there", "", "more text"]}))
    rows = [np.asarray(r) for r in out.collect_column("completions")]
    assert len(rows[0]) == 4 and len(rows[2]) == 4
    assert len(rows[1]) == 0


def test_result_n_tokens_matches_output_ids_on_eos(tiny_lm):
    """result_for strips the trailing EOS from output_ids — n_tokens must
    count the SAME list, not the raw generated length."""
    from synapseml_tpu.hf import HuggingFaceCausalLM

    lm = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=4,
                             engine="paged")
    adapter = lm.serving_engine()
    seq = adapter.submit({"prompt": "x"}, "r")
    seq.generated.extend([5, 6, adapter._engine.eos_id or 0])
    if adapter._engine.eos_id is None:
        adapter._engine.eos_id = 0  # force the eos-strip branch
        seq.generated[-1] = 0
    seq.finish_reason = "eos"
    out = adapter.result_for(seq)
    assert out["n_tokens"] == len(out["output_ids"]) == 2
    adapter.release()


def test_generate_progress_is_engine_wide(tiny_lm):
    """The stall detector keys off the ENGINE's progress ticks, so another
    caller's tokens count as progress and concurrent use cannot raise the
    spurious 'stalled' error."""
    cfg, params = tiny_lm
    eng = PagedDecodeEngine(cfg, params, block_len=8, max_slots=2)
    t0 = eng._progress_ticks
    eng.generate([[3, 4, 5]], 3)
    assert eng._progress_ticks > t0
    eng.release()


def test_serving_submit_keeps_prompt_whole_under_large_max_new():
    """A large max_new_tokens clamps the BUDGET, never truncates the
    prompt: serving and offline submit agree on (prompt, horizon-clamped
    max_new) semantics."""
    from synapseml_tpu.hf import HuggingFaceCausalLM

    lm = HuggingFaceCausalLM(model_name="llama-tiny", engine="paged")
    adapter = lm.serving_engine()
    prompt = "many words " * 40
    want_ids = adapter.submit({"prompt": prompt, "max_new_tokens": 1},
                              "ref").prompt_ids
    assert len(want_ids) > 1
    seq = adapter.submit({"prompt": prompt, "max_new_tokens": 10_000}, "r2")
    assert seq.prompt_ids == want_ids
    assert len(seq.prompt_ids) + seq.max_new_tokens <= adapter._max_len
    adapter.release()


# ---------------------------------------------------------------------------
# continuous refill (no run-to-completion barrier) + compile bounds
# ---------------------------------------------------------------------------

def test_slots_refill_before_long_sequence_finishes(tiny_lm):
    """With 2 slots, a long generation and two short ones: the second short
    request must be admitted and FINISH while the long one is still
    decoding — the barrier the dense path imposes is gone."""
    cfg, params = tiny_lm
    rng = np.random.default_rng(2)
    mk = lambda: rng.integers(2, cfg.vocab_size, (6,)).tolist()  # noqa: E731
    eng = PagedDecodeEngine(cfg, params, block_len=8, max_slots=2)
    long_seq = eng.submit(mk(), 40)
    short_a = eng.submit(mk(), 3)
    short_b = eng.submit(mk(), 3)  # waits: only 2 slots
    while not short_b.done:
        eng.admit()
        eng.step()
        assert not long_seq.done, \
            "long sequence finished first — refill never happened"
    assert short_a.done and short_b.done and not long_seq.done
    while not long_seq.done:
        eng.admit()
        eng.step()
    assert len(long_seq.generated) == 40
    eng.release()


def test_compile_counts_bounded_by_ladders(tiny_lm):
    """A stream of many distinct prompt lengths and active-slot counts
    compiles <= seq-ladder-many prefill and <= slot-ladder-many decode
    executables (the CompiledCache miss counters are the proof)."""
    cfg, params = tiny_lm
    cache = cb.get_compiled_cache()
    p0 = cache.miss_count("llama_paged_prefill")
    d0 = cache.miss_count("llama_paged_decode")
    eng = PagedDecodeEngine(
        cfg, params, block_len=16, max_slots=8,
        bucketer=ShapeBucketer(ladder=[2, 4, 8], seq_ladder=[16, 32, 64]))
    rng = np.random.default_rng(21)
    prompts = [rng.integers(2, cfg.vocab_size, (int(n),)).tolist()
               for n in rng.integers(3, 60, 24)]  # every rung hit
    budgets = [int(n) for n in rng.integers(1, 10, 24)]
    eng.generate(prompts, budgets)
    n_prefill = cache.miss_count("llama_paged_prefill") - p0
    n_decode = cache.miss_count("llama_paged_decode") - d0
    assert 0 < n_prefill <= len(eng.bucketer.seq_ladder)
    assert 0 < n_decode <= len(eng.slot_rungs)
    eng.release()


def test_warmup_precompiles_all_rungs(tiny_lm):
    """After warmup(), a full mixed stream causes ZERO new prefill/decode
    compiles — the zero-compile-stall guarantee /admin/load relies on."""
    cfg, params = tiny_lm
    cache = cb.get_compiled_cache()
    eng = PagedDecodeEngine(
        cfg, params, block_len=16, max_slots=4,
        bucketer=ShapeBucketer(ladder=[2, 4], seq_ladder=[16, 32, 64]))
    n = eng.warmup()
    # prompt rungs 16/32/64 + the max_len cap bucket (128) + two slot rungs
    assert n == 4 + 2
    p0 = cache.miss_count("llama_paged_prefill")
    d0 = cache.miss_count("llama_paged_decode")
    rng = np.random.default_rng(8)
    prompts = [rng.integers(2, cfg.vocab_size, (int(n),)).tolist()
               for n in rng.integers(3, 60, 10)]
    eng.generate(prompts, 6)
    assert cache.miss_count("llama_paged_prefill") == p0
    assert cache.miss_count("llama_paged_decode") == d0
    eng.release()


def test_warmup_does_not_corrupt_live_sequences(tiny_lm):
    """Warmup mid-flight (trash-page writes only) must not change any live
    sequence's continuation."""
    cfg, params = tiny_lm
    rng = np.random.default_rng(17)
    prompts = [rng.integers(2, cfg.vocab_size, (10,)).tolist()
               for _ in range(2)]
    eng = PagedDecodeEngine(cfg, params, block_len=8, max_slots=2)
    want = eng.generate(prompts, 10)
    seqs = [eng.submit(p, 10) for p in prompts]
    eng.admit()
    for _ in range(4):
        eng.step()
    eng.warmup()  # all writes land on the trash page
    while any(not s.done for s in seqs):
        eng.step()
    assert [list(s.generated) for s in seqs] == want
    eng.release()


# ---------------------------------------------------------------------------
# offline transform() through the paged engine
# ---------------------------------------------------------------------------

def test_causal_lm_paged_engine_matches_dense_transform():
    from synapseml_tpu.core import DataFrame
    from synapseml_tpu.hf import HuggingFaceCausalLM

    df = DataFrame.from_dict(
        {"prompt": ["hello world", "the quick brown fox jumps over the "
                    "lazy dog again and again", "a", "short one"]},
        num_partitions=2)
    kw = dict(model_name="llama-tiny", max_new_tokens=7, prompt_bucket=8,
              batch_size=2)
    dense = HuggingFaceCausalLM(**kw)
    paged = HuggingFaceCausalLM(**kw, engine="paged")
    # one param pytree drives both engines
    paged.set(model_params=dense._model_and_params()[1])
    a = [np.asarray(g).tolist()
         for g in dense.transform(df).collect_column("completions")]
    b = [np.asarray(g).tolist()
         for g in paged.transform(df).collect_column("completions")]
    assert a == b
    # the paged path reuses ONE engine across transforms
    assert len(paged.__dict__["_cache_engines"]) == 1
    b2 = [np.asarray(g).tolist()
          for g in paged.transform(df).collect_column("completions")]
    assert b2 == b


# ---------------------------------------------------------------------------
# token scheduler over HTTP (serve_llm)
# ---------------------------------------------------------------------------

def _llm_request(address, payload, timeout=30):
    import http.client

    host, port = address.split("//")[1].split(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    conn.request("POST", "/", body=json.dumps(payload).encode())
    return conn, conn.getresponse()


def test_serve_llm_final_stream_and_errors():
    from synapseml_tpu.hf import HuggingFaceCausalLM
    from synapseml_tpu.io.serving import serve_llm

    lm = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=6,
                             batch_size=4, engine="paged")
    srv = serve_llm(lm, warmup=False)
    try:
        # final-text mode
        conn, r = _llm_request(srv.address, {"prompt": "hello world"})
        body = json.loads(r.read())
        assert r.status == 200
        assert body["done"] and body["n_tokens"] == 6
        assert len(body["output_ids"]) == 6
        conn.close()
        # offline transform through the SAME engine agrees token-for-token
        from synapseml_tpu.core import DataFrame

        offline = lm.transform(
            DataFrame.from_dict({"prompt": ["hello world"]}))
        assert np.asarray(
            offline.collect_column("completions")[0]).tolist() \
            == body["output_ids"]

        # streaming mode: one NDJSON chunk per token + terminal record
        conn, r = _llm_request(srv.address,
                               {"prompt": "the quick brown fox",
                                "max_new_tokens": 4, "stream": True})
        assert r.status == 200
        assert r.getheader("Transfer-Encoding") == "chunked"
        chunks = [json.loads(line) for line in iter(r.readline, b"")]
        conn.close()
        assert len(chunks) == 5  # 4 tokens + terminal
        assert [c["token"] for c in chunks[:4]] == chunks[-1]["output_ids"]
        assert chunks[-1]["done"] and chunks[-1]["finish_reason"] == "length"

        # malformed payloads get terminal 4xx replies, fast
        for bad in ([1, 2], {"prompt": ""}, {"no_prompt": 1}):
            t0 = time.perf_counter()
            conn, r = _llm_request(srv.address, bad)
            assert r.status == 400, bad
            assert "error" in json.loads(r.read())
            assert time.perf_counter() - t0 < 5.0
            conn.close()
    finally:
        srv.stop()


def test_serve_llm_interleaves_short_under_long():
    """A short request submitted AFTER a long one completes first — the
    token scheduler refills decode slots mid-generation (no whole-batch
    barrier), and per-request streams stay isolated."""
    import threading

    from synapseml_tpu.hf import HuggingFaceCausalLM
    from synapseml_tpu.io.serving import serve_llm

    lm = HuggingFaceCausalLM(model_name="llama-tiny", batch_size=2,
                             engine="paged", decode_slots=2)
    srv = serve_llm(lm, warmup=False)
    results = {}

    def fire(name, payload):
        conn, r = _llm_request(srv.address, payload)
        results[name] = (time.perf_counter(), json.loads(r.read()))
        conn.close()

    try:
        threads = [
            threading.Thread(target=fire, args=(
                "long", {"prompt": "a long story", "max_new_tokens": 100})),
            threading.Thread(target=fire, args=(
                "short", {"prompt": "quick", "max_new_tokens": 3})),
        ]
        threads[0].start()
        time.sleep(0.15)  # the long one is decoding by now
        threads[1].start()
        for t in threads:
            t.join(timeout=60)
        assert results["short"][1]["n_tokens"] == 3
        assert results["long"][1]["n_tokens"] == 100
        assert results["short"][0] < results["long"][0], \
            "short request waited out the long one (barrier came back)"
    finally:
        srv.stop()


def test_serve_llm_hot_swap_rebuilds_engine():
    """PipelineHolder swap mid-serve: the loop rebuilds + warms the new
    stage's engine and subsequent requests decode with the new params."""
    from synapseml_tpu.hf import HuggingFaceCausalLM
    from synapseml_tpu.io.serving import PipelineHolder, serve_llm

    lm_a = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=4,
                               engine="paged")
    lm_b = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=9,
                               engine="paged")
    holder = PipelineHolder(lm_a, "v1")
    srv = serve_llm(holder, warmup=False)
    try:
        conn, r = _llm_request(srv.address, {"prompt": "before swap"})
        assert json.loads(r.read())["n_tokens"] == 4
        conn.close()
        holder.swap(lm_b, "v2")
        deadline = time.perf_counter() + 30
        n = None
        while time.perf_counter() < deadline:
            conn, r = _llm_request(srv.address, {"prompt": "after swap"})
            # a request racing the engine rebuild can get a terminal abort
            # reply (503) — terminal, never a silent stall — so retry it
            n = json.loads(r.read()).get("n_tokens")
            conn.close()
            if n == 9:
                break
            time.sleep(0.2)
        assert n == 9, "swap never took effect"
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# dropped-after-dequeue exchanges get a terminal reply (satellite)
# ---------------------------------------------------------------------------

def test_dropped_exchange_gets_terminal_504():
    """An exchange whose deadline passed in the queue is dropped by the
    batch readers — and must receive a terminal 504 reply the instant it is
    dropped, so a handler racing the deadline can never park to its full
    timeout on a silently-dropped request."""
    from synapseml_tpu.io.serving import ServingServer, _Exchange

    srv = ServingServer(reply_timeout_s=5.0)
    try:
        fresh = _Exchange("fresh", "POST", "/", {}, b"{}")
        stale = _Exchange("stale", "POST", "/", {}, b"{}")
        stale.enqueued_at -= 10.0  # expired while queued
        for ex in (fresh, stale):
            srv._pending[ex.request_id] = ex
            srv._queue.put(ex)
        batch = srv.read_batch_adaptive(poll_timeout_s=0.05)
        served = list(batch.collect_column("id"))
        assert served == ["fresh"]
        assert stale.reply_event.is_set(), \
            "dropped exchange got no terminal reply"
        assert stale.reply_status == 504
        assert b"expired" in stale.reply_body
        assert not fresh.reply_event.is_set()
        # the terminal reply does not clobber a later real reply race: the
        # first respond() wins
        stale.respond({"late": True}, status=200)
        assert stale.reply_status == 504
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# survivable serving: live KV migration, journaled streams, deadlines
# ---------------------------------------------------------------------------

def _prom_value(name: str, label_sub: str = "") -> float:
    """Current value of one metric series from the process registry's
    exposition text (0.0 when the series does not exist yet)."""
    from synapseml_tpu.core.observability import prometheus_exposition

    for line in prometheus_exposition()[0].decode().splitlines():
        if line.startswith("#"):
            continue
        if line.startswith(name) and label_sub in line:
            return float(line.rsplit(" ", 1)[1])
    return 0.0


def _run_to_done(eng, seq):
    """Drive admit/step until ``seq`` finishes; returns its token ids."""
    deadline = time.perf_counter() + 120
    while not seq.done and time.perf_counter() < deadline:
        eng.admit()
        eng.step()
    assert seq.done, "sequence never finished"
    return list(seq.generated)


def test_export_import_greedy_token_identity(tiny_lm):
    """The tentpole contract: a sequence exported mid-decode and imported
    on a SECOND engine (same params) finishes with exactly the tokens the
    unmigrated run produces — and both allocators account to zero."""
    cfg, params = tiny_lm
    rng = np.random.default_rng(21)
    prompt = rng.integers(2, cfg.vocab_size, (11,)).tolist()
    max_new = 12
    reference = _dense_greedy(cfg, params, prompt, max_new)

    src = PagedDecodeEngine(cfg, params, block_len=8, max_slots=2)
    dst = PagedDecodeEngine(cfg, params, block_len=8, max_slots=2)
    try:
        seq = src.submit(prompt, max_new, request_id="mig", stream=True)
        while len(seq.generated) < 4:  # decode a few tokens on the source
            src.admit()
            src.step()
        snap = src.export_sequence(seq.uid)
        assert snap is not None
        assert src.allocator.used_count == 0, "export leaked source pages"
        assert snap["manifest"]["model_digest"] == dst.model_digest()
        moved = dst.import_sequence(snap)
        assert list(moved.generated) == list(seq.generated)
        got = _run_to_done(dst, moved)
        assert got == reference, "migrated decode diverged from unmigrated"
        assert dst.allocator.used_count == 0, "import leaked dest pages"
    finally:
        src.release()
        dst.release()


def test_import_digest_mismatch_falls_back_to_reprefill(tiny_lm):
    """A snapshot whose model digest does not match the importing engine
    must NOT splice foreign KV pages in — it re-prefills over
    prompt + emitted instead, which is still token-identical under
    greedy."""
    cfg, params = tiny_lm
    rng = np.random.default_rng(22)
    prompt = rng.integers(2, cfg.vocab_size, (9,)).tolist()
    max_new = 10
    reference = _dense_greedy(cfg, params, prompt, max_new)
    src = PagedDecodeEngine(cfg, params, block_len=8, max_slots=2)
    dst = PagedDecodeEngine(cfg, params, block_len=8, max_slots=2)
    try:
        seq = src.submit(prompt, max_new, request_id="mig2", stream=True)
        while len(seq.generated) < 3:
            src.admit()
            src.step()
        snap = src.export_sequence(seq.uid)
        snap["manifest"]["model_digest"] = "not-the-same-model"
        preempt0 = _prom_value("synapseml_llm_slots_preempted_total")
        moved = dst.import_sequence(snap)
        assert moved.tokens_in_pages == 0, "mismatched digest spliced KV"
        assert _run_to_done(dst, moved) == reference
        assert _prom_value("synapseml_llm_slots_preempted_total") > preempt0
        assert dst.allocator.used_count == 0
    finally:
        src.release()
        dst.release()


def test_export_import_sampled_identity(tiny_lm):
    """Sampling folds (seed, uid, step): a migrated SAMPLED sequence keeps
    its uid, so the continuation draws the same tokens the unmigrated run
    draws on an engine with the same seed."""
    cfg, params = tiny_lm
    rng = np.random.default_rng(23)
    prompt = rng.integers(2, cfg.vocab_size, (9,)).tolist()
    kw = dict(block_len=8, max_slots=2, temperature=0.9, top_p=0.95, seed=5)
    ref_eng = PagedDecodeEngine(cfg, params, **kw)
    src = PagedDecodeEngine(cfg, params, **kw)
    dst = PagedDecodeEngine(cfg, params, **kw)
    try:
        reference = ref_eng.generate([prompt], 10, uids=[77])[0]
        seq = src.submit(prompt, 10, request_id="smp", stream=True, uid=77)
        while len(seq.generated) < 4:
            src.admit()
            src.step()
        moved = dst.import_sequence(src.export_sequence(seq.uid))
        assert moved.uid == 77
        assert _run_to_done(dst, moved) == reference
    finally:
        ref_eng.release()
        src.release()
        dst.release()


def test_deadline_expires_sequence_with_504(tiny_lm):
    """A client deadline propagates as ``X-Deadline-Ms`` and the engine
    expires the sequence: pages freed, terminal 504 with
    ``finish_reason=deadline``."""
    import http.client

    from synapseml_tpu.hf import HuggingFaceCausalLM
    from synapseml_tpu.io.serving import serve_llm

    lm = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=64,
                             engine="paged")
    srv = serve_llm(lm, warmup=False)
    try:
        host, port = srv.address.split("//")[1].split(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=60)
        conn.request("POST", "/",
                     body=json.dumps({"prompt": "too slow"}).encode(),
                     headers={"X-Deadline-Ms": "1"})
        r = conn.getresponse()
        body = json.loads(r.read())
        conn.close()
        assert r.status == 504
        assert body["finish_reason"] == "deadline"
        assert _prom_value("synapseml_llm_sequences_finished_total",
                           'reason="deadline"') >= 1
    finally:
        srv.stop()


def test_client_disconnect_reaps_sequence():
    """Satellite: a client that walks away after 3 chunks must not leave
    the sequence decoding to max_new while holding KV pages — the dead
    exchange is detected and the sequence aborts with
    ``finish_reason=client_gone``."""
    import socket

    from synapseml_tpu.hf import HuggingFaceCausalLM
    from synapseml_tpu.io.serving import serve_llm

    lm = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=64,
                             engine="paged")
    srv = serve_llm(lm, warmup=False)
    try:
        host, port = srv.address.split("//")[1].split(":")
        before = _prom_value("synapseml_llm_sequences_finished_total",
                             'reason="client_gone"')
        raw = socket.create_connection((host, int(port)), timeout=60)
        payload = json.dumps({"prompt": "walk away", "stream": True,
                              "max_new_tokens": 500}).encode()
        raw.sendall(b"POST / HTTP/1.1\r\nHost: t\r\nContent-Length: "
                    + str(len(payload)).encode() + b"\r\n\r\n" + payload)
        got = b""
        while got.count(b"\n") < 10:  # headers + ~3 chunks
            got += raw.recv(4096)
        raw.close()  # client gone, sequence still decoding
        deadline = time.perf_counter() + 60
        while time.perf_counter() < deadline:
            if _prom_value("synapseml_llm_sequences_finished_total",
                           'reason="client_gone"') > before:
                break
            time.sleep(0.2)
        assert _prom_value("synapseml_llm_sequences_finished_total",
                           'reason="client_gone"') > before, \
            "disconnected client's sequence was never reaped"
    finally:
        srv.stop()


def test_hot_swap_terminates_live_streams():
    """Satellite: a hot swap must send a TERMINAL error chunk to every
    live streaming exchange of the replaced engine — never a silent hang
    to client timeout."""
    import http.client
    import threading

    from synapseml_tpu.hf import HuggingFaceCausalLM
    from synapseml_tpu.io.serving import PipelineHolder, serve_llm

    lm_a = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=500,
                               engine="paged")
    lm_b = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=4,
                               engine="paged")
    holder = PipelineHolder(lm_a, "v1")
    srv = serve_llm(holder, warmup=False)
    try:
        host, port = srv.address.split("//")[1].split(":")
        out = {}

        def run():
            conn = http.client.HTTPConnection(host, int(port), timeout=120)
            conn.request("POST", "/", body=json.dumps(
                {"prompt": "long running", "stream": True,
                 "max_new_tokens": 500}).encode())
            r = conn.getresponse()
            out["chunks"] = [json.loads(l) for l in iter(r.readline, b"")
                             if l.strip()]
            conn.close()

        t = threading.Thread(target=run)
        t.start()
        deadline = time.perf_counter() + 60
        while time.perf_counter() < deadline:  # wait for live decode
            if _prom_value("synapseml_llm_kv_block_occupancy") > 0:
                break
            time.sleep(0.1)
        holder.swap(lm_b, "v2")
        t.join(90)
        assert not t.is_alive(), "stream hung through the hot swap"
        chunks = out["chunks"]
        assert chunks, "no chunks before the swap terminal"
        last = chunks[-1]
        assert last.get("done") and "error" in last, \
            f"expected terminal error chunk, got {last}"
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# journaled streams through the RoutingFront (survivable serving plane)
# ---------------------------------------------------------------------------

def _start_llm_worker(max_new=64, warmup=False, **lm_kw):
    from synapseml_tpu.hf import HuggingFaceCausalLM
    from synapseml_tpu.io.serving import serve_llm

    lm = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=max_new,
                             engine="paged", **lm_kw)
    return serve_llm(lm, warmup=warmup)


def _request(address, payload, headers=None, timeout=120, path="/"):
    """POST ``payload`` and collect the reply: non-stream -> (status,
    body-dict, headers); stream -> (status, [chunk, ...], headers)."""
    import http.client

    host, port = address.split("//")[1].split(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    conn.request("POST", path, body=json.dumps(payload).encode(),
                 headers=headers or {})
    r = conn.getresponse()
    try:
        if payload.get("stream"):
            out = [json.loads(l) for l in iter(r.readline, b"") if l.strip()]
        else:
            out = json.loads(r.read() or b"null")
    finally:
        conn.close()
    return r.status, out, dict(r.getheaders())


def _assert_contiguous_seqs(chunks):
    """Zero duplicate + zero lost tokens: token chunks carry seq 0..n-1
    with no gaps or repeats, terminal carries seq == n."""
    toks = [c for c in chunks if "token" in c and not c.get("done")]
    seqs = [c["seq"] for c in toks]
    assert seqs == list(range(len(seqs))), f"dup/lost chunk seqs: {seqs}"
    term = chunks[-1]
    assert term.get("done"), f"stream not terminated: {term}"
    assert "error" not in term, f"terminal error: {term}"
    assert term["seq"] == len(seqs)
    return [c["token"] for c in toks]


def test_front_journal_stream_seq_and_terminal_dedup():
    """Layer-3 contract: journaled streams number every chunk, and a
    retried non-streaming request with the same idempotency key replays
    the recorded terminal instead of generating twice."""
    from synapseml_tpu.io.distributed_serving import RoutingFront

    srv = _start_llm_worker()
    front = RoutingFront([{"host": srv.host, "port": srv.port, "pid": 1}],
                         timeout_s=60, journal=True)
    try:
        prompt = {"input_ids": [5, 9, 17, 4], "max_new_tokens": 6}
        st, chunks, _ = _request(front.address, dict(prompt, stream=True),
                                 headers={"X-Request-Key": "k-stream"})
        assert st == 200
        ids = _assert_contiguous_seqs(chunks)
        assert len(ids) == 6

        replays0 = _prom_value("synapseml_llm_journal_replays_total")
        st1, body1, h1 = _request(front.address, prompt,
                                  headers={"X-Request-Key": "k-once"})
        st2, body2, h2 = _request(front.address, prompt,
                                  headers={"X-Request-Key": "k-once"})
        assert st1 == st2 == 200
        assert body1["output_ids"] == body2["output_ids"] == ids
        assert h1.get("X-Journal-Replay") is None
        assert h2.get("X-Journal-Replay") == "1"
        assert _prom_value("synapseml_llm_journal_replays_total") \
            == replays0 + 1
        assert _prom_value("synapseml_llm_journal_depth") >= 1
    finally:
        front.close()
        srv.stop()


def test_front_hedges_stuck_prefill_first_writer_wins():
    """Layer-4: a prefill with no first token within the hedging budget
    races a second worker; the client sees one winner's stream,
    token-identical and well before the slow path clears."""
    from synapseml_tpu.core.faults import FaultPlan, FaultSpec, inject_faults
    from synapseml_tpu.io.distributed_serving import RoutingFront

    srv_a = _start_llm_worker()
    srv_b = _start_llm_worker()
    front = RoutingFront([{"host": srv_a.host, "port": srv_a.port, "pid": 1},
                          {"host": srv_b.host, "port": srv_b.port, "pid": 2}],
                         timeout_s=60, journal=True, hedge_after_s=1.0)
    payload = {"input_ids": [3, 11, 7], "max_new_tokens": 5, "stream": True}
    try:
        # warm both workers so decode speed, not compile, dominates timing
        for srv in (srv_a, srv_b):
            st, ref, _ = _request(srv.address, payload)
            assert st == 200
        ref_ids = _assert_contiguous_seqs(ref)

        won0 = _prom_value("synapseml_llm_hedges_total", 'outcome="won"')
        # no match filter: whichever worker the rotation picks as PRIMARY
        # eats the one-shot stall; the hedge connect (second) is clean
        plan = FaultPlan([FaultSpec(kind="latency", latency_ms=8000,
                                    times=1,
                                    planes=("distributed_serving",))],
                         seed=7)
        with inject_faults(plan):
            t0 = time.perf_counter()
            st, chunks, _ = _request(front.address, payload,
                                     headers={"X-Request-Key": "k-hedge"})
            took = time.perf_counter() - t0
        assert st == 200
        assert _assert_contiguous_seqs(chunks) == ref_ids
        assert len(plan.injected) == 1, "latency fault never fired"
        assert took < 7.0, f"hedge never cut the slow path short ({took:.1f}s)"
        assert _prom_value("synapseml_llm_hedges_total",
                           'outcome="won"') == won0 + 1
    finally:
        front.close()
        srv_a.stop()
        srv_b.stop()


@pytest.mark.chaos
def test_llmchaos_connection_faults_streams_all_terminate():
    """Satellite chaos scenario: seeded connection faults between front
    and decode worker during streaming — every exchange terminates with a
    complete, greedy-identical generation and the fault log reconciles
    with what clients observed (zero error terminals, zero dup chunks)."""
    import threading

    from synapseml_tpu.core.faults import FaultPlan, FaultSpec, inject_faults
    from synapseml_tpu.io.distributed_serving import RoutingFront

    srv_a = _start_llm_worker()
    srv_b = _start_llm_worker()
    front = RoutingFront([{"host": srv_a.host, "port": srv_a.port, "pid": 1},
                          {"host": srv_b.host, "port": srv_b.port, "pid": 2}],
                         timeout_s=60, journal=True)
    n_streams, n_faults = 6, 4
    rng = np.random.default_rng(31)
    prompts = [rng.integers(2, 200, (5,)).tolist() for _ in range(n_streams)]
    try:
        refs = []
        for p in prompts:  # references + warmup, direct to one worker
            st, chunks, _ = _request(srv_b.address,
                                     {"input_ids": p, "max_new_tokens": 8,
                                      "stream": True})
            assert st == 200
            refs.append(_assert_contiguous_seqs(chunks))

        import urllib.request

        def _retry_count():
            with urllib.request.urlopen(front.address + "/stats",
                                        timeout=10) as r:
                return json.loads(r.read())["resilience"]["retry_count"]

        plan = FaultPlan([FaultSpec(kind="connection_error",
                                    match=f":{srv_a.port}", times=n_faults,
                                    planes=("distributed_serving",))],
                         seed=13)
        retries0 = _retry_count()
        results = [None] * n_streams

        def run(i):
            results[i] = _request(
                front.address,
                {"input_ids": prompts[i], "max_new_tokens": 8,
                 "stream": True},
                headers={"X-Request-Key": f"k-chaos-{i}"})

        with inject_faults(plan):
            threads = [threading.Thread(target=run, args=(i,))
                       for i in range(n_streams)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(120)
        assert all(t is not None for t in results), "a client never returned"
        # counters reconcile with client-observed outcomes: at least one
        # fault fired (the breaker may shield A after the first, which IS
        # the containment working) and every one became a front-side
        # retry, never a client-visible failure
        assert len(plan.injected) >= 1, "no fault ever fired"
        assert _retry_count() - retries0 >= len(plan.injected)
        for i, (st, chunks, _) in enumerate(results):
            assert st == 200
            assert _assert_contiguous_seqs(chunks) == refs[i], \
                f"stream {i} diverged after faulted rerouting"
    finally:
        front.close()
        srv_a.stop()
        srv_b.stop()


@pytest.mark.chaos
def test_live_drain_migrates_every_active_sequence():
    """The acceptance bar for layer 2: /admin/drain with a migrate_to
    front hands EVERY active sequence to a peer — migrations ok == active
    count, zero client-visible errors, and each migrated stream is
    byte-equal (token ids AND text deltas) to an unmigrated run."""
    import threading
    import urllib.request

    from synapseml_tpu.io.distributed_serving import RoutingFront, \
        WorkerRegistry

    srv_a = _start_llm_worker()
    srv_b = _start_llm_worker()
    registry = WorkerRegistry()
    front = RoutingFront(registry=registry, timeout_s=60, journal=True)
    n_streams, max_new = 3, 24
    rng = np.random.default_rng(33)
    prompts = [rng.integers(2, 200, (6,)).tolist() for _ in range(n_streams)]
    try:
        refs = []
        for p in prompts:  # unmigrated references; also warms BOTH workers
            ref_by_worker = []
            for srv in (srv_a, srv_b):
                st, chunks, _ = _request(
                    srv.address, {"input_ids": p, "max_new_tokens": max_new,
                                  "stream": True})
                assert st == 200
                ref_by_worker.append(chunks)
            a, b = ref_by_worker
            assert [c.get("token") for c in a] == \
                [c.get("token") for c in b], "workers disagree undrained"
            refs.append(a)

        # only A registered: all streams land there
        urllib.request.urlopen(urllib.request.Request(
            registry.address + "/register",
            data=json.dumps({"host": srv_a.host, "port": srv_a.port,
                             "pid": 1}).encode(), method="POST"),
            timeout=10).read()

        results = [None] * n_streams
        progress = [0] * n_streams

        def run(i):
            import http.client

            host, port = front.address.split("//")[1].split(":")
            conn = http.client.HTTPConnection(host, int(port), timeout=120)
            conn.request("POST", "/", body=json.dumps(
                {"input_ids": prompts[i], "max_new_tokens": max_new,
                 "stream": True}).encode(),
                headers={"X-Request-Key": f"k-drain-{i}"})
            r = conn.getresponse()
            chunks = []
            for line in iter(r.readline, b""):
                if line.strip():
                    chunks.append(json.loads(line))
                    progress[i] = len(chunks)
            conn.close()
            results[i] = (r.status, chunks)

        mig0 = _prom_value("synapseml_llm_migrations_total", 'outcome="ok"')
        err0 = _prom_value("synapseml_llm_migrations_total",
                           'outcome="error"')
        imp0 = _prom_value("synapseml_llm_resubmits_total", 'mode="import"')
        res0 = _prom_value("synapseml_llm_resubmits_total", 'mode="resume"')
        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(n_streams)]
        for t in threads:
            t.start()
        deadline = time.perf_counter() + 60
        while time.perf_counter() < deadline:  # all streams mid-decode on A
            if all(p >= 2 for p in progress):
                break
            time.sleep(0.05)
        assert all(p >= 2 for p in progress), "streams never got going"

        # peer B joins, then A live-drains: every active sequence must move
        urllib.request.urlopen(urllib.request.Request(
            registry.address + "/register",
            data=json.dumps({"host": srv_b.host, "port": srv_b.port,
                             "pid": 2}).encode(), method="POST"),
            timeout=10).read()
        st, body, _ = _request(srv_a.address,
                               {"migrate_to": front.address},
                               path="/admin/drain")
        assert st < 300, body
        for t in threads:
            t.join(120)
        assert all(r is not None for r in results), "a stream never finished"

        for i, (st, chunks) in enumerate(results):
            assert st == 200
            got = _assert_contiguous_seqs(chunks)
            want = _assert_contiguous_seqs(list(refs[i]))
            assert got == want, f"stream {i} tokens diverged after migration"
            text = "".join(c.get("text") or "" for c in chunks)
            ref_text = "".join(c.get("text") or "" for c in refs[i])
            assert text == ref_text, f"stream {i} text not byte-equal"
        assert _prom_value("synapseml_llm_migrations_total",
                           'outcome="ok"') == mig0 + n_streams
        assert _prom_value("synapseml_llm_migrations_total",
                           'outcome="error"') == err0
        assert _prom_value("synapseml_llm_resubmits_total",
                           'mode="import"') == imp0 + n_streams
        # the KV splice itself served every stream: no re-prefill fallback
        assert _prom_value("synapseml_llm_resubmits_total",
                           'mode="resume"') == res0
    finally:
        front.close()
        registry.close()
        srv_a.stop()
        srv_b.stop()


# ---------------------------------------------------------------------------
# SIGKILL acceptance: crash-transparent decode across real worker processes
# ---------------------------------------------------------------------------

def _worker_metric(address, name):
    import urllib.request

    with urllib.request.urlopen(address + "/metrics", timeout=10) as r:
        for line in r.read().decode().splitlines():
            if line.startswith(name):
                return float(line.rsplit(" ", 1)[1])
    return None


@pytest.mark.chaos(timeout_s=480)
def test_sigkill_one_of_two_workers_mid_decode_16_streams():
    """THE chaos acceptance bar: SIGKILL 1 of 2 decode-worker PROCESSES
    with 16 concurrent streams in flight. Every client still receives a
    complete generation, greedy-token-identical to an uninterrupted
    single-worker reference, with zero duplicate chunks; the survivor
    ends with zero KV pages in use (allocator accounting exact)."""
    import os
    import signal
    import subprocess
    import sys
    import threading

    from synapseml_tpu.io.distributed_serving import RoutingFront, \
        WorkerRegistry

    registry = WorkerRegistry()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = os.pathsep.join(
        [repo_root, env.get("PYTHONPATH", "")])
    code = ("from synapseml_tpu.io.distributed_serving import "
            "llm_worker_main; "
            f"llm_worker_main('llama-tiny', "
            f"{registry.address + '/register'!r}, max_new_tokens=64)")
    procs = [subprocess.Popen([sys.executable, "-c", code], env=env)
             for _ in range(2)]
    front = None
    n_streams, max_new = 16, 24
    rng = np.random.default_rng(35)
    prompts = [rng.integers(2, 200, (6,)).tolist() for _ in range(n_streams)]
    try:
        workers = registry.wait_for(2, timeout_s=240)
        by_pid = {w["pid"]: w for w in workers}
        victim = procs[0]
        survivor_info = next(w for w in workers
                             if w["pid"] != victim.pid)
        survivor_addr = f"http://{survivor_info['host']}:" \
                        f"{survivor_info['port']}"
        assert victim.pid in by_pid, "victim worker never registered"

        # uninterrupted single-worker reference (greedy): ask the SURVIVOR
        # directly; this also warms its prefill/decode executables
        refs = []
        for p in prompts:
            st, body, _ = _request(survivor_addr,
                                   {"input_ids": p,
                                    "max_new_tokens": max_new}, timeout=240)
            assert st == 200, body
            refs.append(body["output_ids"])

        front = RoutingFront(registry=registry, timeout_s=60, journal=True)
        results = [None] * n_streams
        progress = [0] * n_streams

        def run(i):
            import http.client

            host, port = front.address.split("//")[1].split(":")
            conn = http.client.HTTPConnection(host, int(port), timeout=300)
            conn.request("POST", "/", body=json.dumps(
                {"input_ids": prompts[i], "max_new_tokens": max_new,
                 "stream": True}).encode(),
                headers={"X-Request-Key": f"k-kill-{i}"})
            r = conn.getresponse()
            chunks = []
            for line in iter(r.readline, b""):
                if line.strip():
                    chunks.append(json.loads(line))
                    progress[i] = len(chunks)
            conn.close()
            results[i] = (r.status, chunks)

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(n_streams)]
        for t in threads:
            t.start()
        deadline = time.perf_counter() + 120
        while time.perf_counter() < deadline:  # decode genuinely in flight
            if sum(progress) >= 2 * n_streams:
                break
            time.sleep(0.02)
        assert sum(progress) >= 2 * n_streams, "streams never got going"
        os.kill(victim.pid, signal.SIGKILL)  # mid-decode, no goodbye
        victim.wait(30)
        for t in threads:
            t.join(240)
        assert all(r is not None for r in results), "a client hung forever"

        for i, (st, chunks) in enumerate(results):
            assert st == 200
            got = _assert_contiguous_seqs(chunks)  # zero dup / zero lost
            want = refs[i]
            assert got == want, \
                f"stream {i}: crash recovery diverged from reference"

        # the survivor must hold ZERO kv pages once every stream is done
        deadline = time.perf_counter() + 30
        occ = None
        while time.perf_counter() < deadline:
            occ = _worker_metric(survivor_addr,
                                 "synapseml_llm_kv_block_occupancy")
            if occ == 0.0:
                break
            time.sleep(0.25)
        assert occ == 0.0, f"survivor leaked KV pages (occupancy={occ})"
        # the front actually exercised the crash path
        assert _prom_value("synapseml_llm_resubmits_total") > 0
    finally:
        if front is not None:
            front.close()
        registry.close()
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait(30)


# ---------------------------------------------------------------------------
# prefix-cached KV reuse + greedy speculative decoding
# ---------------------------------------------------------------------------

def test_prefix_and_spec_parity_across_rungs(tiny_lm):
    """BOTH features on (prefix cache + speculation) stay token-identical
    to the plain paged engine across >= 3 seq-ladder rungs, on a stream
    where several prompts share a long head (cache hits, a fully-cached
    COW prompt, and multi-token speculative steps all fire) — run twice so
    round 2 decodes entirely over cached prefix pages."""
    cfg, params = tiny_lm
    rng = np.random.default_rng(41)
    head = rng.integers(2, cfg.vocab_size, (24,)).tolist()  # 3 blocks of 8
    prompts = [
        head[:5],                                                 # rung 8
        head[:14],                                                # rung 16
        head + rng.integers(2, cfg.vocab_size, (4,)).tolist(),    # rung 32
        head + rng.integers(2, cfg.vocab_size, (30,)).tolist(),   # rung 64
        list(head),                     # block-multiple prompt: COW path
    ]
    max_new = 10
    kw = dict(block_len=8, max_slots=4,
              bucketer=ShapeBucketer(ladder=[1, 2, 4, 8],
                                     seq_ladder=[8, 16, 32, 64]))
    plain = PagedDecodeEngine(cfg, params, **kw)
    want = plain.generate(prompts, max_new)
    plain.release()

    boosted = PagedDecodeEngine(cfg, params, prefix_cache=True,
                                draft_tokens=3, **kw)
    for round_ in range(2):
        got = boosted.generate(prompts, max_new)
        assert got == want, f"boosted engine diverged on round {round_}"
    pc = boosted.stats()["prefix_cache"]
    assert pc["hits"] > 0 and pc["tokens_reused"] > 0, \
        "stream never exercised the prefix cache"
    sp = boosted.stats()["speculation"]
    assert sp["steps"] > 0, "stream never exercised speculation"
    boosted.release()


def test_prefix_and_spec_parity_with_early_eos(tiny_lm):
    """Early-EOS parity with both features on: a draft window that crosses
    the EOS must discard the speculated tail, and the freed shared pages
    must not corrupt any still-running row."""
    cfg, params = tiny_lm
    rng = np.random.default_rng(43)
    head = rng.integers(2, cfg.vocab_size, (16,)).tolist()
    prompts = [head + rng.integers(2, cfg.vocab_size, (int(n),)).tolist()
               for n in (2, 9, 21, 40)]
    max_new = 16
    kw = dict(block_len=8, max_slots=4,
              bucketer=ShapeBucketer(ladder=[1, 2, 4, 8],
                                     seq_ladder=[8, 16, 32, 64]))
    free_eng = PagedDecodeEngine(cfg, params, **kw)
    free_run = free_eng.generate(prompts, max_new)
    free_eng.release()
    eos_id = None  # an eos that hits mid-stream for some rows, not all
    for row in free_run:
        for tok in row[1:max_new // 2]:
            if sum(tok in r for r in free_run) < len(free_run):
                eos_id = int(tok)
                break
        if eos_id is not None:
            break
    assert eos_id is not None

    plain = PagedDecodeEngine(cfg, params, eos_id=eos_id, **kw)
    want = [_trim_eos(r, eos_id) for r in plain.generate(prompts, max_new)]
    plain.release()
    assert any(len(r) < max_new for r in want), "eos never fired"

    boosted = PagedDecodeEngine(cfg, params, eos_id=eos_id,
                                prefix_cache=True, draft_tokens=3, **kw)
    got = [_trim_eos(r, eos_id) for r in boosted.generate(prompts, max_new)]
    assert got == want
    # every non-cache page freed once every sequence finished
    assert boosted.allocator.used_count == \
        len(boosted.prefix_cache.block_ids())
    boosted.release()


def test_prefix_and_spec_parity_under_preemption(tiny_lm):
    """A pool too small for the working set still produces token-identical
    output with both features on: preemption releases shared pages to the
    cache (refcounts, not frees), eviction makes room, and the preempted
    sequence's re-prefill may legitimately ride its OWN cached blocks."""
    cfg, params = tiny_lm
    rng = np.random.default_rng(45)
    prompts = [rng.integers(2, cfg.vocab_size, (20,)).tolist()
               for _ in range(4)]
    max_new = 20
    kw = dict(block_len=8, max_slots=4,
              bucketer=ShapeBucketer(ladder=[1, 2, 4, 8],
                                     seq_ladder=[8, 16, 32, 64]))
    roomy = PagedDecodeEngine(cfg, params, **kw)
    want = roomy.generate(prompts, max_new)
    roomy.release()

    tight = PagedDecodeEngine(cfg, params, n_blocks=14, prefix_cache=True,
                              draft_tokens=3, **kw)
    seqs = [tight.submit(p, max_new) for p in prompts]
    deadline = time.perf_counter() + 120
    while any(not s.done for s in seqs) and time.perf_counter() < deadline:
        tight.admit()
        tight.step()
    assert all(s.done for s in seqs), "tight pool wedged"
    assert [list(s.generated) for s in seqs] == want
    assert sum(s.preemptions for s in seqs) >= 1, \
        "pool was never actually tight"
    tight.release()


def test_speculation_is_greedy_only(tiny_lm):
    """draft_tokens > 0 with a sampling temperature must be rejected up
    front — the acceptance rule compares argmaxes, so sampling would
    silently break the token-identity guarantee."""
    cfg, params = tiny_lm
    with pytest.raises(ValueError, match="greedy"):
        PagedDecodeEngine(cfg, params, block_len=8, max_slots=2,
                          draft_tokens=3, temperature=0.9)


def test_compile_counts_bounded_with_prefix_and_spec(tiny_lm):
    """The acceptance bar on executables: two rounds of a shared-prefix
    stream (heavy extend + spec traffic) compile at most one program per
    ladder rung for EACH of the four paged fn ids — no per-shape or
    per-request recompiles."""
    cfg, params = tiny_lm
    cache = cb.get_compiled_cache()
    ids = ("llama_paged_prefill", "llama_paged_extend",
           "llama_paged_decode", "llama_paged_spec")
    before = {i: cache.miss_count(i) for i in ids}
    eng = PagedDecodeEngine(
        cfg, params, block_len=16, max_slots=8, prefill_batch=2,
        prefix_cache=True, draft_tokens=3,
        bucketer=ShapeBucketer(ladder=[2, 4, 8], seq_ladder=[16, 32, 64]))
    rng = np.random.default_rng(47)
    heads = [rng.integers(2, cfg.vocab_size, (20,)).tolist()
             for _ in range(3)]
    prompts = [heads[k % 3] + rng.integers(
        2, cfg.vocab_size, (int(rng.integers(1, 30)),)).tolist()
        for k in range(24)]
    for _ in range(2):  # round 2: every family head is cache-resident
        assert eng.generate(prompts, 8) is not None
    pc = eng.stats()["prefix_cache"]
    assert pc["hits"] > 0, "no extend traffic — the bound proved nothing"
    assert eng.stats()["speculation"]["steps"] > 0
    n_seq = len(eng.bucketer.seq_buckets_upto(eng.max_len))
    deltas = {i: cache.miss_count(i) - before[i] for i in ids}
    assert 0 < deltas["llama_paged_prefill"] <= n_seq, deltas
    assert 0 < deltas["llama_paged_extend"] <= n_seq, deltas
    # plain decode only compiles on spec FALLBACK — with an ample pool
    # every step rides the spec program, so 0 is legitimate here
    assert deltas["llama_paged_decode"] <= len(eng.slot_rungs), deltas
    assert 0 < deltas["llama_paged_spec"] <= len(eng.slot_rungs), deltas
    eng.release()


def test_warmup_covers_extend_and_spec_rungs(tiny_lm):
    """warmup() on a both-features engine precompiles the suffix-extend
    and draft/verify rungs too: a mixed shared-prefix stream afterwards
    causes ZERO new compiles of any paged program (the /admin/load
    zero-compile-stall contract extends to the new executables)."""
    cfg, params = tiny_lm
    cache = cb.get_compiled_cache()
    eng = PagedDecodeEngine(
        cfg, params, block_len=16, max_slots=4, prefill_batch=2,
        prefix_cache=True, draft_tokens=3,
        bucketer=ShapeBucketer(ladder=[2, 4], seq_ladder=[16, 32, 64]))
    eng.warmup()
    ids = ("llama_paged_prefill", "llama_paged_extend",
           "llama_paged_decode", "llama_paged_spec")
    before = {i: cache.miss_count(i) for i in ids}
    rng = np.random.default_rng(49)
    head = rng.integers(2, cfg.vocab_size, (32,)).tolist()
    prompts = [head + rng.integers(2, cfg.vocab_size, (int(n),)).tolist()
               for n in rng.integers(1, 30, (8,))]
    for _ in range(2):
        eng.generate(prompts, 6)
    assert eng.stats()["prefix_cache"]["hits"] > 0
    for i in ids:
        assert cache.miss_count(i) == before[i], \
            f"{i} compiled after warmup"
    eng.release()


def test_block_allocator_refcount_invariants_property():
    """Satellite: randomized ref/free/alloc interleaving — a shared block
    is never handed out again while ANY holder remains, refcounts are
    conserved exactly, and ref/free on a non-live block is a hard error
    (no silent double-free, no resurrect-after-free)."""
    rng = np.random.default_rng(1)
    alloc = BlockAllocator(25)
    holders: dict[int, int] = {}  # block -> expected refcount
    for _ in range(800):
        r = rng.random()
        if holders and r < 0.35:
            b = int(rng.choice(list(holders)))
            alloc.free([b])
            holders[b] -= 1
            if holders[b] == 0:
                del holders[b]
        elif holders and r < 0.55:
            b = int(rng.choice(list(holders)))
            alloc.ref(b)
            holders[b] += 1
        else:
            got = alloc.alloc(int(rng.integers(1, 4)))
            if got is None:
                continue
            assert 0 not in got, "trash page handed out"
            assert not (set(got) & set(holders)), \
                "block re-allocated while still referenced"
            for b in got:
                holders[b] = 1
        for b, n in holders.items():
            assert alloc.refcount(b) == n, (b, n)
        assert alloc.used_count == len(holders)
        assert alloc.free_count == alloc.capacity - len(holders)
    for b, n in list(holders.items()):  # drain every remaining ref
        for _ in range(n):
            alloc.free([b])
    assert alloc.used_count == 0
    with pytest.raises(RuntimeError):
        alloc.free([1])  # fully-released block: freeing again is fatal
    with pytest.raises(RuntimeError):
        alloc.ref(1)  # ...and so is resurrecting it with a new ref
    with pytest.raises(RuntimeError):
        alloc.ref(0)  # the trash page is never shareable


def _assert_refcount_conservation(eng):
    """Every live block's refcount equals its holder count (active
    sequences + the prefix cache), the pool accounts exactly, and the
    block each sequence will write next is PRIVATE — shared pages are
    immutable while shared."""
    holders: dict[int, int] = {}
    cache_blocks = eng.prefix_cache.block_ids()
    for s in eng._active:
        assert 0 not in s.blocks, "trash page in a live block table"
        for b in s.blocks:
            holders[b] = holders.get(b, 0) + 1
        wi = s.tokens_in_pages // eng.block_len
        if wi < len(s.blocks):
            wb = s.blocks[wi]
            assert eng.allocator.refcount(wb) == 1, \
                f"seq {s.uid} would write shared block {wb}"
            assert wb not in cache_blocks
    for b in cache_blocks:
        holders[b] = holders.get(b, 0) + 1
    for b, n in holders.items():
        assert eng.allocator.refcount(b) == n, (b, n)
    assert eng.allocator.used_count == len(holders)


def test_prefix_cache_fuzz_refcounts_cow_and_parity(tiny_lm):
    """Satellite fuzz: a randomized stream of prompts forking off two
    shared heads (exact-head COW forks, divergent suffixes, unrelated
    prompts) churns through a SMALL pool with speculation on. After every
    scheduler tick: refcount conservation, write-block privacy, exact pool
    accounting. Every completion must match a plain single-sequence run —
    a child's writes never leak into a parent's shared pages."""
    cfg, params = tiny_lm
    rng = np.random.default_rng(51)
    heads = [rng.integers(2, cfg.vocab_size, (16,)).tolist()
             for _ in range(2)]
    bucketer = ShapeBucketer(ladder=[1, 2, 4], seq_ladder=[8, 16, 32, 64])
    eng = PagedDecodeEngine(cfg, params, block_len=8, max_slots=4,
                            n_blocks=28, prefix_cache=True, draft_tokens=2,
                            bucketer=bucketer)
    plain = PagedDecodeEngine(cfg, params, block_len=8, max_slots=4,
                              bucketer=bucketer)
    live, done = [], []
    for _ in range(30):
        if rng.random() < 0.7:
            r = rng.random()
            h = heads[int(rng.integers(0, 2))]
            if r < 0.3:
                p = list(h)  # block-multiple prompt: the COW path
            elif r < 0.8:
                p = h + rng.integers(2, cfg.vocab_size,
                                     (int(rng.integers(1, 12)),)).tolist()
            else:
                p = rng.integers(2, cfg.vocab_size,
                                 (int(rng.integers(3, 20)),)).tolist()
            live.append(eng.submit(p, int(rng.integers(2, 8))))
        eng.admit()
        eng.step()
        _assert_refcount_conservation(eng)
        done += [s for s in live if s.done]
        live = [s for s in live if not s.done]
    deadline = time.perf_counter() + 120
    while any(not s.done for s in live) and time.perf_counter() < deadline:
        eng.admit()
        eng.step()
        _assert_refcount_conservation(eng)
    done += live
    assert eng.stats()["prefix_cache"]["hits"] > 0
    for s in done:
        assert s.done
        want = plain.generate([list(s.prompt_ids)], s.max_new_tokens)[0]
        assert list(s.generated) == want, \
            f"seq {s.uid} diverged (shared-page corruption?)"
    plain.release()
    eng.release()


def test_export_import_with_shared_prefix_pages(tiny_lm):
    """PR-14 compat: a sequence holding SHARED (refcounted) prefix pages
    exports and imports with zero duplicated and zero lost tokens; the
    source's cached pages survive the export intact (a same-prefix rerun
    on the source still matches), and both allocators account exactly to
    their caches' holdings."""
    cfg, params = tiny_lm
    rng = np.random.default_rng(53)
    head = rng.integers(2, cfg.vocab_size, (16,)).tolist()
    prompt = head + rng.integers(2, cfg.vocab_size, (5,)).tolist()
    max_new = 12
    kw = dict(block_len=8, max_slots=2)
    plain = PagedDecodeEngine(cfg, params, **kw)
    reference = plain.generate([prompt], max_new)[0]
    plain.release()

    src = PagedDecodeEngine(cfg, params, prefix_cache=True, **kw)
    dst = PagedDecodeEngine(cfg, params, prefix_cache=True, **kw)
    try:
        # seed the source cache so the migrating sequence SHARES its head
        src.generate([head + [3, 5]], 4)
        seq = src.submit(prompt, max_new, request_id="shared-mig",
                         stream=True)
        while len(seq.generated) < 4:
            src.admit()
            src.step()
        assert any(src.allocator.refcount(b) > 1 for b in seq.blocks), \
            "setup failed: the migrating sequence shares no pages"
        snap = src.export_sequence(seq.uid)
        assert snap is not None
        # export released the sequence's refs; the cache's refs survive
        assert src.allocator.used_count == \
            len(src.prefix_cache.block_ids()), "export leaked source pages"
        moved = dst.import_sequence(snap)
        assert list(moved.generated) == list(seq.generated)
        assert _run_to_done(dst, moved) == reference, \
            "migrated decode diverged"
        assert dst.allocator.used_count == \
            len(dst.prefix_cache.block_ids()), "import leaked dest pages"
        # source cache pages are still byte-valid after the export
        assert src.generate([prompt], max_new)[0] == reference
    finally:
        src.release()
        dst.release()


def test_spec_decode_replays_token_identically_through_kill(tiny_lm):
    """PR-14 compat: kill the engine mid-draft-window (release, no
    export), resume every unfinished sequence on a survivor ALSO running
    prefix cache + speculation through the crash-path manifest the
    RoutingFront journal uses. Combined emissions must carry zero
    duplicate and zero lost token indices and equal the uninterrupted
    stream — ``index`` is stamped at emission time, so multi-token
    speculative steps number their chunks exactly."""
    cfg, params = tiny_lm
    rng = np.random.default_rng(55)
    prompts = [rng.integers(2, cfg.vocab_size, (int(n),)).tolist()
               for n in (7, 18, 33)]
    max_new = 14
    kw = dict(block_len=8, max_slots=4,
              bucketer=ShapeBucketer(ladder=[1, 2, 4, 8],
                                     seq_ladder=[8, 16, 32, 64]))
    plain = PagedDecodeEngine(cfg, params, **kw)
    want = plain.generate(prompts, max_new)
    plain.release()

    boost = dict(kw, prefix_cache=True, draft_tokens=3)
    victim = PagedDecodeEngine(cfg, params, **boost)
    seqs = [victim.submit(p, max_new, request_id=str(i), stream=True)
            for i, p in enumerate(prompts)]
    by_uid = {s.uid: i for i, s in enumerate(seqs)}
    emissions: list[list] = [[] for _ in prompts]

    def drain(events):
        for ev in events:
            if ev.get("token") is not None:
                emissions[by_uid[ev["seq"].uid]].append(
                    (int(ev["index"]), int(ev["token"])))

    while sum(len(e) for e in emissions) < len(prompts) * max_new // 2:
        drain(victim.admit())
        drain(victim.step())
    unfinished = [s for s in seqs if not s.done]
    assert unfinished, "kill point too late to prove anything"
    victim.release()  # SIGKILL analog: pages gone, nothing exported

    survivor = PagedDecodeEngine(cfg, params, **boost)
    moved = [survivor.import_sequence({"manifest": {
        "uid": s.uid, "prompt_ids": list(s.prompt_ids),
        "generated": list(s.generated),
        "max_new_tokens": s.max_new_tokens, "request_id": s.request_id,
        "stream": True, "tokens_in_pages": 0,
        "model_digest": "crashed-worker"}}) for s in unfinished]
    deadline = time.perf_counter() + 120
    while any(not s.done for s in moved) and time.perf_counter() < deadline:
        drain(survivor.admit())
        drain(survivor.step())
    assert all(s.done for s in moved)
    assert survivor.stats()["speculation"]["steps"] > 0, \
        "the resumed run never speculated"
    survivor.release()
    for i, ems in enumerate(emissions):
        idxs = [ix for ix, _ in ems]
        assert len(idxs) == len(set(idxs)), f"duplicate tokens, stream {i}"
        got = [t for _, t in sorted(ems)]
        assert got == want[i], f"stream {i} diverged through the kill"


def test_causal_lm_resolves_speculation_params(tiny_lm):
    """The Params surface wires through: prefix_cache/draft_tokens reach
    the engine, 'self:<n>' pins the early-exit layer, and a registry
    drafter_ref resolves a real (cfg, params) drafter."""
    from synapseml_tpu.hf import HuggingFaceCausalLM

    lm = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=4,
                             engine="paged", prefix_cache=True,
                             draft_tokens=2)
    eng = lm._paged_engine(lm._effective_gen_cfg())
    assert eng.prefix_cache is not None
    assert eng.draft_tokens == 2
    assert eng._drafter is None  # self-draft default

    lm2 = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=4,
                              engine="paged", draft_tokens=2,
                              drafter_ref="self:1")
    eng2 = lm2._paged_engine(lm2._effective_gen_cfg())
    assert eng2.draft_layers == 1

    lm3 = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=4,
                              engine="paged", draft_tokens=2,
                              drafter_ref="llama-tiny")
    eng3 = lm3._paged_engine(lm3._effective_gen_cfg())
    assert eng3._drafter is not None


def test_admin_stats_exposes_prefix_and_speculation():
    """Satellite: GET /admin/stats on a serving worker carries the
    engine's prefix-cache occupancy/hit-rate and speculation acceptance
    under an ``llm`` key — the same numbers the fleet autoscaler and the
    prefix-affinity router consume."""
    import urllib.request

    srv = _start_llm_worker(max_new=6, prefix_cache=True, draft_tokens=2)
    try:
        ids = list(range(2, 22))
        for _ in range(2):  # second pass hits the cache
            st, body, _ = _request(srv.address,
                                   {"input_ids": ids, "max_new_tokens": 4})
            assert st == 200, body
        with urllib.request.urlopen(srv.address + "/admin/stats",
                                    timeout=30) as r:
            stats = json.loads(r.read())
        llm = stats["llm"]
        assert llm["prefix_cache"]["hits"] >= 1
        assert 0.0 < llm["prefix_cache"]["hit_rate"] <= 1.0
        assert "occupancy" in llm["prefix_cache"]
        assert llm["speculation"]["draft_tokens"] == 2
        assert "acceptance_rate" in llm["speculation"]
        # the gauge mirror on /metrics agrees
        assert _prom_value("synapseml_llm_prefix_hit_rate") > 0.0
    finally:
        srv.stop()


def test_front_prefix_routing_beats_unrouted_hit_rate():
    """Fleet E2E acceptance: 2 prefix-cached workers behind a
    RoutingFront, one request stream drawn from 3 shared-prefix families.
    With ``route_by_prefix`` each family packs onto one worker (one cold
    miss per family fleet-wide); plain rotation cold-misses every family
    on BOTH workers — the routed fleet's aggregate hit rate must beat the
    unrouted fleet's on the SAME stream, same round."""
    import urllib.request

    from synapseml_tpu.io.distributed_serving import RoutingFront

    rng = np.random.default_rng(57)
    families = [rng.integers(2, 200, (24,)).tolist() for _ in range(3)]
    stream = [families[k % 3]
              + rng.integers(2, 200, (int(rng.integers(1, 6)),)).tolist()
              for k in range(18)]

    def run_round(route_by_prefix):
        workers = [_start_llm_worker(max_new=4, prefix_cache=True)
                   for _ in range(2)]
        front = RoutingFront(
            [{"host": s.host, "port": s.port, "pid": i + 1}
             for i, s in enumerate(workers)],
            timeout_s=60, route_by_prefix=route_by_prefix)
        try:
            for ids in stream:
                st, body, _ = _request(front.address,
                                       {"input_ids": ids,
                                        "max_new_tokens": 2})
                assert st == 200, body
            hits = misses = 0
            for s in workers:
                with urllib.request.urlopen(s.address + "/admin/stats",
                                            timeout=30) as r:
                    pc = (json.loads(r.read()).get("llm") or {}) \
                        .get("prefix_cache") or {}
                hits += pc.get("hits", 0)
                misses += pc.get("misses", 0)
            return hits / max(hits + misses, 1)
        finally:
            front.close()
            for s in workers:
                s.stop()

    routed = run_round(True)
    unrouted = run_round(False)
    assert routed > unrouted, (routed, unrouted)
