"""Token-level LLM serving: paged KV cache + prefill/decode continuous
batching (models/paged_engine.py, models/flax_nets/llama.py paged modules,
io/serving.serve_llm).

The load-bearing guarantees:
  * greedy paged prefill+decode is TOKEN-IDENTICAL to the dense
    ``greedy_generate`` across prompt lengths spanning >= 3 seq-ladder
    rungs, including early-EOS rows;
  * block free/realloc never aliases a live page (property test);
  * decode slots refill the moment a sequence finishes — no
    run-to-completion barrier;
  * compile counts stay bounded by the ladders and every jit goes through
    the shared CompiledCache (static check in test_codegen.py);
  * the token scheduler streams chunked replies and never strands a client
    on a dropped request.
"""

import json
import time

import numpy as np
import pytest

from synapseml_tpu.core import batching as cb
from synapseml_tpu.core.batching import ShapeBucketer
from synapseml_tpu.models.paged_engine import BlockAllocator, PagedDecodeEngine


def _tiny_cfg_params(**kw):
    import jax
    import jax.numpy as jnp
    from flax.core import meta

    from synapseml_tpu.models.flax_nets.llama import LlamaLM, llama_tiny

    cfg = llama_tiny(**kw)
    params = LlamaLM(cfg).init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 8), jnp.int32))["params"]
    params = jax.tree.map(
        lambda x: x.value if isinstance(x, meta.Partitioned) else x, params,
        is_leaf=lambda x: isinstance(x, meta.Partitioned))
    return cfg, params


@pytest.fixture(scope="module")
def tiny_lm():
    # f32 compute: the parity guarantee is exact under f32, where XLA
    # fusion cannot move bf16 rounding points. Under bf16 the dense and
    # paged PROGRAMS round intermediates at different fusion boundaries,
    # so a near-tie argmax can flip (observed: top-2 logits 0.0035 apart
    # flipped on one prompt) — documented in docs/SERVING.md. Serving and
    # offline transform share ONE engine (same executables), so they are
    # token-identical to each other at any dtype.
    import jax.numpy as jnp

    return _tiny_cfg_params(dtype=jnp.float32)


def _dense_greedy(cfg, params, prompt, max_new, eos_id=None):
    import jax.numpy as jnp

    from synapseml_tpu.models.flax_nets.llama import LlamaLM, greedy_generate

    P = max(((len(prompt) + 7) // 8) * 8, 8)
    ids = np.zeros((1, P), np.int32)
    mask = np.zeros((1, P), np.int32)
    ids[0, :len(prompt)] = prompt
    mask[0, :len(prompt)] = 1
    out = np.asarray(greedy_generate(
        LlamaLM(cfg, decode=True), params, jnp.asarray(ids), max_new,
        eos_id=eos_id, prompt_mask=jnp.asarray(mask)))[0, P:]
    return out.tolist()


def _trim_eos(tokens, eos_id):
    if eos_id is None:
        return list(tokens)
    out = []
    for t in tokens:
        if t == eos_id:
            break
        out.append(t)
    return out


# ---------------------------------------------------------------------------
# parity
# ---------------------------------------------------------------------------

def test_paged_greedy_parity_across_rungs(tiny_lm):
    """Paged prefill+decode produces bit-identical token ids to the dense
    greedy_generate for prompt lengths spanning FOUR seq-ladder rungs, run
    through the continuous scheduler all at once (mixed buckets in flight
    together)."""
    cfg, params = tiny_lm
    rng = np.random.default_rng(3)
    lens = [5, 12, 27, 50]  # rungs 8, 16, 32, 64
    prompts = [rng.integers(2, cfg.vocab_size, (n,)).tolist() for n in lens]
    max_new = 12
    dense = [_dense_greedy(cfg, params, p, max_new) for p in prompts]

    eng = PagedDecodeEngine(
        cfg, params, block_len=16, max_slots=4,
        bucketer=ShapeBucketer(ladder=[1, 2, 4, 8],
                               seq_ladder=[8, 16, 32, 64]))
    paged = eng.generate(prompts, max_new)
    for d, p, n in zip(dense, paged, lens):
        assert d == p, f"paged decode diverged from dense at prompt len {n}"
    eng.release()


def test_paged_greedy_parity_with_early_eos(tiny_lm):
    """Early-EOS parity: pick a token the dense output actually emits
    mid-stream, rerun BOTH engines with it as eos_id — the paged row must
    stop at the same token, and its freed capacity must not corrupt any
    still-running row."""
    cfg, params = tiny_lm
    rng = np.random.default_rng(5)
    lens = [5, 12, 27, 50]
    prompts = [rng.integers(2, cfg.vocab_size, (n,)).tolist() for n in lens]
    max_new = 16
    free_run = [_dense_greedy(cfg, params, p, max_new) for p in prompts]
    # an eos that hits mid-stream for at least one row but not all rows
    eos_id = None
    for row in free_run:
        for tok in row[1:max_new // 2]:
            others = sum(tok in r for r in free_run)
            if others < len(free_run):
                eos_id = int(tok)
                break
        if eos_id is not None:
            break
    assert eos_id is not None
    dense = [_trim_eos(_dense_greedy(cfg, params, p, max_new, eos_id=eos_id),
                       eos_id) for p in prompts]
    assert any(len(d) < max_new for d in dense), "eos never fired"

    eng = PagedDecodeEngine(
        cfg, params, block_len=16, max_slots=4, eos_id=eos_id,
        bucketer=ShapeBucketer(ladder=[1, 2, 4, 8],
                               seq_ladder=[8, 16, 32, 64]))
    paged = [_trim_eos(row, eos_id) for row in eng.generate(prompts, max_new)]
    assert paged == dense
    # every page freed once every sequence finished
    assert eng.allocator.used_count == 0
    eng.release()


def test_paged_sampling_deterministic_per_uid(tiny_lm):
    """Sampled paged decode is a pure function of (seed, uid): same uids ->
    identical streams, different engine seed -> different streams."""
    cfg, params = tiny_lm
    rng = np.random.default_rng(11)
    prompts = [rng.integers(2, cfg.vocab_size, (9,)).tolist()
               for _ in range(3)]
    kw = dict(block_len=16, max_slots=4, temperature=0.9, top_p=0.95)
    a = PagedDecodeEngine(cfg, params, seed=1, **kw).generate(
        prompts, 8, uids=[10, 11, 12])
    b = PagedDecodeEngine(cfg, params, seed=1, **kw).generate(
        prompts, 8, uids=[10, 11, 12])
    c = PagedDecodeEngine(cfg, params, seed=2, **kw).generate(
        prompts, 8, uids=[10, 11, 12])
    assert a == b
    assert a != c


# ---------------------------------------------------------------------------
# block allocator: free/realloc never aliases live pages
# ---------------------------------------------------------------------------

def test_block_allocator_invariants_property():
    rng = np.random.default_rng(0)
    alloc = BlockAllocator(33)
    live: dict[int, list[int]] = {}
    next_id = 0
    for _ in range(500):
        if live and rng.random() < 0.45:
            victim = int(rng.choice(list(live)))
            alloc.free(live.pop(victim))
        else:
            got = alloc.alloc(int(rng.integers(1, 5)))
            if got is None:
                continue
            assert 0 not in got, "trash page handed out"
            flat = [b for blocks in live.values() for b in blocks]
            assert not (set(got) & set(flat)), "live page re-allocated"
            assert len(set(got)) == len(got)
            live[next_id] = got
            next_id += 1
        held = sum(len(b) for b in live.values())
        assert alloc.used_count == held
        assert alloc.free_count == alloc.capacity - held
    with pytest.raises(RuntimeError):
        alloc.free([0])  # trash page was never allocatable


def test_engine_live_pages_never_alias(tiny_lm):
    """Scheduler-level no-aliasing: while a mixed stream churns through
    admit/finish/refill, the union of active block tables stays disjoint
    and never touches the trash page."""
    cfg, params = tiny_lm
    rng = np.random.default_rng(9)
    prompts = [rng.integers(2, cfg.vocab_size, (int(n),)).tolist()
               for n in rng.integers(3, 40, 12)]
    budgets = [int(n) for n in rng.integers(1, 14, 12)]
    eng = PagedDecodeEngine(cfg, params, block_len=8, max_slots=4,
                            n_blocks=40)
    seqs = [eng.submit(p, n) for p, n in zip(prompts, budgets)]
    while any(not s.done for s in seqs):
        eng.admit()
        eng.step()
        seen: set[int] = set()
        for s in eng._active:
            assert 0 not in s.blocks
            overlap = seen & set(s.blocks)
            assert not overlap, f"live pages aliased: {overlap}"
            seen |= set(s.blocks)
        assert len(seen) == eng.allocator.used_count
    assert eng.allocator.used_count == 0
    eng.release()


def test_preemption_recomputes_identically(tiny_lm):
    """A pool too small for the whole stream forces preemption; preempted
    sequences re-prefill prompt+generated and still produce the exact
    unconstrained greedy output."""
    cfg, params = tiny_lm
    rng = np.random.default_rng(13)
    prompts = [rng.integers(2, cfg.vocab_size, (20,)).tolist()
               for _ in range(4)]
    max_new = 20
    roomy = PagedDecodeEngine(cfg, params, block_len=8, max_slots=4)
    want = roomy.generate(prompts, max_new)
    # 4 seqs x (20 prompt + 20 gen) needs 4x5 blocks of 8; 13 usable
    # blocks cannot hold all four -> at least one preemption
    tight = PagedDecodeEngine(cfg, params, block_len=8, max_slots=4,
                              n_blocks=14)
    seqs = [tight.submit(p, max_new) for p in prompts]
    while any(not s.done for s in seqs):
        tight.admit()
        tight.step()
    assert [list(s.generated) for s in seqs] == want
    assert sum(s.preemptions for s in seqs) >= 1, \
        "pool was supposed to be tight enough to preempt"
    roomy.release()
    tight.release()


def test_oversized_sequence_finishes_kv_capacity_not_wedge(tiny_lm):
    """A sequence whose page need exceeds TOTAL pool capacity can never be
    satisfied by freeing — admit must terminate it (finish_reason
    'kv_capacity') instead of wedging the FIFO head, and the request queued
    behind it must still decode."""
    cfg, params = tiny_lm
    rng = np.random.default_rng(7)
    # capacity = 3 usable blocks of 8 = 24 tokens; 30-token prompt needs 4
    eng = PagedDecodeEngine(cfg, params, block_len=8, max_slots=2,
                            n_blocks=4)
    big = eng.submit(rng.integers(2, cfg.vocab_size, (30,)).tolist(), 4)
    ok = eng.submit(rng.integers(2, cfg.vocab_size, (8,)).tolist(), 4)
    for _ in range(50):
        if big.done and ok.done:
            break
        eng.admit()
        eng.step()
    assert big.finish_reason == "kv_capacity" and not big.generated
    assert ok.finish_reason == "length" and len(ok.generated) == 4
    assert eng.allocator.used_count == 0
    eng.release()


def test_released_engine_is_rebuilt_not_reused():
    """release() may leave donated page buffers consumed — the stage's
    engine cache must hand out a FRESH engine afterwards (the serve_llm
    engine-failure rebuild path depends on this), and the serving adapter
    must delegate single-sequence abort()."""
    from synapseml_tpu.hf import HuggingFaceCausalLM

    lm = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=4,
                             engine="paged")
    eff = lm._effective_gen_cfg()
    e1 = lm._paged_engine(eff)
    e1.release()
    e2 = lm._paged_engine(eff)
    assert e2 is not e1 and not e2._released
    adapter = lm.serving_engine()
    seq = adapter.submit({"prompt": "abort me"}, "r1")
    adapter.abort(seq)
    assert seq.finish_reason == "aborted"
    adapter.release()


def test_stream_chunks_decode_cumulatively_not_per_token():
    """Byte-level BPE pieces are not independently decodable: streamed
    chunk text must be the delta of the CUMULATIVE decode (incomplete
    tails held back), so concatenated chunks equal the final text."""
    from synapseml_tpu.hf import HuggingFaceCausalLM

    lm = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=4,
                             engine="paged")
    adapter = lm.serving_engine()

    def decode(ids):  # id pairs -> one char; odd tail -> replacement char
        s = "".join(chr(97 + (a + b) % 26)
                    for a, b in zip(ids[::2], ids[1::2]))
        return s + ("�" if len(ids) % 2 else "")

    adapter._decode = decode
    seq = adapter.submit({"prompt": "x", "stream": True}, "r")
    texts = []
    for t in (5, 6, 7, 8):
        seq.generated.append(t)
        texts.append(adapter.chunk_for({"token": t, "seq": seq})["text"])
    assert "".join(texts) == decode(seq.generated)
    assert "�" not in "".join(texts)
    adapter.release()


def test_paged_transform_tolerates_zero_token_rows():
    """A row whose text tokenizes to ZERO tokens gets an empty completion;
    it must not fail the whole scan (engine.submit rejects empty prompts,
    the dense path does not)."""
    import numpy as np

    from synapseml_tpu.core import DataFrame
    from synapseml_tpu.hf import HuggingFaceCausalLM

    from synapseml_tpu.models.tokenizer import HashingTokenizer

    class _ZeroForBlank(HashingTokenizer):
        def __call__(self, texts, **kw):
            enc = super().__call__(texts, **kw)
            enc["attention_mask"] = np.asarray(enc["attention_mask"]).copy()
            for i, t in enumerate(texts):
                if not t:
                    enc["attention_mask"][i, :] = 0
            return enc

    lm = HuggingFaceCausalLM(model_name="llama-tiny", engine="paged",
                             tokenizer=_ZeroForBlank(),
                             max_new_tokens=4, batch_size=4)
    out = lm.transform(DataFrame.from_dict(
        {"prompt": ["hello there", "", "more text"]}))
    rows = [np.asarray(r) for r in out.collect_column("completions")]
    assert len(rows[0]) == 4 and len(rows[2]) == 4
    assert len(rows[1]) == 0


def test_result_n_tokens_matches_output_ids_on_eos(tiny_lm):
    """result_for strips the trailing EOS from output_ids — n_tokens must
    count the SAME list, not the raw generated length."""
    from synapseml_tpu.hf import HuggingFaceCausalLM

    lm = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=4,
                             engine="paged")
    adapter = lm.serving_engine()
    seq = adapter.submit({"prompt": "x"}, "r")
    seq.generated.extend([5, 6, adapter._engine.eos_id or 0])
    if adapter._engine.eos_id is None:
        adapter._engine.eos_id = 0  # force the eos-strip branch
        seq.generated[-1] = 0
    seq.finish_reason = "eos"
    out = adapter.result_for(seq)
    assert out["n_tokens"] == len(out["output_ids"]) == 2
    adapter.release()


def test_generate_progress_is_engine_wide(tiny_lm):
    """The stall detector keys off the ENGINE's progress ticks, so another
    caller's tokens count as progress and concurrent use cannot raise the
    spurious 'stalled' error."""
    cfg, params = tiny_lm
    eng = PagedDecodeEngine(cfg, params, block_len=8, max_slots=2)
    t0 = eng._progress_ticks
    eng.generate([[3, 4, 5]], 3)
    assert eng._progress_ticks > t0
    eng.release()


def test_serving_submit_keeps_prompt_whole_under_large_max_new():
    """A large max_new_tokens clamps the BUDGET, never truncates the
    prompt: serving and offline submit agree on (prompt, horizon-clamped
    max_new) semantics."""
    from synapseml_tpu.hf import HuggingFaceCausalLM

    lm = HuggingFaceCausalLM(model_name="llama-tiny", engine="paged")
    adapter = lm.serving_engine()
    prompt = "many words " * 40
    want_ids = adapter.submit({"prompt": prompt, "max_new_tokens": 1},
                              "ref").prompt_ids
    assert len(want_ids) > 1
    seq = adapter.submit({"prompt": prompt, "max_new_tokens": 10_000}, "r2")
    assert seq.prompt_ids == want_ids
    assert len(seq.prompt_ids) + seq.max_new_tokens <= adapter._max_len
    adapter.release()


# ---------------------------------------------------------------------------
# continuous refill (no run-to-completion barrier) + compile bounds
# ---------------------------------------------------------------------------

def test_slots_refill_before_long_sequence_finishes(tiny_lm):
    """With 2 slots, a long generation and two short ones: the second short
    request must be admitted and FINISH while the long one is still
    decoding — the barrier the dense path imposes is gone."""
    cfg, params = tiny_lm
    rng = np.random.default_rng(2)
    mk = lambda: rng.integers(2, cfg.vocab_size, (6,)).tolist()  # noqa: E731
    eng = PagedDecodeEngine(cfg, params, block_len=8, max_slots=2)
    long_seq = eng.submit(mk(), 40)
    short_a = eng.submit(mk(), 3)
    short_b = eng.submit(mk(), 3)  # waits: only 2 slots
    while not short_b.done:
        eng.admit()
        eng.step()
        assert not long_seq.done, \
            "long sequence finished first — refill never happened"
    assert short_a.done and short_b.done and not long_seq.done
    while not long_seq.done:
        eng.admit()
        eng.step()
    assert len(long_seq.generated) == 40
    eng.release()


def test_compile_counts_bounded_by_ladders(tiny_lm):
    """A stream of many distinct prompt lengths and active-slot counts
    compiles <= seq-ladder-many prefill and <= slot-ladder-many decode
    executables (the CompiledCache miss counters are the proof)."""
    cfg, params = tiny_lm
    cache = cb.get_compiled_cache()
    p0 = cache.miss_count("llama_paged_prefill")
    d0 = cache.miss_count("llama_paged_decode")
    eng = PagedDecodeEngine(
        cfg, params, block_len=16, max_slots=8,
        bucketer=ShapeBucketer(ladder=[2, 4, 8], seq_ladder=[16, 32, 64]))
    rng = np.random.default_rng(21)
    prompts = [rng.integers(2, cfg.vocab_size, (int(n),)).tolist()
               for n in rng.integers(3, 60, 24)]  # every rung hit
    budgets = [int(n) for n in rng.integers(1, 10, 24)]
    eng.generate(prompts, budgets)
    n_prefill = cache.miss_count("llama_paged_prefill") - p0
    n_decode = cache.miss_count("llama_paged_decode") - d0
    assert 0 < n_prefill <= len(eng.bucketer.seq_ladder)
    assert 0 < n_decode <= len(eng.slot_rungs)
    eng.release()


def test_warmup_precompiles_all_rungs(tiny_lm):
    """After warmup(), a full mixed stream causes ZERO new prefill/decode
    compiles — the zero-compile-stall guarantee /admin/load relies on."""
    cfg, params = tiny_lm
    cache = cb.get_compiled_cache()
    eng = PagedDecodeEngine(
        cfg, params, block_len=16, max_slots=4,
        bucketer=ShapeBucketer(ladder=[2, 4], seq_ladder=[16, 32, 64]))
    n = eng.warmup()
    # prompt rungs 16/32/64 + the max_len cap bucket (128) + two slot rungs
    assert n == 4 + 2
    p0 = cache.miss_count("llama_paged_prefill")
    d0 = cache.miss_count("llama_paged_decode")
    rng = np.random.default_rng(8)
    prompts = [rng.integers(2, cfg.vocab_size, (int(n),)).tolist()
               for n in rng.integers(3, 60, 10)]
    eng.generate(prompts, 6)
    assert cache.miss_count("llama_paged_prefill") == p0
    assert cache.miss_count("llama_paged_decode") == d0
    eng.release()


def test_warmup_does_not_corrupt_live_sequences(tiny_lm):
    """Warmup mid-flight (trash-page writes only) must not change any live
    sequence's continuation."""
    cfg, params = tiny_lm
    rng = np.random.default_rng(17)
    prompts = [rng.integers(2, cfg.vocab_size, (10,)).tolist()
               for _ in range(2)]
    eng = PagedDecodeEngine(cfg, params, block_len=8, max_slots=2)
    want = eng.generate(prompts, 10)
    seqs = [eng.submit(p, 10) for p in prompts]
    eng.admit()
    for _ in range(4):
        eng.step()
    eng.warmup()  # all writes land on the trash page
    while any(not s.done for s in seqs):
        eng.step()
    assert [list(s.generated) for s in seqs] == want
    eng.release()


# ---------------------------------------------------------------------------
# offline transform() through the paged engine
# ---------------------------------------------------------------------------

def test_causal_lm_paged_engine_matches_dense_transform():
    from synapseml_tpu.core import DataFrame
    from synapseml_tpu.hf import HuggingFaceCausalLM

    df = DataFrame.from_dict(
        {"prompt": ["hello world", "the quick brown fox jumps over the "
                    "lazy dog again and again", "a", "short one"]},
        num_partitions=2)
    kw = dict(model_name="llama-tiny", max_new_tokens=7, prompt_bucket=8,
              batch_size=2)
    dense = HuggingFaceCausalLM(**kw)
    paged = HuggingFaceCausalLM(**kw, engine="paged")
    # one param pytree drives both engines
    paged.set(model_params=dense._model_and_params()[1])
    a = [np.asarray(g).tolist()
         for g in dense.transform(df).collect_column("completions")]
    b = [np.asarray(g).tolist()
         for g in paged.transform(df).collect_column("completions")]
    assert a == b
    # the paged path reuses ONE engine across transforms
    assert len(paged.__dict__["_cache_engines"]) == 1
    b2 = [np.asarray(g).tolist()
          for g in paged.transform(df).collect_column("completions")]
    assert b2 == b


# ---------------------------------------------------------------------------
# token scheduler over HTTP (serve_llm)
# ---------------------------------------------------------------------------

def _llm_request(address, payload, timeout=30):
    import http.client

    host, port = address.split("//")[1].split(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    conn.request("POST", "/", body=json.dumps(payload).encode())
    return conn, conn.getresponse()


def test_serve_llm_final_stream_and_errors():
    from synapseml_tpu.hf import HuggingFaceCausalLM
    from synapseml_tpu.io.serving import serve_llm

    lm = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=6,
                             batch_size=4, engine="paged")
    srv = serve_llm(lm, warmup=False)
    try:
        # final-text mode
        conn, r = _llm_request(srv.address, {"prompt": "hello world"})
        body = json.loads(r.read())
        assert r.status == 200
        assert body["done"] and body["n_tokens"] == 6
        assert len(body["output_ids"]) == 6
        conn.close()
        # offline transform through the SAME engine agrees token-for-token
        from synapseml_tpu.core import DataFrame

        offline = lm.transform(
            DataFrame.from_dict({"prompt": ["hello world"]}))
        assert np.asarray(
            offline.collect_column("completions")[0]).tolist() \
            == body["output_ids"]

        # streaming mode: one NDJSON chunk per token + terminal record
        conn, r = _llm_request(srv.address,
                               {"prompt": "the quick brown fox",
                                "max_new_tokens": 4, "stream": True})
        assert r.status == 200
        assert r.getheader("Transfer-Encoding") == "chunked"
        chunks = [json.loads(line) for line in iter(r.readline, b"")]
        conn.close()
        assert len(chunks) == 5  # 4 tokens + terminal
        assert [c["token"] for c in chunks[:4]] == chunks[-1]["output_ids"]
        assert chunks[-1]["done"] and chunks[-1]["finish_reason"] == "length"

        # malformed payloads get terminal 4xx replies, fast
        for bad in ([1, 2], {"prompt": ""}, {"no_prompt": 1}):
            t0 = time.perf_counter()
            conn, r = _llm_request(srv.address, bad)
            assert r.status == 400, bad
            assert "error" in json.loads(r.read())
            assert time.perf_counter() - t0 < 5.0
            conn.close()
    finally:
        srv.stop()


def test_serve_llm_interleaves_short_under_long():
    """A short request submitted AFTER a long one completes first — the
    token scheduler refills decode slots mid-generation (no whole-batch
    barrier), and per-request streams stay isolated."""
    import threading

    from synapseml_tpu.hf import HuggingFaceCausalLM
    from synapseml_tpu.io.serving import serve_llm

    lm = HuggingFaceCausalLM(model_name="llama-tiny", batch_size=2,
                             engine="paged", decode_slots=2)
    srv = serve_llm(lm, warmup=False)
    results = {}

    def fire(name, payload):
        conn, r = _llm_request(srv.address, payload)
        results[name] = (time.perf_counter(), json.loads(r.read()))
        conn.close()

    try:
        threads = [
            threading.Thread(target=fire, args=(
                "long", {"prompt": "a long story", "max_new_tokens": 100})),
            threading.Thread(target=fire, args=(
                "short", {"prompt": "quick", "max_new_tokens": 3})),
        ]
        threads[0].start()
        time.sleep(0.15)  # the long one is decoding by now
        threads[1].start()
        for t in threads:
            t.join(timeout=60)
        assert results["short"][1]["n_tokens"] == 3
        assert results["long"][1]["n_tokens"] == 100
        assert results["short"][0] < results["long"][0], \
            "short request waited out the long one (barrier came back)"
    finally:
        srv.stop()


def test_serve_llm_hot_swap_rebuilds_engine():
    """PipelineHolder swap mid-serve: the loop rebuilds + warms the new
    stage's engine and subsequent requests decode with the new params."""
    from synapseml_tpu.hf import HuggingFaceCausalLM
    from synapseml_tpu.io.serving import PipelineHolder, serve_llm

    lm_a = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=4,
                               engine="paged")
    lm_b = HuggingFaceCausalLM(model_name="llama-tiny", max_new_tokens=9,
                               engine="paged")
    holder = PipelineHolder(lm_a, "v1")
    srv = serve_llm(holder, warmup=False)
    try:
        conn, r = _llm_request(srv.address, {"prompt": "before swap"})
        assert json.loads(r.read())["n_tokens"] == 4
        conn.close()
        holder.swap(lm_b, "v2")
        deadline = time.perf_counter() + 30
        n = None
        while time.perf_counter() < deadline:
            conn, r = _llm_request(srv.address, {"prompt": "after swap"})
            # a request racing the engine rebuild can get a terminal abort
            # reply (503) — terminal, never a silent stall — so retry it
            n = json.loads(r.read()).get("n_tokens")
            conn.close()
            if n == 9:
                break
            time.sleep(0.2)
        assert n == 9, "swap never took effect"
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# dropped-after-dequeue exchanges get a terminal reply (satellite)
# ---------------------------------------------------------------------------

def test_dropped_exchange_gets_terminal_504():
    """An exchange whose deadline passed in the queue is dropped by the
    batch readers — and must receive a terminal 504 reply the instant it is
    dropped, so a handler racing the deadline can never park to its full
    timeout on a silently-dropped request."""
    from synapseml_tpu.io.serving import ServingServer, _Exchange

    srv = ServingServer(reply_timeout_s=5.0)
    try:
        fresh = _Exchange("fresh", "POST", "/", {}, b"{}")
        stale = _Exchange("stale", "POST", "/", {}, b"{}")
        stale.enqueued_at -= 10.0  # expired while queued
        for ex in (fresh, stale):
            srv._pending[ex.request_id] = ex
            srv._queue.put(ex)
        batch = srv.read_batch_adaptive(poll_timeout_s=0.05)
        served = list(batch.collect_column("id"))
        assert served == ["fresh"]
        assert stale.reply_event.is_set(), \
            "dropped exchange got no terminal reply"
        assert stale.reply_status == 504
        assert b"expired" in stale.reply_body
        assert not fresh.reply_event.is_set()
        # the terminal reply does not clobber a later real reply race: the
        # first respond() wins
        stale.respond({"late": True}, status=200)
        assert stale.reply_status == 504
    finally:
        srv.stop()
