"""HPO fused-array A/B: serial thread-pool sweep vs ONE fused training array.

The same N-config LightGBM sweep runs twice in the SAME round (the
serving-microbatch / data-pipeline discipline — both arms share the process,
the dataset, and the round's thermal/load conditions):

  (a) serial — ``TuneHyperparameters(fuse_trials=False)``: the reference
      port's thread pool, one fit per config, each distinct config compiling
      its own level-step ladder while the device serializes the dispatches;
  (b) fused  — ``TuneHyperparameters(fuse_trials=True)``: all N configs
      train inside one jitted boosting iteration (per-trial scalars as
      traced inputs), acquired ONCE through the shared ``CompiledCache``.

Compile cost is part of the measurement ON PURPOSE: paying one trace
instead of N is the fused array's claim (HFTA arXiv:2102.02344 + the TVM
amortization lesson), so each arm starts from cold compile caches.

Emits sweep wall-clock, trials/sec, executables compiled, and
best-metric/per-config parity per arm. Acceptance (ISSUE 7): fused >= 2x
serial trials/sec at N >= 8 fusable configs, fused executable count <= the
trial-count ladder size, per-config metrics equal within f32 tolerance.
Prints one JSON line.
"""
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).parent))
sys.path.insert(0, str(Path(__file__).parent.parent))

N_ROWS = 4000
N_FEATURES = 12
NUM_ITERATIONS = 20
SEED = 11


def _dataset():
    from synapseml_tpu.core import DataFrame

    rs = np.random.default_rng(SEED)
    X = rs.normal(size=(N_ROWS, N_FEATURES)).astype(np.float32)
    logit = X[:, 0] + 0.6 * X[:, 1] - 0.8 * X[:, 2] * X[:, 3] + 0.3 * X[:, 4]
    y = (logit + 0.5 * rs.normal(size=N_ROWS) > 0).astype(np.int64)
    return DataFrame.from_dict({"features": list(X), "label": y})


def _space():
    """8 fusable configs: scalar knobs only, one fused signature."""
    from synapseml_tpu.automl import DiscreteHyperParam, HyperparamBuilder

    return (HyperparamBuilder()
            .add_hyperparam("learning_rate",
                            DiscreteHyperParam([0.03, 0.06, 0.1, 0.2]))
            .add_hyperparam("lambda_l2", DiscreteHyperParam([0.0, 0.5]))
            .build())


def _run_arm(df, fuse: bool) -> dict:
    from synapseml_tpu.automl import TuneHyperparameters
    from synapseml_tpu.core.batching import (get_compiled_cache,
                                             reset_compiled_cache)
    from synapseml_tpu.gbdt import LightGBMClassifier
    from synapseml_tpu.gbdt import trees as T

    # cold compile caches: each arm pays its own traces (that asymmetry IS
    # the measurement — see the module docstring)
    reset_compiled_cache()
    T._level_steps.cache_clear()
    fused_misses0 = get_compiled_cache().miss_count("gbdt_fused_iter")

    tuner = TuneHyperparameters(
        models=[LightGBMClassifier(num_iterations=NUM_ITERATIONS,
                                   num_leaves=15)],
        hyperparam_space=_space(), search_mode="grid",
        evaluation_metric="accuracy", seed=SEED, fuse_trials=fuse,
        parallelism=4)
    t0 = time.perf_counter()
    best = tuner.fit(df)
    wall = time.perf_counter() - t0

    results = best.get("all_results")
    n_trials = len(results)
    ladders = T._level_steps.cache_info().misses
    # serial executables: one level ladder (max_depth + final level jits)
    # per distinct GrowthConfig; fused: CompiledCache misses on the one
    # fused-iteration fn_id
    fused_execs = int(get_compiled_cache().miss_count("gbdt_fused_iter")
                      - fused_misses0)
    return {
        "mode": "fused" if fuse else "serial",
        "wall_s": round(wall, 3),
        "n_trials": n_trials,
        "trials_per_sec": round(n_trials / wall, 4),
        "best_params": best.get("best_params"),
        "best_metric": best.get("best_metric"),
        "metrics_by_config": {
            json.dumps(cfg, sort_keys=True): v for _n, cfg, v in results},
        "serial_config_ladders_compiled": ladders,
        "fused_executables_compiled": fused_execs,
    }


def run(jax, platform, n_chips):
    from synapseml_tpu.core.batching import TRIAL_LADDER

    df = _dataset()
    jax.block_until_ready(jax.numpy.zeros(8))  # backend up before timing
    serial = _run_arm(df, fuse=False)
    fused = _run_arm(df, fuse=True)

    speedup = (fused["trials_per_sec"] / serial["trials_per_sec"]
               if serial["trials_per_sec"] else None)
    deltas = [abs(fused["metrics_by_config"][k] -
                  serial["metrics_by_config"][k])
              for k in fused["metrics_by_config"]]
    for arm in (serial, fused):
        del arm["metrics_by_config"]  # folded into the parity summary
    return {
        "metric": "hpo fused-array sweep speedup (trials/sec vs serial "
                  "thread-pool, same round)",
        "value": round(speedup, 3) if speedup else None,
        "unit": "x", "lower_is_better": False,
        "platform": platform, "n_chips": n_chips,
        "n_configs": fused["n_trials"],
        "fused": fused,
        "serial_baseline": serial,
        "parity": {
            "best_params_equal": fused["best_params"] ==
            serial["best_params"],
            "best_metric_delta": abs(fused["best_metric"] -
                                     serial["best_metric"]),
            "max_per_config_metric_delta": max(deltas) if deltas else None,
        },
        "compile_bound": {
            "fused_executables": fused["fused_executables_compiled"],
            "trial_ladder_size": len(TRIAL_LADDER),
            "serial_config_ladders": serial["serial_config_ladders_compiled"],
        },
    }


def main():
    from _common import init_jax

    jax, platform, n_chips = init_jax()
    print(json.dumps(run(jax, platform, n_chips)))


if __name__ == "__main__":
    main()
