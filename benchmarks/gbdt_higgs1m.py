"""Higgs-1M-shaped GBDT training throughput on the TPU (BASELINE.md config:
LightGBM Higgs-1M, 100 iterations, binary)."""
import json, sys, time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).parent))
sys.path.insert(0, str(Path(__file__).parent.parent))

def run(jax, platform, n_chips):
    from synapseml_tpu.gbdt.booster import train_booster
    rng = np.random.default_rng(0)
    # full Higgs-1M shape on the chip; smoke scale elsewhere. AUC is computed
    # on a HELD-OUT tail (never passed to train_booster), not training rows.
    N, F = (1_000_000, 28) if platform == "tpu" else (50_000, 28)
    n_test = min(100_000, N // 5)
    X = rng.normal(size=(N + n_test, F)).astype(np.float32)
    w = rng.normal(size=F); w[F//2:] = 0
    logits = X @ w * 0.5 + rng.normal(size=N + n_test) * 0.5
    y = (logits > 0).astype(np.float32)
    degraded = None
    hist_impl = "segment"
    if platform == "tpu":
        # The 2026-07-31 window died inside this child with "UNAVAILABLE: TPU
        # device error" at full scale, then the relay hung — which leaves
        # "our kernel faults anywhere" vs "scale-dependent" vs "relay infra"
        # undistinguished. A 20k-row canary first makes the failure mode
        # informative: canary fails => universal/infra; canary passes but
        # 1M fails => scale. If the default segment (scatter-add) backend is
        # what faults, the one-hot MXU backend is a different lowering —
        # switch to it and still capture a chip number. On a scale failure,
        # retry at smaller N so a partial number still lands.
        for impl in ("segment", "onehot"):
            try:
                t0 = time.perf_counter()
                train_booster(X[:20_000], y[:20_000], objective="binary",
                              num_iterations=5, learning_rate=0.1,
                              num_leaves=31, max_bin=255, histogram_impl=impl)
                hist_impl = impl
                print(f"# gbdt canary 20k ok ({impl}) in "
                      f"{time.perf_counter() - t0:.1f}s", flush=True)
                break
            except Exception as e:  # noqa: BLE001
                print(f"# gbdt canary ({impl}) failed: {type(e).__name__}: "
                      f"{str(e)[:200]}", flush=True)
                if impl == "onehot":
                    raise
    scales = [N, 250_000, 100_000] if platform == "tpu" else [N]
    for attempt_n in scales:
        n_iter = 100 if platform == "tpu" else 20
        try:
            t0 = time.perf_counter()
            booster = train_booster(X[:attempt_n], y[:attempt_n],
                                    objective="binary",
                                    num_iterations=n_iter, learning_rate=0.1,
                                    num_leaves=31, max_bin=255,
                                    histogram_impl=hist_impl)
            train_s = time.perf_counter() - t0
            if attempt_n != N:
                degraded = f"device error at {N} rows; measured at {attempt_n}"
            N = attempt_n
            break
        except Exception as e:  # noqa: BLE001 — device errors surface as JaxRuntimeError
            print(f"# gbdt {attempt_n}-row train failed: {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)
            if attempt_n == scales[-1]:
                raise
    n_pred = n_test
    t0 = time.perf_counter()
    p = booster.predict(X[-n_test:])  # last n_test rows: held out at every fallback scale
    pred_s = time.perf_counter() - t0
    auc_y, auc_p = y[-n_test:], np.asarray(p).ravel()
    from scipy.stats import rankdata
    ranks = rankdata(auc_p)  # average tied ranks (exact Mann-Whitney)
    n1 = auc_y.sum(); n0 = len(auc_y) - n1
    auc = (ranks[auc_y == 1].sum() - n1*(n1+1)/2) / (n1*n0)
    # a degraded-scale run gets its own metric key: row-iters/sec at 100k
    # rows is not comparable to 1M rows, and keep-best seeding must never
    # pin a small-scale number as the Higgs-1M baseline
    if platform != "tpu":
        metric = "LightGBM 50k (CPU smoke)"
    elif degraded:
        metric = f"LightGBM GBDT {N // 1000}k train (degraded fallback)"
    else:
        metric = "LightGBM Higgs-1M train"
    result = {"metric": metric,
              "value": round(N * n_iter / train_s), "unit": "row-iters/sec",
              "platform": platform, "train_s": round(train_s, 2),
              "hist_impl": hist_impl,
              "pred_rows": n_pred, "pred_s": round(pred_s, 3),
              "auc": round(float(auc), 4)}
    if degraded:
        result["degraded"] = degraded
    if hist_impl != "segment":
        # fault-forced backend switch: the metric key stays (BASELINE.md's
        # target is Higgs-1M train time, whichever lowering wins), but the
        # provenance must ride along into PERF_BASELINE.json so a
        # cross-backend keep-best comparison is visible, not silent
        result["note"] = "segment backend faulted on-chip; measured with onehot"
    return result


def main():
    from _common import init_jax

    jax, platform, n_chips = init_jax()
    print(json.dumps(run(jax, platform, n_chips)))


if __name__ == "__main__":
    main()
