"""Higgs-1M-shaped GBDT training throughput on the TPU (BASELINE.md config:
LightGBM Higgs-1M, 100 iterations, binary)."""
import json, sys, time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).parent))
sys.path.insert(0, str(Path(__file__).parent.parent))

def run(jax, platform, n_chips):
    from synapseml_tpu.gbdt.booster import train_booster
    rng = np.random.default_rng(0)
    # full Higgs-1M shape on the chip; smoke scale elsewhere. AUC is computed
    # on a HELD-OUT tail (never passed to train_booster), not training rows.
    N, F = (1_000_000, 28) if platform == "tpu" else (50_000, 28)
    n_test = min(100_000, N // 5)
    X = rng.normal(size=(N + n_test, F)).astype(np.float32)
    w = rng.normal(size=F); w[F//2:] = 0
    logits = X @ w * 0.5 + rng.normal(size=N + n_test) * 0.5
    y = (logits > 0).astype(np.float32)
    t0 = time.perf_counter()
    n_iter = 100 if platform == "tpu" else 20
    booster = train_booster(X[:N], y[:N], objective="binary",
                            num_iterations=n_iter, learning_rate=0.1,
                            num_leaves=31, max_bin=255)
    train_s = time.perf_counter() - t0
    n_pred = n_test
    t0 = time.perf_counter()
    p = booster.predict(X[N:])
    pred_s = time.perf_counter() - t0
    auc_y, auc_p = y[N:], np.asarray(p).ravel()
    from scipy.stats import rankdata
    ranks = rankdata(auc_p)  # average tied ranks (exact Mann-Whitney)
    n1 = auc_y.sum(); n0 = len(auc_y) - n1
    auc = (ranks[auc_y == 1].sum() - n1*(n1+1)/2) / (n1*n0)
    return {"metric": "LightGBM Higgs-1M train" if platform == "tpu"
            else "LightGBM 50k (CPU smoke)",
            "value": round(N * n_iter / train_s), "unit": "row-iters/sec",
            "platform": platform, "train_s": round(train_s, 2),
            "pred_rows": n_pred, "pred_s": round(pred_s, 3),
            "auc": round(float(auc), 4)}


def main():
    from _common import init_jax

    jax, platform, n_chips = init_jax()
    print(json.dumps(run(jax, platform, n_chips)))


if __name__ == "__main__":
    main()
