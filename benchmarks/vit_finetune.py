"""ViT-B/16 fine-tune throughput (BASELINE.md DeepVisionClassifier config)."""
import json, sys, time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).parent))
sys.path.insert(0, str(Path(__file__).parent.parent))


def run(jax, platform, n_chips):
    from synapseml_tpu.models.flax_nets.vit import ViTClassifier, vit_b16, vit_tiny
    from synapseml_tpu.models.trainer import Trainer, TrainerConfig
    from synapseml_tpu.parallel.mesh import MeshConfig, create_mesh

    on_tpu = platform == "tpu"
    cfg = vit_b16() if on_tpu else vit_tiny()
    patch = 16 if on_tpu else 8
    B, S = (64, 224) if on_tpu else (8, 32)
    model = ViTClassifier(cfg, num_classes=1000 if on_tpu else 10, patch=patch)
    tr = Trainer(model, create_mesh(MeshConfig(data=-1)),
                 TrainerConfig(learning_rate=1e-4, total_steps=1000))
    rng = np.random.default_rng(0)
    batch = {"x": rng.normal(size=(B, S, S, 3)).astype(np.float32),
             "labels": rng.integers(0, 10, (B,)).astype(np.int32)}
    state = tr.init_state(batch)
    k = 16 if on_tpu else 4
    stacked = jax.tree.map(lambda x: np.broadcast_to(x, (k,) + x.shape).copy(), batch)
    st, m = tr.train_steps_scan(state, stacked)
    float(np.asarray(m["loss"])[-1])  # compile+run
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        st, m = tr.train_steps_scan(st, stacked)
        np.asarray(m["loss"])
        best = min(best, time.perf_counter() - t0)
    return {"metric": "ViT-B/16 fine-tune" if on_tpu else "vit-tiny (CPU smoke)",
            "value": round(B * k / best / n_chips, 2),
            "unit": "samples/sec/chip", "platform": platform,
            "n_chips": n_chips, "step_ms": round(best / k * 1e3, 2)}


def main():
    from _common import init_jax

    jax, platform, n_chips = init_jax()
    print(json.dumps(run(jax, platform, n_chips)))


if __name__ == "__main__":
    main()
