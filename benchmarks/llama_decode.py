"""Llama decode throughput (BASELINE.md: Llama-2-7B batch inference,
tokens/sec). On the single v5e chip a 7B model doesn't fit (weights alone
~13.5 GB bf16 vs 16 GB HBM with no KV/activation headroom at max_len), so
the TPU mode runs the largest single-chip Llama-shaped config (all the 7B
structure at ~1.1B params) and reports tokens/sec/chip; the 7B multi-chip
path itself is exercised (reduced width, tensor x fsdp mesh) in
tests/test_hf_cyber.py::test_llama2_7b_code_path_reduced_width."""
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).parent))
sys.path.insert(0, str(Path(__file__).parent.parent))


def run(jax, platform, n_chips):
    import jax.numpy as jnp

    from synapseml_tpu.models.flax_nets.llama import (LlamaLM, generate,
                                                      llama2_7b, llama_tiny)

    on_tpu = platform == "tpu"
    if on_tpu:
        # 7B structure, single-chip width: 32 layers, GQA-free MHA, RoPE,
        # SwiGLU; ~1.1B params bf16
        cfg = llama2_7b(hidden=1536, mlp_dim=4128, n_layers=32, n_heads=24,
                        n_kv_heads=24, max_len=2048)
        B, P, new = 8, 128, 128
    else:
        cfg = llama_tiny()
        B, P, new = 4, 16, 16

    model = LlamaLM(cfg, decode=True)
    params = LlamaLM(cfg).init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 8), jnp.int32))["params"]
    n_params = sum(int(np.prod(np.shape(x))) for x in jax.tree.leaves(params))
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, P)), jnp.int32)

    fn = jax.jit(lambda i: generate(model, params, i, new))
    np.asarray(fn(ids))  # compile + warm
    trials = []
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(fn(ids))
        trials.append(time.perf_counter() - t0)
    dt = min(trials)
    toks = B * new
    return {
        "metric": "Llama decode throughput" if on_tpu
                  else "Llama decode (CPU smoke)",
        "value": round(toks / dt, 1), "unit": "tokens/sec/chip",
        "platform": platform, "n_params": n_params, "batch": B,
        "prompt_len": P, "new_tokens": new,
        "decode_ms_per_token": round(dt / new * 1e3, 2)}


def main():
    from _common import init_jax

    jax, platform, n_chips = init_jax()
    print(json.dumps(run(jax, platform, n_chips)))


if __name__ == "__main__":
    main()
