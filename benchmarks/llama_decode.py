"""Llama decode throughput (BASELINE.md: Llama-2-7B batch inference,
tokens/sec). On the single v5e chip a 7B model doesn't fit (weights alone
~13.5 GB bf16 vs 16 GB HBM with no KV/activation headroom at max_len), so
the TPU mode runs the largest single-chip Llama-shaped config (all the 7B
structure at ~1.1B params) and reports tokens/sec/chip; the 7B multi-chip
path itself is exercised (reduced width, tensor x fsdp mesh) in
tests/test_hf_cyber.py::test_llama2_7b_code_path_reduced_width.

A/B mode (same round, serving-microbatch discipline): a MIXED-LENGTH
request stream — prompt lengths spanning three seq-ladder rungs, generation
budgets 4..48 tokens — decoded two ways:

  (a) rtc   — run-to-completion ``generate``: requests batched in arrival
              order, the whole batch decodes until its LONGEST member
              finishes (the lax.while_loop exits only when every row is
              done), so short requests pay the group's worst case;
  (b) paged — the token-granular paged-KV engine: decode slots refill the
              moment a sequence finishes, sequences share one physical
              page pool.

Both arms run warmed (compile excluded) on identical token workloads and
count only REQUESTED tokens as useful. Emits tokens/sec, per-token p50/p99
per request, KV-block occupancy, and the paged compile counts (decode
executables must stay <= the slot-ladder size)."""
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).parent))
sys.path.insert(0, str(Path(__file__).parent.parent))


def _legacy_throughput(jax, platform):
    """The original single-config dense decode number (PERF_BASELINE
    continuity: metric name and method unchanged)."""
    import jax.numpy as jnp

    from synapseml_tpu.models.flax_nets.llama import (LlamaLM, generate,
                                                      llama2_7b, llama_tiny)

    on_tpu = platform == "tpu"
    if on_tpu:
        # 7B structure, single-chip width: 32 layers, GQA-free MHA, RoPE,
        # SwiGLU; ~1.1B params bf16
        cfg = llama2_7b(hidden=1536, mlp_dim=4128, n_layers=32, n_heads=24,
                        n_kv_heads=24, max_len=2048)
        B, P, new = 8, 128, 128
    else:
        cfg = llama_tiny()
        B, P, new = 4, 16, 16

    model = LlamaLM(cfg, decode=True)
    params = LlamaLM(cfg).init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 8), jnp.int32))["params"]
    n_params = sum(int(np.prod(np.shape(x))) for x in jax.tree.leaves(params))
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, P)), jnp.int32)

    fn = jax.jit(lambda i: generate(model, params, i, new))
    np.asarray(fn(ids))  # compile + warm
    trials = []
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(fn(ids))
        trials.append(time.perf_counter() - t0)
    dt = min(trials)
    toks = B * new
    return {
        "metric": "Llama decode throughput" if on_tpu
                  else "Llama decode (CPU smoke)",
        "value": round(toks / dt, 1), "unit": "tokens/sec/chip",
        "platform": platform, "n_params": n_params, "batch": B,
        "prompt_len": P, "new_tokens": new,
        "decode_ms_per_token": round(dt / new * 1e3, 2)}


def _mixed_stream(rng, n_requests: int, vocab: int):
    """(prompt_ids, n_new) per request: prompt lengths span >= 3 seq-ladder
    rungs (16/32/64); generation budgets are HEAVY-TAILED (mostly short
    chat-style turns, ~20% long completions) — the real serving mix where
    the run-to-completion barrier hurts, since most batches contain one
    long member every short request must wait out."""
    reqs = []
    for _ in range(n_requests):
        plen = int(rng.choice([6, 12, 20, 30, 44, 56]))
        if rng.random() < 0.2:
            n_new = int(rng.choice([48, 64]))
        else:
            n_new = int(rng.choice([4, 6, 8, 12, 16, 24]))
        reqs.append((rng.integers(2, vocab, (plen,)).tolist(), n_new))
    return reqs


def _percentiles(values):
    values = sorted(values)
    return (round(values[len(values) // 2], 3),
            round(values[int(len(values) * 0.99)], 3))


def _run_rtc(jax, cfg, params, requests, slots: int, trials: int = 3):
    """Run-to-completion arm: batches of ``slots`` in arrival order, prompts
    padded to the group's seq-ladder rung, ONE ``generate`` call decoding
    max(group budgets) steps — the whole-batch barrier the dense serving
    path pays today. Per-request wall = its group's wall (a request is done
    only when its batch returns)."""
    import jax.numpy as jnp

    from synapseml_tpu.core.batching import default_bucketer
    from synapseml_tpu.models.flax_nets.llama import LlamaLM, generate

    model = LlamaLM(cfg, decode=True)
    bucketer = default_bucketer()
    groups = [requests[i:i + slots] for i in range(0, len(requests), slots)]

    compiled = {}

    def fn_for(B, P, new):
        key = (B, P, new)
        if key not in compiled:
            compiled[key] = jax.jit(
                lambda ids, mask: generate(model, params, ids, new,
                                           prompt_mask=mask))
        return compiled[key]

    def run_group(group, t0_stream=None, timed_lat=None):
        B = len(group)
        P = bucketer.seq_bucket_for(max(len(p) for p, _ in group),
                                    cap=cfg.max_len)
        new = max(n for _, n in group)
        ids = np.zeros((B, P), np.int32)
        mask = np.zeros((B, P), np.int32)
        for i, (p, _) in enumerate(group):
            ids[i, :len(p)] = p
            mask[i, :len(p)] = 1
        np.asarray(fn_for(B, P, new)(jnp.asarray(ids), jnp.asarray(mask)))
        if timed_lat is not None:
            # every request in the group completes when the GROUP returns;
            # latency counts from stream start (queue wait included), same
            # clock the paged arm is measured on
            done = time.perf_counter()
            for _, n in group:
                timed_lat.append((done - t0_stream) * 1e3 / n)

    for g in groups:  # warm every (B, P, new) combo
        run_group(g)
    best = None
    for _ in range(trials):  # min-of-N: host contention hits both arms alike
        lat = []
        t0 = time.perf_counter()
        for g in groups:
            run_group(g, t0_stream=t0, timed_lat=lat)
        wall = time.perf_counter() - t0
        if best is None or wall < best[0]:
            best = (wall, lat)
    wall, lat = best
    useful = sum(n for _, n in requests)
    p50, p99 = _percentiles(lat)
    return {"tokens_per_sec": round(useful / wall, 1),
            "token_p50_ms": p50, "token_p99_ms": p99,
            "useful_tokens": useful, "wall_s": round(wall, 3),
            "executables": len(compiled)}


def _run_paged(cfg, params, requests, slots: int, trials: int = 3):
    """Continuous arm: every request runs exactly its budget; slots refill
    the moment one finishes. Per-request wall = submit -> its own finish.
    The warm pass runs the identical workload so every prefill/decode rung
    compiles (through the shared CompiledCache) before timing."""
    from synapseml_tpu.core.batching import get_compiled_cache
    from synapseml_tpu.models.paged_engine import PagedDecodeEngine

    engine = PagedDecodeEngine(cfg, params, block_len=16, max_slots=slots,
                               prefill_batch=2)
    cache = get_compiled_cache()
    d0 = cache.miss_count("llama_paged_decode")
    p0 = cache.miss_count("llama_paged_prefill")

    def one_pass():
        seqs = [engine.submit(p, n) for p, n in requests]
        starts = {s.uid: time.perf_counter() for s in seqs}
        lat, occ = [], []
        t0 = time.perf_counter()
        while any(not s.done for s in seqs):
            done_events = engine.admit() + engine.step()
            now = time.perf_counter()
            occ.append(engine.stats()["occupancy"])
            for ev in done_events:
                if ev["done"]:
                    s = ev["seq"]
                    lat.append((now - starts[s.uid]) * 1e3
                               / max(len(s.generated), 1))
        return time.perf_counter() - t0, lat, occ

    one_pass()              # warm: all compiles land here
    wall, lat, occ = min((one_pass() for _ in range(trials)),
                         key=lambda r: r[0])
    useful = sum(n for _, n in requests)
    p50, p99 = _percentiles(lat)
    out = {"tokens_per_sec": round(useful / wall, 1),
           "token_p50_ms": p50, "token_p99_ms": p99,
           "useful_tokens": useful, "wall_s": round(wall, 3),
           "kv_occupancy_mean": round(float(np.mean(occ)), 3),
           "kv_occupancy_max": round(float(np.max(occ)), 3),
           "slot_rungs": list(engine.slot_rungs),
           "decode_executables":
               int(cache.miss_count("llama_paged_decode") - d0),
           "prefill_executables":
               int(cache.miss_count("llama_paged_prefill") - p0)}
    engine.release()
    return out


def _run_kill_mid_decode(cfg, params, requests, slots: int,
                         engine_kw: dict | None = None):
    """Survivable-serving arm: the same stream, but the engine is "killed"
    at t=50% of the token budget (its KV pool abandoned, nothing exported
    — a SIGKILL, not a drain) and every unfinished sequence resubmits to a
    survivor engine through the crash path the RoutingFront journal uses:
    re-prefill over prompt + already-emitted ids, emitting only NEW
    tokens. Reports recovery latency (kill -> first resumed token) and
    duplicate / lost token counts against an uninterrupted reference —
    the bar for both is zero. ``engine_kw`` overlays engine knobs (the
    both-features-on rerun: prefix_cache + draft_tokens)."""
    from synapseml_tpu.models.paged_engine import PagedDecodeEngine

    kw = dict(block_len=16, max_slots=slots, prefill_batch=2,
              **(engine_kw or {}))
    ref_eng = PagedDecodeEngine(cfg, params, **kw)
    refs = ref_eng.generate([p for p, _ in requests],
                            [n for _, n in requests])
    ref_eng.release()

    victim = PagedDecodeEngine(cfg, params, **kw)
    seqs = [victim.submit(p, n, request_id=str(i), stream=True)
            for i, (p, n) in enumerate(requests)]
    by_uid = {s.uid: i for i, s in enumerate(seqs)}
    total = sum(n for _, n in requests)
    # every emission as (request, global token index, token id): the same
    # monotonic chunk numbering the serving plane dedups on
    emissions = [[] for _ in requests]
    t0 = time.perf_counter()

    def drain(events):
        for ev in events:
            if ev.get("token") is not None:
                # ev["index"] is stamped at emission time, so it stays
                # exact when a speculative step emits several tokens for
                # one sequence in one events batch
                i = by_uid[ev["seq"].uid]
                emissions[i].append((int(ev["index"]), int(ev["token"])))

    emitted = 0
    while emitted < total // 2:
        # drain each phase separately (same discipline as serve_llm's
        # dispatch loop)
        drain(victim.admit())
        drain(victim.step())
        emitted = sum(len(e) for e in emissions)
    t_kill = time.perf_counter()
    unfinished = [s for s in seqs if not s.done]
    victim.release()  # SIGKILL analog: pages gone, no export ran

    survivor = PagedDecodeEngine(cfg, params, **kw)
    moved = []
    for s in unfinished:
        # the front's __resume__ wire form: manifest only, no KV payload,
        # foreign digest -> deterministic re-prefill over prompt+emitted
        moved.append(survivor.import_sequence({"manifest": {
            "uid": s.uid, "prompt_ids": list(s.prompt_ids),
            "generated": list(s.generated),
            "max_new_tokens": s.max_new_tokens, "request_id": s.request_id,
            "stream": True, "tokens_in_pages": 0,
            "model_digest": "crashed-worker"}}))
    first_resumed = None
    while any(not s.done for s in moved):
        for phase in (survivor.admit, survivor.step):
            events = phase()  # drain before the next phase appends tokens
            if first_resumed is None and any(
                    ev.get("token") is not None for ev in events):
                first_resumed = time.perf_counter()
            drain(events)
    wall = time.perf_counter() - t0
    leaked = survivor.allocator.used_count
    pc = getattr(survivor, "prefix_cache", None)
    if pc is not None:
        # cache-pinned pages are RESIDENT by design (the cache holds its
        # own refs), not leaks — only blocks nothing accounts for count
        leaked -= len(pc.block_ids())
    survivor.release()

    dup = lost = mismatched = 0
    for i, ems in enumerate(emissions):
        idxs = [ix for ix, _ in ems]
        dup += len(idxs) - len(set(idxs))
        got = [t for _, t in sorted(dict(ems).items())]
        lost += max(len(refs[i]) - len(set(idxs)), 0)
        if got != refs[i]:
            mismatched += 1
    return {"tokens_per_sec": round(total / wall, 1),
            "recovery_ms": (round((first_resumed - t_kill) * 1e3, 1)
                            if first_resumed else None),
            "resumed_sequences": len(moved),
            "duplicate_tokens": dup, "lost_tokens": lost,
            "mismatched_sequences": mismatched,
            "survivor_leaked_blocks": int(leaked)}


def _tiny_model(jax):
    """The shared A/B model: big enough that a decode step is
    device-dominated (per-call dispatch overhead under 20% of a step),
    small enough for the CPU budget."""
    import jax.numpy as jnp
    from flax.core import meta

    from synapseml_tpu.models.flax_nets.llama import LlamaLM, llama_tiny

    cfg = llama_tiny(hidden=320, n_layers=6, n_heads=8, n_kv_heads=4,
                     mlp_dim=768, vocab_size=1024, max_len=128)
    params = LlamaLM(cfg).init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 8), jnp.int32))["params"]
    params = jax.tree.map(
        lambda x: x.value if isinstance(x, meta.Partitioned) else x, params,
        is_leaf=lambda x: isinstance(x, meta.Partitioned))
    return cfg, params


def _continuous_ab(jax, platform):
    """Both arms in the same round on the same stream (the serving-microbatch
    A/B discipline)."""
    from synapseml_tpu.core.batching import default_bucketer

    cfg, params = _tiny_model(jax)
    rng = np.random.default_rng(7)
    # TPU runs through the (flaky, high-RTT) relay: a smaller stream and a
    # single timed pass keep the A/B inside the config deadline — numbers
    # land opportunistically, the CPU A/B is the gating one
    on_tpu = platform == "tpu"
    requests = _mixed_stream(rng, n_requests=24 if on_tpu else 48,
                             vocab=cfg.vocab_size)
    slots = 8
    trials = 1 if on_tpu else 3
    rtc = _run_rtc(jax, cfg, params, requests, slots, trials=trials)
    paged = _run_paged(cfg, params, requests, slots, trials=trials)
    # the survivable-serving arm stays off the (deadline-bound) TPU relay:
    # recovery latency and dup/lost accounting are platform-independent
    kill = None if on_tpu else _run_kill_mid_decode(
        cfg, params, requests, slots)
    ladder = default_bucketer()
    return {
        "stream": {"n_requests": len(requests), "slots": slots,
                   "prompt_rungs": sorted({ladder.seq_bucket_for(
                       len(p), cap=cfg.max_len) for p, _ in requests}),
                   "total_tokens": sum(n for _, n in requests)},
        "paged": paged,
        "rtc_baseline": rtc,
        "tokens_per_sec_vs_rtc": round(
            paged["tokens_per_sec"] / rtc["tokens_per_sec"], 3)
        if rtc["tokens_per_sec"] else None,
        "token_p99_vs_rtc": round(
            paged["token_p99_ms"] / rtc["token_p99_ms"], 3)
        if rtc["token_p99_ms"] else None,
        "decode_ladder_size": len(paged["slot_rungs"]),
        "kill_mid_decode": kill,
    }


def _shared_prefix_stream(rng, n_requests: int, vocab: int, prefix):
    """Heavy-tailed shared-prefix stream: every request starts with the
    same ``prefix`` (a system/RAG/few-shot head, ~80% of each prompt's
    tokens) followed by a unique suffix — mostly short (chat turns), ~20%
    longer. Generation budgets are tiny: this arm measures TTFT, which is
    prefill-dominated."""
    reqs = []
    for _ in range(n_requests):
        if rng.random() < 0.2:
            slen = int(rng.choice([24, 32]))
        else:
            slen = int(rng.choice([8, 12, 16]))
        suffix = rng.integers(2, vocab, (slen,)).tolist()
        reqs.append((list(prefix) + suffix, 4))
    return reqs


def _run_prefix_arm(cfg, params, passes, slots: int, prefix_cache: bool):
    """One prefix-cache arm over per-pass request streams. TTFT per request
    is submit (= pass start; all requests are queued up front) -> its first
    emitted token, the same clock both arms use. The warm pass lands every
    compile AND (cache on) seeds the shared prefix; each timed pass uses
    FRESH suffixes, so cache reuse comes from the shared head only — never
    from replaying a previous pass's full prompts."""
    from synapseml_tpu.models.paged_engine import PagedDecodeEngine

    engine = PagedDecodeEngine(cfg, params, block_len=16, max_slots=slots,
                               prefill_batch=2, prefix_cache=prefix_cache)

    def one_pass(requests):
        seqs = [engine.submit(p, n) for p, n in requests]
        first: dict = {}
        t0 = time.perf_counter()
        while any(not s.done for s in seqs):
            events = engine.admit() + engine.step()
            now = time.perf_counter()
            for ev in events:
                if ev.get("token") is not None:
                    first.setdefault(ev["seq"].uid, (now - t0) * 1e3)
        return time.perf_counter() - t0, list(first.values())

    one_pass(passes[0])
    pc0 = (engine.stats().get("prefix_cache") or {})
    reused0 = pc0.get("tokens_reused", 0)
    timed = [one_pass(reqs) for reqs in passes[1:]]
    wall, ttft = min(timed, key=lambda r: r[0])
    pc = engine.stats().get("prefix_cache") or {}
    prompt_tokens = sum(len(p) for reqs in passes[1:] for p, _ in reqs)
    reused = int(pc.get("tokens_reused", 0)) - int(reused0)
    engine.release()
    p50, p99 = _percentiles(ttft)
    out = {"ttft_mean_ms": round(float(np.mean(ttft)), 3),
           "ttft_p50_ms": p50, "ttft_p99_ms": p99,
           "wall_s": round(wall, 3),
           # prefill work across ALL timed passes (reuse accumulates per
           # pass; wall/TTFT above are the best single pass)
           "prompt_tokens": int(prompt_tokens),
           "prefill_tokens_computed": int(prompt_tokens - reused)}
    if prefix_cache:
        out["prefix_cache"] = {k: pc.get(k) for k in (
            "hits", "misses", "hit_rate", "tokens_reused", "entries",
            "evictions")}
    return out


def _shared_prefix_ab(jax, platform):
    """Prefix-cache A/B (same round, same per-pass streams, min-of-3):
    cache OFF prefills every prompt whole; cache ON prefills only the
    uncached suffix once the shared head's pages are resident. The bar:
    >= 2x TTFT improvement at ~80% prefix share, with prefill tokens
    computed dropping superlinearly relative to the prefix share."""
    cfg, params = _tiny_model(jax)
    rng = np.random.default_rng(11)
    prefix = rng.integers(2, cfg.vocab_size, (64,)).tolist()  # 4 KV blocks
    on_tpu = platform == "tpu"
    n_req = 16 if on_tpu else 32
    trials = 1 if on_tpu else 3
    passes = [_shared_prefix_stream(rng, n_req, cfg.vocab_size, prefix)
              for _ in range(trials + 1)]
    slots = 8
    off = _run_prefix_arm(cfg, params, passes, slots, prefix_cache=False)
    on = _run_prefix_arm(cfg, params, passes, slots, prefix_cache=True)
    share = len(prefix) * sum(len(reqs) for reqs in passes[1:]) \
        / max(sum(len(p) for reqs in passes[1:] for p, _ in reqs), 1)
    return {
        "stream": {"n_requests_per_pass": n_req, "passes": trials,
                   "slots": slots, "prefix_len": len(prefix),
                   "prefix_share": round(share, 3)},
        "cache_off": off,
        "cache_on": on,
        "ttft_improvement": round(
            off["ttft_mean_ms"] / on["ttft_mean_ms"], 3)
        if on["ttft_mean_ms"] else None,
        "prefill_tokens_ratio": round(
            on["prefill_tokens_computed"]
            / max(off["prefill_tokens_computed"], 1), 3),
    }


def _zero_late_layers(jax, params, keep: int):
    """Draft-friendly weights: layers >= ``keep`` become EXACT identities
    (attention o-proj and mlp down-proj zeroed, so both residual branches
    contribute nothing). Early-exit at ``keep`` layers then equals the full
    model — greedy speculation accepts every draft by construction, which
    makes the A/B a clean measurement of the spec step's mechanics instead
    of a bet on a random drafter's luck."""
    import jax.numpy as jnp

    zero = lambda t: jax.tree.map(jnp.zeros_like, t)  # noqa: E731
    dec = dict(params["decoder"])
    for name in list(dec.keys()):
        if name.startswith("layer_") \
                and int(name.split("_", 1)[1]) >= keep:
            layer = dict(dec[name])
            attn = dict(layer["attn"])
            attn["o"] = zero(attn["o"])
            mlp = dict(layer["mlp"])
            mlp["down"] = zero(mlp["down"])
            layer["attn"], layer["mlp"] = attn, mlp
            dec[name] = layer
    out = {k: v for k, v in params.items() if k != "decoder"}
    out["decoder"] = dec
    return out


def _run_spec_arm(cfg, params, requests, slots: int, trials: int,
                  **engine_kw):
    from synapseml_tpu.models.paged_engine import PagedDecodeEngine

    engine = PagedDecodeEngine(cfg, params, block_len=16, max_slots=slots,
                               prefill_batch=2, **engine_kw)

    def one_pass():
        seqs = [engine.submit(p, n) for p, n in requests]
        t0 = time.perf_counter()
        while any(not s.done for s in seqs):
            engine.admit()
            engine.step()
        return time.perf_counter() - t0, [list(s.generated) for s in seqs]

    one_pass()  # warm: prefill + decode + (spec) draft/verify rungs
    results = [one_pass() for _ in range(trials)]
    wall = min(r[0] for r in results)
    gen = results[0][1]
    stats = engine.stats()
    engine.release()
    useful = sum(len(g) for g in gen)
    return {"tokens_per_sec": round(useful / wall, 1),
            "useful_tokens": useful, "wall_s": round(wall, 3)}, gen, stats


def _spec_decode_ab(jax, platform):
    """Speculative-decoding A/B (same round, same stream, min-of-3) on a
    DRAFT-FRIENDLY model: late layers zeroed to identities so the early-
    exit drafter is exact and acceptance is ~1.0 — the bar is tokens/sec
    >= 1.2x plain decode with tokens identical. A second rerun drives the
    kill-mid-decode arm with BOTH features on (prefix cache + speculation):
    the zero-dup / zero-loss bar must hold through a crash resume."""
    cfg, params = _tiny_model(jax)
    K, E = 6, 1
    friendly = _zero_late_layers(jax, params, E)
    rng = np.random.default_rng(13)
    reqs = []
    n_req = 16 if platform == "tpu" else 32
    for _ in range(n_req):  # decode-heavy: speculation pays on decode steps
        plen = int(rng.choice([6, 12, 20, 30]))
        n_new = int(rng.choice([16, 24, 32, 48]))
        reqs.append((rng.integers(2, cfg.vocab_size, (plen,)).tolist(),
                     n_new))
    slots = 8
    trials = 1 if platform == "tpu" else 3
    plain, gen_plain, _ = _run_spec_arm(cfg, friendly, reqs, slots, trials)
    spec, gen_spec, stats = _run_spec_arm(
        cfg, friendly, reqs, slots, trials, draft_tokens=K, draft_layers=E)
    sp = stats.get("speculation") or {}
    kill = None
    if platform != "tpu":
        kill = _run_kill_mid_decode(
            cfg, friendly, reqs, slots,
            engine_kw=dict(prefix_cache=True, draft_tokens=K,
                           draft_layers=E))
    return {
        "stream": {"n_requests": n_req, "slots": slots,
                   "draft_tokens": K, "draft_layers": E,
                   "total_tokens": sum(n for _, n in reqs)},
        "plain": plain,
        "spec": spec,
        "tokens_per_sec_vs_plain": round(
            spec["tokens_per_sec"] / plain["tokens_per_sec"], 3)
        if plain["tokens_per_sec"] else None,
        "acceptance_rate": sp.get("acceptance_rate"),
        "spec_steps": sp.get("steps"), "spec_fallbacks": sp.get("fallbacks"),
        "tokens_identical": gen_spec == gen_plain,
        "kill_mid_decode_both_on": kill,
    }


def run(jax, platform, n_chips):
    result = _legacy_throughput(jax, platform)
    try:
        result["continuous_ab"] = _continuous_ab(jax, platform)
    except Exception as e:  # noqa: BLE001 — A/B failure must not eat the
        result["continuous_ab"] = {"error": repr(e)}  # legacy TPU number
    try:
        result["shared_prefix_ab"] = _shared_prefix_ab(jax, platform)
    except Exception as e:  # noqa: BLE001
        result["shared_prefix_ab"] = {"error": repr(e)}
    try:
        result["spec_decode_ab"] = _spec_decode_ab(jax, platform)
    except Exception as e:  # noqa: BLE001
        result["spec_decode_ab"] = {"error": repr(e)}
    return result


def main():
    from _common import init_jax

    jax, platform, n_chips = init_jax()
    print(json.dumps(run(jax, platform, n_chips)))


if __name__ == "__main__":
    main()
