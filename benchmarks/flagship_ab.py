"""Flagship A/B: HEAD vs the round-2 commit, back to back on one window.

VERDICT r4 next-#2: the 1664 -> 1271 samples/s/chip flagship drop was filed
as relay contention on circumstantial evidence. This script settles it the
only honest way — both revisions measured on the SAME healthy window with
the same protocol:

1. run the flagship bench child at HEAD (in-process);
2. materialize the round-2 measurement commit (48e5726) in a git worktree
   and run ITS bench.py flagship child as a subprocess;
3. print one JSON line with both numbers and the verdict field.

Run it manually on a window, or let relay_watch.py reach it in the queue
(it is last — the never-measured configs keep priority). Exits cleanly
when the relay is down (platform 'none' result).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))
sys.path.insert(0, str(Path(__file__).parent.parent))

ROUND2_COMMIT = "48e5726"
REPO = str(Path(__file__).parent.parent)


def _head_flagship(budget_s: float = 420.0):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    result, err, _elapsed, hang, backend_up = bench._run_child(
        "tpu", "flagship", 75, budget_s)
    return result, err, hang, backend_up


def _round2_flagship(budget_s: float = 420.0):
    """Round-2 bench.py in a worktree subprocess (its own flagship child)."""
    wt = tempfile.mkdtemp(prefix="r2ab_")
    try:
        subprocess.run(["git", "-C", REPO, "worktree", "add", "--detach",
                        wt, ROUND2_COMMIT], check=True, capture_output=True)
        proc = subprocess.run(
            [sys.executable, os.path.join(wt, "bench.py")],
            # FULL environment with targeted overrides: the HEAD leg (via
            # bench._run_child) inherits everything, so the round-2 leg
            # must too or the comparison is structurally asymmetric
            env={**os.environ, "PYTHONPATH": wt,
                 "BENCH_CONFIGS": "flagship"},
            capture_output=True, text=True, timeout=budget_s + 240, cwd=wt)
        for line in reversed(proc.stdout.splitlines()):
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "metric" in d:
                return d, None
        return None, f"no JSON line; stderr tail: {proc.stderr[-400:]}"
    except subprocess.TimeoutExpired:
        return None, "round-2 bench timed out"
    finally:
        subprocess.run(["git", "-C", REPO, "worktree", "remove", "--force",
                        wt], capture_output=True)
        subprocess.run(["git", "-C", REPO, "worktree", "prune"],
                       capture_output=True)


def main() -> None:
    head, err_h, hang, backend_up = _head_flagship()
    if not head or head.get("platform") != "tpu":
        print(json.dumps({"metric": "flagship A/B (skipped)", "value": 0.0,
                          "unit": "n/a", "platform": "none",
                          "hang": bool(hang), "backend_up": bool(backend_up),
                          "reason": err_h or "no TPU window"}))
        return
    r2, err_2 = _round2_flagship()
    out = {"metric": "flagship A/B HEAD vs round-2",
           "unit": "samples/sec/chip", "platform": "tpu",
           "head": head, "round2_commit": ROUND2_COMMIT, "round2": r2,
           "value": head.get("value", 0.0)}
    if r2 and r2.get("platform") == "tpu" and r2.get("value"):
        ratio = head["value"] / r2["value"]
        out["head_over_round2"] = round(ratio, 4)
        out["verdict"] = ("HEAD >= round-2: contention confirmed"
                          if ratio >= 0.95 else
                          "HEAD slower on the same window: REAL regression "
                          "— bisect the einsum-path changes since round 2")
    else:
        out["round2_error"] = (err_2 or (
            f"round-2 leg ran on {r2.get('platform')!r}, not tpu — window "
            "degraded between the legs" if r2 else "no round-2 result"))
    print(json.dumps(out))


if __name__ == "__main__":
    main()
