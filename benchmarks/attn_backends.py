"""einsum vs flash attention, BERT-base train step (results:
docs/BENCHMARKS.md). Round-4 relevance: the flash kernel's dots now run in
bf16 on the MXU (previously pre-cast to f32, ~4x slower) — the round-2
numbers that made einsum the default at every T need remeasuring. Runs as a
bench.py/relay_watch child (``run``) or standalone (``main``)."""
import dataclasses
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).parent))
sys.path.insert(0, str(Path(__file__).parent.parent))


def run(jax, platform, n_chips):
    from synapseml_tpu.models.flax_nets.bert import BertClassifier, bert_base, bert_tiny
    from synapseml_tpu.models.trainer import Trainer, TrainerConfig
    from synapseml_tpu.parallel.mesh import MeshConfig, create_mesh

    on_tpu = platform == "tpu"
    # longest-T configs first: that is where the blockwise kernel can win
    # (the T=128 flagship einsum number is already recorded); keep the
    # compile count low — the relay serves brief windows
    shapes = ((2048, 2), (512, 8)) if on_tpu else ((32, 8),)
    results = {}
    for T, B in shapes:
        for impl in ("flash", "einsum"):
            base = bert_base() if on_tpu else bert_tiny()
            cfg = dataclasses.replace(base, attn_impl=impl)
            tr = Trainer(BertClassifier(cfg, num_classes=2),
                         create_mesh(MeshConfig(data=-1)),
                         TrainerConfig(learning_rate=5e-5, total_steps=1000))
            rng = np.random.default_rng(0)
            batch = {"input_ids": rng.integers(0, cfg.vocab_size, (B, T)).astype(np.int32),
                     "attention_mask": np.ones((B, T), np.int32),
                     "labels": rng.integers(0, 2, (B,)).astype(np.int32)}
            state = tr.init_state(batch)
            k = 16 if on_tpu else 4
            stacked = jax.tree.map(lambda x: np.broadcast_to(x, (k,) + x.shape).copy(), batch)
            st, m = tr.train_steps_scan(state, stacked)
            float(np.asarray(m["loss"])[-1])
            best = 1e9
            for _ in range(3):
                t0 = time.perf_counter()
                st, m = tr.train_steps_scan(st, stacked)
                np.asarray(m["loss"])
                best = min(best, time.perf_counter() - t0)
            results[f"T{T}_{impl}_ms"] = round(best / k * 1e3, 2)
            print(f"# attn {impl} T={T}: {results[f'T{T}_{impl}_ms']} ms/step",
                  flush=True)
    t_long = shapes[0][0]
    result = {
        "metric": "attention backend BERT-base train step"
                  + ("" if on_tpu else " (CPU smoke)"),
        "value": results[f"T{t_long}_flash_ms"], "unit": "ms/step",
        "lower_is_better": True, "platform": platform,
        "longest_T": t_long,
        "flash_vs_einsum_longT": round(
            results[f"T{t_long}_einsum_ms"] / results[f"T{t_long}_flash_ms"], 3),
    }
    result.update(results)
    return result


def main():
    from _common import init_jax

    jax, platform, n_chips = init_jax()
    print(json.dumps(run(jax, platform, n_chips)))


if __name__ == "__main__":
    main()
