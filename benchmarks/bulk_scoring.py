"""Bulk scoring: in-memory ``transform`` vs streamed ``transform_source``.

Writes a multi-shard synthetic jsonl corpus (the stand-in for a >RAM
dataset — the streamed path's in-flight bytes stay O(queued shards) no
matter how large this is scaled), fits one LightGBM classifier, then scores
the WHOLE corpus two ways in the SAME round, each arm end-to-end from files
on disk to scored output on disk and each from a cold compile cache:

  (a) in-memory — ``io.files.read_jsonl`` materializes every row, ONE
                  ``model.transform`` over the full DataFrame (the exact
                  shape-polymorphic jit path — no padding), ``write_jsonl``
                  of the scored frame: the all-in-RAM baseline, paying the
                  full parse before the first score;
  (b) streamed  — ``ShardedSource.jsonl`` + ``JsonlSink`` through
                  ``model.transform_source``: shard reads and sink writes
                  overlap device compute on the bounded-queue pipeline,
                  batches ride the bucket ladder through the shared
                  ``CompiledCache`` (compile count <= ladder size).

Then the distributed half: the same scan as two simulated hosts (two
threads, ``host_index`` 0/1 of ``host_count=2``, one shared sink directory
— the real multi-host layout) vs the 1-host wall clock.

Reports rows/sec for both arms: one COLD streamed run first records the
compile count (<= ladder bound — on a real corpus of millions of rows that
one-time trace amortizes to nothing, so it stays out of the throughput
wall), then min-of-3 warm walls per arm, interleaved — the llama_decode
discipline; host-side json work makes single runs noisy on a shared box.
Also: peak in-flight queue bytes vs the memory budget (dataset >>
budget: the bounded-memory claim), an output-equality check (streamed rows
== in-memory rows, id for id), and the 2-host wall. Acceptance bar (ISSUE
8): streamed rows/sec >= 0.9x in-memory on CPU with compile count <=
ladder size. Prints one JSON line.
"""
import json
import os
import shutil
import sys
import tempfile
import threading
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).parent))
sys.path.insert(0, str(Path(__file__).parent.parent))

N_SHARDS = 8
ROWS_PER_SHARD = 8192
N_FEATURES = 16
N_TRAIN = 4096
BATCH_ROWS = 1024  # the top default-ladder rung: 64 full batches
NUM_ITERATIONS = 256  # a production-sized forest: device compute carries
# real weight, so the streamed arm's read/compute/write overlap is the
# thing being measured — jax releases the GIL during forest execution, so
# shard parse and sink writes proceed under it (a small toy forest is all
# GIL-bound json on both arms and measures nothing but thread coordination)
# a scoring backfill emits ids + scores, not the raw features it read —
# both arms project to the same output schema
OUT_COLUMNS = ["id", "prediction", "probability"]
# the configured memory budget the streamed arm must hold: far below the
# materialized dataset (read_jsonl's object rows cost several x this on the
# in-memory arm)
MEMORY_BUDGET_BYTES = 8 << 20


def _write_corpus(directory: str) -> tuple[int, int, np.ndarray]:
    """One jsonl file per shard; rows carry a global ``id`` so the
    equality check is exact. Returns (rows, bytes, true weight vector)."""
    rs = np.random.default_rng(0)
    w = rs.normal(size=N_FEATURES)
    i, total = 0, 0
    for s in range(N_SHARDS):
        p = os.path.join(directory, f"part-{s:03d}.jsonl")
        with open(p, "w") as f:
            X = rs.normal(size=(ROWS_PER_SHARD, N_FEATURES))
            for j in range(ROWS_PER_SHARD):
                f.write(json.dumps({
                    "features": [round(float(v), 5) for v in X[j]],
                    "id": i}) + "\n")
                i += 1
        total += os.path.getsize(p)
    return i, total, w


def _fit_model(w: np.ndarray):
    from synapseml_tpu.core.dataframe import DataFrame
    from synapseml_tpu.gbdt import LightGBMClassifier

    rs = np.random.default_rng(1)
    X = rs.normal(size=(N_TRAIN, N_FEATURES)).astype(np.float32)
    y = (X @ w > 0).astype(np.int64)
    df = DataFrame([{"features": X, "labels": y}])
    return LightGBMClassifier(num_iterations=NUM_ITERATIONS, num_leaves=15,
                              label_col="labels").fit(df)


def _cold_cache(model=None):
    """Cold-start compile state for ONE arm trial: the shared CompiledCache
    (streamed arm's bucketed jits) AND the booster's private polymorphic
    ``_predict_cache`` (the in-memory arm's beyond-ladder path) — otherwise
    min-of-3 hands the in-memory arm warm executables the streamed arm
    re-pays every trial."""
    from synapseml_tpu.core.batching import (get_compiled_cache,
                                             reset_compiled_cache)

    reset_compiled_cache()
    if model is not None:
        model.get_booster()._predict_cache.clear()
    c = get_compiled_cache()
    return c.miss_count("gbdt_predict") + c.miss_count("gbdt_predict_scored")


def _run_in_memory(model, directory: str, out_dir: str,
                   n_rows: int) -> dict:
    from synapseml_tpu.core.dataframe import DataFrame
    from synapseml_tpu.io.files import read_jsonl, write_jsonl

    os.makedirs(out_dir, exist_ok=True)
    t0 = time.perf_counter()
    df = read_jsonl(os.path.join(directory, "*.jsonl"))
    load_s = time.perf_counter() - t0
    t1 = time.perf_counter()
    scored = model.transform(df)
    score_s = time.perf_counter() - t1
    t2 = time.perf_counter()
    part = scored.collect()
    write_jsonl(DataFrame([{c: part[c] for c in OUT_COLUMNS}]),
                os.path.join(out_dir, "scored.jsonl"))
    write_s = time.perf_counter() - t2
    wall = time.perf_counter() - t0
    return {"wall_s": round(wall, 3), "load_s": round(load_s, 3),
            "score_s": round(score_s, 3), "write_s": round(write_s, 3),
            "rows_per_sec": round(n_rows / wall, 1),
            "_rows": {"id": np.asarray(part["id"]),
                      "prediction": np.asarray(part["prediction"])}}


def _sink(out_dir: str):
    from synapseml_tpu.scoring import JsonlSink

    return JsonlSink(out_dir, columns=OUT_COLUMNS)


def _run_streamed(model, directory: str, out_dir: str,
                  cold: bool = False) -> dict:
    from synapseml_tpu.core.batching import get_compiled_cache
    from synapseml_tpu.data import ShardedSource
    from synapseml_tpu.scoring import plan_scan

    misses0 = _cold_cache(model) if cold else 0
    src = ShardedSource.jsonl(os.path.join(directory, "*.jsonl"))
    plan = plan_scan(src, BATCH_ROWS, host_index=0, host_count=1)
    sink = _sink(out_dir)
    report = model.transform_source(src, sink, batch_rows=BATCH_ROWS,
                                    host_index=0, host_count=1)
    c = get_compiled_cache()
    compiles = int(c.miss_count("gbdt_predict")
                   + c.miss_count("gbdt_predict_scored") - misses0) \
        if cold else None
    rows = [json.loads(ln) for p in sink.part_files()
            for ln in open(p) if ln.strip()]
    return {"wall_s": round(report.wall_s, 3),
            "rows_per_sec": round(report.rows_per_sec, 1),
            "rows_written": report.rows_written,
            "batches": report.batches,
            "padded_rows": report.rows_padded,
            "shards": report.shards_done,
            "complete": report.complete,
            "peak_inflight_bytes": report.peak_inflight_bytes,
            "gbdt_predict_compiles": compiles,
            "ladder_bound": len(plan.buckets),
            "_rows": {"id": np.asarray([r["id"] for r in rows]),
                      "prediction": np.asarray([r["prediction"]
                                                for r in rows])}}


def _run_two_hosts(model, directory: str, out_dir: str) -> dict:
    """The same scan as two simulated hosts sharing one sink directory —
    two threads so shard reads/writes genuinely interleave (on one CPU the
    compute serializes under the GIL/device; the TPU upside is real
    per-host devices)."""
    from synapseml_tpu.data import ShardedSource

    _cold_cache(model)
    src = ShardedSource.jsonl(os.path.join(directory, "*.jsonl"))
    reports: dict[int, object] = {}
    errors: list = []

    def host(idx: int) -> None:
        try:
            reports[idx] = model.transform_source(
                src, _sink(out_dir), batch_rows=BATCH_ROWS,
                host_index=idx, host_count=2)
        except Exception as e:  # noqa: BLE001 — surfaced in the record
            errors.append(repr(e))

    t0 = time.perf_counter()
    threads = [threading.Thread(target=host, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors:
        return {"error": errors[0], "wall_s": round(wall, 3)}
    total = sum(r.rows_written for r in reports.values())
    return {"wall_s": round(wall, 3),
            "rows_per_sec": round(total / wall, 1) if wall > 0 else 0.0,
            "rows_written": total,
            "complete": any(r.complete for r in reports.values()),
            "per_host_shards": {i: reports[i].shards_done for i in reports}}


def run(jax, platform, n_chips):
    directory = tempfile.mkdtemp(prefix="synapseml_bulkscore_")
    try:
        data_dir = os.path.join(directory, "data")
        os.makedirs(data_dir)
        n_rows, n_bytes, w = _write_corpus(data_dir)
        model = _fit_model(w)
        # one cold streamed run: the compile-count-vs-ladder record (and
        # the warmup for both executables' shared forest tensors)
        cold = _run_streamed(model, data_dir,
                             os.path.join(directory, "out_cold"), cold=True)
        # then min-of-3 WARM walls per arm, arms interleaved so a load
        # spike on the shared box can't bias one side; each trial scans
        # into a fresh sink dir
        in_mem = streamed = None
        for t in range(3):
            im = _run_in_memory(model, data_dir,
                                os.path.join(directory, f"out_mem{t}"),
                                n_rows)
            st = _run_streamed(model, data_dir,
                               os.path.join(directory, f"out_stream{t}"))
            if in_mem is None or im["wall_s"] < in_mem["wall_s"]:
                in_mem = im
            if streamed is None or st["wall_s"] < streamed["wall_s"]:
                streamed = st
        streamed["gbdt_predict_compiles"] = cold["gbdt_predict_compiles"]
        streamed["cold_wall_s"] = cold["wall_s"]
        two_host = _run_two_hosts(model, data_dir,
                                  os.path.join(directory, "out_2host"))

        a, b = in_mem.pop("_rows"), streamed.pop("_rows")
        oa, ob = np.argsort(a["id"]), np.argsort(b["id"])
        outputs_equal = bool(
            a["id"].shape == b["id"].shape
            and np.array_equal(a["id"][oa], b["id"][ob])
            and np.allclose(a["prediction"][oa], b["prediction"][ob]))
    finally:
        shutil.rmtree(directory, ignore_errors=True)
    return {
        "metric": "bulk scoring streamed rows/sec "
                  "(transform_source vs in-memory transform)",
        "value": streamed["rows_per_sec"], "unit": "rows/sec",
        "lower_is_better": False, "platform": platform,
        "dataset_rows": n_rows, "dataset_bytes": n_bytes,
        "memory_budget_bytes": MEMORY_BUDGET_BYTES,
        "streamed": streamed, "in_memory_baseline": in_mem,
        "two_host_simulated": two_host,
        "throughput_vs_in_memory": round(
            streamed["rows_per_sec"] / in_mem["rows_per_sec"], 3)
        if in_mem["rows_per_sec"] else None,
        "compile_count_within_ladder":
            streamed["gbdt_predict_compiles"] <= streamed["ladder_bound"],
        "peak_inflight_within_budget":
            streamed["peak_inflight_bytes"] <= MEMORY_BUDGET_BYTES,
        "outputs_equal": outputs_equal,
    }


def main():
    from _common import init_jax

    jax, platform, n_chips = init_jax()
    print(json.dumps(run(jax, platform, n_chips)))


if __name__ == "__main__":
    main()
