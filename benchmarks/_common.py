"""Shared setup for benchmark scripts."""
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))


def init_jax():
    """Import jax honoring $JAX_PLATFORMS via the config API (sitecustomize
    pins jax_platforms=axon at interpreter boot, so env alone is ignored).
    Returns (jax module, platform string, device count)."""
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    devs = jax.devices()
    return jax, devs[0].platform, len(devs)


from synapseml_tpu.core.pipeline import Transformer as _Transformer


class EchoT(_Transformer):
    """Picklable trivial Transformer for serving benchmarks (module-level so
    worker processes can unpickle it by reference)."""

    def _transform(self, df):
        import numpy as np

        def per_part(p):
            out = dict(p)
            out["reply"] = np.asarray([{"ok": True} for _ in p["body"]],
                                      dtype=object)
            return out

        return df.map_partitions(per_part)
