"""Shared setup for benchmark scripts."""
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))


def init_jax():
    """Import jax honoring $JAX_PLATFORMS via the config API (sitecustomize
    pins jax_platforms=axon at interpreter boot, so env alone is ignored).
    Returns (jax module, platform string, device count)."""
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    devs = jax.devices()
    return jax, devs[0].platform, len(devs)


from synapseml_tpu.core.pipeline import Transformer as _Transformer


class EchoT(_Transformer):
    """Picklable trivial Transformer for serving benchmarks (module-level so
    worker processes can unpickle it by reference)."""

    def _transform(self, df):
        import numpy as np

        def per_part(p):
            out = dict(p)
            out["reply"] = np.asarray([{"ok": True} for _ in p["body"]],
                                      dtype=object)
            return out

        return df.map_partitions(per_part)


class GBDTScorerT(_Transformer):
    """Picklable MODEL-BACKED serving payload: a fitted GBDT classifier
    scores each request's ``features`` list — the non-trivial pipeline the
    latency claims should be judged against (a real tree-ensemble forward
    per request, not an echo)."""

    def __init__(self, model, **kw):
        super().__init__(**kw)
        self._model = model

    def _transform(self, df):
        import numpy as np

        from synapseml_tpu.core import DataFrame

        def per_part(p):
            feats = np.asarray([np.asarray(b["features"], np.float32)
                                for b in p["body"]])
            scored = self._model.transform(
                DataFrame.from_dict({"features": feats}))
            preds = scored.collect_column("prediction")
            out = dict(p)
            out["reply"] = np.asarray([{"prediction": float(v)}
                                       for v in preds], dtype=object)
            return out

        return df.map_partitions(per_part)


def train_tiny_gbdt(seed: int = 0):
    """A quickly-fitted GBDT classification model for serving benches."""
    import numpy as np

    from synapseml_tpu.core import DataFrame
    from synapseml_tpu.gbdt import LightGBMClassifier

    rs = np.random.default_rng(seed)
    X = rs.normal(size=(400, 8)).astype(np.float32)
    y = (X @ rs.normal(size=8) > 0).astype(np.int32)
    df = DataFrame.from_dict({"features": X, "label": y})
    return LightGBMClassifier(num_iterations=20, num_leaves=15,
                              max_bin=63).fit(df)
