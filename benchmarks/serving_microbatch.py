"""Serving microbatch: continuous batching + shared compile cache A/B.

Fires a MIXED-SIZE request stream (concurrency phases 1/4/16 so the serve
loop drains genuinely variable batch sizes) at ``serve_pipeline`` wrapping
an ONNX MLP scorer — the stage whose jits now come from the process-wide
``CompiledCache`` over the pow-2 bucket ladder. Two runs in the SAME round:

  (a) fixed    — the old fixed-timeout ``read_batch`` scheduler (baseline);
  (b) adaptive — the continuous-batching scheduler (flush on a full bucket,
                 wait up to the latency budget otherwise).

Emits p50/p99 latency, rows/sec, and the compile-cache hit rate per run.
The acceptance bar: adaptive p99 and throughput no worse than fixed.
Prints one JSON line.
"""
import json
import sys
import threading
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).parent))
sys.path.insert(0, str(Path(__file__).parent.parent))


def _make_onnx_scorer():
    """Tiny MLP as ONNX protobuf bytes -> ONNXModel -> serving Transformer."""
    from synapseml_tpu.core import DataFrame
    from synapseml_tpu.core.pipeline import Transformer
    from synapseml_tpu.onnx import ONNXModel
    from synapseml_tpu.onnx.proto import (AttributeProto, GraphProto,
                                          ModelProto, NodeProto,
                                          ValueInfoProto, numpy_to_tensor)
    from synapseml_tpu.onnx import proto as P

    rs = np.random.default_rng(0)
    din, dh, dout = 16, 64, 4
    W1 = rs.normal(size=(din, dh)).astype(np.float32)
    b1 = rs.normal(size=(dh,)).astype(np.float32)
    W2 = rs.normal(size=(dh, dout)).astype(np.float32)
    b2 = rs.normal(size=(dout,)).astype(np.float32)

    def node(op, inputs, outputs, **attrs):
        return NodeProto(input=list(inputs), output=list(outputs), op_type=op,
                         attribute=[AttributeProto.make(k, v)
                                    for k, v in attrs.items()])

    g = GraphProto(
        name="mlp",
        node=[node("Gemm", ["x", "W1", "b1"], ["h_pre"]),
              node("Relu", ["h_pre"], ["h"]),
              node("Gemm", ["h", "W2", "b2"], ["logits"]),
              node("Softmax", ["logits"], ["probs"], axis=-1)],
        initializer=[numpy_to_tensor(W1, "W1"), numpy_to_tensor(b1, "b1"),
                     numpy_to_tensor(W2, "W2"), numpy_to_tensor(b2, "b2")],
        input=[ValueInfoProto(name="x", elem_type=P.FLOAT, dims=["N", din])],
        output=[ValueInfoProto(name="probs", elem_type=P.FLOAT,
                               dims=["N", dout])],
    )
    onnx = ONNXModel(ModelProto(graph=g).encode(),
                     feed_dict={"x": "features"},
                     fetch_dict={"probs": "probs"}, mini_batch_size=64)

    class OnnxScorerT(Transformer):
        def _transform(self, df):
            def per_part(p):
                feats = np.asarray([np.asarray(b["features"], np.float32)
                                    for b in p["body"]])
                scored = onnx.transform(
                    DataFrame.from_dict({"features": feats}))
                probs = scored.collect_column("probs")
                out = dict(p)
                out["reply"] = np.asarray(
                    [{"argmax": int(np.argmax(row))} for row in probs],
                    dtype=object)
                return out

            return df.map_partitions(per_part)

    return OnnxScorerT(), din


def _requester(address: str, body: bytes):
    import http.client
    import socket

    host, port = address.split("//")[1].split(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=60)
    conn.connect()
    conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def request():
        conn.request("POST", "/", body=body)
        r = conn.getresponse()
        payload = r.read()
        assert r.status == 200, (r.status, payload[:200])

    request.close = conn.close
    return request


def _phase(address: str, body: bytes, clients: int, per_client: int) -> list:
    """One concurrency phase; returns per-request latencies (ms)."""
    lat_all: list = []
    errors: list = []
    ready = threading.Barrier(clients)

    def loop():
        try:
            request = _requester(address, body)
            ready.wait()
            lat = []
            for _ in range(per_client):
                t0 = time.perf_counter()
                request()
                lat.append((time.perf_counter() - t0) * 1e3)
            request.close()
            lat_all.extend(lat)
        except Exception as e:  # noqa: BLE001 — surfaced after join
            errors.append(e)
            try:
                ready.abort()
            except Exception:  # noqa: BLE001
                pass

    threads = [threading.Thread(target=loop) for _ in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise RuntimeError(f"{len(errors)} bench clients failed: "
                           f"{errors[0]!r}") from errors[0]
    return lat_all


def _run_scheduler(scheduler: str, n_per_client: int = 80) -> dict:
    from synapseml_tpu.core.batching import reset_compiled_cache
    from synapseml_tpu.io.serving import serve_pipeline

    cache = reset_compiled_cache()
    stage, din = _make_onnx_scorer()
    body = json.dumps({"features": [0.1] * din}).encode()
    srv = serve_pipeline(stage, batch_interval_ms=5, scheduler=scheduler)
    try:
        _phase(srv.address, body, clients=2, per_client=10)  # warm compile
        lat = []
        t0 = time.perf_counter()
        for clients in (1, 8, 32):  # mixed-size stream: 1..32-deep queues
            lat.extend(_phase(srv.address, body, clients,
                              per_client=n_per_client))
        wall = time.perf_counter() - t0
    finally:
        srv.stop()
    lat.sort()
    stats = cache.stats()
    lookups = stats["hits"] + stats["misses"]
    return {"p50_ms": round(lat[len(lat) // 2], 3),
            "p99_ms": round(lat[int(len(lat) * 0.99)], 3),
            "rows_per_sec": round(len(lat) / wall, 1),
            "n": len(lat),
            "compile_cache": {**stats,
                              "hit_rate": round(stats["hits"] / lookups, 4)
                              if lookups else None}}


def run(jax, platform, n_chips):
    fixed = _run_scheduler("fixed")
    adaptive = _run_scheduler("adaptive")
    return {
        "metric": "serving microbatch p99 (adaptive continuous batching)",
        "value": adaptive["p99_ms"], "unit": "ms", "lower_is_better": True,
        "platform": "cpu host (latency is host-side)",
        "adaptive": adaptive,
        "fixed_baseline": fixed,
        "p99_vs_fixed": round(adaptive["p99_ms"] / fixed["p99_ms"], 3)
        if fixed["p99_ms"] else None,
        "throughput_vs_fixed": round(adaptive["rows_per_sec"]
                                     / fixed["rows_per_sec"], 3)
        if fixed["rows_per_sec"] else None,
    }


def main():
    from _common import init_jax

    jax, platform, n_chips = init_jax()
    print(json.dumps(run(jax, platform, n_chips)))


if __name__ == "__main__":
    main()
