"""Deploy cold-start A/B: publish-once AOT executable ladders vs JIT warmup.

The ISSUE-9 acceptance measurement: publish ONE artifact (a deep ONNX MLP
pipeline with its full bucket ladder AOT-compiled + serialized into the
registry at publish time), then hot-swap it onto a fresh worker process
twice in the SAME round:

  (a) aot — ``/admin/load`` maps in the precompiled executables (the
      manifest's full-ladder warmup replays; the PR-4 "rungs <= 64"
      default cap is lifted because loading an executable is I/O);
  (b) jit — the same artifact with ``"aot": false`` (identical bytes,
      identical numerics), paying jit traces at warmup under the default
      small-rung cap, exactly like every pre-ISSUE-9 rollout.

Each arm is a FRESH subprocess (cold process-level caches — the honest
cold-start). Reported per arm: total swap wall (``load_ms``), the warmup
breakdown (io_ms / compile_ms / executables loaded vs traced), the first
post-swap HTTP request, and the FIRST RUNG-128 BATCH: 96 rows pushed
through the exact serve-loop batch preparation (``run_warmup`` — what the
adaptive scheduler hands the pipeline when a post-cutover burst drains),
a rung the JIT arm's capped warmup never compiled, so its first big batch
pays the compile the AOT arm shipped from publish. (A threaded HTTP burst
measures GIL contention on a small host, not the compile stall — the
direct serve-loop form is the low-noise measurement of the same event.)
Gates: byte-identical predictions between arms, zero traced executables
in the AOT arm, and AOT first-128-batch wall <= 0.5x the JIT arm's.

All measurement subprocesses force ``JAX_PLATFORMS=cpu`` so publish and
load fingerprints match regardless of the parent's backend (a TPU A/B
needs the grandchildren to own the chip — land opportunistically when the
relay cooperates). Prints one JSON line.
"""
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).parent))
sys.path.insert(0, str(Path(__file__).parent.parent))

BUCKETS = [8, 16, 32, 64, 128]
DIN, DOUT, WIDTH, DEPTH = 16, 4, 256, 12
FIRST_BATCH = 96  # pads to rung 128 — past the default JIT warmup cap


# ---------------------------------------------------------------------------
# the published pipeline (module-level: grandchildren import by name, so
# the serialized class path 'deploy_coldstart.*' resolves everywhere)
# ---------------------------------------------------------------------------

from synapseml_tpu.core.params import Param, TypeConverters  # noqa: E402
from synapseml_tpu.core.pipeline import (PipelineModel,  # noqa: E402
                                         Transformer)


class BodyToFeatures(Transformer):
    din = Param("din", "feature width", default=DIN,
                converter=TypeConverters.to_int)

    def _transform(self, df):
        d = self.get("din")

        def per_part(p):
            out = dict(p)
            feats = np.zeros((len(p["body"]), d), np.float32)
            for i, body in enumerate(p["body"]):
                if isinstance(body, dict) and "features" in body:
                    feats[i] = np.asarray(body["features"], np.float32)
            out["features"] = feats
            return out

        return df.map_partitions(per_part)


class PredToReply(Transformer):
    def _transform(self, df):
        def per_part(p):
            out = dict(p)
            out["reply"] = np.asarray(
                [{"pred": int(p["pred"][i]),
                  "probs": [round(float(x), 6) for x in p["probs"][i]]}
                 for i in range(len(p["pred"]))], dtype=object)
            return out

        return df.map_partitions(per_part)


def build_pipeline(seed=0):
    from synapseml_tpu.onnx import ONNXModel
    from synapseml_tpu.onnx import proto as P
    from synapseml_tpu.onnx.proto import (AttributeProto, GraphProto,
                                          ModelProto, NodeProto,
                                          ValueInfoProto, numpy_to_tensor)

    rs = np.random.default_rng(seed)

    def node(op, inputs, outputs, **attrs):
        return NodeProto(input=list(inputs), output=list(outputs),
                         op_type=op,
                         attribute=[AttributeProto.make(k, v)
                                    for k, v in attrs.items()])

    nodes, inits = [], []
    prev, prev_w = "x", DIN
    for layer in range(DEPTH):
        w = rs.normal(size=(prev_w, WIDTH)).astype(np.float32) * 0.2
        b = rs.normal(size=(WIDTH,)).astype(np.float32) * 0.1
        inits += [numpy_to_tensor(w, f"W{layer}"),
                  numpy_to_tensor(b, f"b{layer}")]
        nodes += [node("Gemm", [prev, f"W{layer}", f"b{layer}"],
                       [f"h{layer}_pre"]),
                  node("Relu", [f"h{layer}_pre"], [f"h{layer}"])]
        prev, prev_w = f"h{layer}", WIDTH
    w = rs.normal(size=(prev_w, DOUT)).astype(np.float32) * 0.2
    b = rs.normal(size=(DOUT,)).astype(np.float32) * 0.1
    inits += [numpy_to_tensor(w, "Wout"), numpy_to_tensor(b, "bout")]
    nodes += [node("Gemm", [prev, "Wout", "bout"], ["logits"]),
              node("Softmax", ["logits"], ["probs"], axis=-1)]
    g = GraphProto(
        name="deep_mlp", node=nodes, initializer=inits,
        input=[ValueInfoProto(name="x", elem_type=P.FLOAT,
                              dims=["N", DIN])],
        output=[ValueInfoProto(name="probs", elem_type=P.FLOAT,
                               dims=["N", DOUT])],
    )
    onnx = ONNXModel(ModelProto(graph=g).encode(),
                     feed_dict={"x": "features"},
                     fetch_dict={"probs": "probs"},
                     argmax_dict={"probs": "pred"},
                     mini_batch_size=BUCKETS[-1])
    return PipelineModel(stages=[BodyToFeatures(din=DIN), onnx,
                                 PredToReply()])


def sample_rows(n=4, seed=7):
    rs = np.random.default_rng(seed)
    return [{"features": [round(float(x), 6) for x in
                          rs.normal(size=DIN)]} for _ in range(n)]


# ---------------------------------------------------------------------------
# grandchild drivers (fresh processes, cold caches)
# ---------------------------------------------------------------------------

def publish_driver(store: str) -> None:
    from synapseml_tpu.registry import ModelRegistry

    t0 = time.perf_counter()
    ModelRegistry(store).publish(
        "coldstart", build_pipeline(), version="v1",
        aot={"rows": sample_rows(), "buckets": BUCKETS})
    print(json.dumps({"publish_s": round(time.perf_counter() - t0, 2)}))


def arm_driver(store: str, use_aot: bool) -> None:
    import urllib.request

    from synapseml_tpu.core import batching as cb
    from synapseml_tpu.core.pipeline import Transformer
    from synapseml_tpu.io.serving import serve_pipeline

    class Placeholder(Transformer):
        def _transform(self, df):
            def pp(p):
                out = dict(p)
                out["reply"] = np.asarray([{}] * len(p["id"]),
                                          dtype=object)
                return out

            return df.map_partitions(pp)

    srv = serve_pipeline(Placeholder(), batch_interval_ms=5, version="v0",
                         max_batch_rows=BUCKETS[-1])

    def post(path, payload, timeout=600):
        req = urllib.request.Request(
            srv.address + path, data=json.dumps(payload).encode(),
            method="POST")
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read())

    cache = cb.get_compiled_cache()
    misses0 = cache.miss_count("onnx_model")
    t0 = time.perf_counter()
    reply = post("/admin/load", {"registry": store, "model": "coldstart",
                                 "ref": "v1", "aot": use_aot})
    swap_wall_ms = (time.perf_counter() - t0) * 1e3
    # first post-swap request over HTTP (a rung both arms warmed)
    t0 = time.perf_counter()
    post("/", sample_rows(1, seed=77)[0])
    http_first_ms = (time.perf_counter() - t0) * 1e3
    # first rung-128 batch through the exact serve-loop preparation — the
    # drained burst a fleet cutover sees; the JIT arm's capped warmup
    # never compiled this rung
    from synapseml_tpu.io.serving import run_warmup

    loaded = srv.pipeline_holder.pipeline
    bodies = sample_rows(FIRST_BATCH, seed=1234)
    loop_cfg = {"parse_json": True, "input_col": "body"}
    t0 = time.perf_counter()
    run_warmup(loaded, bodies, [FIRST_BATCH], loop_cfg)
    first_batch_ms = (time.perf_counter() - t0) * 1e3
    # warm reference for the same batch (steady-state floor, min of 3)
    warm_ms = min(
        _timed(lambda: run_warmup(loaded, bodies, [FIRST_BATCH], loop_cfg))
        for _ in range(3))
    # deterministic probe replies for the byte-identity gate
    probes = [post("/", b) for b in sample_rows(8, seed=42)]
    print(json.dumps({
        "arm": "aot" if use_aot else "jit",
        "swap_wall_ms": round(swap_wall_ms, 2),
        "load_ms": reply["load_ms"],
        "warmup": reply["warmup"],
        "http_first_request_ms": round(http_first_ms, 2),
        "first_128_batch_ms": round(first_batch_ms, 2),
        "warm_128_batch_ms": round(warm_ms, 2),
        "traced_after_swap": cache.miss_count("onnx_model") - misses0,
        "probes": probes,
    }))
    srv.stop()


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return (time.perf_counter() - t0) * 1e3


def _grandchild(args: list, timeout_s: float) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    bench_dir = str(Path(__file__).parent)
    repo = str(Path(__file__).parent.parent)
    code = ("import sys; sys.path.insert(0, %r); sys.path.insert(0, %r); "
            "import deploy_coldstart as dc; dc.%s" %
            (bench_dir, repo, args[0]))
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True,
                          timeout=timeout_s, env=env)
    if proc.returncode != 0:
        raise RuntimeError(f"grandchild {args[0]} failed:\n"
                           f"{proc.stderr[-3000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def run(jax, platform, n_chips):
    directory = tempfile.mkdtemp(prefix="synapseml_coldstart_")
    store = os.path.join(directory, "store")
    try:
        pub = _grandchild([f"publish_driver({store!r})"], 420)
        arms = {}
        for use_aot in (True, False):
            out = _grandchild(
                [f"arm_driver({store!r}, {use_aot})"], 420)
            arms[out["arm"]] = out
        aot, jit = arms["aot"], arms["jit"]
        identical = (json.dumps(aot["probes"], sort_keys=True)
                     == json.dumps(jit["probes"], sort_keys=True))
        ratio_first = (round(aot["first_128_batch_ms"]
                             / jit["first_128_batch_ms"], 3)
                       if jit["first_128_batch_ms"] else None)
        ratio_swap = (round(aot["load_ms"] / jit["load_ms"], 3)
                      if jit["load_ms"] else None)
    finally:
        shutil.rmtree(directory, ignore_errors=True)
    return {
        "metric": "deploy cold-start first rung-128 batch after hot swap, "
                  "AOT vs JIT warmup"
                  + ("" if platform == "tpu" else " (CPU A/B)"),
        "value": aot["first_128_batch_ms"], "unit": "ms",
        "lower_is_better": True,
        # the subprocess arms force CPU so publish/load fingerprints match
        "platform": "cpu",
        "publish_s": pub["publish_s"],
        "ladder": BUCKETS, "first_batch_rows": FIRST_BATCH,
        "aot": aot, "jit": jit,
        "first_batch_aot_vs_jit": ratio_first,
        "swap_wall_aot_vs_jit": ratio_swap,
        "aot_zero_traces": aot["warmup"]["executables_traced"] == 0
        and aot["traced_after_swap"] == 0,
        "outputs_equal": identical,
    }


def main():
    from _common import init_jax

    jax, platform, n_chips = init_jax()
    print(json.dumps(run(jax, platform, n_chips)))


if __name__ == "__main__":
    main()
