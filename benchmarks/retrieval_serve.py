"""Retrieval serving A/B: sharded fan-out serve vs in-process brute force.

The ISSUE-18 acceptance measurement, both arms over the SAME corpus and
query workload in the same round:

  (a) brute — one in-process [N, D] matrix scored through the shared
      ``retrieval/scorer.py`` kernel (the exact-search speed-of-light on
      this host; its top-10 ids are also the recall truth set);
  (b) served — the corpus built into a multi-shard index, published to a
      registry, and served by 2 subprocess workers (each advertising half
      the shards) behind a ``RoutingFront`` ``/retrieval/<index>``
      fan-out; the client POSTs the same query batches over HTTP.

Embeddings are integer-valued hash-trick vectors, so distances are exact
in float32 and recall@10 compares true id lists, not approximations.
After the serve A/B, the continual-ingest leg logs fresh documents
through the flywheel ``RequestLogger``, runs ``ingest_deltas``, and
measures (i) the reported log-to-publish freshness lag and (ii) the wall
from publish to the FIRST fan-out answer containing a fresh doc — with
every poll required to answer 200 (the zero-downtime contract).

Gates: recall@10 >= 0.99, served QPS >= 0.9x brute force (the fan-out
parallelism must at least pay for the HTTP hop), fresh docs queryable
with zero downtime and full coverage (no partials while both workers
live). Workers force ``JAX_PLATFORMS=cpu``; the brute arm runs on the
session backend (on the CPU fallback both arms are CPU — an honest A/B).
Prints one JSON line.
"""
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
import urllib.request
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).parent))
sys.path.insert(0, str(Path(__file__).parent.parent))

N_DOCS = 300_000       # large enough that per-shard scoring, not the
DIM = 128              # per-request HTTP/JSON hop, dominates the serve arm
N_FILES = 4            # corpus files -> source shards -> index shards
QUERY_BATCH = 64
N_REQUESTS = 8
K = 10
N_FRESH = 64


def _texts(n, start=0):
    return [f"doc{start + i} alpha{i % 11} beta{i % 29} gamma{i % 97}"
            for i in range(n)]


def _write_corpus(directory, texts):
    os.makedirs(directory, exist_ok=True)
    per = (len(texts) + N_FILES - 1) // N_FILES
    for f_i in range(N_FILES):
        with open(os.path.join(directory, f"corpus-{f_i:03d}.jsonl"),
                  "w") as f:
            for i in range(f_i * per, min((f_i + 1) * per, len(texts))):
                f.write(json.dumps({"id": i, "text": texts[i]}) + "\n")


def _post(url, payload, timeout=120.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read()), dict(r.headers)


def _spawn_worker(store, reg_url, shards):
    code = ("import synapseml_tpu.retrieval.serve as s\n"
            f"s.retrieval_worker_main({store!r}, 'docs', {reg_url!r}, "
            f"shards={shards!r}, refresh_s=0.2)\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=str(Path(__file__).parent.parent))
    return subprocess.Popen([sys.executable, "-c", code], env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.STDOUT)


def _brute_arm(E, ids, batches):
    """In-process exact search through the shared kernel: per-request
    top-k over the ONE full-corpus shard, with the plane's (distance, id)
    tie-break. Returns (qps, truth id lists per request)."""
    from synapseml_tpu.retrieval import score_batches

    x_sq = np.sum(E * E, axis=1, dtype=np.float32)
    score_batches(batches[0], E, K, x_sq=x_sq)  # warm the ladder
    truth = []
    t0 = time.perf_counter()
    for Q in batches:
        dist, idx = score_batches(Q, E, K, x_sq=x_sq)
        rows = []
        for i in range(len(Q)):
            order = sorted(zip(dist[i], idx[i]),
                           key=lambda t: (t[0], ids[t[1]]))
            rows.append([int(ids[j]) for _, j in order])
        truth.append(rows)
    wall = time.perf_counter() - t0
    return len(batches) * len(batches[0]) / wall, truth


def run(jax, platform, n_chips):
    from synapseml_tpu.continual import RequestLogger
    from synapseml_tpu.io.distributed_serving import (RoutingFront,
                                                      WorkerRegistry)
    from synapseml_tpu.registry import ModelRegistry
    from synapseml_tpu.data.source import ShardedSource
    from synapseml_tpu.retrieval import (HashEmbedder, build_index,
                                         ingest_deltas)

    directory = tempfile.mkdtemp(prefix="synapseml_retrieval_serve_")
    store = os.path.join(directory, "store")
    texts = _texts(N_DOCS)
    emb = HashEmbedder(dim=DIM)
    procs, front, wreg = [], None, None
    try:
        _write_corpus(os.path.join(directory, "corpus"), texts)
        registry = ModelRegistry(store)
        t0 = time.perf_counter()
        published, _report = build_index(
            registry, "docs", HashEmbedder(dim=DIM),
            ShardedSource.jsonl(os.path.join(directory, "corpus",
                                             "*.jsonl")),
            os.path.join(directory, "build"), k=K, batch_rows=2048)
        build_s = time.perf_counter() - t0
        resolved = registry.resolve("docs", "latest")
        roster = [s["name"] for s in
                  resolved.manifest["extra"]["retrieval"]["shards"]]

        # the brute-force corpus matrix comes back OUT of the published
        # shards — one embed pass total, and the arms provably score the
        # same bytes
        from synapseml_tpu.retrieval import list_shards
        committed = list_shards(os.path.join(resolved.path, "shards"))
        E = np.concatenate([s.vectors() for s in committed])
        ids = np.concatenate([s.ids() for s in committed])
        rs = np.random.default_rng(0)
        batches = [E[rs.integers(0, N_DOCS, size=QUERY_BATCH)]
                   for _ in range(N_REQUESTS)]

        brute_qps, truth = _brute_arm(E, ids, batches)

        wreg = WorkerRegistry()
        front = RoutingFront(registry=wreg)
        reg_url = wreg.address + "/register"
        half = (len(roster) + 1) // 2
        procs = [_spawn_worker(store, reg_url, roster[:half]),
                 _spawn_worker(store, reg_url, roster[half:])]
        wreg.wait_for(2, timeout_s=180)
        url = front.address + "/retrieval/docs"
        _post(url, {"queries": batches[0][:4].tolist(), "k": K})  # warm

        hits = total = 0
        t0 = time.perf_counter()
        for r_i, Q in enumerate(batches):
            status, reply, hdrs = _post(url, {"queries": Q.tolist(),
                                              "k": K})
            assert status == 200 and not reply["missing"]
            for got, want in zip(reply["matches"], truth[r_i]):
                hits += len(set(m["id"] for m in got) & set(want))
                total += K
        serve_wall = time.perf_counter() - t0
        served_qps = N_REQUESTS * QUERY_BATCH / serve_wall
        recall = hits / total

        # --- continual ingest: freshness + zero-downtime ------------------
        fresh = [f"freshdoc{i} delta{i} live" for i in range(N_FRESH)]
        with RequestLogger(os.path.join(directory, "logs"),
                           shard_rows=32) as lg:
            for t in fresh:
                lg.log(method="POST", path="/ingest/docs",
                       body=json.dumps({"doc": t}).encode(), reply=b"ok",
                       status=200, latency_ms=1.0)
            lg.flush()
        t_pub = time.perf_counter()
        report = ingest_deltas(registry, "docs",
                               os.path.join(directory, "logs"),
                               HashEmbedder(dim=DIM),
                               os.path.join(directory, "ingest"))
        probe = np.asarray(emb.embed([fresh[3]]), np.float32)[0].tolist()
        want_id = N_DOCS + 3
        serve_lag_s = None
        downtime_free = True
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            try:
                status, reply, hdrs = _post(url, {"query": probe, "k": 3})
            except Exception:  # noqa: BLE001 — any failed poll = downtime
                downtime_free = False
                break
            if status != 200:
                downtime_free = False
                break
            top = reply["matches"][0]
            if (top and top[0]["id"] == want_id and not reply["missing"]):
                serve_lag_s = time.perf_counter() - t_pub
                break
            time.sleep(0.1)

        result = {
            "metric": "retrieval-serve QPS (2-worker shard fan-out, "
                      f"{N_DOCS} docs x {DIM}d, k={K})",
            "value": round(served_qps, 1),
            "unit": "queries/s", "lower_is_better": False,
            "platform": "cpu host (workers force CPU; brute arm on "
                        f"{platform})",
            "brute_force_qps": round(brute_qps, 1),
            "qps_vs_brute": round(served_qps / brute_qps, 3),
            "recall_at_10": round(recall, 5),
            "index": {"docs": N_DOCS, "dim": DIM, "shards": len(roster),
                      "build_s": round(build_s, 2),
                      "version": published.version},
            "ingest": {"docs": N_FRESH,
                       "version": report["version"],
                       "freshness_lag_s": round(
                           report["freshness_lag_s"], 2),
                       "publish_to_queryable_s": (
                           round(serve_lag_s, 2)
                           if serve_lag_s is not None else None)},
            "bars": {
                "recall_at_10_geq_0_99": recall >= 0.99,
                "qps_geq_0_9x_brute": served_qps >= 0.9 * brute_qps,
                "fresh_docs_queryable": serve_lag_s is not None,
                "zero_downtime_through_swap": downtime_free,
            },
        }
        return result
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        if front is not None:
            front.close()
        if wreg is not None:
            wreg.close()
        shutil.rmtree(directory, ignore_errors=True)


def main():
    from _common import init_jax

    jax, platform, n_chips = init_jax()
    print(json.dumps(run(jax, platform, n_chips)))


if __name__ == "__main__":
    main()
