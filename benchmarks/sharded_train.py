"""Sharded-train A/B: replicated vs ZeRO-sharded weight update, same round.

Two arms train the SAME MLP for the same optimizer steps over the same
seeded :class:`~synapseml_tpu.data.DataLoader` stream, each in a FRESH
subprocess forced onto a multi-device CPU mesh (4 virtual devices — the
deploy-coldstart fresh-arm discipline, so neither arm inherits the other's
compile cache and the parent backend's device count doesn't matter):

  (a) replicated — the status-quo trainer: optimizer state replicated on
      every data-parallel replica;
  (b) zero       — ``TrainerConfig(partition_rules=..., zero_shard=True)``:
      the optimizer state partitions over the ``('data','fsdp')`` replica
      group inside the one jitted step (arXiv:2004.13336).

Reports per arm: per-replica and total optimizer-state bytes (measured
from the live shardings), warm per-step wall time, final loss; plus the
cross-arm bars — per-replica opt-state bytes <= replicated/dp + epsilon,
step-time ratio >= 0.9x, final-loss delta 0.0 and final-params max abs
diff at f32. CPU A/B per the bench discipline; TPU numbers land
opportunistically when the relay cooperates. Prints one JSON line.
"""
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).parent))
sys.path.insert(0, str(Path(__file__).parent.parent))

DEVICES = 4
D_IN = 64
HIDDEN = 512
BATCH = 256
STEPS = 40
WARM_SKIP = 4  # steps excluded from the warm per-step wall (compiles)
EPS_BYTES = 8192  # unshardable leaves: count scalar + small bias moments


def _arm_main(arm: str, out_path: str) -> None:
    """Runs inside the fresh subprocess: train one arm, dump the record +
    final params."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    import flax.linen as nn

    from synapseml_tpu.data import DataLoader
    from synapseml_tpu.data.source import MemorySource
    from synapseml_tpu.models.trainer import Trainer, TrainerConfig
    from synapseml_tpu.parallel import partition as pp
    from synapseml_tpu.parallel.mesh import MeshConfig, create_mesh

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            h = nn.relu(nn.Dense(HIDDEN)(x))
            h = nn.relu(nn.Dense(HIDDEN)(h))
            return nn.Dense(2)(h)

    rs = np.random.default_rng(0)
    X = rs.normal(size=(4096, D_IN)).astype(np.float32)
    data = {"x": X, "labels": (X[:, 0] > 0).astype(np.int32)}

    mesh = create_mesh(MeshConfig(data=-1))
    dp = mesh.data_parallel_size()
    cfg = TrainerConfig(total_steps=STEPS, learning_rate=1e-2)
    if arm == "zero":
        cfg.partition_rules = pp.PartitionRules(
            zero_axes=("data", "fsdp"), mesh=mesh.config)
        cfg.zero_shard = True
    trainer = Trainer(MLP(), mesh, cfg)
    loader = DataLoader(MemorySource(data), BATCH, seed=13, multiple_of=dp)
    it = iter(loader)
    first = next(it)
    state = trainer.init_state(first, jax.random.PRNGKey(3))

    losses: list = []
    step_walls: list = []
    t_prev = [time.perf_counter()]

    def cb(i, metrics):
        losses.append(float(metrics["loss"]))
        now = time.perf_counter()
        step_walls.append(now - t_prev[0])
        t_prev[0] = now

    def chain():
        yield first
        yield from it

    t0 = time.perf_counter()
    state = trainer.fit(state, chain(), max_steps=STEPS, callback=cb)
    wall = time.perf_counter() - t0
    loader.close()

    host_params = jax.tree.map(lambda x: np.asarray(x, np.float32),
                               state.params)
    np.savez(out_path + ".params.npz",
             **{str(i): leaf for i, leaf in
                enumerate(jax.tree.leaves(host_params))})
    record = {
        "arm": arm, "dp": dp, "steps": int(state.step),
        "final_loss": losses[-1],
        "wall_s": round(wall, 3),
        "warm_step_ms": round(
            1e3 * float(np.mean(step_walls[WARM_SKIP:])), 3),
        "opt_bytes_total": pp.total_bytes(state.opt_state),
        "opt_bytes_per_replica": pp.per_device_bytes(state.opt_state),
        "param_bytes_total": pp.total_bytes(state.params),
    }
    with open(out_path, "w") as f:
        json.dump(record, f)


def _run_arm(arm: str, tmp: str) -> dict:
    out_path = os.path.join(tmp, f"{arm}.json")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + f" --xla_force_host_platform_device_count={DEVICES}"
                        ).strip()
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--arm", arm, out_path],
        env=env, capture_output=True, text=True, timeout=240)
    if proc.returncode != 0:
        raise RuntimeError(f"{arm} arm failed:\n{proc.stdout}\n{proc.stderr}")
    with open(out_path) as f:
        record = json.load(f)
    params = np.load(out_path + ".params.npz")
    record["_params"] = [params[k] for k in sorted(params, key=int)]
    return record


def run(jax, platform, n_chips):
    tmp = tempfile.mkdtemp(prefix="synapseml_shardedtrain_")
    try:
        replicated = _run_arm("replicated", tmp)
        zero = _run_arm("zero", tmp)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    param_diff = max(
        float(np.max(np.abs(a - b))) if a.size else 0.0
        for a, b in zip(replicated.pop("_params"), zero.pop("_params")))
    dp = zero["dp"]
    opt_ratio = (zero["opt_bytes_per_replica"]
                 / max(replicated["opt_bytes_per_replica"], 1))
    step_ratio = (replicated["warm_step_ms"]
                  / max(zero["warm_step_ms"], 1e-9))
    loss_delta = abs(replicated["final_loss"] - zero["final_loss"])
    bars = {
        "opt_bytes_bound": zero["opt_bytes_per_replica"]
        <= replicated["opt_bytes_per_replica"] / dp + EPS_BYTES,
        "step_time_ratio_ge_0p9": step_ratio >= 0.9,
        "loss_delta_zero": loss_delta <= 1e-5,
        "param_parity_f32": param_diff <= 5e-6,
    }
    return {
        "benchmark": "sharded_train", "platform": platform,
        "mode": "cpu_ab" if platform != "tpu" else "tpu_ab",
        "devices_per_arm": DEVICES, "dp": dp, "steps": STEPS,
        "replicated": replicated, "zero": zero,
        "opt_bytes_per_replica_ratio": round(opt_ratio, 4),
        "step_time_ratio": round(step_ratio, 3),
        "final_loss_delta": loss_delta,
        "param_max_abs_diff": param_diff,
        "bars": bars, "all_bars_pass": all(bars.values()),
    }


def main():
    if len(sys.argv) >= 4 and sys.argv[1] == "--arm":
        _arm_main(sys.argv[2], sys.argv[3])
        return
    from benchmarks._common import init_jax

    jax, platform, n_chips = init_jax()
    print(json.dumps(run(jax, platform, n_chips)))


if __name__ == "__main__":
    main()
