"""Sharded-train A/B: replicated vs ZeRO-sharded weight update, same round,
plus the kill-and-resume arm (elastic gang recovery vs uninterrupted).

Two arms train the SAME MLP for the same optimizer steps over the same
seeded :class:`~synapseml_tpu.data.DataLoader` stream, each in a FRESH
subprocess forced onto a multi-device CPU mesh (4 virtual devices — the
deploy-coldstart fresh-arm discipline, so neither arm inherits the other's
compile cache and the parent backend's device count doesn't matter):

  (a) replicated — the status-quo trainer: optimizer state replicated on
      every data-parallel replica;
  (b) zero       — ``TrainerConfig(partition_rules=..., zero_shard=True)``:
      the optimizer state partitions over the ``('data','fsdp')`` replica
      group inside the one jitted step (arXiv:2004.13336).

Reports per arm: per-replica and total optimizer-state bytes (measured
from the live shardings), warm per-step wall time, final loss; plus the
cross-arm bars — per-replica opt-state bytes <= replicated/dp + epsilon,
step-time ratio >= 0.9x, final-loss delta 0.0 and final-params max abs
diff at f32.

The ELASTIC section (same round, CPU A/B): an uninterrupted 2-worker gang
run vs a 2-worker gang SIGKILLed at one member mid-run and resumed on the
survivor (N=2→M=1 elastic resume from the last committed coordinated
checkpoint). Reports **recovery seconds** (survivor relaunch → first
post-resume optimizer step, restore + re-rendezvous + compile included)
and **goodput** (useful steps / total wall-clock including the lost work
and the second launch) as a ratio against the uninterrupted arm. CPU A/B
per the bench discipline; TPU numbers land opportunistically when the
relay cooperates. Prints one JSON line.
"""
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import textwrap
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).parent))
sys.path.insert(0, str(Path(__file__).parent.parent))

DEVICES = 4
D_IN = 64
HIDDEN = 512
BATCH = 256
STEPS = 40
WARM_SKIP = 4  # steps excluded from the warm per-step wall (compiles)
EPS_BYTES = 8192  # unshardable leaves: count scalar + small bias moments

GANG_STEPS = 40
GANG_STEP_MS = 60.0       # per-step floor so the kill lands mid-run
GANG_CHECKPOINT_EVERY = 5
GANG_KILL_AFTER_STEP = 15  # SIGKILL once this step's commit lands


def _arm_main(arm: str, out_path: str) -> None:
    """Runs inside the fresh subprocess: train one arm, dump the record +
    final params."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    import flax.linen as nn

    from synapseml_tpu.data import DataLoader
    from synapseml_tpu.data.source import MemorySource
    from synapseml_tpu.models.trainer import Trainer, TrainerConfig
    from synapseml_tpu.parallel import partition as pp
    from synapseml_tpu.parallel.mesh import MeshConfig, create_mesh

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            h = nn.relu(nn.Dense(HIDDEN)(x))
            h = nn.relu(nn.Dense(HIDDEN)(h))
            return nn.Dense(2)(h)

    rs = np.random.default_rng(0)
    X = rs.normal(size=(4096, D_IN)).astype(np.float32)
    data = {"x": X, "labels": (X[:, 0] > 0).astype(np.int32)}

    mesh = create_mesh(MeshConfig(data=-1))
    dp = mesh.data_parallel_size()
    cfg = TrainerConfig(total_steps=STEPS, learning_rate=1e-2)
    if arm == "zero":
        cfg.partition_rules = pp.PartitionRules(
            zero_axes=("data", "fsdp"), mesh=mesh.config)
        cfg.zero_shard = True
    trainer = Trainer(MLP(), mesh, cfg)
    loader = DataLoader(MemorySource(data), BATCH, seed=13, multiple_of=dp)
    it = iter(loader)
    first = next(it)
    state = trainer.init_state(first, jax.random.PRNGKey(3))

    losses: list = []
    step_walls: list = []
    t_prev = [time.perf_counter()]

    def cb(i, metrics):
        losses.append(float(metrics["loss"]))
        now = time.perf_counter()
        step_walls.append(now - t_prev[0])
        t_prev[0] = now

    def chain():
        yield first
        yield from it

    t0 = time.perf_counter()
    state = trainer.fit(state, chain(), max_steps=STEPS, callback=cb)
    wall = time.perf_counter() - t0
    loader.close()

    host_params = jax.tree.map(lambda x: np.asarray(x, np.float32),
                               state.params)
    np.savez(out_path + ".params.npz",
             **{str(i): leaf for i, leaf in
                enumerate(jax.tree.leaves(host_params))})
    record = {
        "arm": arm, "dp": dp, "steps": int(state.step),
        "final_loss": losses[-1],
        "wall_s": round(wall, 3),
        "warm_step_ms": round(
            1e3 * float(np.mean(step_walls[WARM_SKIP:])), 3),
        "opt_bytes_total": pp.total_bytes(state.opt_state),
        "opt_bytes_per_replica": pp.per_device_bytes(state.opt_state),
        "param_bytes_total": pp.total_bytes(state.params),
    }
    with open(out_path, "w") as f:
        json.dump(record, f)


def _run_arm(arm: str, tmp: str) -> dict:
    out_path = os.path.join(tmp, f"{arm}.json")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + f" --xla_force_host_platform_device_count={DEVICES}"
                        ).strip()
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--arm", arm, out_path],
        env=env, capture_output=True, text=True, timeout=240)
    if proc.returncode != 0:
        raise RuntimeError(f"{arm} arm failed:\n{proc.stdout}\n{proc.stderr}")
    with open(out_path) as f:
        record = json.load(f)
    params = np.load(out_path + ".params.npz")
    record["_params"] = [params[k] for k in sorted(params, key=int)]
    return record


GANG_WORKER = textwrap.dedent("""
    import json, sys, time

    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import flax.linen as nn

    from synapseml_tpu.parallel.gang import run_gang_member
    from synapseml_tpu.models.trainer import Trainer, TrainerConfig
    from synapseml_tpu.parallel.mesh import MeshConfig, create_mesh
    from synapseml_tpu.data.source import MemorySource

    addr, part = sys.argv[1], int(sys.argv[2])
    ckdir, logp = sys.argv[3], sys.argv[4]
    total_steps, step_ms = int(sys.argv[5]), float(sys.argv[6])
    checkpoint_every = int(sys.argv[7])

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(2)(nn.relu(nn.Dense(64)(x)))

    rs = np.random.default_rng(7)
    X = rs.normal(size=(2048, 8)).astype(np.float32)
    src = MemorySource({"x": X, "labels": (X[:, 0] > 0).astype(np.int32)},
                       shard_rows=64)
    log = open(logp, "a")

    def trainer_fn(info):
        mesh = create_mesh(MeshConfig(data=1))
        return Trainer(MLP(), mesh, TrainerConfig(
            total_steps=total_steps, learning_rate=1e-2))

    def cb(i, metrics):
        log.write(json.dumps({"t": time.time(),
                              "loss": float(metrics["loss"])}) + "\\n")
        log.flush()
        if step_ms:
            time.sleep(step_ms / 1000.0)

    code = run_gang_member(addr, part, trainer_fn=trainer_fn, source=src,
                           checkpoint_dir=ckdir, total_steps=total_steps,
                           batch_size=32, seed=3,
                           checkpoint_every=checkpoint_every, grace_s=60.0,
                           epochs=None, shuffle_rows="none", callback=cb)
    log.close()
    sys.exit(code)
""")


def _launch_gang(tmp, tag, world, ckdir, steps, step_ms):
    from synapseml_tpu.parallel.gang import launch_gang_processes

    script = os.path.join(tmp, "gang_worker.py")
    if not os.path.exists(script):
        with open(script, "w") as f:
            f.write(GANG_WORKER)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    repo_root = str(Path(__file__).resolve().parent.parent)
    env["PYTHONPATH"] = repo_root + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    logs = [os.path.join(tmp, f"gang_{tag}_{p}.jsonl") for p in range(world)]
    procs, coord, _ = launch_gang_processes(
        script, world, checkpoint_dir=ckdir,
        worker_args_fn=lambda p, addr: [
            addr, str(p), ckdir, logs[p], str(steps), str(step_ms),
            str(GANG_CHECKPOINT_EVERY)],
        env=env, coordinator_kw=dict(beat_timeout_s=90.0, grace_s=60.0,
                                     poll_s=0.05))
    return procs, coord, logs


def _finish_gang(procs, coord, timeout_s=200, wait_commit_step=None):
    from synapseml_tpu.parallel.gang import finish_gang_processes

    _, codes = finish_gang_processes(procs, coord, timeout_s=timeout_s,
                                     wait_commit_step=wait_commit_step)
    return codes


def _first_step_time(log_path):
    with open(log_path) as f:
        for line in f:
            return json.loads(line)["t"]
    return None


def _gang_elastic_section(tmp):
    """Same-round A/B: uninterrupted 2-worker gang vs killed-and-resumed.
    Useful steps = GANG_STEPS (the steps in the surviving lineage)."""
    from synapseml_tpu.parallel import checkpoint as cp
    from synapseml_tpu.parallel.gang import EXIT_RESIZE

    # arm U: uninterrupted
    ck_u = os.path.join(tmp, "ck_unint")
    os.makedirs(ck_u)
    t0 = time.perf_counter()
    procs, coord, _ = _launch_gang(tmp, "unint", 2, ck_u, GANG_STEPS,
                                   GANG_STEP_MS)
    codes_u = _finish_gang(procs, coord, wait_commit_step=GANG_STEPS)
    wall_u = time.perf_counter() - t0
    if codes_u != [0, 0]:
        raise RuntimeError(f"uninterrupted gang arm failed: {codes_u}")

    # arm E phase 1: 2 workers, SIGKILL rank 1 after the commit lands
    ck_e = os.path.join(tmp, "ck_elastic")
    os.makedirs(ck_e)
    t1 = time.perf_counter()
    procs, coord, _ = _launch_gang(tmp, "e1", 2, ck_e, GANG_STEPS,
                                   GANG_STEP_MS)
    committed = coord.wait_commit(step=GANG_KILL_AFTER_STEP, timeout_s=150)
    if committed is None:  # kill only AFTER a restorable point exists —
        # otherwise phase 2 fresh-starts from scratch and every elastic
        # bar (final_step, recovery, goodput) passes without a single
        # checkpoint ever restoring, masking commit-path regressions
        raise RuntimeError(
            f"no commit landed at step {GANG_KILL_AFTER_STEP} before kill")
    t_kill = time.perf_counter()
    procs[1].send_signal(signal.SIGKILL)
    codes_1 = _finish_gang(procs, coord)
    phase1_wall = time.perf_counter() - t1
    if codes_1[0] != EXIT_RESIZE or codes_1[1] != -signal.SIGKILL:
        raise RuntimeError(f"kill phase exits unexpected: {codes_1}")
    resume_step = cp.latest_verified_step(ck_e)
    if resume_step is None or resume_step < GANG_KILL_AFTER_STEP:
        raise RuntimeError(
            f"survivor has no restorable checkpoint >= "
            f"{GANG_KILL_AFTER_STEP} (latest verified: {resume_step}) — "
            "phase 2 would not be an elastic resume")

    # arm E phase 2: N=2 -> M=1 elastic resume on the survivor
    t2 = time.perf_counter()
    t2_epoch = time.time()
    procs, coord, logs = _launch_gang(tmp, "e2", 1, ck_e, GANG_STEPS,
                                      GANG_STEP_MS)
    codes_2 = _finish_gang(procs, coord, wait_commit_step=GANG_STEPS)
    phase2_wall = time.perf_counter() - t2
    if codes_2 != [0]:
        raise RuntimeError(f"resume phase failed: {codes_2}")
    first_step_t = _first_step_time(logs[0])
    recovery_s = (first_step_t - t2_epoch) if first_step_t else None

    goodput_unint = GANG_STEPS / wall_u
    goodput_elastic = GANG_STEPS / (phase1_wall + phase2_wall)
    final_step = cp.latest_verified_step(ck_e)
    # orig_world stays frozen at the FIRST launch's world across resumes —
    # a fresh start on the survivor would stamp 1, proving phase 2
    # restarted instead of resuming
    orig_world = cp.checkpoint_meta(ck_e).get("orig_world")
    bars = {
        "resumed_to_completion": final_step == GANG_STEPS
        and orig_world == 2,
        "recovery_under_60s": recovery_s is not None and recovery_s < 60.0,
        "goodput_ratio_ge_0p25": goodput_elastic / goodput_unint >= 0.25,
    }
    return {
        "committed_before_kill": committed,
        "resume_step": resume_step,
        "final_step": final_step,
        "orig_world": orig_world,
        "detect_plus_drain_s": round(phase1_wall
                                     - (t_kill - t1), 3),
        "recovery_s": round(recovery_s, 3) if recovery_s else None,
        "uninterrupted_wall_s": round(wall_u, 3),
        "elastic_wall_s": round(phase1_wall + phase2_wall, 3),
        "goodput_steps_per_s": {
            "uninterrupted": round(goodput_unint, 3),
            "elastic": round(goodput_elastic, 3)},
        "goodput_ratio": round(goodput_elastic / goodput_unint, 3),
        "bars": bars,
    }


def run(jax, platform, n_chips):
    tmp = tempfile.mkdtemp(prefix="synapseml_shardedtrain_")
    try:
        replicated = _run_arm("replicated", tmp)
        zero = _run_arm("zero", tmp)
        elastic = _gang_elastic_section(tmp)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    param_diff = max(
        float(np.max(np.abs(a - b))) if a.size else 0.0
        for a, b in zip(replicated.pop("_params"), zero.pop("_params")))
    dp = zero["dp"]
    opt_ratio = (zero["opt_bytes_per_replica"]
                 / max(replicated["opt_bytes_per_replica"], 1))
    step_ratio = (replicated["warm_step_ms"]
                  / max(zero["warm_step_ms"], 1e-9))
    loss_delta = abs(replicated["final_loss"] - zero["final_loss"])
    bars = {
        "opt_bytes_bound": zero["opt_bytes_per_replica"]
        <= replicated["opt_bytes_per_replica"] / dp + EPS_BYTES,
        "step_time_ratio_ge_0p9": step_ratio >= 0.9,
        "loss_delta_zero": loss_delta <= 1e-5,
        "param_parity_f32": param_diff <= 5e-6,
    }
    bars.update({f"elastic_{k}": v for k, v in elastic["bars"].items()})
    return {
        "metric": "sharded-train ZeRO per-replica opt-state bytes ratio"
                  + ("" if platform == "tpu" else " (CPU A/B)"),
        "value": round(opt_ratio, 4), "unit": "x", "lower_is_better": True,
        "benchmark": "sharded_train", "platform": platform,
        "mode": "cpu_ab" if platform != "tpu" else "tpu_ab",
        "devices_per_arm": DEVICES, "dp": dp, "steps": STEPS,
        "replicated": replicated, "zero": zero,
        "opt_bytes_per_replica_ratio": round(opt_ratio, 4),
        "step_time_ratio": round(step_ratio, 3),
        "final_loss_delta": loss_delta,
        "param_max_abs_diff": param_diff,
        "elastic": elastic,
        "bars": bars, "all_bars_pass": all(bars.values()),
    }


def main():
    if len(sys.argv) >= 4 and sys.argv[1] == "--arm":
        _arm_main(sys.argv[2], sys.argv[3])
        return
    from benchmarks._common import init_jax

    jax, platform, n_chips = init_jax()
    print(json.dumps(run(jax, platform, n_chips)))


if __name__ == "__main__":
    main()
