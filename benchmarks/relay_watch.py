"""Relay-window watcher: capture the missing BASELINE TPU numbers.

The axon relay serves brief, unpredictable windows (round 2: one window;
rounds 3: none; round 4: one window that captured the flagship then
degraded). This watcher loops for as long as it is left running: it
attempts the still-missing TPU configs via bench.py's staged-deadline
child machinery, seeds every success into PERF_BASELINE.json (keep-best),
and backs off while the relay is hung. Run it in the background during a
build session:

    python benchmarks/relay_watch.py >> /tmp/relay_watch.log 2>&1 &

It exits when every queued config has a captured chip number (or has
failed MAX_ATTEMPTS times with the backend up, which means the config
itself — not the relay — is broken).
"""
from __future__ import annotations

import importlib.util
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location("bench", os.path.join(REPO, "bench.py"))
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)

# (config, total child deadline seconds) — generous: this path has no
# driver kill-timeout to stay under, only the session's lifetime.
# smallest-compile-first: a brief window should bank the cheap configs
# before the ViT-B/16 compile (which outran 450s and appeared to wedge the
# relay in both 2026-07-31 windows) gets its attempt
QUEUE = [
    ("onnx-resnet", 600),
    ("llama-decode", 600),
    ("flagship", 480),   # recapture: the 2026-07-31 window number was contended
    ("gbdt-higgs", 900),
    ("gbdt-hist-backends", 900),
    ("attn-backends", 900),   # einsum-vs-flash decision after the bf16 kernel fix
    ("vit", 900),
    ("flagship-ab", 1500),    # HEAD vs round-2 A/B — settles 1664-vs-1271 last
]
MAX_ATTEMPTS = 4         # per config, counting only backend-up failures
HANG_BACKOFF_S = 480
FAIL_BACKOFF_S = 90


def _note(msg: str) -> None:
    print(f"[{time.strftime('%Y-%m-%d %H:%M:%S')}] {msg}", flush=True)


RESULTS_JSONL = "/tmp/relay_watch_results.jsonl"


def _run_flagship_ab(budget: float):
    """Adapter giving flagship_ab.py the (result, err, elapsed, hang,
    backend_up) shape the queue loop expects."""
    import subprocess
    import sys as _sys

    t0 = time.time()
    try:
        proc = subprocess.run(
            [_sys.executable, os.path.join(REPO, "benchmarks",
                                           "flagship_ab.py")],
            capture_output=True, text=True, timeout=budget)
    except subprocess.TimeoutExpired:
        return None, "flagship A/B timed out", time.time() - t0, True, False
    elapsed = time.time() - t0
    for line in reversed(proc.stdout.splitlines()):
        try:
            d = json.loads(line)
        except json.JSONDecodeError:
            continue
        if "metric" not in d:
            continue
        if d.get("platform") == "tpu" and "verdict" in d:
            return d, None, elapsed, False, True
        if d.get("platform") == "tpu":
            # head leg landed but round-2 didn't: BANK the scarce head
            # measurement (results log + baseline seed) before retrying —
            # the A/B question stays unsettled, so this still counts as a
            # backend-up failure with a bounded attempt count
            head = d.get("head")
            if isinstance(head, dict) and head.get("platform") == "tpu":
                with open(RESULTS_JSONL, "a") as f:
                    f.write(json.dumps({"config": "flagship-ab-head-only",
                                        **head}) + "\n")
                if not bench._seed_baseline(head, bench._load_recorded()):
                    _note("A/B head-only capture: baseline seed FAILED — "
                          f"result only in {RESULTS_JSONL}")
            return (None, d.get("round2_error", "round-2 leg failed"),
                    elapsed, False, True)
        # skipped line: hang/backend_up say whether this was relay trouble
        # (wait for a window) or a real config failure (bounded retries)
        return (None, d.get("reason", "no window"), elapsed,
                bool(d.get("hang", True)), bool(d.get("backend_up", False)))
    return (None, f"no JSON line: {proc.stderr[-200:]}", elapsed, False,
            True)


def main() -> None:
    queue = list(QUEUE)
    attempts: dict = {}
    while queue:
        name, budget = queue[0]
        if name == "flagship-ab":
            # the HEAD-vs-round-2 A/B (VERDICT r4 next-#2): runs last, only
            # once the regular configs have had their windows
            result, err, elapsed, hang, backend_up = _run_flagship_ab(budget)
        else:
            result, err, elapsed, hang, backend_up = bench._run_child(
                "tpu", name, 75, budget)
        if result is not None and result.get("platform") == "tpu":
            with open(RESULTS_JSONL, "a") as f:   # belt-and-braces record
                f.write(json.dumps({"config": name, **result}) + "\n")
            if name == "flagship-ab":
                # diagnostic composite, NOT a baseline: the head leg's
                # flagship number seeds under its own metric; the A/B
                # verdict lives in RESULTS_JSONL and the log
                if not bench._seed_baseline(result["head"],
                                            bench._load_recorded()):
                    _note("A/B head seed FAILED — head number only in "
                          f"{RESULTS_JSONL}")
                _note(f"A/B VERDICT in {elapsed:.0f}s: {json.dumps(result)}")
                queue.pop(0)
                continue
            if bench._seed_baseline(result, bench._load_recorded()):
                _note(f"CAPTURED {name} in {elapsed:.0f}s: {json.dumps(result)}")
            else:
                _note(f"CAPTURED {name} but PERF_BASELINE.json seed FAILED — "
                      f"result only in {RESULTS_JSONL}: {json.dumps(result)}")
            queue.pop(0)
            continue
        if hang or not backend_up:
            # killed before BENCH_UP (hang) or died before announcing the
            # backend (the relay raising UNAVAILABLE during init): both are
            # relay trouble, not a config failure — wait for the next window
            _note(f"{name}: relay down (hang={hang}, {elapsed:.0f}s, {err}); "
                  f"backing off {HANG_BACKOFF_S}s")
            time.sleep(HANG_BACKOFF_S)
            continue
        attempts[name] = attempts.get(name, 0) + 1
        _note(f"{name}: backend up but failed (attempt {attempts[name]}, "
              f"{elapsed:.0f}s): {err}")
        queue.pop(0)
        if attempts[name] < MAX_ATTEMPTS:
            queue.append((name, budget))   # rotate to the back, try others first
        else:
            _note(f"{name}: giving up after {MAX_ATTEMPTS} backend-up failures")
        time.sleep(FAIL_BACKOFF_S)
    _note("queue drained; exiting")


if __name__ == "__main__":
    main()
