"""ONNXModel ResNet-50 inference imgs/sec (BASELINE.md ONNX config): a REAL
torch-exported ResNet-50 graph through the proto codec + converter + jit."""
import json, sys, time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).parent))
sys.path.insert(0, str(Path(__file__).parent.parent))
sys.path.insert(0, str(Path(__file__).parent.parent / "tests"))


def run(jax, platform, n_chips):
    import torch
    from _torch_resnet import export_onnx_bytes, resnet50, resnet_small
    from synapseml_tpu.onnx import convert_graph

    on_tpu = platform == "tpu"
    torch.manual_seed(0)
    model = (resnet50() if on_tpu else resnet_small()).eval()
    S = 224 if on_tpu else 32
    data = export_onnx_bytes(model, torch.zeros(1, 3, S, S))
    conv = convert_graph(data)
    fn = jax.jit(lambda x: conv(input=x)["logits"])
    B = 64 if on_tpu else 8
    x = np.random.default_rng(0).normal(size=(B, 3, S, S)).astype(np.float32)
    np.asarray(fn(x))  # compile
    best = 1e9
    for _ in range(5):
        t0 = time.perf_counter()
        np.asarray(fn(x))
        best = min(best, time.perf_counter() - t0)
    return {"metric": "ONNX ResNet-50 inference" if on_tpu
            else "ONNX resnet-small (CPU smoke)",
            "value": round(B / best, 1), "unit": "imgs/sec",
            "platform": platform, "batch": B, "image": S}


def main():
    from _common import init_jax

    jax, platform, n_chips = init_jax()
    print(json.dumps(run(jax, platform, n_chips)))


if __name__ == "__main__":
    main()
