"""GBDT histogram backend comparison: segment_sum (scatter) vs one-hot
matmul (MXU) on the Higgs-1M shape. The measurement this exists for is the
TPU one — scatter-adds serialize on TPU while the one-hot form is matmul
FLOPs — but it runs anywhere (CPU mode uses a smaller shape). Prints one
JSON line with per-backend train seconds; the winner should become
``histogram_impl``'s default on that platform."""
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).parent))
sys.path.insert(0, str(Path(__file__).parent.parent))


def run(jax, platform, n_chips):
    from synapseml_tpu.gbdt.booster import train_booster

    on_tpu = platform == "tpu"
    # CPU smoke must stay tiny: the one-hot form is matmul FLOPs, which one
    # CPU core grinds through slowly (the MXU is the point)
    N, F = (1_000_000, 28) if on_tpu else (10_000, 28)
    n_iter = 50 if on_tpu else 5
    max_bin = 255 if on_tpu else 63
    rng = np.random.default_rng(0)
    X = rng.normal(size=(N, F)).astype(np.float32)
    w = rng.normal(size=F)
    y = ((X @ w + rng.normal(size=N)) > 0).astype(np.float32)

    times = {}
    for impl in ("segment", "onehot"):
        t0 = time.perf_counter()
        train_booster(X, y, objective="binary", num_iterations=n_iter,
                      learning_rate=0.1, num_leaves=31, max_bin=max_bin,
                      histogram_impl=impl)
        times[impl] = round(time.perf_counter() - t0, 2)

    return {
        "metric": "GBDT histogram backend train time"
                  + ("" if on_tpu else " (CPU smoke)"),
        "value": min(times.values()), "unit": "s", "lower_is_better": True,
        "platform": platform,
        "rows": N, "iters": n_iter,
        "segment_s": times["segment"], "onehot_s": times["onehot"],
        "speedup_onehot": round(times["segment"] / times["onehot"], 2),
        "winner": min(times, key=times.get)}


def main():
    from _common import init_jax

    jax, platform, n_chips = init_jax()
    print(json.dumps(run(jax, platform, n_chips)))


if __name__ == "__main__":
    main()
