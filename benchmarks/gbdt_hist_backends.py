"""GBDT histogram backend comparison on the Higgs-1M shape: segment_sum
(scatter — TPUs serialize it) vs XLA one-hot matmul (MXU FLOPs but the
one-hot operand is materialized in HBM) vs the Pallas fused kernel (one-hot
tiles generated in VMEM, ``gbdt/pallas_hist.py``). The measurement this
exists for is the TPU one, but it runs anywhere (CPU mode uses a smaller
shape and skips the interpret-mode Pallas kernel — interpret timings say
nothing about the chip). Prints one JSON line with per-backend train
seconds; the winner should become ``histogram_impl``'s default on that
platform."""
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).parent))
sys.path.insert(0, str(Path(__file__).parent.parent))


def run(jax, platform, n_chips):
    from synapseml_tpu.gbdt.booster import train_booster

    on_tpu = platform == "tpu"
    # CPU smoke must stay tiny: the one-hot form is matmul FLOPs, which one
    # CPU core grinds through slowly (the MXU is the point)
    N, F = (1_000_000, 28) if on_tpu else (10_000, 28)
    n_iter = 50 if on_tpu else 5
    max_bin = 255 if on_tpu else 63
    rng = np.random.default_rng(0)
    X = rng.normal(size=(N, F)).astype(np.float32)
    w = rng.normal(size=F)
    y = ((X @ w + rng.normal(size=N)) > 0).astype(np.float32)

    times = {}
    impls = ("segment", "onehot", "pallas") if on_tpu else ("segment", "onehot")
    for impl in impls:
        t0 = time.perf_counter()
        train_booster(X, y, objective="binary", num_iterations=n_iter,
                      learning_rate=0.1, num_leaves=31, max_bin=max_bin,
                      histogram_impl=impl)
        times[impl] = round(time.perf_counter() - t0, 2)

    result = {
        "metric": "GBDT histogram backend train time"
                  + ("" if on_tpu else " (CPU smoke)"),
        "value": min(times.values()), "unit": "s", "lower_is_better": True,
        "platform": platform,
        "rows": N, "iters": n_iter,
        "winner": min(times, key=times.get)}
    for impl, t in times.items():
        result[f"{impl}_s"] = t
    return result


def main():
    from _common import init_jax

    jax, platform, n_chips = init_jax()
    print(json.dumps(run(jax, platform, n_chips)))


if __name__ == "__main__":
    main()
