"""Bulk explanation: fused perturbation scoring vs serial, and streamed
``explain_source`` vs one in-memory ``transform``.

Writes a multi-shard synthetic jsonl corpus, builds a linear scorer that
exposes BOTH faces of the rai score-fn protocol (a serial DataFrame
``transform`` and a pure jax array fn), then explains the WHOLE corpus
with ``VectorSHAP`` three ways in the SAME round:

  (a) serial  — ``fused=False``: the seed path, one coalition batch per
                explained row through ``model.transform`` (a DataFrame
                round trip per row — the per-row tax being measured);
  (b) fused   — ``fused=True``: many rows' coalition samples concatenated
                into one ``[B, M]`` array scored through the shared
                ``CompiledCache`` pow-2 ladder under ONE ``rai.fused_score``
                fn_id (compile count <= ladder size, recorded from a COLD
                cache on the first run);
  (c) streamed — ``explain_source`` over ``ShardedSource.jsonl`` +
                ``JsonlSink``: the same fused engine riding the scoring
                plane's exactly-once shard pipeline, files in -> committed
                explanation parts out.

Sampling is content-keyed (``row_rng``), so all three arms must produce
the SAME explanation vectors — parity is asserted at f32 tolerance, and
streamed-vs-in-memory equality is exact row-for-row by id.

Reports explanations/sec per arm: one cold fused run records the compile
count, then min-of-3 warm walls per arm, interleaved (the bulk_scoring
discipline — host-side json work makes single runs noisy). Acceptance bar
(ISSUE 20): fused >= 3x serial explanations/sec at f32 parity, streamed
>= 0.9x in-memory rows/sec, executable count <= ladder size. Prints one
JSON line.
"""
import json
import os
import shutil
import sys
import tempfile
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).parent))
sys.path.insert(0, str(Path(__file__).parent.parent))

N_SHARDS = 6
ROWS_PER_SHARD = 1024
N_FEATURES = 12
NUM_SAMPLES = 128    # coalitions per explained row
BATCH_ROWS = 512     # streamed source batch size
OUT_COLUMNS = ["id", "explanation"]


def _write_corpus(directory: str) -> tuple[int, int]:
    rs = np.random.default_rng(0)
    i, total = 0, 0
    for s in range(N_SHARDS):
        p = os.path.join(directory, f"part-{s:03d}.jsonl")
        with open(p, "w") as f:
            X = rs.normal(size=(ROWS_PER_SHARD, N_FEATURES))
            for j in range(ROWS_PER_SHARD):
                f.write(json.dumps({
                    "features": [round(float(v), 5) for v in X[j]],
                    "id": i}) + "\n")
                i += 1
        total += os.path.getsize(p)
    return i, total


def _make_model():
    """Linear scorer exposing both protocol faces — the serial arm goes
    through ``_transform`` (one DataFrame round trip per coalition batch),
    the fused arm through ``score_fn``'s pure array fn."""
    from synapseml_tpu.core.pipeline import Transformer

    w = np.linspace(-1.0, 1.0, N_FEATURES).astype(np.float32)

    class BenchLinear(Transformer):
        def _transform(self, df):
            def score(p):
                X = np.stack([np.asarray(v, np.float64)
                              for v in p["features"]])
                s = X @ w.astype(np.float64)
                return np.asarray([np.asarray([v]) for v in s])

            return df.with_column("probability", score)

        def score_fn(self):
            return lambda X: (X.astype("float32") @ w)[:, None]

    return BenchLinear()


def _background(data_dir: str):
    """Fixed background frame shared by every arm — a streamed run has no
    'whole dataset' to default to, so the background must be pinned for
    the arms to be comparable (and for phi0 to mean one thing)."""
    from synapseml_tpu.io.files import read_jsonl

    df = read_jsonl(os.path.join(data_dir, "part-000.jsonl"))
    return df.limit(64)


def _explainer(model, fused, bg):
    from synapseml_tpu.explainers import VectorSHAP

    return VectorSHAP(model=model, fused=fused, seed=0,
                      num_samples=NUM_SAMPLES, background_data=bg)


def _cold_cache() -> int:
    from synapseml_tpu.core.batching import (get_compiled_cache,
                                             reset_compiled_cache)
    from synapseml_tpu.rai import FUSED_SCORE_FN_ID

    reset_compiled_cache()
    return get_compiled_cache().miss_count(FUSED_SCORE_FN_ID)


def _run_scoring_path(model, df, bg, n_rows: int, fused: bool,
                      cold: bool = False) -> dict:
    """The fused-vs-serial A/B: same pre-parsed frame, only the
    perturbation-scoring path differs."""
    from synapseml_tpu.core.batching import get_compiled_cache
    from synapseml_tpu.rai import FUSED_SCORE_FN_ID

    misses0 = _cold_cache() if cold else 0
    t0 = time.perf_counter()
    out = _explainer(model, fused, bg).transform(df)
    exps = [np.asarray(v) for v in out.collect_column("explanation")]
    wall = time.perf_counter() - t0
    compiles = int(get_compiled_cache().miss_count(FUSED_SCORE_FN_ID)
                   - misses0) if cold else None
    return {"wall_s": round(wall, 3),
            "explanations_per_sec": round(n_rows / wall, 1),
            "fused_score_compiles": compiles,
            "_exps": np.stack(exps),
            "_ids": np.asarray(df.collect_column("id"))}


def _run_in_memory(model, data_dir: str, bg, out_dir: str,
                   n_rows: int) -> dict:
    """End-to-end in-memory arm: files in -> explained files out, the full
    parse paid before the first explanation (the all-in-RAM baseline the
    streamed arm is measured against)."""
    from synapseml_tpu.core.dataframe import DataFrame
    from synapseml_tpu.io.files import read_jsonl, write_jsonl

    os.makedirs(out_dir, exist_ok=True)
    t0 = time.perf_counter()
    df = read_jsonl(os.path.join(data_dir, "*.jsonl"))
    out = _explainer(model, True, bg).transform(df)
    part = out.collect()
    write_jsonl(DataFrame([{c: part[c] for c in OUT_COLUMNS}]),
                os.path.join(out_dir, "explained.jsonl"))
    wall = time.perf_counter() - t0
    return {"wall_s": round(wall, 3),
            "rows_per_sec": round(n_rows / wall, 1)}


def _run_streamed(model, data_dir: str, bg, out_dir: str) -> dict:
    from synapseml_tpu.data import ShardedSource
    from synapseml_tpu.rai import explain_source
    from synapseml_tpu.scoring import JsonlSink

    src = ShardedSource.jsonl(os.path.join(data_dir, "*.jsonl"))
    sink = JsonlSink(out_dir, columns=OUT_COLUMNS)
    t0 = time.perf_counter()
    report = explain_source(_explainer(model, True, bg), src, sink,
                            batch_rows=BATCH_ROWS)
    wall = time.perf_counter() - t0
    rows = [json.loads(ln) for p in sink.part_files()
            for ln in open(p) if ln.strip()]
    return {"wall_s": round(wall, 3),
            "rows_per_sec": round(report.rows_written / max(wall, 1e-9), 1),
            "rows_written": report.rows_written,
            "shards": report.shards_done,
            "complete": report.complete,
            "_exps": {r["id"]: np.asarray(r["explanation"]) for r in rows}}


def run(jax, platform, n_chips):
    from synapseml_tpu.core.batching import default_bucketer
    from synapseml_tpu.io.files import read_jsonl
    from synapseml_tpu.rai import MAX_FUSED_ROWS

    directory = tempfile.mkdtemp(prefix="synapseml_explainbulk_")
    try:
        data_dir = os.path.join(directory, "data")
        os.makedirs(data_dir)
        n_rows, n_bytes = _write_corpus(data_dir)
        model = _make_model()
        bg = _background(data_dir)
        df = read_jsonl(os.path.join(data_dir, "*.jsonl"))
        ladder = len(default_bucketer().buckets_upto(MAX_FUSED_ROWS))

        # one cold fused run: the compile-count-vs-ladder record
        cold = _run_scoring_path(model, df, bg, n_rows, fused=True,
                                 cold=True)
        # then min-of-3 WARM walls per arm, arms interleaved so a load
        # spike on the shared box can't bias one side
        serial = fused = in_mem = streamed = None
        for t in range(3):
            se = _run_scoring_path(model, df, bg, n_rows, fused=False)
            fu = _run_scoring_path(model, df, bg, n_rows, fused=True)
            im = _run_in_memory(model, data_dir, bg,
                                os.path.join(directory, f"out_mem{t}"),
                                n_rows)
            st = _run_streamed(model, data_dir, bg,
                               os.path.join(directory, f"out_stream{t}"))
            if serial is None or se["wall_s"] < serial["wall_s"]:
                serial = se
            if fused is None or fu["wall_s"] < fused["wall_s"]:
                fused = fu
            if in_mem is None or im["wall_s"] < in_mem["wall_s"]:
                in_mem = im
            if streamed is None or st["wall_s"] < streamed["wall_s"]:
                streamed = st
        fused["fused_score_compiles"] = cold["fused_score_compiles"]
        fused["cold_wall_s"] = cold["wall_s"]

        f_exp, s_exp = fused.pop("_exps"), serial.pop("_exps")
        ids = fused.pop("_ids")
        serial.pop("_ids")
        cold.pop("_exps"), cold.pop("_ids")
        parity = bool(np.allclose(f_exp, s_exp, rtol=1e-4, atol=1e-5))
        by_id = streamed.pop("_exps")
        streamed_equal = (len(by_id) == n_rows and all(
            np.allclose(by_id[int(i)], f_exp[k], rtol=1e-6, atol=1e-7)
            for k, i in enumerate(ids)))
    finally:
        shutil.rmtree(directory, ignore_errors=True)
    in_memory_rps = in_mem["rows_per_sec"]
    return {
        "metric": "bulk explanation fused explanations/sec "
                  "(fused perturbation engine vs serial per-row transform)",
        "value": fused["explanations_per_sec"], "unit": "explanations/sec",
        "lower_is_better": False, "platform": platform,
        "dataset_rows": n_rows, "dataset_bytes": n_bytes,
        "num_samples": NUM_SAMPLES,
        "fused": fused, "serial_baseline": serial,
        "in_memory_baseline": in_mem, "streamed": streamed,
        "fused_vs_serial": round(
            fused["explanations_per_sec"] / serial["explanations_per_sec"], 3)
        if serial["explanations_per_sec"] else None,
        "streamed_vs_in_memory": round(
            streamed["rows_per_sec"] / in_memory_rps, 3)
        if in_memory_rps else None,
        "ladder_bound": ladder,
        "compile_count_within_ladder":
            fused["fused_score_compiles"] <= ladder,
        "fused_serial_parity_f32": parity,
        "streamed_equals_in_memory": bool(streamed_equal),
    }


def main():
    from _common import init_jax

    jax, platform, n_chips = init_jax()
    print(json.dumps(run(jax, platform, n_chips)))


if __name__ == "__main__":
    main()
