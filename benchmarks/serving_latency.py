"""Serving-plane latency: request->reply p50/p99 + concurrency sweep.

Reference claim: "sub-millisecond latency" for the serving plane
(``docs/Deploy Models/Overview.md:151-155``). Measures, over PERSISTENT
client connections (HTTP/1.1 keep-alive, like any real serving client):

  (a) direct     — one ``serve_pipeline`` worker hit directly;
  (b) routed     — RoutingFront -> worker (one proxy hop, pooled
                   keep-alive worker connections);
  (c) client-routed — ``RoutingClient`` direct-to-worker via the /routes
                   table (serve-where-it-lands: zero proxy hops).

Each path also gets a 1/8/32-client concurrency sweep (p50/p99 across all
requests + aggregate throughput). Prints one JSON line.
"""
import http.client
import json
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))
sys.path.insert(0, str(Path(__file__).parent.parent))

BODY = json.dumps({"x": 1}).encode()


def _worker_loop(host: str, port: int, n: int, warmup: int, out: list):
    import socket

    conn = http.client.HTTPConnection(host, port, timeout=30)
    conn.connect()
    conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    lat = []
    for i in range(n + warmup):
        t0 = time.perf_counter()
        conn.request("POST", "/", body=BODY)
        r = conn.getresponse()
        r.read()
        if i >= warmup:
            lat.append((time.perf_counter() - t0) * 1e3)
    conn.close()
    out.append(lat)


def _bench(address: str, n: int = 400, warmup: int = 40,
           clients: int = 1) -> dict:
    host, port = address.split("//")[1].split(":")
    per_client = max(n // clients, 50)
    outs: list = []
    t0 = time.perf_counter()
    threads = [threading.Thread(target=_worker_loop,
                                args=(host, int(port), per_client, warmup, outs))
               for _ in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    lat = sorted(x for l in outs for x in l)
    total = len(lat)
    return {"p50_ms": round(lat[total // 2], 3),
            "p99_ms": round(lat[int(total * 0.99)], 3),
            "rps": round(total / wall), "n": total, "clients": clients}


def _client_routed_bench(client, n: int = 400, warmup: int = 40) -> dict:
    lat = []
    for i in range(n + warmup):
        t0 = time.perf_counter()
        status, _ = client.request("/", body=BODY)
        assert status == 200, status
        if i >= warmup:
            lat.append((time.perf_counter() - t0) * 1e3)
    lat.sort()
    return {"p50_ms": round(lat[len(lat) // 2], 3),
            "p99_ms": round(lat[int(len(lat) * 0.99)], 3), "n": n}


def run(jax, platform, n_chips):
    from _common import EchoT

    from synapseml_tpu.io.distributed_serving import (RoutingClient,
                                                      serve_pipeline_distributed)
    from synapseml_tpu.io.serving import serve_pipeline

    srv = serve_pipeline(EchoT(), batch_interval_ms=0, num_threads=2)
    direct = _bench(srv.address)
    srv.stop()

    handle = serve_pipeline_distributed(EchoT(), num_workers=2,
                                        batch_interval_ms=0)
    try:
        routed = _bench(handle.address)
        sweep = {str(c): _bench(handle.address, n=400, clients=c)
                 for c in (1, 8, 32)}
        client = RoutingClient(front_address=handle.address)
        client_routed = _client_routed_bench(client)
        client.close()
    finally:
        handle.stop()

    return {"metric": "serving latency (trivial pipeline)",
            "value": routed["p50_ms"], "unit": "ms",
            "platform": "cpu host (latency is host-side)",
            "direct": direct, "routed_2_workers": routed,
            "client_routed_2_workers": client_routed,
            "routed_concurrency_sweep": sweep,
            "reference_claim": "sub-millisecond (Overview.md:151)"}


def main():
    from _common import init_jax

    jax, platform, n_chips = init_jax()
    print(json.dumps(run(jax, platform, n_chips)))


if __name__ == "__main__":
    main()
