"""Serving-plane latency: request->reply p50/p99 for a trivial pipeline.

Reference claim: "sub-millisecond latency" for the serving plane
(``docs/Deploy Models/Overview.md:151-155``). Measures (a) a single
``serve_pipeline`` worker hit directly and (b) the distributed plane
(RoutingFront -> worker) which adds one proxy hop. Prints one JSON line.
"""
import json
import sys
import time
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))
sys.path.insert(0, str(Path(__file__).parent.parent))




def _bench(address: str, n: int = 400, warmup: int = 40) -> dict:
    lat = []
    body = json.dumps({"x": 1}).encode()
    for i in range(n + warmup):
        t0 = time.perf_counter()
        req = urllib.request.Request(address, data=body, method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            r.read()
        if i >= warmup:
            lat.append((time.perf_counter() - t0) * 1e3)
    lat.sort()
    return {"p50_ms": round(lat[len(lat) // 2], 3),
            "p99_ms": round(lat[int(len(lat) * 0.99)], 3),
            "n": n}


def main():
    from _common import EchoT, init_jax

    init_jax()
    from synapseml_tpu.io.distributed_serving import serve_pipeline_distributed
    from synapseml_tpu.io.serving import serve_pipeline

    srv = serve_pipeline(EchoT(), batch_interval_ms=0)
    direct = _bench(srv.address)
    srv.stop()

    handle = serve_pipeline_distributed(EchoT(), num_workers=2,
                                        batch_interval_ms=0)
    try:
        routed = _bench(handle.address)
    finally:
        handle.stop()

    print(json.dumps({"metric": "serving latency (trivial pipeline)",
                      "direct": direct, "routed_2_workers": routed,
                      "unit": "ms",
                      "reference_claim": "sub-millisecond (Overview.md:151)"}))


main()
