"""Serving-plane latency: request->reply p50/p99 + concurrency sweep.

Reference claim: "sub-millisecond latency" for the serving plane
(``docs/Deploy Models/Overview.md:151-155``). Measures, over PERSISTENT
client connections (HTTP/1.1 keep-alive, like any real serving client):

  (a) direct     — one ``serve_pipeline`` worker hit directly;
  (b) routed     — RoutingFront -> worker (one proxy hop, pooled
                   keep-alive worker connections);
  (c) client-routed — ``RoutingClient`` direct-to-worker via the /routes
                   table (serve-where-it-lands: zero proxy hops).

Each path also gets a 1/8/32-client concurrency sweep (p50/p99 across all
requests + aggregate throughput). Prints one JSON line.
"""
import http.client
import json
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))
sys.path.insert(0, str(Path(__file__).parent.parent))

BODY = json.dumps({"x": 1}).encode()


def _http_requester(address: str, body: bytes):
    """Request callable over one persistent keep-alive+NODELAY connection."""
    import socket

    host, port = address.split("//")[1].split(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=30)
    conn.connect()
    conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def request():
        conn.request("POST", "/", body=body)
        r = conn.getresponse()
        payload = r.read()
        assert r.status == 200, (r.status, payload[:200])

    request.close = conn.close
    return request


def _routing_requester(front_address: str, body: bytes):
    """Request callable via a per-thread RoutingClient (direct worker hits)."""
    from synapseml_tpu.io.distributed_serving import RoutingClient

    client = RoutingClient(front_address=front_address)

    def request():
        status, payload = client.request("/", body=body)
        assert status == 200, (status, str(payload)[:200])

    request.close = client.close
    return request


def _fanout(make_requester, n: int = 400, warmup: int = 40,
            clients: int = 1) -> dict:
    """The one measurement harness: `clients` threads, each with its own
    requester; warmup excluded from BOTH latency samples and the wall
    clock; thread failures propagate instead of silently thinning data."""
    per_client = max(n // clients, 50)
    outs: list = []
    errors: list = []
    ready = threading.Barrier(clients)

    def loop():
        try:
            request = make_requester()
            for _ in range(warmup):
                request()
            ready.wait()  # synchronized post-warmup start = honest wall
            start = time.perf_counter()
            lat = []
            for _ in range(per_client):
                t0 = time.perf_counter()
                request()
                lat.append((time.perf_counter() - t0) * 1e3)
            request.close()
            outs.append((start, lat, time.perf_counter()))
        except Exception as e:  # noqa: BLE001 — re-raised after join
            errors.append(e)
            try:
                ready.abort()
            except Exception:  # noqa: BLE001
                pass

    threads = [threading.Thread(target=loop) for _ in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise RuntimeError(f"{len(errors)}/{clients} bench clients failed: "
                           f"{errors[0]!r}") from errors[0]
    wall = max(o[2] for o in outs) - min(o[0] for o in outs)
    lat = sorted(x for _, l, _ in outs for x in l)
    total = len(lat)
    return {"p50_ms": round(lat[total // 2], 3),
            "p99_ms": round(lat[int(total * 0.99)], 3),
            "rps": round(total / wall), "n": total, "clients": clients}


def _bench(address: str, n: int = 400, warmup: int = 40, clients: int = 1,
           body: bytes = BODY) -> dict:
    return _fanout(lambda: _http_requester(address, body), n, warmup, clients)


def run(jax, platform, n_chips):
    from _common import EchoT, GBDTScorerT, train_tiny_gbdt

    from synapseml_tpu.io.distributed_serving import serve_pipeline_distributed
    from synapseml_tpu.io.serving import serve_pipeline

    srv = serve_pipeline(EchoT(), batch_interval_ms=0, num_threads=2)
    direct = _bench(srv.address)
    srv.stop()

    handle = serve_pipeline_distributed(EchoT(), num_workers=2,
                                        batch_interval_ms=0)
    try:
        routed = _bench(handle.address)
        sweep = {str(c): _bench(handle.address, n=400, clients=c)
                 for c in (1, 8, 32)}
        client_routed = _fanout(
            lambda: _routing_requester(handle.address, BODY), n=400)
    finally:
        handle.stop()

    # multi-worker SCALING curve (VERDICT r4 weak-#7): fixed 16-client load,
    # client-routed (per-thread RoutingClient, workers hit directly -- no
    # proxy serialization point), throughput vs worker count
    scaling = {}
    for workers in (1, 2, 4):
        h = serve_pipeline_distributed(EchoT(), num_workers=workers,
                                       batch_interval_ms=0)
        try:
            scaling[str(workers)] = _fanout(
                lambda h=h: _routing_requester(h.address, BODY),
                n=16 * 120, warmup=15, clients=16)
        finally:
            h.stop()

    # MODEL-BACKED pipeline: a fitted GBDT scoring each request -- the
    # latency number a real deployment sees, not the echo floor
    model_body = json.dumps({"features": [0.1] * 8}).encode()
    model_srv = serve_pipeline(GBDTScorerT(train_tiny_gbdt()),
                               batch_interval_ms=0, num_threads=2)
    try:
        model_1 = _bench(model_srv.address, n=300, body=model_body)
        model_8 = _bench(model_srv.address, n=300, clients=8,
                         body=model_body)
    finally:
        model_srv.stop()

    return {"metric": "serving latency (trivial pipeline)",
            "value": routed["p50_ms"], "unit": "ms",
            "platform": "cpu host (latency is host-side)",
            "direct": direct, "routed_2_workers": routed,
            "client_routed_2_workers": client_routed,
            "routed_concurrency_sweep": sweep,
            "worker_scaling_16_clients": scaling,
            "gbdt_backed_direct": {"1": model_1, "8": model_8},
            "reference_claim": "sub-millisecond (Overview.md:151)"}


def main():
    from _common import init_jax

    jax, platform, n_chips = init_jax()
    print(json.dumps(run(jax, platform, n_chips)))


if __name__ == "__main__":
    main()
