"""Data pipeline: eager ``fit_arrays`` vs streamed ``fit_source`` A/B.

Writes a multi-shard synthetic jsonl dataset (the stand-in for a >RAM-quota
corpus — the streamed path's memory stays O(shard) no matter how large this
is scaled), then trains the same MLP for the same number of optimizer steps
two ways in the SAME round:

  (a) eager    — ``io.files.read_jsonl`` materializes every row, then
                 ``fit_arrays`` (which itself now rides the data plane over
                 a MemorySource) — the all-in-RAM baseline, and it pays the
                 full parse up front;
  (b) streamed — ``ShardedSource.jsonl`` + ``DataLoader`` feeding
                 ``Trainer.fit`` directly: shard reads overlap training in
                 the background prefetcher.

Reports rows/sec for both, plus the streamed path's prefetch-queue mean
occupancy and step-time stall fraction (the share of wall time the train
loop spent blocked on the queue — the number arXiv:1810.11112 says caps
scaling). Acceptance bar: streamed end-to-end throughput within ~25% of
eager on an in-RAM dataset (the streamed path's advantage only appears once
the dataset can't be materialized — this guards the overhead). Prints one
JSON line.
"""
import json
import os
import shutil
import sys
import tempfile
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).parent))
sys.path.insert(0, str(Path(__file__).parent.parent))

N_SHARDS = 8
ROWS_PER_SHARD = 4096
N_FEATURES = 16
BATCH = 256
STEPS = 96
SCAN_CHUNK = 4


def _write_dataset(directory: str) -> tuple[int, int]:
    rs = np.random.default_rng(0)
    w = rs.normal(size=N_FEATURES)
    total = 0
    for i in range(N_SHARDS):
        with open(os.path.join(directory, f"part-{i:03d}.jsonl"), "w") as f:
            X = rs.normal(size=(ROWS_PER_SHARD, N_FEATURES)).astype(np.float32)
            y = (X @ w > 0).astype(int)
            for j in range(ROWS_PER_SHARD):
                f.write(json.dumps({"x": [round(float(v), 5) for v in X[j]],
                                    "labels": int(y[j])}) + "\n")
        total += os.path.getsize(os.path.join(directory, f"part-{i:03d}.jsonl"))
    return N_SHARDS * ROWS_PER_SHARD, total


def _mlp():
    import flax.linen as nn

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(2)(nn.relu(nn.Dense(64)(x)))

    return MLP()


def _trainer():
    from synapseml_tpu.models.trainer import Trainer, TrainerConfig
    from synapseml_tpu.parallel.mesh import MeshConfig, create_mesh

    mesh = create_mesh(MeshConfig())
    return Trainer(_mlp(), mesh, TrainerConfig(total_steps=STEPS))


def _run_eager(directory: str) -> dict:
    from synapseml_tpu.io.files import read_jsonl
    from synapseml_tpu.models.trainer import fit_arrays

    t0 = time.perf_counter()
    df = read_jsonl(os.path.join(directory, "*.jsonl"))
    data = {"x": np.stack(df.collect_column("x")).astype(np.float32),
            "labels": df.collect_column("labels").astype(np.int32)}
    load_s = time.perf_counter() - t0
    trainer = _trainer()
    t1 = time.perf_counter()
    state = fit_arrays(trainer, data, batch_size=BATCH, total_steps=STEPS,
                       seed=0, scan_chunk=SCAN_CHUNK)
    train_s = time.perf_counter() - t1
    wall = time.perf_counter() - t0
    rows = STEPS * BATCH
    return {"wall_s": round(wall, 3), "load_s": round(load_s, 3),
            "train_s": round(train_s, 3),
            "rows_per_sec": round(rows / wall, 1), "steps": int(state.step)}


def _run_streamed(directory: str) -> dict:
    import jax

    from synapseml_tpu.data import DataLoader, ShardedSource

    trainer = _trainer()
    src = ShardedSource.jsonl(os.path.join(directory, "*.jsonl"))
    t0 = time.perf_counter()
    loader = DataLoader(src, BATCH, seed=0, columns=["x", "labels"],
                        multiple_of=trainer.mesh.data_parallel_size(),
                        host_index=0, host_count=1)
    it = iter(loader)
    first = next(it)
    state = trainer.init_state(first, jax.random.PRNGKey(0))

    def chain():
        yield first
        yield from it

    state = trainer.fit(state, chain(), max_steps=STEPS,
                        scan_chunk=SCAN_CHUNK)
    wall = time.perf_counter() - t0
    stats = loader.stats()
    loader.close()
    rows = STEPS * BATCH
    return {"wall_s": round(wall, 3), "rows_per_sec": round(rows / wall, 1),
            "steps": int(state.step),
            "stall_fraction": round(stats["stall_fraction"], 4),
            "prefetch_wait_s": round(stats["wait_s_total"], 3),
            "mean_queue_occupancy": round(stats["mean_queue_occupancy"], 3),
            "shards": src.num_shards}


def run(jax, platform, n_chips):
    directory = tempfile.mkdtemp(prefix="synapseml_datapipe_")
    try:
        n_rows, n_bytes = _write_dataset(directory)
        eager = _run_eager(directory)
        streamed = _run_streamed(directory)
    finally:
        shutil.rmtree(directory, ignore_errors=True)
    return {
        "metric": "data pipeline streamed rows/sec (fit_source vs fit_arrays)",
        "value": streamed["rows_per_sec"], "unit": "rows/sec",
        "lower_is_better": False, "platform": platform,
        "dataset_rows": n_rows, "dataset_bytes": n_bytes,
        "streamed": streamed, "eager_baseline": eager,
        "throughput_vs_eager": round(streamed["rows_per_sec"]
                                     / eager["rows_per_sec"], 3)
        if eager["rows_per_sec"] else None,
    }


def main():
    from _common import init_jax

    jax, platform, n_chips = init_jax()
    print(json.dumps(run(jax, platform, n_chips)))


if __name__ == "__main__":
    main()
