"""Fleet elasticity A/B: step-function load against static vs elastic fleets.

The ISSUE-13 acceptance measurement: one registry artifact (ONNX MLP +
a fixed per-row cost stage, published ONCE with its AOT executable ladder),
served through two fleets in the SAME round under the SAME 1x -> 8x -> 1x
closed-loop client step load:

  (a) static  — 3 subprocess workers, fixed (provisioned for the mean);
  (b) elastic — FleetAutoscaler over a SubprocessWorkerLauncher,
      min=1 max=8, reconciling on worker queue depth + routed p95; every
      scale-up worker ``/admin/load``s the registry ref with ``use_aot``
      so its first batch serves from precompiled executables.

Reported per arm: SLO-violation seconds (1-second windows whose p95
exceeds the SLO calibrated off a single-worker baseline), worker-seconds
(the cost integral — the autoscaler's own accounting for the elastic arm,
workers x wall for the static one), request outcome counts, and for the
elastic arm the scale-event trace plus every worker's swap breakdown.

Gates: elastic SLO-violation seconds STRICTLY below static at <= static
worker-seconds, zero client errors in both arms, and every elastic
worker's swap traced ZERO new executables (``executables_traced == 0`` —
the PR-9 AOT hit counters stay flat through scale-up). All worker
subprocesses force ``JAX_PLATFORMS=cpu`` so publish/load fingerprints
match regardless of the parent backend; the orchestration (front,
autoscaler, clients) is host-side python. Prints one JSON line.
"""
import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).parent))
sys.path.insert(0, str(Path(__file__).parent.parent))
sys.path.insert(0, str(Path(__file__).parent.parent / "tests"))

from synapseml_tpu.core.params import Param, TypeConverters  # noqa: E402
from synapseml_tpu.core.pipeline import PipelineModel, Transformer  # noqa: E402

BUCKETS = [2, 4, 8, 16, 32, 64]
DIN = 4
WORK_MS_PER_ROW = 10.0     # the fixed per-row serving cost (GIL-released)
PHASE_1X_S = 40.0          # lead/tail phases at baseline load
PHASE_8X_S = 20.0          # the step: 8x the client concurrency
CLIENTS_1X = 3
CLIENTS_8X = 24
STATIC_WORKERS = 3         # provisioned for the mean, as a static fleet is
ELASTIC_MIN, ELASTIC_MAX = 1, 8
# worker serve knobs: batches cap at 4 rows so SERVICE time stays bounded
# (a pow-2 rung of 8+ sleepy rows would cost 80+ ms and blur the arms) —
# latency then tracks per-worker queueing, which is what elasticity fixes
SERVE_KWARGS = {"batch_interval_ms": 2, "max_batch_rows": 4,
                "bucket_ladder": [1, 2, 4]}
# Closed-loop equilibrium latency ~ (in-flight per worker) x work_ms, and
# the per-row cost is sleep-dominated (machine-independent), so the SLO is
# a CONSTANT between the 8x-phase equilibria of the two fleets:
#   static-3:  24/3 = 8 in flight x 10 ms  ~ 80-130 ms   (violates)
#   elastic-8: 24/8 = 3 in flight x 10 ms  ~ 30-60 ms    (meets)
#   1x phases:  3/1 = 3 in flight x 10 ms  ~ 30-60 ms    (meets on ONE)
SLO_MS = 80.0


class ThrottleStage(Transformer):
    """A deterministic per-row serving cost: sleeps ``work_ms`` per row of
    each batch (releasing the GIL — the stand-in for a model whose per-row
    compute is real). Makes per-worker capacity ~1000/work_ms rows/sec, so
    the 8x client step genuinely saturates a small fleet."""

    work_ms = Param("work_ms", "sleep per row (ms)", default=WORK_MS_PER_ROW,
                    converter=TypeConverters.to_float)

    def _transform(self, df):
        ms = float(self.get("work_ms"))

        def per_part(p):
            time.sleep(ms * len(p["id"]) / 1000.0)
            return p

        return df.map_partitions(per_part)


def build_fleet_pipeline(seed=0):
    from _aot_pipeline import BodyToFeatures, PredToReply, make_mlp_onnx

    return PipelineModel(stages=[
        BodyToFeatures(din=DIN),
        make_mlp_onnx(din=DIN, seed=seed, mini_batch_size=BUCKETS[-1]),
        ThrottleStage(),
        PredToReply(),
    ])


def sample_rows(n=4, seed=7):
    rs = np.random.default_rng(seed)
    return [{"features": [round(float(x), 6) for x in rs.normal(size=DIN)]}
            for _ in range(n)]


def publish_driver(store: str) -> None:
    """Grandchild (forced CPU): publish the pipeline with its AOT ladder."""
    from synapseml_tpu.registry import ModelRegistry

    t0 = time.perf_counter()
    ModelRegistry(store).publish(
        "fleet-mlp", build_fleet_pipeline(), version="v1",
        aot={"rows": sample_rows(), "buckets": BUCKETS})
    print(json.dumps({"publish_s": round(time.perf_counter() - t0, 2)}))


# ---------------------------------------------------------------------------
# load generation + SLO accounting
# ---------------------------------------------------------------------------

class _LoadRecorder:
    def __init__(self):
        self.samples: list[tuple[float, float]] = []  # (t_done, latency_ms)
        self.errors = 0
        self.lock = threading.Lock()

    def violation_seconds(self, slo_ms: float) -> int:
        """1-second windows whose p95 exceeded the SLO."""
        if not self.samples:
            return 0
        t0 = min(t for t, _ in self.samples)
        windows: dict[int, list] = {}
        for t, lat in self.samples:
            windows.setdefault(int(t - t0), []).append(lat)
        bad = 0
        for lats in windows.values():
            lats.sort()
            if lats[min(len(lats) - 1, int(len(lats) * 0.95))] > slo_ms:
                bad += 1
        return bad

    def p95(self) -> float | None:
        lats = sorted(lat for _, lat in self.samples)
        if not lats:
            return None
        return lats[min(len(lats) - 1, int(len(lats) * 0.95))]


def _fire_phase(url: str, body: bytes, clients: int, duration_s: float,
                rec: _LoadRecorder) -> None:
    """Closed-loop clients for one phase (each sends, waits, repeats)."""
    import http.client
    import socket
    import urllib.parse

    stop = threading.Event()

    def client():
        parsed = urllib.parse.urlsplit(url)
        conn = None
        while not stop.is_set():
            try:
                if conn is None:
                    conn = http.client.HTTPConnection(
                        parsed.hostname, parsed.port, timeout=30)
                    conn.connect()
                    conn.sock.setsockopt(socket.IPPROTO_TCP,
                                         socket.TCP_NODELAY, 1)
                t0 = time.perf_counter()
                conn.request("POST", parsed.path, body=body)
                r = conn.getresponse()
                r.read()
                lat_ms = (time.perf_counter() - t0) * 1e3
                with rec.lock:
                    if r.status == 200:
                        rec.samples.append((time.monotonic(), lat_ms))
                    else:
                        rec.errors += 1
            except OSError:
                with rec.lock:
                    rec.errors += 1
                if conn is not None:
                    conn.close()
                    conn = None
                time.sleep(0.05)
        if conn is not None:
            conn.close()

    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(clients)]
    for th in threads:
        th.start()
    time.sleep(duration_s)
    stop.set()
    for th in threads:
        th.join(timeout=10)


def _collect_swap_reports(wreg) -> list[dict]:
    import urllib.request

    reports = []
    for w in wreg.workers():
        try:
            with urllib.request.urlopen(
                    f"http://{w['host']}:{w['port']}/admin/stats",
                    timeout=5) as r:
                stats = json.loads(r.read())
            reports.append({"pid": w.get("pid"), "swap": stats.get("swap")})
        except OSError:
            continue
    return reports


def _run_arm(store: str, elastic: bool, slo_ms: float | None) -> dict:
    from synapseml_tpu.fleet import (FleetAutoscaler, FleetSpec, ModelSLO,
                                     SubprocessWorkerLauncher)
    from synapseml_tpu.io.distributed_serving import (RoutingFront,
                                                      WorkerRegistry)

    tests_dir = str(Path(__file__).parent.parent / "tests")
    bench_dir = str(Path(__file__).parent)
    wreg = WorkerRegistry()
    slo = ModelSLO(
        model="fleet-mlp", ref="v1",
        min_workers=ELASTIC_MIN if elastic else STATIC_WORKERS,
        max_workers=ELASTIC_MAX if elastic else STATIC_WORKERS,
        target_queue_depth=3.0, p95_slo_ms=slo_ms,
        scale_down_after=2, up_cooldown_s=1.0, down_cooldown_s=1.0,
        serve=dict(SERVE_KWARGS))
    spec = FleetSpec(models=[slo], reconcile_interval_s=0.5)
    launcher = SubprocessWorkerLauncher(
        store, wreg, use_aot=True,
        extra_sys_path=(tests_dir, bench_dir))
    front = RoutingFront(registry=wreg, timeout_s=30.0)
    asc = FleetAutoscaler(spec, launcher, front=front, worker_registry=wreg)
    rec = _LoadRecorder()
    t_start = time.monotonic()
    try:
        asc.reconcile_once()
        asc.wait_ready("fleet-mlp", slo.min_workers, timeout_s=120)
        asc.start()
        body = json.dumps(sample_rows(1, seed=42)[0]).encode()
        url = front.address + "/m/fleet-mlp"
        peak = {"workers": slo.min_workers}

        def watch_peak():
            while not watch_stop.is_set():
                peak["workers"] = max(peak["workers"],
                                      asc.actual("fleet-mlp"))
                time.sleep(0.25)

        watch_stop = threading.Event()
        watcher = threading.Thread(target=watch_peak, daemon=True)
        watcher.start()
        _fire_phase(url, body, CLIENTS_1X, PHASE_1X_S, rec)
        _fire_phase(url, body, CLIENTS_8X, PHASE_8X_S, rec)
        swap_reports = _collect_swap_reports(wreg)  # while peak fleet lives
        _fire_phase(url, body, CLIENTS_1X, PHASE_1X_S, rec)
        watch_stop.set()
        watcher.join(timeout=5)
        wall_s = time.monotonic() - t_start
        asc.reconcile_once()  # final worker-seconds integration tick
        if elastic:
            worker_seconds = asc.worker_seconds["fleet-mlp"]
        else:
            worker_seconds = STATIC_WORKERS * wall_s
        events = [{k: (round(v, 2) if isinstance(v, float) else v)
                   for k, v in e.items()}
                  for e in asc.events if e["event"] in
                  ("up", "down", "lost", "spawn", "drain", "drained")]
        return {
            "arm": "elastic" if elastic else "static",
            "wall_s": round(wall_s, 1),
            "requests": len(rec.samples),
            "client_errors": rec.errors,
            "p95_ms": round(rec.p95() or 0.0, 2),
            "slo_ms": round(slo_ms, 2) if slo_ms else None,
            "slo_violation_s": (rec.violation_seconds(slo_ms)
                                if slo_ms else None),
            "worker_seconds": round(worker_seconds, 1),
            "peak_workers": peak["workers"],
            "scale_events": events if elastic else [],
            "swap_reports": swap_reports,
            "recorder": rec,
        }
    finally:
        asc.stop()
        front.close()
        wreg.close()


def _grandchild_publish(store: str, timeout_s: float = 420) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    bench_dir = str(Path(__file__).parent)
    repo = str(Path(__file__).parent.parent)
    tests_dir = str(Path(__file__).parent.parent / "tests")
    code = ("import sys; "
            f"[sys.path.insert(0, p) for p in [{tests_dir!r}, {repo!r}, "
            f"{bench_dir!r}]]; "
            f"import fleet_elastic as fe; fe.publish_driver({store!r})")
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True,
                          timeout=timeout_s, env=env)
    if proc.returncode != 0:
        raise RuntimeError(f"publish grandchild failed:\n"
                           f"{proc.stderr[-3000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def run(jax, platform, n_chips):
    directory = tempfile.mkdtemp(prefix="synapseml_fleet_elastic_")
    store = os.path.join(directory, "store")
    try:
        pub = _grandchild_publish(store)
        slo_ms = SLO_MS
        static = _run_arm(store, elastic=False, slo_ms=slo_ms)
        elastic = _run_arm(store, elastic=True, slo_ms=slo_ms)
        static.pop("recorder")
        elastic.pop("recorder")
        # the zero-new-traces gate: every elastic worker's swap mapped in
        # AOT executables and traced NOTHING
        swaps = [r["swap"] for r in elastic["swap_reports"]
                 if r.get("swap")]
        aot_zero_traces = bool(swaps) and all(
            s.get("mode") == "aot" and s.get("executables_traced") == 0
            for s in swaps)
        result = {
            "metric": "fleet-elastic SLO-violation seconds (elastic fleet, "
                      "1x->8x->1x step load)",
            "value": float(elastic["slo_violation_s"]),
            "unit": "s", "lower_is_better": True,
            # the load is host-driven; the workers force CPU so the AOT
            # fingerprints match — an honest CPU A/B either way
            "platform": "cpu host (fleet orchestration is host-side)",
            "publish_s": pub["publish_s"],
            "slo_ms": round(slo_ms, 2),
            "static": static,
            "elastic": elastic,
            "violation_s_vs_static": (
                round(elastic["slo_violation_s"]
                      / static["slo_violation_s"], 3)
                if static["slo_violation_s"] else None),
            "worker_seconds_vs_static": round(
                elastic["worker_seconds"] / static["worker_seconds"], 3),
            "aot_zero_traces": aot_zero_traces,
            "bars": {
                "elastic_fewer_violation_s": elastic["slo_violation_s"]
                < static["slo_violation_s"],
                "elastic_leq_worker_seconds": elastic["worker_seconds"]
                <= static["worker_seconds"],
                "aot_zero_traces": aot_zero_traces,
                # < 0.1% transport errors per arm (keep-alive reconnects on
                # a loaded loopback are noise, not drops — every request
                # still ends terminally)
                "client_error_rate_ok": all(
                    arm["client_errors"]
                    <= max(1, arm["requests"] // 1000)
                    for arm in (static, elastic)),
            },
        }
        return result
    finally:
        shutil.rmtree(directory, ignore_errors=True)


def main():
    from _common import init_jax

    jax, platform, n_chips = init_jax()
    print(json.dumps(run(jax, platform, n_chips)))


if __name__ == "__main__":
    main()
