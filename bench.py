"""Benchmark rotation over NINE configs: the five BASELINE.md targets, two
TPU-only decision benches, and the host-side serving-microbatch and
data-pipeline A/Bs.

Prints one JSON line per config — flagship (BERT-base fine-tune) LAST so a
single-line consumer parses the flagship metric — and exits 0 regardless of
TPU-relay state. Configs: ONNX ResNet-50, Llama decode, Higgs-1M GBDT,
histogram-backend decision, attention-backend decision, serving-microbatch
(continuous batching vs fixed-timeout, same round), data-pipeline (streamed
fit_source vs eager fit_arrays, same round), flagship BERT,
ViT-B/16 (BASELINE.md:23-29; measurement order rationale at CONFIGS). The
summed TPU deadlines intentionally exceed GLOBAL_BUDGET_S — late configs
are truncated by design when earlier ones consume a healthy window. Any
TPU (non-smoke) result is seeded into PERF_BASELINE.json so one healthy
relay window captures driver-recorded chip numbers, not just the flagship.

Method: K optimizer steps run on-device inside one lax.scan dispatch
(Trainer.train_steps_scan), so host/tunnel round-trip latency is excluded by
subtracting the fetch latency of a trivial jitted function (measured on the
same path); only one scan program is compiled (the remote-compile relay is
flaky under many compilations).

Hang-proofing (rounds 1+2 both failed to emit a JSON line — r01 raised on
UNAVAILABLE, r02 hung inside jax.devices() until the driver's rc=124 kill):
the parent process never imports jax. The measurement runs in a CHILD process
with two staged deadlines — the backend must come up within BACKEND_UP_TIMEOUT_S
(a hung relay is detected early), and the result must arrive within the
child's total budget. Fast transient failures (the relay raising UNAVAILABLE,
the round-1 mode) are retried with backoff; a hang (the round-2 mode) is
killed at the deadline and demoted to a CPU child. Note JAX_PLATFORMS=cpu env
alone is ignored here — sitecustomize pins the tunnel backend at interpreter
boot — so the CPU child forces jax.config.update("jax_platforms", "cpu")
in-process. If every child dies, the parent still prints a JSON line.

The reference publishes no hardware numbers for this path (BASELINE.md — the
horovod.spark BERT fine-tune is only accuracy-gated), so the baseline is this
framework's own round-2 single-v5e-chip measurement recorded in
PERF_BASELINE.json; vs_baseline tracks round-over-round progress.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
BASELINE_FILE = os.path.join(REPO, "PERF_BASELINE.json")

BACKEND_UP_TIMEOUT_S = 75   # deadline for jax.devices() inside the child
TPU_FAST_FAIL_S = 120       # child death this early = transient raise, worth a retry
TPU_MAX_ATTEMPTS = 2        # flagship only; other configs get one shot
GLOBAL_BUDGET_S = 1320      # stay under the driver's kill timeout (~25+ min)

# (name, benchmarks/ module or None for the in-file flagship, tpu_s, cpu_s)
# cpu_s = 0 marks a TPU-only config (its measurement question is about the
# MXU; a CPU fallback would waste the budget) — skipped with a reason line
# when the relay is down.
# Measurement order = value of a scarce healthy window (VERDICT r4 next-#1):
# the four never-measured-on-chip configs and the two decision benches go
# BEFORE the flagship (which has recorded numbers since round 2); ViT goes
# dead last because its remote compile outran 450s and appeared to wedge
# the relay in both 2026-07-31 windows. Printing order is separate — the
# flagship line still prints last for the single-line consumer.
CONFIGS = [
    ("onnx-resnet", "onnx_resnet50", 300, 300),
    # llama-decode also carries the continuous_ab record: run-to-completion
    # generate vs paged continuous decode on a mixed-length stream (both
    # arms in the same round, serving-microbatch discipline)
    ("llama-decode", "llama_decode", 300, 300),
    ("gbdt-higgs", "gbdt_higgs1m", 420, 300),
    ("gbdt-hist-backends", "gbdt_hist_backends", 420, 0),
    ("attn-backends", "attn_backends", 600, 0),  # 4 BERT-base scan compiles
    # host-side serving A/B (adaptive continuous batching vs fixed-timeout
    # baseline, same round) — cheap, runs fine on the CPU fallback
    ("serving-microbatch", "serving_microbatch", 240, 240),
    # streamed fit_source vs eager fit_arrays over a multi-shard jsonl
    # dataset (rows/sec + prefetch occupancy + stall fraction); host-driven,
    # fine on the CPU fallback
    ("data-pipeline", "data_pipeline", 240, 240),
    # HPO sweep A/B: serial thread-pool TuneHyperparameters vs ONE fused
    # training array over the same 8-config space, both arms in-round from
    # cold compile caches (the N-compiles-vs-one asymmetry IS the metric)
    ("hpo-fused", "hpo_fused", 300, 300),
    # bulk-scoring A/B: in-memory transform vs streamed transform_source
    # over a multi-shard jsonl corpus, both arms end-to-end (files in,
    # scored files out) from cold compile caches, plus a simulated-2-host
    # scan; host-driven, fine on the CPU fallback
    ("bulk-scoring", "bulk_scoring", 240, 240),
    # deploy cold-start A/B: publish-once AOT executable ladder vs JIT
    # warmup, each arm a FRESH subprocess hot-swapping the same artifact
    # (first-burst latency + swap wall + byte-identity gate); subprocess
    # arms force CPU so fingerprints match — an honest CPU A/B either way
    ("deploy-coldstart", "deploy_coldstart", 420, 420),
    # sharded-train A/B: replicated vs ZeRO-sharded weight update, each arm
    # a FRESH subprocess on a 4-device CPU mesh (per-replica opt-state
    # bytes <= 1/dp + eps, step-time >= 0.9x, f32 param parity); the
    # fresh-arm subprocesses force CPU, honest on the fallback
    ("sharded-train", "sharded_train", 300, 300),
    # fleet-elastic A/B: static (3 fixed) vs autoscaled (1..8) subprocess
    # fleets under the same 1x->8x->1x closed-loop step load, same round —
    # SLO-violation seconds + worker-seconds + zero-new-traces AOT gate on
    # every scale-up worker; host-driven (workers force CPU), honest on
    # the fallback
    ("fleet-elastic", "fleet_elastic", 360, 360),
    # retrieval-serve A/B: 2-worker shard fan-out through the RoutingFront
    # vs in-process brute force over the SAME published shard bytes, then
    # a live delta ingest — recall@10 >= 0.99, served QPS >= 0.9x brute,
    # fresh docs queryable with zero downtime; workers force CPU
    ("retrieval-serve", "retrieval_serve", 300, 300),
    # explain-bulk A/B: fused perturbation scoring vs serial per-row
    # transform, plus streamed explain_source vs in-memory transform over
    # the same jsonl corpus — all three arms same round, cold-cache compile
    # count vs the ladder, content-keyed rng makes the arms byte-comparable;
    # host-driven, fine on the CPU fallback
    ("explain-bulk", "explain_bulk", 240, 240),
    ("flagship", None, 420, 360),
    ("vit", "vit_finetune", 450, 300),
]


# --------------------------------------------------------------------------
# child: the actual measurement (runs in a subprocess with staged deadlines)
# --------------------------------------------------------------------------

def _timed_scan(trainer, state, batch, k):
    import jax

    stacked = jax.tree.map(lambda x: np.broadcast_to(x, (k,) + x.shape).copy(), batch)
    t0 = time.perf_counter()
    new_state, metrics = trainer.train_steps_scan(state, stacked)
    losses = np.asarray(metrics["loss"])  # value fetch = real sync
    if not np.all(np.isfinite(losses)) or np.count_nonzero(losses) == 0:
        raise RuntimeError(f"scan returned degenerate losses: {losses[:4]}...")
    return time.perf_counter() - t0, new_state, float(losses[-1])


def _roundtrip_latency(n_trials: int = 5) -> float:
    """Fixed dispatch+fetch latency of a trivial program on the same path."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1.0)
    x = jnp.zeros(())
    float(f(x))  # compile
    ts = []
    for _ in range(n_trials):
        t0 = time.perf_counter()
        float(f(x))
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def run_bench(devices):
    import jax

    from synapseml_tpu.core.instrumentation import chip_peak_tflops
    from synapseml_tpu.models.flax_nets.bert import BertClassifier, bert_base, bert_tiny
    from synapseml_tpu.models.trainer import Trainer, TrainerConfig
    from synapseml_tpu.parallel.mesh import MeshConfig, create_mesh

    platform = devices[0].platform
    on_tpu = platform not in ("cpu",)
    if on_tpu:
        cfg = bert_base()          # 110M params, the reference DeepTextClassifier default
        B, T = 32, 128             # reference max_token_len default = 128
        k = 48
    else:                          # CPU smoke mode so the script always works
        cfg = bert_tiny()
        B, T = 16, 32
        k = 8

    model = BertClassifier(cfg, num_classes=2)
    mesh = create_mesh(MeshConfig(data=-1))
    trainer = Trainer(model, mesh, TrainerConfig(learning_rate=5e-5, total_steps=10_000))

    rng = np.random.default_rng(0)
    batch = {
        "input_ids": rng.integers(0, cfg.vocab_size, (B, T)).astype(np.int32),
        "attention_mask": np.ones((B, T), np.int32),
        "labels": rng.integers(0, 2, (B,)).astype(np.int32),
    }
    state = trainer.init_state(batch)

    from synapseml_tpu.core.observability import get_registry

    _, state, _ = _timed_scan(trainer, state, batch, k)  # compile + warm
    overhead = _roundtrip_latency()
    trials = []
    loss = float("nan")
    step_hist = get_registry().histogram(
        "synapseml_train_step_duration_ms",
        "training step (boosting iteration / optimizer step) wall time",
        ("engine",)).labels(engine="flagship")
    for _ in range(3):
        t, state, loss = _timed_scan(trainer, state, batch, k)
        trials.append(t)
        step_hist.observe(max(t - overhead, 0.0) / k * 1e3)
    step_s = max((min(trials) - overhead) / k, 1e-9)
    n_chips = jax.device_count()
    samples_per_sec_chip = B / step_s / n_chips

    # model FLOPs estimate: 6 * params * tokens per fwd+bwd
    n_params = sum(int(np.prod(np.shape(x))) for x in jax.tree.leaves(state.params))
    tflops = 6 * n_params * B * T / step_s / 1e12

    result = {
        "metric": "DeepTextClassifier BERT-base fine-tune throughput"
                  if on_tpu else "DeepTextClassifier bert-tiny (CPU smoke)",
        "value": round(samples_per_sec_chip, 2),
        "unit": "samples/sec/chip",
        "platform": platform,
        "batch": B,
        "seq_len": T,
        "step_ms": round(step_s * 1e3, 2),
        "model_tflops_per_sec": round(tflops, 1),
        "final_loss": round(loss, 4),
    }
    peak = chip_peak_tflops(getattr(devices[0], "device_kind", "") or "")
    if on_tpu and peak:
        result["mfu"] = round(tflops / n_chips / peak, 4)
        get_registry().gauge(
            "synapseml_train_mfu",
            "model FLOPs utilization vs chip_peak_tflops", ("engine",),
        ).set(result["mfu"], engine="flagship")
    return result


def _probe_main() -> None:
    """``--probe`` child: bring the backend up and print one line. Runs in
    its own process so a relay hang can only cost the parent's probe
    timeout, never a wedged interpreter."""
    sys.path.insert(0, os.path.join(REPO, "benchmarks"))
    from benchmarks._common import init_jax

    _jax, plat, n = init_jax()
    print("PROBE_OK " + json.dumps({"platform": plat, "n": n}), flush=True)


def _probe_timeout_s() -> float:
    """Probe deadline: ``SYNAPSEML_PROBE_TIMEOUT_S`` when set (slow pods
    need longer than the default; CI smoke wants shorter), else
    BACKEND_UP_TIMEOUT_S."""
    raw = os.environ.get("SYNAPSEML_PROBE_TIMEOUT_S", "").strip()
    if raw:
        try:
            return max(1.0, float(raw))
        except ValueError:
            pass
    return float(BACKEND_UP_TIMEOUT_S)


def _probe_backend(timeout_s: float | None = None) -> tuple[bool, dict]:
    """(tpu_usable, probe record): probe the JAX backend in a subprocess
    with a HARD timeout before the rotation spends any per-config budget. A
    hung relay (the round-2 failure mode: jax.devices() never returns) is
    killed at the deadline and the whole rotation falls back to CPU
    immediately — every config still emits its BENCH line instead of each
    one separately burning its backend-up window against a dead relay.

    The record distinguishes WHY: ``kind`` is ``up`` | ``timeout`` |
    ``no_tpu`` | ``error``, with the child's merged stdout/stderr tail —
    so a CPU-only BENCH round carries diagnosable evidence instead of the
    bare "cpu fallback" caveat."""
    if timeout_s is None:
        timeout_s = _probe_timeout_s()
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--probe"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, cwd=REPO)
    try:
        out, _ = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        # second communicate() collects whatever the child buffered before
        # the kill — the last thing it printed is usually the hang site
        out, _ = proc.communicate()
        tail = " | ".join((out or "").splitlines()[-4:])
        return False, {
            "kind": "timeout", "timeout_s": timeout_s,
            "reason": f"backend probe hung past {timeout_s:.0f}s "
                      "(relay hang)",
            "stderr_tail": tail[-300:]}
    for line in (out or "").splitlines():
        if line.startswith("PROBE_OK "):
            try:
                info = json.loads(line[len("PROBE_OK "):])
            except json.JSONDecodeError:
                continue
            if info.get("platform") not in ("cpu",):
                return True, {"kind": "up", "timeout_s": timeout_s,
                              "reason": f"backend up: {info}",
                              "stderr_tail": ""}
            tail = " | ".join((out or "").splitlines()[-4:])
            return False, {
                "kind": "no_tpu", "timeout_s": timeout_s,
                "reason": f"probe came up on {info.get('platform')} "
                          "(no TPU)",
                "stderr_tail": tail[-300:]}
    tail = " | ".join((out or "").splitlines()[-4:])
    return False, {
        "kind": "error", "timeout_s": timeout_s,
        "reason": f"probe died rc={proc.returncode}: {tail[-300:]}",
        "stderr_tail": tail[-300:]}


def _child_main(platform: str, config: str) -> None:
    """Bring up the backend (announce it), measure, print the result line."""
    if platform == "cpu":
        # Env vars are NOT enough: the site hook pins the tunnel backend at
        # interpreter boot, so force the platform through the config API.
        os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, os.path.join(REPO, "benchmarks"))
    from benchmarks._common import init_jax

    jax, plat, n_chips = init_jax()
    devices = jax.devices()
    print("BENCH_UP " + json.dumps(
        {"platform": devices[0].platform, "n": len(devices),
         "device_kind": getattr(devices[0], "device_kind", "")}), flush=True)
    module = dict((name, mod) for name, mod, _, _ in CONFIGS)[config]
    if module is None:
        result = run_bench(devices)
    else:
        import importlib

        result = importlib.import_module(module).run(jax, plat, n_chips)
    # every record carries the child's MetricsRegistry snapshot so the
    # perf trajectory keeps full histograms (p50/p95/p99), not just means
    try:
        from synapseml_tpu.core.observability import get_registry

        result["metrics"] = get_registry().snapshot()
    except Exception as e:  # noqa: BLE001 — a metrics bug must not eat a
        result["metrics"] = {"error": str(e)}  # scarce healthy TPU window
    print("BENCH_RESULT " + json.dumps(result), flush=True)


# --------------------------------------------------------------------------
# parent: orchestration (never imports jax, cannot hang)
# --------------------------------------------------------------------------

def _log(msg: str) -> None:
    print(f"# {msg}", flush=True)


def _run_child(platform: str, config: str, up_timeout_s: float,
               total_timeout_s: float):
    """Run a bench child with staged deadlines.

    Returns (result-dict-or-None, reason, elapsed_s, hang, backend_up). The
    backend must announce BENCH_UP within up_timeout_s (catches a hung relay
    early) and BENCH_RESULT must arrive within total_timeout_s. `hang` is
    True only when the child was killed BEFORE announcing the backend — a
    relay hang worth disabling TPU for; a kill after BENCH_UP just means this
    config's measurement outran its (possibly budget-truncated) deadline.
    `backend_up` distinguishes a fast relay raise during init (no BENCH_UP —
    relay trouble) from a measurement failure on a healthy backend.
    """
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child", platform, config],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, cwd=REPO,
    )
    lines: list = []
    done = threading.Event()

    def _reader():
        for line in proc.stdout:
            lines.append(line.rstrip("\n"))
        done.set()

    t = threading.Thread(target=_reader, daemon=True)
    t.start()
    start = time.monotonic()

    def _find(tag):
        for line in lines:
            if line.startswith(tag):
                try:
                    return json.loads(line[len(tag):])
                except json.JSONDecodeError:
                    continue  # mangled line (interleaved child output); keep scanning
        return None

    def _kill(why, hang):
        proc.kill()
        proc.wait()
        return None, why, time.monotonic() - start, hang, _find("BENCH_UP") is not None

    while time.monotonic() - start < up_timeout_s:
        if _find("BENCH_UP") or done.is_set():
            break
        time.sleep(0.5)
    else:
        return _kill(f"backend init exceeded {up_timeout_s}s (relay hang)",
                     hang=True)

    while time.monotonic() - start < total_timeout_s and not done.is_set():
        time.sleep(0.5)
    if not done.is_set():
        # backend DID come up: too slow for this deadline, not a relay hang
        return _kill(f"bench exceeded {total_timeout_s}s", hang=False)
    proc.wait()

    backend_up = _find("BENCH_UP") is not None
    result = _find("BENCH_RESULT")
    if result is not None:
        return result, None, time.monotonic() - start, False, backend_up
    tail = " | ".join(line for line in lines[-6:] if not line.startswith("BENCH_UP"))
    return (None, f"rc={proc.returncode}: {tail[-500:]}",
            time.monotonic() - start, False, backend_up)


def _load_recorded() -> dict:
    if os.path.exists(BASELINE_FILE):
        try:
            with open(BASELINE_FILE) as f:
                return json.load(f)
        except (json.JSONDecodeError, OSError) as e:
            _log(f"ignoring unreadable {BASELINE_FILE}: {e}")
    return {}


def _attach_vs_baseline(result: dict, recorded: dict) -> None:
    baseline = recorded.get(result["metric"])
    if isinstance(baseline, dict):  # rich entries: {"value": N, ...}
        baseline = baseline.get("value")
    value = result.get("value") or 0.0
    if not (baseline and value):
        result["vs_baseline"] = 1.0
    elif result.get("lower_is_better"):
        result["vs_baseline"] = round(baseline / value, 3)
    else:
        result["vs_baseline"] = round(value / baseline, 3)


def _seed_baseline(result: dict, recorded: dict) -> bool:
    """Record a fresh chip number so later rounds compare against it.

    Keep-best: a chip measurement worse than the recorded baseline (relay
    contention is real — the 2026-07-31 window measured the flagship 24%
    under its round-2 number) does NOT replace it; it is noted as
    ``latest`` on the prior entry so vs_baseline keeps tracking progress
    against the best verified number, not the most recent window's mood.

    Concurrency-safe: relay_watch.py may seed from another process while a
    rotation runs, so the read-modify-write happens under an exclusive
    flock and the write goes through a temp file + os.replace (a torn
    in-place write would read back as {} and wipe every prior baseline).
    The caller's ``recorded`` dict is refreshed from disk under the lock.
    """
    if result.get("platform") not in ("tpu",) or not result.get("value"):
        return False
    # "metrics" (the registry snapshot) stays in the BENCH record but NOT in
    # the baseline file — baselines hold the comparison scalar only
    entry = {k: v for k, v in result.items()
             if k not in ("vs_baseline", "reason", "metrics")}
    entry["measured"] = "round 4+ driver bench rotation"
    import fcntl

    try:
        with open(BASELINE_FILE + ".lock", "w") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            fresh = _load_recorded()
            if fresh:
                recorded.clear()
                recorded.update(fresh)
            prior = recorded.get(result["metric"])
            lower = bool(result.get("lower_is_better"))
            if (isinstance(prior, dict) and prior.get("value")
                    and str(prior.get("platform", "")).startswith("tpu")):
                worse = (entry["value"] >= prior["value"] if lower
                         else entry["value"] <= prior["value"])
                if worse:
                    prior["latest"] = {"value": entry["value"],
                                       "measured": entry["measured"]}
                    # keep-best must not silently bury a real regression
                    # (VERDICT r4 weak-#1): >10% below the stored best gets
                    # flagged on BOTH the baseline entry and the printed
                    # result, demanding an on-chip A/B before it is filed
                    # as contention
                    shortfall = (entry["value"] / prior["value"] - 1.0
                                 if lower
                                 else 1.0 - entry["value"] / prior["value"])
                    if shortfall > 0.1:
                        prior["latest"]["regression_suspect"] = True
                        result["regression_suspect"] = True
                        result["best_value"] = prior["value"]
                        _log(f"{result['metric']}: {entry['value']} is "
                             f"{shortfall:.0%} worse than best "
                             f"{prior['value']} — regression_suspect")
                else:
                    entry["prev_best"] = prior["value"]
                    recorded[result["metric"]] = entry
            else:
                recorded[result["metric"]] = entry
            tmp = BASELINE_FILE + ".tmp"
            with open(tmp, "w") as f:
                json.dump(recorded, f, indent=1)
            os.replace(tmp, BASELINE_FILE)
        return True
    except OSError as e:
        _log(f"could not seed {BASELINE_FILE}: {e}")
        return False


def main() -> None:
    if "--child" in sys.argv:
        i = sys.argv.index("--child")
        _child_main(sys.argv[i + 1], sys.argv[i + 2])
        return
    if "--probe" in sys.argv:
        _probe_main()
        return

    start = time.monotonic()

    def remaining() -> float:
        return GLOBAL_BUDGET_S - (time.monotonic() - start)

    recorded = _load_recorded()
    tpu_ok = True
    probe_info = None  # attached to every BENCH record when the probe failed
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        _log("JAX_PLATFORMS=cpu requested; skipping all TPU attempts")
        tpu_ok = False
        probe_info = {"kind": "skipped", "timeout_s": 0.0,
                      "reason": "JAX_PLATFORMS=cpu requested",
                      "stderr_tail": ""}
    if tpu_ok:
        # one hard-deadline subprocess probe up front: a hung relay demotes
        # the WHOLE rotation to CPU now, instead of every config separately
        # discovering the hang against its own backend-up window
        tpu_ok, probe = _probe_backend()
        _log(f"backend probe: {probe['reason']}"
             + ("" if tpu_ok else "; cpu fallback"))
        if not tpu_ok:
            probe_info = probe

    # BENCH_CONFIGS=flagship,vit restricts the rotation (CI smoke, manual
    # single-config runs); unset = all configs
    only = {c.strip() for c in os.environ.get("BENCH_CONFIGS", "").split(",")
            if c.strip()}
    configs = [c for c in CONFIGS if not only or c[0] in only]

    lines: list = []  # result dicts in config order; flagship printed last

    # every config is guaranteed at least one (possibly truncated) TPU
    # attempt: configs earlier in the rotation may not spend past their
    # deadline into the reserve held for the ones still queued
    MIN_ATTEMPT_S = BACKEND_UP_TIMEOUT_S + 90

    for i, (name, _module, tpu_s, cpu_s) in enumerate(configs):
        reserve = MIN_ATTEMPT_S * sum(
            1 for c in configs[i + 1:] if not (c[3] == 0 and not tpu_ok))
        result = None
        reason = None
        if tpu_ok:
            attempts = TPU_MAX_ATTEMPTS if name == "flagship" else 1
            for attempt in range(attempts):
                budget_here = remaining() - reserve
                if budget_here < MIN_ATTEMPT_S:
                    reason = "no budget left for a tpu attempt"
                    break
                result, err, elapsed, hang, _up = _run_child(
                    "tpu", name, BACKEND_UP_TIMEOUT_S, min(tpu_s, budget_here))
                if result is not None:
                    reason = None  # a retry that succeeded is a clean TPU number
                    break
                # A fast death is the relay *raising* (round-1 mode): retry
                # with backoff. A kill BEFORE backend-up is a *hang*
                # (round-2 mode): stop trying TPU for this AND all remaining
                # configs. A kill AFTER backend-up is just this config
                # outrunning its (possibly budget-truncated) deadline — the
                # relay is fine, keep trying the remaining configs.
                transient = elapsed < TPU_FAST_FAIL_S and not hang
                reason = f"tpu {name} attempt {attempt + 1} failed ({err}); cpu fallback"
                _log(reason)
                if hang:
                    tpu_ok = False
                    if probe_info is None:
                        probe_info = {
                            "kind": "timeout", "timeout_s": float(
                                BACKEND_UP_TIMEOUT_S),
                            "reason": f"relay hang during {name} (killed "
                                      "before backend-up)",
                            "stderr_tail": str(err or "")[-300:]}
                    break
                if not (transient and attempt + 1 < attempts):
                    break
                time.sleep(20.0)

        if result is None and cpu_s == 0:  # TPU-only decision benchmark
            result = {"metric": f"{name} (skipped)", "value": 0.0,
                      "unit": "n/a", "platform": "none"}
            reason = ((reason or "tpu unavailable")
                      + "; tpu-only config, no cpu fallback")
        if result is None:
            # a CPU fallback must not eat the reserve held for later
            # configs' TPU attempts while the relay is still considered up
            budget = min(cpu_s, remaining() - (reserve if tpu_ok else 0))
            if budget < 90:
                result = {"metric": f"{name} (skipped)", "value": 0.0,
                          "unit": "n/a", "platform": "none",
                          "reason": ((reason + "; ") if reason else "")
                          + f"global budget exhausted ({int(remaining())}s left)"}
                reason = None
            else:
                result, err, _, _, _up = _run_child("cpu", name, budget, budget)
                if result is None:
                    _log(f"cpu {name} bench failed too: {err}")
                    result = {"metric": f"{name} (failed)", "value": 0.0,
                              "unit": "n/a", "platform": "none", "error": err}

        _attach_vs_baseline(result, recorded)  # against the PRIOR record
        if result.get("platform") == "tpu" and _seed_baseline(result, recorded):
            _log(f"seeded PERF_BASELINE.json with {result['metric']}")
        if reason:
            result["reason"] = reason
        if probe_info is not None:
            # the round went CPU-only (or degraded mid-rotation): every
            # record says WHY the TPU probe failed, not just that it did
            result["probe"] = probe_info
        lines.append((name, result))

    # flagship line last so a single-JSON-line consumer parses the flagship
    for name, result in lines:
        if name != "flagship":
            print(json.dumps(result), flush=True)
    for name, result in lines:
        if name == "flagship":
            print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
