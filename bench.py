"""Flagship benchmark: DeepTextClassifier BERT-base fine-tune throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...} and
exits 0 regardless of TPU-relay state.

Method: K optimizer steps run on-device inside one lax.scan dispatch
(Trainer.train_steps_scan), so host/tunnel round-trip latency is excluded by
subtracting the fetch latency of a trivial jitted function (measured on the
same path); only one scan program is compiled (the remote-compile relay is
flaky under many compilations).

Hang-proofing (rounds 1+2 both failed to emit a JSON line — r01 raised on
UNAVAILABLE, r02 hung inside jax.devices() until the driver's rc=124 kill):
the parent process never imports jax. The measurement runs in a CHILD process
with two staged deadlines — the backend must come up within BACKEND_UP_TIMEOUT_S
(a hung relay is detected early), and the result must arrive within the
child's total budget. Fast transient failures (the relay raising UNAVAILABLE,
the round-1 mode) are retried with backoff; a hang (the round-2 mode) is
killed at the deadline and demoted to a CPU child. Note JAX_PLATFORMS=cpu env
alone is ignored here — sitecustomize pins the tunnel backend at interpreter
boot — so the CPU child forces jax.config.update("jax_platforms", "cpu")
in-process. If every child dies, the parent still prints a JSON line.

The reference publishes no hardware numbers for this path (BASELINE.md — the
horovod.spark BERT fine-tune is only accuracy-gated), so the baseline is this
framework's own round-2 single-v5e-chip measurement recorded in
PERF_BASELINE.json; vs_baseline tracks round-over-round progress.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
BASELINE_FILE = os.path.join(REPO, "PERF_BASELINE.json")

BACKEND_UP_TIMEOUT_S = 75   # deadline for jax.devices() inside the child
TPU_CHILD_TIMEOUT_S = 420   # full measurement on the chip (~2-4 min when healthy)
CPU_CHILD_TIMEOUT_S = 360   # bert-tiny smoke on CPU
TPU_FAST_FAIL_S = 120       # child death this early = transient raise, worth a retry
TPU_MAX_ATTEMPTS = 2


# --------------------------------------------------------------------------
# child: the actual measurement (runs in a subprocess with staged deadlines)
# --------------------------------------------------------------------------

def _timed_scan(trainer, state, batch, k):
    import jax

    stacked = jax.tree.map(lambda x: np.broadcast_to(x, (k,) + x.shape).copy(), batch)
    t0 = time.perf_counter()
    new_state, metrics = trainer.train_steps_scan(state, stacked)
    losses = np.asarray(metrics["loss"])  # value fetch = real sync
    if not np.all(np.isfinite(losses)) or np.count_nonzero(losses) == 0:
        raise RuntimeError(f"scan returned degenerate losses: {losses[:4]}...")
    return time.perf_counter() - t0, new_state, float(losses[-1])


def _roundtrip_latency(n_trials: int = 5) -> float:
    """Fixed dispatch+fetch latency of a trivial program on the same path."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1.0)
    x = jnp.zeros(())
    float(f(x))  # compile
    ts = []
    for _ in range(n_trials):
        t0 = time.perf_counter()
        float(f(x))
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def run_bench(devices):
    import jax

    from synapseml_tpu.core.instrumentation import chip_peak_tflops
    from synapseml_tpu.models.flax_nets.bert import BertClassifier, bert_base, bert_tiny
    from synapseml_tpu.models.trainer import Trainer, TrainerConfig
    from synapseml_tpu.parallel.mesh import MeshConfig, create_mesh

    platform = devices[0].platform
    on_tpu = platform not in ("cpu",)
    if on_tpu:
        cfg = bert_base()          # 110M params, the reference DeepTextClassifier default
        B, T = 32, 128             # reference max_token_len default = 128
        k = 48
    else:                          # CPU smoke mode so the script always works
        cfg = bert_tiny()
        B, T = 16, 32
        k = 8

    model = BertClassifier(cfg, num_classes=2)
    mesh = create_mesh(MeshConfig(data=-1))
    trainer = Trainer(model, mesh, TrainerConfig(learning_rate=5e-5, total_steps=10_000))

    rng = np.random.default_rng(0)
    batch = {
        "input_ids": rng.integers(0, cfg.vocab_size, (B, T)).astype(np.int32),
        "attention_mask": np.ones((B, T), np.int32),
        "labels": rng.integers(0, 2, (B,)).astype(np.int32),
    }
    state = trainer.init_state(batch)

    _, state, _ = _timed_scan(trainer, state, batch, k)  # compile + warm
    overhead = _roundtrip_latency()
    trials = []
    loss = float("nan")
    for _ in range(3):
        t, state, loss = _timed_scan(trainer, state, batch, k)
        trials.append(t)
    step_s = max((min(trials) - overhead) / k, 1e-9)
    n_chips = jax.device_count()
    samples_per_sec_chip = B / step_s / n_chips

    # model FLOPs estimate: 6 * params * tokens per fwd+bwd
    n_params = sum(int(np.prod(np.shape(x))) for x in jax.tree.leaves(state.params))
    tflops = 6 * n_params * B * T / step_s / 1e12

    result = {
        "metric": "DeepTextClassifier BERT-base fine-tune throughput"
                  if on_tpu else "DeepTextClassifier bert-tiny (CPU smoke)",
        "value": round(samples_per_sec_chip, 2),
        "unit": "samples/sec/chip",
        "platform": platform,
        "batch": B,
        "seq_len": T,
        "step_ms": round(step_s * 1e3, 2),
        "model_tflops_per_sec": round(tflops, 1),
        "final_loss": round(loss, 4),
    }
    peak = chip_peak_tflops(getattr(devices[0], "device_kind", "") or "")
    if on_tpu and peak:
        result["mfu"] = round(tflops / n_chips / peak, 4)
    return result


def _child_main(platform: str) -> None:
    """Bring up the backend (announce it), measure, print the result line."""
    if platform == "cpu":
        # Env vars are NOT enough: the site hook pins the tunnel backend at
        # interpreter boot, so force the platform through the config API.
        os.environ["JAX_PLATFORMS"] = "cpu"
    from benchmarks._common import init_jax

    jax, _, _ = init_jax()
    devices = jax.devices()
    print("BENCH_UP " + json.dumps(
        {"platform": devices[0].platform, "n": len(devices),
         "device_kind": getattr(devices[0], "device_kind", "")}), flush=True)
    result = run_bench(devices)
    print("BENCH_RESULT " + json.dumps(result), flush=True)


# --------------------------------------------------------------------------
# parent: orchestration (never imports jax, cannot hang)
# --------------------------------------------------------------------------

def _log(msg: str) -> None:
    print(f"# {msg}", flush=True)


def _run_child(platform: str, up_timeout_s: float, total_timeout_s: float):
    """Run a bench child with staged deadlines.

    Returns (result-dict-or-None, reason, elapsed_s, killed). The backend
    must announce BENCH_UP within up_timeout_s (catches a hung relay early)
    and BENCH_RESULT must arrive within total_timeout_s; `killed` is True
    when a deadline fired (a hang), False when the child died on its own.
    """
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child", platform],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, cwd=REPO,
    )
    lines: list = []
    done = threading.Event()

    def _reader():
        for line in proc.stdout:
            lines.append(line.rstrip("\n"))
        done.set()

    t = threading.Thread(target=_reader, daemon=True)
    t.start()
    start = time.monotonic()

    def _find(tag):
        for line in lines:
            if line.startswith(tag):
                try:
                    return json.loads(line[len(tag):])
                except json.JSONDecodeError:
                    continue  # mangled line (interleaved child output); keep scanning
        return None

    def _kill(why):
        proc.kill()
        proc.wait()
        return None, why, time.monotonic() - start, True

    while time.monotonic() - start < up_timeout_s:
        if _find("BENCH_UP") or done.is_set():
            break
        time.sleep(0.5)
    else:
        return _kill(f"backend init exceeded {up_timeout_s}s (relay hang)")

    while time.monotonic() - start < total_timeout_s and not done.is_set():
        time.sleep(0.5)
    if not done.is_set():
        return _kill(f"bench exceeded {total_timeout_s}s")
    proc.wait()

    result = _find("BENCH_RESULT")
    if result is not None:
        return result, None, time.monotonic() - start, False
    tail = " | ".join(line for line in lines[-6:] if not line.startswith("BENCH_UP"))
    return None, f"rc={proc.returncode}: {tail[-500:]}", time.monotonic() - start, False


def main() -> None:
    if "--child" in sys.argv:
        _child_main(sys.argv[sys.argv.index("--child") + 1])
        return

    reason = None
    result = None

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        _log("JAX_PLATFORMS=cpu requested; skipping the TPU attempt")
    else:
        for attempt in range(TPU_MAX_ATTEMPTS):
            result, err, elapsed, killed = _run_child(
                "tpu", BACKEND_UP_TIMEOUT_S, TPU_CHILD_TIMEOUT_S)
            if result is not None:
                reason = None  # a retry that succeeded is a clean TPU number
                break
            # A fast death is the relay *raising* (round-1 mode): retry with
            # backoff. A deadline kill is a *hang* (round-2 mode): do not
            # re-wait, demote to CPU immediately.
            transient = elapsed < TPU_FAST_FAIL_S and not killed
            reason = f"tpu attempt {attempt + 1} failed ({err}); cpu fallback"
            _log(reason)
            if not (transient and attempt + 1 < TPU_MAX_ATTEMPTS):
                break
            time.sleep(20.0)

    if result is None:
        result, err, _, _ = _run_child("cpu", CPU_CHILD_TIMEOUT_S, CPU_CHILD_TIMEOUT_S)
        if result is None:
            _log(f"cpu bench failed too: {err}")
            result = {
                "metric": "DeepTextClassifier bert-tiny (CPU smoke)",
                "value": 0.0, "unit": "samples/sec/chip", "platform": "none",
                "error": err, "vs_baseline": 0.0,
            }
            if reason:
                result["reason"] = reason
            print(json.dumps(result), flush=True)
            return

    recorded = {}
    if os.path.exists(BASELINE_FILE):
        try:
            with open(BASELINE_FILE) as f:
                recorded = json.load(f)
        except (json.JSONDecodeError, OSError) as e:
            _log(f"ignoring unreadable {BASELINE_FILE}: {e}")
    baseline = recorded.get(result["metric"])
    if isinstance(baseline, dict):  # rich entries: {"value": N, ...}
        baseline = baseline.get("value")
    result["vs_baseline"] = round(result["value"] / baseline, 3) if baseline else 1.0
    if reason:
        result["reason"] = reason
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
