"""Flagship benchmark: DeepTextClassifier BERT-base fine-tune throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Method: K optimizer steps run on-device inside one lax.scan dispatch
(Trainer.train_steps_scan), so host/tunnel round-trip latency is excluded by
subtracting the fetch latency of a trivial jitted function (measured on the
same path); only one scan program is compiled (the remote-compile relay is
flaky under many compilations).

The reference publishes no hardware numbers for this path (BASELINE.md — the
horovod.spark BERT fine-tune is only accuracy-gated), so the baseline is this
framework's own round-1 single-v5e-chip measurement recorded in
PERF_BASELINE.json; vs_baseline tracks round-over-round progress.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

BASELINE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "PERF_BASELINE.json")


def _timed_scan(trainer, state, batch, k):
    import jax

    stacked = jax.tree.map(lambda x: np.broadcast_to(x, (k,) + x.shape).copy(), batch)
    t0 = time.perf_counter()
    new_state, metrics = trainer.train_steps_scan(state, stacked)
    losses = np.asarray(metrics["loss"])  # value fetch = real sync
    if not np.all(np.isfinite(losses)) or np.count_nonzero(losses) == 0:
        raise RuntimeError(f"scan returned degenerate losses: {losses[:4]}...")
    return time.perf_counter() - t0, new_state, float(losses[-1])


def _roundtrip_latency(n_trials: int = 5) -> float:
    """Fixed dispatch+fetch latency of a trivial program on the same path."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1.0)
    x = jnp.zeros(())
    float(f(x))  # compile
    ts = []
    for _ in range(n_trials):
        t0 = time.perf_counter()
        float(f(x))
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def _chip_peak_tflops(device_kind: str):
    from synapseml_tpu.core.instrumentation import chip_peak_tflops

    return chip_peak_tflops(device_kind)


def _init_devices(max_tries: int = 5):
    """Initialize a jax backend with retry/backoff; fall back to CPU.

    The TPU tunnel is flaky (round-1 bench died on a single UNAVAILABLE at
    backend init); a bench that can't survive that records nothing. Retries
    clear any half-initialized backend, back off, and ultimately drop to the
    CPU smoke path so the driver always gets a JSON line (rc=0).
    """
    import jax
    import jax.extend.backend  # noqa: F401  (jax.extend is not auto-imported)

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    last_err = None
    for attempt in range(max_tries):
        try:
            devs = jax.devices()
            if devs:
                return devs
        except Exception as e:  # UNAVAILABLE / backend setup errors
            last_err = e
            try:
                jax.extend.backend.clear_backends()
            except Exception:
                pass
            print(f"# backend init failed (try {attempt + 1}/{max_tries}): "
                  f"{type(last_err).__name__}: {last_err}", flush=True)
            if attempt + 1 < max_tries:
                time.sleep(min(10.0 * (2 ** attempt), 120.0))
    print("# backend unavailable after retries; falling back to CPU", flush=True)
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.extend.backend.clear_backends()
    except Exception:
        pass
    return jax.devices()


def run_bench():
    import jax

    devices = _init_devices()
    from synapseml_tpu.models.flax_nets.bert import BertClassifier, bert_base, bert_tiny
    from synapseml_tpu.models.trainer import Trainer, TrainerConfig
    from synapseml_tpu.parallel.mesh import MeshConfig, create_mesh

    platform = devices[0].platform
    on_tpu = platform not in ("cpu",)
    if on_tpu:
        cfg = bert_base()          # 110M params, the reference DeepTextClassifier default
        B, T = 32, 128             # reference max_token_len default = 128
        k = 48
    else:                          # CPU smoke mode so the script always works
        cfg = bert_tiny()
        B, T = 16, 32
        k = 8

    model = BertClassifier(cfg, num_classes=2)
    mesh = create_mesh(MeshConfig(data=-1))
    trainer = Trainer(model, mesh, TrainerConfig(learning_rate=5e-5, total_steps=10_000))

    rng = np.random.default_rng(0)
    batch = {
        "input_ids": rng.integers(0, cfg.vocab_size, (B, T)).astype(np.int32),
        "attention_mask": np.ones((B, T), np.int32),
        "labels": rng.integers(0, 2, (B,)).astype(np.int32),
    }
    state = trainer.init_state(batch)

    _, state, _ = _timed_scan(trainer, state, batch, k)  # compile + warm
    overhead = _roundtrip_latency()
    trials = []
    loss = float("nan")
    for _ in range(3):
        t, state, loss = _timed_scan(trainer, state, batch, k)
        trials.append(t)
    step_s = max((min(trials) - overhead) / k, 1e-9)
    n_chips = jax.device_count()
    samples_per_sec_chip = B / step_s / n_chips

    # model FLOPs estimate: 6 * params * tokens per fwd+bwd
    n_params = sum(int(np.prod(np.shape(x))) for x in jax.tree.leaves(state.params))
    tflops = 6 * n_params * B * T / step_s / 1e12

    result = {
        "metric": "DeepTextClassifier BERT-base fine-tune throughput"
                  if on_tpu else "DeepTextClassifier bert-tiny (CPU smoke)",
        "value": round(samples_per_sec_chip, 2),
        "unit": "samples/sec/chip",
        "platform": platform,
        "batch": B,
        "seq_len": T,
        "step_ms": round(step_s * 1e3, 2),
        "model_tflops_per_sec": round(tflops, 1),
        "final_loss": round(loss, 4),
    }
    peak = _chip_peak_tflops(getattr(devices[0], "device_kind", "") or "")
    if on_tpu and peak:
        result["mfu"] = round(tflops / n_chips / peak, 4)
    return result


def _run_bench_resilient():
    """One retry on CPU if the TPU path dies mid-bench (compile/scan/fetch can
    hit the same UNAVAILABLE tunnel flake as backend init)."""
    try:
        return run_bench()
    except Exception as e:
        print(f"# bench failed on primary backend: {type(e).__name__}: {e}; "
              f"retrying on CPU", flush=True)
        import jax
        import jax.extend.backend

        try:
            jax.extend.backend.clear_backends()
        except Exception:
            pass
        jax.config.update("jax_platforms", "cpu")
        os.environ["JAX_PLATFORMS"] = "cpu"
        return run_bench()


def main():
    result = _run_bench_resilient()
    recorded = {}
    if os.path.exists(BASELINE_FILE):
        try:
            with open(BASELINE_FILE) as f:
                recorded = json.load(f)
        except (json.JSONDecodeError, OSError) as e:
            print(f"# ignoring unreadable {BASELINE_FILE}: {e}", flush=True)
    baseline = recorded.get(result["metric"])
    result["vs_baseline"] = round(result["value"] / baseline, 3) if baseline else 1.0
    if baseline is None and result["platform"] != "cpu":
        # seed the round-over-round baseline with the first real TPU number
        recorded[result["metric"]] = result["value"]
        with open(BASELINE_FILE, "w") as f:
            json.dump(recorded, f, indent=1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
