"""Balance measures (reference ``exploratory/{FeatureBalanceMeasure,
DistributionBalanceMeasure,AggregateBalanceMeasure}.scala``).

Measure definitions follow the reference's documented set:
  * feature (pairwise gaps): statistical parity dp, pointwise mutual info pmi,
    sorensen-dice sdc, jaccard index ji, log-likelihood ratio llr, krc
    (kendall rank via concordance of indicator vectors is reduced to the
    normalized pointwise measure the reference reports), t-test statistic.
  * distribution: KL divergence, JS distance, Wasserstein (1D), infinity-norm
    (total variation x2), total variation, chi-squared statistic + p-value
    proxy, reference = uniform over observed classes.
  * aggregate: Atkinson index (eps=1), Theil L, Theil T.
"""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import Param, TypeConverters
from ..core.pipeline import Transformer

__all__ = ["FeatureBalanceMeasure", "DistributionBalanceMeasure",
           "AggregateBalanceMeasure"]

_EPS = 1e-12


class FeatureBalanceMeasure(Transformer):
    """(ref ``FeatureBalanceMeasure.scala:38``) — one row per (feature,
    classA, classB) pair with gap measures between the two classes."""

    feature_name = "exploratory"

    sensitive_cols = Param("sensitive_cols", "sensitive feature columns",
                           converter=TypeConverters.to_list)
    label_col = Param("label_col", "binary label column", default="label")

    def _pair_measures(self, pa, pb, pa_y, pb_y, py) -> dict:
        """p(class), p(class & positive), p(positive)."""
        dp_a, dp_b = pa_y / max(pa, _EPS), pb_y / max(pb, _EPS)
        pmi_a = np.log(max(dp_a, _EPS) / max(py, _EPS))
        pmi_b = np.log(max(dp_b, _EPS) / max(py, _EPS))
        sdc_a = pa_y / max(pa + py, _EPS)
        sdc_b = pb_y / max(pb + py, _EPS)
        ji_a = pa_y / max(pa + py - pa_y, _EPS)
        ji_b = pb_y / max(pb + py - pb_y, _EPS)
        llr_a = np.log(max(pa_y, _EPS) / max(py, _EPS))
        llr_b = np.log(max(pb_y, _EPS) / max(py, _EPS))
        krc_a = pa_y - pa * py
        krc_b = pb_y - pb * py
        return {
            "dp": dp_a - dp_b,            # statistical parity / demographic parity
            "pmi": pmi_a - pmi_b,
            "sdc": sdc_a - sdc_b,
            "ji": ji_a - ji_b,
            "llr": llr_a - llr_b,
            "krc": krc_a - krc_b,
            "n_pmi_y": (pmi_a - pmi_b) / max(-np.log(max(py, _EPS)), _EPS),
        }

    def _transform(self, df: DataFrame) -> DataFrame:
        cols = self.get("sensitive_cols")
        self.require_columns(df, self.get("label_col"), *cols)
        y = np.asarray(df.collect_column(self.get("label_col"))).astype(float) > 0
        n = len(y)
        py = float(y.mean()) if n else 0.0
        rows = {"FeatureName": [], "ClassA": [], "ClassB": []}
        measure_rows = []
        for col in cols:
            v = np.asarray(df.collect_column(col))
            classes = np.unique(v)
            for i, a in enumerate(classes):
                for b in classes[i + 1:]:
                    pa = float((v == a).mean())
                    pb = float((v == b).mean())
                    pa_y = float(((v == a) & y).mean())
                    pb_y = float(((v == b) & y).mean())
                    rows["FeatureName"].append(col)
                    rows["ClassA"].append(a)
                    rows["ClassB"].append(b)
                    measure_rows.append(self._pair_measures(pa, pb, pa_y, pb_y, py))
        out = {k: np.asarray(v) for k, v in rows.items()}
        # static measure schema even with zero class pairs (schema stability)
        keys = (list(measure_rows[0]) if measure_rows
                else list(self._pair_measures(0.5, 0.5, 0.25, 0.25, 0.5)))
        for key in keys:
            out[key] = np.asarray([m[key] for m in measure_rows])
        return DataFrame([out])


class DistributionBalanceMeasure(Transformer):
    """(ref ``DistributionBalanceMeasure.scala``) — one row per feature:
    divergence of the observed class distribution from uniform."""

    feature_name = "exploratory"

    sensitive_cols = Param("sensitive_cols", "sensitive feature columns",
                           converter=TypeConverters.to_list)

    def _measures(self, counts: np.ndarray) -> dict:
        n = counts.sum()
        p = counts / max(n, 1)
        k = len(counts)
        q = np.full(k, 1.0 / k)
        kl = float(np.sum(p * np.log(np.maximum(p, _EPS) / q)))
        m = 0.5 * (p + q)
        js = float(0.5 * np.sum(p * np.log(np.maximum(p, _EPS) / m))
                   + 0.5 * np.sum(q * np.log(q / m)))
        tv = float(0.5 * np.abs(p - q).sum())
        inf_norm = float(np.abs(p - q).max())
        ws = float(np.abs(np.cumsum(p) - np.cumsum(q)).mean())  # 1D wasserstein
        chi_sq = float(np.sum((counts - n / k) ** 2 / max(n / k, _EPS)))
        return {"kl_divergence": kl, "js_dist": float(np.sqrt(max(js, 0.0))),
                "total_variation_dist": tv, "inf_norm_dist": inf_norm,
                "wasserstein_dist": ws, "chi_sq_stat": chi_sq}

    def _transform(self, df: DataFrame) -> DataFrame:
        cols = self.get("sensitive_cols")
        self.require_columns(df, *cols)
        out = {"FeatureName": []}
        measures = []
        for col in cols:
            v = np.asarray(df.collect_column(col))
            _, counts = np.unique(v, return_counts=True)
            out["FeatureName"].append(col)
            measures.append(self._measures(counts.astype(float)))
        result = {"FeatureName": np.asarray(out["FeatureName"])}
        keys = (list(measures[0]) if measures
                else list(self._measures(np.asarray([1.0]))))
        for key in keys:
            result[key] = np.asarray([m[key] for m in measures])
        return DataFrame([result])


class AggregateBalanceMeasure(Transformer):
    """(ref ``AggregateBalanceMeasure.scala``) — single row: inequality indices
    over the joint distribution of all sensitive columns."""

    feature_name = "exploratory"

    sensitive_cols = Param("sensitive_cols", "sensitive feature columns",
                           converter=TypeConverters.to_list)
    epsilon = Param("epsilon", "Atkinson inequality-aversion parameter",
                    default=1.0, converter=TypeConverters.to_float)

    def _transform(self, df: DataFrame) -> DataFrame:
        cols = self.get("sensitive_cols")
        self.require_columns(df, *cols)
        vals = [np.asarray(df.collect_column(c)).astype(str) for c in cols]
        joint = np.array([" | ".join(t) for t in zip(*vals)])
        _, counts = np.unique(joint, return_counts=True)
        p = counts / counts.sum()
        k = len(p)
        mu = 1.0 / k
        eps = self.get("epsilon")
        if abs(eps - 1.0) < 1e-9:
            atkinson = 1.0 - np.exp(np.mean(np.log(np.maximum(p, _EPS)))) / mu
        else:
            atkinson = 1.0 - (np.mean((p / mu) ** (1 - eps))) ** (1 / (1 - eps))
        theil_t = float(np.mean((p / mu) * np.log(np.maximum(p / mu, _EPS))))
        theil_l = float(-np.mean(np.log(np.maximum(p / mu, _EPS))))
        return DataFrame([{
            "atkinson_index": np.asarray([float(atkinson)]),
            "theil_t_index": np.asarray([theil_t]),
            "theil_l_index": np.asarray([theil_l]),
        }])
