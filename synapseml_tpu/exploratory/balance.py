"""Balance measures (reference ``exploratory/{FeatureBalanceMeasure,
DistributionBalanceMeasure,AggregateBalanceMeasure}.scala``).

Measure definitions follow the reference's documented set:
  * feature (pairwise gaps): statistical parity dp, pointwise mutual info pmi,
    sorensen-dice sdc, jaccard index ji, log-likelihood ratio llr, krc
    (kendall rank via concordance of indicator vectors is reduced to the
    normalized pointwise measure the reference reports), t-test statistic.
  * distribution: KL divergence, JS distance, Wasserstein (1D), infinity-norm
    (total variation x2), total variation, chi-squared statistic + p-value
    proxy, reference = uniform over observed classes.
  * aggregate: Atkinson index (eps=1), Theil L, Theil T.
"""

from __future__ import annotations

import numpy as np

from ..core.batching import default_bucketer, get_compiled_cache, pad_rows
from ..core.dataframe import DataFrame
from ..core.params import Param, TypeConverters
from ..core.pipeline import Transformer

__all__ = ["FeatureBalanceMeasure", "DistributionBalanceMeasure",
           "AggregateBalanceMeasure"]

_EPS = 1e-12

PAIR_FN_ID = "exploratory.balance_pairs"
_MEASURE_KEYS = ("dp", "pmi", "sdc", "ji", "llr", "krc", "n_pmi_y")
_MAX_PAIR_ROWS = 1024


def _build_pair_measures():
    """One executable per pair-count bucket: every (classA, classB) gap
    measure for a whole table of pairs in one fused elementwise pass.
    Input is [P, 5] rows of (pa, pb, pa_y, pb_y, py); output [P, 7] in
    ``_MEASURE_KEYS`` order."""
    import jax
    import jax.numpy as jnp

    def measures(pairs):
        pa, pb, pa_y, pb_y, py = (pairs[:, i] for i in range(5))
        eps = _EPS  # representable in f32; dtype follows the input
        dp_a = pa_y / jnp.maximum(pa, eps)
        dp_b = pb_y / jnp.maximum(pb, eps)
        log_py = jnp.log(jnp.maximum(py, eps))
        pmi = (jnp.log(jnp.maximum(dp_a, eps))
               - jnp.log(jnp.maximum(dp_b, eps)))
        sdc = pa_y / jnp.maximum(pa + py, eps) - pb_y / jnp.maximum(pb + py,
                                                                    eps)
        ji = (pa_y / jnp.maximum(pa + py - pa_y, eps)
              - pb_y / jnp.maximum(pb + py - pb_y, eps))
        llr = (jnp.log(jnp.maximum(pa_y, eps))
               - jnp.log(jnp.maximum(pb_y, eps)))
        krc = (pa_y - pa * py) - (pb_y - pb * py)
        n_pmi_y = pmi / jnp.maximum(-log_py, eps)
        return jnp.stack([dp_a - dp_b, pmi, sdc, ji, llr, krc, n_pmi_y],
                         axis=1)

    return jax.jit(measures)


def _pair_measure_table(pairs: np.ndarray) -> np.ndarray:
    """[P, 5] (pa, pb, pa_y, pb_y, py) -> [P, 7] measures through the
    shared CompiledCache on the bucket ladder (``PAIR_FN_ID``)."""
    P = len(pairs)
    if P == 0:
        return np.zeros((0, len(_MEASURE_KEYS)), np.float64)
    arr = np.ascontiguousarray(np.asarray(pairs, np.float64))
    cache = get_compiled_cache()
    out = np.empty((P, len(_MEASURE_KEYS)), np.float64)
    for start, stop, bucket in default_bucketer().slices(
            P, max_rows=_MAX_PAIR_ROWS):
        chunk = pad_rows(arr[start:stop], bucket, mode="edge")
        exe = cache.get(PAIR_FN_ID, (bucket, chunk.shape[1]),
                        _build_pair_measures, dtype=str(chunk.dtype))
        y = np.asarray(exe(chunk), np.float64)
        out[start:stop] = y[: stop - start]
    return out


class FeatureBalanceMeasure(Transformer):
    """(ref ``FeatureBalanceMeasure.scala:38``) — one row per (feature,
    classA, classB) pair with gap measures between the two classes."""

    feature_name = "exploratory"

    sensitive_cols = Param("sensitive_cols", "sensitive feature columns",
                           converter=TypeConverters.to_list)
    label_col = Param("label_col", "binary label column", default="label")

    def _pair_measures(self, pa, pb, pa_y, pb_y, py) -> dict:
        """p(class), p(class & positive), p(positive) — the scalar reference
        for the compiled ``_pair_measure_table`` path (parity oracle)."""
        dp_a, dp_b = pa_y / max(pa, _EPS), pb_y / max(pb, _EPS)
        pmi_a = np.log(max(dp_a, _EPS) / max(py, _EPS))
        pmi_b = np.log(max(dp_b, _EPS) / max(py, _EPS))
        sdc_a = pa_y / max(pa + py, _EPS)
        sdc_b = pb_y / max(pb + py, _EPS)
        ji_a = pa_y / max(pa + py - pa_y, _EPS)
        ji_b = pb_y / max(pb + py - pb_y, _EPS)
        llr_a = np.log(max(pa_y, _EPS) / max(py, _EPS))
        llr_b = np.log(max(pb_y, _EPS) / max(py, _EPS))
        krc_a = pa_y - pa * py
        krc_b = pb_y - pb * py
        return {
            "dp": dp_a - dp_b,            # statistical parity / demographic parity
            "pmi": pmi_a - pmi_b,
            "sdc": sdc_a - sdc_b,
            "ji": ji_a - ji_b,
            "llr": llr_a - llr_b,
            "krc": krc_a - krc_b,
            "n_pmi_y": (pmi_a - pmi_b) / max(-np.log(max(py, _EPS)), _EPS),
        }

    def _transform(self, df: DataFrame) -> DataFrame:
        cols = self.get("sensitive_cols")
        self.require_columns(df, self.get("label_col"), *cols)
        y = np.asarray(df.collect_column(self.get("label_col"))).astype(float) > 0
        n = len(y)
        py = float(y.mean()) if n else 0.0
        feature_names: list = []
        class_a: list = []
        class_b: list = []
        pair_blocks = []
        for col in cols:
            v = np.asarray(df.collect_column(col))
            # one unique pass per column: class fractions + positive-class
            # fractions via bincount, then every (i < j) pair at once
            classes, inverse = np.unique(v, return_inverse=True)
            counts = np.bincount(inverse, minlength=len(classes))
            pos = np.bincount(inverse, weights=y.astype(np.float64),
                              minlength=len(classes))
            p_class = counts / max(n, 1)
            p_class_y = pos / max(n, 1)
            ia, ib = np.triu_indices(len(classes), k=1)
            feature_names.extend([col] * len(ia))
            class_a.extend(classes[ia].tolist())
            class_b.extend(classes[ib].tolist())
            pair_blocks.append(np.stack(
                [p_class[ia], p_class[ib], p_class_y[ia], p_class_y[ib],
                 np.full(len(ia), py)], axis=1))
        pairs = (np.concatenate(pair_blocks) if pair_blocks
                 else np.zeros((0, 5)))
        table = _pair_measure_table(pairs)
        out = {"FeatureName": np.asarray(feature_names),
               "ClassA": np.asarray(class_a),
               "ClassB": np.asarray(class_b)}
        # static measure schema even with zero class pairs (schema stability)
        for j, key in enumerate(_MEASURE_KEYS):
            out[key] = table[:, j]
        return DataFrame([out])


class DistributionBalanceMeasure(Transformer):
    """(ref ``DistributionBalanceMeasure.scala``) — one row per feature:
    divergence of the observed class distribution from uniform."""

    feature_name = "exploratory"

    sensitive_cols = Param("sensitive_cols", "sensitive feature columns",
                           converter=TypeConverters.to_list)

    def _measures(self, counts: np.ndarray) -> dict:
        n = counts.sum()
        p = counts / max(n, 1)
        k = len(counts)
        q = np.full(k, 1.0 / k)
        kl = float(np.sum(p * np.log(np.maximum(p, _EPS) / q)))
        m = 0.5 * (p + q)
        js = float(0.5 * np.sum(p * np.log(np.maximum(p, _EPS) / m))
                   + 0.5 * np.sum(q * np.log(q / m)))
        tv = float(0.5 * np.abs(p - q).sum())
        inf_norm = float(np.abs(p - q).max())
        ws = float(np.abs(np.cumsum(p) - np.cumsum(q)).mean())  # 1D wasserstein
        chi_sq = float(np.sum((counts - n / k) ** 2 / max(n / k, _EPS)))
        return {"kl_divergence": kl, "js_dist": float(np.sqrt(max(js, 0.0))),
                "total_variation_dist": tv, "inf_norm_dist": inf_norm,
                "wasserstein_dist": ws, "chi_sq_stat": chi_sq}

    def _transform(self, df: DataFrame) -> DataFrame:
        cols = self.get("sensitive_cols")
        self.require_columns(df, *cols)
        out = {"FeatureName": []}
        measures = []
        for col in cols:
            v = np.asarray(df.collect_column(col))
            _, counts = np.unique(v, return_counts=True)
            out["FeatureName"].append(col)
            measures.append(self._measures(counts.astype(float)))
        result = {"FeatureName": np.asarray(out["FeatureName"])}
        keys = (list(measures[0]) if measures
                else list(self._measures(np.asarray([1.0]))))
        for key in keys:
            result[key] = np.asarray([m[key] for m in measures])
        return DataFrame([result])


class AggregateBalanceMeasure(Transformer):
    """(ref ``AggregateBalanceMeasure.scala``) — single row: inequality indices
    over the joint distribution of all sensitive columns."""

    feature_name = "exploratory"

    sensitive_cols = Param("sensitive_cols", "sensitive feature columns",
                           converter=TypeConverters.to_list)
    epsilon = Param("epsilon", "Atkinson inequality-aversion parameter",
                    default=1.0, converter=TypeConverters.to_float)

    def _transform(self, df: DataFrame) -> DataFrame:
        cols = self.get("sensitive_cols")
        self.require_columns(df, *cols)
        vals = [np.asarray(df.collect_column(c)).astype(str) for c in cols]
        joint = np.array([" | ".join(t) for t in zip(*vals)])
        _, counts = np.unique(joint, return_counts=True)
        p = counts / counts.sum()
        k = len(p)
        mu = 1.0 / k
        eps = self.get("epsilon")
        if abs(eps - 1.0) < 1e-9:
            atkinson = 1.0 - np.exp(np.mean(np.log(np.maximum(p, _EPS)))) / mu
        else:
            atkinson = 1.0 - (np.mean((p / mu) ** (1 - eps))) ** (1 / (1 - eps))
        theil_t = float(np.mean((p / mu) * np.log(np.maximum(p / mu, _EPS))))
        theil_l = float(-np.mean(np.log(np.maximum(p / mu, _EPS))))
        return DataFrame([{
            "atkinson_index": np.asarray([float(atkinson)]),
            "theil_t_index": np.asarray([theil_t]),
            "theil_l_index": np.asarray([theil_l]),
        }])
