"""Data balance analysis (responsible AI) — reference ``core/.../exploratory/``
(SURVEY.md §2.5): FeatureBalanceMeasure (association-gap measures between
sensitive-feature values w.r.t. a label), DistributionBalanceMeasure
(per-feature distribution vs a uniform reference), AggregateBalanceMeasure
(inequality indices over the whole feature)."""

from .balance import (
    AggregateBalanceMeasure,
    DistributionBalanceMeasure,
    FeatureBalanceMeasure,
)

__all__ = ["FeatureBalanceMeasure", "DistributionBalanceMeasure",
           "AggregateBalanceMeasure"]
