"""Sharded scoring sinks with exactly-once semantics.

Output layout (one directory, shared by every host of a scan)::

    part-00042.jsonl            # or part-00042.<col>.npy per column
    part-00042.DONE             # JSON: rows, files, host, quarantined
    cursor-00000.jsonl          # append-only per-host completion log
    errors-00000.jsonl          # per-host quarantine sidecar (poisoned rows)
    _SUCCESS                    # whole-scan marker, all shards DONE

The exactly-once contract rests on three disciplines borrowed from the
rest of the codebase:

* **atomic parts** — every payload file streams to a same-directory temp
  and appears via ``os.replace`` (the ``registry/store`` write-then-rename
  pattern, through ``io.files``'s streamed writers), so a killed scan can
  never leave a torn part under a committed name;
* **DONE markers** — a shard counts as emitted only when its ``.DONE``
  marker exists AND every payload file it lists is present (the
  ``parallel/checkpoint`` completeness rule), written strictly AFTER the
  payload renames;
* **append-only cursor** — each host appends one fsynced record per
  finished shard to its own cursor file. The DONE markers are the resume
  ground truth (:meth:`ScoreSink.completed`); the cursor is the ordered
  audit trail (when was each shard finished, by which host, how many rows)
  that also survives marker deletion.

A resume therefore skips exactly the shards whose markers are complete and
re-runs the rest from scratch; since part content is a deterministic
function of the shard, the merged output is row-for-row identical to an
uninterrupted run — no duplicates, no gaps (``tests/test_scoring.py``).
"""

from __future__ import annotations

import glob as _glob
import json
import os
import time
from typing import Any, Sequence

import numpy as np

from ..io import files as iofiles
from ..registry.store import atomic_write_bytes

__all__ = ["ScoreSink", "JsonlSink", "NpySink", "open_sink",
           "SUCCESS_MARKER"]

SUCCESS_MARKER = "_SUCCESS"
_PART_PREFIX = "part-"
_DONE_SUFFIX = ".DONE"


class _OpenPart:
    """One in-flight shard's payload writers; produced by
    :meth:`ScoreSink.begin_shard`, driven by the runner's writer thread."""

    def __init__(self, sink: "ScoreSink", shard_index: int, host_index: int):
        self.sink = sink
        self.shard_index = int(shard_index)
        self.host_index = int(host_index)
        self.rows = 0
        self._writers = sink._open_writers(shard_index)

    def write(self, cols: dict, n_valid: int) -> None:
        """Append ``n_valid`` already-unpadded rows of one scored batch."""
        self.sink._write_chunk(self._writers, cols, int(n_valid))
        self.rows += int(n_valid)

    def finish(self, meta: dict | None = None) -> dict:
        """Commit payload file(s), then the DONE marker, then the cursor
        record — strictly in that order, so every observable completion
        state is recoverable."""
        files = [os.path.basename(w.commit()) for w in self._writers]
        record = {"shard": self.shard_index, "rows": self.rows,
                  "files": files, "host": self.host_index,
                  "quarantined": False}
        if meta:
            record.update(meta)
        self.sink._mark_done(record)
        return record

    def abort(self) -> None:
        for w in self._writers:
            w.abort()


class ScoreSink:
    """Base sharded sink: directory layout, DONE markers, cursor, errors
    sidecar, ``_SUCCESS``. Subclasses provide the payload format."""

    format = "none"

    def __init__(self, path: str):
        self.path = str(path)
        os.makedirs(self.path, exist_ok=True)
        self._cursor_f = None
        self._cursor_host = None
        self._errors_f = None
        self._errors_host = None

    # -- payload hooks (subclass) -------------------------------------------
    def _open_writers(self, shard_index: int) -> Sequence[Any]:
        raise NotImplementedError

    def _write_chunk(self, writers: Sequence[Any], cols: dict,
                     n_valid: int) -> None:
        raise NotImplementedError

    # -- naming -------------------------------------------------------------
    def part_stem(self, shard_index: int) -> str:
        return f"{_PART_PREFIX}{int(shard_index):05d}"

    def done_path(self, shard_index: int) -> str:
        return os.path.join(self.path,
                            self.part_stem(shard_index) + _DONE_SUFFIX)

    # -- lifecycle ----------------------------------------------------------
    def begin_shard(self, shard_index: int, host_index: int = 0) -> _OpenPart:
        """Open the shard's payload writers. Crash leftovers from a
        previous attempt at THIS shard (temp files named under its stem)
        are swept first — a shard is owned by exactly one host, and one
        host runs its shards sequentially, so nothing live can match. The
        glob is anchored at the stem boundary (payload names always put a
        ``.`` after the stem): ``part-12345*`` would also match another
        shard's live ``part-123456.*`` temp once stems outgrow 5 digits."""
        for stale in _glob.glob(os.path.join(
                self.path, self.part_stem(shard_index) + ".*.tmp.*")):
            try:
                os.unlink(stale)
            except OSError:
                pass
        return _OpenPart(self, shard_index, host_index)

    def mark_quarantined(self, shard_index: int, host_index: int,
                         error: str) -> dict:
        """Record a poisoned SHARD: zero-row DONE marker (so the scan
        completes and a resume does not retry it forever) + an errors-
        sidecar record. Re-score deliberately by deleting the marker."""
        record = {"shard": int(shard_index), "rows": 0, "files": [],
                  "host": int(host_index), "quarantined": True,
                  "error": str(error)}
        self._mark_done(record)
        self.quarantine(host_index, {"kind": "shard", "shard": int(shard_index),
                                     "error": str(error)})
        return record

    def _mark_done(self, record: dict) -> None:
        atomic_write_bytes(self.done_path(record["shard"]),
                           json.dumps(record, sort_keys=True).encode())
        self._append_cursor(record)

    def _append_cursor(self, record: dict) -> None:
        host = int(record.get("host", 0))
        if self._cursor_f is None or self._cursor_host != host:
            if self._cursor_f is not None:
                self._cursor_f.close()
            self._cursor_f = open(os.path.join(
                self.path, f"cursor-{host:05d}.jsonl"), "a")
            self._cursor_host = host
        self._cursor_f.write(json.dumps(
            {**record, "ts": time.time()}, sort_keys=True) + "\n")
        self._cursor_f.flush()
        os.fsync(self._cursor_f.fileno())

    def quarantine(self, host_index: int, record: dict) -> None:
        """Append one poisoned row/shard record to this host's errors
        sidecar (plain appended jsonl — the sidecar is diagnostic, not part
        of the exactly-once output set, so records flush per append but
        fsync only on close: a 1000-row poisoned batch must not turn into
        1000 blocking fsyncs on the writer thread)."""
        host = int(host_index)
        if self._errors_f is None or self._errors_host != host:
            if self._errors_f is not None:
                self._errors_f.close()
            self._errors_f = open(os.path.join(
                self.path, f"errors-{host:05d}.jsonl"), "a")
            self._errors_host = host
        self._errors_f.write(json.dumps(record, sort_keys=True,
                                        default=iofiles.json_default) + "\n")
        self._errors_f.flush()

    def close(self) -> None:
        if self._cursor_f is not None:
            self._cursor_f.close()
            self._cursor_f = None
        if self._errors_f is not None:
            try:
                os.fsync(self._errors_f.fileno())
            except OSError:
                pass
            self._errors_f.close()
            self._errors_f = None

    def __enter__(self) -> "ScoreSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- resume / inspection ------------------------------------------------
    def completed(self) -> dict[int, dict]:
        """shard_index -> DONE record, for every COMPLETE shard: marker
        present and every payload file it lists still on disk (the
        checkpoint completeness rule — a marker beside a vanished payload
        is not a completion)."""
        out: dict[int, dict] = {}
        for marker in _glob.glob(os.path.join(
                self.path, _PART_PREFIX + "*" + _DONE_SUFFIX)):
            try:
                with open(marker) as f:
                    rec = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue  # torn/foreign marker: treat as incomplete
            if not isinstance(rec, dict) or not isinstance(
                    rec.get("files"), list):
                continue  # valid JSON but not OUR record shape: foreign
            try:
                shard = int(rec["shard"])
            except (KeyError, TypeError, ValueError):
                continue
            if all(os.path.exists(os.path.join(self.path, name))
                   for name in rec["files"]):
                out[shard] = rec
        return out

    @staticmethod
    def _read_jsonl_tolerant(path: str) -> list[dict]:
        """Appended diagnostic jsonl with a possibly-torn final line (a
        host killed mid-append): return the intact prefix — the audit
        trail must stay readable in exactly the crash it explains."""
        out = []
        with open(path) as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    out.append(json.loads(ln))
                except json.JSONDecodeError:
                    continue
        return out

    def cursor_records(self) -> list[dict]:
        """Every host's cursor records, in (host, append) order."""
        return [r for p in sorted(_glob.glob(
            os.path.join(self.path, "cursor-*.jsonl")))
            for r in self._read_jsonl_tolerant(p)]

    def error_records(self) -> list[dict]:
        return [r for p in sorted(_glob.glob(
            os.path.join(self.path, "errors-*.jsonl")))
            for r in self._read_jsonl_tolerant(p)]

    def finalize(self, num_shards: int, done: dict | None = None) -> bool:
        """Write ``_SUCCESS`` iff every one of the scan's ``num_shards``
        shards is complete (whichever host finishes last wins the write —
        it is idempotent). Returns scan completeness. ``done`` accepts a
        just-computed :meth:`completed` dict so end-of-scan callers don't
        re-glob + re-parse every marker."""
        done = self.completed() if done is None else done
        complete = all(i in done for i in range(int(num_shards)))
        if complete:
            atomic_write_bytes(
                os.path.join(self.path, SUCCESS_MARKER),
                json.dumps({"shards": int(num_shards),
                            "rows": sum(r["rows"] for r in done.values()),
                            "quarantined_shards": sum(
                                1 for r in done.values()
                                if r.get("quarantined"))},
                           sort_keys=True).encode())
        return complete

    def is_complete(self) -> bool:
        return os.path.exists(os.path.join(self.path, SUCCESS_MARKER))

    def part_files(self, done: dict | None = None) -> list[str]:
        """Completed payload files in shard order (the scan's output set).
        ``done`` as in :meth:`finalize`."""
        done = self.completed() if done is None else done
        return [os.path.join(self.path, name)
                for i in sorted(done) for name in done[i]["files"]]


class JsonlSink(ScoreSink):
    """One ``part-NNNNN.jsonl`` per input shard. ``columns=None`` writes
    every output column; pass a list to project (e.g. drop the raw input
    features from an embedding backfill)."""

    format = "jsonl"

    def __init__(self, path: str, columns: Sequence[str] | None = None):
        super().__init__(path)
        self.columns = list(columns) if columns else None

    def _open_writers(self, shard_index: int):
        return [iofiles.jsonl_writer(os.path.join(
            self.path, self.part_stem(shard_index) + ".jsonl"))]

    def _write_chunk(self, writers, cols: dict, n_valid: int) -> None:
        names = self.columns or list(cols.keys())
        missing = [c for c in names if c not in cols]
        if missing:
            raise ValueError(f"sink columns {missing} not in scored batch "
                             f"(has {sorted(cols)})")
        writers[0].write_columns({c: cols[c] for c in names}, n_valid)

    def collect_rows(self) -> list[dict]:
        """Read every completed part back, in shard order (test/bench
        surface — NOT a bulk API; the output of a real scan is consumed
        file-by-file)."""
        rows: list[dict] = []
        for p in self.part_files():
            with open(p) as f:
                rows += [iofiles.loads_jsonl_line(ln, p, k + 1)
                         for k, ln in enumerate(f) if ln.strip()]
        return rows


class NpySink(ScoreSink):
    """One ``part-NNNNN.<col>.npy`` per selected column per shard — the
    embedding-corpus layout (rectangular numeric outputs, zero JSON
    overhead)."""

    format = "npy"

    def __init__(self, path: str, columns: Sequence[str]):
        super().__init__(path)
        if not columns:
            raise ValueError("NpySink needs an explicit column list "
                             "(e.g. columns=['prediction'])")
        self.columns = list(columns)

    def _open_writers(self, shard_index: int):
        stem = self.part_stem(shard_index)
        return [iofiles.npy_writer(os.path.join(
            self.path, f"{stem}.{c}.npy")) for c in self.columns]

    def _write_chunk(self, writers, cols: dict, n_valid: int) -> None:
        for w, c in zip(writers, self.columns):
            if c not in cols:
                raise ValueError(f"sink column {c!r} not in scored batch "
                                 f"(has {sorted(cols)})")
            w.append(np.asarray(cols[c])[:n_valid])

    def collect_column(self, column: str) -> np.ndarray:
        """Concatenate one column across completed parts, shard order.
        Zero-row parts (a shard whose every row quarantined) carry no
        dtype/trailing-shape information — the streamed writer stamps them
        ``(0,)`` float64 — so they are skipped rather than poisoning the
        concatenation."""
        done = self.completed()
        # exact payload names — a suffix match would also collect
        # 'raw.a' parts when asked for column 'a'
        chunks = [np.load(os.path.join(self.path, name))
                  for i in sorted(done) for name in done[i]["files"]
                  if name == f"{self.part_stem(i)}.{column}.npy"]
        chunks = [c for c in chunks if c.shape[0]]
        if not chunks:
            return np.empty(0)
        return np.concatenate(chunks, axis=0)


def open_sink(path: str, format: str = "jsonl",
              columns: Sequence[str] | None = None) -> ScoreSink:
    """Sink factory: ``format`` is ``'jsonl'`` or ``'npy'``."""
    if format == "jsonl":
        return JsonlSink(path, columns=columns)
    if format == "npy":
        if columns is None:
            raise ValueError("format='npy' requires columns=[...]")
        return NpySink(path, columns=columns)
    raise ValueError(f"unknown sink format {format!r}; "
                     "one of ('jsonl', 'npy')")
