"""Distributed bulk-scoring plane: out-of-core ``transform_source`` with
exactly-once sharded sinks.

The Spark ``transform()``-over-arbitrarily-large-DataFrames role rebuilt on
the streaming data plane — the offline-batch workload class (backfills,
embedding corpora, nightly scoring for millions of users) that
request/response serving can't touch:

* :mod:`.planner` — per-host shard assignment off the jax process topology
  (strided disjoint exact cover) + bucket-ladder batch formation with
  tail-rung padding, so a whole corpus scan compiles at most ladder-many
  executables per stage fn through the shared ``core/batching``
  ``CompiledCache``.
* :mod:`.sink` — sharded jsonl/npy sinks with atomic write-then-rename part
  files, per-shard DONE markers, an append-only per-host cursor, and a
  quarantine errors sidecar: kill/resume emits each input row exactly once.
* :mod:`.runner` — :func:`~synapseml_tpu.scoring.runner.transform_source`:
  a bounded-queue pipeline overlapping shard read -> host prep -> device
  compute -> sink write, with ``synapseml_scoring_*`` metrics, one span per
  shard, retried reads, and poisoned-row/shard quarantine.

Entry point: every fitted ``Transformer``/``PipelineModel`` carries
``stage.transform_source(source, sink)`` (wired in ``core/pipeline.py``).
See ``docs/SCORING.md``.
"""

from .planner import (ScoringPlan, assign_shards, iter_shard_batches,  # noqa: F401
                      plan_scan)
from .runner import (ScoringContractError, ScoringReport,  # noqa: F401
                     transform_source)
from .sink import JsonlSink, NpySink, ScoreSink, open_sink  # noqa: F401

__all__ = [
    "ScoringPlan", "assign_shards", "plan_scan", "iter_shard_batches",
    "transform_source", "ScoringReport", "ScoringContractError",
    "ScoreSink", "JsonlSink", "NpySink", "open_sink",
]
