"""Bulk-scoring scan planning: per-host shard assignment + batch formation.

The distributed half of ``transform_source``: every host derives the SAME
plan from the jax process topology — the shard list in source order, each
host taking the strided slice ``range(num_shards)[host_index::host_count]``
(mirroring ``data.DataLoader``'s per-host striding, minus the seeded
shuffle: a scoring scan is order-deterministic so kill/resume can prove
byte-identical output). The slices are a disjoint exact cover of the
dataset, asserted by ``tests/test_scoring.py``.

Batch formation rides the ``core/batching`` bucket ladder: a shard's rows
chunk at the largest ladder rung <= ``batch_rows`` and the final partial
chunk pads to its OWN rung (``ShapeBucketer.slices``), so a whole corpus
scan presents at most :attr:`ScoringPlan.buckets` distinct batch shapes to
every stage — compile count <= ladder size per stage fn through the shared
``CompiledCache``, enforced by the miss-counter test.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator

import numpy as np

from ..core import batching as cb
from ..data.source import ShardedSource, _n_rows, resolve_host

__all__ = ["ScoringPlan", "assign_shards", "plan_scan", "iter_shard_batches"]


def assign_shards(num_shards: int, host_index: int | None = None,
                  host_count: int | None = None) -> list[int]:
    """This host's shard indices: the strided slice
    ``range(num_shards)[host_index::host_count]``. Defaults come from the
    jax process topology; the slices across hosts partition the shard set
    exactly (disjoint, union complete)."""
    host_index, host_count = resolve_host(host_index, host_count)
    return list(range(int(num_shards)))[host_index::host_count]


@dataclasses.dataclass(frozen=True)
class ScoringPlan:
    """One host's share of a corpus scan, plus the closed set of batch
    shapes the scan can emit (the warmup/precompile set AND the compile
    bound)."""

    num_shards: int                 # whole dataset, all hosts
    shard_indices: tuple[int, ...]  # this host's assignment, scan order
    host_index: int
    host_count: int
    batch_rows: int                 # chunking cap (ladder-aligned by slices)
    multiple_of: int
    buckets: tuple[int, ...]        # every padded batch size the scan emits


def plan_scan(source: ShardedSource, batch_rows: int = 256,
              bucketer: cb.ShapeBucketer | None = None,
              multiple_of: int = 1, host_index: int | None = None,
              host_count: int | None = None) -> ScoringPlan:
    """Derive this host's :class:`ScoringPlan` for ``source``."""
    if batch_rows < 1:
        raise ValueError(f"batch_rows must be >= 1, got {batch_rows}")
    b = bucketer or cb.default_bucketer()
    host_index, host_count = resolve_host(host_index, host_count)
    mine = assign_shards(source.num_shards, host_index, host_count)
    return ScoringPlan(
        num_shards=source.num_shards, shard_indices=tuple(mine),
        host_index=host_index, host_count=host_count,
        batch_rows=int(batch_rows), multiple_of=max(int(multiple_of), 1),
        buckets=tuple(b.buckets_upto(batch_rows, multiple_of)))


def _pad_any(a: np.ndarray, bucket: int, mode: str) -> np.ndarray:
    """``cb.pad_rows`` extended to non-numeric columns: scoring corpora
    carry string ids/urls and heterogeneous-key (object) passthrough
    columns, which always pad edge-style (repeat the last real row —
    padded rows are stripped from the output, their content only has to
    be shape-valid for the stage)."""
    if a.dtype == object or a.dtype.kind in "US":
        n = a.shape[0]
        pad = int(bucket) - n
        if pad <= 0 or not n:
            return a
        return np.concatenate([a, np.repeat(a[-1:], pad, axis=0)], axis=0)
    return cb.pad_rows(a, bucket, mode=mode)


def iter_shard_batches(cols: dict, batch_rows: int,
                       bucketer: cb.ShapeBucketer | None = None,
                       multiple_of: int = 1, pad_mode: str = "edge"
                       ) -> Iterator[tuple[dict, int, int, int]]:
    """Chunk one shard's columnar dict into fixed-shape batches:
    ``(padded_batch, n_valid, bucket, row_offset)`` per chunk. Full chunks
    run at the ladder-aligned cap; the tail pads to its own rung
    (``pad_mode='edge'`` repeats the last real row — the ONNXModel padding,
    safe for models where an all-zero row hits a different numeric path;
    string/object passthrough columns always edge-pad). Padded rows are
    stripped from the transform OUTPUT by the runner, never written to the
    sink."""
    b = bucketer or cb.default_bucketer()
    n = _n_rows(cols)
    for start, stop, bucket in b.slices(n, batch_rows, multiple_of):
        batch = {k: _pad_any(np.asarray(v)[start:stop], bucket, pad_mode)
                 for k, v in cols.items()}
        yield batch, stop - start, bucket, start
