"""``transform_source``: stream an out-of-core source through a fitted
pipeline into an exactly-once sharded sink.

The offline-batch workload class (backfills, embedding corpora, nightly
scoring) the request/response serving plane can't touch — the Spark
``transform()``-over-arbitrarily-large-DataFrames role, rebuilt on the
streaming data plane. End-to-end throughput is set by OVERLAP of I/O, host
prep, and device compute (the arXiv:1810.11112 input-pipeline discipline),
so the runner is a three-stage bounded-queue pipeline:

    reader thread   -> shard read (+ retry/fault guards) + schema prep
    main thread     -> bucket-ladder batches through ``stage.transform``
    writer thread   -> streamed part writes, DONE markers, cursor appends

Memory is bounded by (prefetch + in-flight) shards, never the dataset.
Exactly-once comes from the sink's atomic-part + DONE-marker + cursor
discipline (``scoring/sink.py``): a killed scan resumes by skipping
completed shards and re-running the rest, producing byte-identical output.

Resilience: shard-read faults (``FaultPlan.on_read``) retry under the
source's ``RetryPolicy`` inside ``ShardedSource.read_shard``; a shard whose
reads exhaust retries — or a row whose transform raises — is quarantined to
the errors sidecar instead of killing the scan (``on_error='quarantine'``,
the default; ``'raise'`` propagates). Sink/write failures always propagate:
losing output silently is never acceptable.

Observability: ``synapseml_scoring_*`` series (rows/sec, shard progress,
queue depths, padded-vs-real rows, resume skips, quarantines) in the
unified registry plus one ``scoring.shard`` span per shard.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time

import numpy as np

from ..core import batching as cb
from ..core import observability as obs
from ..core.dataframe import DataFrame
from ..data.source import ShardedSource, _n_rows
from .planner import ScoringPlan, iter_shard_batches, plan_scan
from .sink import ScoreSink

__all__ = ["transform_source", "ScoringReport", "ScoringContractError"]

_END = object()

_SCORING_METRICS = obs.HandleCache(lambda reg: {
    "rows": reg.counter(
        "synapseml_scoring_rows_total",
        "input rows scored and written to the sink", ("format",)),
    "padded": reg.counter(
        "synapseml_scoring_padded_rows_total",
        "pad rows added by bucket-ladder batch formation (wasted compute)",
        ("format",)),
    "shards": reg.counter(
        "synapseml_scoring_shards_total",
        "shards finished by outcome",
        ("format", "status")),  # done | skipped | quarantined
    "batch_ms": reg.histogram(
        "synapseml_scoring_batch_ms",
        "wall time of one batch through stage.transform", ("format",)),
    "quarantined_rows": reg.counter(
        "synapseml_scoring_quarantined_rows_total",
        "poisoned rows diverted to the errors sidecar", ("format",)),
    "read_queue": reg.gauge(
        "synapseml_scoring_read_queue_depth",
        "prefetched shards buffered ahead of the compute loop", ("format",)),
    "write_queue": reg.gauge(
        "synapseml_scoring_write_queue_depth",
        "scored batches buffered ahead of the sink writer", ("format",)),
    "rows_per_sec": reg.gauge(
        "synapseml_scoring_rows_per_sec",
        "scan throughput since the run started", ("format",)),
    "progress": reg.gauge(
        "synapseml_scoring_progress_pct",
        "scan progress for this host (shards seen / shards assigned)",
        ("format",)),
    "eta": reg.gauge(
        "synapseml_scoring_eta_s",
        "estimated seconds to scan completion for this host", ("format",)),
})


class ScoringContractError(RuntimeError):
    """The stage broke the bulk-scoring contract (e.g. changed the row
    count): a configuration error, never quarantined."""


@dataclasses.dataclass
class ScoringReport:
    """What one ``transform_source`` call did (one host's view)."""

    rows_written: int = 0
    rows_padded: int = 0
    batches: int = 0
    shards_assigned: int = 0
    shards_done: int = 0
    shards_skipped: int = 0        # resume: already complete in the sink
    rows_quarantined: int = 0
    shards_quarantined: int = 0
    wall_s: float = 0.0
    rows_per_sec: float = 0.0
    complete: bool = False         # whole scan (all hosts) — _SUCCESS written
    estimated_rows: int | None = None   # whole dataset, estimate_rows()
    peak_inflight_bytes: int = 0   # max bytes buffered across the queues
    parts: list = dataclasses.field(default_factory=list)
    sink_path: str = ""


def transform_source(stage, source: ShardedSource, sink: ScoreSink, *,
                     batch_rows: int = 256,
                     bucketer: cb.ShapeBucketer | None = None,
                     multiple_of: int = 1, pad_mode: str = "edge",
                     columns: list[str] | None = None,
                     host_index: int | None = None,
                     host_count: int | None = None,
                     on_error: str = "quarantine",
                     prefetch: int = 2, write_queue: int = 4,
                     estimate: bool = True) -> ScoringReport:
    """Score every row of ``source`` through ``stage.transform`` into
    ``sink``, exactly once, in bounded memory. See the module docstring;
    ``columns`` selects the input columns handed to the stage (heterogeneous
    corpora), ``batch_rows`` caps batch memory (chunking runs at ladder
    rungs <= it). Returns this host's :class:`ScoringReport`."""
    if not callable(getattr(stage, "transform", None)):
        raise TypeError(f"{type(stage).__name__} has no transform(); "
                        "transform_source needs a fitted Transformer")
    if on_error not in ("quarantine", "raise"):
        raise ValueError(f"on_error must be 'quarantine' or 'raise', "
                         f"got {on_error!r}")
    plan = plan_scan(source, batch_rows, bucketer, multiple_of,
                     host_index, host_count)
    b = bucketer or cb.default_bucketer()
    m = _SCORING_METRICS.get()
    fmt = sink.format
    report = ScoringReport(shards_assigned=len(plan.shard_indices),
                           sink_path=sink.path)

    done = sink.completed()
    todo = [i for i in plan.shard_indices if i not in done]
    report.shards_skipped = len(plan.shard_indices) - len(todo)
    if report.shards_skipped:
        m["shards"].inc(report.shards_skipped, format=fmt, status="skipped")
    if estimate:
        try:
            # read_fallback=False: a progress gauge must never cost a full
            # shard read (custom-reader sources just report no estimate)
            report.estimated_rows = source.estimate_rows(read_fallback=False)
        except Exception:  # noqa: BLE001 — progress is best-effort
            report.estimated_rows = None

    t_start = time.perf_counter()
    runner = _Runner(stage, source, sink, plan, b, pad_mode, columns,
                     on_error, prefetch, write_queue, report, m, fmt,
                     t_start)
    try:
        runner.run(todo)
    finally:
        runner.shutdown()
    end_done = sink.completed()  # ONE end-of-scan marker scan, reused
    report.complete = sink.finalize(plan.num_shards, done=end_done)
    report.wall_s = time.perf_counter() - t_start
    report.rows_per_sec = (report.rows_written / report.wall_s
                           if report.wall_s > 0 else 0.0)
    report.parts = sink.part_files(done=end_done)
    m["rows_per_sec"].set(report.rows_per_sec, format=fmt)
    return report


class _Runner:
    """One scan's thread plumbing (reader -> compute -> writer)."""

    def __init__(self, stage, source, sink, plan: ScoringPlan, bucketer,
                 pad_mode, columns, on_error, prefetch, write_queue,
                 report: ScoringReport, metrics, fmt, t_start):
        self.stage, self.source, self.sink, self.plan = stage, source, sink, plan
        self.bucketer, self.pad_mode = bucketer, pad_mode
        self.columns = list(columns) if columns else None
        self.on_error = on_error
        self.report, self.m, self.fmt = report, metrics, fmt
        self.t_start = t_start
        self._stop = threading.Event()
        self._read_q: "queue.Queue" = queue.Queue(maxsize=max(int(prefetch), 1))
        self._write_q: "queue.Queue" = queue.Queue(
            maxsize=max(int(write_queue), 1))
        self._writer_error: list[BaseException] = []
        self._inflight_bytes = 0
        self._inflight_lock = threading.Lock()
        self._reader: threading.Thread | None = None
        self._writer: threading.Thread | None = None

    # -- bounded-memory accounting ------------------------------------------
    def _track(self, nbytes: int) -> None:
        with self._inflight_lock:
            self._inflight_bytes += nbytes
            if self._inflight_bytes > self.report.peak_inflight_bytes:
                self.report.peak_inflight_bytes = self._inflight_bytes

    def _untrack(self, nbytes: int) -> None:
        with self._inflight_lock:
            self._inflight_bytes -= nbytes

    # -- reader thread ------------------------------------------------------
    def _read_loop(self, todo: list[int]) -> None:
        shards = self.source.shards()
        for i in todo:
            if self._stop.is_set():
                return
            try:
                cols = self.source.read_shard(shards[i])
                if self.columns is not None:
                    missing = [c for c in self.columns if c not in cols]
                    if missing and cols:
                        raise ScoringContractError(
                            f"shard {shards[i].target} is missing column(s) "
                            f"{missing}; pass columns=[...] that every "
                            "shard carries")
                    cols = {c: cols[c] for c in self.columns if c in cols}
                item = ("shard", i, cols, _cols_nbytes(cols))
                self._track(item[3])
            except ScoringContractError as e:
                item = ("config_error", i, e, 0)
            except Exception as e:  # noqa: BLE001 — retries exhausted
                item = ("read_error", i, e, 0)
            if not self._put(self._read_q, item):
                return
            self.m["read_queue"].set(self._read_q.qsize(), format=self.fmt)
        self._put(self._read_q, _END)

    # -- writer thread ------------------------------------------------------
    def _write_loop(self) -> None:
        open_part = None
        try:
            while True:
                cmd = self._write_q.get()
                self.m["write_queue"].set(self._write_q.qsize(),
                                          format=self.fmt)
                if cmd is _END:
                    return
                verb = cmd[0]
                if verb == "begin":
                    open_part = self.sink.begin_shard(
                        cmd[1], self.plan.host_index)
                elif verb == "write":
                    _, cols, n_valid, nbytes = cmd
                    open_part.write(cols, n_valid)
                    self._untrack(nbytes)
                elif verb == "finish":
                    _, rows, padded, quarantined = cmd
                    open_part.finish()
                    open_part = None
                    # commit accounting lives HERE, after finish() returned:
                    # the DONE marker exists, so monotonic counters can
                    # never record rows that exist in no output file
                    self.report.shards_done += 1
                    self.m["rows"].inc(rows, format=self.fmt)
                    self.m["padded"].inc(padded, format=self.fmt)
                    if quarantined:
                        self.m["quarantined_rows"].inc(quarantined,
                                                       format=self.fmt)
                    self.m["shards"].inc(format=self.fmt, status="done")
                elif verb == "abort_shard":
                    # shard-level quarantine mid-shard: discard its temp
                    # payload so nothing partial can ever commit
                    if open_part is not None:
                        open_part.abort()
                        open_part = None
                elif verb == "quarantine_shard":
                    self.sink.mark_quarantined(cmd[1], self.plan.host_index,
                                               cmd[2])
                elif verb == "quarantine_row":
                    self.sink.quarantine(self.plan.host_index, cmd[1])
        except BaseException as e:  # noqa: BLE001 — surfaced to the main loop
            self._writer_error.append(e)
            self._stop.set()
            # drain so a blocked producer wakes and sees the stop flag
            while True:
                try:
                    self._write_q.get_nowait()
                except queue.Empty:
                    break
        finally:
            if open_part is not None:
                open_part.abort()

    def _put(self, q: "queue.Queue", item) -> bool:
        while not self._stop.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _send_write(self, cmd) -> None:
        if not self._put(self._write_q, cmd) or self._writer_error:
            raise self._writer_error[0] if self._writer_error \
                else RuntimeError("scoring writer stopped")

    # -- compute (main thread) ----------------------------------------------
    def run(self, todo: list[int]) -> None:
        if not todo:
            return
        self._reader = threading.Thread(
            target=self._read_loop, args=(todo,), daemon=True)
        self._writer = threading.Thread(target=self._write_loop, daemon=True)
        self._reader.start()
        self._writer.start()
        tracer = obs.get_tracer()
        shards = self.source.shards()
        while True:
            # timed get + stop check: a writer failure stops the reader
            # before its _END sentinel, so the compute loop must notice the
            # stop flag itself rather than block forever
            try:
                item = self._read_q.get(timeout=0.5)
            except queue.Empty:
                if self._stop.is_set():
                    raise self._writer_error[0] if self._writer_error \
                        else RuntimeError("scoring reader stopped")
                continue
            if item is _END:
                break
            kind, i, payload, nbytes = item
            self.m["read_queue"].set(self._read_q.qsize(), format=self.fmt)
            if kind == "config_error":
                raise payload
            if kind == "read_error":
                if self.on_error == "raise":
                    raise payload
                self._send_write(("quarantine_shard", i, repr(payload)))
                self.report.shards_quarantined += 1
                self.m["shards"].inc(format=self.fmt, status="quarantined")
                continue
            shard = shards[i]
            with tracer.span("scoring.shard",
                             {"shard": i, "target": shard.target,
                              "rows": _n_rows(payload)}):
                rep = self.report
                snap = (rep.rows_written, rep.rows_padded, rep.batches,
                        rep.rows_quarantined)
                try:
                    self._score_shard(i, payload)
                except ScoringContractError:
                    raise  # configuration error, never contained
                except Exception as e:  # noqa: BLE001 — shard quarantine
                    if self.on_error == "raise":
                        raise
                    # e.g. batch formation failed on this shard's columns:
                    # abort the open part (nothing partial commits), roll
                    # the report back to pre-shard, quarantine the shard
                    (rep.rows_written, rep.rows_padded, rep.batches,
                     rep.rows_quarantined) = snap
                    self._send_write(("abort_shard",))
                    self._send_write(("quarantine_shard", i,
                                      f"shard scoring failed: {e!r}"))
                    rep.shards_quarantined += 1
                    self.m["shards"].inc(format=self.fmt,
                                         status="quarantined")
            self._untrack(nbytes)
            self._progress()
        self._send_write(_END)
        self._writer.join()
        if self._writer_error:
            raise self._writer_error[0]
        # shards_done moves on the writer thread at commit time, so the
        # per-shard progress updates lag it — settle the gauges now that
        # every commit is in
        self._progress()

    def _score_shard(self, i: int, cols: dict) -> None:
        self._send_write(("begin", i))
        rows = padded = quarantined_total = 0
        for batch, n_valid, bucket, offset in iter_shard_batches(
                cols, self.plan.batch_rows, self.bucketer,
                self.plan.multiple_of, self.pad_mode):
            t0 = time.perf_counter()
            out, quarantined = self._score_batch(batch, n_valid, bucket,
                                                 shard_index=i, offset=offset)
            self.m["batch_ms"].observe((time.perf_counter() - t0) * 1e3,
                                       format=self.fmt)
            n_out = _n_rows(out) if out else 0
            if n_out:
                nbytes = _cols_nbytes(out)
                self._track(nbytes)
                self._send_write(("write", out, n_out, nbytes))
            self.report.batches += 1
            self.report.rows_written += n_out
            self.report.rows_padded += bucket - n_valid
            self.report.rows_quarantined += quarantined
            rows += n_out
            padded += bucket - n_valid
            quarantined_total += quarantined
        # the writer increments shards_done + the monotonic counters AFTER
        # open_part.finish() returns (part + DONE marker on disk) — a shard
        # that never commits, whether quarantined here or dead in the
        # writer, moves no counter
        self._send_write(("finish", rows, padded, quarantined_total))

    def _score_batch(self, batch: dict, n_valid: int, bucket: int, *,
                     shard_index: int, offset: int) -> tuple[dict, int]:
        """One fixed-shape batch through the stage. Returns (unpadded output
        columns, quarantined-row count). A batch-level exception falls back
        to row-at-a-time scoring so ONE poisoned row costs one sidecar
        record, not the scan."""
        try:
            return self._transform_cols(batch, n_valid, bucket), 0
        except ScoringContractError:
            raise
        except Exception as batch_err:  # noqa: BLE001 — contained below
            if self.on_error == "raise":
                raise
            good: list[dict] = []
            quarantined = 0
            for r in range(n_valid):
                row = {k: np.asarray(v)[r:r + 1] for k, v in batch.items()}
                try:
                    good.append(self._transform_cols(row, 1, 1))
                except Exception as row_err:  # noqa: BLE001
                    quarantined += 1
                    self._send_write(("quarantine_row", {
                        "kind": "row", "shard": shard_index,
                        "row": offset + r,
                        "error": repr(row_err),
                        "batch_error": repr(batch_err),
                        "data": _json_safe_row(batch, r)}))
            if not good:
                out: dict = {}
            else:
                out = {k: np.concatenate([g[k] for g in good])
                       for k in good[0]}
            return out, quarantined

    def _transform_cols(self, batch: dict, n_valid: int,
                        bucket: int) -> dict:
        out = self.stage.transform(DataFrame([batch])).collect()
        n_out = _n_rows(out)
        if n_out != bucket:
            raise ScoringContractError(
                f"{type(self.stage).__name__}.transform returned {n_out} "
                f"rows for a {bucket}-row batch; transform_source needs a "
                "row-preserving transformer (filters/aggregations have no "
                "exactly-once row mapping)")
        return {k: np.asarray(v)[:n_valid] for k, v in out.items()}

    def _progress(self) -> None:
        rep = self.report
        wall = time.perf_counter() - self.t_start
        rate = rep.rows_written / wall if wall > 0 else 0.0
        self.m["rows_per_sec"].set(rate, format=self.fmt)
        assigned = max(len(self.plan.shard_indices), 1)
        seen = rep.shards_skipped + rep.shards_done + rep.shards_quarantined
        # pct is pure shard counting — no row estimate needed, so even
        # custom-reader sources (estimated_rows=None) get a progress gauge
        self.m["progress"].set(min(100.0 * seen / assigned, 100.0),
                               format=self.fmt)
        if rep.estimated_rows and self.plan.num_shards:
            host_est = rep.estimated_rows * assigned / self.plan.num_shards
            if rate > 0:
                # remaining work by UNSEEN shard fraction — resumed scans
                # skip shards whose rows this run never wrote, so
                # host_est - rows_written would never converge to 0
                remaining = host_est * max(assigned - seen, 0) / assigned
                self.m["eta"].set(remaining / rate, format=self.fmt)

    def shutdown(self) -> None:
        self._stop.set()
        for q in (self._read_q, self._write_q):
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
        for t in (self._reader, self._writer):
            if t is not None:
                try:
                    # unblock a writer parked on an empty queue
                    self._write_q.put_nowait(_END)
                except queue.Full:
                    pass
                t.join(timeout=5.0)
        self.sink.close()


def _cols_nbytes(cols: dict) -> int:
    total = 0
    for v in cols.values():
        a = np.asarray(v)
        total += int(a.nbytes) if a.dtype != object else 64 * a.size
    return total


def _json_safe_row(batch: dict, r: int) -> dict:
    """A truncated, JSON-safe copy of one input row for the errors sidecar."""
    out = {}
    for k, v in batch.items():
        val = np.asarray(v)[r]
        if isinstance(val, np.ndarray) and val.size > 16:
            out[k] = val.ravel()[:16].tolist() + ["..."]
        else:
            out[k] = val
    return out
