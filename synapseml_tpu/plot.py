"""Plotting helpers — the reference's ``synapse.ml.plot`` python glue
(``core/src/main/python/synapse/ml/plot/plot.py``: confusionMatrix + roc over
a scored DataFrame).

Accepts this framework's DataFrame or a pandas frame; renders onto the
current matplotlib axes (Agg-safe) and returns the Axes so notebooks can
compose. ``confusionMatrix``/``roc`` aliases keep the reference's camelCase
call sites working verbatim.
"""

from __future__ import annotations

import numpy as np

__all__ = ["confusion_matrix_plot", "roc_plot", "confusionMatrix", "roc"]


def _columns(df, cols):
    if hasattr(df, "collect_column"):  # synapseml_tpu DataFrame
        return [np.asarray(df.collect_column(c)) for c in cols]
    return [np.asarray(df[c]) for c in cols]


def confusion_matrix_plot(df, y_col: str, y_hat_col: str, labels, ax=None):
    """Row-normalized confusion-matrix heatmap with per-cell counts and the
    accuracy in the title area (the reference's layout)."""
    import matplotlib.pyplot as plt
    from sklearn.metrics import confusion_matrix

    y, y_hat = _columns(df, [y_col, y_hat_col])
    ax = ax or plt.gca()
    accuracy = float(np.mean(np.asarray(y) == np.asarray(y_hat)))
    cm = confusion_matrix(y, y_hat)
    cmn = cm.astype(float) / np.maximum(cm.sum(axis=1)[:, None], 1)
    im = ax.imshow(cmn, interpolation="nearest", cmap="Blues", vmin=0, vmax=1)
    ticks = np.arange(len(labels))
    ax.set_xticks(ticks, labels=labels)
    ax.set_yticks(ticks, labels=labels, rotation=90)
    for i in range(cm.shape[0]):
        for j in range(cm.shape[1]):
            ax.text(j, i, str(cm[i, j]), ha="center",
                    color="white" if cmn[i, j] > 0.1 else "black")
    ax.set_xlabel("Predicted Label")
    ax.set_ylabel("True Label")
    ax.set_title(f"Accuracy = {accuracy * 100:.1f}%")
    ax.figure.colorbar(im, ax=ax)
    return ax


def roc_plot(df, y_col: str, y_hat_col: str, thresh: float = 0.5, ax=None):
    """ROC curve of score column vs (thresholded) label column, AUC in the
    legend."""
    import matplotlib.pyplot as plt
    from sklearn.metrics import auc, roc_curve

    y, scores = _columns(df, [y_col, y_hat_col])
    y_bin = (np.asarray(y, dtype=float) > thresh).astype(int)
    fpr, tpr, _ = roc_curve(y_bin, np.asarray(scores, dtype=float))
    ax = ax or plt.gca()
    ax.plot(fpr, tpr, label=f"AUC = {auc(fpr, tpr):.3f}")
    ax.plot([0, 1], [0, 1], linestyle="--", linewidth=0.8)
    ax.set_xlabel("False Positive Rate")
    ax.set_ylabel("True Positive Rate")
    ax.legend(loc="lower right")
    return ax


# reference-verbatim camelCase call sites
confusionMatrix = confusion_matrix_plot
roc = roc_plot
