"""Plotting helpers — the reference's ``synapse.ml.plot`` python glue
(``core/src/main/python/synapse/ml/plot/plot.py``: confusionMatrix + roc over
a scored DataFrame).

Accepts this framework's DataFrame or a pandas frame; renders onto the
current matplotlib axes (Agg-safe) and returns the Axes so notebooks can
compose. Metric math comes from :mod:`synapseml_tpu.train.statistics`
(pure numpy — no sklearn dependency). ``confusionMatrix``/``roc`` aliases
keep the reference's camelCase call sites working verbatim.
"""

from __future__ import annotations

import numpy as np

__all__ = ["confusion_matrix_plot", "roc_plot", "confusionMatrix", "roc"]


def _columns(df, cols):
    if hasattr(df, "collect_column"):  # synapseml_tpu DataFrame
        return [np.asarray(df.collect_column(c)) for c in cols]
    return [np.asarray(df[c]) for c in cols]


def confusion_matrix_plot(df, y_col: str, y_hat_col: str, labels, ax=None):
    """Row-normalized confusion-matrix heatmap with per-cell counts and the
    accuracy in the title (the reference's layout). ``labels`` PINS the
    row/column order — classes are matched to it, absent classes render as
    empty rows/columns rather than shifting the grid."""
    import matplotlib.pyplot as plt

    y, y_hat = _columns(df, [y_col, y_hat_col])
    ax = ax or plt.gca()
    accuracy = float(np.mean(y == y_hat))
    k = len(labels)
    # build the matrix against the CALLER'S label order; integer-coded
    # classes index positionally into `labels` (the reference's usage)
    if y.dtype.kind in "iub" and not any(v in set(labels) for v in np.unique(y)):
        classes = list(range(k))
    else:
        classes = list(labels)
    lut = {c: i for i, c in enumerate(classes)}
    cm = np.zeros((k, k), dtype=np.int64)
    for t, p in zip(y, y_hat):
        ti, pi = lut.get(t), lut.get(p)
        if ti is not None and pi is not None:
            cm[ti, pi] += 1
    cmn = cm.astype(float) / np.maximum(cm.sum(axis=1)[:, None], 1)
    im = ax.imshow(cmn, interpolation="nearest", cmap="Blues", vmin=0, vmax=1)
    ticks = np.arange(k)
    ax.set_xticks(ticks, labels=labels)
    ax.set_yticks(ticks, labels=labels, rotation=90)
    for i in range(k):
        for j in range(k):
            ax.text(j, i, str(cm[i, j]), ha="center",
                    color="white" if cmn[i, j] > 0.5 else "black")
    ax.set_xlabel("Predicted Label")
    ax.set_ylabel("True Label")
    ax.set_title(f"Accuracy = {accuracy * 100:.1f}%")
    ax.figure.colorbar(im, ax=ax)
    return ax


def roc_plot(df, y_col: str, y_hat_col: str, thresh: float = 0.5, ax=None):
    """ROC curve of the score column vs the label column, AUC in the legend.

    Labels binarize with the same ``> 0`` convention as
    :func:`synapseml_tpu.train.statistics.roc_auc` for numeric labels (so
    {0,1} and {-1,1} codings both work); non-numeric labels use the
    second-sorted class as positive. ``thresh`` only applies when the label
    column is itself a float score (the reference's signature).
    """
    import matplotlib.pyplot as plt

    from .train.statistics import roc_auc

    y, scores = _columns(df, [y_col, y_hat_col])
    scores = np.asarray(scores, dtype=float)
    if y.dtype.kind == "f":
        y_bin = (y > thresh).astype(int)
    elif y.dtype.kind in "iub":
        y_bin = (y > 0).astype(int)
    else:  # string/object labels: positive = last class in sorted order
        classes = sorted(set(y.tolist()))
        if len(classes) != 2:
            raise ValueError(f"roc needs binary labels, got {classes}")
        y_bin = (y == classes[1]).astype(int)

    # fpr/tpr by descending-score sweep (pure numpy)
    order = np.argsort(-scores, kind="stable")
    ys = y_bin[order]
    tp = np.concatenate([[0], np.cumsum(ys)])
    fp = np.concatenate([[0], np.cumsum(1 - ys)])
    n_pos, n_neg = max(tp[-1], 1), max(fp[-1], 1)
    tpr, fpr = tp / n_pos, fp / n_neg
    ax = ax or plt.gca()
    ax.plot(fpr, tpr, label=f"AUC = {roc_auc(y_bin, scores):.3f}")
    ax.plot([0, 1], [0, 1], linestyle="--", linewidth=0.8)
    ax.set_xlabel("False Positive Rate")
    ax.set_ylabel("True Positive Rate")
    ax.legend(loc="lower right")
    return ax


# reference-verbatim camelCase call sites
confusionMatrix = confusion_matrix_plot
roc = roc_plot
