"""synapseml_tpu — a TPU-native distributed ML framework with the capability
surface of SynapseML (reference surveyed in SURVEY.md), built on JAX/Flax/
Pallas/pjit with a C++ native runtime for host-side hot paths.

Subpackages mirror the reference's module layout:
  core/        data plane (DataFrame), params, pipeline API, logging, utils
  data/        streaming plane: sharded sources, prefetching loader, resume
  parallel/    the one communication backend: mesh, collectives, checkpoint
  ops/         Pallas/XLA kernels (histogram, ring attention, quantize)
  models/      Flax model zoo + DeepText/DeepVision/CausalLM estimators
  lightgbm/    GBDT estimators on a Pallas histogram engine
  vw/          hashed-feature linear/bandit learners + policy evaluation
  image/       ImageTransformer-equivalent preprocessing
  onnx/        ONNX protobuf import -> JAX inference path
  io/          HTTP-on-Spark-equivalent client stack + serving
  services/    AI service transformers (OpenAI et al.)
  stages/      generic transformers (minibatch, lambda, repartition, ...)
  featurize/   auto-featurization, text featurization
  explainers/  LIME/SHAP/ICE
  causal/      DoubleML, diff-in-diff, synthetic control
  recommendation/ SAR, ranking evaluation
  nn/          KNN (TPU brute-force matmul + ball tree)
  automl/      hyperparameter search, FindBestModel
  train/       TrainClassifier/TrainRegressor/ComputeModelStatistics
  exploratory/ data balance measures
  cyber/       access-anomaly detection
  isolationforest/ isolation forest
"""

__version__ = "0.1.0"

from .core import (  # noqa: F401
    DataFrame,
    Estimator,
    GlobalParams,
    Model,
    Pipeline,
    PipelineModel,
    PipelineStage,
    Transformer,
    load_stage,
)
