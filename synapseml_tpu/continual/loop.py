"""The declarative flywheel: watch → retrain → gate → publish → canary.

:class:`ContinualLoop` drives one model's serve→log→retrain→canary cycle
from a single :class:`ContinualSpec`. An iteration (:meth:`ContinualLoop.
run_once`) walks seven seams, each consulting the active ``FaultPlan``
(``plan.on_continual("<model>:<seam>")``) so a seeded chaos plan can fail
any one of them:

====================  ====================================================
seam                  degradation on failure
====================  ====================================================
``watch``             iteration skipped, nothing mutated
``snapshot``          iteration aborted, logged shards stay unconsumed
``train``             supervisor restarts (bounded) from the latest
                      verified checkpoint; NaN rewinds skip the poisoned
                      window; budget exhaustion aborts the iteration
``eval``              gate unanswerable ⇒ iteration aborted, no publish
``publish``           nothing published, aliases untouched
``canary``            auto-rollback (``CanaryController``) snaps traffic
                      and the ``prod`` alias back to the stable version
``promote``           rollback to the stable version, alias untouched
====================  ====================================================

In EVERY failure row ``prod`` — the alias and the fleet serving it — is
byte-identical to before the iteration; the loop records the outcome on
``synapseml_continual_iterations_total{outcome}`` and stays runnable.

Training data is the request logger's DONE-committed shards: rows map
through ``row_fn`` with per-row quarantine (a poisoned record is one
counter tick + one skipped row, never a dead loop), a deterministic
fraction of PARTS is held out, and the candidate must beat the CURRENT
prod model on that held-out slice by ``gate_min_margin`` before anything
is published.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
import urllib.request
from typing import Callable

import numpy as np

from ..core import observability as obs
from ..core.faults import active_fault_plan
from ..registry.store import atomic_write_bytes
from .logger import _DONE_SUFFIX, _PART_PREFIX  # shared layout constants
from .supervisor import TrainSupervisor

__all__ = ["ContinualSpec", "ContinualLoop", "LoopAborted",
           "annotate_drift_gauge", "drift_annotation"]

# gauge name -> opaque evidence ref (e.g. the rai plane's audit artifact
# "name:version"). A drift-triggered iteration appends it to the trigger
# reason, so the retrain record carries WHY the gauge fired, not just that
# it did. Process-local like the gauge itself; last writer wins.
_DRIFT_ANNOTATIONS: dict[str, str] = {}


def annotate_drift_gauge(gauge: str, evidence: str | None) -> None:
    """Attach (or clear, with ``None``) the evidence ref behind a drift
    gauge — the rai ``AuditJob`` calls this with the audit artifact it
    published alongside setting the per-segment gauge values."""
    if evidence is None:
        _DRIFT_ANNOTATIONS.pop(gauge, None)
    else:
        _DRIFT_ANNOTATIONS[gauge] = str(evidence)


def drift_annotation(gauge: str) -> str | None:
    """The current evidence ref behind ``gauge``, if any."""
    return _DRIFT_ANNOTATIONS.get(gauge)

_LOOP_METRICS = obs.HandleCache(lambda reg: {
    "iterations": reg.counter(
        "synapseml_continual_iterations_total",
        "flywheel iterations by outcome (promoted / gate_failed / "
        "canary_rolled_back / skipped:* / error:*)", ("model", "outcome")),
    "gate_margin": reg.gauge(
        "synapseml_continual_gate_margin",
        "last eval-gate margin (prod metric - candidate metric, sign "
        "normalized so positive = candidate better)", ("model",)),
    "quarantined": reg.counter(
        "synapseml_continual_quarantined_rows_total",
        "logged rows dropped while building the training set (malformed "
        "record / row_fn failure / schema mismatch)", ("model",)),
    "train_rows": reg.gauge(
        "synapseml_continual_train_rows",
        "rows in the last iteration's training split", ("model",)),
})


class LoopAborted(RuntimeError):
    """An iteration died at ``seam`` — contained by :meth:`ContinualLoop.
    run_once` into an ``error:<seam>`` outcome with ``prod`` untouched."""

    def __init__(self, seam: str, cause: BaseException):
        super().__init__(f"continual iteration aborted at seam "
                         f"{seam!r}: {type(cause).__name__}: {cause}")
        self.seam = seam
        self.cause = cause


@dataclasses.dataclass
class ContinualSpec:
    """One model's flywheel, declaratively. JSON round-trips so a fleet
    config file can carry it (``to_json``/``from_json``)."""

    model: str
    # -- watch triggers ----------------------------------------------------
    min_new_rows: int = 1            # freshness: new logged rows required
    drift_gauge: str | None = None   # PR-2 gauge name; fires when ...
    drift_threshold: float | None = None  # ... its value exceeds this
    cadence_s: float = 0.0           # run_forever poll interval
    # -- training ----------------------------------------------------------
    seed: int = 0
    holdout_fraction: float = 0.25   # fraction of PARTS held out for eval
    max_restarts: int = 3
    max_rewinds: int = 2
    # preemption/resize resumes (elastic gang exits) get their OWN budget:
    # a preempted retraining iteration RESUMES from its coordinated
    # checkpoint instead of aborting, without eating the crash budget
    max_preempts: int = 16
    hang_timeout_s: float = 60.0
    # -- eval gate ---------------------------------------------------------
    gate_metric: str = "loss"        # label on the published metrics
    gate_min_margin: float = 0.0     # candidate must beat prod by this
    higher_is_better: bool = False
    # -- publish / rollout -------------------------------------------------
    publish: dict | None = None      # extra registry.publish kwargs (aot=...)
    alias: str = "prod"
    canary_weight: float = 0.1
    canary_workers: int = 1
    canary_min_requests: int = 10
    canary_timeout_s: float = 30.0
    canary: dict | None = None       # CanaryController kwargs ({} = defaults)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "ContinualSpec":
        return cls(**json.loads(text))


def _tolerant_rows(path: str) -> list:
    """One committed part's records; a torn/garbage line inside a
    COMMITTED part should be impossible (atomic commit), but a poisoned
    upstream must cost one quarantined row, not the whole iteration —
    malformed lines yield ``None`` placeholders the caller counts."""
    rows = []
    with open(path, "rb") as f:
        for line in f:
            if not line.strip():
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                rows.append(None)
    return rows


def default_row_fn(record: dict) -> dict:
    """Default logged-record → training-row mapping: the request body IS
    the row (the serving payload carries the features, and — for logged
    supervised traffic — the label). Override with ``row_fn=`` for any
    other schema."""
    body = record.get("body")
    if not isinstance(body, dict):
        raise ValueError("logged record body is not a JSON object")
    return body


class ContinualLoop:
    """Drive one :class:`ContinualSpec` against a registry + (optionally)
    a serving fleet.

    * ``log_dir`` — the :class:`~synapseml_tpu.continual.RequestLogger`'s
      directory (or any directory of DONE-committed jsonl parts);
    * ``train_fn(ctx, attempt)`` — build/resume the candidate model; MUST
      checkpoint into ``ctx.checkpoint_dir`` and honor ``attempt.resume``
      / ``attempt.skip_fn`` (run under :class:`TrainSupervisor`); returns
      the candidate STAGE to publish;
    * ``eval_fn(stage, holdout_cols) -> float`` — the gate metric on the
      held-out slice (lower is better unless ``spec.higher_is_better``);
    * ``deployment`` — a :class:`~synapseml_tpu.registry.Deployment` for
      canary + promote; ``None`` pins the alias directly after the gate
      (no-fleet mode);
    * ``traffic_fn(n)`` — drive ``n`` requests through the fleet during
      the canary window; defaults to replaying logged request bodies
      through the front.

    ``ctx`` (a :class:`TrainContext`) carries the training source, the
    holdout columns, the iteration's checkpoint dir, the resolved prod
    model (warm-start donor) and the previous champion's checkpoint dir.
    """

    def __init__(self, spec: ContinualSpec, registry, log_dir: str,
                 train_fn: Callable, eval_fn: Callable,
                 row_fn: Callable | None = None, deployment=None,
                 state_dir: str | None = None,
                 traffic_fn: Callable | None = None):
        self.spec = spec
        self.registry = registry
        self.log_dir = str(log_dir)
        self.train_fn = train_fn
        self.eval_fn = eval_fn
        self.row_fn = row_fn or default_row_fn
        self.deployment = deployment
        self.traffic_fn = traffic_fn
        self.state_dir = str(state_dir or
                             os.path.join(self.log_dir, "_continual"))
        os.makedirs(self.state_dir, exist_ok=True)
        self.state = self._load_state()
        self.history: list[dict] = self.state.setdefault("history", [])

    # -- persistent loop state ---------------------------------------------
    def _state_path(self) -> str:
        return os.path.join(self.state_dir, "loop_state.json")

    def _load_state(self) -> dict:
        try:
            with open(self._state_path()) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return {"iteration": 0, "consumed": [], "champion_ckpt": None,
                    "history": []}

    def _save_state(self) -> None:
        atomic_write_bytes(self._state_path(),
                           json.dumps(self.state, indent=2).encode())

    # -- seams --------------------------------------------------------------
    def _seam(self, name: str) -> None:
        plan = active_fault_plan()
        if plan is not None:
            plan.on_continual(f"{self.spec.model}:{name}")

    # -- watch --------------------------------------------------------------
    def _committed_parts(self) -> list[str]:
        try:
            names = sorted(os.listdir(self.log_dir))
        except OSError:
            return []
        return [n for n in names
                if n.startswith(_PART_PREFIX) and n.endswith(".jsonl")
                and os.path.exists(os.path.join(self.log_dir,
                                                n + _DONE_SUFFIX))]

    def _new_parts(self) -> list[str]:
        consumed = set(self.state.get("consumed", []))
        return [n for n in self._committed_parts() if n not in consumed]

    def _part_rows(self, name: str) -> int:
        try:
            with open(os.path.join(self.log_dir, name + _DONE_SUFFIX)) as f:
                return int(json.load(f).get("rows", 0))
        except (OSError, json.JSONDecodeError, ValueError):
            return 0

    def should_run(self) -> tuple[bool, str]:
        """(run?, reason). Freshness: enough new committed rows. Drift: a
        named PR-2 gauge above its threshold forces a run regardless."""
        fresh_rows = sum(self._part_rows(n) for n in self._new_parts())
        if fresh_rows >= max(self.spec.min_new_rows, 1):
            return True, f"fresh_rows={fresh_rows}"
        if self.spec.drift_gauge and self.spec.drift_threshold is not None:
            value = self._gauge_value(self.spec.drift_gauge)
            if value is not None and value > self.spec.drift_threshold:
                reason = (f"drift {self.spec.drift_gauge}="
                          f"{value:g}>{self.spec.drift_threshold:g}")
                evidence = drift_annotation(self.spec.drift_gauge)
                if evidence:
                    # e.g. the rai plane's published audit artifact: the
                    # retrain record names its triggering evidence
                    reason += f" audit={evidence}"
                return True, reason
        return False, f"fresh_rows={fresh_rows}<{self.spec.min_new_rows}"

    @staticmethod
    def _gauge_value(name: str) -> float | None:
        """Max value across the named series in the PR-2 registry snapshot
        (snapshot keys are ``name{label=...}``; unlabeled = bare name)."""
        snap = obs.get_registry().snapshot()
        values = [v for k, v in snap.items()
                  if (k == name or k.startswith(name + "{"))
                  and isinstance(v, (int, float))]
        return max(values) if values else None

    # -- dataset ------------------------------------------------------------
    def _holdout_part(self, name: str) -> bool:
        import hashlib

        h = int(hashlib.sha256(
            f"{self.spec.seed}:{name}".encode()).hexdigest()[:8], 16)
        return (h % 1000) < int(self.spec.holdout_fraction * 1000)

    def _build_dataset(self, parts: list[str]) -> tuple[dict, dict, int]:
        """(train_cols, holdout_cols, quarantined). Parts split into
        train/holdout deterministically by seeded hash; rows map through
        ``row_fn`` with per-row quarantine; the row schema is fixed by the
        first good row (rows missing keys quarantine)."""
        train_rows: list[dict] = []
        holdout_rows: list[dict] = []
        quarantined = 0
        schema: tuple | None = None
        for name in parts:
            self._seam(f"read:{name}")
            bucket = (holdout_rows if self._holdout_part(name)
                      else train_rows)
            for record in _tolerant_rows(os.path.join(self.log_dir, name)):
                if record is None:
                    quarantined += 1
                    continue
                try:
                    row = self.row_fn(record)
                    if not isinstance(row, dict) or not row:
                        raise ValueError("row_fn must return a non-empty "
                                         "dict")
                    key = tuple(sorted(row))
                    if schema is None:
                        schema = key
                    elif key != schema:
                        raise ValueError(f"row schema {key} != {schema}")
                    # fail NOW on a non-numeric value, inside quarantine
                    row = {k: np.asarray(v) for k, v in row.items()}
                    if any(v.dtype == object for v in row.values()):
                        raise ValueError("non-numeric row value")
                    bucket.append(row)
                except Exception:  # noqa: BLE001 — one bad row, one tick
                    quarantined += 1
        if quarantined:
            _LOOP_METRICS.get()["quarantined"].inc(quarantined,
                                                   model=self.spec.model)
        # both splits must be non-empty for the gate to mean anything; with
        # few parts the hash split can starve one side — rebalance by
        # MOVING tail rows across (deterministic), never by sharing them:
        # an overlap would let an overfit candidate grade its own homework.
        # Too few rows to keep the splits disjoint ⇒ one side stays empty
        # and the iteration skips (skipped:no_usable_rows).
        if train_rows and not holdout_rows:
            cut = max(len(train_rows) // 5, 1)
            if len(train_rows) > cut:
                holdout_rows, train_rows = (train_rows[-cut:],
                                            train_rows[:-cut])
        elif holdout_rows and not train_rows:
            cut = max(len(holdout_rows) // 5, 1)
            if len(holdout_rows) > cut:
                train_rows, holdout_rows = (holdout_rows[:-cut],
                                            holdout_rows[-cut:])

        def columnar(rows: list[dict]) -> dict:
            if not rows:
                return {}
            return {k: np.stack([np.asarray(r[k]) for r in rows])
                    for k in rows[0]}

        return columnar(train_rows), columnar(holdout_rows), quarantined

    # -- canary traffic -----------------------------------------------------
    def _replay_traffic(self, n: int) -> int:
        """Default canary probe: replay the newest logged request bodies
        through the deployment's front (they are known-serveable traffic).
        Returns requests actually sent."""
        if self.deployment is None:
            return 0
        address = self.deployment.serving.front.address
        bodies: list[tuple[str, bytes]] = []
        for name in reversed(self._committed_parts()):
            for record in reversed(_tolerant_rows(
                    os.path.join(self.log_dir, name))):
                if record is None or record.get("method") != "POST":
                    continue
                body = record.get("body")
                path = record.get("path", "/")
                bodies.append((path, json.dumps(body).encode()))
                if len(bodies) >= n:
                    break
            if len(bodies) >= n:
                break
        sent = 0
        for i in range(n):
            path, body = bodies[i % len(bodies)] if bodies else ("/", b"{}")
            req = urllib.request.Request(
                address + path, data=body, method="POST",
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=10) as r:
                    r.read()
                sent += 1
            except Exception:  # noqa: BLE001 — probe failures are the
                sent += 1      # canary controller's signal, not ours
        return sent

    def _drive_canary(self, controller, version: str) -> bool:
        """Send probe traffic until the canary has judged
        ``canary_min_requests`` or the controller rolls back. True when
        the canary is healthy and promotable."""
        spec = self.spec
        deadline = time.monotonic() + spec.canary_timeout_s
        front = self.deployment.serving.front
        send = self.traffic_fn or self._replay_traffic
        while time.monotonic() < deadline:
            if controller is not None and controller.rolled_back:
                return False
            stats = front.version_stats().get(version, {})
            seen = stats.get("ok", 0) + stats.get("err", 0)
            if seen >= spec.canary_min_requests:
                # let the controller ingest the final counters
                if controller is not None:
                    reason = controller.check_once()
                    if reason is not None or controller.rolled_back:
                        return False
                return True
            send(max(spec.canary_min_requests - seen, 1))
            time.sleep(0.05)
        return controller is None or not controller.rolled_back

    # -- the iteration ------------------------------------------------------
    def run_once(self, raise_errors: bool = False) -> dict:
        """One flywheel iteration. NEVER raises for operational failures
        (containment contract): the outcome lands in the returned record
        (and the metric series), ``prod`` stays untouched on every
        non-promoted path, and the next ``run_once`` proceeds from clean
        state. ``raise_errors=True`` ADDITIONALLY re-raises the contained
        failure as :class:`LoopAborted` after recording it — for operators
        driving one iteration by hand."""
        spec = self.spec
        t0 = time.perf_counter()
        record: dict = {"iteration": self.state.get("iteration", 0),
                        "model": spec.model, "outcome": None}
        seam = "watch"
        canary_started = False
        stable = None
        try:
            self._seam("watch")
            ok, reason = self.should_run()
            record["trigger"] = reason
            if not ok:
                record["outcome"] = "skipped:not_due"
                return self._finish(record, t0)

            seam = "snapshot"
            self._seam("snapshot")
            parts = self._new_parts()
            train_cols, holdout_cols, quarantined = \
                self._build_dataset(parts)
            record["parts"] = len(parts)
            record["quarantined"] = quarantined
            n_train = (len(next(iter(train_cols.values())))
                       if train_cols else 0)
            record["train_rows"] = n_train
            _LOOP_METRICS.get()["train_rows"].set(n_train, model=spec.model)
            if not train_cols or not holdout_cols:
                record["outcome"] = "skipped:no_usable_rows"
                return self._finish(record, t0)

            seam = "train"
            self._seam("train")
            prod = self._resolve_prod()
            ckpt_dir = os.path.join(self.state_dir,
                                    f"it{record['iteration']:04d}", "ckpt")
            ctx = TrainContext(
                spec=spec, train_cols=train_cols,
                holdout_cols=holdout_cols, checkpoint_dir=ckpt_dir,
                prod=prod,
                champion_ckpt=self.state.get("champion_ckpt"))
            supervisor = TrainSupervisor(
                ckpt_dir, max_restarts=spec.max_restarts,
                max_rewinds=spec.max_rewinds,
                max_preempts=spec.max_preempts,
                hang_timeout_s=spec.hang_timeout_s)
            record["supervisor"] = {"restarts": 0, "rewinds": 0,
                                    "preempts": 0}
            stage = supervisor.run(
                lambda attempt: self.train_fn(ctx, attempt))
            record["supervisor"] = {"restarts": supervisor.restarts,
                                    "rewinds": supervisor.rewinds,
                                    "preempts": supervisor.preempts}
            # the data is consumed whatever the gate says — retraining on
            # the same poisoned shards next tick would loop forever
            self.state.setdefault("consumed", []).extend(parts)

            seam = "eval"
            self._seam("eval")
            cand_metric = float(self.eval_fn(stage, holdout_cols))
            prod_metric = (float(self.eval_fn(prod.stage, holdout_cols))
                           if prod is not None else None)
            sign = 1.0 if spec.higher_is_better else -1.0
            margin = (sign * (cand_metric - prod_metric)
                      if prod_metric is not None else float("inf"))
            record["gate"] = {spec.gate_metric: cand_metric,
                              "prod": prod_metric,
                              "margin": None if margin == float("inf")
                              else margin}
            _LOOP_METRICS.get()["gate_margin"].set(
                0.0 if margin == float("inf") else margin,
                model=spec.model)
            # NaN-safe comparison: a NaN candidate metric (diverged model)
            # makes `margin >= threshold` False and FAILS the gate — the
            # `<` form would let a NaN model sail through to prod
            if not (margin >= spec.gate_min_margin):
                record["outcome"] = "gate_failed"
                return self._finish(record, t0)

            seam = "publish"
            self._seam("publish")
            pub = self.registry.publish(
                spec.model, stage,
                metrics={spec.gate_metric: cand_metric,
                         "gate_margin": (None if margin == float("inf")
                                         else margin)},
                **(spec.publish or {}))
            record["version"] = pub.version

            if self.deployment is not None:
                seam = "canary"
                self._seam("canary")
                stable = self.deployment.stable_version()
                controller = self.deployment.canary(
                    pub.version, weight=spec.canary_weight,
                    num_workers=spec.canary_workers,
                    autorollback=spec.canary if spec.canary is not None
                    else {})
                canary_started = True
                healthy = self._drive_canary(controller, pub.version)
                if not healthy:
                    self.deployment.stop_controller()
                    if controller is not None and not controller.rolled_back:
                        self.deployment.rollback(stable=stable)
                    record["outcome"] = "canary_rolled_back"
                    record["rollback_reason"] = (
                        controller.reason if controller is not None
                        else "unhealthy")
                    return self._finish(record, t0)
                seam = "promote"
                self._seam("promote")
                self.deployment.promote(pub.version)
            else:
                seam = "promote"
                self._seam("promote")
                self.registry.pin(spec.model, spec.alias, pub.version)
            self.state["champion_ckpt"] = ckpt_dir
            record["outcome"] = "promoted"
            return self._finish(record, t0)
        except Exception as e:  # noqa: BLE001 — containment contract
            # (KeyboardInterrupt/SystemExit pass through: the operator —
            # or the chaos watchdog — outranks the containment contract)
            if canary_started:
                # never leave a half-rolled-out canary behind: traffic and
                # alias snap back to the stable version
                try:
                    self.deployment.stop_controller()
                    self.deployment.rollback(stable=stable)
                except Exception:  # noqa: BLE001
                    pass
            record["outcome"] = f"error:{seam}"
            record["error"] = f"{type(e).__name__}: {e}"
            record = self._finish(record, t0)
            if raise_errors:
                raise LoopAborted(seam, e) from e
            return record

    def _finish(self, record: dict, t0: float) -> dict:
        record["duration_s"] = round(time.perf_counter() - t0, 3)
        self.state["iteration"] = int(self.state.get("iteration", 0)) + 1
        self.history.append(record)
        self.state["history"] = self.history[-50:]
        self._save_state()
        _LOOP_METRICS.get()["iterations"].inc(model=self.spec.model,
                                              outcome=record["outcome"])
        return record

    def _resolve_prod(self):
        """The current prod model (None before the first promote)."""
        try:
            if self.registry.alias_target(self.spec.model,
                                          self.spec.alias) is None:
                return None
            return self.registry.resolve(self.spec.model, self.spec.alias)
        except FileNotFoundError:
            return None

    # -- background driver ---------------------------------------------------
    def run_forever(self, stop_event=None, max_iterations: int | None = None
                    ) -> list[dict]:
        """Poll ``should_run`` every ``spec.cadence_s`` seconds and run due
        iterations until ``stop_event`` is set (or ``max_iterations`` ran).
        Synchronous — callers wanting a daemon wrap it in a thread."""
        import threading

        stop_event = stop_event or threading.Event()
        out = []
        while not stop_event.is_set():
            out.append(self.run_once())
            if max_iterations is not None and len(out) >= max_iterations:
                break
            stop_event.wait(max(self.spec.cadence_s, 0.05))
        return out


@dataclasses.dataclass
class TrainContext:
    """Everything a ``train_fn`` needs for one iteration. The training
    split is materialized columnar (wrap in a
    :class:`~synapseml_tpu.data.MemorySource` — or shard it to disk —
    before ``fit_source``); ``prod`` is the warm-start donor;
    ``checkpoint_dir`` is where the supervisor expects progress."""

    spec: ContinualSpec
    train_cols: dict
    holdout_cols: dict
    checkpoint_dir: str
    prod: object | None
    champion_ckpt: str | None
