"""Closed-loop continual training: the serve→log→retrain→canary flywheel.

The five planes this package wires together already exist —

* serving (``io/serving.py`` workers, ``io/distributed_serving.py`` front
  with canary splits + shadow traffic),
* the streaming data plane (``data/source.py`` sharded sources,
  ``models/trainer.fit_source`` with checkpointable iterators),
* the registry/deploy plane (``registry/`` publish with AOT + autotune,
  ``Deployment`` canary with auto-rollback),
* the resilience plane (``core/resilience.py`` + seeded ``core/faults.py``
  injection),
* the observability plane (``core/observability.py`` metric series).

What was missing is the LOOP: production traffic was measured then
discarded, and retraining was a manual offline act that could silently
ship a corrupted model. This package closes it with fault containment as
the headline contract — a fault injected at ANY seam (bad data, killed
trainer, torn checkpoint, regressing canary) leaves ``prod`` untouched
and the loop able to resume:

* :class:`RequestLogger` (``logger.py``) — a sampled, SLO-safe,
  PII-scrubbed request/response logger hooked into ``RoutingFront`` /
  ``ServingServer`` that appends jsonl shards in ``ShardedSource`` layout
  with atomic part/DONE commits, turning production traffic into a
  first-class training source;
* :class:`TrainSupervisor` (``supervisor.py``) — crash-safe long fits:
  hang watchdog keyed off step progress, bounded restarts resuming from
  the latest *verified* checkpoint, and a non-finite-loss rewind that
  skips past the poisoned batch window instead of letting NaN poison the
  params;
* :class:`ContinualLoop` (``loop.py``) — one declarative
  :class:`ContinualSpec` driving watch → warm-started ``fit_source`` →
  eval gate vs prod on a held-out slice → ``registry.publish`` → canary
  with auto-rollback, every seam consulting the active ``FaultPlan`` and
  every outcome landing on the ``synapseml_continual_*`` series.

See ``docs/CONTINUAL.md`` for the seam-by-seam degradation contract.
"""

from .logger import RequestLogger, logged_request_source
from .loop import (ContinualLoop, ContinualSpec, LoopAborted,
                   annotate_drift_gauge, drift_annotation)
from .supervisor import TrainAttempt, TrainSupervisor

__all__ = [
    "ContinualLoop",
    "ContinualSpec",
    "LoopAborted",
    "RequestLogger",
    "TrainAttempt",
    "TrainSupervisor",
    "annotate_drift_gauge",
    "drift_annotation",
    "logged_request_source",
]
