"""Sampled request/response logging: production traffic → training source.

:class:`RequestLogger` hooks into the serving tier (``RoutingFront`` at the
fleet level, ``ServingServer.reply_batch`` on a single worker) and turns
served traffic into jsonl shards a :class:`~synapseml_tpu.data.ShardedSource`
can stream — the feedstock of the continual-training flywheel.

Contracts, in priority order:

1. **SLO-safe** — :meth:`RequestLogger.log` runs on the serving thread and
   must never delay a reply: it draws the (seeded) sampling decision, does
   ONE non-blocking queue insert, and returns. A full queue sheds the
   record and counts it (``synapseml_continual_log_dropped_total``);
   scrubbing/serialization/IO all happen on the writer thread.
2. **Scrubbed** — every payload passes through the ``core/logging``
   scrubber before it touches disk (named secrets, bearer/JWT tokens,
   emails, long digit runs), applied per string field so the shard stays
   valid JSON; numeric card-shaped values (12+ digits) mask too. Per-kind
   counts land on ``synapseml_scrub_fields_total`` and in each shard's
   DONE marker.
3. **Atomic shards** — records append to an in-flight temp file invisible
   to readers; at ``shard_rows`` the part commits via the scoring-sink
   discipline: fsync → ``os.replace`` to ``part-NNNNN.jsonl`` → atomic
   ``part-NNNNN.DONE`` marker (JSON: rows, bytes, scrub tally). A crash
   mid-shard loses at most the in-flight tail; a committed part is never
   torn. :func:`logged_request_source` reads ONLY DONE-gated parts.

Fault injection: the commit seam consults the active ``FaultPlan``
(``plan.on_continual("log_commit:<part>")``); an injected failure sheds
that shard's rows (counted) and the logger keeps going — degraded, never
corrupt.
"""

from __future__ import annotations

import json
import os
import queue
import random
import threading
import time

from ..core import observability as obs
from ..core.faults import active_fault_plan
from ..core.logging import scrub_json
from ..registry.store import atomic_write_bytes

__all__ = ["RequestLogger", "logged_request_source"]

_PART_PREFIX = "part-"
_DONE_SUFFIX = ".DONE"

_LOG_METRICS = obs.HandleCache(lambda reg: {
    "rows": reg.counter(
        "synapseml_continual_logged_rows_total",
        "request/response records committed to logged shards", ("dir",)),
    "dropped": reg.counter(
        "synapseml_continual_log_dropped_total",
        "records shed before logging (full queue / commit failure / "
        "writer error)", ("reason",)),
    "scrubbed": reg.counter(
        "synapseml_continual_scrubbed_fields_total",
        "fields masked while writing logged shards", ("kind",)),
    "parts": reg.counter(
        "synapseml_continual_log_parts_total",
        "jsonl shards committed by the request logger", ("dir",)),
})


def _decode_payload(payload):
    """bytes → parsed JSON when possible, utf-8 text otherwise; everything
    else passes through (the serve loop hands dict replies directly)."""
    if isinstance(payload, (bytes, bytearray)):
        text = bytes(payload).decode("utf-8", errors="replace")
        try:
            return json.loads(text or "null")
        except json.JSONDecodeError:
            return text
    return payload


class RequestLogger:
    """Bounded async request/response logger writing ShardedSource-layout
    jsonl shards. Attach with ``front.set_request_logger(lg)`` or
    ``server.request_logger = lg``; both call :meth:`log` after each reply.

    ``sample_rate`` draws from ONE seeded RNG so a test (or a replayed
    trace) sees a deterministic kept-set; ``shard_rows`` bounds part size;
    ``max_queue`` bounds memory — the writer sheds, it never backpressures
    the serving thread."""

    def __init__(self, path: str, sample_rate: float = 1.0, seed: int = 0,
                 shard_rows: int = 256, max_queue: int = 4096,
                 scrub_payloads: bool = True):
        if not 0.0 <= sample_rate <= 1.0:
            raise ValueError(f"sample_rate must be in [0, 1], got "
                             f"{sample_rate}")
        self.path = str(path)
        os.makedirs(self.path, exist_ok=True)
        self.sample_rate = float(sample_rate)
        self.shard_rows = int(shard_rows)
        self.scrub_payloads = bool(scrub_payloads)
        self._rng = random.Random(seed)
        self._rng_lock = threading.Lock()
        self._queue: "queue.Queue" = queue.Queue(maxsize=max_queue)
        self._next_part = self._scan_next_part()
        self._inflight_path: str | None = None
        self._inflight_f = None
        self._inflight_rows = 0
        self._inflight_scrubs: dict[str, int] = {}
        self.logged = 0       # rows committed to DONE'd parts
        self.dropped = 0      # shed records (all reasons)
        self._pending_rows = 0  # written to the in-flight part, not committed
        self._closed = False
        self._wake = threading.Event()
        self._flush_req: "queue.Queue" = queue.Queue()
        self._writer = threading.Thread(target=self._run, daemon=True,
                                        name="request-logger")
        self._writer.start()

    # -- serving-thread surface (must never block) --------------------------
    def log(self, *, method: str, path: str, body, reply, status: int,
            latency_ms: float, version: str | None = None) -> None:
        """Record one served exchange. Runs on the serving thread: sampling
        draw + one ``put_nowait``; a full queue sheds the record."""
        if self._closed:
            return
        if self.sample_rate < 1.0:
            with self._rng_lock:
                if self._rng.random() >= self.sample_rate:
                    return
        record = (time.time(), method, path, body, reply, int(status),
                  float(latency_ms), version)
        try:
            self._queue.put_nowait(record)
        except queue.Full:
            self.dropped += 1
            _LOG_METRICS.get()["dropped"].inc(reason="queue_full")

    # -- writer thread ------------------------------------------------------
    def _run(self) -> None:
        while True:
            try:
                item = self._queue.get(timeout=0.05)
            except queue.Empty:
                if self._closed and self._queue.empty():
                    return
                self._serve_flush_requests()
                continue
            if item is None:  # close sentinel
                return
            try:
                self._write_record(item)
            except Exception:  # noqa: BLE001 — logging must never die
                self.dropped += 1
                _LOG_METRICS.get()["dropped"].inc(reason="writer_error")
            self._serve_flush_requests()

    def _serve_flush_requests(self) -> None:
        while True:
            try:
                done_evt = self._flush_req.get_nowait()
            except queue.Empty:
                return
            try:
                self._drain_queue()
                self._commit_part()
            finally:
                done_evt.set()

    def _drain_queue(self) -> None:
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                return
            if item is None:
                continue
            try:
                self._write_record(item)
            except Exception:  # noqa: BLE001
                self.dropped += 1
                _LOG_METRICS.get()["dropped"].inc(reason="writer_error")

    def _write_record(self, item) -> None:
        ts, method, path, body, reply, status, latency_ms, version = item
        record = {"ts": ts, "method": method, "path": path,
                  "status": status, "latency_ms": round(latency_ms, 3),
                  "body": _decode_payload(body),
                  "reply": _decode_payload(reply)}
        if version is not None:
            record["version"] = version
        if self._inflight_f is None:
            # open BEFORE scrubbing: _open_part resets the scrub tally, so
            # scrubbing first would drop the first record's counts from
            # every shard's DONE marker
            self._open_part()
        if self.scrub_payloads:
            # the structural core scrubber: per-field masking keeps the
            # shard valid JSON (a textual digit mask on a bare number
            # would not), secret-worded keys mask their values
            record = scrub_json(record, self._inflight_scrubs)
        line = json.dumps(record, default=str) + "\n"
        self._inflight_f.write(line.encode())
        self._inflight_rows += 1
        self._pending_rows += 1
        if self._inflight_rows >= self.shard_rows:
            self._commit_part()

    # -- shard lifecycle ----------------------------------------------------
    def _scan_next_part(self) -> int:
        taken = [-1]
        for name in os.listdir(self.path):
            if name.startswith(_PART_PREFIX) and name.endswith(".jsonl"):
                try:
                    taken.append(int(name[len(_PART_PREFIX):-len(".jsonl")]))
                except ValueError:
                    continue
        return max(taken) + 1

    def _part_name(self, index: int) -> str:
        return f"{_PART_PREFIX}{index:05d}.jsonl"

    def _open_part(self) -> None:
        # the leading dot keeps the in-flight file invisible to part globs
        self._inflight_path = os.path.join(
            self.path, f".inflight-{self._next_part:05d}.tmp")
        self._inflight_f = open(self._inflight_path, "wb")
        self._inflight_rows = 0
        self._inflight_scrubs = {}

    def _commit_part(self) -> None:
        """Commit the in-flight part: fsync → rename → DONE marker (the
        scoring-sink atomic discipline). A failure — injected via the
        ``continual`` fault plane or real — sheds this shard's rows
        (counted) rather than leaving a torn committed part."""
        if self._inflight_f is None or self._inflight_rows == 0:
            if self._inflight_f is not None:
                self._abort_part()
            return
        name = self._part_name(self._next_part)
        rows, scrubs = self._inflight_rows, dict(self._inflight_scrubs)
        try:
            plan = active_fault_plan()
            if plan is not None:
                plan.on_continual(f"log_commit:{name}")
            self._inflight_f.flush()
            os.fsync(self._inflight_f.fileno())
            self._inflight_f.close()
            final = os.path.join(self.path, name)
            os.replace(self._inflight_path, final)
            size = os.path.getsize(final)
            atomic_write_bytes(
                final + _DONE_SUFFIX,
                json.dumps({"rows": rows, "bytes": size,
                            "scrubbed": scrubs}).encode())
        except Exception:  # noqa: BLE001 — shed, don't corrupt
            self._abort_part()
            self.dropped += rows
            self._pending_rows -= rows
            _LOG_METRICS.get()["dropped"].inc(rows, reason="commit_failed")
            self._next_part += 1  # never reuse a possibly-littered index
            return
        self._inflight_f = None
        self._inflight_path = None
        self._inflight_rows = 0
        self.logged += rows
        self._pending_rows -= rows
        m = _LOG_METRICS.get()
        m["rows"].inc(rows, dir=self.path)
        m["parts"].inc(dir=self.path)
        for kind, n in scrubs.items():
            m["scrubbed"].inc(n, kind=kind)
        self._next_part += 1

    def _abort_part(self) -> None:
        try:
            if self._inflight_f is not None:
                self._inflight_f.close()
            if self._inflight_path and os.path.exists(self._inflight_path):
                os.remove(self._inflight_path)
        except OSError:
            pass
        self._inflight_f = None
        self._inflight_path = None
        self._inflight_rows = 0

    # -- reader surface -----------------------------------------------------
    def flush(self, timeout_s: float = 10.0) -> None:
        """Drain the queue and commit the current partial shard — call
        before building a training source so the freshest traffic is
        readable. Processed ON the writer thread (one writer, no interleaved
        file state)."""
        if self._closed:
            return
        evt = threading.Event()
        self._flush_req.put(evt)
        if not evt.wait(timeout_s):
            raise TimeoutError("request logger flush timed out")

    def committed_parts(self) -> list[str]:
        """DONE-gated committed part paths, in commit order."""
        out = []
        for name in sorted(os.listdir(self.path)):
            if not (name.startswith(_PART_PREFIX)
                    and name.endswith(".jsonl")):
                continue
            if os.path.exists(os.path.join(self.path, name + _DONE_SUFFIX)):
                out.append(os.path.join(self.path, name))
        return out

    def source(self, shard_bytes: int | None = None):
        """The committed log as a :class:`~synapseml_tpu.data.ShardedSource`
        (jsonl kind) — feed it to ``fit_source`` / the continual loop."""
        return logged_request_source(self.path, shard_bytes=shard_bytes)

    def stats(self) -> dict:
        return {"logged": self.logged, "dropped": self.dropped,
                "pending": self._pending_rows + self._queue.qsize(),
                "parts": len(self.committed_parts()),
                "next_part": self._next_part}

    def close(self, timeout_s: float = 10.0) -> None:
        if self._closed:
            return
        try:
            self.flush(timeout_s)
        finally:
            self._closed = True
            try:
                self._queue.put_nowait(None)
            except queue.Full:
                pass  # the writer's closed+empty check ends the thread
            self._writer.join(timeout=timeout_s)

    def __enter__(self) -> "RequestLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def logged_request_source(path: str, shard_bytes: int | None = None):
    """A :class:`~synapseml_tpu.data.ShardedSource` over the DONE-committed
    request-log parts under ``path`` — in-flight and torn parts are
    invisible by construction (the atomic part/DONE discipline)."""
    from ..data.source import DEFAULT_SHARD_BYTES, ShardedSource

    parts = []
    for name in sorted(os.listdir(path)):
        if not (name.startswith(_PART_PREFIX) and name.endswith(".jsonl")):
            continue
        if os.path.exists(os.path.join(path, name + _DONE_SUFFIX)):
            parts.append(os.path.join(path, name))
    if not parts:
        raise FileNotFoundError(
            f"no committed request-log parts under {path!r} (flush the "
            "logger, or serve some traffic first)")
    return ShardedSource.jsonl(
        parts, shard_bytes=shard_bytes or DEFAULT_SHARD_BYTES)
