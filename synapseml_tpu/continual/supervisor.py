"""Crash-safe long training runs: restarts, hang watchdog, NaN rewind.

:class:`TrainSupervisor` wraps a ``fit_source``-shaped training attempt so
the three ways a long fit dies become bounded, observable degradations
instead of a corrupted (or lost) run:

* **crash** — the attempt raises (a killed subprocess, an injected
  ``FaultSpec(..., planes=("training",))``, an OOM): the supervisor
  restarts it under a bounded :class:`~synapseml_tpu.core.resilience.
  RetryPolicy` (each restart counts into ``resilience_measures
  ("training")`` and ``synapseml_continual_supervisor_restarts_total``);
  the attempt resumes from the latest *verified* checkpoint
  (``parallel.checkpoint.latest_verified_step`` — a torn newest payload
  demotes one step instead of resuming garbage);
* **hang** — subprocess mode (:meth:`TrainSupervisor.run_subprocess`)
  watches step progress through the checkpoint directory; no new
  completed step within ``hang_timeout_s`` ⇒ SIGKILL + restart (a hung
  trainer is indistinguishable from a dead one to the loop above);
* **NaN** — the trainer (``TrainerConfig.nonfinite_action="raise"``)
  aborts with :class:`~synapseml_tpu.models.trainer.NonFiniteLossError`;
  the supervisor REWINDS: the next attempt resumes from the latest
  verified checkpoint and ``skip_fn`` skips the batch window from that
  checkpoint through the poisoned step — the stream stays aligned, the
  params never train on the offending batches, and
  ``synapseml_continual_rewinds_total`` moves;
* **preemption / gang resize** — a gang-trained attempt exits with
  :data:`~synapseml_tpu.parallel.gang.EXIT_PREEMPTED` /
  :data:`~synapseml_tpu.parallel.gang.EXIT_RESIZE` (subprocess mode) or
  raises :class:`~synapseml_tpu.parallel.gang.Preempted` /
  :class:`~synapseml_tpu.parallel.gang.GangAborted` (in-process): these
  are EXPECTED elastic events, not crashes — the supervisor resumes them
  under a SEPARATE ``max_preempts`` budget (a preempted flywheel
  iteration continues instead of aborting, and a crash-loop bug cannot
  hide behind the preemption budget).

In-process mode cannot preempt a hung Python thread — hang detection is
subprocess-mode only (documented contract; the loop's cadence bounds an
in-process wedge at the iteration level).
"""

from __future__ import annotations

import os
import signal
import subprocess
import time
from typing import Callable

from ..core import observability as obs
from ..core.faults import active_fault_plan
from ..core.resilience import RetryPolicy, resilience_measures
from ..models.trainer import NonFiniteLossError
from ..parallel.checkpoint import latest_step, latest_verified_step
from ..parallel.gang import (EXIT_PREEMPTED, EXIT_RESIZE, GangAborted,
                             Preempted)

__all__ = ["TrainSupervisor", "TrainAttempt"]

_SUP_METRICS = obs.HandleCache(lambda reg: {
    "restarts": reg.counter(
        "synapseml_continual_supervisor_restarts_total",
        "supervised training attempts restarted after a crash/hang",
        ("mode",)),
    "rewinds": reg.counter(
        "synapseml_continual_rewinds_total",
        "NaN rewinds: resume from the last verified checkpoint, skip the "
        "poisoned batch window", ()),
})


class TrainAttempt:
    """One supervised attempt's context, handed to the attempt callable.

    * ``index`` — 0 for the first attempt, +1 per restart/rewind;
    * ``resume`` — True when a previous attempt made checkpoint progress
      (the attempt should ``fit_source(resume_from=checkpoint_dir)``);
    * ``skip_fn`` — the accumulated NaN-rewind skip predicate (None when
      no rewind happened); pass it straight to ``fit_source(skip_fn=)``;
    * ``heartbeat(step)`` — call once per optimizer step: feeds the fault
      plane's ``training`` hook (``step:<n>`` targets, so a seeded plan
      can kill the trainer at an exact step) and records progress.
    """

    def __init__(self, supervisor: "TrainSupervisor", index: int,
                 skip_windows: list):
        self.supervisor = supervisor
        self.index = index
        self.skip_windows = list(skip_windows)
        self.resume = index > 0 or supervisor.checkpoint_progress() is not None
        self.last_step: int | None = None

    @property
    def skip_fn(self) -> Callable[[int], bool] | None:
        if not self.skip_windows:
            return None
        windows = tuple(self.skip_windows)

        def skip(batch_index: int) -> bool:
            return any(lo <= batch_index < hi for lo, hi in windows)

        return skip

    def heartbeat(self, step: int) -> None:
        self.last_step = int(step)
        plan = active_fault_plan()
        if plan is not None:
            plan.on_training(f"step:{step}")


class TrainSupervisor:
    """Supervise training attempts against one checkpoint directory.

    ``max_restarts`` bounds crash/hang restarts; ``max_rewinds`` bounds
    NaN rewinds (each rewind widens the skip set — an input stream that is
    ALL poison must eventually surface, not spin). ``retry_policy``
    optionally rate-limits restarts with a shared
    :class:`~synapseml_tpu.core.resilience.RetryBudget` and supplies the
    jittered backoff between attempts."""

    def __init__(self, checkpoint_dir: str, max_restarts: int = 3,
                 max_rewinds: int = 2, hang_timeout_s: float = 60.0,
                 poll_s: float = 0.25,
                 retry_policy: RetryPolicy | None = None,
                 max_preempts: int = 16):
        self.checkpoint_dir = str(checkpoint_dir)
        os.makedirs(self.checkpoint_dir, exist_ok=True)
        self.max_restarts = int(max_restarts)
        self.max_rewinds = int(max_rewinds)
        self.max_preempts = int(max_preempts)
        self.hang_timeout_s = float(hang_timeout_s)
        self.poll_s = float(poll_s)
        self.retry_policy = retry_policy or RetryPolicy(
            backoffs_ms=(50, 200, 500))
        self.restarts = 0
        self.rewinds = 0
        self.preempts = 0
        self.skip_windows: list[tuple[int, int]] = []
        self.current_pid: int | None = None  # subprocess mode

    def checkpoint_progress(self) -> int | None:
        """Newest VERIFIED checkpoint step (the resume point)."""
        return latest_verified_step(self.checkpoint_dir)

    def _backoff(self) -> None:
        time.sleep(self.retry_policy.backoff_ms(
            max(self.restarts - 1, 0)) / 1000.0)

    def _on_restart(self, mode: str) -> bool:
        """Account one restart; False when the budget is exhausted."""
        if self.restarts >= self.max_restarts \
                or not self.retry_policy.acquire_retry():
            return False
        self.restarts += 1
        resilience_measures("training").count("retry")
        _SUP_METRICS.get()["restarts"].inc(mode=mode)
        return True

    def _on_preempt(self, mode: str) -> bool:
        """Account one elastic resume (preemption / gang resize) on its own
        budget: an emergency-checkpointed exit is bounded lost work, not a
        crash — it must neither abort the run nor eat the crash budget."""
        if self.preempts >= self.max_preempts:
            return False
        self.preempts += 1
        resilience_measures("training").count("preempt_resume")
        _SUP_METRICS.get()["restarts"].inc(mode=mode)
        return True

    def _on_rewind(self, err: NonFiniteLossError) -> bool:
        """Account one NaN rewind and extend the skip set: the next attempt
        resumes from the latest verified checkpoint and skips every batch
        from there THROUGH the poisoned step."""
        if self.rewinds >= self.max_rewinds:
            return False
        self.rewinds += 1
        _SUP_METRICS.get()["rewinds"].inc()
        lo = self.checkpoint_progress() or 0
        self.skip_windows.append((lo, err.step))
        return True

    # -- in-process mode ----------------------------------------------------
    def run(self, attempt_fn: Callable[[TrainAttempt], object]):
        """Drive ``attempt_fn(attempt)`` to completion. The attempt MUST
        checkpoint into ``checkpoint_dir`` and honor ``attempt.resume`` /
        ``attempt.skip_fn`` (i.e. call ``fit_source(resume_from=
        checkpoint_dir, skip_fn=attempt.skip_fn)``) — that is what makes a
        restart bit-identical to an uninterrupted run. Returns the
        attempt's result; raises the final error when budgets run out."""
        index = 0
        while True:
            attempt = TrainAttempt(self, index, self.skip_windows)
            try:
                plan = active_fault_plan()
                if plan is not None:
                    plan.on_training(f"attempt:{index}")
                return attempt_fn(attempt)
            except NonFiniteLossError as e:
                if not self._on_rewind(e):
                    raise
            except Preempted:
                # an emergency checkpoint COMMITTED — resume the iteration
                # from it instead of aborting the flywheel
                if not self._on_preempt("preempt"):
                    raise
            except GangAborted:
                if not self._on_preempt("resize"):
                    raise
            except Exception:
                if not self._on_restart("inprocess"):
                    raise
                self._backoff()
            index += 1

    # -- subprocess mode ----------------------------------------------------
    def run_subprocess(self, argv: list[str], env: dict | None = None,
                       timeout_s: float = 600.0) -> int:
        """Run ``argv`` as the training process; restart it (bounded) when
        it dies, SIGKILL + restart when it hangs (no new completed
        checkpoint step within ``hang_timeout_s``). The child is expected
        to resume from ``checkpoint_dir`` on its own (``fit_source(
        resume_from=...)``) and exit 0 when the run is complete. Returns
        the number of attempts it took."""
        deadline = time.monotonic() + timeout_s
        attempts = 0
        while True:
            attempts += 1
            proc = subprocess.Popen(argv, env=env)
            self.current_pid = proc.pid
            last_progress = time.monotonic()
            # progress polling uses the DONE-marker scan (latest_step),
            # not the verified scan — re-hashing a multi-GB payload 4x/s
            # for the whole run would be the watchdog DoS'ing the trainer;
            # verification happens once, at restore time
            last_step = latest_step(self.checkpoint_dir)
            hung = False
            while True:
                rc = proc.poll()
                if rc is not None:
                    break
                now = time.monotonic()
                step = latest_step(self.checkpoint_dir)
                if step != last_step:
                    last_step, last_progress = step, now
                if now - last_progress > self.hang_timeout_s:
                    hung = True
                    proc.send_signal(signal.SIGKILL)
                    proc.wait(timeout=30)
                    rc = proc.returncode
                    break
                if now > deadline:
                    proc.send_signal(signal.SIGKILL)
                    proc.wait(timeout=30)
                    raise TimeoutError(
                        f"supervised subprocess run exceeded {timeout_s}s")
                time.sleep(self.poll_s)
            self.current_pid = None
            if rc == 0 and not hung:
                return attempts
            if not hung and rc in (EXIT_PREEMPTED, EXIT_RESIZE):
                # elastic gang exits: the child either committed an
                # emergency checkpoint (preempt) or lost a member (resize)
                # — resume it on the preemption budget, no crash counted
                mode = "preempt" if rc == EXIT_PREEMPTED else "resize"
                if not self._on_preempt(mode):
                    raise RuntimeError(
                        f"supervised trainer preempted {self.preempts} "
                        f"time(s) — preemption budget exhausted")
                continue
            if not self._on_restart("hang" if hung else "subprocess"):
                raise RuntimeError(
                    f"supervised trainer failed after {attempts} attempt(s) "
                    f"(last exit code {rc}"
                    f"{', hang-killed' if hung else ''}) — restart budget "
                    "exhausted")
            self._backoff()
