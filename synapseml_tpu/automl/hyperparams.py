"""Hyperparameter spaces (reference ``automl/HyperparamBuilder.scala`` +
``DefaultHyperparams.scala``)."""

from __future__ import annotations

import numpy as np

__all__ = ["DiscreteHyperParam", "RangeHyperParam", "HyperparamBuilder",
           "GridSpace", "RandomSpace", "DefaultHyperparams",
           "fusable_param_names"]


def _learner_name(learner) -> str:
    """Accepts a name, an estimator class, or an instance — the same three
    forms every learner-keyed helper here takes."""
    if isinstance(learner, str):
        return learner
    return learner.__name__ if isinstance(learner, type) \
        else type(learner).__name__


def fusable_param_names(learner) -> tuple[str, ...]:
    """Sweep dimensions that can ride a horizontally fused training array
    for this learner: the scalar, architecture-preserving knobs declared by
    the estimator's ``_FUSED_SCALAR_PARAMS`` contract (see docs/AUTOML.md).
    A space restricted to these keys partitions into one fused group per
    candidate estimator (``num_leaves`` may still split groups by the tree
    depth it derives when ``max_depth`` is unset). Returns ``()`` for
    learners without a fused path."""
    if isinstance(learner, str):
        from .. import gbdt

        cls = getattr(gbdt, learner, None)
        if cls is None:
            return ()
    else:
        cls = learner if isinstance(learner, type) else type(learner)
    scalars = getattr(cls, "_FUSED_SCALAR_PARAMS", None)
    return tuple(sorted(scalars)) if scalars else ()


class DiscreteHyperParam:
    def __init__(self, values):
        self.values = list(values)

    def sample(self, rng: np.random.Generator):
        return self.values[int(rng.integers(0, len(self.values)))]

    def grid(self):
        return list(self.values)


class RangeHyperParam:
    def __init__(self, low, high, log: bool = False, integer: bool | None = None):
        self.low, self.high, self.log = low, high, log
        self.integer = (isinstance(low, int) and isinstance(high, int)
                        if integer is None else integer)

    def sample(self, rng: np.random.Generator):
        if self.log:
            v = float(np.exp(rng.uniform(np.log(self.low), np.log(self.high))))
        else:
            v = float(rng.uniform(self.low, self.high))
        return int(round(v)) if self.integer else v

    def grid(self, n: int = 5):
        if self.log:
            vals = np.exp(np.linspace(np.log(self.low), np.log(self.high), n))
        else:
            vals = np.linspace(self.low, self.high, n)
        return [int(round(v)) for v in vals] if self.integer else [float(v) for v in vals]


class HyperparamBuilder:
    """Collects param-name -> space mappings (ref ``HyperparamBuilder.scala``)."""

    def __init__(self):
        self._space: dict[str, object] = {}

    def add_hyperparam(self, name: str, space) -> "HyperparamBuilder":
        self._space[name] = space
        return self

    def build(self) -> dict:
        return dict(self._space)


class GridSpace:
    """Cartesian product of every space's grid()."""

    def __init__(self, space: dict):
        self.space = space

    def configs(self) -> list[dict]:
        import itertools

        names = list(self.space)
        grids = [self.space[n].grid() for n in names]
        return [dict(zip(names, combo)) for combo in itertools.product(*grids)]


class RandomSpace:
    def __init__(self, space: dict, seed: int = 0):
        self.space = space
        self.rng = np.random.default_rng(seed)

    def configs(self, n: int) -> list[dict]:
        return [{k: v.sample(self.rng) for k, v in self.space.items()} for _ in range(n)]


class DefaultHyperparams:
    """Good default sweep ranges per learner family (reference
    ``automl/DefaultHyperparams.scala`` — publicly visible so users can pick
    the ranges to sweep). Keyed by estimator CLASS or instance; ranges are
    expressed against this framework's learners (GBDT and VW linear replace
    SparkML's tree/LR families)."""

    @staticmethod
    def default_range(learner) -> dict:
        name = _learner_name(learner)
        spaces = {
            "LightGBMClassifier": {
                "num_leaves": RangeHyperParam(8, 63),
                "num_iterations": RangeHyperParam(20, 100),
                "learning_rate": RangeHyperParam(0.01, 0.3, log=True),
                "min_data_in_leaf": RangeHyperParam(5, 50),
                "lambda_l2": RangeHyperParam(1e-3, 1.0, log=True),
            },
            "LightGBMRegressor": {
                "num_leaves": RangeHyperParam(8, 63),
                "num_iterations": RangeHyperParam(20, 100),
                "learning_rate": RangeHyperParam(0.01, 0.3, log=True),
                "lambda_l2": RangeHyperParam(1e-3, 1.0, log=True),
            },
            "VowpalWabbitClassifier": {
                "learning_rate": RangeHyperParam(0.01, 1.0, log=True),
                "num_passes": RangeHyperParam(1, 10),
                "l2": RangeHyperParam(1e-8, 1e-2, log=True),
            },
            "VowpalWabbitRegressor": {
                "learning_rate": RangeHyperParam(0.01, 1.0, log=True),
                "num_passes": RangeHyperParam(1, 10),
                "l2": RangeHyperParam(1e-8, 1e-2, log=True),
            },
        }
        if name not in spaces:
            raise ValueError(f"no default hyperparameter range for {name}; "
                             f"have {sorted(spaces)}")
        return spaces[name]

    @staticmethod
    def fused_range(learner) -> dict:
        """The :meth:`default_range` restricted to dimensions that fuse into
        one training array (:func:`fusable_param_names`) — the sweep space
        to pick when you want ``TuneHyperparameters`` to train every config
        in one jitted step instead of a thread pool of serial fits."""
        fusable = set(fusable_param_names(learner))
        if not fusable:
            raise ValueError(f"{_learner_name(learner)} has no fused training "
                             "path; use default_range and the serial sweep")
        full = DefaultHyperparams.default_range(learner)
        return {k: v for k, v in full.items() if k in fusable}
