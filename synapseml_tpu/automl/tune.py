"""TuneHyperparameters / FindBestModel
(reference ``automl/TuneHyperparameters.scala:38``, ``FindBestModel.scala:53``).

Parallelism: candidates are first partitioned into **fusable groups** —
same estimator class, architecture-identical configs (equal fused
signatures, see ``_fused_plan`` on the estimator) — and each group trains
inside ONE horizontally fused training array (HFTA, arXiv:2102.02344): one
jitted step / boosting iteration drives every trial in the group, data is
loaded and device-put once, and N configs share one compiled executable
through the process-wide ``CompiledCache`` instead of N thread-pool fits
serializing N dispatch streams (and N compiles) on the device. Candidates
without a fused path — different architectures, bagging/DART, categorical
splits, non-GBDT learners — fall back to the reference-style thread pool,
where host-side prep overlaps while the device serializes fits.
"""

from __future__ import annotations

import logging
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..core import observability as obs
from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Estimator, Model
from ..train.statistics import ComputeModelStatistics

__all__ = ["TuneHyperparameters", "BestModel", "FindBestModel", "FindBestModelResult"]

_log = logging.getLogger("synapseml_tpu")

_METRIC_DIRECTION = {"accuracy": 1, "precision": 1, "recall": 1, "AUC": 1, "R^2": 1,
                     "mean_squared_error": -1, "root_mean_squared_error": -1,
                     "mean_absolute_error": -1}

_SWEEP_METRICS = obs.HandleCache(lambda reg: {
    "trials": reg.counter(
        "synapseml_hpo_trials_total",
        "hyperparameter-sweep candidate fits", ("stage", "mode")),
    "sweep_trials_per_sec": reg.gauge(
        "synapseml_hpo_sweep_trials_per_sec",
        "end-to-end candidate fits per second of the last sweep", ("stage",)),
    "fused_groups": reg.counter(
        "synapseml_hpo_fused_groups_total",
        "fusable candidate groups trained as one fused array", ("stage",)),
    "fused_fallbacks": reg.counter(
        "synapseml_hpo_fused_fallbacks_total",
        "fused groups demoted to the serial path by a group-level failure",
        ("stage",)),
})


def _evaluate(model, df: DataFrame, metric: str, label_col: str) -> float:
    scored = model.transform(df)
    pred_col = None
    has_param = getattr(model, "has_param", None)
    if callable(has_param) and has_param("prediction_col"):
        declared = model.get("prediction_col")
        if declared in scored.columns:
            pred_col = declared
    if pred_col is None and "prediction" in scored.columns:
        pred_col = "prediction"
    if pred_col is None:
        # never silently grab an arbitrary column — a wrong pick scores the
        # sweep on garbage and crowns a random winner
        raise ValueError(
            f"cannot locate the prediction column on {type(model).__name__}'s "
            f"scored output: no declared prediction_col or 'prediction' among "
            f"columns {list(scored.columns)}; set the model's prediction_col "
            "to its score column")
    kind = ("regression" if metric in ("mean_squared_error", "root_mean_squared_error",
                                       "mean_absolute_error", "R^2") else "classification")
    stats = ComputeModelStatistics(
        label_col=label_col, scores_col=pred_col, evaluation_metric=kind,
        scored_probabilities_col="probability" if "probability" in scored.columns else None,
    ).transform(scored)
    if metric not in stats.columns:
        raise ValueError(
            f"metric {metric!r} unavailable for this model/dataset "
            f"(computed: {stats.columns}). 'AUC' needs a binary label and a "
            f"'probability' column on the scored output.")
    return float(stats.collect_column(metric)[0])


def _merged_cfg(est, cfg: dict) -> dict:
    """The candidate's COMPLETE training config as an override dict: the
    estimator's set values + the sweep overrides, with the estimator's
    fusable scalar values pinned explicitly — so a fused group's base
    estimator can reproduce any member via ``base.copy(merged)`` even when
    members are distinct instances with different set values."""
    merged = dict(est._param_values)
    scalars = getattr(type(est), "_FUSED_SCALAR_PARAMS", None)
    if scalars:
        has_param = getattr(est, "has_param", lambda _n: False)
        for name in scalars:
            if has_param(name):
                merged.setdefault(name, est.get(name))
    merged.update(cfg)
    return merged


def _fusable_groups(candidates: list[tuple], enabled: bool = True
                    ) -> tuple[list[tuple], list[tuple]]:
    """Partition ``(idx, name, est, user_cfg, merged_cfg)`` candidates.

    Returns ``(groups, singles)``: each group is ``(base_est, members)``
    where every member shares the base's fused signature under its MERGED
    config (estimator-set values + sweep overrides), so the group differs
    only in traced scalar hyperparameters and trains as one fused array.
    Signature-less candidates (no ``_fused_plan``, architecture-changing
    overrides, unsupported modes) and singleton groups go to ``singles`` —
    the serial thread-pool path."""
    groups_map: dict = {}
    singles: list[tuple] = []
    if not enabled:
        return [], list(candidates)
    for cand in candidates:
        _idx, _name, est, _cfg, merged = cand
        plan = getattr(est, "_fused_plan", None)
        sig = None
        # fitted Transformers (FindBestModel candidates) inherit _fused_plan
        # from their params mixin but have nothing to train — singles, not a
        # doomed fused group that would count as a spurious fallback
        if isinstance(est, Estimator) and callable(plan):
            try:
                sig = plan(merged)
            except Exception:  # a broken plan must not sink the sweep
                sig = None
        if sig is None:
            singles.append(cand)
        else:
            groups_map.setdefault(sig, []).append(cand)
    groups = []
    for members in groups_map.values():
        if len(members) >= 2:
            groups.append((members[0][2], members))
        else:
            singles.extend(members)
    return groups, singles


def _run_sweep(stage: str, candidates: list[tuple], fit_serial, fit_fused,
               evaluate, fuse: bool, parallelism: int) -> list[tuple]:
    """Shared sweep engine for TuneHyperparameters and FindBestModel.

    ``candidates``: (idx, name, est, user_cfg, merged_cfg) tuples.
    ``fit_serial(cand) -> model`` and ``fit_fused(base_est, merged_cfgs) ->
    models`` may raise per candidate/group; ``evaluate(model) -> float`` may
    raise per model. Returns results aligned with ``candidates``:
    ``(name, user_cfg_with_error, model_or_None, metric)`` — a bad candidate
    records ``__error__`` + NaN instead of sinking the sweep."""
    m = _SWEEP_METRICS.get()
    t0 = time.perf_counter()
    results: dict[int, tuple] = {}
    groups, singles = _fusable_groups(candidates, enabled=fuse)

    def record(cand, model, metric, error=None):
        idx, name, _est, cfg, _merged = cand
        if error is not None:
            cfg = dict(cfg, __error__=error)
        results[idx] = (name, cfg, model, metric)

    def eval_contained(cand, model, mode):
        try:
            metric = evaluate(model)
        except Exception as e:  # noqa: BLE001 — containment by contract
            record(cand, None, float("nan"), f"{type(e).__name__}: {e}")
        else:
            record(cand, model, metric)
        m["trials"].inc(stage=stage, mode=mode)

    def run_single(cand):
        try:
            model = fit_serial(cand)
        except Exception as e:  # noqa: BLE001 — a bad config must not sink
            record(cand, None, float("nan"), f"{type(e).__name__}: {e}")
            m["trials"].inc(stage=stage, mode="serial")
            return
        eval_contained(cand, model, mode="serial")

    with ThreadPoolExecutor(max_workers=max(parallelism, 1)) as pool:
        # singles go to the pool FIRST so their host-side prep overlaps the
        # device-bound fused-group training on this thread; fused members'
        # (host-heavy) evaluation and any demoted group join the same pool
        done = [pool.submit(run_single, cand) for cand in singles]
        for base_est, members in groups:
            try:
                models = fit_fused(base_est, [c[4] for c in members])
            except Exception as e:  # noqa: BLE001 — group demotes to serial
                # the sweep survives on the thread pool, but a silent demotion
                # would hide a fused-path regression behind an N-fold slowdown
                _log.warning(
                    "%s: fused group of %d %s candidates demoted to the "
                    "serial path: %s: %s", stage, len(members),
                    type(base_est).__name__, type(e).__name__, e)
                m["fused_fallbacks"].inc(stage=stage)
                done += [pool.submit(run_single, c) for c in members]
                continue
            m["fused_groups"].inc(stage=stage)
            done += [pool.submit(eval_contained, cand, model, "fused")
                     for cand, model in zip(members, models)]
        for f in done:
            f.result()

    wall = max(time.perf_counter() - t0, 1e-9)
    m["sweep_trials_per_sec"].set(len(candidates) / wall, stage=stage)
    return [results[c[0]] for c in candidates]


class BestModel(Model):
    best_model = ComplexParam("best_model", "winning fitted model")
    best_params = ComplexParam("best_params", "winning hyperparameter dict")
    best_metric = Param("best_metric", "winning validation metric value",
                        converter=TypeConverters.to_float)
    all_results = ComplexParam(
        "all_results", "list of (estimator_name, params, metric) tuples — "
        "estimator_name is 'ClassName[i]' for candidate i of the models "
        "list, so multi-estimator sweeps keep model identity")

    def _transform(self, df: DataFrame) -> DataFrame:
        return self.get("best_model").transform(df)


class TuneHyperparameters(Estimator):
    """Random/grid search over (possibly several) learners
    (ref ``TuneHyperparameters.scala:38``). Architecture-identical configs
    of the same learner train as ONE horizontally fused array (see the
    module docstring); the rest ride the thread pool."""

    feature_name = "automl"

    models = ComplexParam("models", "list of candidate Estimators")
    hyperparam_space = ComplexParam("hyperparam_space",
                                    "dict name->space, or list aligned with models")
    search_mode = Param("search_mode", "random | grid", default="random",
                        validator=lambda v: v in ("random", "grid"))
    num_runs = Param("num_runs", "samples for random search", default=8,
                     converter=TypeConverters.to_int)
    parallelism = Param("parallelism", "concurrent serial-path fits", default=4,
                        converter=TypeConverters.to_int)
    fuse_trials = Param("fuse_trials", "train architecture-identical configs "
                        "as one fused training array (serial fallback on "
                        "group failure); False forces the thread pool",
                        default=True, converter=TypeConverters.to_bool)
    evaluation_metric = Param("evaluation_metric", "metric name", default="accuracy")
    label_col = Param("label_col", "label column", default="label")
    validation_fraction = Param("validation_fraction", "holdout fraction", default=0.25,
                                converter=TypeConverters.to_float)
    seed = Param("seed", "search seed", default=0, converter=TypeConverters.to_int)

    def _fit(self, df: DataFrame) -> BestModel:
        from .hyperparams import GridSpace, RandomSpace

        models = self.get("models")
        if not isinstance(models, (list, tuple)):
            models = [models]
        spaces = self.get("hyperparam_space")
        if isinstance(spaces, dict):
            spaces = [spaces] * len(models)
        train, valid = df.random_split(
            [1 - self.get("validation_fraction"), self.get("validation_fraction")],
            seed=self.get("seed"))
        metric = self.get("evaluation_metric")
        direction = _METRIC_DIRECTION.get(metric, 1)

        candidates: list[tuple] = []
        for mi, (m, space) in enumerate(zip(models, spaces)):
            if self.get("search_mode") == "grid":
                configs = GridSpace(space).configs()
            else:
                configs = RandomSpace(space, seed=self.get("seed") + mi).configs(
                    self.get("num_runs"))
            name = f"{type(m).__name__}[{mi}]"
            for c in configs:
                candidates.append((len(candidates), name, m, dict(c),
                                   _merged_cfg(m, c)))

        results = _run_sweep(
            "TuneHyperparameters", candidates,
            fit_serial=lambda cand: cand[2].copy(cand[3]).fit(train),
            fit_fused=lambda base, cfgs: base._fit_fused(train, cfgs),
            evaluate=lambda model: _evaluate(model, valid, metric,
                                             self.get("label_col")),
            fuse=self.get("fuse_trials"), parallelism=self.get("parallelism"))

        scored = [(nm, c, mdl, v) for nm, c, mdl, v in results
                  if mdl is not None and np.isfinite(v)]
        if not scored:
            errors = {c["__error__"] for _, c, _, _ in results if "__error__" in c}
            raise RuntimeError("TuneHyperparameters: every candidate failed; "
                               f"causes: {sorted(errors)}")
        best = max(scored, key=lambda t: direction * t[3])
        return BestModel(best_model=best[2], best_params=best[1], best_metric=best[3],
                         all_results=[(nm, c, v) for nm, c, _, v in results])


class FindBestModelResult(Model):
    best_model = ComplexParam("best_model", "winning fitted model")
    all_model_metrics = ComplexParam("all_model_metrics", "list of (name, metric)")
    best_metric = Param("best_metric", "winning metric", converter=TypeConverters.to_float)

    def _transform(self, df: DataFrame) -> DataFrame:
        return self.get("best_model").transform(df)


class FindBestModel(Estimator):
    """Pick the best among already-specified models by eval metric
    (ref ``FindBestModel.scala:53``). Models may be fitted Transformers
    (evaluated directly) or Estimators (fitted first). Estimator candidates
    ride the same fusable-group partitioning TuneHyperparameters uses —
    same-class, architecture-identical candidates train as one fused array,
    the rest fit on a thread pool — and a failing candidate records NaN
    instead of sinking the comparison."""

    feature_name = "automl"

    models = ComplexParam("models", "candidate models")
    evaluation_metric = Param("evaluation_metric", "metric name", default="accuracy")
    label_col = Param("label_col", "label column", default="label")
    parallelism = Param("parallelism", "concurrent serial-path fits", default=4,
                        converter=TypeConverters.to_int)
    fuse_trials = Param("fuse_trials", "train architecture-identical "
                        "estimator candidates as one fused training array",
                        default=True, converter=TypeConverters.to_bool)

    def _fit(self, df: DataFrame) -> FindBestModelResult:
        metric = self.get("evaluation_metric")
        direction = _METRIC_DIRECTION.get(metric, 1)
        candidates = []
        for i, m in enumerate(self.get("models")):
            merged = _merged_cfg(m, {}) if isinstance(m, Estimator) else {}
            candidates.append((i, f"{type(m).__name__}[{i}]", m, {}, merged))

        def fit_serial(cand):
            m = cand[2]
            return m.fit(df) if isinstance(m, Estimator) else m

        def fit_fused(base, merged_cfgs):
            if not isinstance(base, Estimator):
                raise TypeError("fitted models have no fused path")
            return base._fit_fused(df, merged_cfgs)

        results = _run_sweep(
            "FindBestModel", candidates, fit_serial=fit_serial,
            fit_fused=fit_fused,
            evaluate=lambda model: _evaluate(model, df, metric,
                                             self.get("label_col")),
            fuse=self.get("fuse_trials"), parallelism=self.get("parallelism"))

        scored = [(nm, mdl, v) for nm, _c, mdl, v in results
                  if mdl is not None and np.isfinite(v)]
        if not scored:
            errors = {c["__error__"] for _, c, _, _ in results if "__error__" in c}
            raise RuntimeError("FindBestModel: every candidate failed; "
                               f"causes: {sorted(errors)}")
        best = max(scored, key=lambda t: direction * t[2])
        # 'ClassName[i]' uniformly (success or failure) — the fitted model's
        # class name would collapse duplicate-class candidates into one label
        return FindBestModelResult(
            best_model=best[1], best_metric=best[2],
            all_model_metrics=[(nm, v) for nm, _c, _mdl, v in results])
