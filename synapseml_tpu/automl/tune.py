"""TuneHyperparameters / FindBestModel
(reference ``automl/TuneHyperparameters.scala:38``, ``FindBestModel.scala:53``).

Parallelism note: candidate fits run on a thread pool — each fit dispatches its
own XLA programs, and the TPU runtime serializes device work while the host
side (binning, featurization, data prep) overlaps, mirroring the reference's
parallel fits across a Spark cluster."""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Estimator, Model
from ..train.statistics import ComputeModelStatistics

__all__ = ["TuneHyperparameters", "BestModel", "FindBestModel", "FindBestModelResult"]

_METRIC_DIRECTION = {"accuracy": 1, "precision": 1, "recall": 1, "AUC": 1, "R^2": 1,
                     "mean_squared_error": -1, "root_mean_squared_error": -1,
                     "mean_absolute_error": -1}


def _evaluate(model, df: DataFrame, metric: str, label_col: str) -> float:
    scored = model.transform(df)
    pred_col = "prediction" if "prediction" in scored.columns else scored.columns[-1]
    kind = ("regression" if metric in ("mean_squared_error", "root_mean_squared_error",
                                       "mean_absolute_error", "R^2") else "classification")
    stats = ComputeModelStatistics(
        label_col=label_col, scores_col=pred_col, evaluation_metric=kind,
        scored_probabilities_col="probability" if "probability" in scored.columns else None,
    ).transform(scored)
    if metric not in stats.columns:
        raise ValueError(
            f"metric {metric!r} unavailable for this model/dataset "
            f"(computed: {stats.columns}). 'AUC' needs a binary label and a "
            f"'probability' column on the scored output.")
    return float(stats.collect_column(metric)[0])


class BestModel(Model):
    best_model = ComplexParam("best_model", "winning fitted model")
    best_params = ComplexParam("best_params", "winning hyperparameter dict")
    best_metric = Param("best_metric", "winning validation metric value",
                        converter=TypeConverters.to_float)
    all_results = ComplexParam("all_results", "list of (params, metric) tuples")

    def _transform(self, df: DataFrame) -> DataFrame:
        return self.get("best_model").transform(df)


class TuneHyperparameters(Estimator):
    """Random/grid search over (possibly several) learners
    (ref ``TuneHyperparameters.scala:38``)."""

    feature_name = "automl"

    models = ComplexParam("models", "list of candidate Estimators")
    hyperparam_space = ComplexParam("hyperparam_space",
                                    "dict name->space, or list aligned with models")
    search_mode = Param("search_mode", "random | grid", default="random",
                        validator=lambda v: v in ("random", "grid"))
    num_runs = Param("num_runs", "samples for random search", default=8,
                     converter=TypeConverters.to_int)
    parallelism = Param("parallelism", "concurrent fits", default=4,
                        converter=TypeConverters.to_int)
    evaluation_metric = Param("evaluation_metric", "metric name", default="accuracy")
    label_col = Param("label_col", "label column", default="label")
    validation_fraction = Param("validation_fraction", "holdout fraction", default=0.25,
                                converter=TypeConverters.to_float)
    seed = Param("seed", "search seed", default=0, converter=TypeConverters.to_int)

    def _fit(self, df: DataFrame) -> BestModel:
        from .hyperparams import GridSpace, RandomSpace

        models = self.get("models")
        if not isinstance(models, (list, tuple)):
            models = [models]
        spaces = self.get("hyperparam_space")
        if isinstance(spaces, dict):
            spaces = [spaces] * len(models)
        train, valid = df.random_split(
            [1 - self.get("validation_fraction"), self.get("validation_fraction")],
            seed=self.get("seed"))
        metric = self.get("evaluation_metric")
        direction = _METRIC_DIRECTION.get(metric, 1)

        candidates: list[tuple[Estimator, dict]] = []
        for mi, (m, space) in enumerate(zip(models, spaces)):
            if self.get("search_mode") == "grid":
                configs = GridSpace(space).configs()
            else:
                configs = RandomSpace(space, seed=self.get("seed") + mi).configs(
                    self.get("num_runs"))
            candidates.extend((m, c) for c in configs)

        def run(pair):
            est, cfg = pair
            try:
                model = est.copy(cfg).fit(train)
                return model, cfg, _evaluate(model, valid, metric, self.get("label_col"))
            except Exception as e:  # a bad config must not sink the sweep
                return None, dict(cfg, __error__=f"{type(e).__name__}: {e}"), float("nan")

        with ThreadPoolExecutor(max_workers=self.get("parallelism")) as pool:
            results = list(pool.map(run, candidates))
        scored = [(m, c, v) for m, c, v in results if m is not None and np.isfinite(v)]
        if not scored:
            errors = {c["__error__"] for _, c, _ in results if "__error__" in c}
            raise RuntimeError("TuneHyperparameters: every candidate failed; "
                               f"causes: {sorted(errors)}")
        best = max(scored, key=lambda t: direction * t[2])
        return BestModel(best_model=best[0], best_params=best[1], best_metric=best[2],
                         all_results=[(c, v) for _, c, v in results])


class FindBestModelResult(Model):
    best_model = ComplexParam("best_model", "winning fitted model")
    all_model_metrics = ComplexParam("all_model_metrics", "list of (name, metric)")
    best_metric = Param("best_metric", "winning metric", converter=TypeConverters.to_float)

    def _transform(self, df: DataFrame) -> DataFrame:
        return self.get("best_model").transform(df)


class FindBestModel(Estimator):
    """Pick the best among already-specified models by eval metric
    (ref ``FindBestModel.scala:53``). Models may be fitted Transformers
    (evaluated directly) or Estimators (fitted first)."""

    feature_name = "automl"

    models = ComplexParam("models", "candidate models")
    evaluation_metric = Param("evaluation_metric", "metric name", default="accuracy")
    label_col = Param("label_col", "label column", default="label")

    def _fit(self, df: DataFrame) -> FindBestModelResult:
        metric = self.get("evaluation_metric")
        direction = _METRIC_DIRECTION.get(metric, 1)
        results = []
        for m in self.get("models"):
            fitted = m.fit(df) if isinstance(m, Estimator) else m
            results.append((fitted, _evaluate(fitted, df, metric, self.get("label_col"))))
        best = max(results, key=lambda t: direction * t[1])
        return FindBestModelResult(
            best_model=best[0], best_metric=best[1],
            all_model_metrics=[(type(m).__name__, v) for m, v in results])
