"""AutoML (reference ``core/.../automl/``, SURVEY.md §2.5): parallel
hyperparameter search and best-model selection."""

from .hyperparams import (  # noqa: F401
    DefaultHyperparams,
    DiscreteHyperParam,
    GridSpace,
    HyperparamBuilder,
    RandomSpace,
    RangeHyperParam,
    fusable_param_names,
)
from .tune import BestModel, FindBestModel, FindBestModelResult, TuneHyperparameters  # noqa: F401
