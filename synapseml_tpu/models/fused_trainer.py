"""Horizontally fused training arrays: ONE jitted step trains N trials.

The HFTA result (PAPERS.md, arXiv:2102.02344): hyperparameter trials of the
same architecture differ only in scalar knobs, so fusing N model replicas
along a leading "trial" axis recovers close to an order of magnitude of
accelerator utilization versus running the trials back-to-back (or on a
thread pool, where the device serializes N separate dispatch streams and
each distinct config pays its own XLA compile).

Design:

* **Stacked state.** Params / optimizer state / step counters carry a
  leading trial axis sized to a *rung* of the trial-count ladder
  (:func:`core.batching.default_trial_bucketer`), so sweeps of any size
  compile at most ladder-many step executables — the TVM lesson
  (arXiv:1802.04799): pay compilation once, amortize over many executions.
* **Hyperparameters as data.** Per-trial learning rate / weight decay /
  Adam betas / grad-clip ride inside the optimizer state via
  ``optax.inject_hyperparams`` (loss-side knobs like label smoothing ride
  in a ``hparams`` subtree), so N configs share ONE executable acquired
  through the process-wide :class:`core.batching.CompiledCache` — never N.
  The injected math is the SAME ``clip_by_global_norm -> adamw`` chain the
  serial :class:`Trainer` builds, so fused and serial runs agree to f32
  rounding (the parity suite in ``tests/test_fused_automl.py``).
* **One shared batch.** Every step consumes one batch from the PR-5
  :class:`data.DataLoader` (loaded and device-put once) broadcast across
  trials via ``vmap(in_axes=None)`` — no per-trial input pipelines.
* **Early stop without recompiles.** A per-trial ``active`` mask zeroes
  dead trials' updates inside the same executable; :meth:`compact` at rung
  boundaries gathers survivors into a smaller stacked state (a new rung =
  at most one more ladder compile).

Scope: constant learning rate (per-trial schedules would need
count-dependent hyperparams), no gradient accumulation / layer freezing /
batch_stats — sweeps needing those fall back to the serial path.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Iterable, Iterator

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..core import batching as cb
from ..core.hpo_metrics import HPO_ARRAY_METRICS as _HPO_METRICS
from ..parallel.mesh import MeshContext

__all__ = ["FusedTrainer", "FUSED_OPT_HPARAMS", "FUSED_LOSS_HPARAMS",
           "fused_fit_source", "fused_fit_arrays"]

# scalar knobs that become traced optimizer-state leaves (one executable
# serves any values) vs loss-side knobs threaded into the vmapped loss
FUSED_OPT_HPARAMS = ("learning_rate", "weight_decay", "b1", "b2", "grad_clip")
FUSED_LOSS_HPARAMS = ("label_smoothing",)


def _fused_tx(learning_rate, weight_decay, b1, b2, grad_clip):
    """EXACTLY the serial Trainer's constant-lr optimizer chain
    (``_make_optimizer`` with no freeze/accum) — the parity guarantee
    rests on the two paths sharing this formula."""
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(learning_rate, b1=b1, b2=b2, weight_decay=weight_decay))


def _batch_shape_key(batch: dict) -> tuple:
    return tuple(sorted(
        (k, tuple(np.shape(v)), str(getattr(v, "dtype", None)
                                    or np.asarray(v).dtype))
        for k, v in batch.items()))


class FusedTrainer:
    """Trains ``len(trials)`` hyperparameter variants of one module inside
    a single jitted step.

    ``trials``: one dict per trial; keys may override
    :data:`FUSED_OPT_HPARAMS`, :data:`FUSED_LOSS_HPARAMS` and ``seed``
    (the per-trial init/dropout PRNG seed). Unset keys inherit from
    ``cfg`` (a :class:`TrainerConfig`); ``label_smoothing`` defaults 0 —
    at 0 the fused loss is bit-for-bit the serial ``cross_entropy_loss``.

    State is a plain dict pytree (like the serial step's): ``params`` /
    ``opt_state`` / ``step`` / ``active`` / ``hparams``, every leaf with a
    leading trial-rung axis.
    """

    def __init__(self, module, mesh_ctx: MeshContext, cfg, trials: list[dict],
                 loss_fn: Callable[[Any, dict], jax.Array] | None = None,
                 trial_bucketer: cb.ShapeBucketer | None = None):
        if not trials:
            raise ValueError("FusedTrainer needs at least one trial")
        if cfg.grad_accum > 1 or cfg.freeze_predicate is not None:
            raise ValueError(
                "fused training arrays do not support grad_accum/freezing — "
                "run those configs on the serial Trainer path")
        if cfg.lr_schedule != "constant":
            raise ValueError(
                "fused training arrays support constant learning rates only "
                f"(got lr_schedule={cfg.lr_schedule!r}); schedules need "
                "count-dependent hyperparams — use the serial path")
        base = {"learning_rate": cfg.learning_rate,
                "weight_decay": cfg.weight_decay, "b1": cfg.b1, "b2": cfg.b2,
                "grad_clip": cfg.grad_clip, "label_smoothing": 0.0,
                # None = inherit init_state's default_seed (the sweep seed),
                # matching fit_source's PRNGKey(seed) init on the serial arm
                "seed": None}
        allowed = set(base)
        merged = []
        for i, t in enumerate(trials):
            unknown = set(t) - allowed
            if unknown:
                raise ValueError(
                    f"trial {i} has non-fusable keys {sorted(unknown)}; "
                    f"fusable scalar hyperparameters: {sorted(allowed)}")
            if loss_fn is not None:
                overridden = set(t) & set(FUSED_LOSS_HPARAMS)
                if overridden:
                    # a custom loss_fn(variables, batch) has no hyperparameter
                    # argument — the override would be silently discarded and
                    # identical trials reported as distinct configs
                    raise ValueError(
                        f"trial {i} sets {sorted(overridden)} but a custom "
                        "loss_fn is in use, which cannot receive loss-side "
                        "hyperparameters; drop the override or fold it into "
                        "loss_fn")
            merged.append({**base, **t})
        self.module = module
        self.mesh = mesh_ctx
        self.cfg = cfg
        self.trials = merged
        self.n_trials = len(merged)
        self._loss_fn = loss_fn
        self._bucketer = trial_bucketer or cb.default_trial_bucketer()
        self._tx = optax.inject_hyperparams(_fused_tx)(
            learning_rate=cfg.learning_rate, weight_decay=cfg.weight_decay,
            b1=cfg.b1, b2=cfg.b2, grad_clip=cfg.grad_clip)
        # slot -> original trial index (compact() drops dead slots)
        self.slot_ids: list[int] = []
        self._active_host = np.zeros(0, np.float32)
        self._metrics: list[dict] = []

    # ---- bookkeeping ----
    @property
    def rung(self) -> int:
        return len(self._active_host)

    @property
    def n_live(self) -> int:
        return int(self._active_host.sum())

    def live_trials(self) -> list[int]:
        return [tid for s, tid in enumerate(self.slot_ids)
                if self._active_host[s] > 0]

    def _model_inputs(self, batch: dict) -> dict:
        drop = {"labels", "label", "mask", "_valid"}
        return {k: v for k, v in batch.items() if k not in drop}

    def _hparam_column(self, key: str, slot_trials: list[int]) -> jnp.ndarray:
        return jnp.asarray([self.trials[t][key] for t in slot_trials],
                           jnp.float32)

    # ---- loss (serial cross_entropy_loss + optional label smoothing) ----
    def _trial_loss(self, params, batch: dict, label_smoothing) -> jax.Array:
        if self._loss_fn is not None:
            return self._loss_fn({"params": params}, batch)
        logits = self.module.apply({"params": params},
                                   **self._model_inputs(batch))
        labels = batch.get("labels", batch.get("label"))
        mask = batch.get("_valid")
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(
            logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
        # at label_smoothing == 0 this is EXACTLY cross_entropy_loss
        per = (1.0 - label_smoothing) * nll \
            + label_smoothing * (-jnp.mean(logp, axis=-1))
        if mask is not None:
            return jnp.sum(per * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        return jnp.mean(per)

    # ---- state init ----
    def init_state(self, example_batch: dict, default_seed: int = 0) -> dict:
        """Stacked state for every trial, padded up to the trial-count rung
        (pad slots replicate trial 0 with ``active=0`` — they never train).
        Trials without an explicit ``seed`` init from ``default_seed`` — the
        sweep seed, so a serial ``fit_source`` run under the same seed inits
        identically."""
        rung = self._bucketer.bucket_for(self.n_trials)
        slot_trials = list(range(self.n_trials)) \
            + [0] * (rung - self.n_trials)
        inputs = self._model_inputs(example_batch)
        cache = cb.get_compiled_cache()
        token = cb.instance_token(self)
        module = self.module

        def build():
            from flax.core import meta

            def init_one(key):
                return meta.unbox(module.init(key, **inputs)["params"])

            return jax.jit(jax.vmap(init_one))

        init_fn = cache.get("fused_init",
                            (rung,) + _batch_shape_key(example_batch),
                            build, instance=token)
        keys = jnp.stack([
            jax.random.PRNGKey(int(default_seed
                                   if self.trials[t]["seed"] is None
                                   else self.trials[t]["seed"]))
            for t in slot_trials])
        with self.mesh.mesh:
            params = init_fn(keys)
        tx = self._tx

        def _build_opt():
            return jax.jit(jax.vmap(tx.init))

        with self.mesh.mesh:
            opt_state = cache.get("fused_opt_init", (rung,), _build_opt,
                                  instance=token)(params)
        hp = dict(opt_state.hyperparams)
        for key in FUSED_OPT_HPARAMS:
            hp[key] = self._hparam_column(key, slot_trials)
        opt_state = opt_state._replace(hyperparams=hp)
        self.slot_ids = slot_trials[: self.n_trials]
        self._active_host = np.asarray(
            [1.0] * self.n_trials + [0.0] * (rung - self.n_trials),
            np.float32)
        _HPO_METRICS.get()["active"].set(self.n_live, engine="fused_trainer")
        return {
            "params": params, "opt_state": opt_state,
            "step": jnp.zeros((rung,), jnp.int32),
            "active": jnp.asarray(self._active_host),
            "hparams": {
                "label_smoothing": self._hparam_column("label_smoothing",
                                                       slot_trials)},
        }

    # ---- the one fused step ----
    def _build_step(self):
        tx = self._tx
        trial_loss = self._trial_loss

        def build():
            def one_trial(tstate, batch, ls):
                loss, grads = jax.value_and_grad(
                    lambda p: trial_loss(p, batch, ls))(tstate["params"])
                updates, new_opt = tx.update(grads, tstate["opt_state"],
                                             tstate["params"])
                new_params = optax.apply_updates(tstate["params"], updates)
                return (new_params, new_opt, loss.astype(jnp.float32),
                        optax.global_norm(grads).astype(jnp.float32))

            def step(state, batch):
                new_params, new_opt, loss, gnorm = jax.vmap(
                    one_trial,
                    in_axes=({"params": 0, "opt_state": 0}, None, 0))(
                        {"params": state["params"],
                         "opt_state": state["opt_state"]},
                        batch, state["hparams"]["label_smoothing"])
                live = state["active"] > 0.0

                def keep(new, old):
                    m = live.reshape(live.shape + (1,) * (jnp.ndim(new) - 1))
                    return jnp.where(m, new, old)

                metrics = {"loss": jnp.where(live, loss, jnp.nan),
                           "grad_norm": jnp.where(live, gnorm, 0.0)}
                return {"params": jax.tree.map(keep, new_params,
                                               state["params"]),
                        "opt_state": jax.tree.map(keep, new_opt,
                                                  state["opt_state"]),
                        "step": state["step"] + live.astype(jnp.int32),
                        "active": state["active"],
                        "hparams": state["hparams"]}, metrics

            return jax.jit(step, donate_argnums=(0,))

        return build

    def train_step(self, state: dict, batch: dict) -> tuple[dict, dict]:
        """One fused optimizer step for every live trial. The executable is
        acquired through the shared CompiledCache keyed on (trial rung,
        batch shape) — any number of configs rides ladder-many compiles."""
        fn = cb.get_compiled_cache().get(
            "fused_train_step", (self.rung,) + _batch_shape_key(batch),
            self._build_step(), instance=cb.instance_token(self))
        placed = self.mesh.shard_batch(batch)
        with self.mesh.mesh:
            return fn(state, placed)

    # ---- early-stop masking + rung compaction ----
    def deactivate(self, state: dict, trial_ids: Iterable[int]) -> dict:
        """Freeze the given trials (by ORIGINAL trial index): their updates
        are masked to zero inside the SAME executable — no recompile."""
        doomed = set(trial_ids)
        for slot, tid in enumerate(self.slot_ids):
            if tid in doomed:
                self._active_host[slot] = 0.0
        _HPO_METRICS.get()["active"].set(self.n_live, engine="fused_trainer")
        return dict(state, active=jnp.asarray(self._active_host))

    def compact(self, state: dict) -> dict:
        """Gather surviving trials into the smallest trial-count rung that
        holds them (rung boundaries only — same rung is a no-op, so sweeps
        compile at most ladder-many step executables total). Dead trials'
        states are dropped; :meth:`unstack` them first if needed."""
        keep = [s for s in range(len(self.slot_ids))
                if self._active_host[s] > 0]
        if not keep:
            raise RuntimeError("compact() with zero live trials — "
                               "the sweep is already finished")
        new_rung = self._bucketer.bucket_for(len(keep))
        if new_rung == self.rung:
            return state
        idx = keep + [keep[0]] * (new_rung - len(keep))

        def build():
            def gather(st, ix):
                return jax.tree.map(lambda x: jnp.take(x, ix, axis=0), st)

            return jax.jit(gather)

        fn = cb.get_compiled_cache().get(
            "fused_compact", (self.rung, new_rung), build,
            instance=cb.instance_token(self))
        core = {k: state[k] for k in ("params", "opt_state", "step",
                                      "hparams")}
        with self.mesh.mesh:
            core = fn(core, jnp.asarray(idx, jnp.int32))
        self.slot_ids = [self.slot_ids[s] for s in keep]
        self._active_host = np.asarray(
            [1.0] * len(keep) + [0.0] * (new_rung - len(keep)), np.float32)
        _HPO_METRICS.get()["compactions"].inc(engine="fused_trainer")
        return dict(core, active=jnp.asarray(self._active_host))

    # ---- results ----
    def unstack(self, state: dict) -> dict[int, Any]:
        """Per-trial :class:`TrainState` views (host-fetched once), keyed by
        ORIGINAL trial index. Early-stopped trials still occupying a slot
        return their frozen state; trials dropped by :meth:`compact` are
        absent."""
        from .trainer import TrainState

        host = jax.device_get({"params": state["params"],
                               "opt_state": state["opt_state"],
                               "step": state["step"]})
        out = {}
        for slot, tid in enumerate(self.slot_ids):
            pick = lambda x, s=slot: x[s]  # noqa: E731
            out[tid] = TrainState(
                params=jax.tree.map(pick, host["params"]),
                opt_state=jax.tree.map(pick, host["opt_state"]),
                step=host["step"][slot])
        return out

    # ---- loop ----
    def fit(self, state: dict, batch_iter: Iterator[dict], max_steps: int,
            *, early_stop: Callable[[int, dict], Iterable[int]] | None = None,
            check_every: int = 25, compact_on_stop: bool = True) -> dict:
        """Drive the fused array over a shared batch stream.

        ``early_stop(step, {trial_id: loss})`` runs every ``check_every``
        steps over the live trials' current losses and returns trial ids to
        stop; stopped trials are masked out immediately and survivors are
        gathered to a smaller rung when they fit one
        (``compact_on_stop``)."""
        m = _HPO_METRICS.get()
        it = iter(batch_iter)
        done = object()
        t_start = time.perf_counter()
        trial_steps = 0
        for i in range(max_steps):
            batch = next(it, done)
            if batch is done:
                break
            t0 = time.perf_counter()
            state, metrics = self.train_step(state, batch)
            m["step_ms"].observe((time.perf_counter() - t0) * 1e3,
                                 engine="fused_trainer")
            m["steps"].inc(engine="fused_trainer")
            trial_steps += self.n_live
            if early_stop is not None and (i + 1) % check_every == 0:
                losses = np.asarray(metrics["loss"])
                live_losses = {tid: float(losses[s])
                               for s, tid in enumerate(self.slot_ids)
                               if self._active_host[s] > 0}
                doomed = list(early_stop(i + 1, live_losses))
                if doomed:
                    state = self.deactivate(state, doomed)
                    if self.n_live == 0:
                        break
                    if compact_on_stop:
                        state = self.compact(state)
        wall = max(time.perf_counter() - t_start, 1e-9)
        m["trials_per_sec"].set(trial_steps / wall, engine="fused_trainer")
        self._metrics.append({"trial_steps": trial_steps, "wall_s": wall,
                              "live": self.n_live})
        return state

    @property
    def metrics(self) -> list[dict]:
        return self._metrics


def fused_fit_source(trainer: FusedTrainer, source, *, batch_size: int,
                     total_steps: int, seed: int, epochs: int | None = None,
                     drop_remainder: bool = True, shuffle_rows: str = "full",
                     shuffle_window: int = 4096, prefetch: int = 2,
                     columns: list | None = None,
                     early_stop=None, check_every: int = 25) -> dict:
    """Fused-array fit over a :class:`data.ShardedSource`: ONE deterministic
    :class:`data.DataLoader` stream (seeded shuffles, bucket-ladder padding,
    background prefetch, device-put once per batch) shared by every trial —
    the same loader configuration ``fit_source`` uses, so a serial run under
    the same seed consumes the identical batch sequence (the parity-suite
    contract)."""
    from ..data import DataLoader

    loader = DataLoader(
        source, batch_size, seed=seed, epochs=epochs,
        drop_remainder=drop_remainder, shuffle_rows=shuffle_rows,
        shuffle_window=shuffle_window,
        multiple_of=trainer.mesh.data_parallel_size(), prefetch=prefetch,
        columns=columns)
    it = iter(loader)
    try:
        first = next(it)
        state = trainer.init_state(first, default_seed=seed)

        def chain():
            yield first
            yield from it

        return trainer.fit(state, chain(), max_steps=total_steps,
                           early_stop=early_stop, check_every=check_every)
    finally:
        loader.close()


def fused_fit_arrays(trainer: FusedTrainer, data: dict, *, batch_size: int,
                     total_steps: int, seed: int, **kwargs) -> dict:
    """In-memory twin of :func:`fused_fit_source` (mirrors
    ``trainer.fit_arrays``: same MemorySource + drop_remainder policy, so
    fused and serial arms see bit-identical batch streams)."""
    from ..data.source import MemorySource

    n = next(iter(data.values())).shape[0]
    kwargs.setdefault("drop_remainder", n >= batch_size)
    return fused_fit_source(trainer, MemorySource(data),
                            batch_size=batch_size, total_steps=total_steps,
                            seed=seed, **kwargs)
