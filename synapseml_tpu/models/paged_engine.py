"""Token-granular paged-KV decode engine for the causal LM path.

The dense ``generate`` path (``models/flax_nets/llama.py``) is
run-to-completion: one ``lax.while_loop`` decodes a whole batch until every
row finishes, so one long generation holds the batch hostage and a finished
row's ``[max_len]`` KV cache stays pinned to the end. This engine is the
vLLM-style alternative the serving plane schedules tokens on:

* **Paged KV pool** — a fixed physical pool of
  ``(n_blocks, block_len, kv_heads, head_dim)`` pages per layer plus a
  per-sequence block table (``models/flax_nets/llama.py`` paged modules).
  Sequences of any length share one pool; a finished sequence's pages free
  the moment it emits EOS or exhausts ``max_new_tokens``. Block 0 is the
  reserved trash page — never allocated, absorbing masked writes — so live
  pages can never alias.
* **Prefill/decode split** — a jitted prefill program per bucketed prompt
  length (``ShapeBucketer.seq_bucket_for``) and a jitted single-step decode
  program per bucketed active-slot count (``bucket_for``). Both are
  acquired ONLY through the shared :class:`~..core.batching.CompiledCache`
  (enforced statically in ``tests/test_codegen.py``), so a variable request
  stream compiles at most ladder-many executables each, all warmable.
* **Continuous batching** — :meth:`admit` prefills waiting sequences into
  free slots between decode steps and :meth:`step` decodes one token for
  every active slot; the scheduler in ``io/serving.py`` drives the loop.
  When the pool runs dry mid-decode the youngest sequence is preempted
  (pages freed, re-queued for re-prefill over prompt+generated — greedy
  decode makes the recomputation token-identical).

Greedy paged decode is token-for-token identical to ``greedy_generate``
(parity-tested across prompt buckets in ``tests/test_paged_llm.py``); both
paths read the same param pytree.
"""

from __future__ import annotations

import hashlib
import io
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Sequence

import numpy as np

from ..core import batching as cb
from ..core import observability as obs
from ..core import serialization

__all__ = ["BlockAllocator", "PagedDecodeEngine", "SequenceState"]


_ENGINE_METRICS = obs.HandleCache(lambda reg: {
    "step_ms": reg.histogram(
        "synapseml_llm_step_ms",
        "wall time of one engine step, split prefill vs decode", ("phase",)),
    "token_ms": reg.histogram(
        "synapseml_llm_token_latency_ms",
        "decode wall time per emitted token (step time / tokens emitted)"),
    "ttft_ms": reg.histogram(
        "synapseml_llm_ttft_ms",
        "submit -> first generated token (queue wait + prefill)"),
    "tokens": reg.counter(
        "synapseml_llm_tokens_total",
        "generated tokens by phase (prefill = first token)", ("phase",)),
    "occupancy": reg.gauge(
        "synapseml_llm_kv_block_occupancy",
        "fraction of the physical KV block pool allocated to live sequences"),
    "fragmentation": reg.gauge(
        "synapseml_llm_kv_fragmentation",
        "unused token slots inside allocated blocks / allocated capacity "
        "(tail waste of the page granularity)"),
    "refilled": reg.counter(
        "synapseml_llm_slots_refilled_total",
        "decode slots handed to a waiting sequence after a finish freed "
        "capacity (the no-run-to-completion-barrier counter)"),
    "preempted": reg.counter(
        "synapseml_llm_slots_preempted_total",
        "sequences evicted mid-decode because the block pool ran dry "
        "(re-queued for re-prefill)"),
    "finished": reg.counter(
        "synapseml_llm_sequences_finished_total",
        "sequences completed, by finish reason", ("reason",)),
    "spec_proposed": reg.counter(
        "synapseml_llm_spec_tokens_proposed_total",
        "draft tokens proposed to the speculative verify step"),
    "spec_accepted": reg.counter(
        "synapseml_llm_spec_tokens_accepted_total",
        "draft tokens the full model confirmed (greedy match)"),
    "spec_steps": reg.counter(
        "synapseml_llm_spec_steps_total",
        "engine steps by decode mode: 'spec' = fused draft+verify, "
        "'fallback' = plain single-token (pool too tight for the window)",
        ("mode",)),
    "spec_accept_rate": reg.gauge(
        "synapseml_llm_spec_acceptance_rate",
        "cumulative accepted / proposed draft tokens"),
})


def _npz_safe(arr: np.ndarray) -> np.ndarray:
    """npz-writable view of one KV chunk: numpy's format cannot serialize
    extension dtypes (bf16), so those ride as raw uint8 bytes and the
    manifest's recorded dtype restores them on import."""
    arr = np.ascontiguousarray(arr)
    if np.dtype(arr.dtype).isbuiltin == 1:  # 2 = extension dtype (bf16)
        return arr
    return np.frombuffer(arr.tobytes(), np.uint8)


class BlockAllocator:
    """Free-list allocator over the physical page pool. Block 0 is the
    reserved trash page and is never handed out; double-free and
    allocate-while-live are hard errors (the no-aliasing invariant the
    property test leans on).

    Blocks are REFERENCE-COUNTED for prefix-KV sharing: ``alloc`` hands out
    blocks at refcount 1, :meth:`ref` lets another holder (the prefix
    cache, a prefix-hit sequence) pin an already-live block, and ``free``
    drops ONE reference per call — the block returns to the free list only
    when the last holder lets go. Freeing a non-live block (refcount
    already zero) is still the same hard error, so a double free cannot
    hide behind sharing."""

    def __init__(self, n_blocks: int):
        if n_blocks < 2:
            raise ValueError(f"need >= 2 blocks (1 is the trash page), "
                             f"got {n_blocks}")
        self.n_blocks = int(n_blocks)
        self._free: list[int] = list(range(self.n_blocks - 1, 0, -1))
        self._live: set[int] = set()
        self._refs: dict[int, int] = {}

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return len(self._live)

    @property
    def capacity(self) -> int:
        """Allocatable blocks (pool minus the trash page)."""
        return self.n_blocks - 1

    def alloc(self, n: int) -> list[int] | None:
        """``n`` blocks or None (never a partial allocation)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        self._live.update(out)
        for b in out:
            self._refs[b] = 1
        return out

    def ref(self, block: int) -> None:
        """Add one reference to an already-live block (prefix sharing).
        Referencing a non-live block is a hard error — it would resurrect
        freed pages and alias whoever allocates them next."""
        if block not in self._live:
            raise RuntimeError(
                f"ref on block {block} that is not live (use-after-free "
                f"or trash-page share — an aliasing bug)")
        self._refs[block] += 1

    def refcount(self, block: int) -> int:
        return self._refs.get(block, 0)

    def free(self, blocks: Iterable[int]) -> None:
        for b in blocks:
            if b not in self._live:
                raise RuntimeError(
                    f"freeing block {b} that is not live (double free or "
                    f"trash-page free — an aliasing bug)")
            self._refs[b] -= 1
            if self._refs[b] == 0:
                del self._refs[b]
                self._live.remove(b)
                self._free.append(b)


@dataclass
class SequenceState:
    """One request's decode state (host side; device state is the pages)."""

    uid: int
    prompt_ids: list
    max_new_tokens: int
    request_id: str | None = None
    stream: bool = False
    generated: list = field(default_factory=list)
    blocks: list = field(default_factory=list)
    tokens_in_pages: int = 0       # prompt + generated tokens written to pages
    preemptions: int = 0
    submitted_at: float = field(default_factory=time.perf_counter)
    first_token_at: float | None = None
    finish_reason: str | None = None
    deadline: float | None = None  # perf_counter instant; past it the
    #                                engine frees the pages and finishes
    #                                with reason='deadline'
    journal_key: str | None = None  # the RoutingFront's idempotency key —
    #                                 rides exports so a drained worker's
    #                                 handoff can find the front's journal
    #                                 entry (worker request_ids are local)
    registered_blocks: int = 0     # full blocks already chain-hashed into
    prefix_digest: bytes = b""     # the prefix cache, + the chain digest
    #                                at that boundary (incremental hashing)

    @property
    def context_ids(self) -> list:
        """Tokens a (re-)prefill must process: prompt + generated so far."""
        return list(self.prompt_ids) + list(self.generated)

    @property
    def done(self) -> bool:
        return self.finish_reason is not None


class PagedDecodeEngine:
    """Continuous-batching decode engine over a paged KV pool.

    ``submit`` -> waiting queue; ``admit`` prefills waiting sequences into
    capacity (bucketed prompt lengths, fixed prefill batch width);
    ``step`` decodes ONE token for every active sequence (bucketed slot
    count). Both return event dicts
    ``{"seq", "token", "text"?, "done", "finish_reason"}`` the serving
    scheduler turns into streamed chunks / terminal replies.

    Sampling config (``temperature``/``top_k``/``top_p``/``seed``) is fixed
    per engine — it is baked into the compiled programs' cache key; greedy
    (the default) is what the parity guarantee covers. ``eos_id`` and
    per-sequence ``max_new_tokens`` are host-side and never recompile.
    """

    def __init__(self, cfg, params, *, block_len: int = 16,
                 n_blocks: int | None = None, max_slots: int = 8,
                 max_len: int | None = None, prefill_batch: int = 4,
                 temperature: float = 0.0, top_k: int | None = None,
                 top_p: float | None = None, seed: int = 0,
                 eos_id: int | None = None, bucketer=None,
                 instance: Any = None, fn_prefix: str = "llama_paged",
                 donate_pages: bool = True, prefix_cache: bool = False,
                 draft_tokens: int = 0, draft_layers: int | None = None,
                 drafter: tuple | None = None):
        import jax.numpy as jnp

        self.cfg = cfg
        self.params = params
        self.block_len = int(block_len)
        self.max_len = int(max_len or cfg.max_len)
        if self.max_len > cfg.max_len:
            raise ValueError(f"max_len {self.max_len} exceeds the model's "
                             f"RoPE/cache horizon {cfg.max_len}")
        self.max_blocks = -(-self.max_len // self.block_len)
        self.max_slots = int(max_slots)
        self.prefill_batch = int(prefill_batch)
        if n_blocks is None:
            # default: every slot can run to max_len concurrently + trash
            n_blocks = 1 + self.max_slots * self.max_blocks
        self.allocator = BlockAllocator(n_blocks)
        self.eos_id = eos_id
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.seed = int(seed)
        self.bucketer = bucketer or cb.default_bucketer()
        self._fn_prefix = fn_prefix
        self._instance = instance if instance is not None \
            else cb.instance_token(self)
        # decode slot rungs: ladder rungs <= max_slots, plus max_slots itself
        rungs = [r for r in self.bucketer.ladder if r <= self.max_slots]
        if not rungs or rungs[-1] < self.max_slots:
            rungs.append(self.max_slots)
        self.slot_rungs: tuple[int, ...] = tuple(rungs)
        # physical pool: one [n_blocks, bl, KV, D] leaf per layer (a tuple,
        # so each layer's page writes update one leaf in place — see
        # PagedEncoder)
        shape = (n_blocks, self.block_len, cfg.kv_heads, cfg.head_dim)
        self._k_pages = tuple(jnp.zeros(shape, cfg.dtype)
                              for _ in range(cfg.n_layers))
        self._v_pages = tuple(jnp.zeros(shape, cfg.dtype)
                              for _ in range(cfg.n_layers))
        # page pools are DONATED into every prefill/decode call (each call
        # returns the updated pools and the engine rebinds them), so a step
        # updates pages in place instead of copying the whole pool — on the
        # CPU backend this is the difference between winning and losing the
        # continuous-vs-RTC A/B
        self._donate = bool(donate_pages)
        # --- prefix KV cache (OFF by default: zero behavior change) ------
        self._prefix_cache = None
        if prefix_cache:
            from .prefix_cache import PrefixCache
            self._prefix_cache = PrefixCache(self.allocator, self.block_len)
        # --- greedy speculative decoding (OFF by default) ----------------
        self.draft_tokens = int(draft_tokens)
        if self.draft_tokens < 0:
            raise ValueError(f"draft_tokens={draft_tokens}")
        if self.draft_tokens > 0 and temperature is not None \
                and temperature > 0.0:
            raise ValueError(
                "speculative decoding is greedy-only (the acceptance rule "
                "compares argmaxes); temperature > 0 would break the "
                "token-identity guarantee — set draft_tokens=0 to sample")
        self.draft_layers = None
        self._drafter = None
        self._draft_params = None
        if self.draft_tokens > 0:
            if drafter is not None:
                # a registry-resolved small model drafts over a dense
                # LEFT-ALIGNED context window (no second page pool; window
                # truncation only affects draft quality, never correctness
                # — the full model's verify is the ground truth)
                d_cfg = drafter[0]
                if d_cfg.max_len < self.max_len:
                    raise ValueError(
                        f"drafter max_len={d_cfg.max_len} cannot position-"
                        f"encode the engine horizon max_len={self.max_len}")
                self._drafter = (d_cfg, drafter[1])
                self._draft_params = drafter[1]
                self._draft_window = self.bucketer.seq_bucket_for(
                    min(64, self.max_len), cap=self.max_len)
            else:
                # self-draft: early-exit at draft_layers over the SAME
                # params and pool leaves (layers < E)
                from .flax_nets.llama import early_exit_params
                E = draft_layers if draft_layers is not None \
                    else max(1, cfg.n_layers // 2)
                if not 1 <= E <= cfg.n_layers:
                    raise ValueError(
                        f"draft_layers={E} outside [1, {cfg.n_layers}]")
                self.draft_layers = int(E)
                self._draft_params = early_exit_params(params, self.draft_layers)
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_steps = 0
        self._spec_fallbacks = 0
        self._lock = threading.RLock()
        self._waiting: deque[SequenceState] = deque()
        self._active: list[SequenceState] = []
        self._uid = 0
        self._freed_since_admit = 0  # finish/preempt -> refill accounting
        self._released = False
        self._progress_ticks = 0  # engine-WIDE: any token emitted or
        #                           sequence finished, by any caller

    # ------------------------------------------------------------------
    # compiled programs (CompiledCache is the only jit door)
    # ------------------------------------------------------------------
    def _cfg_key(self) -> tuple:
        return (self.temperature, self.top_k, self.top_p, self.block_len)

    def _selector(self):
        """Per-row selector [S,V] logits + [S] uid + [S] step -> [S] ids —
        the dense `_make_selector` vmapped over per-sequence fold_in keys so
        each request's sample stream is a pure function of (seed, uid)."""
        import jax

        from .flax_nets.llama import _make_selector

        base_select = _make_selector(self.temperature, self.top_k, self.top_p)
        base_key = jax.random.PRNGKey(self.seed)

        def select(logits, uids, steps):
            def one(row, uid, step):
                key = jax.random.fold_in(jax.random.fold_in(base_key, uid),
                                         step)
                return base_select(row[None], key)[0]
            return jax.vmap(one)(logits, uids, steps)

        return select

    def _prefill_fn(self, B: int, P: int) -> Callable:
        def _build():
            import jax

            from .flax_nets.llama import paged_prefill

            cfg, bl = self.cfg, self.block_len
            select = self._selector()

            def fn(params, ids, mask, tables, kp, vp, uids, steps):
                logits, kp, vp = paged_prefill(cfg, bl, params, ids, mask,
                                               tables, kp, vp)
                return select(logits, uids, steps), kp, vp

            donate = (4, 5) if self._donate else ()
            return jax.jit(fn, donate_argnums=donate)

        return cb.get_compiled_cache().get(
            f"{self._fn_prefix}_prefill",
            (B, P, self.max_blocks) + self._cfg_key(), _build,
            instance=self._instance, dtype="int32")

    def _decode_fn(self, S: int) -> Callable:
        def _build():
            import jax

            from .flax_nets.llama import paged_decode_step

            cfg, bl = self.cfg, self.block_len
            select = self._selector()

            def fn(params, tokens, seq_lens, active, tables, kp, vp, uids,
                   steps):
                logits, kp, vp = paged_decode_step(cfg, bl, params, tokens,
                                                   seq_lens, active, tables,
                                                   kp, vp)
                return select(logits, uids, steps), kp, vp

            donate = (5, 6) if self._donate else ()
            return jax.jit(fn, donate_argnums=donate)

        return cb.get_compiled_cache().get(
            f"{self._fn_prefix}_decode",
            (S, self.max_blocks) + self._cfg_key(), _build,
            instance=self._instance, dtype="int32")

    def _extend_fn(self, B: int, Q: int) -> Callable:
        """Suffix prefill over a cached prefix: COW-copies each row's
        divergence block (``cow_dst`` < 0 = no copy; the trash page absorbs
        the no-op write), then prefills only the UNCACHED suffix with
        decode-mode attention over the pooled prefix KV."""
        def _build():
            import jax
            import jax.numpy as jnp

            from .flax_nets.llama import paged_extend

            cfg, bl = self.cfg, self.block_len
            select = self._selector()

            def fn(params, ids, mask, start_pos, tables, cow_src, cow_dst,
                   kp, vp, uids, steps):
                src = jnp.maximum(cow_src, 0)
                dst = jnp.maximum(cow_dst, 0)
                do = (cow_dst >= 0)[:, None, None, None]

                def cow(pages):
                    return pages.at[dst].set(
                        jnp.where(do, pages[src], pages[dst]))

                kp = tuple(cow(p) for p in kp)
                vp = tuple(cow(p) for p in vp)
                logits, kp, vp = paged_extend(cfg, bl, params, ids, mask,
                                              start_pos, tables, kp, vp)
                return select(logits, uids, steps), kp, vp

            donate = (7, 8) if self._donate else ()
            return jax.jit(fn, donate_argnums=donate)

        return cb.get_compiled_cache().get(
            f"{self._fn_prefix}_extend",
            (B, Q, self.max_blocks) + self._cfg_key(), _build,
            instance=self._instance, dtype="int32")

    def _spec_fn(self, S: int) -> Callable:
        """Fused greedy draft + verify: K single-token draft steps (early
        exit over the shared pool leaves, or a dense windowed drafter) then
        ONE K+1-token verify forward of the full model. Returns
        (pred [S,K+1], n_accepted [S], pools); the emitted tokens are
        ``pred[:, :n_accepted+1]`` — token-identical to plain greedy decode
        because a draft survives only where the full model's argmax agrees
        and the first disagreement emits the full model's own token."""
        K = self.draft_tokens

        def _build():
            import dataclasses

            import jax
            import jax.numpy as jnp

            from .flax_nets.llama import (LlamaLM, paged_decode_step,
                                          paged_verify)

            cfg, bl = self.cfg, self.block_len

            def _accept(window, logits):
                pred = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                match = (pred[:, :K] == window[:, 1:]).astype(jnp.int32)
                n_acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
                return pred, n_acc

            if self._drafter is not None:
                d_cfg, _ = self._drafter
                W = self._draft_window
                d_model = LlamaLM(d_cfg)

                def fn(params, d_params, last_tok, seq_lens, active, tables,
                       win, pos, L0, kp, vp):
                    S_ = last_tok.shape[0]
                    wm = (jnp.arange(W)[None, :]
                          < L0[:, None]).astype(jnp.int32)
                    drafts = []
                    for j in range(K):
                        logits = d_model.apply({"params": d_params}, win,
                                               positions=pos,
                                               attention_mask=wm)
                        idx = jnp.maximum(L0 + j - 1, 0)
                        last = jnp.take_along_axis(
                            logits, idx[:, None, None], axis=1)[:, 0]
                        d = jnp.argmax(last, axis=-1).astype(jnp.int32)
                        drafts.append(d)
                        rows = jnp.arange(S_)
                        win = win.at[rows, L0 + j].set(d)
                        wm = wm.at[rows, L0 + j].set(1)
                    window = jnp.stack([last_tok] + drafts, axis=1)
                    logits, kp, vp = paged_verify(cfg, bl, params, window,
                                                  seq_lens, active, tables,
                                                  kp, vp)
                    pred, n_acc = _accept(window, logits)
                    return pred, n_acc, kp, vp

                donate = (9, 10) if self._donate else ()
                return jax.jit(fn, donate_argnums=donate)

            E = self.draft_layers
            d_cfg = dataclasses.replace(cfg, n_layers=E)

            def fn(params, d_params, last_tok, seq_lens, active, tables,
                   kp, vp):
                kpE, vpE = kp[:E], vp[:E]
                drafts = []
                d = last_tok
                for j in range(K):
                    logits, kpE, vpE = paged_decode_step(
                        d_cfg, bl, d_params, d, seq_lens + j, active,
                        tables, kpE, vpE)
                    d = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    drafts.append(d)
                kp = kpE + kp[E:]
                vp = vpE + vp[E:]
                window = jnp.stack([last_tok] + drafts, axis=1)
                logits, kp, vp = paged_verify(cfg, bl, params, window,
                                              seq_lens, active, tables,
                                              kp, vp)
                pred, n_acc = _accept(window, logits)
                return pred, n_acc, kp, vp

            donate = (6, 7) if self._donate else ()
            return jax.jit(fn, donate_argnums=donate)

        mode = ("ext", self._draft_window) if self._drafter is not None \
            else ("self", self.draft_layers)
        return cb.get_compiled_cache().get(
            f"{self._fn_prefix}_spec",
            (S, self.max_blocks, K) + mode + self._cfg_key(), _build,
            instance=self._instance, dtype="int32")

    # ------------------------------------------------------------------
    # scheduling surface
    # ------------------------------------------------------------------
    def submit(self, prompt_ids: Sequence[int], max_new_tokens: int,
               request_id: str | None = None, stream: bool = False,
               uid: int | None = None, deadline: float | None = None,
               journal_key: str | None = None) -> SequenceState:
        """Queue a tokenized prompt. ``uid`` seeds the sequence's sampling
        key stream (auto-assigned when None); offline ``transform()`` passes
        the global row offset so sampled generation is a deterministic
        function of (seed, row), not of submission order. ``deadline`` is a
        ``time.perf_counter()`` instant past which the sequence expires with
        ``finish_reason='deadline'`` instead of holding pages for a client
        that stopped waiting."""
        prompt_ids = [int(t) for t in prompt_ids]
        if not prompt_ids:
            raise ValueError("empty prompt")
        if len(prompt_ids) >= self.max_len:
            raise ValueError(f"prompt ({len(prompt_ids)} tokens) must leave "
                             f"room to generate under max_len={self.max_len}")
        # the engine horizon caps generation; the cap is reported as
        # finish_reason='length' rather than rejecting the request
        max_new = max(1, min(int(max_new_tokens),
                             self.max_len - len(prompt_ids)))
        with self._lock:
            if uid is None:
                self._uid += 1
                uid = self._uid
            seq = SequenceState(uid=int(uid), prompt_ids=prompt_ids,
                                max_new_tokens=max_new,
                                request_id=request_id, stream=stream,
                                deadline=deadline, journal_key=journal_key)
            self._waiting.append(seq)
        return seq

    @property
    def prefix_cache(self):
        """The engine's :class:`~.prefix_cache.PrefixCache`, or None when
        prefix caching is off."""
        return self._prefix_cache

    @property
    def active_count(self) -> int:
        return len(self._active)

    @property
    def waiting_count(self) -> int:
        return len(self._waiting)

    def has_work(self) -> bool:
        return bool(self._active or self._waiting)

    def _blocks_for(self, n_tokens: int) -> int:
        return -(-max(n_tokens, 1) // self.block_len)

    def _reclaim(self, n: int) -> None:
        """Make room for ``n`` blocks by evicting cold prefix-cache entries
        — cached pages are strictly cheaper to give up than preempting (and
        recomputing) a live sequence, so every alloc path tries this
        first."""
        if self._prefix_cache is not None and self.allocator.free_count < n:
            self._prefix_cache.evict(n - self.allocator.free_count)

    def _register_blocks(self, seq: SequenceState) -> None:
        """Chain-hash every newly FILLED block of committed tokens into the
        prefix cache (incremental: picks up from the sequence's recorded
        digest). Full blocks are immutable from here on — writes only ever
        target positions >= ``tokens_in_pages`` — so cached pages stay
        byte-stable while shared."""
        pc = self._prefix_cache
        if pc is None:
            return
        bl = self.block_len
        n_full = min(seq.tokens_in_pages // bl, len(seq.blocks))
        if n_full <= seq.registered_blocks:
            return
        ctx = seq.context_ids
        h = seq.prefix_digest
        for i in range(seq.registered_blocks, n_full):
            h = pc.insert(h, ctx[i * bl:(i + 1) * bl], seq.blocks[i])
        seq.registered_blocks = n_full
        seq.prefix_digest = h

    def _update_pool_gauges(self) -> None:
        m = _ENGINE_METRICS.get()
        cap = self.allocator.capacity
        used = self.allocator.used_count
        m["occupancy"].labels().set(used / cap if cap else 0.0)
        live_tokens = sum(s.tokens_in_pages for s in self._active)
        alloc_tokens = used * self.block_len
        m["fragmentation"].labels().set(
            (alloc_tokens - live_tokens) / alloc_tokens if alloc_tokens
            else 0.0)

    def _finish(self, seq: SequenceState, reason: str) -> None:
        self._progress_ticks += 1
        seq.finish_reason = reason
        if seq.blocks:
            self.allocator.free(seq.blocks)
            seq.blocks = []
        if seq in self._active:
            self._active.remove(seq)
            self._freed_since_admit += 1
        _ENGINE_METRICS.get()["finished"].inc(reason=reason)
        self._update_pool_gauges()

    def _emit(self, seq: SequenceState, token: int) -> dict:
        self._progress_ticks += 1
        now = time.perf_counter()
        m = _ENGINE_METRICS.get()
        if seq.first_token_at is None:
            seq.first_token_at = now
            m["ttft_ms"].labels().observe((now - seq.submitted_at) * 1e3)
        done = False
        if self.eos_id is not None and token == self.eos_id:
            done, reason = True, "eos"
        elif len(seq.generated) >= seq.max_new_tokens:
            done, reason = True, "length"
        if done:
            self._finish(seq, reason)
        # "index" is the token's 0-based position in the generation —
        # every _emit call sits right after its generated.append, so this
        # is exact even when one speculative step emits several tokens
        # (consumers reading len(generated) AFTER the step would see only
        # the window's final length)
        return {"seq": seq, "token": int(token), "done": done,
                "index": len(seq.generated) - 1,
                "finish_reason": seq.finish_reason}

    def admit(self) -> list[dict]:
        """Prefill waiting sequences into free capacity. Batches up to
        ``prefill_batch`` sequences per program call, prompts padded to one
        seq-ladder bucket — compile count stays <= len(seq ladder).

        With the prefix cache on, each candidate first looks up its longest
        cached full-block chain: shared blocks are referenced (never
        written), only fresh blocks are allocated, and the sequence rides
        the EXTEND program — prefill over just the uncached suffix,
        attending to the resident prefix KV through the block table. A
        fully-cached prompt COWs its divergence block so the mandatory
        last-token recompute writes a private copy."""
        import jax.numpy as jnp

        events: list[dict] = self.expire_deadlines()
        with self._lock:
            while self._waiting and len(self._active) < self.max_slots:
                # (seq, reuse_tokens, cow_src) triples
                group: list[tuple[SequenceState, int, int]] = []
                while (self._waiting and len(group) < self.prefill_batch
                       and len(self._active) + len(group) < self.max_slots):
                    seq = self._waiting[0]
                    ctx = seq.context_ids
                    need = self._blocks_for(len(ctx))
                    if need > self.allocator.capacity:
                        # no amount of freeing can ever satisfy this
                        # sequence — terminate it instead of wedging the
                        # FIFO head forever
                        self._waiting.popleft()
                        self._finish(seq, "kv_capacity")
                        events.append({"seq": seq, "token": None,
                                       "done": True,
                                       "finish_reason": "kv_capacity"})
                        continue
                    shared: list[int] = []
                    digests: list[bytes] = []
                    reuse, cow_src = 0, -1
                    if self._prefix_cache is not None:
                        cblocks, digests = self._prefix_cache.lookup(ctx)
                        # whole blocks only, and ALWAYS leave >= 1 token of
                        # suffix to prefill (the last-position logits seed
                        # the first generated token)
                        reuse = min(len(cblocks) * self.block_len,
                                    len(ctx) - 1)
                        n_shared = reuse // self.block_len
                        if reuse % self.block_len:
                            # fully-cached prompt: the divergence block is
                            # shared, so the suffix write gets a COW copy
                            cow_src = cblocks[n_shared]
                        shared = cblocks[:n_shared]
                        # pin BEFORE any eviction can run: _reclaim (ours,
                        # or a later group member's) frees refcount-1
                        # cache entries, so without the extra ref it could
                        # evict these very blocks and alloc() would hand
                        # them back as fresh suffix pages — the "shared
                        # prefix" silently aliasing its own suffix writes.
                        # cow_src is pinned too: the extend program reads
                        # it for the divergence-block copy AFTER every
                        # group member has run its own reclaim (unpinned
                        # once the program has executed).
                        for b in shared:
                            self.allocator.ref(b)
                        if cow_src >= 0:
                            self.allocator.ref(cow_src)
                    need_new = need - len(shared)
                    self._reclaim(need_new)
                    got = self.allocator.alloc(need_new)
                    if got is None:
                        for b in shared:  # unpin: the seq stays waiting
                            self.allocator.free([b])
                        if cow_src >= 0:
                            self.allocator.free([cow_src])
                        break  # pool dry: decode must free pages first
                    self._waiting.popleft()
                    seq.blocks = shared + got
                    seq.registered_blocks = len(shared)
                    seq.prefix_digest = digests[len(shared) - 1] \
                        if shared else b""
                    if reuse and self._prefix_cache is not None:
                        self._prefix_cache.note_reused(reuse)
                    group.append((seq, reuse, cow_src))
                if not group:
                    break
                plain = [g for g in group if g[1] == 0]
                hits = [g for g in group if g[1] > 0]
                B = self.prefill_batch
                m = _ENGINE_METRICS.get()
                admitted: list[SequenceState] = []
                if plain:
                    t0 = time.perf_counter()
                    P = self.bucketer.seq_bucket_for(
                        max(len(s.context_ids) for s, _, _ in plain),
                        cap=self.max_len)
                    ids = np.zeros((B, P), np.int32)
                    mask = np.zeros((B, P), np.int32)
                    tables = np.zeros((B, self.max_blocks), np.int32)
                    uids = np.zeros((B,), np.int32)
                    steps = np.zeros((B,), np.int32)
                    for i, (seq, _, _) in enumerate(plain):
                        ctx = seq.context_ids
                        ids[i, :len(ctx)] = ctx
                        mask[i, :len(ctx)] = 1
                        tables[i, :len(seq.blocks)] = seq.blocks
                        uids[i] = seq.uid
                        steps[i] = len(seq.generated)
                    fn = self._prefill_fn(B, P)
                    next_tok, self._k_pages, self._v_pages = fn(
                        self.params, jnp.asarray(ids), jnp.asarray(mask),
                        jnp.asarray(tables), self._k_pages, self._v_pages,
                        jnp.asarray(uids), jnp.asarray(steps))
                    next_tok = np.asarray(next_tok)
                    m["step_ms"].observe((time.perf_counter() - t0) * 1e3,
                                         phase="prefill")
                    for i, (seq, _, _) in enumerate(plain):
                        seq._admit_token = int(next_tok[i])
                        admitted.append(seq)
                if hits:
                    t0 = time.perf_counter()
                    Q = self.bucketer.seq_bucket_for(
                        max(len(s.context_ids) - r for s, r, _ in hits),
                        cap=self.max_len)
                    ids = np.zeros((B, Q), np.int32)
                    mask = np.zeros((B, Q), np.int32)
                    start = np.zeros((B,), np.int32)
                    tables = np.zeros((B, self.max_blocks), np.int32)
                    cow_src = np.full((B,), -1, np.int32)
                    cow_dst = np.full((B,), -1, np.int32)
                    uids = np.zeros((B,), np.int32)
                    steps = np.zeros((B,), np.int32)
                    for i, (seq, r, cs) in enumerate(hits):
                        suffix = seq.context_ids[r:]
                        ids[i, :len(suffix)] = suffix
                        mask[i, :len(suffix)] = 1
                        start[i] = r
                        tables[i, :len(seq.blocks)] = seq.blocks
                        if cs >= 0:
                            cow_src[i] = cs
                            cow_dst[i] = seq.blocks[r // self.block_len]
                        uids[i] = seq.uid
                        steps[i] = len(seq.generated)
                    fn = self._extend_fn(B, Q)
                    next_tok, self._k_pages, self._v_pages = fn(
                        self.params, jnp.asarray(ids), jnp.asarray(mask),
                        jnp.asarray(start), jnp.asarray(tables),
                        jnp.asarray(cow_src), jnp.asarray(cow_dst),
                        self._k_pages, self._v_pages,
                        jnp.asarray(uids), jnp.asarray(steps))
                    next_tok = np.asarray(next_tok)
                    m["step_ms"].observe((time.perf_counter() - t0) * 1e3,
                                         phase="prefill")
                    for i, (seq, _, cs) in enumerate(hits):
                        if cs >= 0:
                            # the divergence-block copy has executed;
                            # release the lookup-time pin on its source
                            self.allocator.free([cs])
                        seq._admit_token = int(next_tok[i])
                        admitted.append(seq)
                m["tokens"].inc(len(admitted), phase="prefill")
                for seq in admitted:
                    tok = seq._admit_token
                    del seq._admit_token
                    seq.tokens_in_pages = len(seq.context_ids)
                    seq.generated.append(tok)
                    self._active.append(seq)
                    if self._freed_since_admit > 0:
                        self._freed_since_admit -= 1
                        m["refilled"].inc()
                    events.append(self._emit(seq, tok))
                    if not seq.done:
                        self._register_blocks(seq)
                self._update_pool_gauges()
        return events

    def _preempt_youngest(self, keep: SequenceState) -> bool:
        """Free the most recently admitted active sequence (other than
        ``keep``) back to the waiting queue; its next prefill recomputes
        prompt+generated (token-identical under greedy)."""
        for victim in reversed(self._active):
            if victim is keep:
                continue
            self._active.remove(victim)
            self.allocator.free(victim.blocks)
            victim.blocks = []
            victim.tokens_in_pages = 0
            victim.registered_blocks = 0
            victim.prefix_digest = b""
            victim.preemptions += 1
            self._waiting.appendleft(victim)
            self._freed_since_admit += 1
            _ENGINE_METRICS.get()["preempted"].inc()
            return True
        return False

    def _try_spec_step(self, events: list[dict]) -> bool:
        """Attempt one fused draft+verify step for every active sequence
        (caller holds the lock). Returns False — telling :meth:`step` to run
        the plain single-token program — when any sequence's K+1-token
        window would cross ``max_len`` or the pool cannot cover the window
        even after prefix-cache eviction; preempting a neighbor just to
        speculate is never worth it."""
        import jax.numpy as jnp

        K = self.draft_tokens
        batch = [s for s in self._active if not s.done]
        if not batch:
            return True
        # every window write position n..n+K must fit the engine horizon
        if any(s.tokens_in_pages + K >= self.max_len for s in batch):
            return False
        # grow tables to cover the whole window (cache eviction only — no
        # preemption on the speculative path)
        for seq in batch:
            need = (seq.tokens_in_pages + K) // self.block_len + 1
            grow = need - len(seq.blocks)
            if grow <= 0:
                continue
            self._reclaim(grow)
            got = self.allocator.alloc(grow)
            if got is None:
                return False
            seq.blocks.extend(got)
        t0 = time.perf_counter()
        S_active = len(batch)
        S = next(r for r in self.slot_rungs if r >= S_active)
        last_tok = np.zeros((S,), np.int32)
        seq_lens = np.zeros((S,), np.int32)
        active = np.zeros((S,), bool)
        tables = np.zeros((S, self.max_blocks), np.int32)
        for i, seq in enumerate(batch):
            last_tok[i] = seq.generated[-1]
            seq_lens[i] = seq.tokens_in_pages
            active[i] = True
            tables[i, :len(seq.blocks)] = seq.blocks
        fn = self._spec_fn(S)
        if self._drafter is not None:
            W = self._draft_window
            win = np.zeros((S, W), np.int32)
            pos = np.zeros((S, W), np.int32)
            L0 = np.zeros((S,), np.int32)
            for i, seq in enumerate(batch):
                ctx = seq.context_ids
                L = min(len(ctx), W - K)
                win[i, :L] = ctx[-L:]
                pos[i, :] = (len(ctx) - L) + np.arange(W)
                L0[i] = L
            pred, n_acc, self._k_pages, self._v_pages = fn(
                self.params, self._draft_params, jnp.asarray(last_tok),
                jnp.asarray(seq_lens), jnp.asarray(active),
                jnp.asarray(tables), jnp.asarray(win), jnp.asarray(pos),
                jnp.asarray(L0), self._k_pages, self._v_pages)
        else:
            pred, n_acc, self._k_pages, self._v_pages = fn(
                self.params, self._draft_params, jnp.asarray(last_tok),
                jnp.asarray(seq_lens), jnp.asarray(active),
                jnp.asarray(tables), self._k_pages, self._v_pages)
        pred = np.asarray(pred)
        n_acc = np.asarray(n_acc)
        m = _ENGINE_METRICS.get()
        dt_ms = (time.perf_counter() - t0) * 1e3
        m["step_ms"].observe(dt_ms, phase="decode")
        emitted = 0
        for i, seq in enumerate(batch):
            a = int(n_acc[i])
            self._spec_proposed += K
            self._spec_accepted += a
            for t in range(a + 1):
                tok = int(pred[i, t])
                seq.tokens_in_pages += 1
                seq.generated.append(tok)
                emitted += 1
                ev = self._emit(seq, tok)
                events.append(ev)
                if ev["done"]:
                    break  # EOS/length inside the window: the tail tokens
                    #        would not exist under plain decode either
            if not seq.done:
                self._register_blocks(seq)
        self._spec_steps += 1
        m["spec_steps"].inc(mode="spec")
        m["spec_proposed"].inc(K * S_active)
        m["spec_accepted"].inc(int(n_acc[:S_active].sum()))
        if self._spec_proposed:
            m["spec_accept_rate"].labels().set(
                self._spec_accepted / self._spec_proposed)
        m["token_ms"].labels().observe(dt_ms / max(emitted, 1))
        m["tokens"].inc(emitted, phase="decode")
        self._update_pool_gauges()
        return True

    def step(self) -> list[dict]:
        """One decode step for every active sequence (bucketed slot count);
        returns per-sequence token events. Finished sequences free their
        pages immediately — the next :meth:`admit` refills the capacity.
        With ``draft_tokens`` > 0 the step runs the fused draft+verify
        program instead (up to ``draft_tokens``+1 tokens per sequence per
        step), falling back to the plain single-token program whenever the
        pool or the ``max_len`` horizon cannot take a full window."""
        import jax.numpy as jnp

        events: list[dict] = self.expire_deadlines()
        with self._lock:
            if not self._active:
                return events
            if self.draft_tokens > 0 and self._try_spec_step(events):
                return events
            # grow block tables where the next token crosses a page boundary
            for seq in list(self._active):
                if seq.done or seq not in self._active:
                    continue  # preempted/finished by an earlier iteration
                pos = seq.tokens_in_pages
                if pos // self.block_len >= len(seq.blocks):
                    self._reclaim(1)
                    grown = self.allocator.alloc(1)
                    while grown is None:
                        if not self._preempt_youngest(keep=seq):
                            # lone sequence exhausted the whole pool
                            self._finish(seq, "kv_capacity")
                            events.append({"seq": seq, "token": None,
                                           "done": True,
                                           "finish_reason": "kv_capacity"})
                            break
                        grown = self.allocator.alloc(1)
                    if grown is not None:
                        seq.blocks.extend(grown)
            batch = list(self._active)
            if not batch:
                return events
            t0 = time.perf_counter()
            S_active = len(batch)
            S = next(r for r in self.slot_rungs if r >= S_active)
            tokens = np.zeros((S,), np.int32)
            seq_lens = np.zeros((S,), np.int32)
            active = np.zeros((S,), bool)
            tables = np.zeros((S, self.max_blocks), np.int32)
            uids = np.zeros((S,), np.int32)
            steps = np.zeros((S,), np.int32)
            for i, seq in enumerate(batch):
                tokens[i] = seq.generated[-1]
                seq_lens[i] = seq.tokens_in_pages
                active[i] = True
                tables[i, :len(seq.blocks)] = seq.blocks
                uids[i] = seq.uid
                steps[i] = len(seq.generated)
            fn = self._decode_fn(S)
            next_tok, self._k_pages, self._v_pages = fn(
                self.params, jnp.asarray(tokens), jnp.asarray(seq_lens),
                jnp.asarray(active), jnp.asarray(tables), self._k_pages,
                self._v_pages, jnp.asarray(uids), jnp.asarray(steps))
            next_tok = np.asarray(next_tok)
            m = _ENGINE_METRICS.get()
            dt_ms = (time.perf_counter() - t0) * 1e3
            m["step_ms"].observe(dt_ms, phase="decode")
            m["token_ms"].labels().observe(dt_ms / max(S_active, 1))
            m["tokens"].inc(S_active, phase="decode")
            if self.draft_tokens > 0:
                self._spec_fallbacks += 1
                m["spec_steps"].inc(mode="fallback")
            for i, seq in enumerate(batch):
                seq.tokens_in_pages += 1
                seq.generated.append(int(next_tok[i]))
                events.append(self._emit(seq, int(next_tok[i])))
                if not seq.done:
                    self._register_blocks(seq)
            self._update_pool_gauges()
        return events

    # ------------------------------------------------------------------
    # offline driver + warmup
    # ------------------------------------------------------------------
    def generate(self, prompts: Sequence[Sequence[int]], max_new_tokens,
                 uids: Sequence[int] | None = None) -> list[list[int]]:
        """Run a list of tokenized prompts to completion through the
        continuous scheduler; returns generated ids per prompt (EOS kept as
        the final token when hit). ``max_new_tokens`` is an int or a
        per-prompt sequence — the offline ``transform()`` surface of the
        SAME engine serving uses online."""
        if isinstance(max_new_tokens, (int, np.integer)):
            max_new_tokens = [int(max_new_tokens)] * len(prompts)
        seqs = [self.submit(p, n, uid=None if uids is None else int(u))
                for p, n, u in zip(prompts, max_new_tokens,
                                   uids if uids is not None
                                   else range(len(prompts)))]
        # progress = the ENGINE's tick counter, not our own calls returning
        # events: when a live serve loop drives the same shared engine
        # concurrently, ITS admit/step may do the work (and may hold every
        # slot for many seconds) — only a wholly-stalled engine raises
        last, idle = -1, 0
        while any(not s.done for s in seqs):
            self.admit()
            self.step()
            now = self._progress_ticks
            if now == last:
                idle += 1
                if idle > 2000:
                    stuck = [s.uid for s in seqs if not s.done]
                    raise RuntimeError(
                        f"paged engine stalled with sequences {stuck} "
                        f"unfinished (pool too small for a single "
                        f"sequence?)")
                if idle > 10:
                    time.sleep(0.001)  # another thread holds the work
            else:
                last, idle = now, 0
        return [list(s.generated) for s in seqs]

    def warmup(self, prompt_lens: Sequence[int] | None = None,
               slot_counts: Sequence[int] | None = None) -> int:
        """Precompile the prefill rungs (seq ladder up to ``max_len``) and
        decode rungs (slot ladder) WITHOUT touching live state: warmup
        programs run over all-trash block tables, so every write lands on
        the reserved page and the returned pools are discarded. Called from
        ``/admin/load`` so a hot-swapped LLM serves its first real request
        with zero compile stalls. Returns the number of programs exercised."""
        import jax.numpy as jnp

        if prompt_lens is None:
            prompt_lens = self.bucketer.seq_buckets_upto(self.max_len)
        if slot_counts is None:
            slot_counts = self.slot_rungs
        n = 0
        B = self.prefill_batch
        with self._lock:
            for P in sorted({self.bucketer.seq_bucket_for(int(p),
                                                          cap=self.max_len)
                             for p in prompt_lens}):
                fn = self._prefill_fn(B, P)
                ids = jnp.zeros((B, P), jnp.int32)
                mask = jnp.zeros((B, P), jnp.int32).at[:, 0].set(1)
                tables = jnp.zeros((B, self.max_blocks), jnp.int32)
                zi = jnp.zeros((B,), jnp.int32)
                # all writes land on the trash page, so reassigning the
                # returned pools is a no-op for live pages — and REQUIRED
                # under buffer donation (the input buffers are consumed)
                _, self._k_pages, self._v_pages = fn(
                    self.params, ids, mask, tables, self._k_pages,
                    self._v_pages, zi, zi)
                n += 1
            if self._prefix_cache is not None:
                for Q in sorted({self.bucketer.seq_bucket_for(
                        int(p), cap=self.max_len) for p in prompt_lens}):
                    fn = self._extend_fn(B, Q)
                    ids = jnp.zeros((B, Q), jnp.int32)
                    mask = jnp.zeros((B, Q), jnp.int32).at[:, 0].set(1)
                    tables = jnp.zeros((B, self.max_blocks), jnp.int32)
                    none = jnp.full((B,), -1, jnp.int32)
                    zi = jnp.zeros((B,), jnp.int32)
                    _, self._k_pages, self._v_pages = fn(
                        self.params, ids, mask, zi, tables, none, none,
                        self._k_pages, self._v_pages, zi, zi)
                    n += 1
            for S in sorted({int(s) for s in slot_counts}):
                fn = self._decode_fn(S)
                zs = jnp.zeros((S,), jnp.int32)
                tables = jnp.zeros((S, self.max_blocks), jnp.int32)
                _, self._k_pages, self._v_pages = fn(
                    self.params, zs, zs, jnp.zeros((S,), bool), tables,
                    self._k_pages, self._v_pages, zs, zs)
                n += 1
            if self.draft_tokens > 0:
                for S in sorted({int(s) for s in slot_counts}):
                    fn = self._spec_fn(S)
                    zs = jnp.zeros((S,), jnp.int32)
                    off = jnp.zeros((S,), bool)
                    tables = jnp.zeros((S, self.max_blocks), jnp.int32)
                    if self._drafter is not None:
                        W = self._draft_window
                        zw = jnp.zeros((S, W), jnp.int32)
                        _, _, self._k_pages, self._v_pages = fn(
                            self.params, self._draft_params, zs, zs, off,
                            tables, zw, zw, zs, self._k_pages,
                            self._v_pages)
                    else:
                        _, _, self._k_pages, self._v_pages = fn(
                            self.params, self._draft_params, zs, zs, off,
                            tables, self._k_pages, self._v_pages)
                    n += 1
        return n

    # ------------------------------------------------------------------
    # sequence migration (live drain / crash handoff)
    # ------------------------------------------------------------------
    def model_digest(self) -> str:
        """sha256 over the param tree (leaf names, shapes, dtypes, bytes)
        plus the generation-determinism knobs (sampling config, seed,
        eos) — two engines with equal digests emit identical token streams
        for the same ``(uid, prompt, generated)``, which is exactly the
        contract :meth:`import_sequence` needs to resume a migrated
        sequence without recompute. Computed once per engine."""
        if getattr(self, "_model_digest_v", None) is None:
            h = hashlib.sha256()
            for name, leaf in sorted(
                    serialization.flatten_pytree(self.params).items()):
                arr = np.ascontiguousarray(np.asarray(leaf))
                h.update(name.encode())
                h.update(repr((arr.shape, str(arr.dtype))).encode())
                h.update(arr.tobytes())
            h.update(repr((self.temperature, self.top_k, self.top_p,
                           self.seed, self.eos_id)).encode())
            self._model_digest_v = h.hexdigest()
        return self._model_digest_v

    def export_sequence(self, uid: int) -> dict | None:
        """Snapshot one live (active or waiting) sequence as a migratable
        artifact and remove it from this engine (pages freed, finish
        reason ``'migrated'``). Returns None for an unknown/finished uid.

        The snapshot is self-contained and wire-friendly::

            {"manifest": <JSON-able>, "payload": <npz bytes>,
             "digests": {"payload": <sha256 hex>}}

        The manifest carries the host state (prompt ids, emitted ids,
        sampling config, model digest) plus a ``chunks`` section in the
        PR-13 index-range format (``parallel/checkpoint.py``): per layer,
        ``kv/{k,v}/NNN`` maps to ``{"shape", "dtype", "parts": [{"key",
        "start", "stop"}]}`` where each part is one KV page's worth of
        token rows and ``payload`` npz key ``c:<name>#<k>`` holds the
        array. Ranges are TOKEN-indexed, not block-indexed, so an engine
        with a different ``block_len`` can still scatter them. The
        ``digests`` entry is the sha256 sidecar: import verifies it and
        falls back to re-prefill on mismatch rather than decoding over a
        torn payload."""
        with self._lock:
            seq = next((s for s in self._active if s.uid == int(uid)), None)
            was_waiting = False
            if seq is None:
                seq = next((s for s in self._waiting
                            if s.uid == int(uid)), None)
                if seq is None:
                    return None
                was_waiting = True
            T = 0 if was_waiting else int(seq.tokens_in_pages)
            manifest: dict = {
                "version": 1,
                "uid": int(seq.uid),
                "prompt_ids": [int(t) for t in seq.prompt_ids],
                "generated": [int(t) for t in seq.generated],
                "max_new_tokens": int(seq.max_new_tokens),
                "request_id": seq.request_id,
                "stream": bool(seq.stream),
                "preemptions": int(seq.preemptions),
                "tokens_in_pages": T,
                "journal_key": seq.journal_key,
                # deadlines are perf_counter instants, meaningless across
                # processes — ship the REMAINING budget instead
                "deadline_ms_left": (
                    None if seq.deadline is None
                    else (seq.deadline - time.perf_counter()) * 1e3),
                "sampling": {"temperature": self.temperature,
                             "top_k": self.top_k, "top_p": self.top_p,
                             "seed": self.seed, "eos_id": self.eos_id},
                "model_digest": self.model_digest(),
                "chunks": {},
            }
            payload: dict[str, np.ndarray] = {}
            if T > 0:
                rows = np.asarray(seq.blocks, np.int64)
                for axis, pool in (("k", self._k_pages),
                                   ("v", self._v_pages)):
                    for L, pages in enumerate(pool):
                        name = f"kv/{axis}/{L:03d}"
                        kvh, hd = int(pages.shape[2]), int(pages.shape[3])
                        flat = np.asarray(pages[rows]).reshape(
                            -1, kvh, hd)[:T]
                        parts = []
                        for k in range(len(seq.blocks)):
                            start = k * self.block_len
                            stop = min(start + self.block_len, T)
                            if start >= stop:
                                break
                            key = f"c:{name}#{k}"
                            payload[key] = _npz_safe(flat[start:stop])
                            parts.append({"key": key,
                                          "start": [start, 0, 0],
                                          "stop": [stop, kvh, hd]})
                        manifest["chunks"][name] = {
                            "shape": [T, kvh, hd],
                            "dtype": str(flat.dtype),
                            "parts": parts}
            buf = io.BytesIO()
            np.savez(buf, **payload)
            blob = buf.getvalue()
            if was_waiting:
                self._waiting.remove(seq)
            self._finish(seq, "migrated")
            return {"manifest": manifest, "payload": blob,
                    "digests": {
                        "payload": hashlib.sha256(blob).hexdigest()}}

    def import_sequence(self, snapshot: dict) -> SequenceState:
        """Readmit an exported sequence. Fast path: verify the model
        digest and the payload's sha256 sidecar, allocate pages, scatter
        the KV chunks in, and resume decode with ZERO recompute. On digest
        mismatch, sidecar mismatch, torn chunks, slot pressure, or page
        exhaustion: deterministic re-prefill over prompt+generated (the
        PR-6 preemption path — token-identical under greedy). Either way
        the next ``admit()``/``step()`` emits only NEW tokens; previously
        emitted ids ride in ``generated`` and are never re-surfaced."""
        import jax.numpy as jnp

        man = snapshot["manifest"]
        blob = snapshot.get("payload") or b""
        want = (snapshot.get("digests") or {}).get("payload")
        intact = man.get("model_digest") == self.model_digest()
        if intact and want is not None \
                and hashlib.sha256(blob).hexdigest() != want:
            intact = False  # torn payload: recompute, never decode garbage
        T = int(man.get("tokens_in_pages") or 0)
        left = man.get("deadline_ms_left")
        seq = SequenceState(
            uid=int(man["uid"]),
            prompt_ids=[int(t) for t in man["prompt_ids"]],
            max_new_tokens=int(man["max_new_tokens"]),
            request_id=man.get("request_id"),
            stream=bool(man.get("stream")),
            generated=[int(t) for t in man.get("generated") or []],
            preemptions=int(man.get("preemptions") or 0),
            journal_key=man.get("journal_key"),
            deadline=(None if left is None
                      else time.perf_counter() + float(left) / 1e3))
        if seq.generated:
            # ttft was observed at the origin engine; don't double-count
            seq.first_token_at = time.perf_counter()

        def _fallback():
            seq.tokens_in_pages = 0
            seq.preemptions += 1
            self._waiting.appendleft(seq)
            _ENGINE_METRICS.get()["preempted"].inc()
            return seq

        with self._lock:
            self._uid = max(self._uid, seq.uid)
            # invariant of an active sequence: pages hold every context
            # token except the newest generated one (which rides as the
            # next decode step's input token)
            resumable = (intact and T > 0 and seq.generated
                         and T == len(seq.context_ids) - 1
                         and len(self._active) < self.max_slots
                         and T < self.max_len)
            if not resumable:
                return _fallback()
            self._reclaim(self._blocks_for(T))
            blocks = self.allocator.alloc(self._blocks_for(T))
            if blocks is None:
                return _fallback()  # import-side page exhaustion
            try:
                data = np.load(io.BytesIO(blob), allow_pickle=False)
                for axis in ("k", "v"):
                    pool = self._k_pages if axis == "k" else self._v_pages
                    new_pool = []
                    for L, pages in enumerate(pool):
                        name = f"kv/{axis}/{L:03d}"
                        entry = man["chunks"][name]
                        kvh, hd = int(pages.shape[2]), int(pages.shape[3])
                        dt = np.dtype(entry["dtype"])
                        staged = np.zeros(
                            (len(blocks) * self.block_len, kvh, hd), dt)
                        for part in entry["parts"]:
                            arr = np.asarray(data[part["key"]])
                            if arr.dtype == np.uint8 and dt != np.uint8:
                                arr = np.frombuffer(arr.tobytes(), dt)
                            lo, hi = part["start"][0], part["stop"][0]
                            staged[lo:hi] = arr.reshape(hi - lo, kvh, hd)
                        staged = staged.reshape(
                            len(blocks), self.block_len, kvh, hd)
                        new_pool.append(pages.at[jnp.asarray(blocks)].set(
                            jnp.asarray(staged)))
                    if axis == "k":
                        self._k_pages = tuple(new_pool)
                    else:
                        self._v_pages = tuple(new_pool)
            except Exception:
                # torn/incomplete chunk set — the freed blocks may hold
                # partial writes, but pages are only read below a live
                # sequence's seq_len and every (re-)prefill overwrites its
                # pages first, so stale rows can never leak into attention
                self.allocator.free(blocks)
                return _fallback()
            seq.blocks = list(blocks)
            seq.tokens_in_pages = T
            self._active.append(seq)
            self._update_pool_gauges()
            return seq

    def live_sequences(self) -> list[SequenceState]:
        """Every active + waiting sequence (a consistent snapshot) — the
        drain path iterates this to export each one."""
        with self._lock:
            return list(self._active) + list(self._waiting)

    def expire_deadlines(self, now: float | None = None) -> list[dict]:
        """Finish every sequence whose client deadline has passed (pages
        freed immediately, ``finish_reason='deadline'``); returns terminal
        events for the serving layer to 504. Runs at the top of every
        :meth:`admit`/:meth:`step`, so an expired sequence never costs
        another device step."""
        now = time.perf_counter() if now is None else now
        events: list[dict] = []
        with self._lock:
            doomed = [s for s in self._active
                      if s.deadline is not None and now >= s.deadline]
            doomed += [s for s in self._waiting
                       if s.deadline is not None and now >= s.deadline]
            for seq in doomed:
                if seq in self._waiting:
                    self._waiting.remove(seq)
                self._finish(seq, "deadline")
                events.append({"seq": seq, "token": None, "done": True,
                               "finish_reason": "deadline"})
        return events

    def abort(self, seq: SequenceState, reason: str = "aborted") -> None:
        """Terminate one sequence (client gone / stream broken), freeing its
        pages and slot immediately so dead connections cannot pin decode
        capacity. ``reason`` distinguishes ``'client_gone'`` (disconnect
        reaping) from a generic ``'aborted'`` in the finished counter."""
        with self._lock:
            if not seq.done:
                if seq in self._waiting:
                    self._waiting.remove(seq)
                self._finish(seq, reason)

    def abort_all(self, reason: str = "aborted") -> list[SequenceState]:
        """Terminate every waiting and active sequence (reason
        ``'aborted'``), freeing all pages — the hot-swap path drains the
        outgoing engine through this so no request stalls silently."""
        with self._lock:
            doomed = list(self._active) + list(self._waiting)
            self._waiting.clear()
            for seq in doomed:
                if not seq.done:
                    self._finish(seq, reason)
            return doomed

    def stats(self) -> dict:
        with self._lock:
            cap = self.allocator.capacity
            out = {"active": len(self._active),
                   "waiting": len(self._waiting),
                   "blocks_used": self.allocator.used_count,
                   "blocks_free": self.allocator.free_count,
                   "occupancy": self.allocator.used_count / cap if cap
                   else 0.0}
            if self._prefix_cache is not None:
                pc = self._prefix_cache.stats()
                pc["occupancy"] = pc["blocks"] / cap if cap else 0.0
                out["prefix_cache"] = pc
            if self.draft_tokens > 0:
                out["speculation"] = {
                    "draft_tokens": self.draft_tokens,
                    "proposed": self._spec_proposed,
                    "accepted": self._spec_accepted,
                    "acceptance_rate": (
                        self._spec_accepted / self._spec_proposed
                        if self._spec_proposed else 0.0),
                    "steps": self._spec_steps,
                    "fallbacks": self._spec_fallbacks}
            return out

    def release(self) -> None:
        """Evict this engine's compiled programs from the shared cache and
        mark the engine dead — a failed device call may have consumed the
        donated page buffers, so a released engine must never be reused
        (``HuggingFaceCausalLM._paged_engine`` rebuilds instead of
        returning it from its cache)."""
        self._released = True
        cb.get_compiled_cache().evict_instance(self._instance)
