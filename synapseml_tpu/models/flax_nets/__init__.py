from .bert import BertClassifier, bert_base, bert_tiny
from .llama import LlamaLM, generate, greedy_generate, llama2_7b, llama_tiny
from .resnet import ResNet, resnet18, resnet50, resnet_tiny
from .transformer import Attention, Block, Encoder, RMSNorm, TransformerConfig
from .vit import ViTClassifier, vit_b16, vit_tiny

__all__ = [
    "BertClassifier", "bert_base", "bert_tiny",
    "LlamaLM", "generate", "greedy_generate", "llama2_7b", "llama_tiny",
    "ResNet", "resnet18", "resnet50", "resnet_tiny",
    "Attention", "Block", "Encoder", "RMSNorm", "TransformerConfig",
    "ViTClassifier", "vit_b16", "vit_tiny",
]
