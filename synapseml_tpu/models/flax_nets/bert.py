"""BERT-style text encoder + classification head (Flax).

Reference analog: the HF ``AutoModelForSequenceClassification`` wrapped by
``dl/LitDeepTextModel.py:29-176``; here a native Flax module with GSPMD axis
names so `DeepTextClassifier` shards it over the mesh instead of horovod DP.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from .transformer import Encoder, TransformerConfig

__all__ = ["BertConfig", "BertClassifier", "bert_base", "bert_tiny"]


def BertConfig(**kw) -> TransformerConfig:
    defaults = dict(vocab_size=30522, hidden=768, n_layers=12, n_heads=12,
                    mlp_dim=3072, max_len=512, norm="layernorm", act="gelu",
                    norm_position="post", norm_eps=1e-12)
    defaults.update(kw)
    return TransformerConfig(**defaults)


def bert_base(**kw) -> TransformerConfig:
    return BertConfig(**kw)


def bert_tiny(**kw) -> TransformerConfig:
    defaults = dict(vocab_size=1024, hidden=64, n_layers=2, n_heads=2, mlp_dim=128, max_len=128)
    defaults.update(kw)
    return BertConfig(**defaults)


class BertEmbeddings(nn.Module):
    cfg: TransformerConfig
    n_segments: int = 2

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None):
        cfg = self.cfg
        embed = lambda name, num: nn.Embed(  # noqa: E731
            num, cfg.hidden, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("vocab", "embed")),
            name=name)
        x = embed("word", cfg.vocab_size)(input_ids)
        pos = jnp.arange(input_ids.shape[1])[None, :]
        x = x + embed("position", cfg.max_len)(pos)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = x + embed("segment", self.n_segments)(token_type_ids)
        x = nn.LayerNorm(epsilon=cfg.norm_eps, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype)(x)
        if cfg.dropout > 0:
            x = nn.Dropout(cfg.dropout, deterministic=not self.has_rng("dropout"))(x)
        return x


class BertClassifier(nn.Module):
    """[B,T] token ids -> [B,num_classes] logits (CLS pooling)."""

    cfg: TransformerConfig
    num_classes: int = 2

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None):
        cfg = self.cfg
        x = BertEmbeddings(cfg, name="embeddings")(input_ids, token_type_ids)
        mask = None
        if attention_mask is not None:
            mask = attention_mask[:, None, None, :].astype(bool)  # [B,1,1,T]
        x = Encoder(cfg, name="encoder")(x, mask)
        cls = x[:, 0]
        pooled = nn.tanh(nn.Dense(
            cfg.hidden, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(nn.initializers.xavier_uniform(),
                                                     ("embed", "mlp")),
            name="pooler")(cls))
        logits = nn.Dense(
            self.num_classes, dtype=jnp.float32, param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(nn.initializers.xavier_uniform(),
                                                     ("embed", None)),
            name="classifier")(pooled)
        return logits
