"""Vision Transformer (Flax) — backbone for DeepVisionClassifier.

Reference analog: torchvision backbones consumed by
``dl/LitDeepVisionModel.py``; rebuilt as a native Flax ViT so vision transfer
learning runs on the MXU with GSPMD sharding.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from .transformer import Encoder, TransformerConfig

__all__ = ["vit_b16", "vit_tiny", "ViTClassifier"]


def vit_b16(**kw) -> TransformerConfig:
    defaults = dict(vocab_size=1, hidden=768, n_layers=12, n_heads=12, mlp_dim=3072,
                    max_len=1 + (224 // 16) ** 2, norm="layernorm", act="gelu")
    defaults.update(kw)
    return TransformerConfig(**defaults)


def vit_tiny(**kw) -> TransformerConfig:
    defaults = dict(vocab_size=1, hidden=64, n_layers=2, n_heads=2, mlp_dim=128,
                    max_len=1 + (32 // 8) ** 2)
    defaults.update(kw)
    return TransformerConfig(**defaults)


class ViTClassifier(nn.Module):
    """[B,H,W,C] images -> [B,num_classes] logits."""

    cfg: TransformerConfig
    num_classes: int = 1000
    patch: int = 16

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        x = nn.Conv(cfg.hidden, kernel_size=(self.patch, self.patch),
                    strides=(self.patch, self.patch), dtype=cfg.dtype,
                    param_dtype=cfg.param_dtype,
                    kernel_init=nn.with_logical_partitioning(
                        nn.initializers.xavier_uniform(), (None, None, None, "embed")),
                    name="patch_embed")(x.astype(cfg.dtype))
        B, h, w, _ = x.shape
        x = x.reshape(B, h * w, cfg.hidden)
        cls = self.param("cls", nn.with_logical_partitioning(
            nn.initializers.zeros, (None, None, "embed")), (1, 1, cfg.hidden), cfg.param_dtype)
        x = jnp.concatenate([jnp.broadcast_to(cls, (B, 1, cfg.hidden)).astype(cfg.dtype), x], axis=1)
        pos = self.param("pos_embed", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), (None, "seq", "embed")),
            (1, cfg.max_len, cfg.hidden), cfg.param_dtype)
        x = x + pos[:, : x.shape[1]].astype(cfg.dtype)
        x = Encoder(cfg, name="encoder")(x)
        logits = nn.Dense(self.num_classes, dtype=jnp.float32, param_dtype=cfg.param_dtype,
                          kernel_init=nn.with_logical_partitioning(
                              nn.initializers.xavier_uniform(), ("embed", None)),
                          name="head")(x[:, 0])
        return logits
