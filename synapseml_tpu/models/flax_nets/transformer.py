"""Shared transformer building blocks, TPU-first.

Design (not a torch port — reference models arrive via torchvision/HF in
``dl/LitDeepVisionModel.py`` / ``dl/LitDeepTextModel.py``; here they are Flax
modules built for GSPMD):
  * every weight carries logical axis names (``nn.with_logical_partitioning``)
    mapped to mesh axes by ``parallel.mesh.logical_axis_rules`` — tensor
    parallelism is a rule change, not a code change;
  * compute dtype bf16 by default (MXU native), params fp32;
  * attention is einsum-based with optional GQA + rotary embeddings and a
    decode-time KV cache; the sequence axis is ready for ring attention
    (``ops.ring_attention``) when seq-parallel is on;
  * optional ``nn.remat`` on blocks trades FLOPs for HBM.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["TransformerConfig", "Attention", "MlpBlock", "Block", "Encoder", "RMSNorm",
           "apply_rope", "make_causal_mask"]

Dtype = Any


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    hidden: int = 768
    n_layers: int = 12
    n_heads: int = 12
    n_kv_heads: int | None = None  # None -> MHA; < n_heads -> GQA
    mlp_dim: int = 3072
    max_len: int = 512
    dropout: float = 0.0
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32
    causal: bool = False
    use_rope: bool = False
    rope_theta: float = 10000.0
    norm: str = "layernorm"  # or "rmsnorm"
    # 'pre' (norm before attn/mlp + final encoder norm — ViT/Llama) or 'post'
    # (norm after each residual add, no final norm — original BERT). Post-norm
    # is required for faithful ingestion of HF BERT checkpoints.
    norm_position: str = "pre"
    # learned absolute position embeddings added by the LM wrapper (GPT-2
    # family); RoPE models leave this False
    learned_pos: bool = False
    gated_mlp: bool = False  # SwiGLU when True
    act: str = "gelu"
    remat: bool = False
    norm_eps: float = 1e-6
    # attention backend: 'einsum' (XLA, always available), 'flash' (Pallas
    # blockwise kernel, ops.flash_attention), 'ring' (sequence-parallel ring
    # over `seq_axis`, ops.ring_attention — requires a live mesh whose
    # seq axis size > 1; falls back to flash/einsum otherwise).
    # 'einsum' is the measured-fastest default on v5e at T=128..4096
    # (docs/BENCHMARKS.md) — XLA's fused attention beats the Pallas kernel;
    # use 'flash' only when the O(T^2) score buffer doesn't fit, 'ring' for
    # true long-context over the mesh. CAVEAT: that table predates the bf16
    # MXU fix (commit ee387ce) which made the flash/ring kernels ~4x faster;
    # re-measurement is queued as the `attn-backends` bench child — treat
    # the default as provisional until it lands (docs/BENCHMARKS.md).
    attn_impl: str = "einsum"
    seq_axis: str = "seq"
    # mixture-of-experts MLP (switch-transformer routing): 0 = dense MLP.
    # Expert weights carry the 'expert' logical axis, so on a mesh with an
    # expert axis the per-expert matmuls shard and GSPMD inserts the token
    # all-to-alls from the dispatch einsums (expert parallelism).
    moe_experts: int = 0
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    # token->expert routing layout: 'einsum' builds [S, E, C] one-hot
    # dispatch/combine tensors (pure MXU work; right when C is small, i.e.
    # capacity_factor ~1-2 with switch-style dropping). 'scatter' sorts the
    # (token, choice) assignments by expert and scatters rows into [E, C, H]
    # buffers — O(E*C*H) memory and O(S*k*H) index work, never O(S*E*C) —
    # which is the only feasible layout when capacity must be dropless
    # (C = S, e.g. ingested Mixtral checkpoints at real sequence lengths).
    moe_dispatch: str = "einsum"

    @property
    def head_dim(self) -> int:
        return self.hidden // self.n_heads

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads


def _act_fn(name: str) -> Callable:
    # 'gelu' is the exact erf form (what HF BERT/ViT checkpoints were trained
    # with); 'gelu_tanh' is the cheaper approximation
    return {"gelu": lambda x: nn.gelu(x, approximate=False),
            "gelu_tanh": nn.gelu, "relu": nn.relu, "silu": nn.silu}[name]


class RMSNorm(nn.Module):
    eps: float = 1e-6
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.with_logical_partitioning(nn.initializers.ones, ("embed",)),
                           (x.shape[-1],))
        x32 = x.astype(jnp.float32)
        normed = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (normed * scale).astype(self.dtype)


def _norm(cfg: TransformerConfig):
    if cfg.norm == "rmsnorm":
        return RMSNorm(eps=cfg.norm_eps, dtype=cfg.dtype)
    return nn.LayerNorm(epsilon=cfg.norm_eps, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                        scale_init=nn.with_logical_partitioning(nn.initializers.ones, ("embed",)),
                        bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("embed",)))


def rope_frequencies(head_dim: int, max_len: int, theta: float) -> tuple[np.ndarray, np.ndarray]:
    inv = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float32) / head_dim))
    t = np.arange(max_len, dtype=np.float32)
    freqs = np.outer(t, inv)
    return np.cos(freqs), np.sin(freqs)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array, positions: jax.Array) -> jax.Array:
    """x: [B, T, H, D]; positions: [B, T] absolute positions (decode-time offset aware)."""
    c = cos[positions][:, :, None, :]  # [B,T,1,D/2]
    s = sin[positions][:, :, None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1).astype(x.dtype)


def make_causal_mask(q_len: int, kv_len: int, offset: int = 0) -> jax.Array:
    q_pos = jnp.arange(q_len)[:, None] + offset
    kv_pos = jnp.arange(kv_len)[None, :]
    return (kv_pos <= q_pos)[None, None, :, :]  # [1,1,Q,KV]


def _current_mesh():
    """The mesh in scope (``with mesh:`` context or jit sharding env), if any."""
    try:
        m = jax.sharding.get_abstract_mesh()
        if m is not None and m.axis_names:
            return m
    except Exception:
        pass
    try:
        import warnings

        from jax.interpreters import pxla

        with warnings.catch_warnings():
            warnings.simplefilter("ignore", DeprecationWarning)
            m = pxla.thread_resources.env.physical_mesh
        if m is not None and not m.empty:
            return m
    except Exception:
        pass
    return None


class Attention(nn.Module):
    """Multi-head / grouped-query attention with optional rotary embeddings and
    a linen cache collection for autoregressive decode.

    The score/softmax/value core dispatches on ``cfg.attn_impl``:
    'einsum' (XLA), 'flash' (Pallas blockwise kernel), 'ring' (K/V rotation
    over ``cfg.seq_axis``), or 'ulysses' (all-to-all head/token swap over
    ``cfg.seq_axis``) — the long-context paths the reference lacks,
    SURVEY.md §5."""

    cfg: TransformerConfig
    decode: bool = False

    def _attend(self, q, k, v, mask):
        cfg = self.cfg
        D = cfg.head_dim
        # flash/ring support padding (kv-position) masks; arbitrary [.., Q, K]
        # masks (decode-time cache masks) use the einsum path
        kv_mask = None
        mask_is_kv_shaped = (mask is not None and mask.ndim == 4
                             and mask.shape[1] == 1 and mask.shape[2] == 1)
        if mask_is_kv_shaped:
            kv_mask = mask[:, 0, 0, :]
        impl = cfg.attn_impl
        # NOTE: flash/ring never materialize attention probabilities, so
        # attention-probability dropout does not apply on those paths (standard
        # for fused kernels); residual/MLP dropout is unaffected. Falling back
        # to einsum here would silently reintroduce the O(T^2) score matrix.
        eligible = not self.decode and (mask is None or mask_is_kv_shaped)

        if impl in ("ring", "ulysses") and eligible:
            mesh = _current_mesh()
            if mesh is not None and dict(zip(mesh.axis_names, mesh.axis_sizes)
                                         ).get(cfg.seq_axis, 1) > 1:
                if impl == "ulysses":
                    from ...ops import ulysses_attention_sharded

                    return ulysses_attention_sharded(
                        mesh, q, k, v, kv_mask=kv_mask, causal=cfg.causal,
                        seq_axis=cfg.seq_axis)
                from ...ops import ring_attention_sharded

                return ring_attention_sharded(mesh, q, k, v, kv_mask=kv_mask,
                                              causal=cfg.causal,
                                              seq_axis=cfg.seq_axis)
            import warnings

            warnings.warn(
                f"attn_impl={impl!r} requested but no mesh with a "
                f"'{cfg.seq_axis}' axis (size>1) is in scope; using the local "
                f"flash kernel instead", stacklevel=2)
            impl = "flash"

        if impl == "flash" and eligible:
            from ...ops import flash_attention

            return flash_attention(q, k, v, kv_mask=kv_mask, causal=cfg.causal)

        if cfg.causal and not self.decode:
            causal = make_causal_mask(q.shape[1], k.shape[1])
            mask = causal if mask is None else jnp.logical_and(mask, causal)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(D).astype(cfg.dtype)
        if mask is not None:
            scores = jnp.where(mask, scores, jnp.finfo(cfg.dtype).min)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(cfg.dtype)
        if cfg.dropout > 0:
            probs = nn.Dropout(cfg.dropout, deterministic=not self.has_rng("dropout"))(probs)
        return jnp.einsum("bhqk,bkhd->bqhd", probs, v)

    @nn.compact
    def __call__(self, x, mask=None, positions=None):
        cfg = self.cfg
        B, T, _ = x.shape
        H, KV, D = cfg.n_heads, cfg.kv_heads, cfg.head_dim
        dense = lambda name, heads: nn.DenseGeneral(  # noqa: E731
            features=(heads, D), axis=-1, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(nn.initializers.xavier_uniform(),
                                                     ("embed", "heads", "kv")),
            bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("heads", "kv")),
            name=name)
        q = dense("q", H)(x)
        k = dense("k", KV)(x)
        v = dense("v", KV)(x)

        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
        if cfg.use_rope:
            cos_np, sin_np = rope_frequencies(D, cfg.max_len, cfg.rope_theta)
            cos, sin = jnp.asarray(cos_np), jnp.asarray(sin_np)
            q = apply_rope(q, cos, sin, positions)
            k = apply_rope(k, cos, sin, positions)

        if self.decode:
            # linen cache: append at cache_index; the update is skipped on the
            # very first (init) call so a fresh cache starts at index 0
            cache_ready = self.has_variable("cache", "cached_k")
            ck = self.variable("cache", "cached_k", jnp.zeros, (B, cfg.max_len, KV, D), cfg.dtype)
            cv = self.variable("cache", "cached_v", jnp.zeros, (B, cfg.max_len, KV, D), cfg.dtype)
            idx = self.variable("cache", "cache_index", lambda: jnp.zeros((), jnp.int32))
            start = idx.value
            if cache_ready:
                ck.value = jax.lax.dynamic_update_slice(ck.value, k, (0, start, 0, 0))
                cv.value = jax.lax.dynamic_update_slice(cv.value, v, (0, start, 0, 0))
                idx.value = start + T
            k, v = ck.value, cv.value
            kv_len = cfg.max_len
            causal = make_causal_mask(T, kv_len, offset=start)
            mask = causal if mask is None else jnp.logical_and(mask, causal)
        if KV != H:
            k = jnp.repeat(k, H // KV, axis=2)
            v = jnp.repeat(v, H // KV, axis=2)

        out = self._attend(q, k, v, mask)
        return nn.DenseGeneral(
            features=cfg.hidden, axis=(-2, -1), dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(nn.initializers.xavier_uniform(),
                                                     ("heads", "kv", "embed")),
            bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("embed",)),
            name="o")(out)


class MlpBlock(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = lambda name, feat, in_axis, out_axis: nn.Dense(  # noqa: E731
            feat, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(nn.initializers.xavier_uniform(),
                                                     (in_axis, out_axis)),
            bias_init=nn.with_logical_partitioning(nn.initializers.zeros, (out_axis,)),
            name=name)
        act = _act_fn(cfg.act)
        if cfg.gated_mlp:
            g = dense("gate", cfg.mlp_dim, "embed", "mlp")(x)
            u = dense("up", cfg.mlp_dim, "embed", "mlp")(x)
            h = act(g) * u
        else:
            h = act(dense("up", cfg.mlp_dim, "embed", "mlp")(x))
        if cfg.dropout > 0:
            h = nn.Dropout(cfg.dropout, deterministic=not self.has_rng("dropout"))(h)
        return dense("down", cfg.hidden, "mlp", "embed")(h)


class MoEBlock(nn.Module):
    """Switch-transformer MoE MLP: top-k routing, capacity-bucketed einsum
    dispatch, per-expert MLPs with the ``expert`` logical axis.

    Net-new vs the reference (no model parallelism there); the TPU-native
    shape of MoE: dispatch/combine are one-hot einsums (MXU work, static
    shapes), expert weights ``[E, ...]`` shard over the mesh ``expert`` axis
    and GSPMD derives the token all-to-alls from the einsum shardings.
    Tokens overflowing an expert's capacity are dropped (switch behavior —
    the residual connection in :class:`Block` carries them through).
    The load-balancing auxiliary loss is sown under
    ``intermediates/moe_aux_loss`` (mean over layers = the switch aux term).
    """

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        E, k = cfg.moe_experts, cfg.moe_top_k
        B, T, H = x.shape
        S = B * T
        xf = x.reshape(S, H)

        router = nn.Dense(
            E, dtype=jnp.float32, param_dtype=cfg.param_dtype, use_bias=False,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.xavier_uniform(), ("embed", None)),
            name="router")
        logits = router(xf.astype(jnp.float32))           # [S, E] f32
        probs = jax.nn.softmax(logits, axis=-1)

        # capacity per expert, lane-friendly and >= 1
        C = max(int(np.ceil(cfg.moe_capacity_factor * S * k / E)), 1)

        gate_vals, gate_idx = jax.lax.top_k(probs, k)      # [S, k]
        if k > 1:
            # renormalize over the selected experts — identical to Mixtral's
            # softmax-then-topk-then-divide. k=1 keeps the RAW router
            # probability (switch-transformer semantics: the gate carries the
            # router gradient); Mixtral never ships k=1 configs.
            gate_vals = gate_vals / jnp.maximum(
                jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)

        if cfg.moe_dispatch not in ("einsum", "scatter"):
            raise ValueError(
                f"moe_dispatch must be 'einsum' or 'scatter', got "
                f"{cfg.moe_dispatch!r}")
        if cfg.moe_dispatch == "scatter":
            # Sort the S*k (choice, token) assignments by expert so each
            # expert's tokens are contiguous, then scatter rows into [E, C, H]
            # buffers. One extra drop row absorbs capacity overflow (indices
            # stay in-bounds under jit). The flat layout is CHOICE-MAJOR and
            # the sort is stable, so capacity fills all first choices before
            # any second choice — the same drop priority as the einsum loop.
            Sk = S * k
            expert_flat = gate_idx.T.reshape(Sk)
            token_flat = jnp.tile(jnp.arange(S), k)
            gates_flat = gate_vals.T.reshape(Sk)
            order = jnp.argsort(expert_flat, stable=True)
            e_sorted = expert_flat[order]
            t_sorted = token_flat[order]
            g_sorted = gates_flat[order]
            counts = jnp.bincount(e_sorted, length=E)
            starts = jnp.cumsum(counts) - counts
            pos = jnp.arange(Sk) - starts[e_sorted]        # slot within expert
            keep = pos < C
            buf_idx = jnp.where(keep, e_sorted * C + pos, E * C)
            expert_in = jnp.zeros((E * C + 1, H), cfg.dtype)
            expert_in = expert_in.at[buf_idx].set(xf[t_sorted].astype(cfg.dtype))
            expert_in = expert_in[:E * C].reshape(E, C, H)
        else:
            dispatch = jnp.zeros((S, E, C), cfg.dtype)
            combine = jnp.zeros((S, E, C), jnp.float32)
            position_fill = jnp.zeros((E,), jnp.int32)
            for choice in range(k):
                e_oh = jax.nn.one_hot(gate_idx[:, choice], E, dtype=jnp.int32)
                # position of each token within its chosen expert's buffer,
                # continuing after slots used by earlier choices
                pos = jnp.cumsum(e_oh, axis=0) - e_oh + position_fill[None, :]
                pos_tok = jnp.sum(pos * e_oh, axis=1)      # [S]
                keep = pos_tok < C
                slot = jax.nn.one_hot(pos_tok, C, dtype=cfg.dtype) \
                    * keep[:, None].astype(cfg.dtype)      # [S, C]
                d = e_oh.astype(cfg.dtype)[:, :, None] * slot[:, None, :]
                dispatch = dispatch + d
                combine = combine + d.astype(jnp.float32) \
                    * gate_vals[:, choice][:, None, None]
                position_fill = position_fill + jnp.sum(e_oh, axis=0)

            expert_in = jnp.einsum("sec,sh->ech", dispatch, xf,
                                   preferred_element_type=cfg.dtype)
        expert_in = nn.with_logical_constraint(expert_in,
                                               ("expert", None, "embed"))

        def w(name, shape, axes):
            return self.param(name, nn.with_logical_partitioning(
                nn.initializers.xavier_uniform(), axes), shape,
                cfg.param_dtype)

        w_up = w("w_up", (E, H, cfg.mlp_dim), ("expert", "embed", "mlp"))
        b_up = self.param("b_up", nn.with_logical_partitioning(
            nn.initializers.zeros, ("expert", "mlp")), (E, cfg.mlp_dim),
            cfg.param_dtype)
        w_dn = w("w_dn", (E, cfg.mlp_dim, H), ("expert", "mlp", "embed"))
        b_dn = self.param("b_dn", nn.with_logical_partitioning(
            nn.initializers.zeros, ("expert", "embed")), (E, H),
            cfg.param_dtype)

        act = _act_fn(cfg.act)
        up = jnp.einsum("ech,ehm->ecm", expert_in, w_up.astype(cfg.dtype),
                        preferred_element_type=jnp.float32).astype(cfg.dtype) \
            + b_up[:, None, :].astype(cfg.dtype)
        if cfg.gated_mlp:
            # SwiGLU experts (the Mixtral block): act(x W_gate) * (x W_up)
            w_g = w("w_gate", (E, H, cfg.mlp_dim), ("expert", "embed", "mlp"))
            gate = jnp.einsum("ech,ehm->ecm", expert_in, w_g.astype(cfg.dtype),
                              preferred_element_type=jnp.float32).astype(cfg.dtype)
            h = act(gate) * up
        else:
            h = act(up)
        h = nn.with_logical_constraint(h, ("expert", None, "mlp"))
        if cfg.dropout > 0:  # same placement as MlpBlock's hidden dropout
            h = nn.Dropout(cfg.dropout,
                           deterministic=not self.has_rng("dropout"))(h)
        out_e = jnp.einsum("ecm,emh->ech", h, w_dn.astype(cfg.dtype),
                           preferred_element_type=jnp.float32).astype(cfg.dtype) \
            + b_dn[:, None, :].astype(cfg.dtype)

        if cfg.moe_dispatch == "scatter":
            rows = out_e.reshape(E * C, H)[jnp.minimum(buf_idx, E * C - 1)]
            contrib = rows.astype(jnp.float32) \
                * (g_sorted * keep.astype(jnp.float32))[:, None]
            y = jnp.zeros((S, H), jnp.float32).at[t_sorted].add(contrib)
        else:
            y = jnp.einsum("sec,ech->sh", combine.astype(jnp.float32),
                           out_e.astype(jnp.float32),
                           preferred_element_type=jnp.float32)

        # load-balance aux loss: E * sum_e f_e * P_e, with f_e the token
        # fraction averaged over ALL k routing choices (the Mixtral/switch
        # formulation — top-1-only would let second choices escape balancing
        # pressure when k > 1)
        frac_tokens = jnp.mean(
            jax.nn.one_hot(gate_idx, E, dtype=jnp.float32), axis=(0, 1))
        frac_probs = jnp.mean(probs, axis=0)
        self.sow("intermediates", "moe_aux_loss",
                 E * jnp.sum(frac_tokens * frac_probs))
        return y.reshape(B, T, H).astype(cfg.dtype)


class Block(nn.Module):
    cfg: TransformerConfig
    decode: bool = False

    @nn.compact
    def __call__(self, x, mask=None, positions=None):
        cfg = self.cfg
        mlp_cls = MoEBlock if cfg.moe_experts > 0 else MlpBlock
        if cfg.norm_position == "post":
            # original-BERT residual structure: add then norm
            h = Attention(cfg, decode=self.decode, name="attn")(x, mask, positions)
            x = _norm(cfg)(x + h)
            h = mlp_cls(cfg, name="mlp")(x)
            x = _norm(cfg)(x + h)
        else:
            h = _norm(cfg)(x)
            h = Attention(cfg, decode=self.decode, name="attn")(h, mask, positions)
            x = x + h
            h = _norm(cfg)(x)
            h = mlp_cls(cfg, name="mlp")(h)
            x = x + h
        return nn.with_logical_constraint(x, ("batch", "seq", "embed"))


class Encoder(nn.Module):
    """Stack of blocks (used by BERT/ViT encoders and, with causal=True +
    decode, by the Llama decoder)."""

    cfg: TransformerConfig
    decode: bool = False

    @nn.compact
    def __call__(self, x, mask=None, positions=None):
        cfg = self.cfg
        block_cls = Block
        if cfg.remat:
            block_cls = nn.remat(Block, static_argnums=())
        for i in range(cfg.n_layers):
            x = block_cls(cfg, decode=self.decode, name=f"layer_{i}")(x, mask, positions)
        if cfg.norm_position == "post":
            return x  # post-norm blocks already end normalized
        return _norm(cfg)(x)
