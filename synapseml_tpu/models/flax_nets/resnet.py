"""ResNet (Flax) — the ImageFeaturizer/ONNX ResNet-50 analog, XLA-native.

Reference analog: the ONNX ResNet-50 scored through ONNX Runtime in
``onnx/ImageFeaturizer.scala`` and the torchvision resnet backbones of
``dl/DeepVisionClassifier.py``. Convs stay NHWC (TPU-native layout).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["ResNet", "resnet50", "resnet18", "resnet_tiny"]


class Bottleneck(nn.Module):
    features: int
    strides: int = 1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = lambda f, k, s, name: nn.Conv(  # noqa: E731
            f, (k, k), strides=(s, s), padding=[(k // 2, k // 2)] * 2, use_bias=False,
            dtype=self.dtype, name=name)
        bn = lambda name: nn.BatchNorm(  # noqa: E731
            use_running_average=not train, momentum=0.9, dtype=self.dtype, name=name)
        residual = x
        y = nn.relu(bn("bn1")(conv(self.features, 1, 1, "conv1")(x)))
        y = nn.relu(bn("bn2")(conv(self.features, 3, self.strides, "conv2")(y)))
        y = bn("bn3")(conv(self.features * 4, 1, 1, "conv3")(y))
        if residual.shape != y.shape:
            residual = bn("bn_proj")(conv(self.features * 4, 1, self.strides, "proj")(x))
        return nn.relu(y + residual)


class BasicBlock(nn.Module):
    features: int
    strides: int = 1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = lambda f, k, s, name: nn.Conv(  # noqa: E731
            f, (k, k), strides=(s, s), padding=[(k // 2, k // 2)] * 2, use_bias=False,
            dtype=self.dtype, name=name)
        bn = lambda name: nn.BatchNorm(  # noqa: E731
            use_running_average=not train, momentum=0.9, dtype=self.dtype, name=name)
        residual = x
        y = nn.relu(bn("bn1")(conv(self.features, 3, self.strides, "conv1")(x)))
        y = bn("bn2")(conv(self.features, 3, 1, "conv2")(y))
        if residual.shape != y.shape:
            residual = bn("bn_proj")(conv(self.features, 1, self.strides, "proj")(x))
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """[B,H,W,3] -> logits [B,num_classes]; call with method=feature for the
    headless featurizer path (ImageFeaturizer analog)."""

    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    block: str = "bottleneck"
    num_classes: int = 1000
    width: int = 64
    stem_stride: int = 2
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False, features_only: bool = False):
        block_cls = Bottleneck if self.block == "bottleneck" else BasicBlock
        x = x.astype(self.dtype)
        x = nn.Conv(self.width, (7, 7), strides=(self.stem_stride, self.stem_stride),
                    padding=[(3, 3), (3, 3)], use_bias=False, dtype=self.dtype, name="stem")(x)
        x = nn.relu(nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                 dtype=self.dtype, name="stem_bn")(x))
        if self.stem_stride > 1:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if j == 0 and i > 0 else 1
                x = block_cls(self.width * (2 ** i), strides, self.dtype,
                              name=f"stage{i}_block{j}")(x, train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        if features_only:
            return x.astype(jnp.float32)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


def resnet50(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), block="bottleneck", num_classes=num_classes, **kw)


def resnet18(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet(stage_sizes=(2, 2, 2, 2), block="basic", num_classes=num_classes, **kw)


def resnet_tiny(num_classes: int = 10, **kw) -> ResNet:
    return ResNet(stage_sizes=(1, 1), block="basic", num_classes=num_classes, width=8,
                  stem_stride=1, **kw)
