"""Llama-family causal LM (Flax) — backbone for sharded batch inference.

Reference analog: ``hf/HuggingFaceCausalLMTransform.py:103-331`` loads torch
models per-partition; here a native Flax decoder (RMSNorm + SwiGLU + RoPE +
GQA) whose weights shard over the tensor/fsdp mesh axes — the Llama-2-7B
sharded-inference target of BASELINE.md rides this module.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from .transformer import Encoder, TransformerConfig

__all__ = ["llama2_7b", "llama_tiny", "LlamaLM", "generate", "greedy_generate"]


def llama2_7b(**kw) -> TransformerConfig:
    defaults = dict(vocab_size=32000, hidden=4096, n_layers=32, n_heads=32,
                    n_kv_heads=32, mlp_dim=11008, max_len=4096, norm="rmsnorm",
                    act="silu", gated_mlp=True, causal=True, use_rope=True)
    defaults.update(kw)
    return TransformerConfig(**defaults)


def llama_tiny(**kw) -> TransformerConfig:
    defaults = dict(vocab_size=256, hidden=64, n_layers=2, n_heads=4, n_kv_heads=2,
                    mlp_dim=128, max_len=128, norm="rmsnorm", act="silu",
                    gated_mlp=True, causal=True, use_rope=True)
    defaults.update(kw)
    return TransformerConfig(**defaults)


class LlamaLM(nn.Module):
    """[B,T] ids -> [B,T,V] logits; decode=True enables the KV cache."""

    cfg: TransformerConfig
    decode: bool = False

    @nn.compact
    def __call__(self, input_ids, positions=None, attention_mask=None):
        cfg = self.cfg
        x = nn.Embed(cfg.vocab_size, cfg.hidden, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     embedding_init=nn.with_logical_partitioning(
                         nn.initializers.normal(0.02), ("vocab", "embed")),
                     name="embed")(input_ids)
        if cfg.learned_pos:  # GPT-2-family absolute position embeddings
            B, T = input_ids.shape
            pos = (positions if positions is not None
                   else jnp.broadcast_to(jnp.arange(T)[None, :], (B, T)))
            x = x + nn.Embed(
                cfg.max_len, cfg.hidden, dtype=cfg.dtype,
                param_dtype=cfg.param_dtype,
                embedding_init=nn.with_logical_partitioning(
                    nn.initializers.normal(0.02), (None, "embed")),
                name="wpe")(pos)
        mask = None
        if attention_mask is not None:
            mask = attention_mask[:, None, None, :].astype(bool)
        x = Encoder(cfg, decode=self.decode, name="decoder")(x, mask, positions)
        logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=jnp.float32,
                          param_dtype=cfg.param_dtype,
                          kernel_init=nn.with_logical_partitioning(
                              nn.initializers.normal(0.02), ("embed", "vocab")),
                          name="lm_head")(x)
        return logits


def _make_selector(temperature: float, top_k: int | None, top_p: float | None):
    """Token-selection fn [B,V] logits, key -> [B] ids. temperature<=0 is
    greedy argmax; otherwise categorical sampling with optional top-k then
    nucleus (top-p) filtering — the reference forwards the same HF generate
    kwargs (``hf/HuggingFaceCausalLMTransform.py:284-331``). All branches are
    resolved at trace time (the args are Python constants), so the compiled
    program contains only the selected path."""
    if temperature is None or temperature <= 0.0:
        def select(logits, key):
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return select

    def select(logits, key):
        l = logits.astype(jnp.float32) / temperature
        V = l.shape[-1]
        # sort only the surviving support: top_k bounds the sort width, and
        # renormalizing inside the kept set (softmax over the k values) is
        # exactly HF's filter order (top_k mask, then nucleus on the
        # renormalized remainder)
        k = top_k if (top_k is not None and 0 < top_k < V) else V
        if top_p is not None and top_p < 1.0:
            vals, idx = jax.lax.top_k(l, k)  # [B, k] descending
            probs = jax.nn.softmax(vals, axis=-1)
            # keep tokens whose EXCLUSIVE cumulative mass is < top_p (the
            # highest-prob token always survives)
            keep = (jnp.cumsum(probs, axis=-1) - probs) < top_p
            masked = jnp.where(keep, vals, -jnp.inf)
            j = jax.random.categorical(key, masked, axis=-1)
            return jnp.take_along_axis(idx, j[:, None], axis=1)[:, 0].astype(jnp.int32)
        if k < V:
            vals, idx = jax.lax.top_k(l, k)
            j = jax.random.categorical(key, vals, axis=-1)
            return jnp.take_along_axis(idx, j[:, None], axis=1)[:, 0].astype(jnp.int32)
        return jax.random.categorical(key, l, axis=-1).astype(jnp.int32)
    return select


def generate(model: LlamaLM, params, prompt_ids: jax.Array, max_new_tokens: int,
             eos_id: int | None = None,
             prompt_mask: jax.Array | None = None,
             temperature: float = 0.0,
             top_k: int | None = None,
             top_p: float | None = None,
             rng: jax.Array | None = None) -> jax.Array:
    """Prefill + lax.while_loop decode with KV cache — all static shapes.

    prompt_ids: [B, P] padded to a fixed prompt bucket; ``prompt_mask`` [B, P]
    marks real tokens (1) vs right-padding (0). Padded positions are masked out
    of attention and the first generated token reads the logits of the LAST
    REAL prompt token, not the pad tail. Generated tokens land at P, P+1, …
    regardless of per-row prompt length (uniform layout for unpadding).
    Returns [B, P + max_new_tokens].

    temperature<=0 decodes greedily; otherwise sampling runs fully on-device
    (jax.random.categorical with a per-step key folded from ``rng``), with
    optional top_k and nucleus top_p filtering.
    """
    B, P = prompt_ids.shape
    cfg = model.cfg
    if P + max_new_tokens > cfg.max_len:
        raise ValueError(
            f"prompt ({P}) + max_new_tokens ({max_new_tokens}) exceeds the KV "
            f"cache capacity max_len={cfg.max_len}; dynamic_update_slice would "
            f"silently clamp and corrupt the cache")
    if prompt_mask is None:
        prompt_mask = jnp.ones((B, P), jnp.int32)
    prompt_mask = prompt_mask.astype(jnp.int32)
    lengths = jnp.sum(prompt_mask, axis=-1)  # [B]
    select = _make_selector(temperature, top_k, top_p)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    vars0 = model.init(jax.random.PRNGKey(0), jnp.zeros((B, 1), jnp.int32),
                       positions=jnp.zeros((B, 1), jnp.int32))
    cache0 = vars0["cache"]

    # kv-cache-wide validity: prompt pads stay masked for the whole decode
    kv_mask = jnp.zeros((B, cfg.max_len), jnp.int32)
    kv_mask = jax.lax.dynamic_update_slice(kv_mask, prompt_mask, (0, 0))
    kv_mask = kv_mask.at[:, P:].set(1)  # generated positions are always real

    prefill_pos = jnp.broadcast_to(jnp.arange(P)[None, :], (B, P))
    logits, state = model.apply({"params": params, "cache": cache0}, prompt_ids,
                                positions=prefill_pos, mutable=["cache"],
                                attention_mask=kv_mask)
    last_real = jnp.take_along_axis(logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
    next_tok = select(last_real, jax.random.fold_in(rng, 0))

    total = P + max_new_tokens
    out = jnp.zeros((B, total), jnp.int32).at[:, :P].set(prompt_ids)
    out = out.at[:, P].set(next_tok)

    def cond(carry):
        i, _, _, done = carry
        return jnp.logical_and(i < max_new_tokens - 1, ~jnp.all(done))

    def body(carry):
        i, out, cache, done = carry
        tok = jax.lax.dynamic_slice(out, (0, P + i), (B, 1))
        # cache slot is P+i (static layout); RoPE position is the per-row true
        # token index so padded prompts keep correct relative distances
        pos = (lengths + i)[:, None].astype(jnp.int32)
        logits, st = model.apply({"params": params, "cache": cache}, tok,
                                 positions=pos, mutable=["cache"],
                                 attention_mask=kv_mask)
        nxt = select(logits[:, -1, :], jax.random.fold_in(rng, i + 1))
        if eos_id is not None:
            done = jnp.logical_or(done, nxt == eos_id)
            nxt = jnp.where(done, eos_id, nxt)
        out = jax.lax.dynamic_update_slice(out, nxt[:, None], (0, P + i + 1))
        return i + 1, out, st["cache"], done

    done0 = jnp.zeros((B,), bool)
    if eos_id is not None:
        done0 = next_tok == eos_id
    _, out, _, _ = jax.lax.while_loop(cond, body, (jnp.zeros((), jnp.int32), out,
                                                   state["cache"], done0))
    return out


def greedy_generate(model: LlamaLM, params, prompt_ids: jax.Array,
                    max_new_tokens: int, eos_id: int | None = None,
                    prompt_mask: jax.Array | None = None) -> jax.Array:
    """Greedy decode — ``generate`` at temperature 0 (kept as the stable
    name used by serving and tests)."""
    return generate(model, params, prompt_ids, max_new_tokens, eos_id=eos_id,
                    prompt_mask=prompt_mask, temperature=0.0)
