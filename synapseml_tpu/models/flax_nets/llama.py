"""Llama-family causal LM (Flax) — backbone for sharded batch inference.

Reference analog: ``hf/HuggingFaceCausalLMTransform.py:103-331`` loads torch
models per-partition; here a native Flax decoder (RMSNorm + SwiGLU + RoPE +
GQA) whose weights shard over the tensor/fsdp mesh axes — the Llama-2-7B
sharded-inference target of BASELINE.md rides this module.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from .transformer import (Encoder, MlpBlock, MoEBlock, TransformerConfig,
                          _norm, apply_rope, make_causal_mask,
                          rope_frequencies)

__all__ = ["llama2_7b", "llama_tiny", "LlamaLM", "generate", "greedy_generate",
           "PagedLlamaLM", "paged_prefill", "paged_decode_step",
           "paged_extend", "paged_verify", "early_exit_params"]


def llama2_7b(**kw) -> TransformerConfig:
    defaults = dict(vocab_size=32000, hidden=4096, n_layers=32, n_heads=32,
                    n_kv_heads=32, mlp_dim=11008, max_len=4096, norm="rmsnorm",
                    act="silu", gated_mlp=True, causal=True, use_rope=True)
    defaults.update(kw)
    return TransformerConfig(**defaults)


def llama_tiny(**kw) -> TransformerConfig:
    defaults = dict(vocab_size=256, hidden=64, n_layers=2, n_heads=4, n_kv_heads=2,
                    mlp_dim=128, max_len=128, norm="rmsnorm", act="silu",
                    gated_mlp=True, causal=True, use_rope=True)
    defaults.update(kw)
    return TransformerConfig(**defaults)


class LlamaLM(nn.Module):
    """[B,T] ids -> [B,T,V] logits; decode=True enables the KV cache."""

    cfg: TransformerConfig
    decode: bool = False

    @nn.compact
    def __call__(self, input_ids, positions=None, attention_mask=None):
        cfg = self.cfg
        x = nn.Embed(cfg.vocab_size, cfg.hidden, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     embedding_init=nn.with_logical_partitioning(
                         nn.initializers.normal(0.02), ("vocab", "embed")),
                     name="embed")(input_ids)
        if cfg.learned_pos:  # GPT-2-family absolute position embeddings
            B, T = input_ids.shape
            pos = (positions if positions is not None
                   else jnp.broadcast_to(jnp.arange(T)[None, :], (B, T)))
            x = x + nn.Embed(
                cfg.max_len, cfg.hidden, dtype=cfg.dtype,
                param_dtype=cfg.param_dtype,
                embedding_init=nn.with_logical_partitioning(
                    nn.initializers.normal(0.02), (None, "embed")),
                name="wpe")(pos)
        mask = None
        if attention_mask is not None:
            mask = attention_mask[:, None, None, :].astype(bool)
        x = Encoder(cfg, decode=self.decode, name="decoder")(x, mask, positions)
        logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=jnp.float32,
                          param_dtype=cfg.param_dtype,
                          kernel_init=nn.with_logical_partitioning(
                              nn.initializers.normal(0.02), ("embed", "vocab")),
                          name="lm_head")(x)
        return logits


def _make_selector(temperature: float, top_k: int | None, top_p: float | None):
    """Token-selection fn [B,V] logits, key -> [B] ids. temperature<=0 is
    greedy argmax; otherwise categorical sampling with optional top-k then
    nucleus (top-p) filtering — the reference forwards the same HF generate
    kwargs (``hf/HuggingFaceCausalLMTransform.py:284-331``). All branches are
    resolved at trace time (the args are Python constants), so the compiled
    program contains only the selected path."""
    if temperature is None or temperature <= 0.0:
        def select(logits, key):
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return select

    def select(logits, key):
        l = logits.astype(jnp.float32) / temperature
        V = l.shape[-1]
        # sort only the surviving support: top_k bounds the sort width, and
        # renormalizing inside the kept set (softmax over the k values) is
        # exactly HF's filter order (top_k mask, then nucleus on the
        # renormalized remainder)
        k = top_k if (top_k is not None and 0 < top_k < V) else V
        if top_p is not None and top_p < 1.0:
            vals, idx = jax.lax.top_k(l, k)  # [B, k] descending
            probs = jax.nn.softmax(vals, axis=-1)
            # keep tokens whose EXCLUSIVE cumulative mass is < top_p (the
            # highest-prob token always survives)
            keep = (jnp.cumsum(probs, axis=-1) - probs) < top_p
            masked = jnp.where(keep, vals, -jnp.inf)
            j = jax.random.categorical(key, masked, axis=-1)
            return jnp.take_along_axis(idx, j[:, None], axis=1)[:, 0].astype(jnp.int32)
        if k < V:
            vals, idx = jax.lax.top_k(l, k)
            j = jax.random.categorical(key, vals, axis=-1)
            return jnp.take_along_axis(idx, j[:, None], axis=1)[:, 0].astype(jnp.int32)
        return jax.random.categorical(key, l, axis=-1).astype(jnp.int32)
    return select


def generate(model: LlamaLM, params, prompt_ids: jax.Array, max_new_tokens: int,
             eos_id: int | None = None,
             prompt_mask: jax.Array | None = None,
             temperature: float = 0.0,
             top_k: int | None = None,
             top_p: float | None = None,
             rng: jax.Array | None = None) -> jax.Array:
    """Prefill + lax.while_loop decode with KV cache — all static shapes.

    prompt_ids: [B, P] padded to a fixed prompt bucket; ``prompt_mask`` [B, P]
    marks real tokens (1) vs right-padding (0). Padded positions are masked out
    of attention and the first generated token reads the logits of the LAST
    REAL prompt token, not the pad tail. Generated tokens land at P, P+1, …
    regardless of per-row prompt length (uniform layout for unpadding).
    Returns [B, P + max_new_tokens].

    temperature<=0 decodes greedily; otherwise sampling runs fully on-device
    (jax.random.categorical with a per-step key folded from ``rng``), with
    optional top_k and nucleus top_p filtering.
    """
    B, P = prompt_ids.shape
    cfg = model.cfg
    if P + max_new_tokens > cfg.max_len:
        raise ValueError(
            f"prompt ({P}) + max_new_tokens ({max_new_tokens}) exceeds the KV "
            f"cache capacity max_len={cfg.max_len}; dynamic_update_slice would "
            f"silently clamp and corrupt the cache")
    if prompt_mask is None:
        prompt_mask = jnp.ones((B, P), jnp.int32)
    prompt_mask = prompt_mask.astype(jnp.int32)
    lengths = jnp.sum(prompt_mask, axis=-1)  # [B]
    select = _make_selector(temperature, top_k, top_p)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    vars0 = model.init(jax.random.PRNGKey(0), jnp.zeros((B, 1), jnp.int32),
                       positions=jnp.zeros((B, 1), jnp.int32))
    cache0 = vars0["cache"]

    # kv-cache-wide validity: prompt pads stay masked for the whole decode
    kv_mask = jnp.zeros((B, cfg.max_len), jnp.int32)
    kv_mask = jax.lax.dynamic_update_slice(kv_mask, prompt_mask, (0, 0))
    kv_mask = kv_mask.at[:, P:].set(1)  # generated positions are always real

    prefill_pos = jnp.broadcast_to(jnp.arange(P)[None, :], (B, P))
    logits, state = model.apply({"params": params, "cache": cache0}, prompt_ids,
                                positions=prefill_pos, mutable=["cache"],
                                attention_mask=kv_mask)
    last_real = jnp.take_along_axis(logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
    next_tok = select(last_real, jax.random.fold_in(rng, 0))

    total = P + max_new_tokens
    out = jnp.zeros((B, total), jnp.int32).at[:, :P].set(prompt_ids)
    out = out.at[:, P].set(next_tok)

    def cond(carry):
        i, _, _, done = carry
        return jnp.logical_and(i < max_new_tokens - 1, ~jnp.all(done))

    def body(carry):
        i, out, cache, done = carry
        tok = jax.lax.dynamic_slice(out, (0, P + i), (B, 1))
        # cache slot is P+i (static layout); RoPE position is the per-row true
        # token index so padded prompts keep correct relative distances
        pos = (lengths + i)[:, None].astype(jnp.int32)
        logits, st = model.apply({"params": params, "cache": cache}, tok,
                                 positions=pos, mutable=["cache"],
                                 attention_mask=kv_mask)
        nxt = select(logits[:, -1, :], jax.random.fold_in(rng, i + 1))
        if eos_id is not None:
            done = jnp.logical_or(done, nxt == eos_id)
            nxt = jnp.where(done, eos_id, nxt)
        out = jax.lax.dynamic_update_slice(out, nxt[:, None], (0, P + i + 1))
        return i + 1, out, st["cache"], done

    done0 = jnp.zeros((B,), bool)
    if eos_id is not None:
        done0 = next_tok == eos_id
    _, out, _, _ = jax.lax.while_loop(cond, body, (jnp.zeros((), jnp.int32), out,
                                                   state["cache"], done0))
    return out


def greedy_generate(model: LlamaLM, params, prompt_ids: jax.Array,
                    max_new_tokens: int, eos_id: int | None = None,
                    prompt_mask: jax.Array | None = None) -> jax.Array:
    """Greedy decode — ``generate`` at temperature 0 (kept as the stable
    name used by serving and tests)."""
    return generate(model, params, prompt_ids, max_new_tokens, eos_id=eos_id,
                    prompt_mask=prompt_mask, temperature=0.0)


# ---------------------------------------------------------------------------
# Paged/block KV cache (token-granular continuous batching)
# ---------------------------------------------------------------------------
#
# The dense decode path above allocates a [B, max_len] KV cache per batch
# row, so a finished sequence's cache stays pinned until the whole batch
# exits the while_loop (run-to-completion). The paged variant keys KV storage
# off a fixed physical pool of (n_blocks, block_len, kv_heads, head_dim)
# pages plus a per-sequence BLOCK TABLE of page indices: sequences of any
# length share one pool, a finished sequence's pages free immediately, and
# the decode step is a single-token program whose only batch dimension is
# the number of ACTIVE SLOTS — the vLLM PagedAttention layout expressed as
# pure gather/scatter XLA (no custom kernel), which is what the TPU/CPU
# backends compile well today. Block id 0 is RESERVED as the trash page:
# padded prompt positions and inactive slots write there, so live pages are
# never aliased (property-tested in tests/test_paged_llm.py).
#
# The modules below mirror LlamaLM's module tree name-for-name (embed /
# decoder.layer_i.{RMSNorm_0,RMSNorm_1,attn.{q,k,v,o},mlp} / lm_head), so
# one param pytree drives both the dense and the paged path — a checkpoint
# published for `LlamaLM` serves paged with zero conversion, and greedy
# paged decode is token-for-token identical to `greedy_generate`.


class PagedAttention(nn.Module):
    """GQA attention over a paged KV pool.

    ``mode='prefill'``: self-attention over the (padded) prompt with a
    causal + pad mask, writing each REAL token's K/V into its page slot.
    ``mode='decode'``: one query token per slot; K/V gathered from the pool
    through the block table (pages in table order hold the sequence's
    contiguous logical token stream).

    Param tree is identical to :class:`~.transformer.Attention` (same
    ``q/k/v/o`` DenseGeneral submodules, same init), so params are shared
    with the dense path."""

    cfg: TransformerConfig
    block_len: int
    mode: str  # 'prefill' | 'decode'

    @nn.compact
    def __call__(self, x, k_pages, v_pages, block_tables, positions,
                 write_pos, kv_mask_len):
        """x: [B,T,hidden] (T=1 in decode). positions: [B,T] RoPE positions.
        write_pos: [B,T] page-slot index per token (-1 = don't write, goes
        to the trash page). kv_mask_len: [B] number of attendable logical
        positions (prefill: the padded prompt width with a pad mask handled
        by caller-supplied write_pos; decode: seq_len+1 incl. this token),
        or [B,T] per-token visibility horizons for multi-token decode-mode
        windows (suffix-extend prefill over a cached prefix, speculative
        verify). Returns (out, k_pages, v_pages)."""
        cfg = self.cfg
        B, T, _ = x.shape
        H, KV, D = cfg.n_heads, cfg.kv_heads, cfg.head_dim
        bl = self.block_len
        dense = lambda name, heads: nn.DenseGeneral(  # noqa: E731
            features=(heads, D), axis=-1, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.xavier_uniform(), ("embed", "heads", "kv")),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros, ("heads", "kv")),
            name=name)
        q = dense("q", H)(x)
        k = dense("k", KV)(x)
        v = dense("v", KV)(x)
        if cfg.use_rope:
            cos_np, sin_np = rope_frequencies(D, cfg.max_len, cfg.rope_theta)
            cos, sin = jnp.asarray(cos_np), jnp.asarray(sin_np)
            q = apply_rope(q, cos, sin, positions)
            k = apply_rope(k, cos, sin, positions)

        # ---- scatter K/V into the pool (trash page 0 absorbs non-writes) --
        n_blocks = k_pages.shape[0]
        block_of = jnp.take_along_axis(
            block_tables, jnp.maximum(write_pos, 0) // bl, axis=1)  # [B,T]
        flat_idx = block_of * bl + jnp.maximum(write_pos, 0) % bl
        flat_idx = jnp.where(write_pos >= 0, flat_idx, 0).reshape(-1)
        k_flat = k_pages.reshape(n_blocks * bl, KV, D)
        v_flat = v_pages.reshape(n_blocks * bl, KV, D)
        k_flat = k_flat.at[flat_idx].set(k.reshape(B * T, KV, D)
                                         .astype(k_flat.dtype))
        v_flat = v_flat.at[flat_idx].set(v.reshape(B * T, KV, D)
                                         .astype(v_flat.dtype))

        if self.mode == "prefill":
            # prompt is self-contained: attend over the in-flight K/V (not
            # the pool), causal + pad mask. Pads carry write_pos=-1.
            mask = (write_pos >= 0)[:, None, None, :]
            causal = make_causal_mask(T, T)
            mask = jnp.logical_and(mask, causal)
            kk, vv = k, v
        else:
            # decode: gather this slot's logical KV stream from the pool
            L = block_tables.shape[1] * bl
            gather_idx = (block_tables[:, :, None] * bl
                          + jnp.arange(bl)[None, None, :]).reshape(B, L)
            kk = k_flat[gather_idx]                      # [B, L, KV, D]
            vv = v_flat[gather_idx]
            if kv_mask_len.ndim == 2:
                # per-token horizon [B,T]: the scatter above runs BEFORE this
                # gather, so an in-window token already sees earlier window
                # tokens through the pool — a growing horizon per token is
                # exactly intra-window causality
                mask = (jnp.arange(L)[None, None, :]
                        < kv_mask_len[:, :, None])[:, None, :, :]
            else:
                mask = (jnp.arange(L)[None, :]
                        < kv_mask_len[:, None])[:, None, None, :]
        if KV != H:
            kk = jnp.repeat(kk, H // KV, axis=2)
            vv = jnp.repeat(vv, H // KV, axis=2)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, kk) \
            / jnp.sqrt(D).astype(cfg.dtype)
        scores = jnp.where(mask, scores, jnp.finfo(cfg.dtype).min)
        probs = jax.nn.softmax(scores.astype(jnp.float32),
                               axis=-1).astype(cfg.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, vv)
        out = nn.DenseGeneral(
            features=cfg.hidden, axis=(-2, -1), dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.xavier_uniform(), ("heads", "kv", "embed")),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros, ("embed",)),
            name="o")(out)
        return out, k_flat.reshape(k_pages.shape), v_flat.reshape(v_pages.shape)


class PagedBlock(nn.Module):
    """Pre-norm Block with paged attention; param names match
    :class:`~.transformer.Block` (two anonymous norms in the same creation
    order, ``attn``, ``mlp``)."""

    cfg: TransformerConfig
    block_len: int
    mode: str

    @nn.compact
    def __call__(self, x, k_pages, v_pages, block_tables, positions,
                 write_pos, kv_mask_len):
        cfg = self.cfg
        mlp_cls = MoEBlock if cfg.moe_experts > 0 else MlpBlock
        h = _norm(cfg)(x)
        h, k_pages, v_pages = PagedAttention(
            cfg, self.block_len, self.mode, name="attn")(
                h, k_pages, v_pages, block_tables, positions, write_pos,
                kv_mask_len)
        x = x + h
        h = _norm(cfg)(x)
        h = mlp_cls(cfg, name="mlp")(h)
        return x + h, k_pages, v_pages


class PagedEncoder(nn.Module):
    """Layer stack threading the page pool — a TUPLE of per-layer
    ``[n_blocks, block_len, KV, D]`` arrays, NOT one stacked array: each
    layer's scatter then updates only its own pool leaf, which XLA turns
    into an in-place dynamic-update under buffer donation. A stacked pool
    costs a full-stack copy per layer per step (measured 2.3x on the CPU
    A/B)."""

    cfg: TransformerConfig
    block_len: int
    mode: str

    @nn.compact
    def __call__(self, x, k_pages, v_pages, block_tables, positions,
                 write_pos, kv_mask_len):
        cfg = self.cfg
        k_out, v_out = list(k_pages), list(v_pages)
        for i in range(cfg.n_layers):
            x, k_out[i], v_out[i] = PagedBlock(cfg, self.block_len, self.mode,
                                               name=f"layer_{i}")(
                x, k_pages[i], v_pages[i], block_tables, positions,
                write_pos, kv_mask_len)
        return _norm(cfg)(x), tuple(k_out), tuple(v_out)


class PagedLlamaLM(nn.Module):
    """[B,T] ids -> ([B,T,V] logits, updated page pool). ``k_pages`` /
    ``v_pages`` are tuples of per-layer ``[n_blocks, block_len, KV, D]``
    arrays. Same param pytree as :class:`LlamaLM` — one checkpoint drives
    both engines."""

    cfg: TransformerConfig
    block_len: int
    mode: str = "decode"

    @nn.compact
    def __call__(self, input_ids, k_pages, v_pages, block_tables, positions,
                 write_pos, kv_mask_len):
        cfg = self.cfg
        if cfg.norm_position != "pre" or cfg.learned_pos:
            raise ValueError("the paged engine supports pre-norm RoPE/causal "
                             "decoder configs (the Llama family)")
        x = nn.Embed(cfg.vocab_size, cfg.hidden, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype,
                     embedding_init=nn.with_logical_partitioning(
                         nn.initializers.normal(0.02), ("vocab", "embed")),
                     name="embed")(input_ids)
        x, k_pages, v_pages = PagedEncoder(
            cfg, self.block_len, self.mode, name="decoder")(
                x, k_pages, v_pages, block_tables, positions, write_pos,
                kv_mask_len)
        logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=jnp.float32,
                          param_dtype=cfg.param_dtype,
                          kernel_init=nn.with_logical_partitioning(
                              nn.initializers.normal(0.02), ("embed", "vocab")),
                          name="lm_head")(x)
        return logits, k_pages, v_pages


def paged_prefill(cfg: TransformerConfig, block_len: int, params,
                  prompt_ids: jax.Array, prompt_mask: jax.Array,
                  block_tables: jax.Array, k_pages: jax.Array,
                  v_pages: jax.Array):
    """Prompt -> (last-real-token logits [B,V], updated pages).

    ``prompt_ids``/``prompt_mask``: [B,P] right-padded to a seq-ladder
    bucket; real token t writes K/V into page ``block_tables[b, t//bl]``
    slot ``t%bl`` (pads go to the trash page), so each sequence's pages hold
    its dense logical token stream with no pad holes."""
    B, P = prompt_ids.shape
    t_idx = jnp.broadcast_to(jnp.arange(P)[None, :], (B, P))
    write_pos = jnp.where(prompt_mask > 0, t_idx, -1)
    lengths = jnp.sum(prompt_mask.astype(jnp.int32), axis=-1)
    model = PagedLlamaLM(cfg, block_len, mode="prefill")
    logits, k_pages, v_pages = model.apply(
        {"params": params}, prompt_ids, k_pages, v_pages, block_tables,
        t_idx, write_pos, lengths)
    last = jnp.take_along_axis(
        logits, jnp.maximum(lengths - 1, 0)[:, None, None], axis=1)[:, 0]
    return last, k_pages, v_pages


def paged_decode_step(cfg: TransformerConfig, block_len: int, params,
                      tokens: jax.Array, seq_lens: jax.Array,
                      active: jax.Array, block_tables: jax.Array,
                      k_pages: jax.Array, v_pages: jax.Array):
    """One token per active slot -> (logits [S,V], updated pages).

    ``tokens``: [S] current token per slot; ``seq_lens``: [S] tokens already
    in the sequence BEFORE this one (= this token's logical position);
    ``active``: [S] bool — padded slots write to the trash page and produce
    garbage logits the scheduler ignores."""
    S = tokens.shape[0]
    positions = seq_lens[:, None].astype(jnp.int32)
    write_pos = jnp.where(active[:, None], positions, -1)
    kv_mask_len = jnp.where(active, seq_lens + 1, 1)
    model = PagedLlamaLM(cfg, block_len, mode="decode")
    logits, k_pages, v_pages = model.apply(
        {"params": params}, tokens[:, None], k_pages, v_pages, block_tables,
        positions, write_pos, kv_mask_len)
    return logits[:, 0], k_pages, v_pages


def paged_extend(cfg: TransformerConfig, block_len: int, params,
                 suffix_ids: jax.Array, suffix_mask: jax.Array,
                 start_pos: jax.Array, block_tables: jax.Array,
                 k_pages: jax.Array, v_pages: jax.Array):
    """Suffix prefill over a PREFIX-CACHED sequence -> (last-real logits
    [B,V], updated pages).

    ``suffix_ids``/``suffix_mask``: [B,Q] right-padded uncached tail of the
    prompt; ``start_pos``: [B] logical position of the suffix's first token
    (= tokens already resident in the sequence's pages from the prefix
    cache). Runs in decode mode so every suffix token attends over the
    POOLED prefix K/V through the block table; the per-token ``kv_mask_len``
    horizon keeps the window causal while the prompt-style ``write_pos``
    lands each real suffix token in its page slot."""
    B, Q = suffix_ids.shape
    t_idx = jnp.broadcast_to(jnp.arange(Q)[None, :], (B, Q))
    positions = start_pos[:, None].astype(jnp.int32) + t_idx
    write_pos = jnp.where(suffix_mask > 0, positions, -1)
    kv_mask_len = jnp.where(suffix_mask > 0, positions + 1, 1)
    lengths = jnp.sum(suffix_mask.astype(jnp.int32), axis=-1)
    model = PagedLlamaLM(cfg, block_len, mode="decode")
    logits, k_pages, v_pages = model.apply(
        {"params": params}, suffix_ids, k_pages, v_pages, block_tables,
        positions, write_pos, kv_mask_len)
    last = jnp.take_along_axis(
        logits, jnp.maximum(lengths - 1, 0)[:, None, None], axis=1)[:, 0]
    return last, k_pages, v_pages


def paged_verify(cfg: TransformerConfig, block_len: int, params,
                 tokens: jax.Array, seq_lens: jax.Array, active: jax.Array,
                 block_tables: jax.Array, k_pages: jax.Array,
                 v_pages: jax.Array):
    """Speculative verify window -> (logits [S,W,V], updated pages).

    ``tokens``: [S,W] per slot — the last committed token followed by W-1
    draft tokens; ``seq_lens``: [S] tokens already in the pages BEFORE this
    window (= the first window token's logical position); ``active``: [S].
    One forward scores every draft position (logits[s,t] predicts the token
    AFTER tokens[s,t]); rejected drafts' page writes sit past the sequence's
    committed ``tokens_in_pages`` and are overwritten by later steps, so no
    rollback scatter is needed."""
    S, W = tokens.shape
    t_idx = jnp.broadcast_to(jnp.arange(W)[None, :], (S, W))
    positions = seq_lens[:, None].astype(jnp.int32) + t_idx
    write_pos = jnp.where(active[:, None], positions, -1)
    kv_mask_len = jnp.where(active[:, None], positions + 1, 1)
    model = PagedLlamaLM(cfg, block_len, mode="decode")
    logits, k_pages, v_pages = model.apply(
        {"params": params}, tokens, k_pages, v_pages, block_tables,
        positions, write_pos, kv_mask_len)
    return logits, k_pages, v_pages


def early_exit_params(params, n_layers: int):
    """Host-side subset of a ``LlamaLM``/``PagedLlamaLM`` param tree for an
    EARLY-EXIT draft model: keeps ``embed``, ``lm_head``, the decoder's
    final norm (``RMSNorm_0``) and only ``layer_i`` for ``i < n_layers``.
    Applying the paged modules with ``dataclasses.replace(cfg,
    n_layers=n_layers)`` over this subset is the self-draft forward — no
    second checkpoint, no re-init."""
    dec = params["decoder"]
    sub = {}
    for k, v in dec.items():
        if k.startswith("layer_"):
            if int(k.split("_", 1)[1]) < n_layers:
                sub[k] = v
        else:
            sub[k] = v
    out = {k: v for k, v in params.items() if k != "decoder"}
    out["decoder"] = sub
    return out
