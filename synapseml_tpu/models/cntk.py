"""CNTKModel — the reference's legacy CNTK scoring transformer, kept as a
first-class migration surface.

Reference: ``deep-learning/src/main/python/synapse/ml/cntk/CNTKModel.py``
(a feedDict/fetchDict scoring wrapper over the JVM CNTK evaluator; CNTK
itself has been archived upstream since 2019). The TPU rebuild keeps the
class and its param surface (``location`` + feed/fetch dicts + minibatching)
but evaluates through the XLA inference path: CNTK's own exporter emits ONNX
(``cntk.Function.save(..., format=ONNX)`` was the supported interchange
route), so a ``CNTKModel`` is an :class:`~synapseml_tpu.onnx.ONNXModel` over
the ONNX-exported graph — same feed/fetch semantics, one jitted executable
per shape signature instead of a per-partition native CNTK session.

Models still in the native ``.model``/``.dnn`` CNTK v2 format must be
exported to ONNX once (with the archived cntk package or its model-zoo
conversions); the error message on a non-ONNX payload says exactly that.
"""

from __future__ import annotations

from ..onnx.model import ONNXModel

__all__ = ["CNTKModel"]


class CNTKModel(ONNXModel):
    """(ref ``cntk/CNTKModel.py``; scoring semantics of ``_CNTKModel``)

    Same surface as the reference: ``set_model_location(path)`` /
    ``set_feed_dict`` / ``set_fetch_dict`` (snake_case here), minibatched
    transform. The payload must be ONNX — CNTK's interchange format.
    """

    feature_name = "cntk"

    def __init__(self, model_bytes: bytes | None = None, location: str | None = None,
                 **kw):
        super().__init__(model_bytes=model_bytes, **kw)
        if location is not None:
            self.set_model_location(location)

    def set_model_location(self, path: str) -> "CNTKModel":
        with open(path, "rb") as f:
            payload = f.read()
        # CNTK v2 native checkpoints are a different protobuf (Dictionary
        # serialization) — catch them up front with a migration hint instead
        # of a deep parse error inside the ONNX decoder
        if payload[:4] == b"CNTK" or path.endswith((".dnn", ".cntk")):
            raise ValueError(
                f"{path!r} looks like a native CNTK v2 checkpoint. CNTKModel "
                "evaluates CNTK models through their ONNX interchange form — "
                "export once with the cntk package "
                "(model.save(path, format=cntk.ModelFormat.ONNX)) and point "
                "set_model_location at the exported file.")
        return self.set(model_payload=payload)

    # the reference exposes camelCase setters through codegen; keep the two
    # dict setters as conveniences mirroring CNTKModel.setFeedDict/setFetchDict
    def set_feed_dict(self, mapping_or_key, value=None) -> "CNTKModel":
        if value is not None:  # setFeedDict(modelInput, col) short form
            mapping_or_key = {mapping_or_key: value}
        return self.set(feed_dict=dict(mapping_or_key))

    def set_fetch_dict(self, mapping_or_key, value=None) -> "CNTKModel":
        if value is not None:  # setFetchDict(outputCol, modelOutput) short form
            mapping_or_key = {mapping_or_key: value}
        return self.set(fetch_dict=dict(mapping_or_key))
