"""GSPMD trainer: the TPU-native replacement for horovod.spark's TorchEstimator.

Reference call stack being replaced (SURVEY.md §3.2): horovod SparkBackend
spawns per-task python workers running pytorch-lightning with ring-allreduce on
gradients. Here: ONE jitted train step over the named mesh — the batch is
sharded on ('data','fsdp'), params on fsdp/tensor axes per logical rules, and
XLA inserts the gradient reductions (ICI psum) that horovod/NCCL did by hand.

Also covers the reference's fine-tuning semantics:
  * layer freezing (``LitDeepTextModel._fine_tune_layers:120``) via an optax
    masked transform over param-path predicates,
  * gradient accumulation (horovod ``backward_passes_per_step``) via
    optax.MultiSteps,
  * checkpoint/resume via parallel.checkpoint.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Iterator

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..parallel.mesh import MeshContext, logical_axis_rules

__all__ = ["TrainerConfig", "Trainer", "cross_entropy_loss", "TrainState",
           "NonFiniteLossError",
           "fit_source", "fit_arrays", "fit_gang_source",
           # horizontally fused training arrays (HFTA): N hyperparameter
           # trials inside ONE jitted step — implementation lives in
           # .fused_trainer (kept importable from here; the module split
           # lets the no-inline-jit static check cover the fused step)
           "FusedTrainer", "fused_fit_source", "fused_fit_arrays"]


def __getattr__(name):  # PEP 562: lazy, avoids a circular import at load
    if name in ("FusedTrainer", "fused_fit_source", "fused_fit_arrays",
                "FUSED_OPT_HPARAMS", "FUSED_LOSS_HPARAMS"):
        from . import fused_trainer

        return getattr(fused_trainer, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array
    batch_stats: Any | None = None

    def as_dict(self) -> dict:
        d = {"params": self.params, "opt_state": self.opt_state, "step": self.step}
        if self.batch_stats is not None:
            d["batch_stats"] = self.batch_stats
        return d


@dataclasses.dataclass
class TrainerConfig:
    learning_rate: float = 1e-4
    weight_decay: float = 0.01
    warmup_steps: int = 0
    total_steps: int = 1000
    grad_clip: float = 1.0
    grad_accum: int = 1
    freeze_predicate: Callable[[tuple[str, ...]], bool] | None = None  # True -> frozen
    lr_schedule: str = "constant"  # constant | cosine | linear
    b1: float = 0.9
    b2: float = 0.999
    # weight on the switch-MoE load-balance aux loss (sown by MoEBlock as
    # intermediates/moe_aux_loss); only consulted when the module's config
    # has moe_experts > 0
    moe_aux_weight: float = 0.01
    # declarative sharding (parallel.partition.PartitionRules): regex
    # param-path rules place params AND optimizer state on the mesh —
    # plain pytrees need no nn.Partitioned metadata. zero_shard=True adds
    # ZeRO weight-update sharding: optimizer state partitions over the
    # table's zero_axes replica group inside the one jitted step
    # (arXiv:2004.13336), cutting per-replica opt-state memory to ~1/dp.
    partition_rules: Any | None = None
    zero_shard: bool = False
    # non-finite loss guard: every loss value materialized host-side by the
    # fit loops is checked; non-finite steps count into
    # synapseml_train_nonfinite_total and the last finite step lands on the
    # synapseml_train_last_finite_step gauge (the supervisor's rewind
    # trigger is a metric read, not a log grep). "count" only observes;
    # "raise" aborts the fit with NonFiniteLossError naming the poisoned
    # step — what continual.TrainSupervisor rewinds on.
    nonfinite_action: str = "count"  # count | raise


_GUARD_METRICS = None  # lazy obs.HandleCache for the non-finite guard


class NonFiniteLossError(RuntimeError):
    """The fit loop saw a non-finite loss at ``step`` (the optimizer step
    the poisoned batch trained). ``last_finite_step`` is the newest step
    whose loss was still finite — rewind past the window between them."""

    def __init__(self, step: int, last_finite_step: int):
        super().__init__(
            f"non-finite loss at step {step} (last finite step: "
            f"{last_finite_step}) — rewind to a checkpoint at or before "
            f"{last_finite_step} and skip the offending batch window")
        self.step = int(step)
        self.last_finite_step = int(last_finite_step)


def _graft_params(boxed, values):
    """Replace the values inside a (possibly nn.Partitioned-boxed) init tree
    with pretrained host arrays, keeping the partitioning metadata. Every
    module param must exist in ``values`` with a matching shape."""
    from flax.core import meta

    flat_vals = {"/".join(str(getattr(k, "key", k)) for k in path): v
                 for path, v in jax.tree_util.tree_flatten_with_path(values)[0]}
    used = set()

    def pick(path, x):
        key = "/".join(str(getattr(k, "key", k)) for k in path)
        if key not in flat_vals:
            raise KeyError(f"pretrained params missing {key!r}; has "
                           f"{sorted(flat_vals)[:8]}...")
        used.add(key)
        v = np.asarray(flat_vals[key])
        target = x.value if isinstance(x, meta.Partitioned) else x
        if tuple(v.shape) != tuple(np.shape(target)):
            raise ValueError(f"shape mismatch for {key!r}: checkpoint "
                             f"{v.shape} vs module {np.shape(target)}")
        v = v.astype(np.asarray(target).dtype)
        return x.replace_boxed(v) if isinstance(x, meta.Partitioned) else v

    out = jax.tree_util.tree_map_with_path(
        pick, boxed, is_leaf=lambda x: isinstance(x, meta.Partitioned))
    unused = set(flat_vals) - used
    if unused:
        raise ValueError(f"checkpoint keys not consumed by the module: "
                         f"{sorted(unused)[:8]}... — key map out of sync")
    return out


def cross_entropy_loss(logits: jax.Array, labels: jax.Array,
                       mask: jax.Array | None = None) -> jax.Array:
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    if mask is not None:
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(nll)


def _make_schedule(cfg: TrainerConfig):
    if cfg.lr_schedule == "cosine":
        return optax.warmup_cosine_decay_schedule(
            0.0, cfg.learning_rate, max(cfg.warmup_steps, 1), max(cfg.total_steps, 2))
    if cfg.lr_schedule == "linear":
        return optax.join_schedules(
            [optax.linear_schedule(0.0, cfg.learning_rate, max(cfg.warmup_steps, 1)),
             optax.linear_schedule(cfg.learning_rate, 0.0,
                                   max(cfg.total_steps - cfg.warmup_steps, 1))],
            [cfg.warmup_steps])
    return cfg.learning_rate


def _align_restored(fresh, got, path: str):
    """Yield restored leaves in ``fresh``'s flatten order (jax sorts dict
    keys; sequences are positional), matching dict children BY KEY so a
    serialized container whose iteration order differs from the live
    state's flatten order cannot silently swap same-shaped leaves.
    Validates container kinds and leaf shapes, with the failing path in
    every error."""
    if isinstance(fresh, dict):
        if not isinstance(got, dict):
            raise ValueError(f"{path}: expected a dict, restored "
                             f"{type(got).__name__}")
        if set(got) != set(fresh):
            missing = sorted(set(fresh) - set(got))
            extra = sorted(set(got) - set(fresh))
            raise ValueError(f"{path}: restored dict keys differ "
                             f"(missing {missing}, extra {extra})")
        for k in sorted(fresh):  # jax.tree flatten order for dicts
            yield from _align_restored(fresh[k], got[k], f"{path}[{k!r}]")
    elif isinstance(fresh, (list, tuple)):  # incl. optax NamedTuple states
        if not isinstance(got, (list, tuple)):
            raise ValueError(f"{path}: expected a sequence, restored "
                             f"{type(got).__name__}")
        if len(got) != len(fresh):
            raise ValueError(
                f"{path}: restored sequence has {len(got)} children but "
                f"this optimizer expects {len(fresh)} — optimizer config "
                "changed since the checkpoint was written")
        names = getattr(type(fresh), "_fields", None)
        for i, (f, g) in enumerate(zip(fresh, got)):
            label = names[i] if names else i
            yield from _align_restored(f, g, f"{path}.{label}")
    elif fresh is None:
        if got is not None:
            raise ValueError(f"{path}: expected an empty node, restored "
                             f"{type(got).__name__}")
    else:  # leaf: ShapeDtypeStruct from eval_shape
        if tuple(np.shape(got)) != tuple(fresh.shape):
            raise ValueError(
                f"{path}: restored leaf shape {np.shape(got)} != expected "
                f"{tuple(fresh.shape)} — params/optimizer mismatch with "
                "the checkpoint")
        yield got


def _make_optimizer(cfg: TrainerConfig, params) -> optax.GradientTransformation:
    tx = optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip),
        optax.adamw(_make_schedule(cfg), b1=cfg.b1, b2=cfg.b2,
                    weight_decay=cfg.weight_decay),
    )
    if cfg.freeze_predicate is not None:
        def label_tree(p):
            return jax.tree_util.tree_map_with_path(
                lambda path, _: "frozen" if cfg.freeze_predicate(
                    tuple(getattr(k, "key", str(k)) for k in path)) else "train", p)

        tx = optax.multi_transform({"train": tx, "frozen": optax.set_to_zero()},
                                   label_tree(params))
    if cfg.grad_accum > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=cfg.grad_accum)
    return tx


class Trainer:
    """Owns: param init on-mesh, the jitted train step, and the epoch loop."""

    def __init__(self, module: nn.Module, mesh_ctx: MeshContext, cfg: TrainerConfig,
                 loss_fn: Callable[[Any, dict], jax.Array] | None = None,
                 has_batch_stats: bool = False, rules=None):
        self.module = module
        self.mesh = mesh_ctx
        self.cfg = cfg
        self.has_batch_stats = has_batch_stats
        self.rules = rules or logical_axis_rules()
        self._loss_fn = loss_fn
        self._train_step = None
        self._metrics: list[dict] = []
        # newest optimizer step whose loss was finite (post-step numbering,
        # comparable to checkpoint step numbers); -1 until the first loss
        # lands. Mirrored on the synapseml_train_last_finite_step gauge so
        # the rewind trigger is a metric read.
        self.last_finite_step: int = -1

    # ---- sharding helpers ----
    def _unbox_with_sharding(self, tree):
        """nn.Partitioned leaves -> device arrays placed by logical rules."""
        from ..parallel.mesh import shard_params

        return shard_params(tree, self.mesh, self.rules)

    def _rule_place_params(self, params):
        """Declarative placement: the cfg's regex rule table
        (``parallel.partition.PartitionRules``) maps param paths to mesh
        specs — plain pytrees (convert_hf checkpoints, module inits whose
        metadata the logical rules replicated) get real placement. Also
        records the sharding pytree the jitted step constrains against."""
        from ..parallel import partition as pp

        rules = self.cfg.partition_rules
        if rules is None:
            self._param_shardings = None
            return params
        specs = pp.match_partition_rules(rules, params)
        self._param_shardings = pp.tree_shardings(self.mesh, specs, params)
        return pp.place_tree(params, self._param_shardings)

    def _rule_place_opt_state(self, params, opt_state):
        """Optimizer-state placement from the SAME rule table (optax state
        paths embed the param names), plus the ZeRO weight-update sharding
        over the replica axes when ``cfg.zero_shard`` — per-replica
        optimizer memory drops to ~1/dp while the step stays ONE jitted
        program (the constraint in ``_step_fn`` keeps every update
        sharded)."""
        from ..parallel import partition as pp

        rules = self.cfg.partition_rules
        if rules is None:
            self._opt_shardings = None
            return opt_state
        skel = jax.eval_shape(lambda: opt_state)
        specs = pp.opt_state_specs(rules, skel, self.mesh,
                                   zero=self.cfg.zero_shard)
        self._opt_shardings = pp.tree_shardings(self.mesh, specs, skel)
        placed = pp.place_tree(opt_state, self._opt_shardings)
        pp.emit_shard_metrics(params, placed, self.mesh)
        return placed

    def checkpoint_sharding_fn(self):
        """Path-aware ``sharding_fn`` for ``restore_checkpoint``: leaves
        restore DIRECTLY onto their rule-table placement (each device
        receives only its shard slices — no device-resident full copy).
        None when the trainer has no rule table (host-numpy restore)."""
        from ..parallel import partition as pp

        if self.cfg.partition_rules is None:
            return None
        return pp.checkpoint_sharding_fn(self.cfg.partition_rules,
                                         self.mesh,
                                         zero=self.cfg.zero_shard)

    def sharding_manifest(self) -> dict | None:
        """The serializable ``sharding`` section (rule table + mesh) that
        checkpoints and registry manifests carry for round-trips."""
        import dataclasses as dc

        from ..parallel import partition as pp

        rules = self.cfg.partition_rules
        if rules is None:
            return None
        if rules.mesh is None:
            rules = dc.replace(rules, mesh=self.mesh.config)
        return pp.sharding_manifest_section(rules)

    def ensure_optimizer(self, params) -> None:
        """(Re)build the optax transform for externally restored params —
        the checkpoint-resume path that skips init_state."""
        self._tx = _make_optimizer(self.cfg, params)

    def resume_state(self, params, opt_state=None, step: int = 0,
                     batch_stats=None) -> TrainState:
        """Build a TrainState from restored host/device pytrees (see
        parallel.checkpoint.restore_checkpoint) without re-initializing.

        A serialized ``opt_state`` comes back as plain tuples/dicts (the
        npz round-trip keeps order but not optax's NamedTuple node types);
        its leaves are matched STRUCTURALLY against a freshly initialized
        optimizer skeleton — dict children by key (order-insensitive, so a
        dict whose serialized order differs from jax's sorted flatten order
        cannot silently swap same-shaped leaves like Adam's mu/nu),
        sequence children by position — then poured into the skeleton so
        optax transforms see their own state classes again.

        With ``cfg.partition_rules`` set, the restored leaves are placed
        by the rule table (params sharded, optimizer state ZeRO-sharded
        when enabled) — a replicated checkpoint restores ONTO the sharded
        mesh with each device receiving only its shard slices, instead of
        the old host-first full-leaf device_put."""
        self.ensure_optimizer(params)
        params = self._rule_place_params(params)
        if opt_state is None:
            opt_state = self._tx.init(params)
        else:
            # eval_shape: the reference structure/shapes with ZERO allocation
            # (a real init would materialize ~2x-param Adam moments just to
            # throw them away — an OOM risk on 7B-class resumes)
            fresh = jax.eval_shape(self._tx.init, params)
            _, treedef = jax.tree.flatten(fresh)
            opt_state = jax.tree.unflatten(
                treedef, list(_align_restored(fresh, opt_state, "opt_state")))
        opt_state = self._rule_place_opt_state(params, opt_state)
        return TrainState(params=params, opt_state=opt_state,
                          step=jnp.asarray(step, jnp.int32), batch_stats=batch_stats)

    def init_state(self, example_batch: dict, rng: jax.Array | None = None,
                   init_params=None, init_batch_stats=None) -> TrainState:
        """Fresh state; ``init_params`` (host pytree, e.g. from
        models.convert_hf) grafts pretrained values into the module's
        Partitioned boxes so they inherit the logical shardings — the
        transfer-learning entry the reference gets from HF/torchvision
        ``from_pretrained``."""
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        inputs = self._model_inputs(example_batch)
        with self.mesh.mesh:
            variables = self.module.init(rng, **inputs)
        boxed = variables["params"]
        if init_params is not None:
            boxed = _graft_params(boxed, init_params)
        params = self._unbox_with_sharding(boxed)
        params = self._rule_place_params(params)
        batch_stats = None
        if self.has_batch_stats and "batch_stats" in variables:
            batch_stats = self._unbox_with_sharding(
                _graft_params(variables["batch_stats"], init_batch_stats)
                if init_batch_stats is not None else variables["batch_stats"])
        tx = _make_optimizer(self.cfg, params)
        self._tx = tx
        opt_state = self._rule_place_opt_state(params, tx.init(params))
        return TrainState(params=params, opt_state=opt_state,
                          step=jnp.zeros((), jnp.int32), batch_stats=batch_stats)

    def _model_inputs(self, batch: dict) -> dict:
        drop = {"labels", "label", "mask", "_valid"}
        return {k: v for k, v in batch.items() if k not in drop}

    @property
    def _has_moe(self) -> bool:
        return getattr(getattr(self.module, "cfg", None), "moe_experts", 0) > 0

    def default_loss(self, variables, batch, train: bool):
        kwargs = dict(self._model_inputs(batch))
        mutable = []
        if self.has_batch_stats:
            kwargs["train"] = train
            mutable = ["batch_stats"] if train else []
        if train and self._has_moe:
            # collect the sown switch load-balance terms — without this the
            # router trains with zero balancing pressure and can collapse
            # every token onto one expert
            mutable = list(mutable) + ["intermediates"]
        if mutable:
            logits, new_vars = self.module.apply(variables, mutable=mutable, **kwargs)
        else:
            logits, new_vars = self.module.apply(variables, **kwargs), {}
        labels = batch.get("labels", batch.get("label"))
        loss = cross_entropy_loss(logits, labels, batch.get("_valid"))
        inter = new_vars.get("intermediates") if isinstance(new_vars, dict) else None
        if inter:
            aux_terms = [jnp.mean(jnp.asarray(v)) for path, v
                         in jax.tree_util.tree_flatten_with_path(inter)[0]
                         if any("moe_aux_loss" in str(getattr(k, "key", k))
                                for k in path)]
            if aux_terms:
                loss = loss + self.cfg.moe_aux_weight * (
                    sum(aux_terms) / len(aux_terms))
            new_vars = {k: v for k, v in new_vars.items()
                        if k != "intermediates"}
        return loss, (logits, new_vars)

    # ---- the jitted step ----
    def _step_fn(self):
        if not hasattr(self, "_tx"):
            raise RuntimeError("optimizer not built: call init_state() for a fresh "
                               "run or resume_state() after restore_checkpoint()")
        tx = self._tx
        # rule-table shardings captured INTO the jitted step: the constraint
        # keeps every new param/opt-state value on its declared placement —
        # this is where the ZeRO weight update happens (XLA partitions the
        # moment updates across the replica group instead of replicating)
        param_sh = getattr(self, "_param_shardings", None)
        opt_sh = getattr(self, "_opt_shardings", None)

        def step_fn(state: dict, batch: dict) -> tuple[dict, dict]:
            def loss_of(params):
                variables = {"params": params}
                if state.get("batch_stats") is not None:
                    variables["batch_stats"] = state["batch_stats"]
                if self._loss_fn is not None:
                    loss = self._loss_fn(variables, batch)
                    return loss, (None, {})
                return self.default_loss(variables, batch, train=True)

            (loss, (_, new_vars)), grads = jax.value_and_grad(loss_of, has_aux=True)(
                state["params"])
            updates, new_opt = tx.update(grads, state["opt_state"], state["params"])
            new_params = optax.apply_updates(state["params"], updates)
            if param_sh is not None:
                new_params = jax.lax.with_sharding_constraint(
                    new_params, param_sh)
            if opt_sh is not None:
                new_opt = jax.lax.with_sharding_constraint(new_opt, opt_sh)
            new_state = {"params": new_params, "opt_state": new_opt,
                         "step": state["step"] + 1}
            if state.get("batch_stats") is not None:
                new_state["batch_stats"] = new_vars.get("batch_stats", state["batch_stats"])
            else:
                new_state["batch_stats"] = None
            metrics = {"loss": loss.astype(jnp.float32),
                       "grad_norm": optax.global_norm(grads).astype(jnp.float32)}
            return new_state, metrics

        return step_fn

    def train_step(self, state: TrainState, batch: dict) -> tuple[TrainState, dict]:
        if self._train_step is None:
            self._train_step = jax.jit(self._step_fn(), donate_argnums=(0,))
        placed = self.mesh.shard_batch(batch)
        with self.mesh.mesh:
            sd, metrics = self._train_step(state.as_dict() | {"batch_stats": state.batch_stats},
                                           placed)
        return TrainState(params=sd["params"], opt_state=sd["opt_state"], step=sd["step"],
                          batch_stats=sd.get("batch_stats")), metrics

    # ---- scanned multi-step: K optimizer steps in ONE dispatch ----
    # Host dispatch overhead (and, under a remote tunnel, round-trip latency)
    # disappears: the train loop itself lives on-device as a lax.scan, the
    # TPU-idiomatic replacement for horovod's per-step host-driven loop.
    def train_steps_scan(self, state: TrainState, stacked_batches: dict
                         ) -> tuple[TrainState, dict]:
        """stacked_batches: pytree whose leaves have leading dim K (num steps)."""
        if getattr(self, "_scan_step", None) is None:
            step_fn = self._step_fn()

            def multi(sd: dict, batches: dict):
                return jax.lax.scan(step_fn, sd, batches)

            self._scan_step = jax.jit(multi, donate_argnums=(0,))
        placed = self.mesh.shard_stacked_batch(stacked_batches)
        with self.mesh.mesh:
            sd, metrics = self._scan_step(
                state.as_dict() | {"batch_stats": state.batch_stats}, placed)
        return (TrainState(params=sd["params"], opt_state=sd["opt_state"], step=sd["step"],
                           batch_stats=sd.get("batch_stats")), metrics)

    # ---- non-finite loss guard ----
    @staticmethod
    def _guard_metrics():
        global _GUARD_METRICS
        from ..core import observability as obs

        if _GUARD_METRICS is None:
            _GUARD_METRICS = obs.HandleCache(lambda reg: {
                "nonfinite": reg.counter(
                    "synapseml_train_nonfinite_total",
                    "optimizer steps whose loss was NaN/Inf", ("engine",)),
                "last_finite": reg.gauge(
                    "synapseml_train_last_finite_step",
                    "newest optimizer step with a finite loss"),
            })
        return _GUARD_METRICS.get()

    def _observe_losses(self, losses, last_step: int) -> None:
        """Check host-side per-step losses ending at post-step number
        ``last_step``: advance ``last_finite_step``, count non-finite steps
        into ``synapseml_train_nonfinite_total``, and (under
        ``cfg.nonfinite_action='raise'``) abort with
        :class:`NonFiniteLossError` naming the first poisoned step."""
        arr = np.asarray(losses, dtype=np.float64).reshape(-1)
        if arr.size == 0:
            return
        finite = np.isfinite(arr)
        m = self._guard_metrics()
        if bool(finite.all()):
            self.last_finite_step = max(self.last_finite_step, int(last_step))
        else:
            first_bad = int(np.argmax(~finite))
            bad_step = last_step - arr.size + 1 + first_bad
            if first_bad > 0:
                self.last_finite_step = max(self.last_finite_step,
                                            int(bad_step - 1))
            m["nonfinite"].inc(int((~finite).sum()), engine="trainer")
            if self.cfg.nonfinite_action == "raise":
                m["last_finite"].set(self.last_finite_step)
                raise NonFiniteLossError(bad_step, self.last_finite_step)
        m["last_finite"].set(self.last_finite_step)

    # ---- loop ----
    def _flops_per_token(self, params) -> int:
        n_params = sum(int(np.prod(np.shape(x))) for x in jax.tree.leaves(params))
        return 6 * n_params  # fwd + bwd matmul FLOPs per token estimate

    def fit(self, state: TrainState, batch_iter: Iterator[dict], max_steps: int,
            log_every: int = 50, callback: Callable[[int, dict], None] | None = None,
            scan_chunk: int = 8, checkpointer=None,
            checkpoint_every: int = 0,
            skip_fn: Callable[[int], bool] | None = None,
            gang=None) -> TrainState:
        """Streaming fit over ANY batch iterator.

        Default path: ``scan_chunk`` same-shape batches are stacked into ONE
        ``lax.scan`` dispatch while a background thread prefetches the next
        chunk (double buffering) — the DataFrame/streaming plane gets the same
        dispatch amortization as array training. Odd-shaped or leftover
        batches run per-step automatically, so iterators with varying batch
        shapes stay correct (each shape still compiles once). A per-step
        ``callback`` (or ``scan_chunk<=1``) forces the per-step loop.

        ``checkpointer`` (a ``parallel.AsyncCheckpointer``) +
        ``checkpoint_every``: full train state (params/opt_state/step/
        batch_stats) is snapshotted every N steps and written in the
        checkpointer's background thread — training never stalls on disk.
        The final state is always saved; resume via
        ``restore_checkpoint`` + ``Trainer.resume_state``.

        ``skip_fn(batch_index)`` (batch_index = the global pre-step
        counter, i.e. the ``state.step`` value the batch would train from)
        marks batches to CONSUME BUT NOT TRAIN: the batch is pulled from
        the iterator (keeping the deterministic stream position and the
        checkpointable step↔batch alignment) and ``state.step`` advances
        with params untouched. This is the supervisor's NaN-rewind
        mechanism — skip past a poisoned batch window instead of training
        on it again. Forces the per-step path.

        ``gang`` (a :class:`~synapseml_tpu.parallel.gang.GangWorker`)
        makes this fit a gang member: one heartbeat per optimizer step, a
        verdict poll at every step boundary — a ``resize`` verdict raises
        :class:`~synapseml_tpu.parallel.gang.GangAborted` (a member died;
        exit and resume from the last committed checkpoint), an
        ``abort_and_checkpoint`` verdict runs the emergency-checkpoint
        dance (train to the gang's sync step, force a checkpoint, ack,
        wait for the driver's commit) and raises :class:`~synapseml_tpu.
        parallel.gang.Preempted`. Forces the per-step path.
        """
        it = iter(batch_iter)
        if checkpointer is not None and 0 < checkpoint_every < scan_chunk:
            # checkpoints can only happen between dispatches; honor the
            # requested durability by shrinking the fused chunk
            scan_chunk = checkpoint_every
        ckpt_due = self._ckpt_writer(checkpointer, checkpoint_every)
        if callback is not None or skip_fn is not None or scan_chunk <= 1 \
                or max_steps <= 1 or gang is not None:
            meter = _ThroughputMeter(self, state.params)
            base = int(state.step)
            # per-step host materialization of the loss blocks async
            # dispatch — only the "raise" guard (the supervised continual
            # path, which needs prompt NaN detection for its rewind) pays
            # it; "count" mode samples the losses already pulled at the
            # log windows, keeping the default path's overlap intact
            eager_guard = self.cfg.nonfinite_action == "raise"
            if gang is not None:
                gang.heartbeat(base)  # alive before the first (slow) compile
            sync_at: int | None = None
            i = -1
            for i in range(max_steps):
                try:
                    batch = next(it)  # never pull past max_steps batches
                except StopIteration:
                    i -= 1
                    break
                if skip_fn is not None and skip_fn(base + i):
                    # consumed, not trained: the stream stays aligned with
                    # the step counter, the params stay at the checkpoint
                    state = dataclasses.replace(state,
                                                step=state.step + 1)
                    self._count_skipped()
                    ckpt_due(state, i + 1)
                else:
                    state, metrics = self.train_step(state, batch)
                    meter.observe(batch, steps=1)
                    if eager_guard:
                        self._observe_losses(
                            [float(np.asarray(metrics["loss"]))],
                            last_step=base + i + 1)
                    if callback is not None:
                        callback(i, metrics)
                    if (i + 1) % log_every == 0:
                        lf = float(metrics["loss"])
                        if not eager_guard:
                            self._observe_losses([lf],
                                                 last_step=base + i + 1)
                        self._metrics.append(meter.entry(lf))
                    ckpt_due(state, i + 1)
                if gang is not None:
                    step_now = base + i + 1
                    gang.heartbeat(step_now)
                    if sync_at is None:
                        v = gang.check(step_now)
                        if v == "resize":
                            from ..parallel.gang import GangAborted

                            raise GangAborted(
                                "gang verdict: resize — a member failed; "
                                "exit and resume from the last committed "
                                "checkpoint")
                        if isinstance(v, tuple):  # ("sync", S)
                            sync_at = int(v[1])
                    if sync_at is not None and step_now >= sync_at:
                        # emergency coordinated checkpoint at the gang's
                        # sync step: force the write, flush it, phase-2 ack
                        from ..parallel.gang import GangAborted, Preempted

                        ckpt_due(state, i + 1, final=True)
                        if checkpointer is not None:
                            checkpointer.wait()
                        if checkpointer is not None \
                                and gang.ack_and_wait_commit(step_now):
                            raise Preempted(step_now)
                        raise GangAborted(
                            "emergency checkpoint did not commit inside "
                            "the grace window — resume from the last "
                            "committed step")
            ckpt_due(state, i + 1, final=True)
            return state
        return self._fit_chunked(state, it, max_steps, scan_chunk, log_every,
                                 ckpt_due)

    @staticmethod
    def _count_skipped() -> None:
        from ..core import observability as obs

        obs.get_registry().counter(
            "synapseml_train_skipped_steps_total",
            "batches consumed but not trained (NaN-rewind skip windows)",
            ("engine",)).inc(engine="trainer")

    def _ckpt_writer(self, checkpointer, every: int):
        """Periodic full-state async snapshots (no-op without a checkpointer)."""
        last = [0]

        def due(state: TrainState, steps_done: int, final: bool = False):
            if checkpointer is None or steps_done <= 0:
                return
            if final or (every > 0 and steps_done - last[0] >= every):
                if final and last[0] == steps_done:
                    return  # already saved at exactly this step
                checkpointer.save(state.as_dict(), step=int(state.step))
                last[0] = steps_done

        return due

    def _fit_chunked(self, state: TrainState, it: Iterator[dict],
                     max_steps: int, scan_chunk: int,
                     log_every: int = 50, ckpt_due=None) -> TrainState:
        import queue
        import threading

        END = object()
        q: "queue.Queue" = queue.Queue(maxsize=2)  # double buffer
        stop = threading.Event()  # consumer died: unblock the producer

        def shape_key(b: dict):
            # dtype via attribute lookup: np.asarray on a jax.Array would
            # force a device-to-host copy per batch just to read the dtype
            return tuple(sorted(
                (k, np.shape(v), str(getattr(v, "dtype", None)
                                     or np.asarray(v).dtype))
                for k, v in b.items()))

        def put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.5)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                pending: list[dict] = []
                pkey = None
                taken = 0

                def flush() -> bool:
                    nonlocal pending, pkey
                    if not pending:
                        return True
                    if len(pending) == scan_chunk:
                        item = ("chunk", {k: np.stack([b[k] for b in pending])
                                          for k in pending[0]})
                    else:  # short/odd tail: per-step, no extra scan compile
                        item = ("steps", pending)
                    pending, pkey = [], None
                    return put(item)

                while taken < max_steps:
                    try:
                        b = next(it)
                    except StopIteration:
                        break
                    key = shape_key(b)
                    if pending and key != pkey:
                        if not flush():
                            return
                    pending.append(b)
                    pkey = key
                    taken += 1
                    if len(pending) == scan_chunk:
                        if not flush():
                            return
                if flush():
                    put(END)
            except BaseException as e:  # surface producer errors
                put(e)

        threading.Thread(target=producer, daemon=True).start()
        meter = _ThroughputMeter(self, state.params)
        steps_done = logged_at = 0
        base = int(state.step)
        try:
            while True:
                item = q.get()
                if item is END:
                    break
                if isinstance(item, BaseException):
                    raise item
                kind, payload = item
                if kind == "chunk":
                    state, metrics = self.train_steps_scan(state, payload)
                    meter.observe(payload, steps=scan_chunk)
                    steps_done += scan_chunk
                    losses = np.asarray(metrics["loss"])
                    loss = float(losses[-1])
                else:
                    losses = []
                    for b in payload:
                        state, metrics = self.train_step(state, b)
                        meter.observe(b, steps=1)
                        losses.append(float(np.asarray(metrics["loss"])))
                    steps_done += len(payload)
                    loss = losses[-1]
                self._observe_losses(losses, last_step=base + steps_done)
                if steps_done - logged_at >= log_every or steps_done >= max_steps:
                    self._metrics.append(meter.entry(loss))
                    logged_at = steps_done
                if ckpt_due is not None:
                    ckpt_due(state, steps_done)
            if ckpt_due is not None:
                ckpt_due(state, steps_done, final=True)
        finally:
            stop.set()
        return state

    @property
    def metrics(self) -> list[dict]:
        return self._metrics


class _ThroughputMeter:
    """Shared samples/sec + 6ND TFLOP/s + MFU accounting for both the
    per-step and scan-chunked fit loops. Tokens come from the ``input_ids``
    tensor only — the estimate is meaningless for pixel inputs."""

    def __init__(self, trainer: "Trainer", params):
        from ..core.instrumentation import chip_peak_tflops

        self.t0 = time.perf_counter()
        self.steps = 0
        self.n_samples = 0
        self.n_tokens = 0
        self.flops_per_token = trainer._flops_per_token(params)
        dev = jax.devices()[0]
        self.peak = (chip_peak_tflops(getattr(dev, "device_kind", "") or "")
                     if dev.platform == "tpu" else None)
        self._last_t = self.t0
        self._last_steps = 0

    def observe(self, batch: dict, steps: int) -> None:
        """``batch`` leaves are (B, ...) when steps==1, (K, B, ...) stacked
        when steps==K."""
        self.steps += steps
        first = np.shape(next(iter(batch.values())))
        self.n_samples += int(np.prod(first[: (2 if steps > 1 else 1)]))
        ids = batch.get("input_ids")
        if ids is not None:
            self.n_tokens += int(np.prod(np.shape(ids)))

    def entry(self, loss: float) -> dict:
        dt = time.perf_counter() - self.t0
        out = {"step": self.steps, "loss": loss,
               "samples_per_sec": self.n_samples / dt}
        if self.n_tokens:
            out["model_tflops_per_sec"] = (self.flops_per_token * self.n_tokens
                                           / dt / 1e12)
            if self.peak:
                out["mfu"] = round(out["model_tflops_per_sec"]
                                   / jax.device_count() / self.peak, 4)
        self._export(out)
        return out

    def _export(self, out: dict) -> None:
        """Push each logging window onto the unified metrics plane: the
        window-average step time feeds the step histogram (p50/p95/p99 over
        the whole fit), MFU/throughput land as gauges."""
        from ..core import observability as obs

        now = time.perf_counter()
        dsteps = self.steps - self._last_steps
        reg = obs.get_registry()
        if dsteps > 0:
            reg.histogram(
                "synapseml_train_step_duration_ms",
                "training step (boosting iteration / optimizer step) wall "
                "time", ("engine",),
            ).observe((now - self._last_t) * 1e3 / dsteps, engine="trainer")
        self._last_t, self._last_steps = now, self.steps
        reg.gauge("synapseml_train_samples_per_sec",
                  "fit-loop throughput", ("engine",)
                  ).set(out["samples_per_sec"], engine="trainer")
        if "mfu" in out:
            reg.gauge("synapseml_train_mfu",
                      "model FLOPs utilization vs chip_peak_tflops",
                      ("engine",)).set(out["mfu"], engine="trainer")


def plan_fit(n: int, batch_size: int, epochs: int, max_steps: int) -> tuple[int, int]:
    """(effective batch size, total optimizer steps) for an n-row fit.
    Raises on empty input — shared by the DeepText/DeepVision estimators."""
    if n == 0:
        raise ValueError("cannot fit on an empty DataFrame (0 rows)")
    bs = min(batch_size, n)
    steps_per_epoch = max(n // bs, 1)
    total = max_steps if max_steps > 0 else steps_per_epoch * epochs
    return bs, total


def _fit_with_optional_checkpointing(stage, fit_fn):
    """Run a fit under an AsyncCheckpointer when checkpoint_dir is set
    (reference pytorch-lightning ModelCheckpoint role); fit_fn(ck, every)."""
    ckpt_dir = stage.get("checkpoint_dir")
    if not ckpt_dir:
        return fit_fn(None, 0)
    from ..parallel.checkpoint import AsyncCheckpointer

    with AsyncCheckpointer(ckpt_dir, keep=stage.get("checkpoint_keep")) as ck:
        return fit_fn(ck, stage.get("checkpoint_every"))


class _LoaderCheckpointer:
    """Checkpointer shim that rides the loader's iterator state along with
    every train-state snapshot: the saved tree gains a ``data_iter`` subtree
    (see :mod:`synapseml_tpu.data.state`), so a restore resumes the batch
    stream mid-epoch bit-identically — no replayed, no skipped rows. One
    batch == one ``state.step`` increment, so the step number indexes the
    loader's per-batch snapshots directly."""

    def __init__(self, inner, loader):
        self._inner = inner
        self._loader = loader

    def save(self, tree, step: int):
        snap = self._loader.state_for_batch(int(step))
        if snap is None:
            # never save a checkpoint that LOOKS resumable but would restart
            # the stream from epoch 0 — fit_source sizes the loader's
            # snapshot history off scan_chunk/prefetch so this cannot
            # happen unless that sizing drifts
            raise RuntimeError(
                f"loader state for batch {step} is no longer in the "
                "snapshot history — checkpoint would lose its data_iter "
                "subtree (resume guarantee broken); widen state_history")
        tree = dict(tree)
        tree["data_iter"] = snap.to_tree()
        return self._inner.save(tree, step=step)

    def wait(self):
        return self._inner.wait()

    def close(self):
        return self._inner.close()


def fit_source(trainer: "Trainer", source, *, batch_size: int, total_steps: int,
               seed: int, init_params=None, init_batch_stats=None,
               scan_chunk: int = 8, checkpointer=None, checkpoint_every: int = 0,
               state: "TrainState | None" = None, data_state: dict | None = None,
               epochs: int | None = None, drop_remainder: bool = True,
               shuffle_rows: str = "full", shuffle_window: int = 4096,
               prefetch: int = 2, device_prefetch: bool = False,
               columns: list | None = None, host_index: int = 0,
               host_count: int = 1,
               resume_from: str | None = None,
               skip_fn: Callable[[int], bool] | None = None,
               callback: Callable[[int, dict], None] | None = None
               ) -> "TrainState":
    """Streaming fit over a :class:`synapseml_tpu.data.ShardedSource`.

    The data plane supplies seeded shard + row shuffles, bucket-ladder batch
    shapes, and a bounded-queue background prefetcher; this function adds
    mesh alignment (batches pad to a multiple of the data-parallel size),
    state init from the first batch, and resumable checkpointing — when a
    ``checkpointer`` is given, every snapshot carries the loader's iterator
    state so ``restore_checkpoint`` + ``resume_state`` + ``fit_source(...,
    state=..., data_state=tree["data_iter"])`` continues the exact batch
    stream an uninterrupted run would have produced.

    ``total_steps`` is the TOTAL optimizer-step target: resuming from step N
    runs ``total_steps - N`` further steps. ``device_prefetch`` places the
    next batch on the mesh inside the prefetch thread (double-buffered
    ``jax.device_put``) — only engaged on the per-step path
    (``scan_chunk<=1``); the chunked scan path stacks on host and already
    overlaps assembly with device compute.

    ``host_index``/``host_count`` default to 0/1 — ONE logical stream,
    identical on every process, because ``mesh.shard_batch`` expects each
    process to supply the same global batch (GSPMD splits it). Per-host
    disjoint shard feeding is the ``data.DataLoader``-level feature for
    custom multi-host input pipelines.

    ``resume_from`` (a checkpoint directory) restores the latest completed
    checkpoint THROUGH the trainer's rule-table ``sharding_fn`` — each
    restored leaf device_puts directly onto its declared placement, so a
    replicated checkpoint resumes onto a sharded/ZeRO mesh without any
    host-first full-leaf materialization — and threads the saved
    ``data_iter`` state back into the loader. A directory with no
    completed checkpoint starts fresh."""
    from ..data import DataLoader, IteratorState

    if state is None and resume_from is not None:
        from ..parallel.checkpoint import latest_verified_step
        from ..parallel.checkpoint import restore_checkpoint

        # VERIFIED latest: a torn/corrupted newest checkpoint demotes to
        # the previous completed step instead of resuming garbage params
        last = latest_verified_step(resume_from)
        if last is not None:
            tree = restore_checkpoint(
                resume_from, last,
                sharding_fn=trainer.checkpoint_sharding_fn())
            state = trainer.resume_state(
                tree["params"], tree.get("opt_state"),
                step=int(np.asarray(tree["step"])),
                batch_stats=tree.get("batch_stats"))
            if data_state is None:
                data_state = tree.get("data_iter")
    if checkpointer is not None \
            and getattr(checkpointer, "sharding", None) is None \
            and hasattr(checkpointer, "sharding"):
        # checkpoints carry the rule table + mesh so a restore tool (or a
        # resume on a different topology) knows the intended placement
        checkpointer.sharding = trainer.sharding_manifest()

    dp = trainer.mesh.data_parallel_size()
    done = int(state.step) if state is not None else 0
    remaining = total_steps - done
    if state is not None and remaining <= 0:
        return state
    if state is not None and done > 0 and data_state is None:
        raise ValueError(
            f"resuming from step {done} without data_state= — the loader "
            "would silently restart the stream from epoch 0. Pass "
            "data_state=tree['data_iter'] from the restored checkpoint for "
            "a bit-identical continuation, or data_state='fresh' to "
            "deliberately restart the stream")
    if isinstance(data_state, str):
        if data_state != "fresh":
            raise ValueError(f"data_state must be a restored data_iter "
                             f"tree or 'fresh', got {data_state!r}")
        # fresh stream, but keep the batch counter aligned with state.step
        # so checkpoint snapshots stay addressable by step number
        data_state = IteratorState(seed=int(seed),
                                   batches_emitted=done).to_tree()
    place = trainer.mesh.shard_batch if (device_prefetch and scan_chunk <= 1) \
        else None
    loader = DataLoader(
        source, batch_size, seed=seed, epochs=epochs,
        drop_remainder=drop_remainder, shuffle_rows=shuffle_rows,
        shuffle_window=shuffle_window, multiple_of=dp, prefetch=prefetch,
        place_fn=place, columns=columns,
        # the chunked fit's producer consumes up to ~3 chunks ahead of the
        # checkpointed step; the snapshot ring must outlive that lag or
        # saves lose their data_iter subtree
        state_history=max(64, 3 * max(scan_chunk, 1) + prefetch + 8),
        host_index=host_index, host_count=host_count,
        state=IteratorState.from_tree(data_state) if data_state is not None
        else None)
    it = iter(loader)
    try:
        if state is None:
            first = next(it)
            state = trainer.init_state(first, jax.random.PRNGKey(seed),
                                       init_params=init_params,
                                       init_batch_stats=init_batch_stats)

            def chain():
                yield first
                yield from it

            batch_iter: Iterator[dict] = chain()
        else:
            batch_iter = it
        ck = _LoaderCheckpointer(checkpointer, loader) \
            if checkpointer is not None else None
        return trainer.fit(state, batch_iter, max_steps=remaining,
                           scan_chunk=scan_chunk, checkpointer=ck,
                           checkpoint_every=checkpoint_every,
                           skip_fn=skip_fn, callback=callback)
    finally:
        loader.close()


class _ElasticLoaderCheckpointer:
    """The gang-mode counterpart of :class:`_LoaderCheckpointer`: every
    snapshot carries THIS host's per-stream cursors (an
    ``ElasticStreamSet.state_for_batch`` dict keyed by virtual-stream id);
    the multi-host :class:`~synapseml_tpu.parallel.AsyncCheckpointer`
    moves that subtree into the per-host shard payload, so the union of
    all ranks' shards always covers every stream of the
    :class:`~synapseml_tpu.data.ElasticPlan`."""

    def __init__(self, inner, stream, base_step: int):
        self._inner = inner
        self._stream = stream
        self._base = int(base_step)

    def save(self, tree, step: int):
        snap = self._stream.state_for_batch(int(step) - self._base)
        if snap is None:
            raise RuntimeError(
                f"elastic stream state for batch {int(step) - self._base} "
                f"(checkpoint step {step}) is no longer in the snapshot "
                "history — widen state_history")
        tree = dict(tree)
        tree["data_iter"] = snap
        return self._inner.save(tree, step=step)

    def wait(self):
        return self._inner.wait()

    def close(self):
        return self._inner.close()


def fit_gang_source(trainer: "Trainer", source, *, batch_size: int,
                    total_steps: int, seed: int, gang, checkpoint_dir: str,
                    rank: int, world: int, checkpoint_every: int = 10,
                    epochs: int | None = None,
                    drop_remainder: bool = True, shuffle_rows: str = "full",
                    shuffle_window: int = 4096, columns: list | None = None,
                    init_params=None, log_every: int = 50,
                    callback: Callable[[int, dict], None] | None = None
                    ) -> "TrainState":
    """One gang member's preemption-tolerant streaming fit.

    The elastic counterpart of :func:`fit_source`: the run is
    ``orig_world`` frozen virtual streams (an
    :class:`~synapseml_tpu.data.ElasticPlan`); this host serves the
    streams the plan assigns to ``rank`` of ``world`` and trains with the
    gang seams live — per-step heartbeats, verdict polling, coordinated
    per-host shard checkpoints every ``checkpoint_every`` steps. The
    DRIVER commits once every rank's ACK lands and owns the keep-last-K
    verified retention (``GangCoordinator(keep=...)``) — workers never
    commit or prune, so a lone survivor can't publish or destroy a
    world-N checkpoint on its own. A commit needs EVERY rank's ACK, so a
    finite-``epochs`` run whose streams exhaust a rank before
    ``total_steps`` stops committing at that rank's last ACK (a
    structured warning fires; size ``total_steps`` to the dataset or use
    the default ``epochs=None`` infinite cycling).

    On entry the checkpoint dir decides everything: a committed checkpoint
    ⇒ **N→M elastic resume** — the global tree reassembles from the N
    shards, params/optimizer state re-place via the trainer's rule table,
    and every virtual stream continues from its committed cursor (zero
    replayed, zero skipped rows — ``world`` may differ from the world that
    wrote the checkpoint); an empty dir ⇒ fresh start with
    ``orig_world = world``.

    Raises :class:`~synapseml_tpu.parallel.gang.Preempted` (exit
    ``EXIT_PREEMPTED``: an emergency checkpoint committed) or
    :class:`~synapseml_tpu.parallel.gang.GangAborted` (exit
    ``EXIT_RESIZE``: resume from the last commit)."""
    from ..data import ElasticPlan, ElasticStreamSet
    from ..parallel.checkpoint import AsyncCheckpointer
    from ..parallel.gang import elastic_restore

    resume = elastic_restore(checkpoint_dir)
    if resume is not None:
        if resume.plan is None:
            raise ValueError(
                f"checkpoint dir {checkpoint_dir} holds a single-host "
                "checkpoint — fit_gang_source resumes only coordinated "
                "(per-host shard) checkpoints; use fit_source(resume_from=)")
        plan = resume.plan
        done = resume.step
        tree = resume.tree
        state = trainer.resume_state(
            tree["params"], tree.get("opt_state"),
            step=int(np.asarray(tree["step"])),
            batch_stats=tree.get("batch_stats"))
    else:
        plan = ElasticPlan.fresh(world, seed)
        done, state = 0, None
    if world > plan.orig_world:
        raise ValueError(
            f"world={world} exceeds the run's frozen stream count "
            f"(orig_world={plan.orig_world}): extra hosts would have no "
            "virtual stream to serve and no shard to ACK, wedging every "
            "commit — relaunch the gang with world <= orig_world (clamp "
            "in the launcher)")
    remaining = total_steps - done
    if state is not None and remaining <= 0:
        return state
    dp = trainer.mesh.data_parallel_size()
    stream = ElasticStreamSet(
        source, batch_size, plan, rank, world, epochs=epochs,
        drop_remainder=drop_remainder, shuffle_rows=shuffle_rows,
        shuffle_window=shuffle_window, multiple_of=dp, columns=columns,
        state_history=max(64, checkpoint_every + 8))
    ck = AsyncCheckpointer(
        checkpoint_dir, process_index=rank, process_count=world,
        coordinated=True, sharding=trainer.sharding_manifest(),
        meta={"orig_world": plan.orig_world, "seed": int(seed)},
        run_id=getattr(gang, "run_id", None))
    shim = _ElasticLoaderCheckpointer(ck, stream, base_step=done)
    it = iter(stream)
    try:
        if state is None:
            first = next(it)
            state = trainer.init_state(first, jax.random.PRNGKey(seed),
                                       init_params=init_params)

            def chain(head, rest):
                yield head
                yield from rest

            batch_iter: Iterator[dict] = chain(first, it)
        else:
            batch_iter = it
        out = trainer.fit(state, batch_iter, max_steps=remaining,
                          scan_chunk=1, log_every=log_every,
                          checkpointer=shim,
                          checkpoint_every=checkpoint_every, gang=gang,
                          callback=callback)
    except BaseException:
        # a Preempted/GangAborted (or crash) exit wins over any pending
        # background-write error — but still release the writer thread
        stream.close()
        try:
            ck.close()
        except Exception:  # noqa: BLE001
            pass
        raise
    # clean completion: close() surfaces a failed final shard write — the
    # caller must NOT believe the last checkpoint landed when it didn't
    stream.close()
    ck.close()
    if int(out.step) < total_steps:
        # finite-epochs stream dried before total_steps: THIS rank sends
        # no further ACKs, so no commit past its last one can ever form —
        # the other ranks' later steps are unrestorable. Loud, not silent.
        import json as _json
        import logging as _logging

        _logging.getLogger("synapseml_tpu.models.trainer").warning(
            _json.dumps({
                "event": "gang_stream_exhausted_early",
                "rank": int(rank), "step": int(out.step),
                "total_steps": int(total_steps),
                "hint": "commits beyond this rank's last ACK cannot "
                        "complete; size total_steps to the dataset or use "
                        "epochs=None"}))
    return out


def fit_arrays(trainer: "Trainer", data: dict, *, batch_size: int, total_steps: int,
               seed: int, init_params=None, init_batch_stats=None,
               scan_chunk: int = 8, checkpointer=None,
               checkpoint_every: int = 0, shard_rows: int | None = None) -> "TrainState":
    """Shared estimator fit loop over host arrays — a thin wrapper that puts
    the arrays behind a :class:`synapseml_tpu.data.MemorySource` and
    delegates to :func:`fit_source`, so in-memory and out-of-core training
    share ONE batch-assembly/shuffle/prefetch plane. ``shard_rows`` controls
    the virtual shard layout (None = one shard): matching an on-disk layout
    row-for-row makes this stream bit-identical to ``fit_source`` over the
    same rows under the same seed."""
    from ..data.source import MemorySource

    n = next(iter(data.values())).shape[0]
    return fit_source(trainer, MemorySource(data, shard_rows=shard_rows),
                      batch_size=batch_size, total_steps=total_steps,
                      seed=seed, init_params=init_params,
                      init_batch_stats=init_batch_stats, scan_chunk=scan_chunk,
                      checkpointer=checkpointer,
                      checkpoint_every=checkpoint_every,
                      drop_remainder=n >= batch_size)
