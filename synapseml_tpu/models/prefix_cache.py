"""Content-addressed prefix KV cache over the paged block pool.

Chat system prompts, RAG templates, and few-shot headers mean the token
streams hitting the paged engine share long prefixes — but the PR-6
``PagedDecodeEngine`` prefills every prompt from scratch. This module makes
filled KV pages a CONTENT-ADDRESSED asset: every full block of committed
tokens is keyed by a chain hash (``h_i = sha256(h_{i-1} || block_i
tokens)``), so a block's key commits to the whole token prefix behind it,
not just its own ``block_len`` tokens. A radix lookup is then just walking
the chain hash-by-hash until the first miss.

Sharing discipline (the allocator invariants extend to refcounts):

* the cache holds its OWN reference on every cached block
  (``BlockAllocator.ref``); a sequence that hits takes one more ref per
  shared block, so a block is physically freed only when the last holder —
  cache included — lets go;
* shared blocks are NEVER written: the engine reuses only whole blocks and
  starts its suffix prefill at the first uncached position, so the
  shared/private boundary is block-aligned. The one exception — a prompt
  whose full-block chain covers the entire context — is resolved by
  COPY-ON-WRITE: the divergence block is duplicated into a private page
  (in-program, under buffer donation) and only the copy is written;
* eviction is LRU over LEAF entries whose block has refcount 1 (only the
  cache holds it). Interior entries are pinned by their children — evicting
  one would orphan every descendant while their refs kept the pages alive.

The engine consults :meth:`PrefixCache.evict` before preempting a live
sequence: cold cached pages are strictly cheaper to give up than recompute.
"""

from __future__ import annotations

import hashlib

import numpy as np

from ..core import observability as obs

__all__ = ["PrefixCache", "chain_hash"]


_PREFIX_METRICS = obs.HandleCache(lambda reg: {
    "lookups": reg.counter(
        "synapseml_llm_prefix_lookups_total",
        "prefix-cache lookups at admit, by outcome (hit = >= 1 full block "
        "reused)", ("outcome",)),
    "reused": reg.counter(
        "synapseml_llm_prefix_tokens_reused_total",
        "prompt tokens whose prefill was skipped because their KV pages "
        "were already resident"),
    "evictions": reg.counter(
        "synapseml_llm_prefix_evictions_total",
        "cached blocks freed by LRU eviction (pool pressure)"),
    "blocks": reg.gauge(
        "synapseml_llm_prefix_blocks",
        "blocks currently pinned by the prefix cache"),
    "hit_rate": reg.gauge(
        "synapseml_llm_prefix_hit_rate",
        "cumulative fraction of admits that reused >= 1 cached block (the "
        "autoscaler's stickiness signal)"),
})


def chain_hash(parent: bytes, tokens) -> bytes:
    """``sha256(parent || int32 token bytes)`` — the per-block chain link.
    An empty ``parent`` roots the chain, so equal digests imply equal full
    token prefixes (not merely equal blocks)."""
    h = hashlib.sha256(parent)
    h.update(np.asarray(list(tokens), np.int32).tobytes())
    return h.digest()


class _Entry:
    __slots__ = ("block", "parent", "children", "tick")

    def __init__(self, block: int, parent: bytes, tick: int):
        self.block = int(block)
        self.parent = parent
        self.children = 0
        self.tick = tick


class PrefixCache:
    """Radix of chain-hashed full blocks over a :class:`BlockAllocator`.

    Not thread-safe on its own — the owning engine serializes access under
    its scheduler lock, exactly as it does for the allocator."""

    def __init__(self, allocator, block_len: int):
        self.allocator = allocator
        self.block_len = int(block_len)
        self._by_hash: dict[bytes, _Entry] = {}
        self._tick = 0
        self.hits = 0
        self.misses = 0
        self.tokens_reused = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._by_hash)

    @property
    def blocks(self) -> int:
        return len(self._by_hash)

    def block_ids(self) -> set[int]:
        return {e.block for e in self._by_hash.values()}

    # ------------------------------------------------------------------
    def lookup(self, token_ids) -> tuple[list[int], list[bytes]]:
        """Longest cached full-block chain prefixing ``token_ids`` ->
        (block ids, chain digests), both per matched block. Touches matched
        entries (LRU) and records the hit/miss outcome; takes NO references
        — the caller refs each block it actually keeps."""
        bl = self.block_len
        self._tick += 1
        blocks: list[int] = []
        digests: list[bytes] = []
        h = b""
        for i in range(len(token_ids) // bl):
            h = chain_hash(h, token_ids[i * bl:(i + 1) * bl])
            entry = self._by_hash.get(h)
            if entry is None:
                break
            entry.tick = self._tick
            blocks.append(entry.block)
            digests.append(h)
        m = _PREFIX_METRICS.get()
        if blocks:
            self.hits += 1
            m["lookups"].inc(outcome="hit")
        else:
            self.misses += 1
            m["lookups"].inc(outcome="miss")
        self._publish()
        return blocks, digests

    def note_reused(self, n_tokens: int) -> None:
        """Record the tokens ACTUALLY reused after the engine's caps (whole
        blocks, and always leaving >= 1 token to prefill)."""
        if n_tokens > 0:
            self.tokens_reused += int(n_tokens)
            _PREFIX_METRICS.get()["reused"].inc(int(n_tokens))

    def insert(self, parent: bytes, block_tokens, block: int) -> bytes:
        """Register one FULL block of committed tokens whose chain parent
        digest is ``parent``; returns the block's chain digest. Idempotent:
        an existing entry for the same token chain is touched, not
        duplicated (the caller's block stays private — content dedup, not
        pointer swap). A new entry takes the cache's own reference on
        ``block``, so the pages outlive the sequence that filled them."""
        h = chain_hash(parent, block_tokens)
        self._tick += 1
        entry = self._by_hash.get(h)
        if entry is not None:
            entry.tick = self._tick
            return h
        self.allocator.ref(block)
        self._by_hash[h] = _Entry(block, parent, self._tick)
        pe = self._by_hash.get(parent)
        if pe is not None:
            pe.children += 1
        self._publish()
        return h

    def evict(self, n_blocks: int) -> int:
        """Free up to ``n_blocks`` cached blocks, LRU-first, restricted to
        LEAF entries (no children) whose block only the cache holds
        (refcount 1). Cascades: a parent whose last child is evicted
        becomes a leaf and is itself eligible. Returns blocks freed."""
        freed = 0
        while freed < n_blocks:
            victim_h, victim = None, None
            for h, e in self._by_hash.items():
                if e.children:
                    continue
                if self.allocator.refcount(e.block) != 1:
                    continue  # a live sequence still shares these pages
                if victim is None or e.tick < victim.tick:
                    victim_h, victim = h, e
            if victim is None:
                break
            del self._by_hash[victim_h]
            pe = self._by_hash.get(victim.parent)
            if pe is not None:
                pe.children -= 1
            self.allocator.free([victim.block])
            self.evictions += 1
            freed += 1
        if freed:
            m = _PREFIX_METRICS.get()
            m["evictions"].inc(freed)
            self._publish()
        return freed

    def clear(self) -> int:
        """Drop every entry (releasing the cache's refs) — the hot-swap /
        release path. Returns entries dropped."""
        n = len(self._by_hash)
        for e in self._by_hash.values():
            self.allocator.free([e.block])
        self._by_hash.clear()
        self._publish()
        return n

    # ------------------------------------------------------------------
    def _publish(self) -> None:
        m = _PREFIX_METRICS.get()
        m["blocks"].labels().set(float(len(self._by_hash)))
        total = self.hits + self.misses
        m["hit_rate"].labels().set(self.hits / total if total else 0.0)

    def stats(self) -> dict:
        total = self.hits + self.misses
        return {"entries": len(self._by_hash),
                "blocks": len(self._by_hash),
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": self.hits / total if total else 0.0,
                "tokens_reused": self.tokens_reused,
                "evictions": self.evictions}
