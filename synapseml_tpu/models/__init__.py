from .cntk import CNTKModel
from .downloader import ModelDownloader, ModelSchema
from .text import DeepTextClassifier, DeepTextModel
from .tokenizer import HashingTokenizer, resolve_tokenizer
from .fused_trainer import FusedTrainer, fused_fit_arrays, fused_fit_source
from .pipeline_trainer import PipelineTrainer
from .trainer import Trainer, TrainerConfig, TrainState, cross_entropy_loss
from .vision import DeepVisionClassifier, DeepVisionModel

__all__ = [
    "ModelDownloader",
    "ModelSchema",
    "CNTKModel",
    "DeepTextClassifier", "DeepTextModel",
    "DeepVisionClassifier", "DeepVisionModel",
    "HashingTokenizer", "resolve_tokenizer",
    "Trainer", "TrainerConfig", "TrainState", "cross_entropy_loss",
    "FusedTrainer", "fused_fit_source", "fused_fit_arrays",
    "PipelineTrainer",
]
