"""Pipeline-stage trainer: GPipe schedule inside the one jitted train step.

``parallel/pipeline.py`` has carried the full schedule family (GPipe,
scattered-io, interleaved) since the mesh-axis work landed, but nothing
TRAINED through it — every trainer assumed the whole model applies on every
device. This module closes that gap (ROADMAP open item 1c, MPMD pipeline
parallelism per arXiv:2412.14374's framing): a model bigger than one host
declares its stage split — the partition rule table's ``stage_regex``
names the cut points — and trains with each stage's weights AND optimizer
state living only on that stage's ``pipe``-axis coordinate.

Layout contract (the GPipe chainability rule): the model factors into

* ``embed_fn(shared_params, microbatch) -> x``   (runs replicated),
* ``stage_fn(stage_params, x) -> x``             (the repeated block —
  every stage structurally identical; rides the pipeline ring),
* ``head_loss_fn(shared_params, x_out, microbatch) -> scalar loss``
  (replicated; owns labels/masking).

Params assemble as ``{"shared": <embed+head tree>, "stages": <leading-
stage-axis stack>}`` — either pre-split, or a flat tree cut by
``cfg.partition_rules.stage_regex`` via
:func:`~synapseml_tpu.parallel.partition.split_stage_params`. Everything
else — the optax formula, fit/fit_source/fit_arrays loops, checkpoint
resume, ZeRO optimizer-state sharding — is inherited from
:class:`~synapseml_tpu.models.trainer.Trainer` unchanged, so pipeline
training composes with the rest of the sharding plane for free.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from ..parallel.mesh import MeshContext
from .trainer import Trainer, TrainerConfig, TrainState, _make_optimizer

__all__ = ["PipelineTrainer"]


class PipelineTrainer(Trainer):
    """Trains a stage-split model over the mesh's ``pipe`` axis.

    Drop-in for :func:`~synapseml_tpu.models.trainer.fit_source` /
    ``fit_arrays`` — pass the assembled (or flat + ``stage_regex``) param
    tree as ``init_params``. On a mesh whose ``pipe`` axis is absent or
    size 1 the schedule falls back to the sequential stage chain
    (``pipeline_sharded``'s fallback), which is also the parity reference
    the tests hold the 2-stage mesh to.
    """

    def __init__(self, mesh_ctx: MeshContext, cfg: TrainerConfig, *,
                 stage_fn: Callable[[Any, Any], Any],
                 head_loss_fn: Callable[[Any, Any, dict], jax.Array],
                 embed_fn: Callable[[Any, dict], Any] | None = None,
                 n_micro: int = 4, axis_name: str = "pipe",
                 remat: bool = False, io: str = "replicated"):
        super().__init__(None, mesh_ctx, cfg)
        self.stage_fn = stage_fn
        self.embed_fn = embed_fn
        self.head_loss_fn = head_loss_fn
        self.n_micro = int(n_micro)
        self.axis_name = axis_name
        self.remat = remat
        self.io = io
        if self.n_micro < 1:
            raise ValueError(f"n_micro must be >= 1, got {n_micro}")
        # Trainer._step_fn routes through self._loss_fn when set — the
        # whole fit/scan/checkpoint machinery is reused untouched
        self._loss_fn = self._pipeline_loss

    # ---- param assembly ---------------------------------------------------
    def _assemble(self, init_params) -> dict:
        from ..parallel import partition as pp
        from ..parallel.pipeline import stack_stage_params

        if isinstance(init_params, dict) and "stages" in init_params:
            stages = init_params["stages"]
            if isinstance(stages, (list, tuple)):
                stages = stack_stage_params(list(stages))
            return {"shared": init_params.get("shared") or {},
                    "stages": stages}
        rules = self.cfg.partition_rules
        if rules is None or rules.stage_regex is None:
            raise ValueError(
                "PipelineTrainer needs either init_params={'shared': ..., "
                "'stages': [per-stage trees] | stacked} or a flat tree "
                "plus cfg.partition_rules.stage_regex naming the cut "
                "points")
        shared, stacked = pp.stack_stages(init_params, rules.stage_regex)
        return {"shared": shared, "stages": stacked}

    def _n_stages(self, params: dict) -> int:
        return int(jax.tree.leaves(params["stages"])[0].shape[0])

    # ---- placement (overrides the flat-tree rule placement) ---------------
    def _rule_place_params(self, params):
        from ..parallel import partition as pp

        specs = pp.pipeline_param_specs(self.cfg.partition_rules, params,
                                        axis_name=self.axis_name)
        self._param_shardings = pp.tree_shardings(self.mesh, specs, params)
        return pp.place_tree(params, self._param_shardings)

    def _rule_place_opt_state(self, params, opt_state):
        from ..parallel import partition as pp

        skel = jax.eval_shape(lambda: opt_state)
        specs = pp.pipeline_opt_specs(self.cfg.partition_rules, skel,
                                      self.mesh, zero=self.cfg.zero_shard,
                                      axis_name=self.axis_name)
        self._opt_shardings = pp.tree_shardings(self.mesh, specs, skel)
        placed = pp.place_tree(opt_state, self._opt_shardings)
        pp.emit_shard_metrics(params, placed, self.mesh,
                              engine="pipeline_trainer")
        return placed

    def checkpoint_sharding_fn(self):
        from ..parallel import partition as pp

        rules = self.cfg.partition_rules or pp.PartitionRules()
        return pp.checkpoint_sharding_fn(rules, self.mesh,
                                         zero=self.cfg.zero_shard,
                                         pipeline_axis=self.axis_name)

    # ---- state init -------------------------------------------------------
    def init_state(self, example_batch: dict, rng: jax.Array | None = None,
                   init_params=None, init_batch_stats=None) -> TrainState:
        if init_params is None:
            raise ValueError(
                "PipelineTrainer has no module to init from — pass the "
                "stage-split (or flat + stage_regex) param tree as "
                "init_params")
        params = self._assemble(init_params)
        n_stages = self._n_stages(params)
        pipe = self.mesh.axis_sizes.get(self.axis_name, 1)
        if pipe > 1 and n_stages != pipe:
            raise ValueError(
                f"{n_stages} stages cannot split over a {self.axis_name!r} "
                f"axis of size {pipe} (one stage per coordinate)")
        params = self._rule_place_params(params)
        self._tx = _make_optimizer(self.cfg, params)
        opt_state = self._rule_place_opt_state(params,
                                               self._tx.init(params))
        return TrainState(params=params, opt_state=opt_state,
                          step=jnp.zeros((), jnp.int32), batch_stats=None)

    # ---- the pipelined loss (consumed by Trainer._step_fn) ----------------
    def _pipeline_loss(self, variables, batch: dict) -> jax.Array:
        from ..parallel.pipeline import pipeline_sharded

        params = variables["params"]
        batch = {k: v for k, v in batch.items()}
        n_rows = int(jax.tree.leaves(batch)[0].shape[0])
        if n_rows % self.n_micro:
            raise ValueError(
                f"batch of {n_rows} rows does not split into "
                f"{self.n_micro} microbatches — pick batch_size a "
                "multiple of n_micro")
        mb = n_rows // self.n_micro
        micro = jax.tree.map(
            lambda x: x.reshape((self.n_micro, mb) + x.shape[1:]), batch)
        shared = params.get("shared", {})
        if self.embed_fn is not None:
            x0 = jax.vmap(lambda b: self.embed_fn(shared, b))(micro)
        else:
            x0 = micro
        outs = pipeline_sharded(self.mesh, self.stage_fn, params["stages"],
                                x0, axis_name=self.axis_name,
                                remat=self.remat, io=self.io)
        losses = jax.vmap(lambda o, b: self.head_loss_fn(shared, o, b))(
            outs, micro)
        return jnp.mean(losses)
