"""DeepVisionClassifier / DeepVisionModel — vision transfer learning on the mesh.

Reference: ``dl/DeepVisionClassifier.py:31-268`` (horovod TorchEstimator with
torchvision backbones) + ``dl/DeepVisionModel.py`` predict wrapper. Rebuilt:
Flax ViT/ResNet backbones trained by the GSPMD Trainer; images arrive as an
image column ([H,W,C] arrays) produced by image.ImageTransformer.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

from ..core import DataFrame, Estimator, Model
from ..core import batching as cb
from ..core.params import ComplexParam, Param, TypeConverters
from ..parallel.mesh import MeshConfig, create_mesh
from .flax_nets.resnet import resnet18, resnet50, resnet_tiny
from .flax_nets.vit import ViTClassifier, vit_b16, vit_tiny
from .trainer import (Trainer, TrainerConfig,
                      _fit_with_optional_checkpointing, fit_arrays, plan_fit)

__all__ = ["DeepVisionClassifier", "DeepVisionModel"]


def _build_module(backbone: str, num_classes: int, arch_spec=None):
    """(module, has_batch_stats). ``backbone`` is a preset name or a local HF
    checkpoint dir (handled by the caller via ``arch_spec`` from
    convert_hf.pretrained_vision)."""
    if arch_spec is not None:
        kind, info = arch_spec
        if kind == "vit":
            return ViTClassifier(info["cfg"], num_classes=num_classes,
                                 patch=info["patch"]), False
        from .flax_nets.resnet import ResNet

        return ResNet(num_classes=num_classes, **info), True
    if backbone == "vit_b16":
        return ViTClassifier(vit_b16(), num_classes=num_classes, patch=16), False
    if backbone == "vit_tiny":
        return ViTClassifier(vit_tiny(), num_classes=num_classes, patch=8), False
    if backbone == "resnet50":
        return resnet50(num_classes=num_classes), True
    if backbone == "resnet18":
        return resnet18(num_classes=num_classes), True
    if backbone == "resnet_tiny":
        return resnet_tiny(num_classes=num_classes), True
    raise ValueError(f"unknown backbone {backbone!r}; "
                     "have vit_b16|vit_tiny|resnet50|resnet18|resnet_tiny "
                     "or a local HF checkpoint directory")


class _VisionParams:
    image_col = Param("image_col", "input image column ([H,W,C] float arrays)",
                      default="image")
    label_col = Param("label_col", "label column", default="label")
    prediction_col = Param("prediction_col", "argmax output column", default="prediction")
    scores_col = Param("scores_col", "softmax scores column", default="scores")
    backbone = Param("backbone", "vit_b16|vit_tiny|resnet50|resnet18|resnet_tiny",
                     default="resnet_tiny")
    num_classes = Param("num_classes", "number of classes", default=2,
                        converter=TypeConverters.to_int)
    batch_size = Param("batch_size", "global batch size", default=32,
                       converter=TypeConverters.to_int)


class DeepVisionClassifier(Estimator, _VisionParams):
    feature_name = "deep_learning"

    learning_rate = Param("learning_rate", "peak lr", default=1e-3,
                          converter=TypeConverters.to_float)
    num_train_epochs = Param("num_train_epochs", "epochs", default=2,
                             converter=TypeConverters.to_int)
    max_steps = Param("max_steps", "hard step cap (-1 = epochs)", default=-1,
                      converter=TypeConverters.to_int)
    seed = Param("seed", "init seed", default=0, converter=TypeConverters.to_int)
    checkpoint_dir = Param("checkpoint_dir", "when set, write async training "
                           "checkpoints here (reference pytorch-lightning "
                           "ModelCheckpoint role); resume via "
                           "parallel.restore_checkpoint + Trainer.resume_state",
                           default=None)
    checkpoint_every = Param("checkpoint_every", "checkpoint every N optimizer "
                             "steps — the fused scan chunk shrinks to N "
                             "when smaller (0 = only the final state)", default=0,
                             converter=TypeConverters.to_int)
    checkpoint_keep = Param("checkpoint_keep", "retain the most recent K "
                            "checkpoints", default=3,
                            converter=TypeConverters.to_int)
    mesh_config = ComplexParam("mesh_config", "MeshConfig override", default=None)

    def _fit(self, df: DataFrame) -> "DeepVisionModel":
        from .convert_hf import is_checkpoint_dir

        arch_spec = None
        init_params = init_stats = None
        if is_checkpoint_dir(self.get("backbone")):
            # local HF/torchvision-format checkpoint (the reference's
            # torchvision-backbone transfer path, dl/DeepVisionClassifier.py)
            from .convert_hf import pretrained_vision

            kind, info, variables = pretrained_vision(
                self.get("backbone"), num_classes=self.get("num_classes"),
                seed=self.get("seed"))
            arch_spec = (kind, info)
            init_params = variables["params"]
            init_stats = variables.get("batch_stats")
        module, has_bn = _build_module(self.get("backbone"), self.get("num_classes"),
                                       arch_spec)
        mesh = create_mesh(self.get("mesh_config") or MeshConfig())

        labels = df.collect_column(self.get("label_col")).astype(np.int32)
        bs, total = plan_fit(len(labels), self.get("batch_size"),
                             self.get("num_train_epochs"), self.get("max_steps"))
        images = np.stack(list(df.collect_column(self.get("image_col")))).astype(np.float32)

        trainer = Trainer(module, mesh,
                          TrainerConfig(learning_rate=self.get("learning_rate"),
                                        total_steps=total, lr_schedule="cosine",
                                        warmup_steps=max(total // 10, 1)),
                          has_batch_stats=has_bn)
        state = _fit_with_optional_checkpointing(
            self, lambda ck, every: fit_arrays(
                trainer, {"x": images, "labels": labels},
                batch_size=bs, total_steps=total, seed=self.get("seed"),
                init_params=init_params, init_batch_stats=init_stats,
                checkpointer=ck, checkpoint_every=every))

        return DeepVisionModel(
            model_params=jax.tree.map(np.asarray, state.params),
            batch_stats=(jax.tree.map(np.asarray, state.batch_stats)
                         if state.batch_stats is not None else None),
            arch_spec=arch_spec,
            backbone=self.get("backbone"), num_classes=self.get("num_classes"),
            image_col=self.get("image_col"), prediction_col=self.get("prediction_col"),
            scores_col=self.get("scores_col"), batch_size=self.get("batch_size"),
            train_metrics=trainer.metrics,
        )


class DeepVisionModel(Model, _VisionParams):
    feature_name = "deep_learning"

    model_params = ComplexParam("model_params", "trained parameter pytree")
    batch_stats = ComplexParam("batch_stats", "BN running stats", default=None)
    arch_spec = ComplexParam("arch_spec", "(kind, info) for pretrained-dir fits",
                             default=None)
    mesh_config = ComplexParam("mesh_config", "MeshConfig for sharded inference",
                               default=None)
    train_metrics = ComplexParam("train_metrics", "loss/throughput trace", default=None)

    def __init__(self, **kw):
        super().__init__(**kw)
        self._apply_fn = None

    def _post_load(self):
        self._apply_fn = None
        cb.invalidate_token(self)

    _APPLY_KEYS = frozenset({"model_params", "batch_stats", "arch_spec",
                             "backbone", "num_classes", "mesh_config"})

    def set(self, **kw):
        out = super().set(**kw)
        if self._APPLY_KEYS & kw.keys():
            self._apply_fn = None  # cached closure captured the old values
            cb.invalidate_token(self)
        return out

    def _get_apply(self):
        """Returns ``run_for(bucket, img_shape)`` — per-bucket executables
        via the process-wide CompiledCache."""
        if self._apply_fn is None:
            module, has_bn = _build_module(self.get("backbone"), self.get("num_classes"),
                                           self.get("arch_spec"))
            variables = {"params": self.get("model_params")}
            if self.get("batch_stats") is not None:
                variables["batch_stats"] = self.get("batch_stats")
            mesh = None
            if self.get("mesh_config") is not None:
                # batch-sharded inference; explainer perturbation batches ride
                # this path too (SURVEY §7 step 8)
                mesh = create_mesh(self.get("mesh_config"))
                variables = jax.tree.map(
                    lambda v: jax.device_put(np.asarray(v), mesh.replicated()),
                    variables)

            def apply_fn(variables, x):
                logits = module.apply(variables, x)
                return jax.nn.softmax(logits, axis=-1)

            def run_for(bucket: int, img_shape: tuple):
                def build():
                    jitted = jax.jit(apply_fn)
                    if mesh is not None:
                        def run(x, _j=jitted, _m=mesh):
                            with _m.mesh:
                                return _j(variables, _m.shard_batch(x))
                        return run
                    return lambda x: jitted(variables, x)

                return cb.get_compiled_cache().get(
                    "deep_vision_model", (bucket,) + tuple(img_shape), build,
                    instance=cb.instance_token(self), dtype="float32")

            self._module_has_bn = has_bn
            self._mesh = mesh
            self._apply_fn = run_for
        return self._apply_fn

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("image_col"))
        run_for = self._get_apply()
        bs = self.get("batch_size")
        dp = self._mesh.data_parallel_size() if self._mesh is not None else 1
        bucketer = cb.default_bucketer()

        def per_part(part):
            imgs = part[self.get("image_col")]
            if len(imgs) == 0:
                # keep the output schema rectangular across partitions
                out = dict(part)
                out[self.get("scores_col")] = np.zeros((0, self.get("num_classes")), np.float32)
                out[self.get("prediction_col")] = np.zeros(0, np.int32)
                return out
            x = np.stack(list(imgs)).astype(np.float32)
            chunks = []
            for s, e, bucket in bucketer.slices(len(x), bs, multiple_of=dp):
                p = run_for(bucket, x.shape[1:])(cb.pad_rows(x[s:e], bucket))
                chunks.append(cb.unpad_rows(p, e - s))
            probs = np.concatenate(chunks, axis=0)
            out = dict(part)
            out[self.get("scores_col")] = probs
            out[self.get("prediction_col")] = np.argmax(probs, axis=-1).astype(np.int32)
            return out

        return df.map_partitions(per_part)
