"""DeepTextClassifier / DeepTextModel — text fine-tuning on the mesh.

Reference: ``dl/DeepTextClassifier.py:27-288`` (horovod TorchEstimator subclass,
HF checkpoint + tokenizer transformation_fn, layer-freezing fine-tune in
``dl/LitDeepTextModel.py:120``) and the ``DeepTextModel`` per-row predict
(``dl/DeepTextModel.py:84-118``). Rebuilt: Flax BERT + GSPMD Trainer; the
param surface keeps the reference's names (text_col/label_col/checkpoint/
batch_size/learning_rate/max_token_len/num_train_epochs/unfreeze_layers).
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

from ..core import DataFrame, Estimator, Model
from ..core import batching as cb
from ..core.params import ComplexParam, Param, TypeConverters
from ..parallel.mesh import MeshConfig, MeshContext, create_mesh
from .flax_nets.bert import BertClassifier, bert_base, bert_tiny
from .tokenizer import resolve_tokenizer
from .trainer import (Trainer, TrainerConfig, TrainState,
                      _fit_with_optional_checkpointing, fit_arrays, plan_fit)

__all__ = ["DeepTextClassifier", "DeepTextModel"]

_ARCHS = {"bert-base": bert_base, "bert-tiny": bert_tiny}


def _resolve_arch(name: str):
    """Known preset or fail fast — a typo must not silently train a
    randomly-initialized bert-base."""
    try:
        return _ARCHS[name]
    except KeyError:
        raise ValueError(f"unknown checkpoint {name!r}; available presets: "
                         f"{sorted(_ARCHS)} (or pass a local HF checkpoint "
                         f"directory for pretrained weights)") from None




class _TextParams:
    text_col = Param("text_col", "input text column", default="text")
    label_col = Param("label_col", "label column", default="label")
    prediction_col = Param("prediction_col", "argmax output column", default="prediction")
    scores_col = Param("scores_col", "softmax scores output column", default="scores")
    checkpoint = Param("checkpoint", "architecture preset or HF checkpoint name",
                       default="bert-tiny")
    num_classes = Param("num_classes", "number of classes", default=2,
                        converter=TypeConverters.to_int)
    max_token_len = Param("max_token_len", "max sequence length (reference default 128)",
                          default=128, converter=TypeConverters.to_int)
    batch_size = Param("batch_size", "global batch size", default=32,
                       converter=TypeConverters.to_int)


class DeepTextClassifier(Estimator, _TextParams):
    feature_name = "deep_learning"

    learning_rate = Param("learning_rate", "peak learning rate", default=5e-5,
                          converter=TypeConverters.to_float)
    num_train_epochs = Param("num_train_epochs", "training epochs", default=3,
                             converter=TypeConverters.to_int)
    max_steps = Param("max_steps", "hard cap on optimizer steps (-1 = epochs decide)",
                      default=-1, converter=TypeConverters.to_int)
    unfreeze_layers = Param("unfreeze_layers",
                            "train only the last N encoder layers (+head); -1 = all "
                            "(reference LitDeepTextModel._fine_tune_layers)",
                            default=-1, converter=TypeConverters.to_int)
    grad_accum = Param("grad_accum", "gradient accumulation steps "
                       "(horovod backward_passes_per_step analog)", default=1,
                       converter=TypeConverters.to_int)
    seed = Param("seed", "init seed", default=0, converter=TypeConverters.to_int)
    checkpoint_dir = Param("checkpoint_dir", "when set, write async training "
                           "checkpoints here (reference pytorch-lightning "
                           "ModelCheckpoint role); resume via "
                           "parallel.restore_checkpoint + Trainer.resume_state",
                           default=None)
    checkpoint_every = Param("checkpoint_every", "checkpoint every N optimizer "
                             "steps — the fused scan chunk shrinks to N "
                             "when smaller (0 = only the final state)", default=0,
                             converter=TypeConverters.to_int)
    checkpoint_keep = Param("checkpoint_keep", "retain the most recent K "
                            "checkpoints", default=3,
                            converter=TypeConverters.to_int)
    attn_impl = Param("attn_impl", "attention backend: einsum | flash | ring "
                      "| ulysses (None = architecture default; ring/ulysses "
                      "need a mesh with a seq axis > 1; ulysses also needs "
                      "n_heads divisible by the seq-axis size)", default=None,
                      validator=lambda v: v in (None, "einsum", "flash",
                                                "ring", "ulysses"))
    tokenizer = ComplexParam("tokenizer", "tokenizer object/config/name", default=None)
    mesh_config = ComplexParam("mesh_config", "MeshConfig override", default=None)
    weight_decay = Param("weight_decay", "adamw weight decay", default=0.01,
                         converter=TypeConverters.to_float)

    def _make_config(self, vocab_size: int):
        return _resolve_arch(self.get("checkpoint"))(vocab_size=vocab_size)

    def _freeze_predicate(self, n_layers_total: int):
        n = self.get("unfreeze_layers")
        if n is None or n < 0:
            return None
        trainable_layers = {f"layer_{i}" for i in
                            range(max(n_layers_total - n, 0), n_layers_total)}

        def frozen(path: tuple[str, ...]) -> bool:
            if path and path[0] in ("classifier", "pooler"):
                return False
            return not any(p in trainable_layers for p in path)

        return frozen

    def _fit(self, df: DataFrame) -> "DeepTextModel":
        from .convert_hf import is_checkpoint_dir, tokenizer_for_checkpoint

        ck = self.get("checkpoint")
        init_params = None
        if is_checkpoint_dir(ck):
            # local HF checkpoint directory: pretrained weights + its tokenizer
            # (the reference's AutoModelForSequenceClassification.from_pretrained
            # transfer-learning path, dl/DeepTextClassifier.py:27-288)
            from .convert_hf import pretrained_text_classifier

            cfg, init_params = pretrained_text_classifier(
                ck, num_classes=self.get("num_classes"), seed=self.get("seed"))
            tok = tokenizer_for_checkpoint(self.get("tokenizer"), ck, cfg.vocab_size)
        else:
            tok = resolve_tokenizer(self.get("tokenizer"))
            cfg = self._make_config(tok.vocab_size)
        if self.get("attn_impl"):
            import dataclasses

            cfg = dataclasses.replace(cfg, attn_impl=self.get("attn_impl"))
        mesh = create_mesh(self.get("mesh_config") or MeshConfig())
        module = BertClassifier(cfg, num_classes=self.get("num_classes"))

        texts = df.collect_column(self.get("text_col"))
        labels = df.collect_column(self.get("label_col")).astype(np.int32)
        encoded = tok(list(texts), max_len=self.get("max_token_len"))
        data = {**encoded, "labels": labels}

        bs, total = plan_fit(len(labels), self.get("batch_size"),
                             self.get("num_train_epochs"), self.get("max_steps"))
        tcfg = TrainerConfig(
            learning_rate=self.get("learning_rate"),
            weight_decay=self.get("weight_decay"),
            total_steps=total, grad_accum=self.get("grad_accum"),
            warmup_steps=max(total // 10, 1), lr_schedule="linear",
            freeze_predicate=self._freeze_predicate(cfg.n_layers),
        )
        trainer = Trainer(module, mesh, tcfg)
        state = _fit_with_optional_checkpointing(
            self, lambda ck, every: fit_arrays(
                trainer, data, batch_size=bs, total_steps=total,
                seed=self.get("seed"), init_params=init_params,
                checkpointer=ck, checkpoint_every=every))

        host_params = jax.tree.map(np.asarray, state.params)
        # always persist the arch: a preset's meaning may evolve (e.g. the
        # pre->post-norm change) and a saved model must keep evaluating with
        # the architecture it was trained as
        return DeepTextModel(
            model_params=host_params,
            arch_config=cfg,
            tokenizer_config=tok.to_config(),
            checkpoint=self.get("checkpoint"),
            num_classes=self.get("num_classes"),
            text_col=self.get("text_col"),
            prediction_col=self.get("prediction_col"),
            scores_col=self.get("scores_col"),
            max_token_len=self.get("max_token_len"),
            batch_size=self.get("batch_size"),
            train_metrics=trainer.metrics,
        )


class DeepTextModel(Model, _TextParams):
    feature_name = "deep_learning"

    model_params = ComplexParam("model_params", "trained Flax parameter pytree")
    mesh_config = ComplexParam(
        "mesh_config", "MeshConfig for sharded inference (params + batches "
        "distribute over the mesh; explainer perturbation batches ride the "
        "same path)", default=None)
    arch_config = ComplexParam("arch_config", "TransformerConfig (pretrained-dir "
                               "fits; None = resolve checkpoint preset)", default=None)
    tokenizer_config = ComplexParam("tokenizer_config", "tokenizer config dict")
    train_metrics = ComplexParam("train_metrics", "loss/throughput trace", default=None)
    attn_impl = Param("attn_impl", "serve-time attention backend override: "
                      "einsum | flash (None = the trained arch's choice); "
                      "pure kernel selection — the param tree is unchanged",
                      default=None,
                      validator=lambda v: v in (None, "einsum", "flash"))

    # publish-time backend search (registry/autotune.py): the single-chip
    # attention impls the attn_backends decision bench compares — the
    # fastest per platform is pinned into the artifact manifest at publish
    # and re-applied at /admin/load. Declared on the MODEL (the class
    # artifacts actually serve), not the estimator: ring/ulysses need a
    # mesh topology and stay out of the serve-path search.
    _AUTOTUNE_PARAMS = {"attn_impl": ("einsum", "flash")}

    def __init__(self, **kw):
        super().__init__(**kw)
        self._apply_fn = None

    def _post_load(self):
        self._apply_fn = None
        cb.invalidate_token(self)

    _APPLY_KEYS = frozenset({"model_params", "arch_config", "tokenizer_config",
                             "checkpoint", "num_classes", "mesh_config",
                             "attn_impl"})

    def set(self, **kw):
        out = super().set(**kw)
        if self._APPLY_KEYS & kw.keys():
            self._apply_fn = None  # cached closure captured the old values
            cb.invalidate_token(self)
        return out

    def _get_apply(self):
        """Returns ``run_for(bucket, seq_len)`` — a per-bucket executable
        factory backed by the process-wide CompiledCache, so a variable
        scoring stream compiles at most ladder-many programs."""
        if self._apply_fn is None:
            import jax.numpy as jnp

            tok = resolve_tokenizer(self.get("tokenizer_config"))
            cfg = self.get("arch_config")
            if cfg is None:
                from .convert_hf import legacy_prenorm_fixup

                cfg = _resolve_arch(self.get("checkpoint"))(vocab_size=tok.vocab_size)
                cfg = legacy_prenorm_fixup(cfg, self.get("model_params"))
            if self.get("attn_impl"):
                import dataclasses

                # serve-time kernel override (the autotune pin): same math,
                # same param tree, different attention impl
                cfg = dataclasses.replace(cfg,
                                          attn_impl=self.get("attn_impl"))
            module = BertClassifier(cfg, num_classes=self.get("num_classes"))

            params = self.get("model_params")
            mesh = None
            if self.get("mesh_config") is not None:
                from ..parallel.mesh import shard_inference_params

                mesh = create_mesh(self.get("mesh_config"))
                params = shard_inference_params(
                    module, {"input_ids": jnp.zeros((1, 8), jnp.int32),
                             "attention_mask": jnp.ones((1, 8), jnp.int32)},
                    params, mesh)

            def apply_fn(params, input_ids, attention_mask):
                logits = module.apply({"params": params}, input_ids, attention_mask)
                return jax.nn.softmax(logits, axis=-1)

            def run_for(bucket: int, seq_len: int):
                def build():
                    jitted = jax.jit(apply_fn)
                    if mesh is not None:
                        def run(ids, m, _j=jitted, _m=mesh):
                            with _m.mesh:
                                return _j(params, _m.shard_batch(ids),
                                          _m.shard_batch(m))
                        return run
                    return lambda ids, m: jitted(params, ids, m)

                return cb.get_compiled_cache().get(
                    "deep_text_model", (bucket, seq_len), build,
                    instance=cb.instance_token(self), dtype="int32")

            self._tok = tok
            self._mesh = mesh
            self._apply_fn = run_for
        return self._apply_fn

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("text_col"))
        run_for = self._get_apply()
        bs = self.get("batch_size")
        dp = self._mesh.data_parallel_size() if self._mesh is not None else 1
        bucketer = cb.default_bucketer()

        def per_part(part):
            texts = list(part[self.get("text_col")])
            if not texts:
                # keep the output schema rectangular across partitions
                out = dict(part)
                out[self.get("scores_col")] = np.zeros((0, self.get("num_classes")), np.float32)
                out[self.get("prediction_col")] = np.zeros(0, np.int32)
                return out
            enc = self._tok(texts, max_len=self.get("max_token_len"))
            ids = np.asarray(enc["input_ids"])
            mask = np.asarray(enc["attention_mask"])
            probs_chunks = []
            for s, e, bucket in bucketer.slices(len(texts), bs, multiple_of=dp):
                p = run_for(bucket, ids.shape[1])(
                    cb.pad_rows(ids[s:e], bucket), cb.pad_rows(mask[s:e], bucket))
                probs_chunks.append(cb.unpad_rows(p, e - s))
            probs = np.concatenate(probs_chunks, axis=0)
            out = dict(part)
            out[self.get("scores_col")] = probs
            out[self.get("prediction_col")] = np.argmax(probs, axis=-1).astype(np.int32)
            return out

        return df.map_partitions(per_part)
