"""Pretrained-model repository client (reference
``core/src/main/python/synapse/ml/downloader/ModelDownloader.py``).

The reference downloads CNTK model files from a CDN and tracks them with
``ModelSchema`` records. Here the repository holds HF-format checkpoint
directories (the format every ingestion path consumes —
:mod:`synapseml_tpu.models.convert_hf`): ``local_models()`` enumerates
checkpoint dirs under the local path, ``remote_models()`` reads a JSON
index from a model server, and ``download_model()`` fetches a model's
files with sha256 verification. Remote calls honor the environment:
zero-egress hosts get an actionable error, and everything is testable
against an in-process HTTP mock.
"""

from __future__ import annotations

import dataclasses
import json
import os
import urllib.error
import urllib.request
from typing import Any, Iterator

__all__ = ["ModelSchema", "ModelDownloader"]


@dataclasses.dataclass
class ModelSchema:
    """One model's record (the reference's ModelSchema analog)."""

    name: str
    kind: str = "causal-lm"  # causal-lm | text-classifier | vision | other
    uri: str = ""            # local dir or remote base URL
    files: tuple = ()        # file names within the model dir
    sha256: dict = dataclasses.field(default_factory=dict)  # per-file
    size_bytes: int = 0
    extra: dict = dataclasses.field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: dict) -> "ModelSchema":
        known = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in d.items() if k in known}
        kw["files"] = tuple(kw.get("files", ()))
        return cls(**kw)

    def to_dict(self) -> dict:
        out = dataclasses.asdict(self)
        out["files"] = list(out["files"])
        return out


class ModelDownloader:
    """Enumerate/fetch pretrained checkpoints.

    ``local_path`` is the model cache (one subdirectory per model);
    ``server_url`` is a repository serving ``index.json`` (a list of
    ModelSchema dicts) and the model files beneath ``<url>/<name>/``.
    """

    def __init__(self, local_path: str, server_url: str | None = None,
                 timeout_s: float = 10.0):
        self.local_path = local_path
        self.server_url = (server_url or "").rstrip("/") or None
        self.timeout_s = timeout_s
        os.makedirs(local_path, exist_ok=True)

    def _safe_path(self, *rel: str) -> str:
        """Join remote-supplied names into the cache dir, rejecting absolute
        paths and traversal — the index is REMOTE UNTRUSTED data (the same
        guard as ``ONNXHub._safe_cache_path``)."""
        for r in rel:
            if os.path.isabs(r):
                raise ValueError(f"index path must be relative: {r!r}")
        path = os.path.realpath(os.path.join(self.local_path, *rel))
        root = os.path.realpath(self.local_path)
        # STRICTLY inside the root: a name of "", "." or "x/.." resolves to
        # the cache root itself, and download_model's pre-replace rmtree
        # would then delete the entire local model cache
        if path == root or not path.startswith(root + os.sep):
            raise ValueError(f"index path escapes the cache dir: {rel!r}")
        return path

    # ---- local ----
    def local_models(self) -> Iterator[ModelSchema]:
        for name in sorted(os.listdir(self.local_path)):
            d = os.path.join(self.local_path, name)
            # a checkpoint dir = config.json + at least one weights file
            if not (os.path.isdir(d)
                    and os.path.isfile(os.path.join(d, "config.json"))
                    and any(f.endswith((".safetensors", ".bin"))
                            for f in os.listdir(d))):
                continue
            files = tuple(sorted(
                f for f in os.listdir(d)
                if os.path.isfile(os.path.join(d, f))))
            size = sum(os.path.getsize(os.path.join(d, f)) for f in files)
            kind = "other"
            try:
                with open(os.path.join(d, "config.json")) as fh:
                    cfg = json.load(fh)
                mt = cfg.get("model_type", "")
                kind = {"gpt2": "causal-lm", "llama": "causal-lm",
                        "mistral": "causal-lm", "mixtral": "causal-lm",
                        "bert": "text-classifier", "vit": "vision",
                        "resnet": "vision"}.get(mt, "other")
            except (OSError, json.JSONDecodeError):
                pass
            yield ModelSchema(name=name, kind=kind, uri=d, files=files,
                              size_bytes=size)

    # ---- remote ----
    def _open(self, url: str):
        try:
            return urllib.request.urlopen(url, timeout=self.timeout_s)
        except urllib.error.HTTPError as e:
            # the server responded — a bad index entry or missing file, NOT
            # an egress problem; keep the real status in the message
            raise RuntimeError(f"model server returned {e.code} for "
                               f"{url!r}: {e.reason}") from e
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise RuntimeError(
                f"model server unreachable at {url!r}: {e}. On zero-egress "
                "hosts, place checkpoint directories under "
                f"{self.local_path!r} instead (local_models() finds them)."
            ) from e

    def _fetch(self, url: str) -> bytes:
        with self._open(url) as r:
            return r.read()

    def _fetch_to_file(self, url: str, path: str,
                       expected_sha256: str | None = None) -> str:
        """Stream a download to a temp file atomically (.part + os.replace),
        hashing incrementally — one pass, constant memory. With
        ``expected_sha256`` the rename only happens on a digest match, so a
        bad transfer never lands even transiently (shared helper with the
        registry artifact store: ``registry/store.write_stream_verified``)."""
        from ..registry.store import write_stream_verified

        with self._open(url) as r:
            return write_stream_verified(r, path, expected_sha256)

    def remote_models(self) -> list[ModelSchema]:
        if self.server_url is None:
            raise ValueError("remote_models() needs server_url")
        index = json.loads(self._fetch(self.server_url + "/index.json"))
        return [ModelSchema.from_dict(d) for d in index]

    def download_model(self, schema: ModelSchema) -> ModelSchema:
        """Fetch one model's files into the local cache; verifies sha256
        when the index provides digests. Files download into a staging dir
        that only becomes the model dir once EVERY file verified — a failed
        download never leaves a partial checkpoint that local_models()
        would list. Returns the LOCAL schema."""
        if self.server_url is None:
            raise ValueError("download_model() needs server_url")
        dest = self._safe_path(schema.name)
        stage = self._safe_path(schema.name + ".staging")
        os.makedirs(stage, exist_ok=True)
        try:
            for fname in schema.files:
                path = self._safe_path(schema.name + ".staging", fname)
                # verification happens INSIDE the fetch: a digest mismatch
                # removes the temp file and the destination never appears
                self._fetch_to_file(
                    f"{self.server_url}/{schema.name}/{fname}", path,
                    expected_sha256=schema.sha256.get(fname))
        except Exception:
            import shutil

            shutil.rmtree(stage, ignore_errors=True)
            raise
        if os.path.isdir(dest):
            import shutil

            shutil.rmtree(dest)
        os.replace(stage, dest)
        return dataclasses.replace(schema, uri=dest)

    def download_by_name(self, name: str) -> ModelSchema:
        for schema in self.remote_models():
            if schema.name == name:
                return self.download_model(schema)
        raise KeyError(f"model {name!r} not in the remote index")

    def download_models(self, models: list[ModelSchema] | None = None
                        ) -> list[ModelSchema]:
        return [self.download_model(s)
                for s in (models if models is not None
                          else self.remote_models())]
