"""Pretrained-checkpoint ingestion: HF safetensors -> flax_nets param pytrees.

The reference's DL estimators exist to fine-tune *pretrained* models
(``dl/DeepTextClassifier.py:27-288`` loads ``AutoModelForSequenceClassification``,
``dl/DeepVisionClassifier.py:31-268`` torchvision backbones,
``hf/HuggingFaceCausalLMTransform.py:103-331`` ``AutoModelForCausalLM``,
``hf/HuggingFaceSentenceEmbedder.py:26-228`` sentence-transformers). This
module is the TPU-native equivalent of that loading path: explicit key maps +
transpose rules from HF/torchvision ``state_dict`` layouts to our Flax modules,
reading safetensors directly (no torch in the load path).

Supported families: BERT (post-norm encoder), ViT-B/16-style, Llama (incl.
GQA), ResNet (torchvision and HF ``microsoft/resnet-*`` naming).

Conventions recap (torch Linear stores ``weight[out, in]``; Flax Dense kernels
are ``[in, out]``):
  * Dense:        kernel = W.T, bias = b
  * QKV DenseGeneral: kernel = W.T.reshape(hidden, heads, head_dim)
  * Out-proj DenseGeneral(axis=(-2,-1)): kernel = W.T.reshape(heads, hd, hidden)
  * Conv2d:       kernel = W.transpose(2, 3, 1, 0)   (OIHW -> HWIO)
  * Embedding:    used as-is
"""

from __future__ import annotations

import json
import os
from typing import Any

import numpy as np

__all__ = [
    "load_safetensors", "load_checkpoint",
    "is_checkpoint_dir", "tokenizer_for_checkpoint",
    "bert_config_from_hf", "bert_params_from_hf",
    "vit_config_from_hf", "vit_params_from_hf",
    "llama_config_from_hf", "llama_params_from_hf",
    "resnet_variables_from_torch", "resnet_arch_from_hf_config",
    "pretrained_text_classifier", "pretrained_encoder",
    "pretrained_vision", "pretrained_causal_lm",
    "shard_pretrained_params",
]


# ---------------------------------------------------------------------------
# safetensors / checkpoint-dir reading
# ---------------------------------------------------------------------------

def load_safetensors(path: str) -> dict[str, np.ndarray]:
    """Read one ``.safetensors`` file (or a sharded ``*.index.json``) into
    a flat ``{key: np.ndarray}`` state dict."""
    if path.endswith(".index.json"):
        with open(path) as f:
            index = json.load(f)
        base = os.path.dirname(path)
        out: dict[str, np.ndarray] = {}
        for shard in sorted(set(index["weight_map"].values())):
            out.update(load_safetensors(os.path.join(base, shard)))
        return out
    from safetensors.numpy import load_file

    return dict(load_file(path))


def load_checkpoint(ckpt_dir: str) -> tuple[dict, dict[str, np.ndarray]]:
    """(config.json dict, state dict) from an HF-format checkpoint directory."""
    cfg_path = os.path.join(ckpt_dir, "config.json")
    config: dict = {}
    if os.path.exists(cfg_path):
        with open(cfg_path) as f:
            config = json.load(f)
    for name in ("model.safetensors", "model.safetensors.index.json"):
        p = os.path.join(ckpt_dir, name)
        if os.path.exists(p):
            return config, load_safetensors(p)
    raise FileNotFoundError(
        f"no model.safetensors[.index.json] in {ckpt_dir!r} "
        f"(found: {sorted(os.listdir(ckpt_dir)) if os.path.isdir(ckpt_dir) else 'missing dir'})")


# ---------------------------------------------------------------------------
# checkpoint-dir helpers shared by the four pretrained estimator paths
# ---------------------------------------------------------------------------

def is_checkpoint_dir(name) -> bool:
    """True when ``name`` points at a local HF-format checkpoint directory."""
    return isinstance(name, (str, os.PathLike)) and os.path.isdir(str(name))


def resolve_model_source(name, presets: dict, tokenizer_spec, loader,
                         preset_kwargs: dict | None = None):
    """Shared checkpoint-dir-vs-preset dispatch for the pretrained transformer
    paths (HuggingFaceCausalLM / HuggingFaceSentenceEmbedder).

    -> (cfg, pretrained_params_or_None, tokenizer)."""
    from .tokenizer import resolve_tokenizer

    if name in presets:  # presets win over a same-named local directory
        tok = resolve_tokenizer(tokenizer_spec)
        cfg = presets[name](vocab_size=tok.vocab_size, **(preset_kwargs or {}))
        return cfg, None, tok
    if is_checkpoint_dir(name):
        cfg, params = loader(str(name))
        tok = tokenizer_for_checkpoint(tokenizer_spec, str(name), cfg.vocab_size)
        return cfg, params, tok
    raise ValueError(f"unknown model_name {name!r}; presets: {sorted(presets)} "
                     f"or a local HF checkpoint dir")


def legacy_prenorm_fixup(cfg, params):
    """Saved artifacts from before the BERT post-norm change carry pre-norm
    param layouts (an encoder-level final norm) with no arch_config; rebuild
    the architecture they were trained as instead of silently mis-evaluating."""
    import dataclasses

    enc = params.get("encoder", {}) if isinstance(params, dict) else {}
    if cfg.norm_position == "post" and ("LayerNorm_0" in enc or "RMSNorm_0" in enc):
        return dataclasses.replace(cfg, norm_position="pre", norm_eps=1e-6,
                                   act="gelu_tanh")
    return cfg


def tokenizer_for_checkpoint(spec, ckpt_dir: str, model_vocab: int):
    """Resolve the tokenizer for a pretrained checkpoint.

    ``spec`` wins when given; otherwise try the checkpoint dir's own tokenizer
    files. Always guard the resolved vocab against the checkpoint's embedding
    table — oversized ids would be silently clamped by XLA gather and produce
    garbage, not an error."""
    from .tokenizer import resolve_tokenizer

    if spec is not None:
        tok = resolve_tokenizer(spec)
    else:
        try:
            tok = resolve_tokenizer(str(ckpt_dir))
        except ValueError as e:
            raise ValueError(
                f"checkpoint dir {ckpt_dir!r} has no loadable tokenizer files; "
                f"pass tokenizer= explicitly (e.g. HashingTokenizer("
                f"vocab_size={model_vocab})) or an HF tokenizer name") from e
    if tok.vocab_size > model_vocab:
        raise ValueError(
            f"tokenizer vocab ({tok.vocab_size}) exceeds the checkpoint's "
            f"embedding table ({model_vocab}); ids would be silently clamped")
    return tok


# ---------------------------------------------------------------------------
# shared small helpers
# ---------------------------------------------------------------------------

def _dense(sd, key: str) -> dict:
    out = {"kernel": np.ascontiguousarray(sd[f"{key}.weight"].T)}
    if f"{key}.bias" in sd:
        out["bias"] = sd[f"{key}.bias"]
    return out


def _qkv(sd, key: str, heads: int, head_dim: int) -> dict:
    w = sd[f"{key}.weight"]  # [heads*hd, hidden]
    hidden = w.shape[1]
    out = {"kernel": np.ascontiguousarray(w.T).reshape(hidden, heads, head_dim)}
    out["bias"] = (sd[f"{key}.bias"].reshape(heads, head_dim)
                   if f"{key}.bias" in sd
                   else np.zeros((heads, head_dim), w.dtype))
    return out


def _oproj(sd, key: str, heads: int, head_dim: int) -> dict:
    w = sd[f"{key}.weight"]  # [hidden, heads*hd]
    hidden = w.shape[0]
    out = {"kernel": np.ascontiguousarray(w.T).reshape(heads, head_dim, hidden)}
    out["bias"] = sd[f"{key}.bias"] if f"{key}.bias" in sd else np.zeros((hidden,), w.dtype)
    return out


def _ln(sd, key: str) -> dict:
    return {"scale": sd[f"{key}.weight"], "bias": sd[f"{key}.bias"]}


def _conv(sd, key: str) -> dict:
    out = {"kernel": np.ascontiguousarray(sd[f"{key}.weight"].transpose(2, 3, 1, 0))}
    if f"{key}.bias" in sd:
        out["bias"] = sd[f"{key}.bias"]
    return out


def _strip_prefix(sd: dict, *candidates: str) -> dict:
    """Strip a known top-level prefix (e.g. 'bert.') if present. Non-prefixed
    keys (heads like 'classifier.weight') are kept; a stripped key wins on
    collision with a bare key of the same name."""
    for pref in candidates:
        if any(k.startswith(pref) for k in sd):
            return {k: v for k, v in sd.items() if not k.startswith(pref)} | \
                   {k[len(pref):]: v for k, v in sd.items() if k.startswith(pref)}
    return sd


def _zero_bias(shape, dtype=np.float32):
    return np.zeros(shape, dtype)


# ---------------------------------------------------------------------------
# BERT
# ---------------------------------------------------------------------------

def bert_config_from_hf(config: dict, **overrides) -> Any:
    """HF bert config.json -> TransformerConfig (post-norm, exact gelu)."""
    from .flax_nets.bert import BertConfig

    kw = dict(
        vocab_size=config.get("vocab_size", 30522),
        hidden=config.get("hidden_size", 768),
        n_layers=config.get("num_hidden_layers", 12),
        n_heads=config.get("num_attention_heads", 12),
        mlp_dim=config.get("intermediate_size", 3072),
        max_len=config.get("max_position_embeddings", 512),
        norm_eps=config.get("layer_norm_eps", 1e-12),
    )
    act = config.get("hidden_act", "gelu")
    kw["act"] = {"gelu": "gelu", "gelu_new": "gelu_tanh",
                 "gelu_pytorch_tanh": "gelu_tanh"}.get(act, act)
    kw.update(overrides)
    return BertConfig(**kw)


def bert_params_from_hf(sd: dict[str, np.ndarray], num_classes: int | None = None,
                        seed: int = 0, n_heads: int | None = None) -> dict:
    """HF BertModel / BertForSequenceClassification state dict ->
    ``BertClassifier`` param pytree.

    When the checkpoint has no classifier head (plain BertModel) and
    ``num_classes`` is given, the head is seeded with small random values
    (the transfer-learning init of ``LitDeepTextModel``)."""
    body = _strip_prefix(sd, "bert.")
    n_layers = 1 + max(int(k.split(".")[2]) for k in body if k.startswith("encoder.layer."))
    hidden = body["embeddings.word_embeddings.weight"].shape[1]
    if n_heads is None:  # standalone fallback; prefer the config.json value
        n_heads = max(hidden // 64, 1)
    head_dim = hidden // n_heads

    params: dict[str, Any] = {
        "embeddings": {
            "word": {"embedding": body["embeddings.word_embeddings.weight"]},
            "position": {"embedding": body["embeddings.position_embeddings.weight"]},
            "segment": {"embedding": body["embeddings.token_type_embeddings.weight"]},
            "LayerNorm_0": _ln(body, "embeddings.LayerNorm"),
        },
        "encoder": {},
    }
    for i in range(n_layers):
        p = f"encoder.layer.{i}"
        params["encoder"][f"layer_{i}"] = {
            "attn": {
                "q": _qkv(body, f"{p}.attention.self.query", n_heads, head_dim),
                "k": _qkv(body, f"{p}.attention.self.key", n_heads, head_dim),
                "v": _qkv(body, f"{p}.attention.self.value", n_heads, head_dim),
                "o": _oproj(body, f"{p}.attention.output.dense", n_heads, head_dim),
            },
            "LayerNorm_0": _ln(body, f"{p}.attention.output.LayerNorm"),
            "mlp": {
                "up": _dense(body, f"{p}.intermediate.dense"),
                "down": _dense(body, f"{p}.output.dense"),
            },
            "LayerNorm_1": _ln(body, f"{p}.output.LayerNorm"),
        }
    if "pooler.dense.weight" in body:
        params["pooler"] = _dense(body, "pooler.dense")
    if "classifier.weight" in sd:
        params["classifier"] = _dense(sd, "classifier")
    if num_classes is not None:
        if "pooler" not in params:
            rng = np.random.default_rng(seed)
            params["pooler"] = {
                "kernel": rng.normal(0, 0.02, (hidden, hidden)).astype(np.float32),
                "bias": _zero_bias((hidden,))}
        head = params.get("classifier")
        if head is None or head["kernel"].shape[1] != num_classes:
            rng = np.random.default_rng(seed + 1)
            params["classifier"] = {
                "kernel": rng.normal(0, 0.02, (hidden, num_classes)).astype(np.float32),
                "bias": _zero_bias((num_classes,))}
    return params


# ---------------------------------------------------------------------------
# ViT
# ---------------------------------------------------------------------------

def vit_config_from_hf(config: dict, **overrides) -> Any:
    from .flax_nets.vit import vit_b16

    image, patch = config.get("image_size", 224), config.get("patch_size", 16)
    kw = dict(
        hidden=config.get("hidden_size", 768),
        n_layers=config.get("num_hidden_layers", 12),
        n_heads=config.get("num_attention_heads", 12),
        mlp_dim=config.get("intermediate_size", 3072),
        max_len=1 + (image // patch) ** 2,
        norm_eps=config.get("layer_norm_eps", 1e-12),
    )
    act = config.get("hidden_act", "gelu")
    kw["act"] = {"gelu": "gelu", "gelu_new": "gelu_tanh",
                 "gelu_pytorch_tanh": "gelu_tanh"}.get(act, act)
    kw.update(overrides)
    return vit_b16(**kw)


def vit_params_from_hf(sd: dict[str, np.ndarray], num_classes: int | None = None,
                       seed: int = 0, n_heads: int | None = None) -> dict:
    """HF ViTModel / ViTForImageClassification -> ``ViTClassifier`` params."""
    body = _strip_prefix(sd, "vit.")
    n_layers = 1 + max(int(k.split(".")[2]) for k in body if k.startswith("encoder.layer."))
    hidden = body["embeddings.cls_token"].shape[-1]
    if n_heads is None:
        n_heads = max(hidden // 64, 1)
    head_dim = hidden // n_heads

    params: dict[str, Any] = {
        "cls": body["embeddings.cls_token"],
        "pos_embed": body["embeddings.position_embeddings"],
        "patch_embed": _conv(body, "embeddings.patch_embeddings.projection"),
        "encoder": {"LayerNorm_0": _ln(body, "layernorm")},  # final (pre-norm)
    }
    for i in range(n_layers):
        p = f"encoder.layer.{i}"
        params["encoder"][f"layer_{i}"] = {
            "LayerNorm_0": _ln(body, f"{p}.layernorm_before"),
            "attn": {
                "q": _qkv(body, f"{p}.attention.attention.query", n_heads, head_dim),
                "k": _qkv(body, f"{p}.attention.attention.key", n_heads, head_dim),
                "v": _qkv(body, f"{p}.attention.attention.value", n_heads, head_dim),
                "o": _oproj(body, f"{p}.attention.output.dense", n_heads, head_dim),
            },
            "LayerNorm_1": _ln(body, f"{p}.layernorm_after"),
            "mlp": {
                "up": _dense(body, f"{p}.intermediate.dense"),
                "down": _dense(body, f"{p}.output.dense"),
            },
        }
    if "classifier.weight" in sd:
        params["head"] = _dense(sd, "classifier")
    if num_classes is not None:
        head = params.get("head")
        if head is None or head["kernel"].shape[1] != num_classes:
            rng = np.random.default_rng(seed)
            params["head"] = {
                "kernel": rng.normal(0, 0.02, (hidden, num_classes)).astype(np.float32),
                "bias": _zero_bias((num_classes,))}
    return params


# ---------------------------------------------------------------------------
# Llama
# ---------------------------------------------------------------------------

def llama_config_from_hf(config: dict, **overrides) -> Any:
    from .flax_nets.llama import llama2_7b

    kw = dict(
        vocab_size=config.get("vocab_size", 32000),
        hidden=config.get("hidden_size", 4096),
        n_layers=config.get("num_hidden_layers", 32),
        n_heads=config.get("num_attention_heads", 32),
        n_kv_heads=config.get("num_key_value_heads",
                              config.get("num_attention_heads", 32)),
        mlp_dim=config.get("intermediate_size", 11008),
        max_len=config.get("max_position_embeddings", 4096),
        norm_eps=config.get("rms_norm_eps", 1e-5),
        rope_theta=config.get("rope_theta", 10000.0),
    )
    if config.get("num_local_experts"):  # Mixtral-family sparse-MoE decoder
        E = int(config["num_local_experts"])
        k = int(config.get("num_experts_per_tok", 2))
        kw.update(moe_experts=E, moe_top_k=k,
                  # HF routing is DROPLESS: per-token expert choices are
                  # distinct, so one expert receives at most S tokens —
                  # capacity C = cf*S*k/E with cf = E/k gives exactly C = S.
                  # Dropless capacity REQUIRES the scatter dispatch: the
                  # einsum layout's [S, E, C] one-hot tensors are O(S^2*E)
                  # at C = S, unrunnable at real sequence lengths; scatter
                  # keeps it at O(E*S*H) buffers + O(S*k) index vectors.
                  moe_capacity_factor=float(E) / k,
                  moe_dispatch="scatter")
    kw.update(overrides)
    return llama2_7b(**kw)


def llama_params_from_hf(sd: dict[str, np.ndarray],
                         n_heads: int | None = None) -> dict:
    """HF LlamaForCausalLM (or bare LlamaModel) -> ``LlamaLM`` params.

    Handles GQA (kv head count inferred from k_proj shape) and tied
    embeddings (missing lm_head falls back to embed_tokens.T)."""
    body = _strip_prefix(sd, "model.")
    n_layers = 1 + max(int(k.split(".")[1]) for k in body if k.startswith("layers."))
    embed = body["embed_tokens.weight"]
    hidden = embed.shape[1]
    q0 = body["layers.0.self_attn.q_proj.weight"]
    k0 = body["layers.0.self_attn.k_proj.weight"]
    if n_heads is None:  # standalone fallback; prefer the config.json value
        n_heads = max(hidden // 64, 1)
    head_dim = q0.shape[0] // n_heads
    n_kv = k0.shape[0] // head_dim

    decoder: dict[str, Any] = {}
    for i in range(n_layers):
        p = f"layers.{i}"
        decoder[f"layer_{i}"] = {
            "RMSNorm_0": {"scale": body[f"{p}.input_layernorm.weight"]},
            "attn": {
                "q": _qkv(body, f"{p}.self_attn.q_proj", n_heads, head_dim),
                "k": _qkv(body, f"{p}.self_attn.k_proj", n_kv, head_dim),
                "v": _qkv(body, f"{p}.self_attn.v_proj", n_kv, head_dim),
                "o": _oproj(body, f"{p}.self_attn.o_proj", n_heads, head_dim),
            },
            "RMSNorm_1": {"scale": body[f"{p}.post_attention_layernorm.weight"]},
        }
        moe_gate = f"{p}.block_sparse_moe.gate.weight"
        if moe_gate in body:
            # Mixtral sparse-MoE block: router gate [E, H]; per-expert
            # w1 (SwiGLU gate), w3 (up), w2 (down), all bias-free
            E = sum(1 for k in body
                    if k.startswith(f"{p}.block_sparse_moe.experts.")
                    and k.endswith(".w1.weight"))
            ex = f"{p}.block_sparse_moe.experts"
            w_gate = np.stack([np.ascontiguousarray(
                body[f"{ex}.{e}.w1.weight"].T) for e in range(E)])
            w_up = np.stack([np.ascontiguousarray(
                body[f"{ex}.{e}.w3.weight"].T) for e in range(E)])
            w_dn = np.stack([np.ascontiguousarray(
                body[f"{ex}.{e}.w2.weight"].T) for e in range(E)])
            decoder[f"layer_{i}"]["mlp"] = {
                "router": {"kernel": np.ascontiguousarray(body[moe_gate].T)},
                "w_gate": w_gate, "w_up": w_up, "w_dn": w_dn,
                "b_up": _zero_bias(w_up.shape[::2], w_up.dtype),
                "b_dn": _zero_bias(w_dn.shape[::2], w_dn.dtype),
            }
        else:
            decoder[f"layer_{i}"]["mlp"] = {
                "gate": _dense(body, f"{p}.mlp.gate_proj"),
                "up": _dense(body, f"{p}.mlp.up_proj"),
                "down": _dense(body, f"{p}.mlp.down_proj"),
            }
            for proj in ("gate", "up", "down"):
                d = decoder[f"layer_{i}"]["mlp"][proj]
                if "bias" not in d:
                    d["bias"] = _zero_bias((d["kernel"].shape[1],), d["kernel"].dtype)
    decoder["RMSNorm_0"] = {"scale": body["norm.weight"]}

    lm_head = (np.ascontiguousarray(sd["lm_head.weight"].T)
               if "lm_head.weight" in sd else np.ascontiguousarray(embed.T))
    return {"embed": {"embedding": embed}, "decoder": decoder,
            "lm_head": {"kernel": lm_head}}


# ---------------------------------------------------------------------------
# ResNet
# ---------------------------------------------------------------------------

def _hf_resnet_to_torchvision_keys(sd: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
    """Translate HF ``microsoft/resnet-*`` keys to torchvision naming.

    HF layout (default ``downsample_in_bottleneck=False`` matches torchvision
    v1.5 math — stride on the 3x3): ``resnet.embedder.embedder.convolution`` ->
    conv1, ``resnet.encoder.stages.{s}.layers.{j}.layer.{k}.{convolution,
    normalization}`` -> layer{s+1}.{j}.conv{k+1}/bn{k+1}, ``shortcut`` ->
    downsample, ``classifier.1`` -> fc."""
    out: dict[str, np.ndarray] = {}
    for k, v in sd.items():
        k = k.removeprefix("resnet.")
        if k.startswith("embedder.embedder."):
            rest = k.split("embedder.embedder.", 1)[1]
            if rest.startswith("convolution."):
                out["conv1." + rest.split(".", 1)[1]] = v
            else:  # normalization.*
                out["bn1." + rest.split(".", 1)[1]] = v
        elif k.startswith("encoder.stages."):
            parts = k.split(".")
            s, j = int(parts[2]), int(parts[4])
            rest = parts[5:]
            if rest[0] == "layer":  # layer.{k}.convolution/normalization
                kk = int(rest[1])
                mod = "conv" if rest[2] == "convolution" else "bn"
                out[f"layer{s + 1}.{j}.{mod}{kk + 1}.{'.'.join(rest[3:])}"] = v
            elif rest[0] == "shortcut":
                mod = "0" if rest[1] == "convolution" else "1"
                out[f"layer{s + 1}.{j}.downsample.{mod}.{'.'.join(rest[2:])}"] = v
        elif k.startswith("classifier."):
            out["fc." + k.split(".", 2)[2]] = v
        else:
            out[k] = v
    return out


def resnet_variables_from_torch(sd: dict[str, np.ndarray]) -> dict:
    """torchvision-style ResNet state dict -> ``{"params", "batch_stats"}``
    variables for ``flax_nets.resnet.ResNet``. Accepts HF resnet naming too."""
    if any(k.startswith(("resnet.", "embedder.", "encoder.stages.")) for k in sd):
        sd = _hf_resnet_to_torchvision_keys(sd)

    params: dict[str, Any] = {"stem": _conv(sd, "conv1"),
                              "stem_bn": {"scale": sd["bn1.weight"], "bias": sd["bn1.bias"]}}
    stats: dict[str, Any] = {"stem_bn": {"mean": sd["bn1.running_mean"],
                                         "var": sd["bn1.running_var"]}}

    stages = sorted({int(k[5]) for k in sd if k.startswith("layer")})
    for s in stages:
        blocks = sorted({int(k.split(".")[1]) for k in sd if k.startswith(f"layer{s}.")})
        for j in blocks:
            name = f"stage{s - 1}_block{j}"
            base = f"layer{s}.{j}"
            p: dict[str, Any] = {}
            st: dict[str, Any] = {}
            convs = sorted({k.split(".")[2] for k in sd
                            if k.startswith(f"{base}.conv")})
            for c in convs:
                n = c[-1]
                p[f"conv{n}"] = _conv(sd, f"{base}.conv{n}")
                p[f"bn{n}"] = {"scale": sd[f"{base}.bn{n}.weight"],
                               "bias": sd[f"{base}.bn{n}.bias"]}
                st[f"bn{n}"] = {"mean": sd[f"{base}.bn{n}.running_mean"],
                                "var": sd[f"{base}.bn{n}.running_var"]}
            if f"{base}.downsample.0.weight" in sd:
                p["proj"] = _conv(sd, f"{base}.downsample.0")
                p["bn_proj"] = {"scale": sd[f"{base}.downsample.1.weight"],
                                "bias": sd[f"{base}.downsample.1.bias"]}
                st["bn_proj"] = {"mean": sd[f"{base}.downsample.1.running_mean"],
                                 "var": sd[f"{base}.downsample.1.running_var"]}
            params[name] = p
            stats[name] = st
    if "fc.weight" in sd:
        params["head"] = _dense(sd, "fc")
    return {"params": params, "batch_stats": stats}


def resnet_arch_from_hf_config(config: dict) -> dict:
    """HF resnet config.json -> ``ResNet(...)`` constructor kwargs."""
    depths = config.get("depths", [3, 4, 6, 3])
    layer_type = config.get("layer_type", "bottleneck")
    return {"stage_sizes": tuple(depths),
            "block": "bottleneck" if layer_type == "bottleneck" else "basic",
            "width": config.get("embedding_size", 64)}


# ---------------------------------------------------------------------------
# high-level checkpoint-directory entry points
# ---------------------------------------------------------------------------

def pretrained_text_classifier(ckpt_dir: str, num_classes: int, seed: int = 0,
                               **cfg_overrides):
    """(TransformerConfig, params) for ``BertClassifier`` from a local HF dir."""
    config, sd = load_checkpoint(ckpt_dir)
    cfg = bert_config_from_hf(config, **cfg_overrides)
    return cfg, bert_params_from_hf(sd, num_classes=num_classes, seed=seed,
                                    n_heads=cfg.n_heads)


def pretrained_encoder(ckpt_dir: str, **cfg_overrides):
    """(TransformerConfig, params) for the headless BERT encoder
    (HuggingFaceSentenceEmbedder backbone)."""
    config, sd = load_checkpoint(ckpt_dir)
    cfg = bert_config_from_hf(config, **cfg_overrides)
    params = bert_params_from_hf(sd, n_heads=cfg.n_heads)
    params.pop("pooler", None)
    params.pop("classifier", None)
    return cfg, params


def pretrained_vision(ckpt_dir: str, num_classes: int | None = None, seed: int = 0,
                      **cfg_overrides):
    """(module-or-config info, variables) for vision checkpoints.

    Returns ``("vit", cfg, {"params": ...})`` or
    ``("resnet", arch_kwargs, {"params": ..., "batch_stats": ...})``."""
    config, sd = load_checkpoint(ckpt_dir)
    mt = config.get("model_type", "")
    if mt == "vit" or any(k.startswith(("vit.", "embeddings.cls_token")) for k in sd):
        cfg = vit_config_from_hf(config, **cfg_overrides)
        info = {"cfg": cfg, "patch": config.get("patch_size", 16)}
        return "vit", info, {"params": vit_params_from_hf(
            sd, num_classes=num_classes, seed=seed, n_heads=cfg.n_heads)}
    if mt == "resnet" or any("resnet" in k or k.startswith("layer1.") for k in sd):
        arch = resnet_arch_from_hf_config(config)
        variables = resnet_variables_from_torch(sd)
        if num_classes is not None:
            head = variables["params"].get("head")
            if head is None or head["kernel"].shape[1] != num_classes:
                if head is not None:
                    feat = head["kernel"].shape[0]
                else:  # final stage width: width * 2^(stages-1) * expansion
                    expansion = 4 if arch["block"] == "bottleneck" else 1
                    feat = arch["width"] * (2 ** (len(arch["stage_sizes"]) - 1)) * expansion
                rng = np.random.default_rng(seed)
                variables["params"]["head"] = {
                    "kernel": rng.normal(0, 0.02, (feat, num_classes)).astype(np.float32),
                    "bias": _zero_bias((num_classes,))}
        return "resnet", arch, variables
    raise ValueError(f"unrecognized vision checkpoint (model_type={mt!r})")


def gpt2_config_from_hf(config: dict, **overrides) -> Any:
    """HF GPT-2 config -> the generic causal-LM TransformerConfig: LayerNorm
    pre-norm with biases, tanh-GELU MLP, learned absolute positions, no
    RoPE — ``LlamaLM`` runs it unchanged (the wrapper adds wpe when
    ``learned_pos``)."""
    from .flax_nets.llama import llama2_7b

    kw = dict(
        vocab_size=config.get("vocab_size", 50257),
        hidden=config.get("n_embd", 768),
        n_layers=config.get("n_layer", 12),
        n_heads=config.get("n_head", 12),
        n_kv_heads=config.get("n_head", 12),
        mlp_dim=config.get("n_inner") or 4 * config.get("n_embd", 768),
        max_len=config.get("n_positions", config.get("n_ctx", 1024)),
        norm_eps=config.get("layer_norm_epsilon", 1e-5),
    )
    act_map = {"gelu_new": "gelu_tanh", "gelu_pytorch_tanh": "gelu_tanh",
               "gelu": "gelu", "relu": "relu", "silu": "silu",
               "swish": "silu"}
    hf_act = config.get("activation_function", "gelu_new")
    if hf_act not in act_map:
        raise NotImplementedError(
            f"GPT-2 activation_function={hf_act!r} is not supported")
    for flag in ("scale_attn_by_inverse_layer_idx", "reorder_and_upcast_attn"):
        if config.get(flag):
            raise NotImplementedError(
                f"GPT-2 {flag}=true changes attention math; this mapping "
                "covers the standard-attention family only")
    kw.update(norm="layernorm", act=act_map[hf_act], gated_mlp=False,
              use_rope=False, learned_pos=True)
    kw.update(overrides)
    return llama2_7b(**kw)


def gpt2_params_from_hf(sd: dict[str, np.ndarray], n_heads: int) -> dict:
    """HF GPT2LMHeadModel (or bare GPT2Model) -> ``LlamaLM`` params.

    GPT-2 Conv1D weights are stored ``[in, out]`` (already kernel-shaped, no
    transpose); ``c_attn`` fuses qkv and splits here; the LM head is tied
    to ``wte``."""
    body = _strip_prefix(sd, "transformer.")
    n_layers = 1 + max(int(k.split(".")[1]) for k in body
                       if k.startswith("h."))
    embed = body["wte.weight"]
    hidden = embed.shape[1]
    D = hidden // n_heads

    decoder: dict[str, Any] = {}
    for i in range(n_layers):
        p = f"h.{i}"
        w = body[f"{p}.attn.c_attn.weight"]    # Conv1D [H, 3H] (kernel-shaped)
        b = body[f"{p}.attn.c_attn.bias"]      # [3H]
        wq, wk, wv = np.split(w, 3, axis=1)
        bq, bk, bv = np.split(b, 3)
        wo = body[f"{p}.attn.c_proj.weight"]   # [H, H]
        decoder[f"layer_{i}"] = {
            "LayerNorm_0": _ln(body, f"{p}.ln_1"),
            "attn": {
                # DenseGeneral shapes: qkv [H, heads, D], o [heads, D, H]
                "q": {"kernel": wq.reshape(hidden, n_heads, D),
                      "bias": bq.reshape(n_heads, D)},
                "k": {"kernel": wk.reshape(hidden, n_heads, D),
                      "bias": bk.reshape(n_heads, D)},
                "v": {"kernel": wv.reshape(hidden, n_heads, D),
                      "bias": bv.reshape(n_heads, D)},
                "o": {"kernel": wo.reshape(n_heads, D, hidden),
                      "bias": body[f"{p}.attn.c_proj.bias"]},
            },
            "LayerNorm_1": _ln(body, f"{p}.ln_2"),
            "mlp": {
                "up": {"kernel": body[f"{p}.mlp.c_fc.weight"],
                       "bias": body[f"{p}.mlp.c_fc.bias"]},
                "down": {"kernel": body[f"{p}.mlp.c_proj.weight"],
                         "bias": body[f"{p}.mlp.c_proj.bias"]},
            },
        }
    decoder["LayerNorm_0"] = _ln(body, "ln_f")
    lm_head = (np.ascontiguousarray(sd["lm_head.weight"].T)
               if "lm_head.weight" in sd else np.ascontiguousarray(embed.T))
    return {"embed": {"embedding": embed},
            "wpe": {"embedding": body["wpe.weight"]},
            "decoder": decoder, "lm_head": {"kernel": lm_head}}


def pretrained_causal_lm(ckpt_dir: str, **cfg_overrides):
    """(TransformerConfig, params) for ``LlamaLM`` from a local HF dir.

    Dispatches on ``config.json``'s ``model_type``: llama/mistral/mixtral
    share the Llama mapping; ``gpt2`` takes the learned-position LayerNorm
    mapping."""
    config, sd = load_checkpoint(ckpt_dir)
    if config.get("model_type") == "gpt2":
        cfg = gpt2_config_from_hf(config, **cfg_overrides)
        return cfg, gpt2_params_from_hf(sd, n_heads=cfg.n_heads)
    cfg = llama_config_from_hf(config, **cfg_overrides)
    return cfg, llama_params_from_hf(sd, n_heads=cfg.n_heads)


def shard_pretrained_params(params, mesh_config, partition_rules=None):
    """Place a converted plain param pytree on a mesh via the declarative
    rule table (``parallel.partition``) — the sharding plane's replacement
    for the ``eval_shape``-rebox path: no module init, no
    ``nn.Partitioned`` metadata, works for ANY tree this module emits.
    Returns ``(mesh_ctx, placed_params)``; ``partition_rules`` defaults to
    the Llama table (which also covers the GPT-2 mapping's param names).
    """
    from ..parallel.mesh import create_mesh
    from ..parallel.partition import default_llama_rules, shard_tree

    mesh_ctx = create_mesh(mesh_config)
    rules = partition_rules if partition_rules is not None \
        else default_llama_rules(mesh=mesh_ctx.config)
    return mesh_ctx, shard_tree(params, mesh_ctx, rules)
