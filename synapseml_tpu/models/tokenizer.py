"""Tokenizers for the text estimators.

Reference: DeepTextClassifier pins HF ``AutoTokenizer`` downloads
(``dl/DeepTextClassifier.py:10-24``). This container has zero egress, so the
default is a self-contained hashing word-piece tokenizer (deterministic, no
vocab files); an HF tokenizer plugs in transparently when one is available
locally (`from_huggingface`).
"""

from __future__ import annotations

import re
import zlib
from typing import Sequence

import numpy as np

__all__ = ["HashingTokenizer", "from_huggingface", "resolve_tokenizer"]

_WORD_RE = re.compile(r"[a-z0-9]+|[^\sa-z0-9]", re.IGNORECASE)


class HashingTokenizer:
    """Deterministic feature-hashing tokenizer: token -> 2 + crc32(token) % (V-2).
    ids 0/1 reserved for [PAD]/[CLS]."""

    PAD, CLS = 0, 1

    def __init__(self, vocab_size: int = 30522, lowercase: bool = True, add_cls: bool = True):
        self.vocab_size = vocab_size
        self.lowercase = lowercase
        self.add_cls = add_cls

    def tokenize(self, text: str) -> list[int]:
        if self.lowercase:
            text = text.lower()
        toks = _WORD_RE.findall(text or "")
        ids = [2 + (zlib.crc32(t.encode()) % (self.vocab_size - 2)) for t in toks]
        return ([self.CLS] + ids) if self.add_cls else ids

    def __call__(self, texts: Sequence[str], max_len: int = 128,
                 multiple_of: int = 8) -> dict[str, np.ndarray]:
        from ..parallel.batching import pad_sequences

        seqs = [self.tokenize(t) for t in texts]
        ids, mask = pad_sequences(seqs, max_len=max_len, pad_value=self.PAD,
                                  multiple_of=multiple_of)
        return {"input_ids": ids, "attention_mask": mask}

    def to_config(self) -> dict:
        return {"kind": "hashing", "vocab_size": self.vocab_size,
                "lowercase": self.lowercase, "add_cls": self.add_cls}

    @staticmethod
    def from_config(cfg: dict) -> "HashingTokenizer":
        return HashingTokenizer(cfg["vocab_size"], cfg["lowercase"], cfg["add_cls"])


class _HFTokenizerAdapter:
    def __init__(self, tok, name: str):
        self._tok = tok
        self.name = name
        self.vocab_size = tok.vocab_size

    def __call__(self, texts, max_len: int = 128, multiple_of: int = 8):
        from ..parallel.batching import round_up_to_multiple

        L = round_up_to_multiple(max_len, multiple_of)
        enc = self._tok(list(texts), padding="max_length", truncation=True, max_length=L,
                        return_tensors="np")
        return {"input_ids": enc["input_ids"].astype(np.int32),
                "attention_mask": enc["attention_mask"].astype(np.int32)}

    def decode(self, token_ids) -> str:
        """Detokenize (the HF tokenizer can; the hashing one cannot) — the
        causal-LM transform and token-streaming serving probe for this."""
        return self._tok.decode(list(token_ids), skip_special_tokens=True)

    def to_config(self) -> dict:
        return {"kind": "huggingface", "name": self.name}


def from_huggingface(name: str):
    from transformers import AutoTokenizer

    return _HFTokenizerAdapter(AutoTokenizer.from_pretrained(name), name)


def resolve_tokenizer(spec) -> HashingTokenizer | _HFTokenizerAdapter:
    """spec: None | tokenizer obj | config dict | HF checkpoint name."""
    if spec is None:
        return HashingTokenizer()
    if isinstance(spec, (HashingTokenizer, _HFTokenizerAdapter)):
        return spec
    if isinstance(spec, dict):
        if spec.get("kind") == "huggingface":
            return from_huggingface(spec["name"])
        return HashingTokenizer.from_config(spec)
    if isinstance(spec, str):
        try:
            return from_huggingface(spec)
        except Exception as e:
            raise ValueError(
                f"could not load HuggingFace tokenizer {spec!r} ({e}); pass "
                "tokenizer=None for the self-contained HashingTokenizer") from e
    raise TypeError(f"cannot build tokenizer from {spec!r}")
