"""TrainClassifier / TrainRegressor — auto-featurize + fit any learner.

Reference: ``train/TrainClassifier.scala:52`` / ``TrainRegressor.scala`` —
wraps any SparkML learner: featurizes non-numeric columns, indexes string
labels, fits, and returns a model that runs the same featurization at
transform time."""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Estimator, Model
from ..featurize import Featurize, ValueIndexer

__all__ = ["TrainClassifier", "TrainedClassifierModel",
           "TrainRegressor", "TrainedRegressorModel"]


class _TrainBase:
    model = ComplexParam("model", "the learner to fit (an Estimator)")
    label_col = Param("label_col", "label column", default="label")
    features_col = Param("features_col", "assembled features column", default="features")
    num_features = Param("num_features", "hash buckets for high-cardinality strings",
                         default=256, converter=TypeConverters.to_int)

    def _feature_cols(self, df: DataFrame) -> list[str]:
        skip = {self.get("label_col"), self.get("features_col")}
        return [c for c in df.columns if c not in skip]

    def _assemble(self, df: DataFrame):
        if self.get("features_col") in df.columns:
            return None, df  # pre-featurized
        feat = Featurize(input_cols=self._feature_cols(df),
                         output_col=self.get("features_col"),
                         num_features=self.get("num_features")).fit(df)
        return feat, feat.transform(df)


class TrainClassifier(Estimator, _TrainBase):
    """(ref ``TrainClassifier.scala:52``)"""

    feature_name = "train"

    def _fit(self, df: DataFrame) -> "TrainedClassifierModel":
        self.require_columns(df, self.get("label_col"))
        label_col = self.get("label_col")
        labels = df.collect_column(label_col)
        indexer_model = None
        if labels.dtype == object or labels.dtype.kind in ("U", "S"):  # string labels
            indexer_model = ValueIndexer(input_col=label_col, output_col=label_col).fit(df)
            df = indexer_model.transform(df)
        feat, fdf = self._assemble(df)
        learner = self.get("model")
        if learner is None:
            raise ValueError("TrainClassifier: set model=<an Estimator>")
        inner = learner.copy({"label_col": label_col,
                              "features_col": self.get("features_col")}).fit(fdf)
        return TrainedClassifierModel(featurizer=feat, label_indexer=indexer_model,
                                      inner_model=inner,
                                      features_col=self.get("features_col"),
                                      label_col=label_col)


class TrainedClassifierModel(Model):
    feature_name = "train"

    featurizer = ComplexParam("featurizer", "fitted FeaturizeModel (None if pre-featurized)")
    label_indexer = ComplexParam("label_indexer", "fitted label ValueIndexerModel or None")
    inner_model = ComplexParam("inner_model", "fitted learner model")
    features_col = Param("features_col", "assembled features column", default="features")
    label_col = Param("label_col", "label column", default="label")

    def _transform(self, df: DataFrame) -> DataFrame:
        feat = self.get("featurizer")
        cur = feat.transform(df) if feat is not None and self.get("features_col") not in df.columns else df
        out = self.get("inner_model").transform(cur)
        idx = self.get("label_indexer")
        if idx is not None and "prediction" in out.columns:
            from ..featurize import IndexToValue

            out = IndexToValue(input_col="prediction", output_col="predicted_label",
                               levels=idx.get("levels")).transform(out)
        return out


class TrainRegressor(Estimator, _TrainBase):
    """(ref ``train/TrainRegressor.scala``)"""

    feature_name = "train"

    def _fit(self, df: DataFrame) -> "TrainedRegressorModel":
        self.require_columns(df, self.get("label_col"))
        feat, fdf = self._assemble(df)
        learner = self.get("model")
        if learner is None:
            raise ValueError("TrainRegressor: set model=<an Estimator>")
        inner = learner.copy({"label_col": self.get("label_col"),
                              "features_col": self.get("features_col")}).fit(fdf)
        return TrainedRegressorModel(featurizer=feat, inner_model=inner,
                                     features_col=self.get("features_col"),
                                     label_col=self.get("label_col"))


class TrainedRegressorModel(Model):
    feature_name = "train"

    featurizer = ComplexParam("featurizer", "fitted FeaturizeModel (None if pre-featurized)")
    inner_model = ComplexParam("inner_model", "fitted learner model")
    features_col = Param("features_col", "assembled features column", default="features")
    label_col = Param("label_col", "label column", default="label")

    def _transform(self, df: DataFrame) -> DataFrame:
        feat = self.get("featurizer")
        cur = feat.transform(df) if feat is not None and self.get("features_col") not in df.columns else df
        return self.get("inner_model").transform(cur)
