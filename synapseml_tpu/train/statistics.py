"""Model-quality metrics (reference ``train/ComputeModelStatistics.scala:58``,
``ComputePerInstanceStatistics.scala``).

Classification: accuracy, precision, recall, AUC, confusion matrix.
Regression: MSE, RMSE, MAE, R^2. Metric math runs in numpy on the driver —
these are reductions over a column, not MXU work."""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame, _as_column
from ..core.params import Param, TypeConverters
from ..core.pipeline import Transformer

__all__ = ["ComputeModelStatistics", "ComputePerInstanceStatistics",
           "confusion_matrix", "roc_auc"]


def confusion_matrix(y_true: np.ndarray, y_pred: np.ndarray) -> np.ndarray:
    classes = np.unique(np.concatenate([y_true, y_pred]))
    k = len(classes)
    lut = {c: i for i, c in enumerate(classes)}
    cm = np.zeros((k, k), dtype=np.int64)
    for t, p in zip(y_true, y_pred):
        cm[lut[t], lut[p]] += 1
    return cm


def roc_auc(y_true: np.ndarray, scores: np.ndarray) -> float:
    """AUC via the rank statistic (ties get average rank)."""
    y = np.asarray(y_true) > 0
    n_pos, n_neg = int(y.sum()), int((~y).sum())
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    s = np.asarray(scores, dtype=np.float64)
    # average ranks over ties in O(n log n): group start/end from unique counts
    _, inverse, counts = np.unique(s, return_inverse=True, return_counts=True)
    ends = np.cumsum(counts)
    avg_rank = ends - (counts - 1) / 2.0  # mean of [end-count+1 .. end]
    ranks = avg_rank[inverse]
    return float((ranks[y].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


class ComputeModelStatistics(Transformer):
    """(ref ``ComputeModelStatistics.scala:58``) — returns a one-row metrics
    DataFrame; evaluation_metric: classification | regression | auto."""

    label_col = Param("label_col", "ground-truth column", default="label")
    scores_col = Param("scores_col", "prediction column", default="prediction")
    scored_probabilities_col = Param("scored_probabilities_col",
                                     "probability column (binary AUC)", default=None)
    evaluation_metric = Param("evaluation_metric", "classification | regression | auto",
                              default="auto")

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("label_col"), self.get("scores_col"))
        y = np.asarray(df.collect_column(self.get("label_col")))
        pred = np.asarray(df.collect_column(self.get("scores_col")))
        kind = self.get("evaluation_metric")
        if kind == "auto":
            few_levels = len(np.unique(y)) <= max(20, int(np.sqrt(len(y))))
            stringy = y.dtype == object or y.dtype.kind in ("U", "S")
            integral = (stringy or np.issubdtype(y.dtype, np.integer)
                        or bool(np.all(np.asarray(y, np.float64) % 1 == 0)))
            kind = "classification" if few_levels and integral else "regression"
        if kind == "classification":
            cm = confusion_matrix(y, pred)
            acc = float(np.trace(cm)) / max(cm.sum(), 1)
            with np.errstate(invalid="ignore", divide="ignore"):
                prec = np.diag(cm) / np.maximum(cm.sum(axis=0), 1)
                rec = np.diag(cm) / np.maximum(cm.sum(axis=1), 1)
            out = {"evaluation_type": _as_column(["Classification"]),
                   "accuracy": np.array([acc]),
                   "precision": np.array([float(np.mean(prec))]),
                   "recall": np.array([float(np.mean(rec))]),
                   "confusion_matrix": _as_column([cm])}
            pc = self.get("scored_probabilities_col")
            if pc and pc in df.columns and len(np.unique(y)) == 2:
                probs = np.asarray(df.collect_column(pc), np.float64)
                if probs.ndim == 2:
                    probs = probs[:, -1]
                pos = np.unique(y)[1]
                out["AUC"] = np.array([roc_auc(y == pos, probs)])
            return DataFrame([out])
        err = np.asarray(pred, np.float64) - np.asarray(y, np.float64)
        mse = float(np.mean(err**2))
        var = float(np.var(np.asarray(y, np.float64)))
        return DataFrame([{
            "evaluation_type": _as_column(["Regression"]),
            "mean_squared_error": np.array([mse]),
            "root_mean_squared_error": np.array([np.sqrt(mse)]),
            "mean_absolute_error": np.array([float(np.mean(np.abs(err)))]),
            "R^2": np.array([1.0 - mse / var if var > 0 else float("nan")]),
        }])


class ComputePerInstanceStatistics(Transformer):
    """Per-row loss/correctness (ref ``ComputePerInstanceStatistics.scala``)."""

    label_col = Param("label_col", "ground-truth column", default="label")
    scores_col = Param("scores_col", "prediction column", default="prediction")
    scored_probabilities_col = Param("scored_probabilities_col",
                                     "probability column for log-loss", default=None)
    evaluation_metric = Param("evaluation_metric", "classification | regression",
                              default="classification")

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("label_col"), self.get("scores_col"))
        if self.get("evaluation_metric") == "regression":
            def add(p):
                e = np.asarray(p[self.get("scores_col")], np.float64) - \
                    np.asarray(p[self.get("label_col")], np.float64)
                return e * e

            return (df.with_column("squared_error", add)
                      .with_column("absolute_error",
                                   lambda p: np.abs(np.asarray(p[self.get("scores_col")], np.float64)
                                                    - np.asarray(p[self.get("label_col")], np.float64))))
        out = df.with_column("correct",
                             lambda p: (np.asarray(p[self.get("scores_col")])
                                        == np.asarray(p[self.get("label_col")])).astype(np.float64))
        pc = self.get("scored_probabilities_col")
        if pc and pc in df.columns:
            # global class set (not per-partition: a partition missing a class
            # would silently shift every label's probability index)
            all_labels = np.asarray(df.collect_column(self.get("label_col")))
            classes = (np.unique(all_labels)
                       if not np.issubdtype(all_labels.dtype, np.number) else None)

            def logloss(p):
                probs = np.asarray(np.stack([np.atleast_1d(np.asarray(v, np.float64))
                                             for v in p[pc]]))
                y = np.asarray(p[self.get("label_col")])
                if classes is not None:
                    # string/categorical labels: index by globally-sorted
                    # unique value, matching ValueIndexer's label ordering
                    y = np.searchsorted(classes, y)
                if probs.shape[1] == 1:  # binary prob of positive class
                    pr = np.clip(probs[:, 0], 1e-12, 1 - 1e-12)
                    return -(y * np.log(pr) + (1 - y) * np.log(1 - pr))
                idx = y.astype(np.int64)
                pr = np.clip(probs[np.arange(len(y)), idx], 1e-12, None)
                return -np.log(pr)

            out = out.with_column("log_loss", logloss)
        return out
