"""Training convenience layer (reference ``core/.../train/``, SURVEY.md §2.5):
auto-featurize + fit any learner, plus model-quality metrics."""

from .train import TrainClassifier, TrainRegressor, TrainedClassifierModel, TrainedRegressorModel  # noqa: F401
from .statistics import ComputeModelStatistics, ComputePerInstanceStatistics  # noqa: F401
