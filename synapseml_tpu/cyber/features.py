"""Per-tenant feature utilities (reference ``cyber/feature/{scalers,indexers}.py``):
scalers standardize/min-max a numeric column WITHIN each tenant partition;
IdIndexer assigns per-tenant contiguous integer ids."""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Estimator, Model

__all__ = ["PartitionedStandardScaler", "PartitionedMinMaxScaler",
           "IdIndexer", "IdIndexerModel"]

_DEFAULT_TENANT = "__single_tenant__"


class _PartitionedScalerBase(Estimator):
    tenant_col = Param("tenant_col", "tenant column (None = global)", default=None)
    input_col = Param("input_col", "numeric column", default="value")
    output_col = Param("output_col", "scaled column", default="scaled")

    def _tenants_of(self, df: DataFrame) -> np.ndarray:
        tc = self.get("tenant_col")
        n = df.count()
        return (np.asarray(df.collect_column(tc)) if tc
                else np.full(n, _DEFAULT_TENANT, dtype=object))

    def _stats(self, vals: np.ndarray) -> dict:  # pragma: no cover - abstract
        raise NotImplementedError

    def _fit(self, df: DataFrame) -> "_PartitionedScalerModel":
        self.require_columns(df, self.get("input_col"))
        if self.get("tenant_col"):
            self.require_columns(df, self.get("tenant_col"))
        vals = np.asarray(df.collect_column(self.get("input_col")), np.float64)
        tenants = self._tenants_of(df)
        stats = {str(t): self._stats(vals[tenants == t]) for t in np.unique(tenants)}
        return _PartitionedScalerModel(
            stats=stats, kind=type(self).__name__,
            tenant_col=self.get("tenant_col"), input_col=self.get("input_col"),
            output_col=self.get("output_col"))


class PartitionedStandardScaler(_PartitionedScalerBase):
    """(ref ``cyber/feature/scalers.py`` StandardScalarScaler)"""

    feature_name = "cyber"

    def _stats(self, vals: np.ndarray) -> dict:
        return {"mean": float(vals.mean()) if len(vals) else 0.0,
                "std": float(vals.std()) or 1.0}


class PartitionedMinMaxScaler(_PartitionedScalerBase):
    """(ref ``cyber/feature/scalers.py`` LinearScalarScaler)"""

    feature_name = "cyber"

    min_value = Param("min_value", "target range min", default=0.0,
                      converter=TypeConverters.to_float)
    max_value = Param("max_value", "target range max", default=1.0,
                      converter=TypeConverters.to_float)

    def _stats(self, vals: np.ndarray) -> dict:
        lo = float(vals.min()) if len(vals) else 0.0
        hi = float(vals.max()) if len(vals) else 1.0
        return {"lo": lo, "hi": hi, "t_lo": self.get("min_value"),
                "t_hi": self.get("max_value")}


class _PartitionedScalerModel(Model):
    stats = ComplexParam("stats", "per-tenant statistics")
    kind = Param("kind", "scaler flavor")
    tenant_col = Param("tenant_col", "tenant column", default=None)
    input_col = Param("input_col", "numeric column", default="value")
    output_col = Param("output_col", "scaled column", default="scaled")

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))
        tc = self.get("tenant_col")
        stats = self.get("stats")
        standard = self.get("kind") == "PartitionedStandardScaler"

        def scale(p):
            vals = np.asarray(p[self.get("input_col")], np.float64)
            tenants = p[tc] if tc else [_DEFAULT_TENANT] * len(vals)
            out = np.zeros(len(vals))
            for i, (v, t) in enumerate(zip(vals, tenants)):
                s = stats.get(str(t))
                if s is None:
                    out[i] = np.nan
                elif standard:
                    out[i] = (v - s["mean"]) / s["std"]
                else:
                    span = (s["hi"] - s["lo"]) or 1.0
                    out[i] = s["t_lo"] + (v - s["lo"]) / span * (s["t_hi"] - s["t_lo"])
            return out

        return df.with_column(self.get("output_col"), scale)


class IdIndexer(Estimator):
    """(ref ``cyber/feature/indexers.py``) per-tenant contiguous ids."""

    feature_name = "cyber"

    tenant_col = Param("tenant_col", "tenant column (None = global)", default=None)
    input_col = Param("input_col", "id column", default="user")
    output_col = Param("output_col", "indexed column", default="user_id")
    reset_per_partition = Param("reset_per_partition", "ids restart per tenant",
                                default=True, converter=TypeConverters.to_bool)

    def _fit(self, df: DataFrame) -> "IdIndexerModel":
        self.require_columns(df, self.get("input_col"))
        if self.get("tenant_col"):
            self.require_columns(df, self.get("tenant_col"))
        vals = np.asarray(df.collect_column(self.get("input_col")))
        tc = self.get("tenant_col")
        tenants = (np.asarray(df.collect_column(tc)) if tc
                   else np.full(len(vals), _DEFAULT_TENANT, dtype=object))
        mapping: dict = {}
        if self.get("reset_per_partition"):
            for t in np.unique(tenants):
                levels = np.unique(vals[tenants == t])
                mapping[str(t)] = {str(v): i for i, v in enumerate(levels)}
        else:
            levels = np.unique(vals)
            flat = {str(v): i for i, v in enumerate(levels)}
            for t in np.unique(tenants):
                mapping[str(t)] = flat
        return IdIndexerModel(mapping=mapping, tenant_col=tc,
                              input_col=self.get("input_col"),
                              output_col=self.get("output_col"))


class IdIndexerModel(Model):
    mapping = ComplexParam("mapping", "tenant -> value -> id")
    tenant_col = Param("tenant_col", "tenant column", default=None)
    input_col = Param("input_col", "id column", default="user")
    output_col = Param("output_col", "indexed column", default="user_id")

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))
        tc = self.get("tenant_col")
        mapping = self.get("mapping")

        def index(p):
            vals = p[self.get("input_col")]
            tenants = p[tc] if tc else [_DEFAULT_TENANT] * len(vals)
            return np.asarray([mapping.get(str(t), {}).get(str(v), -1)
                               for t, v in zip(tenants, vals)], np.int64)

        return df.with_column(self.get("output_col"), index)
