"""CyberML (reference python-only ``core/src/main/python/synapse/ml/cyber/`` —
SURVEY.md §2.5): user-resource access anomaly detection via collaborative
filtering (``anomaly/collaborative_filtering.py``, 1226 LoC), complement
access sampling, and per-tenant feature scalers/indexers."""

from .anomaly import AccessAnomaly, AccessAnomalyModel, ComplementAccessTransformer
from .features import IdIndexer, IdIndexerModel, PartitionedMinMaxScaler, PartitionedStandardScaler

__all__ = ["AccessAnomaly", "AccessAnomalyModel", "ComplementAccessTransformer",
           "IdIndexer", "IdIndexerModel", "PartitionedStandardScaler",
           "PartitionedMinMaxScaler"]
