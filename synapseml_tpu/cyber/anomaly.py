"""AccessAnomaly (reference ``cyber/anomaly/collaborative_filtering.py:616``):
per-tenant ALS over (user, resource) access counts; the anomaly score of an
observed access is its standardized NEGATIVE predicted affinity — accesses the
factor model finds unlikely score high.

TPU shape: the ALS normal equations are batched solves (``vmap(solve)``
over users/resources). Small tenants materialize the dense [U, R] count
matrix; past ``_DENSE_LIMIT`` cells the solver switches to an
nnz-proportional edge-list formulation (the Hu-Koren-Volinsky identity:
``A_u = FᵀF + Σ_obs (c-1)·f fᵀ + λI``, ``b_u = Σ_obs c·f``) built with
``segment_sum`` — memory scales with observed interactions, never with
U×R, matching the reference's sparse distributed ALS at tenant scale.
"""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Estimator, Model, Transformer

__all__ = ["AccessAnomaly", "AccessAnomalyModel", "ComplementAccessTransformer"]

_DEFAULT_TENANT = "__single_tenant__"


def _als(counts: np.ndarray, rank: int, reg: float, n_iter: int, seed: int,
         alpha: float = 1.0):
    """Implicit-feedback ALS on a dense [U, R] count matrix -> (U_f, R_f)."""
    import jax
    import jax.numpy as jnp

    U, R = counts.shape
    rng = np.random.default_rng(seed)
    u_f = jnp.asarray(rng.normal(scale=0.1, size=(U, rank)), jnp.float32)
    r_f = jnp.asarray(rng.normal(scale=0.1, size=(R, rank)), jnp.float32)
    conf = jnp.asarray(1.0 + alpha * counts, jnp.float32)     # confidence
    pref = jnp.asarray((counts > 0).astype(np.float32))       # preference
    eye = jnp.eye(rank, dtype=jnp.float32) * reg

    @jax.jit
    def solve_side(fixed, conf_rows, pref_rows):
        # per row i: (Fᵀ C_i F + λI) x = Fᵀ C_i p_i
        def one(c, p):
            A = (fixed.T * c) @ fixed + eye
            b = (fixed.T * c) @ p
            return jnp.linalg.solve(A, b)

        return jax.vmap(one)(conf_rows, pref_rows)

    for _ in range(n_iter):
        u_f = solve_side(r_f, conf, pref)
        r_f = solve_side(u_f, conf.T, pref.T)
    return np.asarray(u_f), np.asarray(r_f)


# dense-path ceiling: tenants whose U*R cell count exceeds this solve on the
# edge list instead (identical math — the sparse/dense equivalence is tested)
_DENSE_LIMIT = 1 << 22


def _als_sparse(u_idx: np.ndarray, r_idx: np.ndarray, w: np.ndarray,
                n_users: int, n_res: int, rank: int, reg: float,
                n_iter: int, seed: int, alpha: float = 1.0):
    """Implicit-feedback ALS on the (user, res, weight) edge list.

    Memory and FLOPs are proportional to nnz (plus the [U,k]/[R,k] factors
    and transient [nnz, k, k] outer products), never to U*R. Exactly the
    same math as :func:`_als`: unobserved cells have confidence 1 and
    preference 0, so their whole contribution is the shared FᵀF term.
    """
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    u_f = jnp.asarray(rng.normal(scale=0.1, size=(n_users, rank)), jnp.float32)
    r_f = jnp.asarray(rng.normal(scale=0.1, size=(n_res, rank)), jnp.float32)
    u_e = jnp.asarray(u_idx, jnp.int32)
    r_e = jnp.asarray(r_idx, jnp.int32)
    conf = jnp.asarray(1.0 + alpha * w, jnp.float32)          # per-edge c
    # explicit preference, exactly the dense path's (counts > 0): an edge
    # whose aggregated weight is 0 or negative keeps its confidence term in
    # A but contributes nothing to b — without this, zero-weight edges
    # would silently flip preference depending on which solver a tenant's
    # size routed it to
    pref = jnp.asarray(np.asarray(w) > 0, jnp.float32)
    eye = jnp.eye(rank, dtype=jnp.float32) * reg

    def solve_side(fixed, seg_ids, gather_ids, n_rows):
        # per row i: (FᵀF + Σ_obs (c-1) f fᵀ + λI) x = Σ_obs c p f
        G = fixed.T @ fixed                                    # [k, k]
        f_e = fixed[gather_ids]                                # [nnz, k]
        outer = (conf - 1.0)[:, None, None] \
            * (f_e[:, :, None] * f_e[:, None, :])              # [nnz, k, k]
        A = jax.ops.segment_sum(outer, seg_ids, num_segments=n_rows) \
            + G[None] + eye[None]
        b = jax.ops.segment_sum((conf * pref)[:, None] * f_e, seg_ids,
                                num_segments=n_rows)
        return jnp.linalg.solve(A, b[..., None])[..., 0]

    @jax.jit
    def sweep(uf, rf):
        uf = solve_side(rf, u_e, r_e, n_users)
        rf = solve_side(uf, r_e, u_e, n_res)
        return uf, rf

    for _ in range(n_iter):
        u_f, r_f = sweep(u_f, r_f)
    return np.asarray(u_f), np.asarray(r_f)


class AccessAnomaly(Estimator):
    feature_name = "cyber"

    tenant_col = Param("tenant_col", "tenant column (None = single tenant)",
                       default=None)
    user_col = Param("user_col", "user column", default="user")
    res_col = Param("res_col", "resource column", default="res")
    likelihood_col = Param("likelihood_col", "access count/weight column "
                           "(None = 1 per row)", default=None)
    rank = Param("rank", "latent factor rank", default=10,
                 converter=TypeConverters.to_int)
    reg = Param("reg", "ALS ridge", default=0.1, converter=TypeConverters.to_float)
    max_iter = Param("max_iter", "ALS iterations", default=10,
                     converter=TypeConverters.to_int)
    seed = Param("seed", "rng seed", default=0, converter=TypeConverters.to_int)
    output_col = Param("output_col", "anomaly score column", default="anomaly_score")

    def _fit(self, df: DataFrame) -> "AccessAnomalyModel":
        self.require_columns(df, self.get("user_col"), self.get("res_col"))
        if self.get("likelihood_col"):
            self.require_columns(df, self.get("likelihood_col"))
        tc = self.get("tenant_col")
        if tc:
            self.require_columns(df, tc)
        # ids handled as strings THROUGHOUT so np.unique's sort order matches
        # the searchsorted at scoring time (numeric ids would sort differently)
        users = np.asarray(df.collect_column(self.get("user_col"))).astype(str)
        ress = np.asarray(df.collect_column(self.get("res_col"))).astype(str)
        tenants = (np.asarray(df.collect_column(tc)) if tc
                   else np.full(len(users), _DEFAULT_TENANT, dtype=object))
        weights = (np.asarray(df.collect_column(self.get("likelihood_col")), np.float64)
                   if self.get("likelihood_col") else np.ones(len(users)))
        models = {}
        for tenant in np.unique(tenants):
            m = tenants == tenant
            u_levels, u_idx = np.unique(users[m], return_inverse=True)
            r_levels, r_idx = np.unique(ress[m], return_inverse=True)
            U, R = len(u_levels), len(r_levels)
            rank_t = min(self.get("rank"), min(U, R) or 1)
            if U * R <= _DENSE_LIMIT:
                counts = np.zeros((U, R), np.float64)
                np.add.at(counts, (u_idx, r_idx), weights[m])
                u_f, r_f = _als(counts, rank_t, self.get("reg"),
                                self.get("max_iter"), self.get("seed"))
            else:
                # aggregate duplicate (user, res) edges, then solve on the
                # edge list — never materializing the [U, R] matrix
                key = u_idx.astype(np.int64) * R + r_idx
                uniq, inv = np.unique(key, return_inverse=True)
                w_agg = np.zeros(len(uniq), np.float64)
                np.add.at(w_agg, inv, weights[m])
                u_f, r_f = _als_sparse(uniq // R, uniq % R, w_agg, U, R,
                                       rank_t, self.get("reg"),
                                       self.get("max_iter"), self.get("seed"))
            # standardize affinity over OBSERVED accesses within the tenant
            aff = np.sum(u_f[u_idx] * r_f[r_idx], axis=1)
            mu, sd = float(aff.mean()), float(aff.std() or 1.0)
            # unicode (not object) arrays: the npz pytree serializer is
            # pickle-free, object arrays would fail to load
            models[str(tenant)] = {"users": u_levels, "res": r_levels,
                                   "u_f": u_f, "r_f": r_f, "mean": mu, "std": sd}
        return AccessAnomalyModel(tenant_models=models,
                                  tenant_col=tc, user_col=self.get("user_col"),
                                  res_col=self.get("res_col"),
                                  output_col=self.get("output_col"))


class AccessAnomalyModel(Model):
    tenant_models = ComplexParam("tenant_models", "per-tenant factor models")
    tenant_col = Param("tenant_col", "tenant column", default=None)
    user_col = Param("user_col", "user column", default="user")
    res_col = Param("res_col", "resource column", default="res")
    output_col = Param("output_col", "anomaly score column", default="anomaly_score")

    def _score_one(self, tenant, user, res) -> float:
        m = self.get("tenant_models").get(str(tenant))
        if m is None:
            return float("nan")
        user, res = str(user), str(res)
        ui = np.searchsorted(m["users"], user)
        ri = np.searchsorted(m["res"], res)
        unseen_u = ui >= len(m["users"]) or m["users"][ui] != user
        unseen_r = ri >= len(m["res"]) or m["res"][ri] != res
        if unseen_u or unseen_r:
            return 2.0  # unseen entity: highly unusual for this tenant
        aff = float(m["u_f"][ui] @ m["r_f"][ri])
        return (m["mean"] - aff) / m["std"]  # low affinity -> high score

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("user_col"), self.get("res_col"))
        tc = self.get("tenant_col")

        def score(p):
            n = len(p[self.get("user_col")])
            tenants = p[tc] if tc else [_DEFAULT_TENANT] * n
            return np.asarray([
                self._score_one(tenants[i], p[self.get("user_col")][i],
                                p[self.get("res_col")][i])
                for i in range(n)], np.float64)

        return df.with_column(self.get("output_col"), score)


class ComplementAccessTransformer(Transformer):
    """(ref ``cyber/anomaly/ComplementAccessTransformer``) — emit (user, res)
    pairs the user did NOT access (sampled), for evaluation against observed
    accesses."""

    feature_name = "cyber"

    tenant_col = Param("tenant_col", "tenant column", default=None)
    user_col = Param("user_col", "user column", default="user")
    res_col = Param("res_col", "resource column", default="res")
    factor = Param("factor", "complement rows per observed row", default=1,
                   converter=TypeConverters.to_int)
    seed = Param("seed", "rng seed", default=0, converter=TypeConverters.to_int)

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("user_col"), self.get("res_col"))
        tc = self.get("tenant_col")
        # ids handled as strings THROUGHOUT so np.unique's sort order matches
        # the searchsorted at scoring time (numeric ids would sort differently)
        users = np.asarray(df.collect_column(self.get("user_col"))).astype(str)
        ress = np.asarray(df.collect_column(self.get("res_col"))).astype(str)
        tenants = (np.asarray(df.collect_column(tc)) if tc
                   else np.full(len(users), _DEFAULT_TENANT, dtype=object))
        rng = np.random.default_rng(self.get("seed"))
        out_rows = {self.get("user_col"): [], self.get("res_col"): []}
        if tc:
            out_rows[tc] = []
        for tenant in np.unique(tenants):
            m = tenants == tenant
            seen = set(zip(users[m].tolist(), ress[m].tolist()))
            t_users = np.unique(users[m])
            t_res = np.unique(ress[m])
            want = int(m.sum()) * self.get("factor")
            budget = len(t_users) * len(t_res) - len(seen)
            want = min(want, max(budget, 0))
            got = 0
            attempts = 0
            emitted = set()
            while got < want and attempts < want * 50:
                u = t_users[rng.integers(len(t_users))]
                r = t_res[rng.integers(len(t_res))]
                attempts += 1
                key = (u, r)
                if key in seen or key in emitted:
                    continue
                emitted.add(key)
                out_rows[self.get("user_col")].append(u)
                out_rows[self.get("res_col")].append(r)
                if tc:
                    out_rows[tc].append(tenant)
                got += 1
        if not out_rows[self.get("user_col")]:
            return DataFrame([{}])
        return DataFrame.from_dict({k: np.asarray(v, dtype=object)
                                    for k, v in out_rows.items()})
