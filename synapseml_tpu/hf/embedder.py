"""HuggingFaceSentenceEmbedder (reference ``hf/HuggingFaceSentenceEmbedder.py:26-228``,
sentence-transformers + optional TensorRT): text -> pooled encoder embedding.

Here: a Flax BERT-style encoder jitted once per batch shape; masked mean
pooling (the sentence-transformers default) or CLS pooling; L2 normalization
optional. Padded fixed-size batches keep one compiled program.
"""

from __future__ import annotations

import numpy as np

from ..core import batching as cb
from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Transformer
from ..models.flax_nets.bert import BertEmbeddings, bert_base, bert_tiny
from ..models.flax_nets.transformer import Encoder

__all__ = ["HuggingFaceSentenceEmbedder"]

_ARCHS = {"bert-base": bert_base, "bert-tiny": bert_tiny}


class _BertEncoder:
    """Embeddings + encoder stack (no classification head)."""

    def __init__(self, cfg):
        import flax.linen as nn

        class Net(nn.Module):
            @nn.compact
            def __call__(self, input_ids, attention_mask):
                x = BertEmbeddings(cfg, name="embeddings")(input_ids)
                mask = attention_mask[:, None, None, :].astype(bool)
                return Encoder(cfg, name="encoder")(x, mask)

        self.net = Net()
        self.cfg = cfg


class HuggingFaceSentenceEmbedder(Transformer):
    feature_name = "hf"

    model_name = Param("model_name", "encoder preset or local HF checkpoint dir",
                       default="bert-tiny")
    model_params = ComplexParam("model_params", "flax param pytree (None = random)",
                                default=None)
    tokenizer = ComplexParam("tokenizer", "tokenizer spec/object", default=None)
    input_col = Param("input_col", "text column", default="text")
    output_col = Param("output_col", "embedding column", default="embeddings")
    pooling = Param("pooling", "mean | cls", default="mean",
                    validator=lambda v: v in ("mean", "cls"))
    normalize = Param("normalize", "L2-normalize embeddings (opt in for "
                      "cosine indexes; raw pooled vectors by default so "
                      "callers stop re-normalizing per batch)", default=False,
                      converter=TypeConverters.to_bool)
    max_token_len = Param("max_token_len", "truncation length", default=128,
                          converter=TypeConverters.to_int)
    batch_size = Param("batch_size", "rows per padded batch", default=32,
                       converter=TypeConverters.to_int)
    mesh_config = ComplexParam("mesh_config", "MeshConfig for sharded "
                               "embedding (params + batches over the mesh)",
                               default=None)

    _CACHE_KEYS = frozenset({"model_name", "model_params", "tokenizer",
                             "mesh_config", "pooling", "normalize"})

    def set(self, **kw):
        out = super().set(**kw)
        if self._CACHE_KEYS & kw.keys():
            self.__dict__.pop("_cache_model", None)
            cb.invalidate_token(self)  # cached executables captured old state
        return out

    def _setup(self):
        if self.__dict__.get("_cache_model") is None:
            import jax
            import jax.numpy as jnp

            # pretrained-dir or preset (the reference's sentence-transformers
            # load path, hf/HuggingFaceSentenceEmbedder.py:26-228)
            import functools

            from ..models.convert_hf import (
                legacy_prenorm_fixup,
                pretrained_encoder,
                resolve_model_source,
            )

            cfg, loaded, tok = resolve_model_source(
                self.get("model_name"), _ARCHS, self.get("tokenizer"),
                functools.partial(pretrained_encoder, dtype=jnp.float32),
                preset_kwargs={"dtype": jnp.float32})
            params = self.get("model_params")
            if params is None:
                params = loaded
            elif loaded is None:
                cfg = legacy_prenorm_fixup(cfg, params)
            enc = _BertEncoder(cfg)
            if params is None:
                params = enc.net.init(jax.random.PRNGKey(0),
                                      jnp.zeros((1, 8), jnp.int32),
                                      jnp.ones((1, 8), jnp.int32))["params"]
            mesh = None
            if self.get("mesh_config") is not None:
                from ..parallel.mesh import create_mesh, shard_inference_params

                mesh = create_mesh(self.get("mesh_config"))
                if self.get("batch_size") % mesh.data_parallel_size():
                    raise ValueError(
                        f"batch_size ({self.get('batch_size')}) must be a "
                        f"multiple of the mesh data-parallel size "
                        f"({mesh.data_parallel_size()})")
                params = shard_inference_params(
                    enc.net, {"input_ids": jnp.zeros((1, 8), jnp.int32),
                              "attention_mask": jnp.ones((1, 8), jnp.int32)},
                    params, mesh)

            def embed_fn(ids, mask):
                h = enc.net.apply({"params": params}, ids, mask)  # [B,T,H]
                if self.get("pooling") == "cls":
                    pooled = h[:, 0]
                else:
                    m = mask[:, :, None].astype(h.dtype)
                    pooled = jnp.sum(h * m, axis=1) / jnp.maximum(
                        jnp.sum(m, axis=1), 1e-9)
                if self.get("normalize"):
                    pooled = pooled / jnp.maximum(
                        jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9)
                return pooled

            self.__dict__["_cache_model"] = (embed_fn, tok, mesh)
        return self.__dict__["_cache_model"]

    def _embed_for(self, bucket: int, seq_len: int):
        """Per-(batch bucket, seq len) executable via the CompiledCache —
        a mixed request stream compiles at most ladder-many programs per
        sequence shape instead of one per distinct batch size."""
        embed_fn, _tok, mesh = self._setup()

        def build():
            import jax

            jitted = jax.jit(embed_fn)
            if mesh is not None:
                def sharded(ids, m, _j=jitted, _m=mesh):
                    with _m.mesh:
                        return _j(_m.shard_batch(ids), _m.shard_batch(m))
                return sharded
            return jitted

        return cb.get_compiled_cache().get(
            "hf_embedder", (bucket, seq_len), build,
            instance=cb.instance_token(self), dtype="int32")

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))
        _embed_fn, tok, mesh = self._setup()
        B = self.get("batch_size")
        dp = mesh.data_parallel_size() if mesh is not None else 1
        bucketer = cb.default_bucketer()

        def per_part(p):
            texts = [str(t) for t in p[self.get("input_col")]]
            n = len(texts)
            if n == 0:
                q = dict(p)
                q[self.get("output_col")] = np.empty((0, 0), np.float32)
                return q
            enc = tok(texts, max_len=self.get("max_token_len"))
            ids = np.asarray(enc["input_ids"], np.int32)
            mask = np.asarray(enc["attention_mask"], np.int32)
            chunks = []
            for s, e, bucket in bucketer.slices(n, B, multiple_of=dp):
                ib = cb.pad_rows(ids[s:e], bucket)
                # padded rows keep mask=1 so pooled denominators stay
                # nonzero; their embeddings are sliced off below
                mb = cb.pad_rows(mask[s:e], bucket, mode="constant",
                                 constant=1)
                embed = self._embed_for(bucket, ids.shape[1])
                chunks.append(cb.unpad_rows(embed(ib, mb), e - s))
            q = dict(p)
            q[self.get("output_col")] = np.concatenate(chunks, axis=0)
            return q

        return df.map_partitions(per_part)
