"""HuggingFaceCausalLM (reference ``hf/HuggingFaceCausalLMTransform.py:103-331``).

Batch LLM inference as a Transformer: prompts (or chat message lists) ->
tokenize -> pad to a static prompt bucket -> jitted prefill+decode
(``greedy_generate``: KV cache, lax.while_loop, early EOS exit) -> detokenize.
``engine="paged"`` swaps the decode core for the token-granular paged-KV
engine (``models/paged_engine.py``) — early-EOS rows free their pages and
decode slots mid-batch — and ``serving_engine()`` exposes the SAME engine
to ``io.serving.serve_llm`` for online token streaming.

Model loading: ``set_params`` with a flax param pytree (e.g. restored from an
orbax checkpoint), or random init from the architecture preset for smoke
tests. Tokenization: a transformers tokenizer when available locally
(decode-capable), else token-id passthrough columns.
"""

from __future__ import annotations

import functools

import numpy as np

from ..core import batching as cb
from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Transformer
from ..models.flax_nets.llama import LlamaLM, generate, llama2_7b, llama_tiny
__all__ = ["HuggingFaceCausalLM", "CausalLMServingEngine"]

_ARCHS = {"llama2-7b": llama2_7b, "llama-tiny": llama_tiny}


def default_chat_template(messages) -> str:
    """Minimal chat template (reference applies the HF tokenizer's template;
    ``HuggingFaceCausalLMTransform.py`` chat mode)."""
    parts = [f"<|{m['role']}|>\n{m['content']}" for m in messages]
    return "\n".join(parts) + "\n<|assistant|>\n"


class HuggingFaceCausalLM(Transformer):
    feature_name = "hf"

    model_name = Param("model_name", "architecture preset or local HF checkpoint dir",
                       default="llama-tiny")
    model_params = ComplexParam("model_params", "flax param pytree (None = random init)",
                                default=None)
    tokenizer = ComplexParam("tokenizer", "tokenizer spec/object", default=None)
    input_col = Param("input_col", "prompt text column (completion mode)",
                      default="prompt")
    messages_col = Param("messages_col", "chat messages column (chat mode, "
                         "takes precedence when set)", default=None)
    output_col = Param("output_col", "generated text column", default="completions")
    max_new_tokens = Param("max_new_tokens", "tokens to generate", default=32,
                           converter=TypeConverters.to_int)
    prompt_bucket = Param("prompt_bucket", "pad prompts to multiples of this",
                          default=64, converter=TypeConverters.to_int)
    batch_size = Param("batch_size", "rows per padded device batch", default=8,
                       converter=TypeConverters.to_int)
    eos_id = Param("eos_id", "stop token id", default=None)
    do_sample = Param("do_sample", "sample instead of greedy decode (the "
                      "reference forwards HF generate kwargs, "
                      "HuggingFaceCausalLMTransform.py:284-331)", default=False,
                      converter=TypeConverters.to_bool)
    temperature = Param("temperature", "softmax temperature when sampling",
                        default=1.0, converter=TypeConverters.to_float)
    top_k = Param("top_k", "restrict sampling to the k most likely tokens "
                  "(None = no limit)", default=None)
    top_p = Param("top_p", "nucleus sampling: smallest token set with "
                  "cumulative probability >= top_p (None = no limit)",
                  default=None)
    seed = Param("seed", "on-device RNG seed for sampling; a fixed seed makes "
                 "sampled generation deterministic", default=0,
                 converter=TypeConverters.to_int)
    mesh_config = ComplexParam(
        "mesh_config", "MeshConfig for sharded inference: params shard over "
        "tensor/fsdp axes per the partition rule table (the Llama-2-7B "
        "sharded-batch-inference BASELINE config)", default=None)
    partition_rules = ComplexParam(
        "partition_rules", "parallel.partition.PartitionRules regex table "
        "placing the plain param pytree on the mesh (None = the default "
        "Llama table). Rides registry manifests' `sharding` section so a "
        "published sharded model re-applies its placement at /admin/load",
        default=None)
    generation_params_col = Param(
        "generation_params_col", "optional column of per-row dicts of "
        "generate kwargs (max_new_tokens/do_sample/temperature/top_k/top_p/"
        "seed/eos_id) overriding the transformer-level params — the "
        "reference forwards per-call HF generate kwargs "
        "(HuggingFaceCausalLMTransform.py:284-331). Rows are BUCKETED by "
        "identical config so the jit cache stays bounded by the number of "
        "distinct configs, not rows", default=None)
    engine = Param(
        "engine", "decode engine: 'dense' (run-to-completion lax.while_loop "
        "generate) or 'paged' (token-granular paged-KV continuous batching "
        "— models/paged_engine.py; greedy output is token-identical, "
        "early-EOS rows free their KV pages and decode slots immediately). "
        "Online serving (io.serving.serve_llm) always rides the paged "
        "engine; this picks the offline transform() path", default="dense")
    kv_block_len = Param("kv_block_len", "paged engine: tokens per KV page",
                         default=16, converter=TypeConverters.to_int)
    kv_blocks = Param("kv_blocks", "paged engine: physical KV pages in the "
                      "pool (None = enough for decode_slots x max_len)",
                      default=None)
    decode_slots = Param("decode_slots", "paged engine: max concurrently "
                         "decoding sequences (None = batch_size)",
                         default=None)
    prefix_cache = Param(
        "prefix_cache", "paged engine: content-hash full KV pages so "
        "sequences sharing a prompt prefix (chat system prompts, RAG "
        "templates) reuse resident pages and prefill only the uncached "
        "suffix (models/prefix_cache.py; token-identical output)",
        default=False, converter=TypeConverters.to_bool)
    draft_tokens = Param(
        "draft_tokens", "paged engine: greedy speculative decoding — draft "
        "this many tokens per step and verify them in ONE paged forward "
        "(0 = off; requires greedy decode, and accepted tokens are "
        "token-identical to plain decode)", default=0,
        converter=TypeConverters.to_int)
    drafter_ref = Param(
        "drafter_ref", "paged engine: who drafts when draft_tokens > 0 — "
        "None/'self' self-drafts via early exit at half the layers, "
        "'self:<n>' picks the exit layer, any other value resolves a small "
        "drafter model like model_name (architecture preset or local "
        "checkpoint dir)", default=None)

    _CACHE_KEYS = frozenset({"model_name", "model_params", "tokenizer",
                             "mesh_config", "partition_rules",
                             "max_new_tokens", "eos_id",
                             "do_sample", "temperature", "top_k", "top_p",
                             "seed", "engine", "kv_block_len", "kv_blocks",
                             "decode_slots", "prefix_cache", "draft_tokens",
                             "drafter_ref"})

    def set(self, **kw):
        out = super().set(**kw)
        if self._CACHE_KEYS & kw.keys():
            self.__dict__.pop("_cache_model", None)
            self.__dict__.pop("_cache_engines", None)
            cb.invalidate_token(self)  # cached executables captured old state
        return out

    # ---- lazy model/tokenizer ----
    def _model_and_params(self):
        if self.__dict__.get("_cache_model") is None:
            # pretrained-dir or preset (the reference's
            # AutoModelForCausalLM.from_pretrained path,
            # hf/HuggingFaceCausalLMTransform.py:103-331)
            from ..models.convert_hf import pretrained_causal_lm, resolve_model_source

            cfg, loaded, tok = resolve_model_source(
                self.get("model_name"), _ARCHS, self.get("tokenizer"),
                pretrained_causal_lm)
            params = self.get("model_params")
            if params is None:
                params = loaded
            model = LlamaLM(cfg, decode=True)  # KV-cache mode for generate
            if params is None:
                import jax
                import jax.numpy as jnp

                B, T = 1, 8
                variables = LlamaLM(cfg).init(jax.random.PRNGKey(0),
                                              jnp.zeros((B, T), jnp.int32))
                params = variables["params"]
            mesh = None
            if self.get("mesh_config") is not None:
                # sharded batch inference: weights distribute over the mesh
                # per the declarative partition rule table (plain pytree —
                # no eval_shape rebox, no nn.Partitioned metadata needed);
                # XLA inserts the activation collectives during generate
                import jax

                from ..models.convert_hf import shard_pretrained_params
                from flax.core import meta

                plain = jax.tree.map(
                    lambda x: x.value if isinstance(x, meta.Partitioned) else x,
                    params, is_leaf=lambda x: isinstance(x, meta.Partitioned))
                mesh, params = shard_pretrained_params(
                    plain, self.get("mesh_config"),
                    self.get("partition_rules"))
            self.__dict__["_cache_model"] = (model, params, tok, mesh)
        return self.__dict__["_cache_model"]

    _GEN_KEYS = ("max_new_tokens", "eos_id", "do_sample", "temperature",
                 "top_k", "top_p", "seed")

    def _effective_gen_cfg(self, overrides=None) -> dict:
        """Transformer-level generation params overlaid with a per-row
        override dict (the per-call generate-kwargs surface)."""
        eff = {k: self.get(k) for k in self._GEN_KEYS}
        if overrides:
            unknown = sorted(set(overrides) - set(self._GEN_KEYS))
            if unknown:
                raise ValueError(
                    f"unsupported generation params {unknown}; "
                    f"supported: {list(self._GEN_KEYS)}")
            eff.update(overrides)
        eff["max_new_tokens"] = int(eff["max_new_tokens"])
        return eff

    def _generate_fn(self, B: int, P: int, eff: dict):
        """Per-(batch bucket, prompt bucket, generation config) executable
        through the CompiledCache — the jit population stays bounded by
        ladder size x distinct configs, LRU-evicted, and its misses/trace
        times are observable."""
        eff_key = tuple(eff[k] for k in self._GEN_KEYS)

        def build():
            import jax

            model, params, _, mesh = self._model_and_params()
            sampling = eff["do_sample"]
            temperature = float(eff["temperature"]) if sampling else 0.0
            top_k = eff["top_k"]
            top_p = eff["top_p"]
            rng = jax.random.PRNGKey(int(eff["seed"])) if sampling else None

            def fn(p, ids, mask, offset):
                # fold the batch's global row offset into the stream so
                # identical prompts in different batches draw different
                # samples (same seed + same data stays reproducible)
                r = None if rng is None else jax.random.fold_in(rng, offset)
                return generate(model, p, ids,
                                eff["max_new_tokens"],
                                eos_id=eff["eos_id"],
                                prompt_mask=mask,
                                temperature=temperature,
                                top_k=None if top_k is None else int(top_k),
                                top_p=None if top_p is None else float(top_p),
                                rng=r)

            if mesh is not None:
                dp = mesh.data_parallel_size()
                if B % dp:
                    raise ValueError(
                        f"batch_size ({B}) must be a multiple of the mesh "
                        f"data-parallel size ({dp}) for sharded generation")
                # params ride as a jit ARGUMENT (a closure over weights
                # that span other processes is rejected) and outputs pin
                # replicated, so every process holds the full generated
                # ids even when the weights span hosts
                jitted = jax.jit(fn, out_shardings=mesh.replicated())

                def run(ids, mask, offset, _j=jitted, _m=mesh):
                    with _m.mesh:
                        # batch shards over data/fsdp; params already placed
                        return _j(params, _m.shard_batch(ids),
                                  _m.shard_batch(mask), offset)

                return run
            jitted = jax.jit(functools.partial(fn, params))
            return jitted

        return cb.get_compiled_cache().get(
            "hf_causal_lm", (B, P) + eff_key, build,
            instance=cb.instance_token(self), dtype="int32")

    def _resolve_drafter(self, cfg):
        """Resolve ``drafter_ref`` into engine knobs: (draft_layers,
        drafter). ``None``/``'self'`` self-drafts at half the layers,
        ``'self:<n>'`` picks the early-exit layer, anything else loads a
        small drafter model through the same source-resolution path as
        ``model_name``."""
        if int(self.get("draft_tokens") or 0) <= 0:
            return None, None
        ref = self.get("drafter_ref")
        if ref is None or ref == "self":
            return None, None  # engine default: early exit at n_layers // 2
        if isinstance(ref, str) and ref.startswith("self:"):
            return int(ref.split(":", 1)[1]), None
        from ..models.convert_hf import (pretrained_causal_lm,
                                         resolve_model_source)

        d_cfg, d_params, _tok = resolve_model_source(
            ref, _ARCHS, self.get("tokenizer"), pretrained_causal_lm)
        if d_params is None:
            import jax
            import jax.numpy as jnp

            d_params = LlamaLM(d_cfg).init(
                jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]
        return None, (d_cfg, d_params)

    def _paged_engine(self, eff: dict):
        """The shared token-granular engine (one per distinct sampling
        config; greedy — the default — shares one). Offline ``transform``
        and online ``serve_llm`` both decode through THIS object: one page
        pool, one set of prefill/decode executables in the CompiledCache,
        keyed by this stage's instance token so ``set(...)`` invalidates
        them with the rest of the stage's programs."""
        key = (bool(eff["do_sample"]),
               float(eff["temperature"]) if eff["do_sample"] else 0.0,
               eff["top_k"], eff["top_p"], int(eff["seed"]), eff["eos_id"])
        engines = self.__dict__.setdefault("_cache_engines", {})
        eng = engines.get(key)
        if eng is None or eng._released:
            from ..models.paged_engine import PagedDecodeEngine

            model, params, _tok, mesh = self._model_and_params()
            if mesh is not None:
                raise ValueError(
                    "engine='paged' does not support mesh_config yet; "
                    "sharded generation rides the dense path")
            sampling = bool(eff["do_sample"])
            slots = self.get("decode_slots") or max(int(self.get("batch_size")), 2)
            draft_layers, drafter = self._resolve_drafter(model.cfg)
            eng = PagedDecodeEngine(
                model.cfg, params,
                block_len=int(self.get("kv_block_len")),
                n_blocks=self.get("kv_blocks"), max_slots=int(slots),
                temperature=float(eff["temperature"]) if sampling else 0.0,
                top_k=None if eff["top_k"] is None else int(eff["top_k"]),
                top_p=None if eff["top_p"] is None else float(eff["top_p"]),
                seed=int(eff["seed"]), eos_id=eff["eos_id"],
                instance=cb.instance_token(self),
                prefix_cache=bool(self.get("prefix_cache")),
                draft_tokens=int(self.get("draft_tokens") or 0),
                draft_layers=draft_layers, drafter=drafter)
            engines[key] = eng
            # each engine owns a full device page pool — per-row
            # generation_params must not accumulate one multi-GB pool per
            # distinct sampling config, so bound the cache and release the
            # oldest IDLE engine (a released engine still decodes, it just
            # recompiles; the cache never hands it out again)
            if len(engines) > 4:
                for k in list(engines):
                    if k != key and not engines[k].has_work():
                        engines.pop(k).release()
                        break
        return eng

    def serving_engine(self) -> "CausalLMServingEngine":
        """The text-level adapter ``io.serving.serve_llm`` schedules tokens
        on (tokenize request -> paged engine -> detokenized chunks)."""
        return CausalLMServingEngine(self)

    def _texts_of(self, p) -> list[str]:
        mc = self.get("messages_col")
        if mc:
            return [default_chat_template(list(m)) for m in p[mc]]
        return [str(t) for t in p[self.get("input_col")]]

    def _transform(self, df: DataFrame) -> DataFrame:
        mc = self.get("messages_col")
        self.require_columns(df, mc if mc else self.get("input_col"))
        if self.get("generation_params_col"):
            self.require_columns(df, self.get("generation_params_col"))
        engine_kind = self.get("engine")
        if engine_kind not in ("dense", "paged"):
            raise ValueError(f"engine must be 'dense' or 'paged', "
                             f"got {engine_kind!r}")
        model, params, tok, _mesh = self._model_and_params()
        B = self.get("batch_size")
        bucket = self.get("prompt_bucket")
        dp = _mesh.data_parallel_size() if _mesh is not None else 1
        bucketer = cb.default_bucketer()

        pcol = self.get("generation_params_col")

        def row_groups(p, n):
            """[(override-dict-or-None, row indices)] — rows bucketed by
            identical per-row config so each distinct config compiles once."""
            if pcol is None:
                return [(None, np.arange(n))]
            buckets: dict = {}
            for i, d in enumerate(p[pcol]):
                d = dict(d) if d else {}
                key = tuple(sorted(
                    (k, tuple(v) if isinstance(v, list) else v)
                    for k, v in d.items()))
                buckets.setdefault(key, (d, []))[1].append(i)
            return [(d, np.asarray(ix)) for d, ix in buckets.values()]

        def per_part(p, part_offset):
            n = len(next(iter(p.values()))) if p else 0
            if n == 0:
                return None
            texts = self._texts_of(p)
            col = np.empty(n, dtype=object)
            decode = getattr(tok, "decode", None)
            for overrides, ix in row_groups(p, n):
                eff = self._effective_gen_cfg(overrides)
                enc = tok([texts[i] for i in ix],
                          max_len=model.cfg.max_len - eff["max_new_tokens"],
                          multiple_of=bucket)
                ids = np.asarray(enc["input_ids"], np.int32)
                mask = np.asarray(enc["attention_mask"], np.int32)
                m = len(ix)
                if engine_kind == "paged":
                    # token-granular continuous decode: early-EOS rows free
                    # their pages/slots mid-batch instead of riding the
                    # while_loop to the last row's finish
                    prompts = [ids[j][mask[j] > 0].tolist() for j in range(m)]
                    # a zero-token prompt has nothing to condition on: emit
                    # an empty completion for that ROW instead of letting
                    # engine.submit's ValueError fail the whole scan
                    live = [j for j, pr in enumerate(prompts) if pr]
                    gen_rows = [np.zeros(0, np.int32)] * m
                    if live:
                        for j, g in zip(live, self._paged_engine(eff).generate(
                                [prompts[j] for j in live],
                                eff["max_new_tokens"],
                                uids=[part_offset + int(ix[j])
                                      for j in live])):
                            gen_rows[j] = g
                else:
                    P = ids.shape[1]
                    outs = []
                    for s, e, row_bucket in bucketer.slices(m, B,
                                                            multiple_of=dp):
                        ib = cb.pad_rows(ids[s:e], row_bucket)
                        mb = cb.pad_rows(mask[s:e], row_bucket,
                                         mode="constant", constant=1)
                        fn = self._generate_fn(row_bucket, P, eff)
                        gen = cb.unpad_rows(
                            fn(ib, mb, np.int32(part_offset + int(ix[s]))),
                            e - s)
                        outs.append(gen[:, P:])             # generated ids only
                    gen_rows = list(np.concatenate(outs, axis=0))
                for j, i in enumerate(ix):
                    toks = np.asarray(gen_rows[j])
                    if eff["eos_id"] is not None:
                        stop = np.nonzero(toks == eff["eos_id"])[0]
                        if len(stop):
                            toks = toks[: stop[0]]
                    col[i] = decode(toks.tolist()) if decode else toks
            q = dict(p)
            q[self.get("output_col")] = col
            return q

        offsets = np.cumsum(
            [0] + [len(next(iter(p.values()))) if p else 0
                   for p in df.partitions[:-1]])
        parts = [per_part(p, int(off))
                 for p, off in zip(df.partitions, offsets)]
        out_parts = []
        for p, q in zip(df.partitions, parts):
            if q is None:
                q = dict(p)
                q[self.get("output_col")] = np.empty(0, dtype=object)
            out_parts.append(q)
        return DataFrame(out_parts)


class CausalLMServingEngine:
    """Text adapter between ``io.serving.serve_llm``'s token scheduler and
    the stage's shared :class:`~..models.paged_engine.PagedDecodeEngine`:
    parses request payloads (``{"prompt"| "input_ids", "max_new_tokens",
    "stream"}``), tokenizes through the stage's tokenizer, and renders
    per-token chunks / terminal replies (detokenized when the tokenizer can
    decode, raw token ids otherwise)."""

    def __init__(self, stage: "HuggingFaceCausalLM"):
        model, _params, tok, mesh = stage._model_and_params()
        if mesh is not None:
            raise ValueError("serve_llm rides the paged engine, which does "
                             "not support mesh_config yet")
        self._tok = tok
        self._decode = getattr(tok, "decode", None)
        self._max_len = model.cfg.max_len
        eff = stage._effective_gen_cfg()
        self._default_max_new = int(eff["max_new_tokens"])
        self._engine = stage._paged_engine(eff)

    # -- scheduling delegation (the serve_llm protocol) --
    def admit(self):
        return self._engine.admit()

    def step(self):
        return self._engine.step()

    def has_work(self) -> bool:
        return self._engine.has_work()

    @property
    def waiting_count(self) -> int:
        return self._engine.waiting_count

    def warmup(self) -> int:
        return self._engine.warmup()

    def abort(self, seq, reason: str = "aborted"):
        return self._engine.abort(seq, reason=reason)

    def abort_all(self, reason: str = "aborted"):
        return self._engine.abort_all(reason=reason)

    def live_requests(self):
        return self._engine.live_sequences()

    def release(self) -> None:
        self._engine.release()

    def stats(self) -> dict:
        return self._engine.stats()

    # -- request surface --
    def _prompt_ids(self, payload) -> list:
        if "input_ids" in payload:
            return [int(t) for t in payload["input_ids"]]
        prompt = payload.get("prompt")
        if not isinstance(prompt, str) or not prompt:
            raise ValueError("need 'prompt' (non-empty string) or "
                             "'input_ids'")
        # keep the prompt whole (up to the model horizon); the engine
        # clamps max_new to the remaining room and reports
        # finish_reason='length' — a large max_new_tokens must not
        # silently truncate the prompt out from under the request
        enc = self._tok([prompt], max_len=self._max_len - 1,
                        multiple_of=1)
        row_ids = np.asarray(enc["input_ids"][0])
        row_mask = np.asarray(enc["attention_mask"][0])
        return row_ids[row_mask > 0].tolist()

    def submit(self, payload, request_id: str, max_new_cap: int = 1024,
               deadline: float | None = None,
               journal_key: str | None = None):
        if not isinstance(payload, dict):
            raise ValueError("body must be a JSON object with 'prompt' or "
                             "'input_ids'")
        stream = bool(payload.get("stream", False))
        max_new = int(payload.get("max_new_tokens", self._default_max_new))
        max_new = max(1, min(max_new, int(max_new_cap)))
        ids = self._prompt_ids(payload)
        return self._engine.submit(ids, max_new, request_id=request_id,
                                   stream=stream, deadline=deadline,
                                   journal_key=journal_key)

    # -- live migration surface (serve_llm drain / front resubmit) --
    def export(self, uid: int) -> "dict | None":
        """JSON-able snapshot of one live sequence (the engine's binary
        npz payload rides base64) — the wire form of
        ``PagedDecodeEngine.export_sequence``."""
        import base64

        snap = self._engine.export_sequence(uid)
        if snap is None:
            return None
        return {"manifest": snap["manifest"],
                "payload_b64": base64.b64encode(snap["payload"]).decode(),
                "digests": snap["digests"]}

    def _seed_emitted_text(self, seq) -> None:
        # the client already received the text of every emitted token —
        # prime the cumulative-decode cursor so the next chunk streams only
        # the NEW delta, never a replay of the whole prefix
        if self._decode is not None and seq.generated:
            full = self._decode(list(seq.generated))
            if not full.endswith("�"):
                seq._emitted_text = full

    def import_snapshot(self, obj, request_id: str,
                        deadline: float | None = None,
                        journal_key: str | None = None):
        """Readmit an exported sequence under THIS worker's exchange: the
        continuation always streams (the front owns client-facing framing)
        and keeps the origin's uid so sampled token streams stay
        deterministic across the migration."""
        import base64

        if not isinstance(obj, dict) or "manifest" not in obj:
            raise ValueError("__import__ needs a snapshot with 'manifest'")
        man = dict(obj["manifest"])
        man["request_id"] = request_id
        man["stream"] = True
        if journal_key is not None:
            man["journal_key"] = journal_key
        if deadline is not None:
            import time as _time

            man["deadline_ms_left"] = (deadline
                                       - _time.perf_counter()) * 1e3
        payload = base64.b64decode(obj.get("payload_b64") or "") \
            if obj.get("payload_b64") else (obj.get("payload") or b"")
        seq = self._engine.import_sequence(
            {"manifest": man, "payload": payload,
             "digests": obj.get("digests") or {}})
        self._seed_emitted_text(seq)
        return seq

    def resume(self, obj, request_id: str, max_new_cap: int = 1024,
               deadline: float | None = None,
               journal_key: str | None = None):
        """Crash-path resubmit (no KV snapshot survived): re-tokenize the
        original request body and re-prefill over prompt + the tokens the
        front already relayed — token-identical under greedy, and
        sample-identical too when the origin uid rides along."""
        if not isinstance(obj, dict) or not isinstance(obj.get("body"),
                                                       dict):
            raise ValueError("__resume__ needs {'body': <original "
                             "request>, 'emitted_ids': [...]}")
        body = obj["body"]
        ids = self._prompt_ids(body)
        emitted = [int(t) for t in obj.get("emitted_ids") or []]
        max_new = int(body.get("max_new_tokens", self._default_max_new))
        max_new = max(1, min(max_new, int(max_new_cap)))
        man = {"uid": int(obj["uid"]) if obj.get("uid") is not None
               else hash(request_id) & 0x7FFFFFFF,
               "prompt_ids": ids, "generated": emitted,
               "max_new_tokens": max_new, "request_id": request_id,
               "stream": True, "journal_key": journal_key,
               "tokens_in_pages": 0}
        if deadline is not None:
            import time as _time

            man["deadline_ms_left"] = (deadline
                                       - _time.perf_counter()) * 1e3
        seq = self._engine.import_sequence({"manifest": man,
                                            "payload": b"", "digests": {}})
        self._seed_emitted_text(seq)
        return seq

    def chunk_for(self, event: dict) -> dict:
        out = {"token": event["token"]}
        if self._decode is not None:
            # byte-level BPE pieces are not independently decodable (a
            # char split across tokens decodes per-token to U+FFFD): decode
            # the cumulative ids and stream the text DELTA instead
            seq = event["seq"]
            full = self._decode(list(seq.generated))
            prev = getattr(seq, "_emitted_text", "")
            if full.endswith("�"):
                # incomplete byte sequence at the tail: hold the text back
                # until a later token completes it (the terminal record's
                # full-sequence decode always carries the complete text)
                out["text"] = ""
            else:
                out["text"] = (full[len(prev):] if full.startswith(prev)
                               else full)
                seq._emitted_text = full
        return out

    def result_for(self, seq) -> dict:
        toks = list(seq.generated)
        if (self._engine.eos_id is not None and toks
                and toks[-1] == self._engine.eos_id):
            toks = toks[:-1]
        out = {"done": True, "n_tokens": len(toks),
               "finish_reason": seq.finish_reason,
               "output_ids": toks}
        if self._decode is not None:
            out["text"] = self._decode(toks)
        if seq.preemptions:
            out["preemptions"] = seq.preemptions
        return out
