"""HuggingFace-style LLM transformers (reference ``deep-learning/.../hf/`` —
SURVEY.md §2.3): batch causal-LM generation and sentence embedding as
DataFrame transformers.

TPU design: the reference broadcasts a torch model per partition
(``HuggingFaceCausalLMTransform.py:103-331``); here ONE jitted
prefill+decode program (static prompt buckets, KV cache in HBM,
``flax_nets.llama.greedy_generate``) serves every partition, and the
embedder pools a Flax encoder instead of sentence-transformers
(``HuggingFaceSentenceEmbedder.py:26-228``).
"""

from .causal_lm import HuggingFaceCausalLM
from .embedder import HuggingFaceSentenceEmbedder

__all__ = ["HuggingFaceCausalLM", "HuggingFaceSentenceEmbedder"]
