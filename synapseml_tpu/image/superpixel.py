"""SLIC superpixel segmentation (reference ``core/.../image/Superpixel.scala:147``,
``SuperpixelTransformer.scala``) — feeds the image LIME/SHAP explainers, which
perturb images by blanking superpixels.

The reference grows clusters by BFS from a grid of seeds; here we run SLIC
proper (local k-means in (color, xy) space, fully vectorized per iteration) —
same contract: a per-image integer label map + cluster pixel lists.
"""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import Param, TypeConverters
from ..core.pipeline import Transformer
from .transforms import as_image

__all__ = ["slic_segments", "SuperpixelTransformer"]


def slic_segments(img: np.ndarray, cell_size: float = 16.0, modifier: float = 130.0,
                  n_iter: int = 5) -> np.ndarray:
    """SLIC label map [H, W] int32. ``cell_size`` is the seed-grid pitch;
    ``modifier`` weights color distance vs spatial distance (the reference's
    (cellSize, modifier) parameterization, ``SuperpixelTransformer.scala``)."""
    img = as_image(img)
    H, W, C = img.shape
    S = max(int(round(cell_size)), 2)
    ys = np.arange(S // 2, H, S)
    xs = np.arange(S // 2, W, S)
    cy, cx = np.meshgrid(ys, xs, indexing="ij")
    centers_xy = np.stack([cy.ravel(), cx.ravel()], axis=1).astype(np.float64)
    K = len(centers_xy)
    centers_col = img[centers_xy[:, 0].astype(int), centers_xy[:, 1].astype(int)].astype(np.float64)

    yy, xx = np.meshgrid(np.arange(H), np.arange(W), indexing="ij")
    pix_xy = np.stack([yy, xx], axis=-1).astype(np.float64)          # [H,W,2]
    color_weight = (modifier / 255.0) ** 2
    spatial_weight = 1.0 / (S * S)

    labels = np.zeros((H, W), np.int64)
    dist = np.full((H, W), np.inf)
    for _ in range(n_iter):
        dist[:] = np.inf
        for k in range(K):
            y0, x0 = centers_xy[k]
            ylo, yhi = max(int(y0) - S, 0), min(int(y0) + S + 1, H)
            xlo, xhi = max(int(x0) - S, 0), min(int(x0) + S + 1, W)
            patch = img[ylo:yhi, xlo:xhi].astype(np.float64)
            d_col = np.sum((patch - centers_col[k]) ** 2, axis=-1) * color_weight
            d_sp = np.sum((pix_xy[ylo:yhi, xlo:xhi] - centers_xy[k]) ** 2, axis=-1) * spatial_weight
            d = d_col + d_sp
            win = dist[ylo:yhi, xlo:xhi]
            better = d < win
            win[better] = d[better]
            labels[ylo:yhi, xlo:xhi][better] = k
        # recompute centers
        flat = labels.ravel()
        counts = np.bincount(flat, minlength=K).astype(np.float64)
        counts = np.maximum(counts, 1.0)
        for d_idx in range(2):
            centers_xy[:, d_idx] = np.bincount(flat, weights=pix_xy[..., d_idx].ravel(),
                                               minlength=K) / counts
        for c_idx in range(C):
            centers_col[:, c_idx] = np.bincount(flat, weights=img[..., c_idx].ravel().astype(np.float64),
                                                minlength=K) / counts
    # compact label ids (empty clusters removed)
    uniq, remap = np.unique(labels, return_inverse=True)
    return remap.reshape(H, W).astype(np.int32)


class SuperpixelTransformer(Transformer):
    """(ref ``SuperpixelTransformer.scala``) emits, per image, the superpixel
    clustering as a list of pixel-index arrays (what the image explainers
    toggle on/off)."""

    feature_name = "image"

    input_col = Param("input_col", "image column", default="image")
    output_col = Param("output_col", "superpixel column", default="superpixels")
    cell_size = Param("cell_size", "seed grid pitch in pixels", default=16.0,
                      converter=TypeConverters.to_float)
    modifier = Param("modifier", "color-vs-spatial distance weight", default=130.0,
                     converter=TypeConverters.to_float)

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))

        def per_part(p):
            out = np.empty(len(p[self.get("input_col")]), dtype=object)
            for i, x in enumerate(p[self.get("input_col")]):
                out[i] = slic_segments(x, self.get("cell_size"), self.get("modifier"))
            return out

        return df.with_column(self.get("output_col"), per_part)
