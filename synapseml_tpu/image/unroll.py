"""UnrollImage (reference ``core/.../image/UnrollImage.scala:169,204``):
image column -> flat float vector column (the classical-ML feature bridge,
e.g. for TrainClassifier / KNN over raw pixels)."""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import Param
from ..core.pipeline import Transformer
from .transforms import as_image

__all__ = ["UnrollImage", "UnrollBinaryImage"]


class UnrollImage(Transformer):
    feature_name = "image"

    input_col = Param("input_col", "image column", default="image")
    output_col = Param("output_col", "flattened vector column", default="unrolled")

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))

        def per_part(p):
            flats = [as_image(x).ravel() for x in p[self.get("input_col")]]
            lens = {len(f) for f in flats}
            if len(lens) == 1 and flats:
                return np.stack(flats)
            out = np.empty(len(flats), dtype=object)
            out[:] = flats
            return out

        return df.with_column(self.get("output_col"), per_part)


class UnrollBinaryImage(Transformer):
    """Decode ENCODED image bytes (png/jpeg) straight to the flat vector —
    the reference's binary variant (``image/UnrollImage.scala:204``,
    ``UnrollBinaryImage``) used downstream of the binary-file source without
    an intermediate decoded-image column."""

    feature_name = "image"

    input_col = Param("input_col", "binary image-bytes column", default="content")
    output_col = Param("output_col", "flattened vector column", default="unrolled")

    def _transform(self, df: DataFrame) -> DataFrame:
        from ..io.files import decode_image_bytes

        self.require_columns(df, self.get("input_col"))

        def per_part(p):
            flats = []
            for raw in p[self.get("input_col")]:
                try:
                    flats.append(decode_image_bytes(bytes(raw)).ravel())
                except Exception:  # undecodable bytes -> empty vector
                    flats.append(np.zeros(0, np.uint8))
            lens = {len(f) for f in flats}
            if len(lens) == 1 and flats:
                return np.stack(flats)
            out = np.empty(len(flats), dtype=object)
            out[:] = flats
            return out

        return df.with_column(self.get("output_col"), per_part)
