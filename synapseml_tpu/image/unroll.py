"""UnrollImage (reference ``core/.../image/UnrollImage.scala:169,204``):
image column -> flat float vector column (the classical-ML feature bridge,
e.g. for TrainClassifier / KNN over raw pixels)."""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import Param
from ..core.pipeline import Transformer
from .transforms import as_image

__all__ = ["UnrollImage"]


class UnrollImage(Transformer):
    feature_name = "image"

    input_col = Param("input_col", "image column", default="image")
    output_col = Param("output_col", "flattened vector column", default="unrolled")

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))

        def per_part(p):
            flats = [as_image(x).ravel() for x in p[self.get("input_col")]]
            lens = {len(f) for f in flats}
            if len(lens) == 1 and flats:
                return np.stack(flats)
            out = np.empty(len(flats), dtype=object)
            out[:] = flats
            return out

        return df.with_column(self.get("output_col"), per_part)
